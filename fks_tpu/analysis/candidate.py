"""Candidate-policy pre-flight: reject statically-doomed LLM candidates
BEFORE the sandbox/transpile/compile pipeline spends device-seconds on
them, and fingerprint the survivors for near-duplicate suppression.

Three products per candidate, one AST parse:

- a verdict against the VM transpiler's actual lowerable subset. Every
  table here is DERIVED from ``funsearch.transpiler`` / ``funsearch.
  sandbox`` at import time (arity table, entity field lists, forbidden
  substrings, unroll bound) — there is no second hand-maintained copy to
  drift, and tests/test_analysis.py locks the sync both ways (accepted
  ops transpile; every rejection reproduces as a real transpile/validate
  failure);
- a static cost estimate: op/call counts, loop-nest depth, and a
  per-node work bound as a polynomial in the padded GPU axis G (gpu
  loops and generators multiply by G, constant ``range()`` loops by
  their trip count). Feeds ``sim.engine.resolve_auto_prefilter`` (a
  provably-trivial policy skips the timing probe — prefiltering never
  pays for cheap policies, PROFILE.md round 11) and rides along in
  ``CodeEvaluator.last_eval_stats`` for the budget ladder's probe rung;
- a normalized-AST fingerprint: variables alpha-renamed in first-use
  order (entity names and builtins preserved), numeric constants
  bucketed by sign + magnitude decade, docstrings dropped. Candidates
  that differ only in naming or coefficient jitter collide, so the
  evaluator can score one representative and the elite pool can refuse
  echoes without a difflib pass.

SOUNDNESS MODEL. ``transpile`` = ``sandbox.validate`` + an abstract
interpretation that executes EVERY reachable statement symbolically
(both ``if`` arms run under lane masks). Sandbox-level checks therefore
hold everywhere in the tree. Transpiler-level checks hold wherever
execution is *guaranteed*; the checker threads a ``guaranteed`` flag
that turns False inside the only constructs the interpreter can skip —
``range()`` bodies whose trip count isn't provably nonzero, ``IfExp``
branches / later ``BoolOp`` operands whose condition may be a static
Python bool — so "rejected" always implies "transpile would raise".
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import inspect
import math
import textwrap
from typing import Dict, List, Optional, Set, Tuple

from fks_tpu.funsearch import sandbox, transpiler

#: machine-readable rejection vocabulary — the ``taxonomy`` field of
#: ledger ``candidate_rejected`` events (tools/check_jsonl_schema.py
#: keeps a synced copy; tests/test_analysis.py pins the two together)
REJECT_TAXONOMY = (
    "syntax",                # ast.parse failed
    "forbidden_construct",   # sandbox substring / node-type / private attr
    "bad_signature",         # wrong entry point shape (name/args/structure)
    "unsupported_syntax",    # parses + sandbox-clean, transpiler can't lower
    "unsupported_call",      # call target outside the lowerable builtins
    "bad_arity",             # known call, wrong argument count
    "unknown_attribute",     # pod/node/gpu field the entities don't expose
    "loop_too_long",         # static range() beyond the unroll bound
    "duplicate_fingerprint", # normalized-AST collision with a batch sibling
)


# ---------------------------------------------------------------------------
# tables derived from the transpiler / sandbox (never re-hardcoded)

def _derive_gpu_fields() -> frozenset:
    """GPU attribute names, read out of ``_Gpu.attr``'s own source: the
    method is a chain of ``name == ...`` / ``name in (...)`` comparisons,
    so the accepted field set is exactly the string constants compared
    against ``name``."""
    src = textwrap.dedent(inspect.getsource(transpiler._Gpu.attr))
    fields: Set[str] = set()
    for node in ast.walk(ast.parse(src)):
        if not (isinstance(node, ast.Compare)
                and isinstance(node.left, ast.Name)
                and node.left.id == "name"):
            continue
        for comp in node.comparators:
            for c in ast.walk(comp):
                if isinstance(c, ast.Constant) and isinstance(c.value, str):
                    fields.add(c.value)
    if not fields:  # the derivation itself drifted — fail loudly
        raise RuntimeError("could not derive GPU fields from transpiler._Gpu")
    return frozenset(fields)


POD_FIELDS = frozenset(transpiler._Pod.FIELDS)
NODE_FIELDS = frozenset(transpiler._Node.FIELDS) | {"gpus"}
GPU_FIELDS = _derive_gpu_fields()
MAX_UNROLL = transpiler._Interp.MAX_UNROLL
ARITY = dict(transpiler._ARITY)
MATH_FNS = frozenset(transpiler._MATH_FNS)
#: builtins the transpiler's call() actually lowers in expression position
#: (range/enumerate are iterator-only; sum/sorted are genexp-only and
#: handled before the arity table in call())
EXPR_CALLS = (frozenset(n for n in ARITY if not n.startswith("math."))
              - {"range", "enumerate"}) | {"sum", "sorted"}
_RESERVED = frozenset({"pod", "node", "math"}) | set(sandbox.SAFE_BUILTINS)


# ---------------------------------------------------------------------------
# cost estimate

@dataclasses.dataclass(frozen=True)
class CostEstimate:
    """Static per-node work bound. ``coeffs[d]`` counts operations nested
    under ``d`` GPU-axis loops, so ``work(G) = sum(coeffs[d] * G**d)``
    bounds the op count one node evaluates per policy call. ``range()``
    loops with constant bounds multiply by their trip count at the same
    degree (they don't scale with the cluster); unknown-trip loops are
    bounded by the transpiler's own unroll cap."""

    ops: int
    calls: int
    loop_depth: int
    coeffs: Tuple[int, ...]

    def work(self, g_padded: int = 1) -> int:
        return int(sum(c * g_padded ** d for d, c in enumerate(self.coeffs)))


class _CostVisitor(ast.NodeVisitor):
    def __init__(self):
        self.ops = 0
        self.calls = 0
        self.loop_depth = 0
        self._depth = 0       # current For/genexp nesting (for loop_depth)
        self._degree = 0      # current GPU-axis degree
        self._mult = 1        # current constant-range multiplier
        self.coeffs: Dict[int, int] = {}

    def _count(self, n: int = 1) -> None:
        self.coeffs[self._degree] = (self.coeffs.get(self._degree, 0)
                                     + n * self._mult)

    def visit_BinOp(self, node):
        self.ops += 1
        self._count()
        self.generic_visit(node)

    visit_UnaryOp = visit_BinOp
    visit_BoolOp = visit_BinOp
    visit_Compare = visit_BinOp
    visit_IfExp = visit_BinOp
    visit_Subscript = visit_BinOp
    visit_Attribute = visit_BinOp

    def visit_Call(self, node):
        self.calls += 1
        self.ops += 1
        self._count()
        self.generic_visit(node)

    def _enter_loop(self, *, gpu: bool, trips: int = 1):
        self._depth += 1
        self.loop_depth = max(self.loop_depth, self._depth)
        if gpu:
            self._degree += 1
        else:
            self._mult *= max(1, trips)

    def _exit_loop(self, *, gpu: bool, trips: int = 1):
        self._depth -= 1
        if gpu:
            self._degree -= 1
        else:
            self._mult //= max(1, trips)

    def visit_For(self, node):
        self.visit(node.iter)
        trips, gpu = 1, True
        if isinstance(node.iter, ast.Call) \
                and isinstance(node.iter.func, ast.Name) \
                and node.iter.func.id == "range":
            gpu = False
            trips = _static_range_len(node.iter)
            if trips is None:
                trips = MAX_UNROLL  # bound unknown trips by the unroll cap
        self._enter_loop(gpu=gpu, trips=trips)
        for st in node.body:
            self.visit(st)
        self._exit_loop(gpu=gpu, trips=trips)

    def visit_GeneratorExp(self, node):
        for comp in node.generators:
            self.visit(comp.iter)
        self._enter_loop(gpu=True)
        for comp in node.generators:
            for cond in comp.ifs:
                self.visit(cond)
        self.visit(node.elt)
        self._exit_loop(gpu=True)


def _static_range_len(call: ast.Call) -> Optional[int]:
    """Trip count of ``range(...)`` when every bound is an int literal
    (unary minus allowed); None when any bound is dynamic."""
    vals: List[int] = []
    for a in call.args:
        v = _int_literal(a)
        if v is None:
            return None
        vals.append(v)
    if not 1 <= len(vals) <= 3:
        return None
    try:
        return len(range(*vals))
    except (TypeError, ValueError):
        return None


def _int_literal(node) -> Optional[int]:
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _int_literal(node.operand)
        return None if inner is None else -inner
    return None


def estimate_cost(fn: ast.FunctionDef) -> CostEstimate:
    v = _CostVisitor()
    for st in fn.body:
        v.visit(st)
    hi = max(v.coeffs) if v.coeffs else 0
    return CostEstimate(ops=v.ops, calls=v.calls, loop_depth=v.loop_depth,
                        coeffs=tuple(v.coeffs.get(d, 0)
                                     for d in range(hi + 1)))


# ---------------------------------------------------------------------------
# normalized-AST fingerprint

def _bucket(v) -> str:
    """Sign + magnitude-decade token: 0 -> "0", 0.8 -> "+e0", 7 -> "+e1",
    -3000 -> "-e4". Coefficient jitter inside a decade collides; crossing
    a decade (a real behavioral change at these scales) does not."""
    if v == 0:
        return "0"
    sign = "-" if v < 0 else "+"
    mag = abs(float(v))
    dec = 0 if mag <= 1.0 else int(math.floor(math.log10(mag))) + 1
    return f"{sign}e{dec}"


class _Normalizer(ast.NodeTransformer):
    """Alpha-rename user variables in first-occurrence order; bucket
    numeric constants. Entity names, ``math``, and the sandbox builtins
    keep their identity (renaming those would alias unrelated code)."""

    def __init__(self):
        self.names: Dict[str, str] = {}

    def _rename(self, name: str) -> str:
        if name in _RESERVED:
            return name
        return self.names.setdefault(name, f"v{len(self.names)}")

    def visit_Name(self, node):
        return ast.copy_location(
            ast.Name(id=self._rename(node.id), ctx=node.ctx), node)

    def visit_Constant(self, node):
        if isinstance(node.value, bool) \
                or not isinstance(node.value, (int, float)):
            return node
        return ast.copy_location(ast.Constant(value=_bucket(node.value)),
                                 node)

    def visit_FunctionDef(self, node):
        body = [st for st in node.body
                if not (isinstance(st, ast.Expr)
                        and isinstance(st.value, ast.Constant))]
        node = ast.FunctionDef(
            name=node.name, args=node.args, body=body or [ast.Pass()],
            decorator_list=[], returns=None, type_comment=None)
        return self.generic_visit(node)


def fingerprint(code: str,
                entry_point: str = "priority_function") -> Optional[str]:
    """16-hex-char fingerprint of the normalized candidate AST, or None
    when the code doesn't parse / lacks the entry point."""
    try:
        tree = ast.parse(code)
    except (SyntaxError, ValueError):
        return None
    fn = next((n for n in tree.body if isinstance(n, ast.FunctionDef)
               and n.name == entry_point), None)
    if fn is None:
        return None
    return _fingerprint_fn(fn)


def _fingerprint_fn(fn: ast.FunctionDef) -> str:
    norm = _Normalizer().visit(fn)
    return hashlib.sha256(
        ast.dump(norm, annotate_fields=False).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# pre-flight verdict

@dataclasses.dataclass
class PreflightReport:
    ok: bool
    taxonomy: Optional[str] = None
    reason: str = ""
    cost: Optional[CostEstimate] = None
    fingerprint: Optional[str] = None

    def __bool__(self) -> bool:
        return self.ok


class _Reject(Exception):
    def __init__(self, taxonomy: str, reason: str):
        assert taxonomy in REJECT_TAXONOMY
        self.taxonomy, self.reason = taxonomy, reason
        super().__init__(f"{taxonomy}: {reason}")


def preflight_check(code: str,
                    entry_point: str = "priority_function",
                    ) -> PreflightReport:
    """Static verdict for one candidate. ``ok=False`` guarantees that the
    full pipeline (``sandbox.validate`` -> ``transpile``) would also fail
    — the evaluator can skip it outright; ``ok=True`` carries the cost
    estimate and fingerprint and promises nothing more (the transpiler's
    dynamic checks still run)."""
    try:
        fn = _structure(code, entry_point)
        _sandbox_walk(fn)
        _Checker(fn).run()
    except _Reject as r:
        return PreflightReport(False, r.taxonomy, r.reason)
    return PreflightReport(True, cost=estimate_cost(fn),
                           fingerprint=_fingerprint_fn(fn))


def _structure(code: str, entry_point: str) -> ast.FunctionDef:
    """Substring blacklist + parse + entry-point shape (mirrors
    ``sandbox.validate_source_text`` / the structural half of
    ``sandbox.validate_structure``)."""
    low = code.lower()
    for frag in sandbox.FORBIDDEN_SUBSTRINGS:
        if frag in low:
            raise _Reject("forbidden_construct",
                          f"forbidden substring {frag!r}")
    try:
        tree = ast.parse(code)
    except (SyntaxError, ValueError) as e:
        raise _Reject("syntax", str(e)) from None
    fns = [n for n in tree.body if isinstance(n, ast.FunctionDef)]
    if len(fns) != 1 or fns[0].name != entry_point:
        raise _Reject("bad_signature",
                      f"need exactly one function {entry_point!r}")
    fn = fns[0]
    if [x.arg for x in fn.args.args] != ["pod", "node"]:
        raise _Reject("bad_signature", "signature must be (pod, node)")
    for n in tree.body:
        if n is fn:
            continue
        if isinstance(n, ast.Expr) and isinstance(n.value, ast.Constant):
            continue
        raise _Reject("bad_signature",
                      "only the entry function + docstrings at top level")
    return fn


def _sandbox_walk(fn: ast.FunctionDef) -> None:
    """The sandbox's everywhere-sound checks: node-type allowlist, call
    whitelist, private attribute ban."""
    for node in ast.walk(fn):
        if not isinstance(node, sandbox._ALLOWED_NODES):
            raise _Reject("forbidden_construct",
                          f"disallowed syntax {type(node).__name__}")
        if isinstance(node, ast.Attribute) and node.attr.startswith("_"):
            raise _Reject("forbidden_construct",
                          f"private attribute {node.attr!r}")
        if isinstance(node, ast.FunctionDef) and node is not fn:
            raise _Reject("forbidden_construct", "nested function")
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name):
                if f.id not in sandbox.SAFE_BUILTINS:
                    raise _Reject("unsupported_call",
                                  f"call to {f.id!r} not allowed")
            elif isinstance(f, ast.Attribute):
                if not (isinstance(f.value, ast.Name)
                        and f.value.id == "math"
                        and f.attr in sandbox.SAFE_MATH):
                    raise _Reject("unsupported_call",
                                  "only math.<whitelisted> attribute calls")
            else:
                raise _Reject("unsupported_call", "computed call target")


def _is_node_gpus(node) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "gpus"
            and isinstance(node.value, ast.Name)
            and node.value.id == "node")


class _Checker:
    """Transpiler-subset checks under the guaranteed-execution model (see
    module docstring). One instance per candidate; ``run`` raises
    ``_Reject`` on the first guaranteed transpile failure."""

    def __init__(self, fn: ast.FunctionDef):
        self.fn = fn
        self.assigned: Set[str] = set()
        self.gpu_names: Set[str] = set()
        self.int_targets: Set[str] = set()  # range index / enumerate index
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.assigned.add(t.id)
            elif isinstance(node, ast.For) and _is_node_gpus(node.iter) \
                    and isinstance(node.target, ast.Name):
                self.gpu_names.add(node.target.id)
            elif isinstance(node, ast.For) \
                    and isinstance(node.iter, ast.Call) \
                    and isinstance(node.iter.func, ast.Name):
                fname = node.iter.func.id
                if fname == "enumerate" \
                        and isinstance(node.target, ast.Tuple) \
                        and len(node.target.elts) == 2:
                    if isinstance(node.target.elts[0], ast.Name):
                        self.int_targets.add(node.target.elts[0].id)
                    if isinstance(node.target.elts[1], ast.Name):
                        self.gpu_names.add(node.target.elts[1].id)
                elif fname == "range" and isinstance(node.target, ast.Name):
                    self.int_targets.add(node.target.id)
            elif isinstance(node, ast.comprehension) \
                    and isinstance(node.target, ast.Name):
                self.gpu_names.add(node.target.id)
        # a name that is BOTH a gpu-loop target and a plain assignment
        # target is ambiguous at any given use site — exempt from both
        # the gpu-field check and the non-entity check
        self.gpu_checked = self.gpu_names - self.assigned

    def run(self) -> None:
        self.block(self.fn.body, True)

    # ----- statements

    def block(self, stmts, guaranteed: bool) -> None:
        for st in stmts:
            self.stmt(st, guaranteed)

    def stmt(self, st, g: bool) -> None:
        if isinstance(st, ast.Assign):
            if g and (len(st.targets) != 1
                      or not isinstance(st.targets[0], ast.Name)):
                raise _Reject("unsupported_syntax",
                              "only simple `name = expr` assignment")
            self._assign_target(st.targets[0] if st.targets else None,
                                st.value, g)
            self.expr(st.value, g)
        elif isinstance(st, ast.AugAssign):
            if g and not isinstance(st.target, ast.Name):
                raise _Reject("unsupported_syntax",
                              "only simple augmented assignment")
            self._assign_target(st.target, st.value, g)
            self.expr(st.value, g)
        elif isinstance(st, ast.If):
            # the interpreter runs BOTH arms under lane masks — bodies
            # inherit guaranteedness from the enclosing block
            self.expr(st.test, g)
            self.block(st.body, g)
            self.block(st.orelse, g)
        elif isinstance(st, ast.Return):
            if st.value is None:
                if g:
                    raise _Reject("unsupported_syntax",
                                  "bare return not allowed")
                return
            self.expr(st.value, g)
        elif isinstance(st, ast.For):
            self._for(st, g)
        elif isinstance(st, ast.Expr):
            if isinstance(st.value, ast.Constant):
                return  # docstring position: any constant is dropped
            if g:
                raise _Reject("unsupported_syntax",
                              "expression statements have no effect")
            self.expr(st.value, g)
        elif isinstance(st, ast.Pass):
            return
        elif g:
            raise _Reject("unsupported_syntax",
                          f"unsupported statement {type(st).__name__}")

    def _assign_target(self, target, value, g: bool) -> None:
        if not g:
            return
        if isinstance(target, ast.Name) and target.id in ("pod", "node",
                                                          "math"):
            raise _Reject("unsupported_syntax",
                          f"cannot rebind {target.id!r}")
        if (isinstance(value, ast.Name) and value.id in ("pod", "node")) \
                or _is_node_gpus(value):
            raise _Reject("unsupported_syntax",
                          "cannot store entity objects in variables")

    def _for(self, st: ast.For, g: bool) -> None:
        if g and st.orelse:
            raise _Reject("unsupported_syntax", "for/else not supported")
        it = st.iter
        if _is_node_gpus(it):
            if g and not isinstance(st.target, ast.Name):
                raise _Reject("unsupported_syntax",
                              "gpu loop target must be a name")
            self.block(st.body, g)  # padded G >= 1: body always runs
            return
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "enumerate":
            if g:
                if it.keywords:
                    raise _Reject("unsupported_syntax",
                                  "keyword arguments not supported")
                self._arity("enumerate", len(it.args))
                if not (it.args and _is_node_gpus(it.args[0])):
                    raise _Reject("unsupported_syntax",
                                  "enumerate() only over node.gpus")
                tgt = st.target
                if not (isinstance(tgt, ast.Tuple) and len(tgt.elts) == 2
                        and all(isinstance(e, ast.Name) for e in tgt.elts)):
                    raise _Reject("unsupported_syntax",
                                  "enumerate target must be `i, gpu`")
            self.block(st.body, g)
            return
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "range":
            if g:
                if it.keywords:
                    raise _Reject("unsupported_syntax",
                                  "keyword arguments not supported")
                self._arity("range", len(it.args))
                if not isinstance(st.target, ast.Name):
                    raise _Reject("unsupported_syntax",
                                  "range loop target must be a name")
                for a in it.args:
                    self.expr(a, True)
                    for sub in ast.walk(a):
                        # the interpreter stores every plain assignment as
                        # a traced array (lane-masked blend), so only loop
                        # indices and literal arithmetic stay Python ints
                        bad = (isinstance(sub, ast.Constant)
                               and type(sub.value) is not int) \
                            or isinstance(sub, ast.Attribute) \
                            or (isinstance(sub, ast.Name)
                                and not (sub.id in self.int_targets
                                         and sub.id not in self.assigned))
                        if bad:
                            raise _Reject(
                                "unsupported_syntax",
                                "range() bounds must be static ints")
            trips = _static_range_len(it)
            if g and trips is not None and trips > MAX_UNROLL:
                raise _Reject("loop_too_long",
                              f"range loop longer than {MAX_UNROLL}")
            # empty or unknown trip count: the body may never execute, so
            # transpiler-level findings inside it are not guaranteed
            self.block(st.body, g and trips is not None and trips > 0)
            return
        if g:
            raise _Reject("unsupported_syntax",
                          "only `for gpu in node.gpus`, enumerate(node.gpus)"
                          ", or constant range() loops are supported")
        self.block(st.body, False)

    # ----- expressions

    def expr(self, node, g: bool) -> None:
        if isinstance(node, ast.Constant):
            if g and not isinstance(node.value, (bool, int, float)):
                raise _Reject("unsupported_syntax",
                              f"unsupported constant {node.value!r}")
        elif isinstance(node, ast.Name):
            if g and node.id in ("pod", "node", "math"):
                # bare entity reference outside an attribute base: every
                # consuming position fails (store -> TranspileError,
                # arithmetic/len/int -> trace-time TypeError)
                raise _Reject("unsupported_syntax",
                              f"{node.id!r} used as a plain value")
        elif isinstance(node, ast.Attribute):
            self._attribute(node, g)
        elif isinstance(node, ast.BinOp):
            self.expr(node.left, g)
            self.expr(node.right, g)
        elif isinstance(node, ast.UnaryOp):
            self.expr(node.operand, g)
        elif isinstance(node, ast.BoolOp):
            # later operands are skipped when everything before them is
            # statically boolable — only a definitely-traced prefix makes
            # their evaluation guaranteed
            self.expr(node.values[0], g)
            dyn = self._dynamic(node.values[0])
            for v in node.values[1:]:
                self.expr(v, g and dyn)
                dyn = dyn or self._dynamic(v)
        elif isinstance(node, ast.Compare):
            self.expr(node.left, g)
            for c in node.comparators:
                self.expr(c, g)
        elif isinstance(node, ast.IfExp):
            self.expr(node.test, g)
            both = g and self._dynamic(node.test)
            self.expr(node.body, both)
            self.expr(node.orelse, both)
        elif isinstance(node, ast.Call):
            self._call(node, g)
        elif isinstance(node, ast.Subscript):
            self._subscript(node, g)
        elif isinstance(node, ast.GeneratorExp):
            if g:
                raise _Reject("unsupported_syntax",
                              "generator outside sum/min/max/sorted")
            self._genexp_inner(node, False)
        elif g and isinstance(node, (ast.Tuple, ast.List)):
            raise _Reject("unsupported_syntax",
                          f"unsupported expression {type(node).__name__}")
        else:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.expr, ast.GeneratorExp)):
                    self.expr(child, False)

    def _attribute(self, node: ast.Attribute, g: bool) -> None:
        base = node.value
        if isinstance(base, ast.Name):
            bid = base.id
            if bid == "pod":
                if g and node.attr not in POD_FIELDS:
                    raise _Reject("unknown_attribute",
                                  f"unknown pod attribute {node.attr!r}")
                return
            if bid == "node":
                if g and node.attr not in NODE_FIELDS:
                    raise _Reject("unknown_attribute",
                                  f"unknown node attribute {node.attr!r}")
                return
            if bid == "math":
                if g:  # non-call math attribute: base evals to a plain str
                    raise _Reject("unsupported_syntax",
                                  "attribute access on non-entity value")
                return
            if bid in self.gpu_checked:
                if g and node.attr not in GPU_FIELDS:
                    raise _Reject("unknown_attribute",
                                  f"unknown gpu attribute {node.attr!r}")
                return
            if bid in self.gpu_names:
                return  # ambiguous (also assigned) — skip
            if g:  # plain variable / undefined name: never an entity
                raise _Reject("unsupported_syntax",
                              "attribute access on non-entity value")
            return
        if isinstance(base, ast.Subscript) and _is_node_gpus(base.value):
            self._subscript(base, g)
            if g and node.attr not in GPU_FIELDS:
                raise _Reject("unknown_attribute",
                              f"unknown gpu attribute {node.attr!r}")
            return
        # any other base (chained attribute, call result, arithmetic)
        # evaluates to a non-entity
        self.expr(base, g)
        if g:
            raise _Reject("unsupported_syntax",
                          "attribute access on non-entity value")

    def _call(self, node: ast.Call, g: bool) -> None:
        if g and node.keywords:
            raise _Reject("unsupported_syntax",
                          "keyword arguments not supported")
        for kw in node.keywords:
            self.expr(kw.value, False)
        f = node.func
        if isinstance(f, ast.Attribute):
            # sandbox stage already pinned this to math.<SAFE_MATH>
            if g:
                self._arity(f"math.{f.attr}", len(node.args))
            for a in node.args:
                self.expr(a, g)
            return
        if not isinstance(f, ast.Name):
            return  # sandbox stage rejected computed targets already
        name = f.id
        genexp_arg = (len(node.args) == 1
                      and isinstance(node.args[0], ast.GeneratorExp))
        if name in ("sum", "min", "max") and genexp_arg:
            self._genexp_inner(node.args[0], g)
            return
        if name == "sorted":
            if genexp_arg:
                self._genexp_inner(node.args[0], g)
                return
            if g:
                raise _Reject("unsupported_call",
                              "sorted() only over a generator")
            for a in node.args:
                self.expr(a, False)
            return
        if name == "len":
            if g:
                self._arity("len", len(node.args))
                a = node.args[0] if node.args else None
                ok = _is_node_gpus(a) or (
                    isinstance(a, ast.Call) and isinstance(a.func, ast.Name)
                    and a.func.id == "sorted")
                if not ok:
                    raise _Reject("unsupported_call",
                                  "len() only of node.gpus or sorted(...)")
            for a in node.args:
                if not _is_node_gpus(a):
                    self.expr(a, g)
            return
        if name == "sum":
            if g:
                raise _Reject("unsupported_call",
                              "sum() only over a generator")
            for a in node.args:
                self.expr(a, False)
            return
        if name in ("range", "enumerate"):
            if g:  # iterator builtins in expression position
                raise _Reject("unsupported_call",
                              f"call to unsupported function {name!r}")
            for a in node.args:
                self.expr(a, False)
            return
        if name in EXPR_CALLS:
            if g:
                self._arity(name, len(node.args))
            for a in node.args:
                self.expr(a, g)
            return
        if g:  # inside SAFE_BUILTINS (sandbox-clean) but not lowerable: str
            raise _Reject("unsupported_call",
                          f"call to unsupported function {name!r}")
        for a in node.args:
            self.expr(a, False)

    def _subscript(self, node: ast.Subscript, g: bool) -> None:
        idx = node.slice
        k = _int_literal(idx)
        if g and k is None:
            raise _Reject("unsupported_syntax",
                          "subscripts must use a static integer index")
        if g and k is not None and k < 0 and _is_node_gpus(node.value):
            raise _Reject("unsupported_syntax",
                          "negative gpu index not supported")
        if not _is_node_gpus(node.value):
            self.expr(node.value, g)
        if k is None and isinstance(idx, ast.expr):
            self.expr(idx, False)

    def _genexp_inner(self, gen: ast.GeneratorExp, g: bool) -> None:
        if g:
            if len(gen.generators) != 1:
                raise _Reject("unsupported_syntax",
                              "single-clause generators only")
            comp = gen.generators[0]
            if comp.is_async:
                raise _Reject("unsupported_syntax",
                              "async generators not allowed")
            if not _is_node_gpus(comp.iter):
                raise _Reject("unsupported_syntax",
                              "generators only over node.gpus")
            if not isinstance(comp.target, ast.Name):
                raise _Reject("unsupported_syntax",
                              "generator target must be a name")
        for comp in gen.generators:
            if not _is_node_gpus(comp.iter):
                self.expr(comp.iter, g)
            for cond in comp.ifs:
                self.expr(cond, g)
        self.expr(gen.elt, g)

    def _arity(self, name: str, n: int) -> None:
        lo, hi = ARITY.get(name, (0, None))
        if n < lo or (hi is not None and n > hi):
            raise _Reject("bad_arity", f"{name}() called with {n} "
                          "argument(s)")

    def _dynamic(self, node) -> bool:
        """True when ``node`` DEFINITELY evaluates to a traced array (an
        entity field read on an unconditionally-evaluated path). False is
        always safe — it only widens the maybe-skipped region."""
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and (
                    (base.id == "pod" and node.attr in POD_FIELDS)
                    or (base.id == "node" and node.attr in NODE_FIELDS
                        and node.attr != "gpus")
                    or (base.id in self.gpu_names
                        and node.attr in GPU_FIELDS)):
                return True
            if isinstance(base, ast.Subscript) \
                    and _is_node_gpus(base.value):
                return node.attr in GPU_FIELDS
            return False
        if isinstance(node, (ast.BinOp,)):
            return self._dynamic(node.left) or self._dynamic(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._dynamic(node.operand)
        if isinstance(node, ast.Compare):
            return (self._dynamic(node.left)
                    or any(self._dynamic(c) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return self._dynamic(node.values[0])
        if isinstance(node, ast.IfExp):
            return self._dynamic(node.test)
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "len":
                return True  # len() of node.gpus/sorted is an i32[N] array
            return any(self._dynamic(a) for a in node.args
                       if not isinstance(a, ast.GeneratorExp)) \
                or any(isinstance(a, ast.GeneratorExp)
                       and self._dynamic(a.elt) for a in node.args)
        if isinstance(node, ast.Subscript):
            return self._dynamic(node.value)
        return False
