"""Hand-written scheduling-policy zoo, vectorized over the node axis.

TPU-native re-design of the reference policy zoo: instead of a Python
``(pod, node) -> int`` called N times per event (reference:
tests/test_scheduler.py:20-218, funsearch_integration.py:217-431), each
policy is a jit-traceable ``(PodView, NodeView) -> i32[N]`` scoring every
node in one fused vector computation.

Semantics notes (parity-critical):
- every policy starts with the shared feasibility prologue (CPU/mem/GPU-count
  then per-GPU milli check) and returns 0 for infeasible nodes;
- ``max(1, int(score))`` truncates toward zero then clamps to >= 1
  (so a feasible node NEVER scores 0);
- arithmetic that the reference performs on Python ints (%, //) is done in
  int32 here; float math happens in ``dtype`` (float64 reproduces Python
  exactly; float32 is the TPU-fast default and matches on the shipped
  traces).

Factories return fresh closures so a dtype can be chosen per use.
"""
from __future__ import annotations

import jax.numpy as jnp

from fks_tpu.sim.types import NodeView, PodView, PolicyFn

_NEG = -1e30


def feasible_mask(pod: PodView, nodes: NodeView):
    """Shared feasibility prologue (reference test_scheduler.py:22-33 etc.):
    resource fit + at least num_gpu GPUs with gpu_milli_left >= request."""
    eligible = jnp.sum(
        (nodes.gpu_mask & (nodes.gpu_milli_left >= pod.gpu_milli)).astype(jnp.int32),
        axis=1)
    gpu_ok = jnp.where(pod.num_gpu > 0, eligible >= pod.num_gpu, True)
    return (nodes.node_mask
            & (pod.cpu_milli <= nodes.cpu_milli_left)
            & (pod.memory_mib <= nodes.memory_mib_left)
            & (pod.num_gpu <= nodes.gpu_left)
            & gpu_ok)


def _finish(score, feasible):
    """max(1, int(score)) under the feasibility gate."""
    as_int = jnp.trunc(score).astype(jnp.int32)
    return jnp.where(feasible, jnp.maximum(1, as_int), 0)


def _safe(x, pred, fill=1):
    return jnp.where(pred, x, fill)


# --------------------------------------------------------------- baselines

def first_fit(dtype=jnp.float32) -> PolicyFn:
    """Constant 1000 when feasible (reference test_scheduler.py:203-218)."""

    def policy(pod: PodView, nodes: NodeView):
        return jnp.where(feasible_mask(pod, nodes), 1000, 0).astype(jnp.int32)

    return policy


def best_fit(dtype=jnp.float32) -> PolicyFn:
    """Weighted 1 - normalized-remaining, x10000 (test_scheduler.py:171-200)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        d = dtype
        rem_cpu = (nodes.cpu_milli_left - pod.cpu_milli).astype(d)
        rem_mem = (nodes.memory_mib_left - pod.memory_mib).astype(d)
        rem_gpu = (nodes.gpu_left - pod.num_gpu).astype(d)
        norm = (rem_cpu / nodes.cpu_milli_total.astype(d) * 0.33
                + rem_mem / nodes.memory_mib_total.astype(d) * 0.33
                + rem_gpu / jnp.maximum(nodes.num_gpus, 1).astype(d) * 0.34)
        # reference computes int((1 - norm) * 10000) then max(1, .)
        return _finish((1 - norm) * 10000, f)

    return policy


def worst_fit(dtype=jnp.float32) -> PolicyFn:
    """Prefer the emptiest node (reference funsearch_integration.py:271-297,
    shipped commented out of the seed list)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        d = dtype
        rem_cpu = (nodes.cpu_milli_left - pod.cpu_milli).astype(d) / nodes.cpu_milli_total.astype(d)
        rem_mem = (nodes.memory_mib_left - pod.memory_mib).astype(d) / nodes.memory_mib_total.astype(d)
        rem_gpu = (nodes.gpu_left - pod.num_gpu).astype(d) / jnp.maximum(nodes.num_gpus, 1).astype(d)
        return _finish((rem_cpu * 0.33 + rem_mem * 0.33 + rem_gpu * 0.34) * 10000, f)

    return policy


def micro_best_fit(dtype=jnp.float32) -> PolicyFn:
    """The micro-scenario best-fit: 1000000 // (sum remaining + 1), exact
    integer floor division (reference tests/test_simulator.py:13-38)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        rem = ((nodes.cpu_milli_left - pod.cpu_milli)
               + (nodes.memory_mib_left - pod.memory_mib)
               + (nodes.gpu_left - pod.num_gpu) + 1)
        score = jnp.int32(1_000_000) // jnp.maximum(rem, 1)
        return jnp.where(f, score, 0).astype(jnp.int32)

    return policy


def gpu_aware(dtype=jnp.float32) -> PolicyFn:
    """GPU/CPU workload separation heuristic (funsearch_integration.py:299-353,
    shipped commented out)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        d = dtype
        ngpus = jnp.maximum(nodes.num_gpus, 1).astype(d)
        node_has_gpu = nodes.num_gpus > 0
        pod_needs_gpu = pod.num_gpu > 0
        cpu_util = 1 - nodes.cpu_milli_left.astype(d) / nodes.cpu_milli_total.astype(d)
        mem_util = 1 - nodes.memory_mib_left.astype(d) / nodes.memory_mib_total.astype(d)
        gpu_util = jnp.where(node_has_gpu, 1 - nodes.gpu_left.astype(d) / ngpus, 0)
        rem_cpu = (nodes.cpu_milli_left - pod.cpu_milli).astype(d) / nodes.cpu_milli_total.astype(d)
        rem_mem = (nodes.memory_mib_left - pod.memory_mib).astype(d) / nodes.memory_mib_total.astype(d)

        # GPU-pod branch
        base_g = jnp.where(gpu_util > 0.1, 1000 + 8000, 1000)
        rem_gpu_norm = (nodes.gpu_left - pod.num_gpu).astype(d) / ngpus
        sc_g = jnp.where(
            gpu_util > 0.1,
            base_g + jnp.trunc((1 - rem_gpu_norm) * 5000),
            base_g + 2000.0)
        sc_g = jnp.where((cpu_util > 0.1) & (gpu_util < 0.1),
                         jnp.maximum(1.0, sc_g - 5000), sc_g)
        sc_g = jnp.where(node_has_gpu, sc_g, 0.0)  # return 0 if no GPUs

        # CPU-pod branch
        sc_c_gpu_node = jnp.where(gpu_util > 0.1, 100.0, 1000.0)
        base_c = 1000.0 + 5000.0
        sc_c_plain = jnp.where(
            cpu_util > 0.2,
            base_c + jnp.trunc((1 - (rem_cpu + rem_mem) / 2) * 4000),
            base_c + 2000.0)
        sc_c = jnp.where(node_has_gpu, sc_c_gpu_node, sc_c_plain)

        score = jnp.where(pod_needs_gpu, sc_g, sc_c)
        balance = 1 - jnp.abs(rem_cpu - rem_mem)
        score = score + jnp.trunc(balance * 1000)
        # the gpu-pod/no-gpu-node case returned 0 before the balance bonus
        gate = f & jnp.where(pod_needs_gpu, node_has_gpu, True)
        return _finish(score, gate)

    return policy


def utilization_based(dtype=jnp.float32) -> PolicyFn:
    """Size-adaptive hybrid best/worst fit (funsearch_integration.py:355-401,
    shipped commented out)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        d = dtype
        ngpus = jnp.maximum(nodes.num_gpus, 1).astype(d)
        pod_size = jnp.maximum(
            jnp.maximum(pod.cpu_milli.astype(d) / nodes.cpu_milli_total.astype(d),
                        pod.memory_mib.astype(d) / nodes.memory_mib_total.astype(d)),
            pod.num_gpu.astype(d) / ngpus)
        rem_cpu = (nodes.cpu_milli_left - pod.cpu_milli).astype(d) / nodes.cpu_milli_total.astype(d)
        rem_mem = (nodes.memory_mib_left - pod.memory_mib).astype(d) / nodes.memory_mib_total.astype(d)
        rem_gpu = (nodes.gpu_left - pod.num_gpu).astype(d) / ngpus
        cur_util = 1 - jnp.minimum(
            nodes.cpu_milli_left.astype(d) / nodes.cpu_milli_total.astype(d),
            nodes.memory_mib_left.astype(d) / nodes.memory_mib_total.astype(d))

        large = jnp.trunc((rem_cpu + rem_mem + rem_gpu) * 3333)
        large = large + jnp.where(cur_util < 0.01, 5000.0, 0.0)
        small_mid = jnp.trunc((1 - (rem_cpu + rem_mem + rem_gpu) / 3) * 10000) + 2000
        small_hot = jnp.where(pod_size >= 0.1, 100.0, 8000.0)
        small = jnp.where((cur_util > 0.3) & (cur_util < 0.9), small_mid,
                          jnp.where(cur_util >= 0.9, small_hot, 100.0))
        score = jnp.where(pod_size > 0.3, large, small)
        return _finish(score, f)

    return policy


# ------------------------------------------------- FunSearch champion zoo

def funsearch_4901(dtype=jnp.float32) -> PolicyFn:
    """Champion, score 0.4901 (reference tests/test_scheduler.py:20-96)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        d = dtype
        gm = nodes.gpu_mask
        pod_gpu = pod.num_gpu > 0

        cpu_util = (nodes.cpu_milli_total - nodes.cpu_milli_left).astype(d) \
            / nodes.cpu_milli_total.astype(d)
        cpu_score = (1 - cpu_util) * jnp.where(cpu_util < 0.7, 100.0, 50.0)
        mem_util = (nodes.memory_mib_total - nodes.memory_mib_left).astype(d) \
            / nodes.memory_mib_total.astype(d)
        mem_score = (1 - mem_util) * jnp.where(mem_util < 0.7, 100.0, 50.0)

        free_milli = jnp.sum(jnp.where(gm, nodes.gpu_milli_left, 0), axis=1)
        cap0 = nodes.gpu_milli_total[:, 0]  # node.gpus[0].gpu_milli_total
        den = (nodes.gpu_left * cap0).astype(d)
        gpu_util = (den - free_milli.astype(d)) / _safe(den, den != 0, 1)
        gpu_score = jnp.where(
            pod_gpu,
            (1 - gpu_util) * jnp.where(gpu_util < 0.7, 200.0, 100.0), 0.0)

        score = cpu_score + mem_score + gpu_score
        # fragmentation penalty: (sum free milli) % pod.gpu_milli, int math
        mod = jnp.where(pod.gpu_milli > 0,
                        free_milli % jnp.maximum(pod.gpu_milli, 1), 0)
        score = score - jnp.where(pod_gpu, mod.astype(d) * 0.2, 0.0)

        low_cap = (nodes.cpu_milli_total < 2000) | (nodes.memory_mib_total < 12)
        score = score - jnp.where(
            low_cap,
            (2000 - nodes.cpu_milli_total).astype(d) * 0.01
            + (12 - nodes.memory_mib_total).astype(d) * 0.1, 0.0)

        balance = jnp.abs(
            nodes.cpu_milli_left.astype(d) / jnp.maximum(nodes.memory_mib_left, 1).astype(d)
            - pod.cpu_milli.astype(d) / jnp.maximum(pod.memory_mib, 1).astype(d))
        score = score - balance * 0.5

        ample = (nodes.cpu_milli_left > pod.cpu_milli * 2) \
            & (nodes.memory_mib_left > pod.memory_mib * 2)
        score = score + jnp.where(ample, 25.0, 0.0)

        gmax = jnp.max(jnp.where(gm, nodes.gpu_milli_left, -(2**30)), axis=1)
        gmin = jnp.min(jnp.where(gm, nodes.gpu_milli_left, 2**30), axis=1)
        imb = (gmax - gmin).astype(d)
        score = score - jnp.where(pod_gpu, imb * 0.05, 0.0)

        high_cap = (nodes.cpu_milli_total > 10000) & (nodes.memory_mib_total > 64)
        score = score + jnp.where(high_cap, 15.0, 0.0)
        nearly_full = (cpu_util > 0.9) | (mem_util > 0.9)
        score = score - jnp.where(nearly_full, 20.0, 0.0)
        return _finish(score, f)

    return policy


def funsearch_4816(dtype=jnp.float32) -> PolicyFn:
    """Champion, score 0.4816 (reference tests/test_scheduler.py:99-131)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        d = dtype
        cpu_util = (nodes.cpu_milli_total - nodes.cpu_milli_left + pod.cpu_milli).astype(d) \
            / jnp.maximum(nodes.cpu_milli_total, 1).astype(d)
        mem_util = (nodes.memory_mib_total - nodes.memory_mib_left + pod.memory_mib).astype(d) \
            / jnp.maximum(nodes.memory_mib_total, 1).astype(d)
        balance = 1 - jnp.abs(cpu_util - mem_util)
        efficiency = jnp.sqrt(cpu_util * mem_util)

        # eligible = first num_gpu GPUs (slot order) with milli_left >= req
        elig = nodes.gpu_mask & (nodes.gpu_milli_left >= pod.gpu_milli)
        rank = jnp.cumsum(elig.astype(jnp.int32), axis=1) - 1
        sel = elig & (rank < pod.num_gpu)
        seli = sel.astype(jnp.int32)
        sum_total = jnp.sum(nodes.gpu_milli_total * seli, axis=1)
        sum_left = jnp.sum(nodes.gpu_milli_left * seli, axis=1)
        nsel = jnp.sum(seli, axis=1)
        gpu_util = (sum_total - sum_left + nsel * pod.gpu_milli).astype(d) \
            / jnp.maximum(sum_total, 1).astype(d)
        sq = (nodes.gpu_milli_left - pod.gpu_milli) ** 2
        gpu_frag = jnp.sum(sq * seli, axis=1).astype(d) \
            / jnp.maximum(sum_left, 1).astype(d)
        isolation = 0.5 - jnp.abs(0.5 - jnp.sqrt(gpu_frag))
        score_gpu = (cpu_util * 0.25 + mem_util * 0.15 + gpu_util * 0.45
                     + balance * 0.05 + efficiency * 0.05
                     - gpu_frag * 0.05 + isolation * 0.1) * 10000

        frag_cpu = (nodes.cpu_milli_left % jnp.maximum(pod.cpu_milli, 1)).astype(d) \
            / nodes.cpu_milli_total.astype(d)
        frag_mem = (nodes.memory_mib_left % jnp.maximum(pod.memory_mib, 1)).astype(d) \
            / nodes.memory_mib_total.astype(d)
        frag = jnp.minimum(frag_cpu, frag_mem)
        score_cpu = (cpu_util * 0.45 + mem_util * 0.35 + balance * 0.1
                     + efficiency * 0.1 - frag * 0.1) * 10000

        score = jnp.where(pod.num_gpu > 0, score_gpu, score_cpu)
        return _finish(score, f)

    return policy


def funsearch_4800(dtype=jnp.float32) -> PolicyFn:
    """Champion, score 0.4800 (reference tests/test_scheduler.py:134-167)."""

    def policy(pod: PodView, nodes: NodeView):
        f = feasible_mask(pod, nodes)
        d = dtype
        g = nodes.gpu_milli_left.shape[1]
        cpu_util = (nodes.cpu_milli_total - nodes.cpu_milli_left + pod.cpu_milli).astype(d) \
            / nodes.cpu_milli_total.astype(d)
        mem_util = (nodes.memory_mib_total - nodes.memory_mib_left + pod.memory_mib).astype(d) \
            / nodes.memory_mib_total.astype(d)
        balance = (1 - jnp.abs(cpu_util - mem_util)) ** 2.5 * 300

        # viable sorted by milli_left asc (stable), take num_gpu
        elig = nodes.gpu_mask & (nodes.gpu_milli_left >= pod.gpu_milli)
        iota = jnp.arange(g, dtype=jnp.int32)
        key = jnp.where(elig, nodes.gpu_milli_left * g + iota, 2**30)
        order = jnp.argsort(key, axis=1)
        rank = jnp.zeros_like(key).at[
            jnp.arange(key.shape[0])[:, None], order].set(iota[None, :])
        sel = elig & (rank < pod.num_gpu)
        eff_terms = 1 - (nodes.gpu_milli_left - pod.gpu_milli).astype(d) \
            / jnp.maximum(nodes.gpu_milli_total, 1).astype(d)
        gpu_eff = jnp.sum(jnp.where(sel, eff_terms, 0), axis=1) \
            / jnp.maximum(pod.num_gpu, 1).astype(d)
        n_viable = jnp.sum(elig.astype(jnp.int32), axis=1)
        gpu_score = jnp.where((pod.num_gpu > 0) & (n_viable >= pod.num_gpu),
                              gpu_eff ** 2 * 450, 0.0)

        head = jnp.minimum(nodes.cpu_milli_left - pod.cpu_milli,
                           nodes.memory_mib_left - pod.memory_mib).astype(d)
        frag_score = jnp.maximum(head, 0) ** 0.6 \
            / jnp.maximum(nodes.cpu_milli_total, nodes.memory_mib_total).astype(d) * 300
        util_score = (jnp.minimum(cpu_util, mem_util) * 0.6
                      + jnp.maximum(cpu_util, mem_util) * 0.4) * 600
        return _finish(util_score + balance + gpu_score + frag_score, f)

    return policy


ZOO = {
    "first_fit": first_fit,
    "best_fit": best_fit,
    "funsearch_4901": funsearch_4901,
    "funsearch_4816": funsearch_4816,
    "funsearch_4800": funsearch_4800,
}

BASELINE_FACTORIES = {
    "first_fit": first_fit,
    "best_fit": best_fit,
    "worst_fit": worst_fit,
    "gpu_aware": gpu_aware,
    "utilization_based": utilization_based,
}
