"""Parametric scheduling policies: a fixed feature basis + weight vector.

This is the TPU fast path for population evaluation (SURVEY.md §7 key design
moves): where the reference evaluates each candidate policy as arbitrary
Python code in its own subprocess (reference: funsearch/funsearch_integration.py
:30-64, 535-562), a *parametric* candidate is just a weight vector over a
fixed library of placement features. The whole population then evaluates as
ONE ``vmap`` over the weight axis — a single XLA program, no per-candidate
compilation — and shards across a TPU mesh along the population axis
(fks_tpu.parallel).

Arbitrary LLM-generated code still works through the general path
(fks_tpu.funsearch.transpiler); this module is the throughput backbone and
the search space for gradient-free evolution (mutation = Gaussian jitter on
weights).

Score contract matches the reference policy shape (reference:
funsearch/safe_execution.py:174-224 template): infeasible nodes score 0;
feasible nodes score ``max(1, int(raw))`` so they are never refused.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from fks_tpu.models.zoo import feasible_mask
from fks_tpu.sim.types import NodeView, PodView, PolicyFn

#: Names of the feature basis, in order. Keep appended-only: persisted
#: checkpoints store weights positionally.
FEATURE_NAMES = (
    "bias",
    "rem_cpu_frac",      # (cpu_left - pod.cpu) / cpu_total
    "rem_mem_frac",      # (mem_left - pod.mem) / mem_total
    "rem_gpu_frac",      # (gpu_left - pod.ngpu) / num_gpus
    "cpu_util",          # used fraction before placement
    "mem_util",
    "gpu_count_util",
    "gpu_milli_util",    # node-level milli used fraction
    "balance",           # 1 - |cpu_util - mem_util|
    "frag_mod",          # (free_milli % pod.gpu_milli) / 1000, gpu pods only
    "eligible_frac",     # eligible GPUs / num_gpus for this pod
    "pod_is_gpu",
    "node_has_gpu",
    "best_fit",          # 1 - weighted normalized remaining (zoo best_fit core)
    "gpu_imbalance",     # (max - min free milli) / 1000
    "headroom",          # 1 if node has > 2x the pod's cpu AND mem free
)

NUM_FEATURES = len(FEATURE_NAMES)

#: Raw dot product is scaled by this before int truncation, so weights of
#: order 1 produce score magnitudes comparable to the reference zoo (~1e4).
SCORE_SCALE = 10_000.0


def features(pod: PodView, nodes: NodeView, dtype=jnp.float32):
    """Feature matrix f[N, F] for one pod against all nodes."""
    d = dtype
    cpu_tot = jnp.maximum(nodes.cpu_milli_total, 1).astype(d)
    mem_tot = jnp.maximum(nodes.memory_mib_total, 1).astype(d)
    ngpus = jnp.maximum(nodes.num_gpus, 1).astype(d)
    milli_tot = jnp.maximum(
        jnp.sum(jnp.where(nodes.gpu_mask, nodes.gpu_milli_total, 0), axis=1), 1
    ).astype(d)

    rem_cpu = (nodes.cpu_milli_left - pod.cpu_milli).astype(d) / cpu_tot
    rem_mem = (nodes.memory_mib_left - pod.memory_mib).astype(d) / mem_tot
    rem_gpu = (nodes.gpu_left - pod.num_gpu).astype(d) / ngpus
    cpu_util = 1 - nodes.cpu_milli_left.astype(d) / cpu_tot
    mem_util = 1 - nodes.memory_mib_left.astype(d) / mem_tot
    gpu_count_util = 1 - nodes.gpu_left.astype(d) / ngpus

    free_milli = jnp.sum(jnp.where(nodes.gpu_mask, nodes.gpu_milli_left, 0), axis=1)
    gpu_milli_util = 1 - free_milli.astype(d) / milli_tot

    balance = 1 - jnp.abs(cpu_util - mem_util)
    pod_gpu = pod.num_gpu > 0
    frag_mod = jnp.where(
        pod_gpu, (free_milli % jnp.maximum(pod.gpu_milli, 1)).astype(d) / 1000.0, 0.0)
    eligible = jnp.sum(
        (nodes.gpu_mask & (nodes.gpu_milli_left >= pod.gpu_milli)).astype(jnp.int32),
        axis=1)
    eligible_frac = eligible.astype(d) / ngpus
    node_has_gpu = (nodes.num_gpus > 0).astype(d)
    best_fit = 1 - (rem_cpu * 0.33 + rem_mem * 0.33 + rem_gpu * 0.34)
    gmax = jnp.max(jnp.where(nodes.gpu_mask, nodes.gpu_milli_left, 0), axis=1)
    gmin = jnp.min(jnp.where(nodes.gpu_mask, nodes.gpu_milli_left, 2**30), axis=1)
    gpu_imbalance = jnp.where(
        nodes.num_gpus > 0, (gmax - jnp.minimum(gmin, gmax)).astype(d) / 1000.0, 0.0)
    headroom = ((nodes.cpu_milli_left > pod.cpu_milli * 2)
                & (nodes.memory_mib_left > pod.memory_mib * 2)).astype(d)

    ones = jnp.ones_like(rem_cpu)
    return jnp.stack([
        ones, rem_cpu, rem_mem, rem_gpu, cpu_util, mem_util, gpu_count_util,
        gpu_milli_util, balance, frag_mod, eligible_frac,
        jnp.where(pod_gpu, ones, 0.0), node_has_gpu, best_fit, gpu_imbalance,
        headroom,
    ], axis=1)


def score(params, pod: PodView, nodes: NodeView, dtype=jnp.float32):
    """Parametric policy: ``max(1, int(f @ w * SCALE))`` under feasibility.

    ``params`` is f[F] (or any leading batch dims handled by an outer vmap).
    """
    f = features(pod, nodes, dtype)
    raw = f @ params.astype(dtype) * SCORE_SCALE
    as_int = jnp.trunc(raw).astype(jnp.int32)
    return jnp.where(feasible_mask(pod, nodes), jnp.maximum(1, as_int), 0)


def as_policy(params, dtype=jnp.float32) -> PolicyFn:
    """Close over a concrete weight vector -> a zoo-compatible PolicyFn."""
    return lambda pod, nodes: score(params, pod, nodes, dtype)


# ----------------------------------------------------------- seed weights

def seed_weights(name: str):
    """Hand-picked weight vectors reproducing the spirit (not the bit-exact
    arithmetic) of the reference baseline factories
    (reference: funsearch_integration.py:217-269)."""
    w = {n: 0.0 for n in FEATURE_NAMES}
    if name == "first_fit":
        w["bias"] = 0.1  # constant 1000 for every feasible node
    elif name == "best_fit":
        w["best_fit"] = 1.0
    elif name == "worst_fit":
        w["best_fit"] = -1.0
        w["bias"] = 1.0
    elif name == "packing":
        w["best_fit"] = 0.6
        w["gpu_milli_util"] = 0.3
        w["frag_mod"] = -0.2
        w["balance"] = 0.1
    else:
        raise KeyError(name)
    return jnp.asarray([w[n] for n in FEATURE_NAMES], jnp.float32)


def init_population(key, pop_size: int, noise: float = 0.1):
    """Seeds + Gaussian jitter: the t=0 population for parametric evolution."""
    seeds = jnp.stack([seed_weights(n)
                       for n in ("first_fit", "best_fit", "worst_fit", "packing")])
    reps = (pop_size + seeds.shape[0] - 1) // seeds.shape[0]
    base = jnp.tile(seeds, (reps, 1))[:pop_size]
    jitter = noise * jax.random.normal(key, base.shape, base.dtype)
    keep = jnp.arange(pop_size) < seeds.shape[0]  # keep the seeds themselves pure
    return jnp.where(keep[:, None], base, base + jitter)


def mutate(key, parents, pop_size: int, noise: float = 0.05):
    """Offspring = random parent + Gaussian noise (gradient-free step)."""
    k1, k2 = jax.random.split(key)
    idx = jax.random.randint(k1, (pop_size,), 0, parents.shape[0])
    base = parents[idx]
    return base + noise * jax.random.normal(k2, base.shape, base.dtype)


# ------------------------------------------------- weights -> candidate code

#: Restricted-Python rendering of each feature, in FEATURE_NAMES order.
#: The expressions use only the transpilable subset (and the reference's
#: whitelisted builtins, safe_execution.py:19-27), so a rendered candidate
#: flows through the normal code path: sandbox -> transpiler -> engine.
_FEATURE_EXPRS = (
    "1.0",
    "(node.cpu_milli_left - pod.cpu_milli) / max(1, node.cpu_milli_total)",
    "(node.memory_mib_left - pod.memory_mib) / max(1, node.memory_mib_total)",
    "(node.gpu_left - pod.num_gpu) / max(1, len(node.gpus))",
    "1.0 - node.cpu_milli_left / max(1, node.cpu_milli_total)",
    "1.0 - node.memory_mib_left / max(1, node.memory_mib_total)",
    "1.0 - node.gpu_left / max(1, len(node.gpus))",
    "1.0 - free_milli / max(1, total_milli)",
    "1.0 - abs(node.cpu_milli_left / max(1, node.cpu_milli_total)"
    " - node.memory_mib_left / max(1, node.memory_mib_total))",
    "((free_milli % max(1, pod.gpu_milli)) / 1000.0) if pod.num_gpu > 0 else 0.0",
    "sum(1 for gpu in node.gpus if gpu.gpu_milli_left >= pod.gpu_milli)"
    " / max(1, len(node.gpus))",
    "1.0 if pod.num_gpu > 0 else 0.0",
    "1.0 if len(node.gpus) > 0 else 0.0",
    "1.0 - (0.33 * (node.cpu_milli_left - pod.cpu_milli) / max(1, node.cpu_milli_total)"
    " + 0.33 * (node.memory_mib_left - pod.memory_mib) / max(1, node.memory_mib_total)"
    " + 0.34 * (node.gpu_left - pod.num_gpu) / max(1, len(node.gpus)))",
    "((max(gpu.gpu_milli_left for gpu in node.gpus)"
    " - min(gpu.gpu_milli_left for gpu in node.gpus)) / 1000.0)"
    " if len(node.gpus) > 0 else 0.0",
    "1.0 if (node.cpu_milli_left > 2 * pod.cpu_milli"
    " and node.memory_mib_left > 2 * pod.memory_mib) else 0.0",
)

#: features whose expression reads the free/total gpu_milli prologue vars
_NEEDS_MILLI = {"gpu_milli_util", "frag_mod"}


def render_code(params, threshold: float = 1e-4) -> str:
    """Render a weight vector as a reference-style candidate SOURCE — the
    bridge from the device-resident parametric search back into the code
    population: the rendered candidate re-enters through the normal
    sandbox/transpiler/dedup pipeline and is re-scored there, so rendering
    need not be bit-exact to the f32 on-device arithmetic (and is not).

    Near-zero weights are dropped to keep candidates short and readable.
    """
    import numpy as np

    from fks_tpu.funsearch import template

    w = np.asarray(params, np.float64)
    terms = []
    needs_milli = False
    for name, expr, wi in zip(FEATURE_NAMES, _FEATURE_EXPRS, w):
        if abs(wi) < threshold:
            continue
        terms.append(f"({wi:.6g}) * ({expr})")
        if name in _NEEDS_MILLI:
            needs_milli = True
    if not terms:
        terms = ["0.0"]
    lines = []
    if needs_milli:
        lines.append("free_milli = sum(gpu.gpu_milli_left for gpu in node.gpus)")
        lines.append(
            "total_milli = sum(gpu.gpu_milli_total for gpu in node.gpus)")
    body = "\n    + ".join(terms)
    lines.append(f"score = {SCORE_SCALE:.1f} * ({body})")
    return template.fill_template("\n".join("    " + l if i else l
                                            for i, l in enumerate(lines)))
