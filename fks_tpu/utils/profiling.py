"""Profiling hooks: wall-clock timing that respects async dispatch, JAX
device tracing, and throughput counters.

The reference's only instrumentation is ad-hoc ``time.time()`` deltas around
runs (reference: tests/test_scheduler.py:266-269, test_integration.py:130-137,
funsearch/funsearch_integration.py:586-589) — no profiler hooks at all
(SURVEY.md §5). Here timing is a first-class utility that (a) blocks on the
actual device result before stopping the clock (JAX dispatch is async; a
naive delta measures enqueue time, not compute), and (b) can capture a real
XLA profile for TensorBoard/xprof when a hotspot needs the instruction-level
view.
"""
from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, List, Optional

import jax


@dataclass
class Timing:
    """Result of a ``timed`` block. ``seconds`` is valid after the block."""

    label: str = ""
    seconds: float = 0.0
    _sync: Any = None

    def sync(self, value):
        """Register a value (any pytree of jax arrays) produced inside the
        block; the clock stops only after it is materialized on device.
        Returns the value for inline use."""
        self._sync = value
        return value


@contextlib.contextmanager
def timed(label: str = "", sync: Any = None,
          on_exit: Any = None) -> Iterator[Timing]:
    """Measure a block's wall time. For device work, register the block's
    output via ``t.sync(...)`` so the clock includes the actual compute
    (JAX dispatch is async; without a sync the delta measures enqueue
    time). ``sync=`` covers values that already exist at entry.

    ``on_exit`` (``Callable[[Timing], None]``) fires after the clock stops,
    device sync included — the extension point ``fks_tpu.obs.span`` builds
    its flight-recorder span events on (nesting, xprof mirroring, and the
    run-dir event live there; this stays the bare mechanism).

    >>> with timed("eval") as t:
    ...     result = t.sync(ev(params))
    >>> t.seconds
    """
    out = Timing(label=label, _sync=sync)
    t0 = time.perf_counter()
    try:
        yield out
    finally:
        if out._sync is not None:
            jax.block_until_ready(out._sync)
        out.seconds = time.perf_counter() - t0
        if on_exit is not None:
            on_exit(out)


def block_timed(fn, *args, **kwargs):
    """Call ``fn`` and return (result, seconds) with the result fully
    materialized — the one-liner version of ``timed``.

    The result must be a pytree of jax arrays (or plain scalars):
    ``jax.block_until_ready`` treats unregistered custom objects as opaque
    leaves and silently skips them, so wrapping a function that hides its
    arrays inside plain dataclasses would time only the enqueue."""
    t0 = time.perf_counter()
    result = fn(*args, **kwargs)
    jax.block_until_ready(result)
    return result, time.perf_counter() - t0


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Capture a JAX/XLA profile into ``logdir`` (viewable with
    TensorBoard's profile plugin / xprof). No-op if the profiler is
    unavailable on this backend."""
    try:
        jax.profiler.start_trace(logdir)
        started = True
    except Exception:  # pragma: no cover - backend without profiler
        started = False
    try:
        yield
    finally:
        if started:
            jax.profiler.stop_trace()


@dataclass
class ThroughputMeter:
    """Accumulate (count, seconds) batches; report rates.

    ``bench.py`` feeds it timed benchmark repetitions. ``rate`` is total
    count over total seconds (not a mean of rates, which would overweight
    small batches).
    """

    counts: List[float] = field(default_factory=list)
    seconds: List[float] = field(default_factory=list)

    def add(self, count: float, seconds: float) -> None:
        self.counts.append(float(count))
        self.seconds.append(float(seconds))

    @property
    def total_count(self) -> float:
        return sum(self.counts)

    @property
    def total_seconds(self) -> float:
        return sum(self.seconds)

    @property
    def rate(self) -> Optional[float]:
        """Items per second over everything recorded; None if no time."""
        if self.total_seconds <= 0:
            return None
        return self.total_count / self.total_seconds

    def summary(self) -> str:
        r = self.rate
        return (f"{self.total_count:.0f} in {self.total_seconds:.2f}s"
                + (f" = {r:.1f}/s" if r is not None else ""))
