"""Segment-length validation shared by every seg-steps surface.

The segmented runner's length knob appears in two places with the same
contract — ``FKS_VM_SEG_STEPS`` (environment, ``funsearch.backend``) and
the ``seg_steps`` argument of ``sim.flat.make_segmented_population_run``
— and historically each validated it with its own error text. One
helper keeps the messages and the 0-disables rule identical: a segment
length is a non-negative integer number of events, 0 means "do not
segment" (the env var disables the segmented tier; the runner, which
exists only to segment, points at ``make_population_run_fn`` instead).
"""
from __future__ import annotations

from typing import Any


def segment_budget(max_steps: int, seg_steps: int, *, slack: int = 1) -> int:
    """Dispatch budget for a segmented host loop: enough segments to
    consume ``max_steps`` events on a lane that never goes idle, plus
    ``slack`` observation segments. The classic loop needs slack 1 (one
    extra dispatch to OBSERVE the all-done flag after the draining
    segment); the double-buffered loop needs slack 2 (its flag lags one
    segment behind the dispatch front — see
    ``sim.flat.make_segmented_population_run``). Exhausting the budget
    with lanes still active means the step/cond predicates diverged, and
    callers raise rather than spin."""
    return -(-max_steps // seg_steps) + slack


def validate_seg_steps(value: Any, *, source: str = "seg_steps",
                       zero_disables: bool = True) -> int:
    """Validate a segment length and return it as an int.

    ``source`` names the knob in error messages (e.g. the env var).
    ``zero_disables=True`` accepts 0 as "segmentation off"; with False
    (the segmented runner itself) 0 is rejected with a pointer to the
    unsegmented entry point.
    """
    try:
        steps = int(value)
    except (TypeError, ValueError):
        raise ValueError(
            f"{source} must be an integer (segment length in events; "
            f"0 disables segmentation), got {value!r}") from None
    if steps < 0:
        raise ValueError(
            f"{source} must be >= 0 (0 disables segmentation), got {steps}")
    if steps == 0 and not zero_disables:
        raise ValueError(
            f"{source} must be positive, got {steps}; to disable "
            "segmentation use make_population_run_fn")
    return steps
