"""Structured logging + JSONL metrics.

The reference has no ``logging`` at all — every diagnostic is a bare
``print`` (SURVEY.md §5, grep-verified against the reference). This module
gives the framework a real observability spine without changing the
reference-parity output surfaces (the CLI still prints its tables):

- ``get_logger``: namespaced stdlib loggers under ``fks_tpu``, configured
  once, level from ``FKS_LOG_LEVEL`` (default INFO).
- ``MetricsWriter``: append-only JSONL records. The schema for simulation
  results mirrors the metric set the reference reports per run —
  ``EvaluationResults`` + policy_score + scheduled_pods + simulation_time +
  max_nodes (reference: simulator/evaluator.py:16-25, main.py:42,67-72,
  tests/test_scheduler.py:304-331) — so downstream tooling can consume
  either framework's numbers.
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import IO, Any, Dict, Optional

_CONFIGURED = False


def json_ready(obj: Any) -> Any:
    """``json.dumps`` ``default=`` hook: coerce numpy/jax leaves to plain
    Python. Scalars (``np.float32(...)``, 0-d ``jnp`` arrays, ``np.bool_``)
    become their Python value via ``.item()``; array leaves become nested
    lists via ``.tolist()``. Anything else re-raises ``TypeError`` exactly
    as ``json.dumps`` would, so genuinely unserializable records still fail
    loudly instead of silently degrading."""
    if getattr(obj, "ndim", None) == 0 and hasattr(obj, "item"):
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(
        f"Object of type {type(obj).__name__} is not JSON serializable")


def get_logger(name: str = "fks_tpu") -> logging.Logger:
    """Namespaced logger; configures the ``fks_tpu`` root exactly once."""
    global _CONFIGURED
    root = logging.getLogger("fks_tpu")
    if not _CONFIGURED:
        level = os.environ.get("FKS_LOG_LEVEL", "INFO").upper()
        root.setLevel(getattr(logging, level, logging.INFO))
        if not root.handlers:
            h = logging.StreamHandler()
            h.setFormatter(logging.Formatter(
                "%(asctime)s %(name)s %(levelname).1s %(message)s",
                datefmt="%H:%M:%S"))
            root.addHandler(h)
        root.propagate = False
        _CONFIGURED = True
    if name == "fks_tpu" or name.startswith("fks_tpu."):
        return logging.getLogger(name)
    return logging.getLogger(f"fks_tpu.{name}")


def result_record(result, **extra) -> Dict[str, Any]:
    """Flatten a ``SimResult`` into the reference-compatible metric schema
    (plain floats/ints, JSON-ready)."""
    rec = {
        "policy_score": float(result.policy_score),
        "avg_cpu_utilization": float(result.avg_cpu_utilization),
        "avg_memory_utilization": float(result.avg_memory_utilization),
        "avg_gpu_count_utilization": float(result.avg_gpu_count_utilization),
        "avg_gpu_memory_utilization": float(result.avg_gpu_memory_utilization),
        "gpu_fragmentation_score": float(result.gpu_fragmentation_score),
        "num_snapshots": int(result.num_snapshots),
        "num_fragmentation_events": int(result.num_fragmentation_events),
        "events_processed": int(result.events_processed),
        "scheduled_pods": int(result.scheduled_pods),
        "max_nodes": int(result.max_nodes),
        "failed": bool(result.failed),
        "truncated": bool(result.truncated),
    }
    rec.update(extra)
    return rec


class MetricsWriter:
    """Append JSON lines (one record per event) to a file or stream.

    Each record gets a ``ts`` wall-clock field. Writes are flushed per
    record so an interrupted run (the reference loses everything on crash
    except champion JSONs, SURVEY.md §5 checkpoint note) still leaves a
    complete metric trail.
    """

    def __init__(self, path_or_stream):
        if isinstance(path_or_stream, (str, os.PathLike)):
            parent = os.path.dirname(os.fspath(path_or_stream))
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._f: IO[str] = open(path_or_stream, "a")
            self._owns = True
        else:
            self._f = path_or_stream
            self._owns = False
        # writers are shared across threads (compile listeners fire from
        # the evaluator's thread pool); one line per write call, atomically
        self._lock = threading.Lock()

    def write(self, kind: str, record: Optional[Dict[str, Any]] = None,
              **fields) -> None:
        rec = {"ts": time.time(), "kind": kind}
        if record:
            rec.update(record)
        rec.update(fields)
        # json_ready: metric values routinely arrive as numpy/jax scalars
        # (``write(kind, score=jnp.float32(...))`` must emit a plain float,
        # not raise TypeError)
        line = json.dumps(rec, default=json_ready) + "\n"
        with self._lock:
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        if self._owns:
            self._f.close()

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
