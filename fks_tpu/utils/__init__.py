"""Observability utilities: profiling, logging, JSONL metrics.

The reference has neither profiler hooks nor ``logging`` (SURVEY.md §5);
these are framework additions with a reference-compatible metric schema.
"""
from fks_tpu.utils.logging import MetricsWriter, get_logger, result_record
from fks_tpu.utils.profiling import (
    ThroughputMeter, Timing, block_timed, device_trace, timed,
)

__all__ = [
    "MetricsWriter", "get_logger", "result_record",
    "ThroughputMeter", "Timing", "block_timed", "device_trace", "timed",
]
