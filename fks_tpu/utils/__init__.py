"""Observability utilities: profiling, logging, JSONL metrics.

The reference has neither profiler hooks nor ``logging`` (SURVEY.md §5);
these are framework additions with a reference-compatible metric schema.
"""
from fks_tpu.utils.compat import distributed_is_initialized, shard_map
from fks_tpu.utils.logging import MetricsWriter, get_logger, result_record
from fks_tpu.utils.profiling import (
    ThroughputMeter, Timing, block_timed, device_trace, timed,
)
from fks_tpu.utils.segments import validate_seg_steps

__all__ = [
    "MetricsWriter", "distributed_is_initialized", "get_logger",
    "result_record", "shard_map",
    "ThroughputMeter", "Timing", "block_timed", "device_trace", "timed",
    "validate_seg_steps",
]
