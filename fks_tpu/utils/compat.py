"""Cross-version jax API shims.

The API surfaces this repo uses that moved incompatibly across jax
releases:

- ``shard_map``: modern jax (>= 0.6) exposes top-level ``jax.shard_map``
  whose replication audit is spelled ``check_vma=`` (varying-manual-axes);
  jax 0.4/0.5 (this container ships 0.4.37) has the same transform at
  ``jax.experimental.shard_map.shard_map`` with the audit spelled
  ``check_rep=``.
- ``jax.distributed.is_initialized``: absent before jax 0.5; there the
  equivalent probe is whether the process-group client exists on the
  internal distributed state.

``shard_map`` below presents the MODERN keyword surface and translates to
whatever the installed jax provides, resolved ONCE at import. Every
shard_map call site in the repo (fks_tpu.parallel.mesh and the fused-engine
paths that compose with it) routes through here, so the next jax API move
is a one-file fix instead of a grep across the mesh layer.
"""
from __future__ import annotations

import inspect

import jax


def _resolve():
    """(implementation, audit-kwarg name) for the installed jax."""
    impl = getattr(jax, "shard_map", None)
    if impl is not None:
        params = inspect.signature(impl).parameters
        if "check_vma" in params:
            return impl, "check_vma"
        if "check_rep" in params:
            return impl, "check_rep"
    from jax.experimental.shard_map import shard_map as impl
    return impl, "check_rep"


_IMPL, _CHECK_KW = _resolve()


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Portable ``jax.shard_map``: modern signature on any supported jax.

    ``check_vma`` is forwarded as ``check_rep`` on jax versions that
    predate the rename; the audit's semantics are unchanged.
    """
    return _IMPL(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 **{_CHECK_KW: check_vma})


def distributed_is_initialized() -> bool:
    """Portable ``jax.distributed.is_initialized()`` (added in jax 0.5)."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    from jax._src import distributed
    return distributed.global_state.client is not None
