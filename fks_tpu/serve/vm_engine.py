"""VM-native serving: the champion is an ARGUMENT, not a closure constant.

``ServeEngine`` (serve.artifact) bakes the champion's policy into every
AOT executable as closure constants, so a promotion rebuilds the whole
bucket ladder — seconds of XLA compile for a swap that itself is one
attribute flip. ``VMServeEngine`` inverts that binding the same way the
evolve tier does (fks_tpu.funsearch.vm runs a heterogeneous population
through ONE compiled engine): the champion is lowered to a ``VMProgram``
register program, NOP-padded to a capacity bucket, and passed to the
executable as a device-resident pytree input alongside the batched
queries. One executable per (lane_bucket, pod_bucket, program_capacity)
then serves EVERY champion of that capacity bucket, and a hot-swap
degenerates to ``swap_program``: transpile + lower + pack + H2D upload
of the new opcode/constant tables — zero XLA compiles, microseconds of
device traffic (the evosax / population-based-RL move: replace
per-member compilation with parameter upload).

The program tables are deliberately NOT donated to the executable: they
are the resident champion, reused by every batch until the next swap
(the snapshot-ktable precedent — donation would invalidate the buffer
after one call). The per-batch pods/state buffers stay donated exactly
as in the AOT engine.

Champions outside the VM vocabulary raise ``VMUnsupported`` from the
constructor / ``swap_program`` — the caller (cli serve, the promotion
controller's fast path) falls back to the AOT closure engine, which
remains the exact reference and the escape hatch.
"""
from __future__ import annotations

import copy
import dataclasses
import hashlib
import threading
import time
import warnings
from collections import OrderedDict
from typing import Optional

import jax

from fks_tpu import obs
from fks_tpu.data.entities import Workload
from fks_tpu.obs.memory import record_footprint
from fks_tpu.funsearch import vm
from fks_tpu.parallel.mesh import make_sharded_vm_serve_fn
from fks_tpu.serve.artifact import ChampionSpec, ServeEngine
from fks_tpu.serve.batcher import (
    pack_program_tables, tree_h2d_bytes, unpack_program_tables,
    unpack_query_tables,
)
from fks_tpu.sim.engine import run_batched_lanes


class VMServeEngine(ServeEngine):
    """A serve engine whose executables are champion-agnostic.

    Construction lowers the champion via ``vm.compile_policy`` and pads
    it to ``program_capacity`` (default: ``vm.capacity_bucket`` of the
    lowered op count) — ``VMUnsupported`` propagates to the caller, the
    AOT-fallback trigger. Everything else (shape envelope, bucket
    routing, snapshot-table cache, double-buffered dispatch, mesh
    sharding) is inherited; the executables differ only in taking the
    packed program tables as argument 0, replicated across the mesh
    (``make_sharded_vm_serve_fn``) while the lane axes shard as before.

    ``swap_program(champion)`` is the whole promotion hot path: it
    re-binds the served champion IN PLACE under a lock that excludes
    in-flight batches, and returns the previous ``ChampionSpec`` as the
    rollback handle (``ServeService.swap_engine`` accepts a
    ``ChampionSpec`` and routes it here)."""

    engine_kind = "vm"

    def __init__(self, champion: ChampionSpec, workload: Workload, *,
                 program_capacity: Optional[int] = None, **kw):
        # set BEFORE super().__init__: the parent constructor resolves
        # the policy (which fixes the capacity bucket) during init
        self._capacity_override = (int(program_capacity)
                                   if program_capacity else None)
        self.program_capacity = 0
        self.vm_swaps = 0
        self.vm_swap_h2d_bytes = 0
        self.last_swap_breakdown: dict = {}
        # host-side transpile cache: canonical code key -> padded
        # VMProgram. The transpile is ~60ms of the 64ms swap
        # (ROADMAP-named); a probation rollback or A/B flip re-swaps a
        # champion this engine already lowered, so the warm swap is the
        # H2D upload alone. Bounded FIFO — programs are a few KB.
        self._transpile_cache: "OrderedDict[tuple, vm.VMProgram]" = \
            OrderedDict()
        self._transpile_cache_max = 32
        self.transpile_cache_hits = 0
        self.transpile_cache_misses = 0
        # the cache is shared with shadow views AND (promotion overlap)
        # a background transpile worker — all access goes under this lock
        self._transpile_lock = threading.Lock()
        # code keys whose transpile was overlapped with shadow eval: the
        # next swap of that champion reports transpile_overlapped=True
        self._overlap_warmed: set = set()
        # swaps exclude in-flight batches: answer_batch holds this for
        # the whole batch, swap_program for the pointer flip only
        self._swap_lock = threading.RLock()
        super().__init__(champion, workload, **kw)
        self._prog_dev = self._upload_program(self.params)

    # ----- champion lowering / residency

    def _resolve_policy(self, code: str, n: int, g: int):
        """Champion source -> (score_static, padded VMProgram, "vm").
        No jit fallback here — a champion outside the VM vocabulary
        raises ``VMUnsupported`` to the caller, who serves it on the AOT
        closure engine instead."""
        prog = vm.compile_policy(code, n, g)
        cap = self._capacity_override or vm.capacity_bucket(int(prog.n_ops))
        prog = vm.pad_capacity(prog, cap)  # VMUnsupported if too long
        self.program_capacity = cap
        # seed the transpile cache: re-swapping the construction
        # champion (rollback after a failed promotion) is a warm swap
        with self._transpile_lock:
            self._transpile_cache[self._code_key(code, n, g, cap)] = prog
        return vm.score_static, prog, "vm"

    @staticmethod
    def _code_key(code: str, n: int, g: int, cap: int) -> tuple:
        """Canonical transpile-cache key: exact content hash of the
        champion source plus the lowering shape. NOT the analysis-layer
        ``fingerprint`` — that one buckets constants by decade (dedup
        semantics), which would alias two DIFFERENT champions onto one
        cached program. A swap must serve exactly what was promoted."""
        return (hashlib.sha256(code.encode()).hexdigest(), n, g, cap)

    def _lower_champion(self, code: str, n: int, g: int) -> tuple:
        """``compile_policy`` + ``pad_capacity`` through the host-side
        cache; returns ``(prog, "hit"|"miss")``. ``VMUnsupported``
        propagates uncached — a rejected champion must re-raise on
        retry, not silently hit."""
        key = self._code_key(code, n, g, self.program_capacity)
        with self._transpile_lock:
            hit = self._transpile_cache.get(key)
            if hit is not None:
                self.transpile_cache_hits += 1
                self._transpile_cache.move_to_end(key)
                return hit, "hit"
        prog = vm.pad_capacity(vm.compile_policy(code, n, g),
                               self.program_capacity)
        with self._transpile_lock:
            self.transpile_cache_misses += 1
            self._transpile_cache[key] = prog
            while len(self._transpile_cache) > self._transpile_cache_max:
                self._transpile_cache.popitem(last=False)
        return prog, "miss"

    def begin_overlapped_transpile(self, champion: ChampionSpec):
        """Kick the host-side transpile of ``champion`` on a worker
        thread — the promotion controller calls this when an attempt
        enters SHADOW, so the ~60ms ``compile_policy`` on a cache miss
        overlaps the shadow replay instead of sitting on the commit
        swap's critical path. The worker lowers THROUGH the shared
        transpile cache (lock-guarded — a racing swap that gets there
        first simply wins and the worker hits); the next swap of this
        champion reports ``transpile_overlapped=True`` in its vm_swap /
        slot_swap event. ``VMUnsupported`` candidates are swallowed —
        the swap itself re-raises with full context. Returns the thread
        (joinable in tests)."""
        n, g = self.cluster.n_padded, self.cluster.g_padded
        key = self._code_key(champion.code, n, g, self.program_capacity)

        def _work() -> None:
            try:
                self._lower_champion(champion.code, n, g)
            except vm.VMUnsupported:
                return
            with self._transpile_lock:
                self._overlap_warmed.add(key)

        thread = threading.Thread(target=_work, daemon=True,
                                  name="vm-transpile-overlap")
        thread.start()
        return thread

    def _consume_overlap(self, key: tuple) -> bool:
        """Whether this swap's transpile was prewarmed by an overlapped
        worker (one-shot: the flag is consumed)."""
        with self._transpile_lock:
            if key in self._overlap_warmed:
                self._overlap_warmed.discard(key)
                return True
            return False

    def _upload_program(self, prog: vm.VMProgram):
        """Packed program tables -> device-resident pytree (replicated
        across the mesh), synchronously — the swap's H2D cost must be on
        the swap, not smeared into the next batch."""
        packed = pack_program_tables(prog)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            dev = jax.device_put(packed,
                                 NamedSharding(self.mesh, PartitionSpec()))
        else:
            dev = jax.device_put(packed)
        jax.block_until_ready(dev)
        return dev

    def swap_program(self, champion: ChampionSpec) -> ChampionSpec:
        """The zero-rebuild promotion hot path: lower the new champion,
        pad to THIS engine's capacity bucket, upload the packed tables,
        flip the resident pointers. Raises ``VMUnsupported`` (champion
        outside the vocabulary, or longer than the bucket) with the
        engine untouched. Returns the previous champion — the rollback
        handle; rolling back is another ``swap_program``."""
        t0 = time.perf_counter()
        n, g = self.cluster.n_padded, self.cluster.g_padded
        prog, cache = self._lower_champion(champion.code, n, g)
        overlapped = self._consume_overlap(
            self._code_key(champion.code, n, g, self.program_capacity))
        t1 = time.perf_counter()
        dev = self._upload_program(prog)
        t2 = time.perf_counter()
        h2d = tree_h2d_bytes(pack_program_tables(prog))
        with self._swap_lock:  # exclude in-flight batches for the flip
            old = self.champion
            self.champion = champion
            self.params = prog
            self._prog_dev = dev
        self.vm_swaps += 1
        self.vm_swap_h2d_bytes += h2d
        self.last_swap_breakdown = {
            "transpile_ms": round((t1 - t0) * 1e3, 3),
            "h2d_ms": round((t2 - t1) * 1e3, 3),
            "swap_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "h2d_bytes": h2d,
            "capacity": self.program_capacity,
            "transpile_cache": cache,
            "transpile_overlapped": overlapped,
            "transpile_cache_hits": self.transpile_cache_hits,
            "transpile_cache_misses": self.transpile_cache_misses,
        }
        self.recorder.event("vm_swap", outcome="swapped",
                            champion=champion.source or "<inline>",
                            **self.last_swap_breakdown)
        return old

    def shadow_for(self, champion: ChampionSpec) -> "VMServeEngine":
        """A shadow VIEW of this engine serving ``champion``: shares the
        compiled executable set and the device snapshot cache (warm by
        construction — shadow evaluation compiles nothing) with its own
        champion tables, so the promotion controller can replay traffic
        through the candidate while the incumbent keeps serving.
        ``VMUnsupported`` propagates — the controller's AOT-fallback
        trigger."""
        n, g = self.cluster.n_padded, self.cluster.g_padded
        # shares the incumbent's transpile cache too: promoting the
        # champion just shadow-evaluated is then a warm swap
        prog, _ = self._lower_champion(champion.code, n, g)
        shadow = copy.copy(self)
        shadow.champion = champion
        shadow.params = prog
        shadow._prog_dev = self._upload_program(prog)
        shadow._swap_lock = threading.RLock()
        shadow.last_batch_timing = {"pack_h2d_s": 0.0, "dispatch_s": 0.0}
        shadow.last_swap_breakdown = {}
        return shadow

    # ----- compilation (champion-agnostic executables)

    def _make_serve_fn(self, pod_bucket: int):
        """The parent's batched pipeline with the program as a traced
        argument: ONE program drives every lane (in_axes=None — the
        single-tenant case of the portfolio layout), so the register
        program is loop-invariant and XLA hoists the table reads."""
        cfg = self.bucket_config(pod_bucket)
        max_steps = cfg.max_steps
        mod = self._mod
        plan = self._pack_plan(pod_bucket)
        cluster = dataclasses.replace(self.cluster, node_ids=())

        def step_one(prog, p, k, s):
            w = Workload(cluster=cluster, pods=p, faults=None)
            return mod.build_step(
                w, lambda pod, nodes: vm.score_static(prog, pod, nodes),
                cfg, k, max_steps)(s)

        vstep = jax.vmap(step_one, in_axes=(None, 0, 0, 0))
        vfin = jax.vmap(
            lambda p, s: mod.finalize(
                Workload(cluster=cluster, pods=p, faults=None), cfg, s),
            in_axes=(0, 0))

        def serve_fn(packed, pods, kt, state0):
            prog = unpack_program_tables(packed)
            pods, kt = unpack_query_tables(pods, kt, plan)
            final = run_batched_lanes(
                lambda s: vstep(prog, pods, kt, s), state0,
                max_steps, active_fn=mod.lane_active)
            return vfin(pods, final)

        return serve_fn

    def compiled_for(self, lanes: int, pod_bucket: int):
        """The (lanes, pod_bucket, program_capacity) AOT executable —
        keyed on the CAPACITY BUCKET, never the champion, so it survives
        every ``swap_program``. pods (arg 1) and state0 (arg 3) are
        donated per batch; the resident program tables (arg 0) and the
        cached ktable (arg 2) are NOT — their buffers outlive the call."""
        key = (lanes, pod_bucket, self.program_capacity)
        hit = self._compiled.get(key)
        if hit is not None:
            return hit
        with self.profiler.stage("compile", lanes=lanes, pods=pod_bucket):
            with obs.span("serve_compile", lanes=lanes, pods=pod_bucket,
                          engine=self.engine_name,
                          capacity=self.program_capacity):
                fn = self._make_serve_fn(pod_bucket)
                if self.mesh is not None:
                    fn = make_sharded_vm_serve_fn(fn, self.mesh)
                from fks_tpu.obs.layout import default_spec
                self._layout_key = getattr(fn, "_fks_layout_key",
                                           default_spec().key)
                example = ((self._prog_dev,)
                           + super()._example_batch(lanes, pod_bucket))
                with warnings.catch_warnings():
                    warnings.filterwarnings("ignore",
                                            message="Some donated")
                    compiled = jax.jit(fn, donate_argnums=(1, 3)) \
                        .lower(*example).compile()
        self._compiled[key] = compiled
        self.cold_compiles += 1
        # footprint ledger: the capacity-bucket executable's predicted
        # HBM claim — shared by every champion it will ever serve
        record_footprint(
            "serve_vm",
            f"lanes={lanes},pods={pod_bucket},cap={self.program_capacity}",
            compiled, mesh=self.mesh, recorder=self.recorder,
            engine=self.engine_name, engine_kind=self.engine_kind,
            layout_key=self._layout_key)
        return compiled

    # ----- answering

    def _invoke(self, compiled, pods, kt_dev, s0):
        return compiled(self._prog_dev, pods, kt_dev, s0)

    def answer_batch(self, pod_lists):
        # a whole batch answers under ONE champion: swap_program's flip
        # waits for the in-flight batch instead of tearing it
        with self._swap_lock:
            return super().answer_batch(pod_lists)
