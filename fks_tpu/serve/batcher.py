"""Request coalescing onto the population axis.

The serving insight (ISSUE 8; "Speeding up Policy Simulation in Supply
Chain RL"): the engine already knows how to run many independent lanes in
ONE compiled program — the population/trace-batch machinery
(fks_tpu.parallel). A what-if query is just a one-trace lane, so N
concurrent queries cost one vmapped call: build each query's padded
workload, stack them exactly like ``parallel.traces.stack_traces`` does,
pad the lane axis to the compiled lane bucket with
``parallel.mesh.pad_population`` (the population padder IS the request
batcher), run the AOT executable, and scatter each lane's answer back to
its request.

Three layers here, none of which import the artifact layer (so the
dependency points artifact -> batcher):

- query -> padded ``Workload`` construction (``build_query_workload``)
  + leaf-wise stacking with sentinel-padded snapshot tables
  (``stack_queries``), mirroring ``stack_traces`` at a FIXED bucket
  shape so every same-bucket batch shares one treedef and one aval set;
- ``RequestBatcher``: the flush-policy coalescer (max batch / max wait)
  mapping concurrent ``submit()`` futures onto synchronous batch calls;
- pod-array <-> dict conversion helpers shared by the CLI/service layer.
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu.data.entities import PodArrays, Workload
from fks_tpu.parallel.traces import strip_ids
from fks_tpu.sim.evaluator import max_snapshot_count, snapshot_trigger_table

#: query pod schema — the reference entity field names (simulator/
#: entities.py:29-43), matching the LLM-facing template docstring
POD_FIELDS = ("cpu_milli", "memory_mib", "num_gpu", "gpu_milli",
              "creation_time", "duration_time")

#: default lifetime for query pods that omit duration_time: effectively
#: "never deleted inside the what-if horizon"
DEFAULT_DURATION = 1_000_000


def validate_query_pods(pods: Sequence[Dict[str, Any]], *, max_pods: int,
                        max_gpu_milli: int) -> None:
    """Reject malformed queries before any device work (the error message
    is the service's 4xx body)."""
    if not pods:
        raise ValueError("query has no pods")
    if len(pods) > max_pods:
        raise ValueError(
            f"query has {len(pods)} pods > envelope max_pods {max_pods}")
    for i, p in enumerate(pods):
        if not isinstance(p, dict):
            raise ValueError(f"pod {i} is not an object")
        gm = int(p.get("gpu_milli", 0))
        if gm > max_gpu_milli:
            raise ValueError(
                f"pod {i} gpu_milli {gm} > envelope max_gpu_milli "
                f"{max_gpu_milli}")
        for field in ("cpu_milli", "memory_mib", "num_gpu", "gpu_milli"):
            if int(p.get(field, 0)) < 0:
                raise ValueError(f"pod {i} {field} is negative")


def build_query_workload(cluster, pods: Sequence[Dict[str, Any]],
                         bucket: int) -> Workload:
    """One query -> a ``Workload`` padded to the pod bucket.

    Pod ids are zero-padded ordinals, so the reference's lexicographic
    tie order equals index order and ``tie_rank = arange`` reproduces it
    exactly. Padding rows are zeros under a False pod_mask (the
    ``pad_workload`` idiom — never read by the engine)."""
    p_real = len(pods)
    if p_real > bucket:
        raise ValueError(f"{p_real} pods exceed pod bucket {bucket}")

    def col(field: str, default: int = 0) -> np.ndarray:
        a = np.zeros(bucket, np.int32)
        for i, p in enumerate(pods):
            a[i] = int(p.get(field, default))
        return a

    pa = PodArrays(
        cpu=col("cpu_milli"),
        mem=col("memory_mib"),
        num_gpu=col("num_gpu"),
        gpu_milli=col("gpu_milli"),
        creation_time=col("creation_time"),
        duration=col("duration_time", DEFAULT_DURATION),
        tie_rank=np.arange(bucket, dtype=np.int32),
        pod_mask=np.arange(bucket) < p_real,
        pod_ids=tuple(f"q-{i:05d}" for i in range(p_real)),
    )
    return Workload(cluster=cluster, pods=pa, faults=None)


def stack_queries(mod, cluster, pod_lists: Sequence[Sequence[dict]],
                  bucket: int, cfg, klen: int):
    """Stack Q query workloads into (workload[Q,...], ktable[Q,K],
    state0[Q,...]) at the bucket's fixed shapes.

    The ``stack_traces`` recipe with serving's extra constraint: K
    (``klen``) is fixed per bucket so every batch matches the AOT
    executable's avals. Each query's snapshot table is sized from its
    REAL pod count (the reference's ``initialize(total_events)``
    semantics) and padded with the INT32_MAX sentinel, which never fires.
    ``cfg.max_steps`` must be the bucket's resolved step budget."""
    max_steps = cfg.max_steps
    assert max_steps is not None, "bucket SimConfig must pin max_steps"
    wls = [build_query_workload(cluster, p, bucket) for p in pod_lists]
    sentinel = np.iinfo(np.int32).max
    kt = np.full((len(wls), klen), sentinel, np.int32)
    for i, w in enumerate(wls):
        tbl = snapshot_trigger_table(
            w.num_pods,
            max_snapshot_count(max_steps, w.num_pods, cfg.snapshot_interval),
            cfg.snapshot_interval)
        if len(tbl) > klen:
            raise ValueError(
                f"query with {w.num_pods} pods needs {len(tbl)} snapshot "
                f"slots > bucket table width {klen}; route it to a smaller "
                "bucket")
        kt[i, : len(tbl)] = tbl
    states = [mod.initial_state(w, cfg) for w in wls]
    stacked_wl = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[strip_ids(w) for w in wls])
    stacked_state = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *states)
    return stacked_wl, jnp.asarray(kt), stacked_state


def pods_to_dicts(pods: PodArrays, limit: Optional[int] = None) -> List[dict]:
    """Real pod rows back to query-schema dicts — sources selftest and
    trace-replay queries from a parsed workload."""
    mask = np.asarray(pods.pod_mask)
    idx = np.nonzero(mask)[0]
    if limit is not None:
        idx = idx[:limit]
    cols = {
        "cpu_milli": np.asarray(pods.cpu),
        "memory_mib": np.asarray(pods.mem),
        "num_gpu": np.asarray(pods.num_gpu),
        "gpu_milli": np.asarray(pods.gpu_milli),
        "creation_time": np.asarray(pods.creation_time),
        "duration_time": np.asarray(pods.duration),
    }
    return [{k: int(v[i]) for k, v in cols.items()} for i in idx]


class RequestBatcher:
    """Flush-policy request coalescer over a synchronous batch handler.

    ``submit(query)`` returns a Future; a daemon thread accumulates
    pending requests and flushes a batch when it reaches ``max_batch``
    OR the oldest pending request has waited ``max_wait_s`` — the
    classic latency/occupancy trade. The handler receives
    ``(queries, enqueue_times)`` and returns one answer per query in
    order (scatter-back is positional); a handler exception fails every
    future in the batch. ``close()`` flushes the remainder and joins."""

    def __init__(self, handle_batch: Callable[[list, list], list],
                 max_batch: int = 8, max_wait_s: float = 0.005):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._handle = handle_batch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self.batches = 0
        self.submitted = 0
        self._occupancy_sum = 0.0
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True)
        self._thread.start()

    def submit(self, query) -> Future:
        if self._closed:
            raise RuntimeError("batcher is closed")
        self.submitted += 1
        fut: Future = Future()
        self._q.put((query, fut, time.perf_counter()))
        return fut

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join()

    @property
    def mean_occupancy(self) -> float:
        """Mean fraction of max_batch filled per flushed batch."""
        return self._occupancy_sum / self.batches if self.batches else 0.0

    # ----- internals

    def _loop(self) -> None:
        pending: list = []
        while True:
            timeout = None
            if pending:
                waited = time.perf_counter() - pending[0][2]
                timeout = max(0.0, self.max_wait_s - waited)
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:  # oldest request hit max_wait
                self._flush(pending)
                pending = []
                continue
            if item is None:  # close sentinel
                self._flush(pending)
                return
            pending.append(item)
            if len(pending) >= self.max_batch:
                self._flush(pending)
                pending = []

    def _flush(self, pending: list) -> None:
        if not pending:
            return
        self.batches += 1
        self._occupancy_sum += len(pending) / self.max_batch
        queries = [q for q, _, _ in pending]
        enq = [t for _, _, t in pending]
        try:
            answers = self._handle(queries, enq)
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            for _, fut, _ in pending:
                fut.set_exception(e)
            return
        for (_, fut, _), ans in zip(pending, answers):
            fut.set_result(ans)
