"""Request coalescing onto the population axis.

The serving insight (ISSUE 8; "Speeding up Policy Simulation in Supply
Chain RL"): the engine already knows how to run many independent lanes in
ONE compiled program — the population/trace-batch machinery
(fks_tpu.parallel). A what-if query is just a one-trace lane, so N
concurrent queries cost one vmapped call: build each query's padded
workload, stack them exactly like ``parallel.traces.stack_traces`` does,
pad the lane axis to the compiled lane bucket with
``parallel.mesh.pad_population`` (the population padder IS the request
batcher), run the AOT executable, and scatter each lane's answer back to
its request.

Three layers here, none of which import the artifact layer (so the
dependency points artifact -> batcher):

- query -> padded ``Workload`` construction (``build_query_workload``)
  + leaf-wise stacking with sentinel-padded snapshot tables
  (``stack_queries``), mirroring ``stack_traces`` at a FIXED bucket
  shape so every same-bucket batch shares one treedef and one aval set;
- ``RequestBatcher``: the flush-policy coalescer (max batch / max wait)
  mapping concurrent ``submit()`` futures onto synchronous batch calls;
- pod-array <-> dict conversion helpers shared by the CLI/service layer.
"""
from __future__ import annotations

import dataclasses
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu.data.entities import PodArrays, Workload
from fks_tpu.obs import trace_ctx
from fks_tpu.parallel.traces import strip_ids
from fks_tpu.resilience.admission import AdmissionConfig, AdmissionController
from fks_tpu.resilience.deadline import (
    Deadline, DeadlineExceeded, ResilienceError, ShedError,
)
from fks_tpu.sim.evaluator import max_snapshot_count, snapshot_trigger_table

#: query pod schema — the reference entity field names (simulator/
#: entities.py:29-43), matching the LLM-facing template docstring
POD_FIELDS = ("cpu_milli", "memory_mib", "num_gpu", "gpu_milli",
              "creation_time", "duration_time")

#: default lifetime for query pods that omit duration_time: effectively
#: "never deleted inside the what-if horizon"
DEFAULT_DURATION = 1_000_000


def validate_query_pods(pods: Sequence[Dict[str, Any]], *, max_pods: int,
                        max_gpu_milli: int) -> None:
    """Reject malformed queries before any device work (the error message
    is the service's 4xx body)."""
    if not pods:
        raise ValueError("query has no pods")
    if len(pods) > max_pods:
        raise ValueError(
            f"query has {len(pods)} pods > envelope max_pods {max_pods}")
    for i, p in enumerate(pods):
        if not isinstance(p, dict):
            raise ValueError(f"pod {i} is not an object")
        gm = int(p.get("gpu_milli", 0))
        if gm > max_gpu_milli:
            raise ValueError(
                f"pod {i} gpu_milli {gm} > envelope max_gpu_milli "
                f"{max_gpu_milli}")
        for field in ("cpu_milli", "memory_mib", "num_gpu", "gpu_milli"):
            if int(p.get(field, 0)) < 0:
                raise ValueError(f"pod {i} {field} is negative")


def build_query_workload(cluster, pods: Sequence[Dict[str, Any]],
                         bucket: int) -> Workload:
    """One query -> a ``Workload`` padded to the pod bucket.

    Pod ids are zero-padded ordinals, so the reference's lexicographic
    tie order equals index order and ``tie_rank = arange`` reproduces it
    exactly. Padding rows are zeros under a False pod_mask (the
    ``pad_workload`` idiom — never read by the engine)."""
    p_real = len(pods)
    if p_real > bucket:
        raise ValueError(f"{p_real} pods exceed pod bucket {bucket}")

    def col(field: str, default: int = 0) -> np.ndarray:
        a = np.zeros(bucket, np.int32)
        for i, p in enumerate(pods):
            a[i] = int(p.get(field, default))
        return a

    pa = PodArrays(
        cpu=col("cpu_milli"),
        mem=col("memory_mib"),
        num_gpu=col("num_gpu"),
        gpu_milli=col("gpu_milli"),
        creation_time=col("creation_time"),
        duration=col("duration_time", DEFAULT_DURATION),
        tie_rank=np.arange(bucket, dtype=np.int32),
        pod_mask=np.arange(bucket) < p_real,
        pod_ids=tuple(f"q-{i:05d}" for i in range(p_real)),
    )
    return Workload(cluster=cluster, pods=pa, faults=None)


def _query_ktable(wls: Sequence[Workload], cfg, klen: int) -> np.ndarray:
    """Per-query snapshot trigger tables at the bucket's fixed width:
    each table is sized from the query's REAL pod count (the reference's
    ``initialize(total_events)`` semantics) and padded with the INT32_MAX
    sentinel, which never fires."""
    kt = np.full((len(wls), klen), KT_SENTINEL, np.int32)
    for i, w in enumerate(wls):
        tbl = snapshot_trigger_table(
            w.num_pods,
            max_snapshot_count(cfg.max_steps, w.num_pods,
                               cfg.snapshot_interval),
            cfg.snapshot_interval)
        if len(tbl) > klen:
            raise ValueError(
                f"query with {w.num_pods} pods needs {len(tbl)} snapshot "
                f"slots > bucket table width {klen}; route it to a smaller "
                "bucket")
        kt[i, : len(tbl)] = tbl
    return kt


def stack_queries(mod, cluster, pod_lists: Sequence[Sequence[dict]],
                  bucket: int, cfg, klen: int):
    """Stack Q query workloads into (workload[Q,...], ktable[Q,K],
    state0[Q,...]) at the bucket's fixed shapes.

    The ``stack_traces`` recipe with serving's extra constraint: K
    (``klen``) is fixed per bucket so every batch matches the AOT
    executable's avals. ``cfg.max_steps`` must be the bucket's resolved
    step budget. This is the historical full-workload stacking entry;
    the mesh-sharded hot path uses ``stack_query_tables``, which splits
    the constant cluster out of the per-batch upload."""
    max_steps = cfg.max_steps
    assert max_steps is not None, "bucket SimConfig must pin max_steps"
    wls = [build_query_workload(cluster, p, bucket) for p in pod_lists]
    kt = _query_ktable(wls, cfg, klen)
    states = [mod.initial_state(w, cfg) for w in wls]
    stacked_wl = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[strip_ids(w) for w in wls])
    stacked_state = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *states)
    return stacked_wl, jnp.asarray(kt), stacked_state


def stack_query_tables(mod, cluster, pod_lists: Sequence[Sequence[dict]],
                       bucket: int, cfg, klen: int):
    """``stack_queries`` split for the device-resident serve hot path:
    returns ``(pods[Q,...] numpy, ktable[Q,K] numpy, state0[Q,...])``.

    The constant cluster arrays are NOT stacked or returned — the serve
    engine bakes them into the compiled program as closure constants, so
    a batch ships only the query delta (pod tables), the snapshot trigger
    table (content-hash cached on device by the engine), and the initial
    state. Pods and ktable stay host-side numpy so the engine can hash
    the ktable bytes BEFORE any transfer and account every uploaded
    byte; the upload itself is one explicit ``device_put`` at the
    engine's h2d stage."""
    max_steps = cfg.max_steps
    assert max_steps is not None, "bucket SimConfig must pin max_steps"
    wls = [build_query_workload(cluster, p, bucket) for p in pod_lists]
    kt = _query_ktable(wls, cfg, klen)
    stacked_pods = jax.tree_util.tree_map(
        lambda *xs: np.stack([np.asarray(x) for x in xs]),
        *[strip_ids(w).pods for w in wls])
    states = [mod.initial_state(w, cfg) for w in wls]
    stacked_state = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *states)
    return stacked_pods, kt, stacked_state


# ------------------------------------------------- packed query uploads
#
# SimConfig.state_pack narrows the FLAT engine's carry columns to 16-bit
# where ranges provably fit (sim/flat.py). The serve upload path reuses
# the same idea on the REQUEST tables: the wire/H2D format is 16-bit, the
# engine widens back to int32 on device (a free VPU cast), and every
# packing decision is static per bucket — never per batch — so packed
# avals are stable and the warm path stays recompile-free.

#: int32 sentinel in snapshot trigger tables ("never fires")
KT_SENTINEL = np.iinfo(np.int32).max
#: its image on the packed (uint16) upload path
KT_SENTINEL_PACKED = np.iinfo(np.uint16).max


def query_pack_plan(cfg, bucket: int, max_gpu_milli: int) -> dict:
    """The static per-bucket packing plan for query upload tables (empty
    unless ``cfg.state_pack``). Packable columns and their proofs:

    - ``ktable`` -> uint16: trigger steps are bounded by the bucket's
      ``max_steps`` plus the last fractional-progress rung (< max_steps
      + bucket for the reference 0.05 interval), so they fit below the
      remapped sentinel whenever ``max_steps + bucket + 4 < 65535``;
    - ``gpu_milli`` -> int16: admission validates every pod against the
      envelope's ``max_gpu_milli``;
    - ``tie_rank`` -> int16: always ``arange(bucket)``.

    All casts are integer->integer with proven ranges, so the round trip
    through ``pack_query_tables``/``unpack_query_tables`` is
    bit-identical (asserted by tests/test_serve_sharded.py)."""
    if not getattr(cfg, "state_pack", False):
        return {}
    plan: Dict[str, Any] = {}
    if (cfg.max_steps is not None
            and cfg.max_steps + bucket + 4 < KT_SENTINEL_PACKED):
        plan["ktable"] = np.uint16
    if 0 <= int(max_gpu_milli) <= np.iinfo(np.int16).max:
        plan["gpu_milli"] = np.int16
    if bucket <= np.iinfo(np.int16).max:
        plan["tie_rank"] = np.int16
    return plan


def pack_query_tables(pods: PodArrays, kt: np.ndarray, plan: dict):
    """Apply a ``query_pack_plan`` to host-staged tables (numpy, before
    upload). Identity when the plan is empty."""
    if not plan:
        return pods, kt
    if "ktable" in plan:
        kt = np.where(kt == KT_SENTINEL,
                      KT_SENTINEL_PACKED, kt).astype(plan["ktable"])
    repl = {f: np.asarray(getattr(pods, f)).astype(plan[f])
            for f in ("gpu_milli", "tie_rank") if f in plan}
    if repl:
        pods = dataclasses.replace(pods, **repl)
    return pods, kt


def unpack_query_tables(pods, kt, plan: dict):
    """Invert ``pack_query_tables`` ON DEVICE (traced inside the compiled
    serve program): widen back to the engine's int32, remapping the
    ktable sentinel. The H2D transfer stays packed."""
    if not plan:
        return pods, kt
    if "ktable" in plan:
        kt = jnp.where(kt == np.asarray(KT_SENTINEL_PACKED, plan["ktable"]),
                       jnp.int32(KT_SENTINEL), kt.astype(jnp.int32))
    repl = {f: getattr(pods, f).astype(jnp.int32)
            for f in ("gpu_milli", "tie_rank") if f in plan}
    if repl:
        pods = dataclasses.replace(pods, **repl)
    return pods, kt


def pack_program_tables(prog) -> tuple:
    """A ``VMProgram`` -> the packed host-side wire pytree the VM serve
    engine uploads on a hot-swap: the four i32[O] op-index tables ride
    ONE contiguous ``i32[4, O]`` buffer and the two i32 scalars one
    ``i32[2]`` buffer, so a champion swap ships 4 H2D transfers
    (tables/imm/consts/meta) instead of 8 — the ``query_pack_plan`` idea
    applied to the program side of the upload. Host numpy throughout, so
    the engine can size and account the transfer before it happens."""
    tables = np.stack([np.asarray(prog.opcode), np.asarray(prog.a),
                       np.asarray(prog.b), np.asarray(prog.c)]
                      ).astype(np.int32)
    meta = np.asarray([int(prog.n_ops), int(prog.out_reg)], np.int32)
    return (tables, np.asarray(prog.imm), np.asarray(prog.consts), meta)


def unpack_program_tables(packed):
    """Invert ``pack_program_tables`` ON DEVICE (traced inside the
    compiled VM serve program): split the contiguous table block back
    into the ``VMProgram`` pytree the VM executor consumes."""
    from fks_tpu.funsearch.vm import VMProgram

    tables, imm, consts, meta = packed
    return VMProgram(opcode=tables[0], a=tables[1], b=tables[2],
                     c=tables[3], imm=imm, consts=consts,
                     n_ops=meta[0], out_reg=meta[1])


def pack_portfolio_tables(progs) -> tuple:
    """N ``VMProgram``s -> ONE stacked packed wire pytree: the per-slot
    ``pack_program_tables`` tuples gain a leading slot axis, so the whole
    portfolio ships as the same 4 H2D transfers a single champion does
    (i32[S,4,O] tables / f32[S,O] imm / f32[S,32] consts / i32[S,2]
    meta). A slot swap re-uploads this block — still a pure table upload,
    never a recompile."""
    packed = [pack_program_tables(p) for p in progs]
    return tuple(np.stack([pk[i] for pk in packed]) for i in range(4))


def unpack_portfolio_tables(packed):
    """Invert ``pack_portfolio_tables`` ON DEVICE: the stacked wire block
    back into ONE slot-stacked ``VMProgram`` pytree (leading slot axis on
    every leaf) that ``vm.select_slot`` gathers per lane."""
    from fks_tpu.funsearch.vm import VMProgram

    tables, imm, consts, meta = packed
    return VMProgram(opcode=tables[:, 0], a=tables[:, 1], b=tables[:, 2],
                     c=tables[:, 3], imm=imm, consts=consts,
                     n_ops=meta[:, 0], out_reg=meta[:, 1])


def tree_h2d_bytes(*trees) -> int:
    """Total bytes a host->device upload of these pytrees ships — the
    engine's ``serve_h2d_bytes_per_query`` accounting."""
    return int(sum(x.nbytes for t in trees
                   for x in jax.tree_util.tree_leaves(t)
                   if hasattr(x, "nbytes")))


def pods_to_dicts(pods: PodArrays, limit: Optional[int] = None) -> List[dict]:
    """Real pod rows back to query-schema dicts — sources selftest and
    trace-replay queries from a parsed workload."""
    mask = np.asarray(pods.pod_mask)
    idx = np.nonzero(mask)[0]
    if limit is not None:
        idx = idx[:limit]
    cols = {
        "cpu_milli": np.asarray(pods.cpu),
        "memory_mib": np.asarray(pods.mem),
        "num_gpu": np.asarray(pods.num_gpu),
        "gpu_milli": np.asarray(pods.gpu_milli),
        "creation_time": np.asarray(pods.creation_time),
        "duration_time": np.asarray(pods.duration),
    }
    return [{k: int(v[i]) for k, v in cols.items()} for i in idx]


class QueuedRequest:
    """One queued submit: the query, its Future, its timestamps, and —
    when tracing is on — the caller's ``TraceContext``, carried OBJECT-
    in-hand across the submit-thread -> worker-thread boundary (the hop
    where thread-local span nesting loses causality)."""

    __slots__ = ("query", "fut", "t_enq", "deadline", "ctx", "t_deq")

    def __init__(self, query, fut, t_enq, deadline, ctx):
        self.query = query
        self.fut = fut
        self.t_enq = t_enq
        self.deadline = deadline
        self.ctx = ctx
        self.t_deq = t_enq  # stamped by the worker at dequeue

    @property
    def trace_id(self):
        return self.ctx.trace_id if self.ctx is not None else None


class RequestBatcher:
    """Flush-policy request coalescer over a synchronous batch handler.

    ``submit(query)`` returns a Future; a daemon thread accumulates
    pending requests and flushes a batch when it reaches ``max_batch``
    OR the oldest pending request has waited ``max_wait_s`` — the
    classic latency/occupancy trade. The handler receives
    ``(queries, enqueue_times)`` and returns one answer per query in
    order (scatter-back is positional); a handler exception fails every
    future in the batch. ``close()`` flushes the remainder and joins.

    Resilience hooks (fks_tpu.resilience):

    - every submit passes ADMISSION CONTROL: a bounded queue
      (``max_queue``) plus a projected-wait check against the request's
      ``Deadline`` — refused work raises ``ShedError`` (an HTTP 503 with
      Retry-After upstream) instead of queueing to miss its deadline;
    - a request whose deadline expires while queued is completed with
      ``DeadlineExceeded``, never silently handled late;
    - every dequeued Future is completed EXACTLY ONCE — the batch-failure
      path, a short handler answer list, and drain-time shedding all
      resolve through one ``_complete`` funnel;
    - ``drain()`` is the SIGTERM path: stop admitting, give the worker a
      grace budget to finish real work, then shed whatever remains with
      a typed error so no client ever hangs on a dying server.

    Tracing hooks (fks_tpu.obs.trace_ctx): ``submit(..., ctx=)`` (or the
    submitting thread's active context) rides the ``QueuedRequest`` to
    the worker; every typed error raised or completed for a traced
    request carries its ``trace_id`` (so 503 bodies correlate to the
    flight-recorder trail), and the handler can read the in-flight
    requests' contexts/timestamps via ``inflight()`` to emit per-request
    waterfall spans."""

    def __init__(self, handle_batch: Callable[[list, list], list],
                 max_batch: int = 8, max_wait_s: float = 0.005,
                 max_queue: int = 0,
                 admission_cfg: Optional[AdmissionConfig] = None,
                 recorder: Any = None,
                 expired_cb: Optional[Callable[[Any], None]] = None):
        from fks_tpu import obs

        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self._handle = handle_batch
        # accounting hook: called with the QUERY of every request whose
        # deadline expired while queued (the service charges the tenant;
        # the batcher knows futures, not tenants). Must not raise.
        self._expired_cb = expired_cb
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        cfg = admission_cfg or AdmissionConfig()
        if max_queue:
            cfg = dataclasses.replace(cfg, max_queue=int(max_queue))
        self.admission = AdmissionController(cfg)
        self.recorder = recorder if recorder is not None else obs.get_recorder()
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self.batches = 0
        self.submitted = 0
        self.completed = 0
        self.expired = 0
        self.shed_inflight = 0  # dequeued futures shed at drain time
        self.shed_draining = 0  # submits refused because drain started
        self._occupancy_sum = 0.0
        self._closed = False
        self._draining = False
        self._shed_mode = False  # grace exhausted: flush = shed, not run
        self._inflight: Sequence[QueuedRequest] = ()
        self._thread = threading.Thread(
            target=self._loop, name="serve-batcher", daemon=True)
        self._thread.start()

    def submit(self, query, deadline: Optional[Deadline] = None,
               ctx: Optional[trace_ctx.TraceContext] = None) -> Future:
        if ctx is None:  # inherit the submitting thread's trace, if any
            ctx = trace_ctx.current()
        tid = ctx.trace_id if ctx is not None else None
        if self._draining:  # before the closed check: drain() sets both,
            # and a drained server sheds with a TYPED error
            self.shed_draining += 1
            self.recorder.event("shed", reason="draining",
                                queue_depth=self.admission.depth,
                                **({"trace_id": tid} if tid else {}))
            raise ShedError("server is draining", reason="draining",
                            trace_id=tid)
        if self._closed:
            raise RuntimeError("batcher is closed")
        try:
            # the service's query tuple carries the tenant at index 2
            # (the _note_expired convention): admission uses it to price
            # the Retry-After hint at the SHEDDING tenant's service time
            tenant = (query[2] if isinstance(query, tuple)
                      and len(query) > 2 else None)
            self.admission.admit(deadline, tenant=tenant)
        except ShedError as e:
            e.trace_id = tid
            self.recorder.event("shed", reason=e.reason,
                                queue_depth=self.admission.depth,
                                retry_after_s=e.retry_after_s,
                                **({"trace_id": tid} if tid else {}))
            raise
        self.submitted += 1
        fut: Future = Future()
        self._q.put(QueuedRequest(query, fut, time.perf_counter(),
                                  deadline, ctx))
        return fut

    def inflight(self) -> Sequence[QueuedRequest]:
        """The requests of the batch currently inside the handler (their
        contexts + enqueue/dequeue stamps) — read by the handler itself
        to emit per-request waterfall spans. Empty outside a handler
        call; only meaningful ON the worker thread."""
        return self._inflight

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        self._thread.join()

    def drain(self, grace_s: float = 5.0) -> Dict[str, Any]:
        """SIGTERM path: shed new submits, let the worker finish queued
        work within ``grace_s``, then shed the remainder with a typed
        error. Returns completion accounting; never leaves a Future
        pending."""
        if self._closed:
            return {"pending": 0, "completed": 0, "expired": 0,
                    "shed": 0, "stuck": False}
        pending_at = self.admission.depth
        c0, e0, s0 = self.completed, self.expired, self.shed_inflight
        self._draining = True
        self._q.put(None)
        self._thread.join(max(0.0, float(grace_s)))
        if self._thread.is_alive():
            # grace exhausted — remaining flushes shed instead of running
            self._shed_mode = True
            self._thread.join(max(0.1, float(grace_s)))
        self._closed = True
        return {"pending": pending_at,
                "completed": self.completed - c0,
                "expired": self.expired - e0,
                "shed": self.shed_inflight - s0,
                "stuck": self._thread.is_alive()}

    @property
    def mean_occupancy(self) -> float:
        """Mean fraction of max_batch filled per flushed batch."""
        return self._occupancy_sum / self.batches if self.batches else 0.0

    # ----- internals

    @staticmethod
    def _complete(fut: Future, *, result=None, exc=None) -> bool:
        """The single completion funnel: every dequeued Future resolves
        through here exactly once (a cancelled or already-completed
        Future is left alone, never raised over)."""
        if not fut.set_running_or_notify_cancel():
            return False  # client cancelled while queued
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)
        except InvalidStateError:  # pragma: no cover — funnel invariant
            return False
        return True

    def _loop(self) -> None:
        pending: List[QueuedRequest] = []
        while True:
            timeout = None
            if pending:
                waited = time.perf_counter() - pending[0].t_enq
                timeout = max(0.0, self.max_wait_s - waited)
            try:
                item = self._q.get(timeout=timeout)
            except queue.Empty:  # oldest request hit max_wait
                self._flush(pending)
                pending = []
                continue
            if item is None:  # close/drain sentinel
                self._flush(pending)
                return
            item.t_deq = time.perf_counter()
            pending.append(item)
            if len(pending) >= self.max_batch:
                self._flush(pending)
                pending = []

    def _flush(self, pending: List[QueuedRequest]) -> None:
        if not pending:
            return
        self.admission.release(len(pending))
        if self._shed_mode:  # drain grace exhausted: typed shed, no work
            for r in pending:
                if self._complete(r.fut, exc=ShedError(
                        "server shut down before this request ran",
                        trace_id=r.trace_id)):
                    self.shed_inflight += 1
            return
        live: List[QueuedRequest] = []
        for r in pending:
            if r.deadline is not None and r.deadline.expired():
                if self._complete(r.fut, exc=DeadlineExceeded(
                        "deadline expired while queued",
                        trace_id=r.trace_id)):
                    self.expired += 1
                    self.admission.note_expired()
                    if self._expired_cb is not None:
                        try:
                            self._expired_cb(r.query)
                        except Exception:  # noqa: BLE001 — accounting
                            pass  # must never fail the drain/flush path
            else:
                live.append(r)
        if not live:
            return
        self.batches += 1
        self._occupancy_sum += len(live) / self.max_batch
        queries = [r.query for r in live]
        enq = [r.t_enq for r in live]
        t0 = time.perf_counter()
        self._inflight = live
        try:
            answers = self._handle(queries, enq)
        except Exception as e:  # noqa: BLE001 — fail the batch, not the loop
            for r in live:
                self._complete(r.fut, exc=e)
            return
        finally:
            self._inflight = ()
        self.admission.note_batch(len(live), time.perf_counter() - t0)
        answers = list(answers)
        for i, r in enumerate(live):
            if i < len(answers):
                if self._complete(r.fut, result=answers[i]):
                    self.completed += 1
            else:
                # a short answer list must FAIL the unmatched futures,
                # never leave them hanging (the old zip() bug)
                self._complete(r.fut, exc=ResilienceError(
                    f"batch handler returned {len(answers)} answers for "
                    f"{len(live)} queries", reason="short_answer",
                    trace_id=r.trace_id))
