"""Serving front: requests in, audited answers + latency metrics out.

``ServeService`` wraps a ``ServeEngine`` with the request-side concerns
the engine itself stays free of: query resolution ("place this pod list"
vs "replay this what-if trace"), the ``RequestBatcher`` coalescer, the
per-request ``serve_request`` metric (latency, batch occupancy, bucket
shape) through the FlightRecorder/OpenMetrics stack, and the every-Nth
``ParitySentinel`` audit of served answers against the unbatched exact
engine. Two fronts ride on it: stdin/JSONL (``run_jsonl``) and a
localhost-only HTTP listener (``run_http``); both are thin — the service
is the library entrypoint.

``selftest`` is the batched-vs-unbatched parity sweep the
``run_full_suite`` serve gate (and ``cli serve --selftest``) runs.
"""
from __future__ import annotations

import json
import sys
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from fks_tpu import obs
from fks_tpu.obs import trace_ctx
from fks_tpu.obs.history import SLOConfig, record_slo_burn
from fks_tpu.obs.watchdog import ParitySentinel
from fks_tpu.obs.workload import (
    QueryFingerprinter, TenantAccountant, tenant_of,
)
from fks_tpu.resilience.deadline import Deadline, ResilienceError
from fks_tpu.resilience.degrade import DegradeConfig, DegradedModeManager
from fks_tpu.serve.artifact import ChampionSpec, ServeEngine
from fks_tpu.serve.batcher import RequestBatcher, pods_to_dicts


class ServeService:
    """The request/metrics layer over a warm ``ServeEngine``.

    ``submit(query)`` resolves the query to a pod list (failing fast on
    malformed input, before it can poison a batch), hands it to the
    coalescer, and returns a Future of the answer dict. ``audit_every=N``
    routes every Nth request back through ``engine.reference_answer`` and
    the ParitySentinel — a served answer that drifts from the exact
    engine raises an alert event, not just a log line."""

    def __init__(self, engine: ServeEngine, *, recorder=None,
                 max_batch: Optional[int] = None, max_wait_s: float = 0.005,
                 audit_every: int = 0, audit_tol: float = 1e-5,
                 slo: Optional[SLOConfig] = None, slo_every: int = 100,
                 replay_buffer: int = 64,
                 max_queue: int = 0, default_deadline_s: float = 0.0,
                 accounting: bool = False, workload_every: int = 100):
        self.engine = engine
        self.recorder = recorder if recorder is not None else obs.get_recorder()
        self.audit_every = int(audit_every)
        # tenant/workload accounting (obs.workload): OFF by default —
        # the disabled path allocates nothing and touches no lock, the
        # NullRecorder rule applied to accounting
        self.accountant: Optional[TenantAccountant] = None
        self.fingerprinter: Optional[QueryFingerprinter] = None
        self.workload_every = max(1, int(workload_every))
        self._wl_marks = 0
        if accounting:
            self.accountant = TenantAccountant(slo=slo)
            self.fingerprinter = QueryFingerprinter()
        # resilience knobs: bounded queue + per-request deadline default
        # (a query's own deadline_ms always wins); 0 disables each
        self.default_deadline_s = float(default_deadline_s)
        self._degrade: Optional[DegradedModeManager] = None
        # serve-tier SLO (fks_tpu.obs.history.SLOConfig): p99/qps targets
        # priced as error-budget burn rates — one slo_burn metric every
        # ``slo_every`` requests plus one at summary(), so ``cli watch``
        # alerts live and the exporter publishes fks_slo_* gauges
        self.slo = slo if slo is not None else SLOConfig()
        self.slo_every = max(1, int(slo_every))
        self._slo_marks = 0
        self.sentinel = ParitySentinel(None, tol=audit_tol,
                                       recorder=self.recorder)
        self._batcher = RequestBatcher(
            self._handle_batch,
            max_batch=max_batch or engine.envelope.max_batch,
            max_wait_s=max_wait_s, max_queue=max_queue,
            recorder=self.recorder, expired_cb=self._note_expired)
        if self.accountant is not None:
            # per-tenant Retry-After: a shed request's back-off hint is
            # priced at the shedding tenant's own EWMA service time
            self._batcher.admission.service_time_for = \
                self.accountant.ewma_service_s
        self._seq = 0
        self._latencies_ms: List[float] = []
        self._t_first: Optional[float] = None
        self._t_last: float = 0.0
        self.audits = 0
        self.audit_failures = 0
        # last-N answered pod lists: the shadow-eval replay source for
        # the promotion pipeline (a candidate is judged on the traffic
        # the incumbent actually saw, not a synthetic guess)
        self._replay: deque = deque(maxlen=max(1, int(replay_buffer)))
        self.swaps = 0

    # ----- engine hot-swap + replay (fks_tpu.pipeline)

    def swap_engine(self, new_engine):
        """Flip what the service serves; returns the rollback handle.

        Two shapes, one seam:

        - a warm ``ServeEngine`` (the AOT closure path): a single
          attribute assignment is the entire swap — ``_handle_batch``
          reads ``self.engine`` once per batch, so an in-flight batch
          finishes on the old engine and the next lands on the new one;
          returns the old ENGINE. Safe only if ``new_engine`` is already
          warm (the promotion controller builds and warms the bucket
          ladder off the request path).
        - a ``ChampionSpec`` (the VM-native path): the resident engine
          re-binds its champion tables IN PLACE via ``swap_program`` —
          a packed H2D upload, no rebuild, no new object; returns the
          old ``ChampionSpec``, so a probation rollback passing it back
          here symmetrically re-uploads the old tables."""
        if isinstance(new_engine, ChampionSpec):
            swap = getattr(self.engine, "swap_program", None)
            if swap is None:
                raise TypeError(
                    "swap_engine(ChampionSpec) requires a VM-native engine "
                    "with swap_program; this service runs "
                    f"engine_kind={getattr(self.engine, 'engine_kind', '?')}")
            old = swap(new_engine)
        else:
            old = self.engine
            self.engine = new_engine
        self.swaps += 1
        return old

    def enable_degraded_mode(self, fallback_factory, rebuild_factory=None,
                             config: Optional[DegradeConfig] = None
                             ) -> DegradedModeManager:
        """Arm device-fault degradation: a classified device fault inside
        ``_handle_batch`` flips this service to ``fallback_factory``'s
        reduced-batch exact engine (via ``swap_engine``) and retries the
        batch there, while ``rebuild_factory`` rebuilds the primary off
        the request path; recovery is gated through probation."""
        self._degrade = DegradedModeManager(
            self, fallback_factory, rebuild_factory=rebuild_factory,
            config=config, recorder=self.recorder)
        return self._degrade

    @property
    def degrade(self) -> Optional[DegradedModeManager]:
        return self._degrade

    def recent_queries(self, n: int) -> List[List[dict]]:
        """The last ``n`` answered pod lists, oldest first — shadow-eval
        replay traffic."""
        items = list(self._replay)
        return [list(q) for q in items[-max(0, int(n)):]]

    def preload_replay(self, queries: Sequence[Sequence[dict]]) -> int:
        """Refill the replay buffer from a persisted serve state (the
        drain/resume path) so shadow evals have traffic from minute one."""
        for q in queries:
            self._replay.append([dict(p) for p in q])
        return len(self._replay)

    @property
    def requests_served(self) -> int:
        return len(self._latencies_ms)

    def latencies_since(self, mark: int) -> List[float]:
        """Per-request latencies recorded after request index ``mark`` —
        the probation window the rollback gate prices."""
        return list(self._latencies_ms[max(0, int(mark)):])

    # ----- query resolution

    def resolve_query(self, query: Dict[str, Any]) -> Tuple[str, List[dict]]:
        """A request JSON -> (request_id, pod list).

        ``{"pods": [...]}`` places an explicit pod list; ``{"trace": path,
        "limit": N}`` replays a what-if trace — its first N pods (default:
        whatever fits the envelope) against the PINNED cluster, which is
        the serving question ("what would the champion do with this
        arrival stream here"), not a re-evaluation on the trace's own
        cluster."""
        if not isinstance(query, dict):
            raise ValueError("query must be a JSON object")
        rid = str(query.get("id", ""))
        if not rid:
            self._seq += 1
            rid = f"r{self._seq:06d}"
        if "pods" in query:
            pods = query["pods"]
        elif "trace" in query:
            from fks_tpu.data.traces import TraceParser

            wl = TraceParser().parse_workload(pod_file=query["trace"])
            limit = int(query.get("limit", self.engine.envelope.max_pods))
            pods = pods_to_dicts(wl.pods, limit=limit)
        else:
            raise ValueError("query needs 'pods' (pod list) or 'trace' "
                             "(what-if trace to replay)")
        return rid, pods

    def submit(self, query: Dict[str, Any]):
        """Resolve + enqueue; returns a Future resolving to the answer
        dict (with ``id`` and ``latency_ms`` attached). Raises
        ``ShedError`` when admission control refuses the request (queue
        full / deadline unmeetable / draining)."""
        rid, pods = self.resolve_query(query)
        tenant = tenant_of(query)
        deadline = Deadline.from_query(query, self.default_deadline_s)
        # every admitted request starts ONE causal trace; the context
        # object rides the queue to the batcher thread (null path: no
        # recorder -> no context is ever allocated)
        ctx = (trace_ctx.new_trace()
               if getattr(self.recorder, "enabled", False) else None)
        try:
            return self._batcher.submit(
                self._make_item(rid, pods, tenant, query),
                deadline=deadline, ctx=ctx)
        except ResilienceError:
            if self.accountant is not None:
                self.accountant.note_shed(tenant)
            raise

    def _make_item(self, rid: str, pods: List[dict], tenant: str,
                   query: Dict[str, Any]) -> tuple:
        """The queue item for one admitted request. Position 0 is the
        request id, 1 the pod list, 2 the tenant (the batcher's admission
        and expiry hooks read index 2); subclasses may append routing
        fields (the portfolio service appends the slot index)."""
        return (rid, pods, tenant)

    def _note_expired(self, item) -> None:
        """Batcher callback: a request's deadline expired while queued —
        charge the tenant (the batcher knows futures, not tenants)."""
        if self.accountant is not None:
            self.accountant.note_expired(item[2])

    def close(self) -> None:
        self._batcher.close()

    def drain(self, grace_s: float = 5.0) -> Dict[str, Any]:
        """Preemption path: stop admitting, complete or shed every
        in-flight Future within the grace budget. Returns the batcher's
        completion accounting."""
        return self._batcher.drain(grace_s)

    def healthz(self) -> Dict[str, Any]:
        """The liveness/readiness view the HTTP front serves at
        ``/healthz`` and the exporter publishes as gauges."""
        adm = self._batcher.admission
        degrade = self._degrade.healthz() if self._degrade is not None \
            else {"state": "normal", "flips": 0, "recoveries": 0,
                  "last_fault": ""}
        return {
            "ok": degrade["state"] != "dead",
            "engine": self.engine.engine_name,
            "engine_state": degrade["state"],
            "queue_depth": adm.depth,
            "shed_total": adm.shed_total + self._batcher.shed_draining,
            "shed_rate": round(adm.shed_rate, 4),
            "expired": self._batcher.expired,
            "requests_served": self.requests_served,
            "degrade": degrade,
        }

    # ----- batch handling (batcher thread)

    def _answer(self, engine, items: List[tuple]) -> List[dict]:
        """One batch through one engine — the routing seam. The base
        service serves every request on the pinned engine; the portfolio
        service threads per-request slot indices and splits off
        coverage-fallback requests here."""
        return engine.answer_batch([it[1] for it in items])

    def _handle_batch(self, items: List[tuple],
                      enq_times: List[float]) -> List[dict]:
        # pin the engine once per batch: the promotion controller may
        # swap ``self.engine`` concurrently, and a batch must be answered
        # (and audited) by ONE engine end to end
        engine = self.engine
        t_start = time.perf_counter()
        fault: Optional[Tuple[BaseException, float]] = None
        try:
            answers = self._answer(engine, items)
        except Exception as e:  # noqa: BLE001 — maybe a device fault
            t_fail = time.perf_counter()
            if self._degrade is None or not self._degrade.on_fault(e):
                raise
            # the manager flipped us to the fallback engine: retry the
            # batch there (re-pin — swap_engine already landed); the
            # failed primary attempt stays on each request's trace
            fault = (e, t_fail - t_start)
            engine = self.engine
            answers = self._answer(engine, items)
        done = time.perf_counter()
        inflight = self._batcher.inflight()
        self._trace_batch(engine, inflight, t_start, done, fault)
        if self._t_first is None:
            self._t_first = min(enq_times)
        self._t_last = done
        occupancy = len(items) / self._batcher.max_batch
        for i, (item, enq, ans) in enumerate(
                zip(items, enq_times, answers)):
            rid, pods, tenant = item[0], item[1], item[2]
            latency_ms = (done - enq) * 1e3
            ans["id"] = rid
            ans["latency_ms"] = round(latency_ms, 3)
            tid = inflight[i].trace_id if i < len(inflight) else None
            if tid:
                ans["trace_id"] = tid
            self._replay.append(pods)
            self._latencies_ms.append(latency_ms)
            wl_class = ""
            if self.fingerprinter is not None:
                wl_class = self.fingerprinter.observe(pods)
            if self.accountant is not None:
                self.accountant.note_request(tenant, latency_ms,
                                             degraded=fault is not None)
            self.recorder.metric(
                "serve_request", request_id=rid, tenant=tenant,
                latency_ms=round(latency_ms, 3), batch_size=len(items),
                batch_occupancy=round(occupancy, 4),
                bucket_pods=ans["bucket_pods"],
                bucket_lanes=ans["bucket_lanes"],
                **({"trace_id": tid} if tid else {}),
                **({"workload_class": wl_class} if wl_class else {}))
            if self.audit_every > 0 and \
                    len(self._latencies_ms) % self.audit_every == 0:
                self._audit(engine, rid, pods, ans)
        if (self.slo.enabled
                and len(self._latencies_ms) // self.slo_every
                > self._slo_marks):
            self._slo_marks = len(self._latencies_ms) // self.slo_every
            record_slo_burn(self.slo, self._latencies_ms,
                            self._elapsed(), recorder=self.recorder)
        if (self.accountant is not None
                and len(self._latencies_ms) // self.workload_every
                > self._wl_marks):
            self._wl_marks = len(self._latencies_ms) // self.workload_every
            self.accountant.record(self.recorder)
            if self.fingerprinter is not None:
                self.fingerprinter.record_mix(self.recorder)
        if self._degrade is not None:
            self._degrade.after_batch(len(items))
        return answers

    def _trace_batch(self, engine: ServeEngine, inflight, t_start: float,
                     done: float, fault) -> None:
        """Per-request latency waterfalls: one ``serve/request`` root plus
        queue_wait / batch_wait / pack_h2d / dispatch / scatter_back
        children for every traced request of the batch just answered.

        All spans are written after the fact with EXPLICIT end
        timestamps (``ts`` override), so reconstruction places each bar
        where the work actually happened. The engine-stage split reuses
        the host-wall decomposition the engine already measures
        (``last_batch_timing``); the batch-level pack/dispatch costs are
        shared by every lane, so each request reports the same split —
        the truthful statement for a coalesced batch. A degraded-mode
        retry adds a ``primary_attempt`` child carrying the fault class,
        linking primary-fail -> fallback-retry on ONE trace."""
        if not getattr(self.recorder, "enabled", False):
            return
        timing = getattr(engine, "last_batch_timing", None) or {}
        pack_s = float(timing.get("pack_h2d_s", 0.0))
        disp_s = float(timing.get("dispatch_s", 0.0))
        retry_s = fault[1] if fault is not None else 0.0
        scatter_s = max((done - t_start) - retry_s - pack_s - disp_s, 0.0)
        wall_done = time.time()

        def _ts(perf_t: float) -> float:
            # perf_counter point -> wall-clock event timestamp
            return wall_done - (done - perf_t)

        rec = self.recorder
        t_run = t_start + retry_s  # successful attempt began here
        for r in inflight:
            ctx = r.ctx
            if ctx is None:
                continue
            t_deq = min(max(r.t_deq, r.t_enq), t_start)
            # tenant identity rides the root span as an attribute, so a
            # waterfall (and any span query) can slice by tenant
            tenant = r.query[2] if len(r.query) > 2 else ""
            trace_ctx.emit(rec, trace_ctx.SERVE_ROOT, done - r.t_enq,
                           ctx=ctx, root=True, ts=_ts(done),
                           **({"tenant": tenant} if tenant else {}))
            trace_ctx.emit(rec, "serve/request/queue_wait",
                           t_deq - r.t_enq, ctx=ctx, ts=_ts(t_deq))
            trace_ctx.emit(rec, "serve/request/batch_wait",
                           t_start - t_deq, ctx=ctx, ts=_ts(t_start))
            if fault is not None:
                trace_ctx.emit(rec, "serve/request/primary_attempt",
                               retry_s, ctx=ctx, ts=_ts(t_run),
                               fault=type(fault[0]).__name__)
            trace_ctx.emit(rec, "serve/request/pack_h2d", pack_s,
                           ctx=ctx, ts=_ts(t_run + pack_s))
            trace_ctx.emit(rec, "serve/request/dispatch", disp_s,
                           ctx=ctx, ts=_ts(t_run + pack_s + disp_s))
            trace_ctx.emit(rec, "serve/request/scatter_back", scatter_s,
                           ctx=ctx, ts=_ts(done))

    def _audit(self, engine: ServeEngine, rid: str, pods: List[dict],
               ans: dict) -> None:
        ref = engine.reference_answer(pods)
        ok = self.sentinel.audit_served(
            rid, ans["score"], ref["score"],
            placements_match=ans["placements"] == ref["placements"])
        self.audits += 1
        if not ok:
            self.audit_failures += 1

    # ----- stats

    def _elapsed(self) -> float:
        return (self._t_last - self._t_first) \
            if self._t_first is not None else 0.0

    def summary(self, record: bool = True) -> dict:
        lat = np.asarray(self._latencies_ms, np.float64)
        elapsed = self._elapsed()
        out = {
            "requests": len(lat),
            "batches": self._batcher.batches,
            "mean_occupancy": round(self._batcher.mean_occupancy, 4),
            "p50_ms": round(float(np.percentile(lat, 50)), 3) if len(lat)
            else 0.0,
            "p99_ms": round(float(np.percentile(lat, 99)), 3) if len(lat)
            else 0.0,
            "qps": round(len(lat) / elapsed, 2) if elapsed > 0 else 0.0,
            "cold_compiles": self.engine.cold_compiles,
            "engine_kind": getattr(self.engine, "engine_kind", "aot"),
            "policy_tier": getattr(self.engine, "policy_tier", ""),
            "audits": self.audits,
            "audit_failures": self.audit_failures,
            "swaps": self.swaps,
            "queue_depth": self._batcher.admission.depth,
            "shed_total": (self._batcher.admission.shed_total
                           + self._batcher.shed_draining),
            "shed_rate": round(self._batcher.admission.shed_rate, 4),
            "expired": self._batcher.expired,
            "engine_state": (self._degrade.state
                             if self._degrade is not None else "normal"),
        }
        # VM-native engine extras: the capacity bucket its executables
        # are keyed on and the zero-rebuild swap accounting
        cap = getattr(self.engine, "program_capacity", None)
        if cap:
            out["program_capacity"] = int(cap)
            out["vm_swaps"] = int(getattr(self.engine, "vm_swaps", 0))
            out["vm_swap_h2d_bytes"] = int(
                getattr(self.engine, "vm_swap_h2d_bytes", 0))
        # device-resident snapshot cache + H2D accounting (engines
        # predating the cache — or test doubles — simply omit the block)
        cache_stats = getattr(self.engine, "snapshot_cache_stats", None)
        if callable(cache_stats):
            out["snapshot_cache"] = cache_stats()
        if self.slo.enabled:
            out["slo"] = record_slo_burn(
                self.slo, self._latencies_ms, elapsed,
                recorder=self.recorder if record else obs.NULL)
        if self.accountant is not None:
            out["fairness_index"] = round(
                self.accountant.fairness_index(), 4)
            out["tenants"] = self.accountant.record(
                self.recorder if record else None)
            if self.fingerprinter is not None:
                mix = self.fingerprinter.record_mix(
                    self.recorder if record else None, reset=False)
                if mix:
                    out["workload_mix"] = mix
        if record:
            self.recorder.metric("serve", **{k: v for k, v in out.items()
                                             if k not in ("slo",
                                                          "snapshot_cache",
                                                          "tenants",
                                                          "workload_mix")})
            if callable(cache_stats):
                self.recorder.metric("snapshot_cache",
                                     **out["snapshot_cache"])
        return out


# ------------------------------------------------------------------ fronts


def run_jsonl(service: ServeService, stream_in=None, stream_out=None) -> int:
    """JSONL front: one request object per input line, one answer object
    per output line, INPUT ORDER preserved (answers are scattered back to
    their line even when batching reorders completion). A malformed line
    answers ``{"id", "error"}`` instead of killing the stream. Returns
    the number of failed requests."""
    stream_in = stream_in if stream_in is not None else sys.stdin
    stream_out = stream_out if stream_out is not None else sys.stdout
    results: List[Tuple[str, Any]] = []  # (rid, Future | error dict)
    errors = 0
    for lineno, line in enumerate(stream_in, 1):
        line = line.strip()
        if not line:
            continue
        try:
            query = json.loads(line)
            results.append(("", service.submit(query)))
        except ResilienceError as e:  # shed at admission: typed 503 body
            errors += 1
            results.append(("", {"id": f"line{lineno}", **e.to_json()}))
        except Exception as e:  # noqa: BLE001 — per-line 4xx semantics
            errors += 1
            results.append(("", {"id": f"line{lineno}", "error": str(e)}))
    service.close()  # flush the tail batch before draining futures
    for _, res in results:
        if isinstance(res, dict):
            ans = res
        else:
            try:
                ans = res.result()
            except ResilienceError as e:
                errors += 1
                ans = e.to_json()
            except Exception as e:  # noqa: BLE001
                errors += 1
                ans = {"error": str(e)}
        print(json.dumps(ans), file=stream_out)
    return errors


def make_http_server(service: ServeService, port: int = 0, *,
                     host: str = "127.0.0.1",
                     max_requests: Optional[int] = None,
                     deadline_s: float = 60.0):
    """Build (but do not run) the concurrent HTTP front: POST /query
    (request JSON -> answer JSON), GET /stats (service summary), GET
    /healthz (resilience view). The server is a ``ThreadingHTTPServer``
    with DAEMON threads — each request is handled on its own thread, so
    N clients genuinely overlap (two POSTs can sit in the SAME coalesced
    batch; a single-threaded front would serialize them and every
    measured qps number would be an artifact of the listener, not the
    service) and a wedged keep-alive socket cannot block shutdown.
    ``deadline_s`` bounds how long a POST waits on its Future;
    shed/expired/timed-out requests answer a STRUCTURED 503 with a
    Retry-After hint instead of a hung socket. ``port=0`` binds an
    ephemeral port — read it back from ``server.server_address``.
    ``max_requests`` stops the listener after N queries (test/loadgen
    hook)."""
    import concurrent.futures as cf
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    served = {"n": 0}

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, doc: dict,
                  retry_after_s: Optional[float] = None) -> None:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after_s is not None:
                self.send_header("Retry-After",
                                 f"{max(0.0, retry_after_s):.3f}")
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server API
            if self.path == "/healthz":
                hz = service.healthz()
                self._send(200 if hz["ok"] else 503, hz)
            elif self.path == "/stats":
                self._send(200, service.summary(record=False))
            else:
                self._send(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802 — http.server API
            if self.path != "/query":
                self._send(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                query = json.loads(self.rfile.read(n))
                ans = service.submit(query).result(
                    timeout=deadline_s if deadline_s > 0 else None)
                self._send(200, ans)
            except ResilienceError as e:
                self._send(e.http_status, e.to_json(),
                           retry_after_s=e.retry_after_s)
            except cf.TimeoutError:
                self._send(503, {"error": f"no answer within {deadline_s}s",
                                 "kind": "deadline"})
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — surface, don't crash
                self._send(500, {"error": str(e)})
            served["n"] += 1
            if max_requests is not None and served["n"] >= max_requests:
                import threading
                threading.Thread(target=server.shutdown, daemon=True).start()

        def log_message(self, *a):  # quiet: the recorder is the log
            pass

    class Server(ThreadingHTTPServer):
        # per-request threads must not outlive the process: a client
        # holding a socket open would otherwise block interpreter exit
        daemon_threads = True
        # loadgen opens one connection per request from many concurrent
        # workers; the default listen backlog of 5 intermittently drops
        # a SYN under bursts, stalling that connect into kernel
        # retransmit backoff (seconds to ~30 s) and poisoning the
        # measured elapsed window with one phantom-slow request
        request_queue_size = 128

    server = Server((host, port), Handler)
    return server


def run_http(service: ServeService, port: int, *, host: str = "127.0.0.1",
             max_requests: Optional[int] = None,
             deadline_s: float = 60.0,
             drain_coordinator=None) -> None:
    """Run the concurrent HTTP front (``make_http_server``) until
    interrupted. A ``DrainCoordinator`` (optional) gets the
    server-shutdown callback so SIGTERM drains the batcher, persists
    state, then closes the listener."""
    server = make_http_server(service, port, host=host,
                              max_requests=max_requests,
                              deadline_s=deadline_s)
    if drain_coordinator is not None:
        drain_coordinator.add_callback(
            lambda: __import__("threading").Thread(
                target=server.shutdown, daemon=True).start())
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()


# ----------------------------------------------------------------- selftest


def selftest(engine: ServeEngine, count: int = 8, pods_per_query: int = 4,
             tol: float = 1e-5) -> dict:
    """Batched-vs-unbatched parity sweep: ``count`` queries sliced from
    the pinned workload's real pods (sliding windows, so queries differ),
    answered through the batched warm path and re-answered one-by-one by
    the unbatched exact engine. The serve gate's contract: every score
    within ``tol``, every placement list identical.

    The batched pass runs through a real ``ServeService`` (submit ->
    coalescer -> handler), not a bare ``answer_batch`` call, so every
    selftest request exercises — and, under a flight recorder, TRACES —
    the same path production requests take (the run_full_suite trace
    gate reconstructs a complete waterfall per request from this)."""
    base = engine.base_pods
    if not base:  # artifact pinned with an empty trace — synthesize
        base = [{"cpu_milli": 1 + i, "memory_mib": 1, "creation_time": i,
                 "duration_time": 100} for i in range(pods_per_query * 2)]
    queries = []
    for i in range(count):
        start = i % max(1, len(base) - pods_per_query + 1)
        q = base[start:start + pods_per_query]
        queries.append(q if q else base[:1])
    service = ServeService(engine, max_wait_s=0.002)
    futures = [service.submit({"id": f"selftest-{i:03d}", "pods": q})
               for i, q in enumerate(queries)]
    service.close()  # flush the tail batch; every Future resolves
    batched = [f.result() for f in futures]
    max_drift = 0.0
    placements_ok = True
    failures = []
    for i, (q, ans) in enumerate(zip(queries, batched)):
        ref = engine.reference_answer(q)
        drift = abs(ans["score"] - ref["score"])
        max_drift = max(max_drift, drift)
        same = ans["placements"] == ref["placements"]
        placements_ok = placements_ok and same
        if drift > tol or not same:
            failures.append({"query": i, "drift": round(drift, 8),
                             "placements_match": same})
    out = {
        "ok": not failures,
        "checked": len(queries),
        "max_drift": round(max_drift, 10),
        "placements_match": placements_ok,
        "tol": tol,
        "engine": engine.engine_name,
        "engine_kind": getattr(engine, "engine_kind", "aot"),
        "policy_tier": getattr(engine, "policy_tier", ""),
        "failures": failures[:5],
    }
    cap = getattr(engine, "program_capacity", None)
    if cap:
        out["program_capacity"] = int(cap)
    cache_stats = getattr(engine, "snapshot_cache_stats", None)
    if callable(cache_stats):
        out["snapshot_cache"] = cache_stats()
    if getattr(engine, "mesh", None) is not None:
        out["mesh_devices"] = int(getattr(engine, "_shards", 1))
    return out
