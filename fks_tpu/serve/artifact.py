"""Champion-serving artifacts: pinned champion -> warm AOT query engine.

The evolution loop persists champions as JSON in the ledger format
(``policies/discovered/funsearch_*.json``); this module turns one of them
plus a declared shape envelope into a **no-recompile** query engine:

- ``load_champion`` / ``latest_champion``: read a champion (single-dict
  or top-policies-list ledger files) back off disk.
- ``ShapeEnvelope``: the declared serving envelope — max pods per query,
  max batch, the pod-bucket ladder queries pad to, the gpu_milli range
  the shared wait histogram must cover. Shape-bucketing is what makes
  "warm" possible: a finite set of (lane_bucket, pod_bucket) shapes,
  each compiled exactly once.
- ``ServeEngine``: per (lane_bucket, pod_bucket) combination, the engine
  step/finalize pipeline is AOT-compiled via
  ``jax.jit(fn).lower(example).compile()`` with the champion's policy
  baked in as closure constants and the stacked workload/ktable/state as
  ARGUMENTS — the inverse of ``make_trace_batch_eval``'s closure capture,
  which would re-trace per batch. Calling the resulting ``Compiled``
  executable can never trigger compilation, so the zero-recompile warm
  path is structural, not best-effort. ``jax.export`` does not exist on
  the installed jax (0.4.37), so cross-process persistence rides the JAX
  compilation cache instead (``enable_persistent_cache``): a reloaded
  artifact re-lowers but fetches the XLA binary from the cache.

The engine answers are plain dicts (score, scheduled count, per-pod
placements) so the service layer can JSON them straight out.
"""
from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import time
import warnings
from collections import OrderedDict
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu import obs
from fks_tpu.data.entities import ClusterArrays, Workload
from fks_tpu.obs.memory import record_footprint
from fks_tpu.parallel.mesh import (
    make_sharded_serve_fn, num_shards, occupancy_stats, pad_population,
    serve_lane_count, serve_sharding,
)
from fks_tpu.serve.batcher import (
    build_query_workload, pack_query_tables, pods_to_dicts, query_pack_plan,
    stack_query_tables, tree_h2d_bytes, unpack_query_tables,
    validate_query_pods,
)
from fks_tpu.sim import get_engine
from fks_tpu.sim.engine import (
    SimConfig, resolve_auto_prefilter, run_batched_lanes,
)
from fks_tpu.sim.evaluator import max_snapshot_count

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
#: where the evolution loop lands its champion JSONs
CHAMPION_DIR = os.path.join(REPO, "policies", "discovered")

ARTIFACT_VERSION = 1


# ---------------------------------------------------------------- champions


@dataclasses.dataclass(frozen=True)
class ChampionSpec:
    """A pinned champion: the evolved source plus its ledger provenance."""

    code: str
    score: float = 0.0
    generation: int = -1
    timestamp: str = ""
    source: str = ""  # file path it was loaded from, "" for in-memory

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict, source: str = "") -> "ChampionSpec":
        return cls(code=doc["code"], score=float(doc.get("score", 0.0)),
                   generation=int(doc.get("generation", -1)),
                   timestamp=str(doc.get("timestamp", "")), source=source)


def load_champion(path: str) -> ChampionSpec:
    """Load a champion from an evolution-ledger JSON: either a single
    champion dict (``save_best_policy``) or a top-policies list
    (``save_top_policies`` — the best-scoring entry wins). Validates the
    fields an engine build would otherwise trip over later: ``code`` must
    be a non-empty string and ``score`` a finite number — a torn or
    hand-mangled ledger file fails HERE, with the path in the message,
    not deep inside the transpiler."""
    import math

    try:
        with open(path) as f:
            doc = json.load(f)
    except json.JSONDecodeError as e:
        raise ValueError(f"{path}: not valid JSON "
                         f"(truncated mid-write?): {e}") from e
    if isinstance(doc, list):
        if not doc:
            raise ValueError(f"{path}: empty top-policies list")
        doc = max(doc, key=lambda d: float(d.get("score", 0.0)))
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: champion JSON must be a dict or list, "
                         f"got {type(doc).__name__}")
    code = doc.get("code")
    if not isinstance(code, str) or not code.strip():
        raise ValueError(f"{path}: no usable 'code' field — "
                         "not a champion JSON")
    try:
        score = float(doc.get("score", 0.0))
    except (TypeError, ValueError) as e:
        raise ValueError(f"{path}: non-numeric 'score' "
                         f"{doc.get('score')!r}") from e
    if not math.isfinite(score):
        raise ValueError(f"{path}: non-finite 'score' {score!r}")
    return ChampionSpec.from_json(doc, source=path)


def latest_champion(directory: str = "", recorder=None) -> Optional[str]:
    """Path of the best champion JSON under ``directory`` (default: the
    repo's discovered-policies ledger), by score then filename; None when
    the ledger is empty. A malformed file — typically the newest one,
    torn by a crash mid-write — is skipped with a recorded ``alert``
    event instead of hiding the whole ledger or raising."""
    directory = directory or CHAMPION_DIR
    rec = recorder if recorder is not None else obs.get_recorder()
    best: Optional[Tuple[float, str]] = None
    for path in sorted(glob.glob(os.path.join(directory, "*.json"))):
        try:
            spec = load_champion(path)
        except (ValueError, KeyError, OSError) as e:
            rec.event("alert", source="champion_ledger", path=path,
                      detail=f"skipping unreadable champion: {e}")
            continue  # one malformed file must not hide the ledger
        if best is None or spec.score > best[0]:
            best = (spec.score, path)
    return best[1] if best else None


# ----------------------------------------------------------------- envelope


@dataclasses.dataclass(frozen=True)
class ShapeEnvelope:
    """The declared serving envelope: every shape the warm engine must
    answer without compiling. Queries pad UP to the nearest bucket, so
    the compiled-program set is finite and enumerable (``warmup``)."""

    max_pods: int = 1024       # largest query (pods per what-if)
    max_batch: int = 8         # largest coalesced batch (lane bucket cap)
    min_pod_bucket: int = 16   # smallest pod bucket
    pod_bucket_growth: int = 4  # bucket ladder ratio
    max_gpu_milli: int = 1000  # sizes the shared wait histogram

    def __post_init__(self):
        if self.max_pods < 1 or self.max_batch < 1:
            raise ValueError("max_pods and max_batch must be >= 1")
        if self.min_pod_bucket < 1 or self.pod_bucket_growth < 2:
            raise ValueError("min_pod_bucket >= 1, pod_bucket_growth >= 2")

    def pod_buckets(self) -> Tuple[int, ...]:
        """The pod-bucket ladder: min_bucket * growth^i, clipped at
        max_pods (the top bucket is max_pods itself when the ladder does
        not land on it)."""
        out: List[int] = []
        b = self.min_pod_bucket
        while b < self.max_pods:
            out.append(b)
            b *= self.pod_bucket_growth
        out.append(self.max_pods)
        # dedupe while preserving order (max_pods may equal the last rung)
        return tuple(dict.fromkeys(out))

    def pod_bucket_for(self, n_pods: int) -> int:
        for b in self.pod_buckets():
            if n_pods <= b:
                return b
        raise ValueError(
            f"query with {n_pods} pods exceeds envelope max_pods "
            f"{self.max_pods}")

    def min_real_pods(self, bucket: int) -> int:
        """Smallest real pod count routed to ``bucket`` (the previous
        rung + 1; 1 for the smallest bucket). Sizes the bucket's fixed
        snapshot-table width: tables grow as real pods shrink, and
        routing guarantees no query below this count lands here."""
        buckets = self.pod_buckets()
        i = buckets.index(bucket)
        return 1 if i == 0 else buckets[i - 1] + 1

    def lane_buckets(self) -> Tuple[int, ...]:
        """Lane (batch) buckets: powers of two up to max_batch, plus
        max_batch itself."""
        out = []
        b = 1
        while b < self.max_batch:
            out.append(b)
            b *= 2
        out.append(self.max_batch)
        return tuple(dict.fromkeys(out))

    def lanes_for(self, n_queries: int) -> int:
        for b in self.lane_buckets():
            if n_queries <= b:
                return b
        raise ValueError(
            f"batch of {n_queries} queries exceeds envelope max_batch "
            f"{self.max_batch}; chunk it first")

    @property
    def wait_hist_size(self) -> int:
        """Shared wait-histogram width covering the declared gpu_milli
        range (the engine's own sizing rule, pinned so every bucket's
        states share one shape)."""
        return max(1001, self.max_gpu_milli + 2)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, doc: dict) -> "ShapeEnvelope":
        return cls(**doc)


# ---------------------------------------------------------- persistence


def enable_persistent_cache(cache_dir: str) -> None:
    """Point the JAX compilation cache at ``cache_dir`` with the size/time
    floors dropped, so even small serve programs persist. jax 0.4.37 has
    no ``jax.export``; this cache is the AOT persistence story — a
    process that re-lowers the same program fetches the compiled binary
    instead of re-running XLA."""
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                     ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(opt, val)
        except AttributeError:  # option renamed on some jax versions
            pass


def _cluster_to_json(c: ClusterArrays) -> dict:
    """Cluster arrays as JSON-serializable lists (clusters are small —
    O(nodes) ints — so JSON keeps the artifact single-file-inspectable)."""
    return {
        "cpu_total": np.asarray(c.cpu_total).tolist(),
        "mem_total": np.asarray(c.mem_total).tolist(),
        "gpu_declared": np.asarray(c.gpu_declared).tolist(),
        "num_gpus": np.asarray(c.num_gpus).tolist(),
        "gpu_milli_total": np.asarray(c.gpu_milli_total).tolist(),
        "gpu_mem_total": np.asarray(c.gpu_mem_total).tolist(),
        "gpu_mask": np.asarray(c.gpu_mask).astype(int).tolist(),
        "node_mask": np.asarray(c.node_mask).astype(int).tolist(),
        "node_ids": list(c.node_ids),
    }


def _cluster_from_json(doc: dict) -> ClusterArrays:
    i32 = lambda k: np.asarray(doc[k], np.int32)  # noqa: E731
    return ClusterArrays(
        cpu_total=i32("cpu_total"), mem_total=i32("mem_total"),
        gpu_declared=i32("gpu_declared"), num_gpus=i32("num_gpus"),
        gpu_milli_total=i32("gpu_milli_total"),
        gpu_mem_total=i32("gpu_mem_total"),
        gpu_mask=np.asarray(doc["gpu_mask"], bool),
        node_mask=np.asarray(doc["node_mask"], bool),
        node_ids=tuple(doc["node_ids"]),
    )


# ------------------------------------------------------------------ engine


class _Inflight(NamedTuple):
    """One dispatched-but-unharvested chunk of the double-buffered
    answer pipeline."""

    res: Any            # the executable's (async) SimResult
    idxs: List[int]     # answer slots, in lane order
    bucket: int
    lanes: int
    real: int


class ServeEngine:
    """A pinned (champion, cluster, envelope) triple compiled for serving.

    One AOT ``Compiled`` executable per (lane_bucket, pod_bucket)
    combination, built on demand (or eagerly via ``warmup``) and cached
    for the engine's lifetime. The executable's signature is
    ``(pods[L,...], ktable[L,K], state0[L,...]) -> SimResult[L,...]``
    — the query deltas are arguments; the policy AND the pinned cluster
    tables are closure constants (device-resident, never re-uploaded) —
    so the warm path runs zero Python tracing and zero XLA compilation.

    With a ``mesh`` the lane axis is sharded over the mesh's pop axes
    (``parallel.mesh.make_sharded_serve_fn``): one executable per
    (global_lanes, pod_bucket) spans every device, where global lanes =
    per-device lane bucket x shard count; remainder lanes are
    ``pad_population`` duplicates accounted by ``occupancy_stats``.

    The hot path is built not to touch the host or the PCIe bus more
    than it must: snapshot trigger tables are cached on device keyed on
    a content hash of their bytes (``snapshot_cache_stats``), uploads
    are 16-bit packed under ``state_pack`` (``query_pack_plan``), the
    per-batch pods/state buffers are DONATED to the executable so steady
    state allocates nothing net per batch, and ``answer_batch`` double-
    buffers: chunk N+1's stacking + upload overlaps chunk N's execution,
    synchronizing one chunk behind dispatch like the segmented replay
    runner.

    ``engine`` picks the simulation module ("exact" serves reference
    semantics and is the parity default; "flat" trades the documented
    retry-rule divergence for throughput). ``prefilter_k=None`` engages
    the auto-enable heuristic (``sim.engine.resolve_auto_prefilter``).
    """

    #: how this engine binds the champion: "aot" bakes the policy into
    #: the executable as a closure constant (a new champion = a rebuild);
    #: "vm" (serve.vm_engine.VMServeEngine) passes it as a device-resident
    #: argument (a new champion = a table upload)
    engine_kind = "aot"

    def __init__(self, champion: ChampionSpec, workload: Workload, *,
                 envelope: Optional[ShapeEnvelope] = None,
                 engine: str = "exact",
                 prefilter_k: Optional[int] = None,
                 state_pack: bool = False,
                 max_steps_factor: int = 8,
                 mesh=None,
                 snapshot_cache_max_bytes: int = 0,
                 recorder=None, profiler=None):
        if engine == "fused":
            raise ValueError(
                "the fused kernel evaluates parametric populations only; "
                "serve champions on 'exact' (parity default) or 'flat'")
        self.champion = champion
        self.cluster = workload.cluster
        self.base_pods = pods_to_dicts(workload.pods)
        self.envelope = envelope or ShapeEnvelope()
        self.engine_name = engine
        self.state_pack = bool(state_pack)
        self.max_steps_factor = int(max_steps_factor)
        self.recorder = recorder if recorder is not None else obs.get_recorder()
        # device-time attribution (fks_tpu.obs.profiler): with an enabled
        # StageProfiler every bucket compile, warmup sweep, and steady
        # batch is a fenced device_profile stage; the default NULL
        # profiler adds no fences and no conditionals to the serve path
        self.profiler = (profiler if profiler is not None
                         else obs.NULL_PROFILER)
        self._mod = get_engine(engine)
        self._compiled: Dict[Tuple[int, int], Any] = {}
        self.cold_compiles = 0
        # mesh-wide serving: lane axis sharded over the pop axes
        self.mesh = mesh
        self._shards = num_shards(mesh) if mesh is not None else 1
        self._sharding = serve_sharding(mesh) if mesh is not None else None
        # device-resident snapshot tables: content-hash -> (buffer, bytes)
        self._ktable_cache: "OrderedDict[Tuple, Tuple[Any, int]]" = \
            OrderedDict()
        self._ktable_cache_cap = 32
        # byte ceiling on the resident tables (0 = count-capped only):
        # the LRU evicts until BOTH the entry cap and the byte cap hold,
        # so a configured HBM budget is a hard bound, not a suggestion
        self._ktable_cache_max_bytes = int(snapshot_cache_max_bytes)
        self._ktable_cache_bytes = 0
        self.snapshot_cache_hits = 0
        self.snapshot_cache_misses = 0
        # H2D accounting (bytes actually shipped per answered query)
        self.h2d_bytes_total = 0
        self.h2d_queries = 0
        # host-wall split of the last answer_batch call (pack+upload vs
        # dispatch+harvest) — the serve-request waterfall's engine stages
        # (fks_tpu.serve.service). Plain perf_counter stamps around work
        # the engine already does: zero new fences, zero device effects.
        self.last_batch_timing: Dict[str, float] = {
            "pack_h2d_s": 0.0, "dispatch_s": 0.0}

        n, g = self.cluster.n_padded, self.cluster.g_padded
        self.param_policy, self.params, self.policy_tier = \
            self._resolve_policy(champion.code, n, g)
        self.prefilter_k = resolve_auto_prefilter(
            self.param_policy, self.params, n, g,
            override=prefilter_k, recorder=self.recorder,
            work_hint=self._static_work_hint(champion.code, g))

    @staticmethod
    def _resolve_policy(code: str, n: int, g: int):
        """Champion source -> (param_policy, params, tier). VM lowering
        first (register program as the param pytree — the population
        tier's representation); candidates outside the VM vocabulary fall
        back to direct transpile + jit closure. TranspileError (invalid
        source) propagates: a broken champion is a caller error."""
        from fks_tpu.funsearch import transpiler, vm

        try:
            prog = vm.compile_policy(code, n, g)
            return vm.score_static, prog, "vm"
        except vm.VMUnsupported:
            policy = transpiler.transpile(code)
            return (lambda _p, pod, nodes: policy(pod, nodes)), None, "jit"

    @staticmethod
    def _static_work_hint(code: str, g: int) -> Optional[int]:
        """Static per-node work bound from the pre-flight cost model, fed
        to the prefilter auto-heuristic so trivially cheap champions skip
        the runtime probe entirely. None (no hint) when the analyzer
        cannot price the source — the heuristic then probes as before."""
        from fks_tpu import analysis

        rep = analysis.preflight_check(code)
        if rep.ok and rep.cost is not None:
            return rep.cost.work(g)
        return None

    # ----- bucket plumbing

    def bucket_config(self, pod_bucket: int) -> SimConfig:
        """The bucket's SimConfig — SHARED by the batched path and the
        unbatched exact reference (``reference_answer``), so bucket
        padding is part of the serving semantics, not a parity leak."""
        return SimConfig(
            max_steps=max(64, self.max_steps_factor * pod_bucket),
            wait_hist_size=self.envelope.wait_hist_size,
            node_prefilter_k=self.prefilter_k,
            state_pack=self.state_pack,
        )

    def _klen(self, pod_bucket: int) -> int:
        """Fixed snapshot-table width for the bucket, sized at the
        SMALLEST real pod count routing can send here (tables grow as
        real pods shrink; see ``ShapeEnvelope.min_real_pods``)."""
        cfg = self.bucket_config(pod_bucket)
        return max_snapshot_count(cfg.max_steps,
                                  self.envelope.min_real_pods(pod_bucket),
                                  cfg.snapshot_interval)

    def _pack_plan(self, pod_bucket: int) -> dict:
        """The bucket's static upload-packing plan (empty unless
        ``state_pack``) — shared by compile, example and dispatch so the
        packed avals can never diverge from the executable's."""
        return query_pack_plan(self.bucket_config(pod_bucket), pod_bucket,
                               self.envelope.max_gpu_milli)

    def _make_serve_fn(self, pod_bucket: int):
        """The jittable batched pipeline for one pod bucket: vmapped
        self-masking step driven by the shared ``run_batched_lanes``
        scaffold, finalized per lane. The champion policy AND the pinned
        cluster tables are closure constants (device-resident — a batch
        never re-uploads them); pods/ktable/state are traced ARGUMENTS,
        widened on device from the packed wire format."""
        cfg = self.bucket_config(pod_bucket)
        max_steps = cfg.max_steps
        mod, pp, params = self._mod, self.param_policy, self.params
        plan = self._pack_plan(pod_bucket)
        cluster = dataclasses.replace(self.cluster, node_ids=())

        def step_one(p, k, s):
            w = Workload(cluster=cluster, pods=p, faults=None)
            return mod.build_step(
                w, lambda pod, nodes: pp(params, pod, nodes),
                cfg, k, max_steps)(s)

        vstep = jax.vmap(step_one, in_axes=(0, 0, 0))
        vfin = jax.vmap(
            lambda p, s: mod.finalize(
                Workload(cluster=cluster, pods=p, faults=None), cfg, s),
            in_axes=(0, 0))

        def serve_fn(pods, kt, state0):
            pods, kt = unpack_query_tables(pods, kt, plan)
            final = run_batched_lanes(lambda s: vstep(pods, kt, s), state0,
                                      max_steps, active_fn=mod.lane_active)
            return vfin(pods, final)

        return serve_fn

    @staticmethod
    def _pad_kt(kt: np.ndarray, lanes: int) -> np.ndarray:
        """Replicate the last query's snapshot table into pad lanes (the
        ``pad_population`` rule, host-side so the table can be hashed and
        uploaded as one contiguous buffer)."""
        q = kt.shape[0]
        if q < lanes:
            kt = np.concatenate([kt, np.repeat(kt[-1:], lanes - q, axis=0)])
        return kt

    def _example_batch(self, lanes: int, pod_bucket: int):
        """A minimal valid batch at the bucket's exact avals (and, on a
        mesh, exact shardings), for ``lower()``: the smallest query
        routing can send here, replicated across lanes by the same
        pack/pad path real batches use."""
        pods = [{"cpu_milli": 1, "memory_mib": 1, "creation_time": t,
                 "duration_time": 10}
                for t in range(self.envelope.min_real_pods(pod_bucket))]
        cfg = self.bucket_config(pod_bucket)
        pq, kt, s0 = stack_query_tables(self._mod, self.cluster, [pods],
                                        pod_bucket, cfg,
                                        self._klen(pod_bucket))
        pq, kt = pack_query_tables(pq, kt, self._pack_plan(pod_bucket))
        (pq, s0), _ = pad_population((pq, s0), lanes)
        example = (pq, jnp.asarray(self._pad_kt(kt, lanes)), s0)
        if self._sharding is not None:
            example = jax.device_put(example, self._sharding)
        return example

    def compiled_for(self, lanes: int, pod_bucket: int):
        """The (lanes, pod_bucket) AOT executable, compiling on first use
        (``lanes`` is the GLOBAL lane count — per-device bucket x shard
        count on a mesh). ``jax.jit(...).lower(...).compile()`` returns a
        ``Compiled`` object whose __call__ never compiles — argument
        avals either match or raise. pods (arg 0) and state0 (arg 2) are
        donated: each batch's upload buffers are released to XLA, so
        steady-state serving recycles instead of growing the arena; the
        content-hash-cached ktable (arg 1) is NOT donated — its device
        buffer must survive across batches."""
        key = (lanes, pod_bucket)
        hit = self._compiled.get(key)
        if hit is not None:
            return hit
        with self.profiler.stage("compile", lanes=lanes, pods=pod_bucket):
            with obs.span("serve_compile", lanes=lanes, pods=pod_bucket,
                          engine=self.engine_name):
                fn = self._make_serve_fn(pod_bucket)
                if self.mesh is not None:
                    fn = make_sharded_serve_fn(fn, self.mesh)
                from fks_tpu.obs.layout import default_spec
                self._layout_key = getattr(fn, "_fks_layout_key",
                                           default_spec().key)
                example = self._example_batch(lanes, pod_bucket)
                with warnings.catch_warnings():
                    # buckets whose SimResult cannot alias a donated
                    # input warn once per compile; donation still lets
                    # XLA recycle the buffers as scratch
                    warnings.filterwarnings("ignore",
                                            message="Some donated")
                    compiled = jax.jit(fn, donate_argnums=(0, 2)) \
                        .lower(*example).compile()
        self._compiled[key] = compiled
        self.cold_compiles += 1
        # executable-footprint ledger: every ladder rung's predicted HBM
        # claim (memory_analysis) is one memory_footprint record
        record_footprint("serve_aot", f"lanes={lanes},pods={pod_bucket}",
                         compiled, mesh=self.mesh, recorder=self.recorder,
                         engine=self.engine_name,
                         engine_kind=self.engine_kind,
                         layout_key=self._layout_key)
        return compiled

    def warmup(self, lane_buckets: Optional[Sequence[int]] = None,
               pod_buckets: Optional[Sequence[int]] = None) -> int:
        """Eagerly compile every (lane, pod) bucket combination (or the
        given subsets; lane buckets are PER-DEVICE and scale by the mesh
        shard count). Returns the number of executables now resident."""
        with self.profiler.stage("warmup"):
            for lb in lane_buckets or self.envelope.lane_buckets():
                for pb in pod_buckets or self.envelope.pod_buckets():
                    self.compiled_for(serve_lane_count(lb, self.mesh), pb)
        return len(self._compiled)

    # ----- answering

    def _global_lanes(self, n_queries: int) -> int:
        """Global lane count for an n-query chunk: the smallest envelope
        lane bucket covering the PER-DEVICE share, scaled by the mesh."""
        per_dev = -(-int(n_queries) // self._shards)
        return serve_lane_count(self.envelope.lanes_for(max(1, per_dev)),
                                self.mesh)

    def snapshot_cache_stats(self) -> dict:
        """Device-resident snapshot-table cache counters plus the H2D
        accounting — the ``fks_serve_snapshot_cache_*`` gauge source."""
        total = self.snapshot_cache_hits + self.snapshot_cache_misses
        return {
            "hits": self.snapshot_cache_hits,
            "misses": self.snapshot_cache_misses,
            "entries": len(self._ktable_cache),
            "hit_rate": self.snapshot_cache_hits / total if total else 0.0,
            "h2d_bytes_total": int(self.h2d_bytes_total),
            "h2d_bytes_per_query": (self.h2d_bytes_total / self.h2d_queries
                                    if self.h2d_queries else 0.0),
            "bytes": int(self._ktable_cache_bytes),
            "max_bytes": int(self._ktable_cache_max_bytes),
        }

    @property
    def snapshot_cache_bytes(self) -> int:
        """Bytes of snapshot tables currently resident in the cache."""
        return int(self._ktable_cache_bytes)

    def _ktable_for(self, lanes: int, bucket: int, kt: np.ndarray):
        """The device-resident snapshot-table buffer for this batch:
        content-hash cache keyed on the (packed) table bytes at the
        dispatch shape. Consecutive batches whose queries share pod
        counts — the steady-serving common case — hash identically and
        re-use the resident buffer, shipping zero snapshot bytes."""
        digest = hashlib.blake2b(kt.tobytes(), digest_size=16).digest()
        key = (lanes, bucket, kt.dtype.str, digest)
        hit = self._ktable_cache.get(key)
        if hit is not None:
            self._ktable_cache.move_to_end(key)
            self.snapshot_cache_hits += 1
            return hit[0]
        self.snapshot_cache_misses += 1
        padded = self._pad_kt(kt, lanes)
        dev = (jax.device_put(padded, self._sharding)
               if self._sharding is not None else jnp.asarray(padded))
        nbytes = int(padded.nbytes)
        self.h2d_bytes_total += nbytes
        self._ktable_cache[key] = (dev, nbytes)
        self._ktable_cache_bytes += nbytes
        while self._ktable_cache and (
                len(self._ktable_cache) > self._ktable_cache_cap
                or (self._ktable_cache_max_bytes
                    and self._ktable_cache_bytes
                    > self._ktable_cache_max_bytes)):
            _, (_, freed) = self._ktable_cache.popitem(last=False)
            self._ktable_cache_bytes -= freed
        return dev

    def answer_batch(self, pod_lists: Sequence[Sequence[dict]]) -> List[dict]:
        """Answer N "place this pod list" queries. Queries are grouped by
        pod bucket, chunked at the mesh-wide max batch, lane-padded to
        the compiled lane bucket (``pad_population`` — the request
        batcher), run through the warm executable, and scattered back in
        input order. Chunks are DOUBLE-BUFFERED: chunk i+1 is stacked,
        uploaded and dispatched before chunk i's results are pulled, so
        host staging and H2D overlap device compute (the segmented
        replay runner's one-behind handoff, at the batch level)."""
        for pods in pod_lists:
            validate_query_pods(pods, max_pods=self.envelope.max_pods,
                                max_gpu_milli=self.envelope.max_gpu_milli)
        self.last_batch_timing = {"pack_h2d_s": 0.0, "dispatch_s": 0.0}
        answers: List[Optional[dict]] = [None] * len(pod_lists)
        groups: Dict[int, List[int]] = {}
        for i, pods in enumerate(pod_lists):
            groups.setdefault(
                self.envelope.pod_bucket_for(len(pods)), []).append(i)
        mb = self.envelope.max_batch * self._shards
        inflight: Optional[_Inflight] = None
        for bucket, idxs in groups.items():
            for c0 in range(0, len(idxs), mb):
                nxt = self._dispatch_chunk(bucket, idxs[c0:c0 + mb],
                                           pod_lists)
                if inflight is not None:
                    self._harvest(inflight, pod_lists, answers)
                inflight = nxt
        if inflight is not None:
            self._harvest(inflight, pod_lists, answers)
        return answers  # type: ignore[return-value]

    def _dispatch_chunk(self, bucket: int, idxs: List[int],
                        pod_lists) -> "_Inflight":
        """Stack + pack + upload one chunk and dispatch it (async): the
        h2d profiler stage covers exactly the bytes shipped; execution
        cost lands in ``_harvest``'s steady stage."""
        t0 = time.perf_counter()
        lanes = self._global_lanes(len(idxs))
        cfg = self.bucket_config(bucket)
        pods, kt, s0 = stack_query_tables(
            self._mod, self.cluster, [pod_lists[i] for i in idxs], bucket,
            cfg, self._klen(bucket))
        pods, kt = pack_query_tables(pods, kt, self._pack_plan(bucket))
        compiled = self.compiled_for(lanes, bucket)
        (pods, s0), real = pad_population((pods, s0), lanes)
        with self.profiler.stage("h2d", lanes=lanes, pods=bucket) as hh:
            kt_dev = self._ktable_for(lanes, bucket, kt)
            if self._sharding is not None:
                pods, s0 = jax.device_put((pods, s0), self._sharding)
            else:
                pods, s0 = jax.device_put((pods, s0))
            self.h2d_bytes_total += tree_h2d_bytes(pods, s0)
            hh.sync(jax.tree_util.tree_leaves(s0)[0])
        self.h2d_queries += len(idxs)
        # async dispatch; per-batch buffers donated. _invoke is the
        # engine-kind seam: the AOT engine calls the executable directly,
        # the VM engine prepends its device-resident champion tables.
        res = self._invoke(compiled, pods, kt_dev, s0)
        self.last_batch_timing["pack_h2d_s"] += time.perf_counter() - t0
        return _Inflight(res, list(idxs), bucket, lanes, real)

    def _invoke(self, compiled, pods, kt_dev, s0):
        return compiled(pods, kt_dev, s0)

    def _harvest(self, inflight: "_Inflight", pod_lists, answers) -> None:
        """Block on a dispatched chunk and scatter its answers back."""
        res, idxs, bucket, lanes, real = inflight
        t0 = time.perf_counter()
        with self.profiler.stage("steady", **occupancy_stats(real, lanes)) \
                as hs:
            with obs.span("serve_batch", lanes=lanes, bucket_pods=bucket,
                          real=real) as t:
                t.sync(res.policy_score)
            hs.sync(res.policy_score)
        res = jax.device_get(res)
        self.last_batch_timing["dispatch_s"] += time.perf_counter() - t0
        # eval-time layout ledger row: per-batch occupancy attributed to
        # the serve layout key (deduped by the ledger across equal rows)
        from fks_tpu.obs.layout import record_layout
        record_layout(getattr(self, "layout_component", None) or
                      ("vm_serve" if self.engine_kind == "vm" else "serve"),
                      getattr(self, "_layout_key", None) or
                      "shard[candidates]|vmap[candidates]|seg=0",
                      mesh=self.mesh, recorder=self.recorder,
                      **occupancy_stats(real, lanes))
        for lane, i in enumerate(idxs):
            answers[i] = self._extract(res, lane, len(pod_lists[i]),
                                       bucket, lanes)

    def _extract(self, res, lane: Optional[int], p_real: int,
                 bucket: int, lanes: int) -> dict:
        """One lane's SimResult slice -> an answer dict (``lane=None``
        reads an unbatched scalar result). Placements cover REAL pods
        only; node -1 means unplaced; GPU bitmask unpacked to indices."""
        pick = (lambda x: np.asarray(x)) if lane is None else \
            (lambda x: np.asarray(x)[lane])
        assigned = pick(res.assigned_node)[:p_real]
        gpus = pick(res.assigned_gpus)[:p_real].astype(np.int64)
        node_ids = self.cluster.node_ids
        placements = []
        for i, (nd, gm) in enumerate(zip(assigned, gpus)):
            row = {"pod": i, "node": int(nd),
                   "gpus": [b for b in range(int(gm).bit_length())
                            if int(gm) >> b & 1]}
            if 0 <= int(nd) < len(node_ids):
                row["node_id"] = node_ids[int(nd)]
            placements.append(row)
        return {
            "score": float(pick(res.policy_score)),
            "scheduled": int(pick(res.scheduled_pods)),
            "failed": bool(pick(res.failed)),
            "truncated": bool(pick(res.truncated)),
            "events": int(pick(res.events_processed)),
            "placements": placements,
            "bucket_pods": bucket,
            "bucket_lanes": lanes,
        }

    def reference_answer(self, pods: Sequence[dict]) -> dict:
        """The UNBATCHED exact-engine answer for one query, at the same
        bucket semantics (same padded workload, same SimConfig) — what
        the ParitySentinel audits served answers against. Independent
        code path on purpose: single-lane ``make_param_run_fn`` with its
        own ``loop_tables`` sizing, no vmap, no lane padding."""
        from fks_tpu.sim import engine as exact

        validate_query_pods(pods, max_pods=self.envelope.max_pods,
                            max_gpu_milli=self.envelope.max_gpu_milli)
        bucket = self.envelope.pod_bucket_for(len(pods))
        cfg = self.bucket_config(bucket)
        wl = build_query_workload(self.cluster, pods, bucket)
        run = jax.jit(exact.make_param_run_fn(wl, self.param_policy, cfg))
        res = jax.device_get(run(self.params, exact.initial_state(wl, cfg)))
        return self._extract(res, None, len(pods), bucket, 1)

    # ----- persistence

    def save(self, directory: str) -> str:
        """Persist the engine spec (champion + cluster + envelope + knobs)
        as ``artifact.json`` and point the JAX compilation cache at the
        artifact's ``xla_cache/`` so compiled programs persist alongside.
        ``warmup()`` first to bank every bucket's binary."""
        os.makedirs(directory, exist_ok=True)
        doc = {
            "version": ARTIFACT_VERSION,
            "champion": self.champion.to_json(),
            "envelope": self.envelope.to_json(),
            "engine": self.engine_name,
            "engine_kind": self.engine_kind,
            "prefilter_k": self.prefilter_k,
            "state_pack": self.state_pack,
            "max_steps_factor": self.max_steps_factor,
            "policy_tier": self.policy_tier,
            "cluster": _cluster_to_json(self.cluster),
            "base_pods": self.base_pods,
        }
        cap = getattr(self, "program_capacity", None)
        if cap is not None:
            doc["program_capacity"] = int(cap)
        path = os.path.join(directory, "artifact.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)  # atomic: a loader never sees a half-write
        enable_persistent_cache(os.path.join(directory, "xla_cache"))
        return path

    @classmethod
    def load(cls, directory: str, recorder=None, mesh=None) -> "ServeEngine":
        """Rebuild a saved engine. Self-contained: the artifact pins the
        cluster arrays and the resolved prefilter-k (no re-probe), and
        re-attaches the artifact's compilation cache so ``compiled_for``
        fetches banked binaries instead of re-running XLA. ``mesh`` is a
        RUNTIME property (device topology differs per process), so it is
        passed here, never persisted."""
        with open(os.path.join(directory, "artifact.json")) as f:
            doc = json.load(f)
        if doc.get("version") != ARTIFACT_VERSION:
            raise ValueError(
                f"artifact version {doc.get('version')} != "
                f"{ARTIFACT_VERSION}")
        cluster = _cluster_from_json(doc["cluster"])
        wl = Workload(cluster=cluster,
                      pods=_pods_from_dicts(doc.get("base_pods", [])))
        extra = {}
        portfolio = doc.get("portfolio")
        if doc.get("engine_kind", "aot") == "vm" and cls.engine_kind != "vm":
            # artifact saved by a VMServeEngine: reload it as one (the
            # champion-as-data executable set, not the AOT ladder) — or,
            # when the doc carries a portfolio manifest, as the whole
            # slot table
            if portfolio:
                from fks_tpu.portfolio.engine import PortfolioEngine
                cls = PortfolioEngine
            else:
                from fks_tpu.serve.vm_engine import VMServeEngine
                cls = VMServeEngine
        if cls.engine_kind == "vm" and doc.get("program_capacity"):
            extra["program_capacity"] = int(doc["program_capacity"])
        champ_arg: Any = ChampionSpec.from_json(doc["champion"])
        if portfolio and getattr(cls, "is_portfolio", False):
            champ_arg = [ChampionSpec.from_json(c,
                                                source=c.get("source", ""))
                         for c in portfolio["slots"]]
            extra["n_slots"] = int(portfolio["n_slots"])
        eng = cls(champ_arg, wl,
                  envelope=ShapeEnvelope.from_json(doc["envelope"]),
                  engine=doc["engine"],
                  prefilter_k=int(doc["prefilter_k"]),
                  state_pack=bool(doc["state_pack"]),
                  max_steps_factor=int(doc["max_steps_factor"]),
                  mesh=mesh, recorder=recorder, **extra)
        enable_persistent_cache(os.path.join(directory, "xla_cache"))
        return eng


def _pods_from_dicts(pods: List[dict]):
    """Query-schema dicts -> a real-sized PodArrays (artifact base trace)."""
    from fks_tpu.data.entities import PodArrays

    p = max(1, len(pods))
    col = lambda f, d=0: np.asarray(  # noqa: E731
        [int(x.get(f, d)) for x in pods] + [0] * (p - len(pods)), np.int32)
    from fks_tpu.serve.batcher import DEFAULT_DURATION
    return PodArrays(
        cpu=col("cpu_milli"), mem=col("memory_mib"),
        num_gpu=col("num_gpu"), gpu_milli=col("gpu_milli"),
        creation_time=col("creation_time"),
        duration=col("duration_time", DEFAULT_DURATION),
        tie_rank=np.arange(p, dtype=np.int32),
        pod_mask=np.arange(p) < len(pods),
        pod_ids=tuple(f"q-{i:05d}" for i in range(len(pods))),
    )
