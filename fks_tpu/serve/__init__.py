"""Champion serving: pinned champion -> warm, no-recompile query engine.

- artifact: champion loading, shape envelope, AOT ServeEngine (optionally
  mesh-sharded with device-resident snapshot tables), save/load.
- vm_engine: the VM-native VMServeEngine — champion-as-data executables
  shared across champions, zero-rebuild ``swap_program`` hot-swap.
- batcher: query->workload construction, lane stacking, packed-upload
  helpers (query AND program tables), request coalescer.
- service: request/metrics layer, JSONL + localhost HTTP fronts, selftest.
"""
from fks_tpu.serve.artifact import (
    ChampionSpec, ServeEngine, ShapeEnvelope, enable_persistent_cache,
    latest_champion, load_champion,
)
from fks_tpu.serve.batcher import (
    DEFAULT_DURATION, POD_FIELDS, RequestBatcher, build_query_workload,
    pack_program_tables, pack_query_tables, pods_to_dicts, query_pack_plan,
    stack_queries, stack_query_tables, tree_h2d_bytes,
    unpack_program_tables, unpack_query_tables, validate_query_pods,
)
from fks_tpu.serve.service import ServeService, make_http_server, selftest
from fks_tpu.serve.vm_engine import VMServeEngine

__all__ = [
    "ChampionSpec", "ServeEngine", "ShapeEnvelope", "VMServeEngine",
    "enable_persistent_cache", "latest_champion", "load_champion",
    "DEFAULT_DURATION", "POD_FIELDS", "RequestBatcher",
    "build_query_workload", "pack_program_tables", "pack_query_tables",
    "pods_to_dicts", "query_pack_plan", "stack_queries",
    "stack_query_tables", "tree_h2d_bytes", "unpack_program_tables",
    "unpack_query_tables", "validate_query_pods",
    "ServeService", "make_http_server", "selftest",
]
