"""Multi-tenant champion-portfolio serving.

One warm executable, N resident policies: ``PortfolioEngine`` stacks N
``VMProgram`` champions into a single slot-vmapped VM executable (the
population-batched move applied to the serve tier), ``Router`` maps
requests to slots (tenant pin / workload-class affinity / weighted A-B /
coverage fallback), ``PortfolioService`` threads the slot index through
the request batcher, and ``FleetController`` extends the promotion
pipeline to per-slot lifecycle — shadow slots evaluated inside the live
executable, promotion as one slot-table upload, zero XLA compiles.
"""
from fks_tpu.portfolio.engine import PortfolioEngine, portfolio_selftest
from fks_tpu.portfolio.router import (
    FALLBACK, ROUTE_REASONS, Router, vm_coverage_split,
)
from fks_tpu.portfolio.service import PortfolioService
from fks_tpu.portfolio.fleet import FleetController

__all__ = [
    "FALLBACK",
    "FleetController",
    "PortfolioEngine",
    "PortfolioService",
    "ROUTE_REASONS",
    "Router",
    "portfolio_selftest",
    "vm_coverage_split",
]
