"""FleetController: the promotion pipeline, per slot.

Same state machine, same durable log, same probation/rollback pricing as
``PromotionController`` — narrowed to ONE slot of a portfolio:

- the fitness gate compares the candidate against the TARGET SLOT's
  resident champion, not the engine default;
- the shadow build is a ``swap_slot`` into a designated SHADOW SLOT of
  the live executable (zero XLA compiles by construction — the slot
  table's shape never changes), and shadow evaluation replays mirrored
  live traffic through that slot while every other slot keeps serving;
- the commit swap is ``swap_slot(target, champ)`` — one slot-table
  upload under the engine's batch lock; the rollback handle is the
  slot's previous ``ChampionSpec`` and probation rollback re-uploads it;
- every promotion-log record (and promotion_event metric) carries a
  ``slot`` field, so the log reads per-slot.

Candidates outside the VM vocabulary are REJECTED here (build_failed):
slot promotion is a table upload by definition, and such champions are
the Router's coverage-fallback concern, not the fleet's.
"""
from __future__ import annotations

from typing import Optional

from fks_tpu.pipeline.controller import PromotionConfig, PromotionController
from fks_tpu.serve.artifact import ChampionSpec


class _SlotView:
    """An engine-shaped view answering through ONE slot of the shared
    executable — what shadow eval replays traffic through, and what the
    incumbent side of the comparison is narrowed to. Everything but
    ``answer_batch`` delegates to the real engine (envelope, base_pods,
    cluster — the synthetic-query and robust-suite paths read those)."""

    def __init__(self, engine, slot: int):
        self._engine = engine
        self._slot = int(slot)

    def answer_batch(self, pod_lists):
        return self._engine.answer_batch(
            pod_lists, slots=[self._slot] * len(pod_lists))

    @property
    def params(self):  # the robust-suite gate scores THIS slot's program
        return self._engine._slot_progs[self._slot]

    def __getattr__(self, name):
        return getattr(self._engine, name)


class FleetController(PromotionController):
    """Per-slot promotion over a ``PortfolioService``.

    ``slot`` is the lifecycle target; ``shadow_slot`` is the staging
    slot candidates are uploaded into for mirrored-traffic evaluation
    (a spare slot by convention — routing never sends live tenants
    there). The two must differ: a shadow that overwrites its own
    incumbent cannot be compared against it."""

    def __init__(self, service, workload=None, *, slot: int,
                 shadow_slot: int,
                 config: Optional[PromotionConfig] = None, **kw):
        super().__init__(service, workload, config=config, **kw)
        self.slot = int(slot)
        self.shadow_slot = int(shadow_slot)
        n = service.engine.n_slots
        for s, what in ((self.slot, "slot"),
                        (self.shadow_slot, "shadow_slot")):
            if not 0 <= s < n:
                raise ValueError(f"{what} {s} outside portfolio [0, {n})")
        if self.slot == self.shadow_slot:
            raise ValueError(
                f"slot and shadow_slot must differ (both {self.slot})")

    # ----- seams narrowed to the slot

    def _incumbent_spec(self, incumbent) -> ChampionSpec:
        return incumbent.slot_champions[self.slot]

    def _build_shadow(self, champ: ChampionSpec, incumbent, aid: str,
                      path: str):
        """Stage the candidate in the shadow slot of the LIVE executable
        — a table upload, zero compiles. ``VMUnsupported`` propagates to
        the caller's build_failed reject (slot promotion is VM-only; the
        Router's coverage fallback owns non-lowerable champions)."""
        incumbent.swap_slot(self.shadow_slot, champ)
        return _SlotView(incumbent, self.shadow_slot), "vm"

    def _shadow_eval(self, shadow, incumbent, exact_reference: bool = True):
        # compare slot against slot: the incumbent side of the replay is
        # the TARGET slot's champion, not the engine default. The VM
        # parity contract is offline (portfolio_selftest / the gate), so
        # no exact reference is re-jitted on the serving process.
        return super()._shadow_eval(shadow, _SlotView(incumbent, self.slot),
                                    exact_reference=False)

    def _commit_swap(self, champ: ChampionSpec, shadow, engine_kind: str):
        return self.service.engine.swap_slot(self.slot, champ)

    def _restore(self, old: ChampionSpec) -> None:
        self.service.engine.swap_slot(self.slot, old)

    def _transition(self, aid: str, state: str, **detail) -> None:
        super()._transition(aid, state, slot=self.slot, **detail)
