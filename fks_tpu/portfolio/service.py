"""PortfolioService: the routed request front over a PortfolioEngine.

Extends ``ServeService`` at its two seams only: ``_make_item`` routes
every admitted request to a slot (emitting one ``portfolio_route``
metric) and appends the slot index to the queue item; ``_answer``
threads the per-request slot list into ``PortfolioEngine.answer_batch``
and splits coverage-fallback requests off to the kept-warm AOT engine,
merging answers back positionally so the batcher's exactly-once Future
funnel never notices the fork. Everything else — admission, deadlines,
tracing, accounting, audits, SLO burn — is inherited untouched.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from fks_tpu.portfolio.engine import PortfolioEngine
from fks_tpu.portfolio.router import FALLBACK, Router
from fks_tpu.serve.service import ServeService


class PortfolioService(ServeService):
    """Request routing + slot threading over the shared executable.

    ``fallback_engine`` (a warm AOT ``ServeEngine``) arms the coverage
    escape hatch: requests routed to ``FALLBACK`` are answered there.
    Without one, fallback routes degrade to the router's default slot —
    a portfolio must never shed a request it could answer."""

    def __init__(self, engine: PortfolioEngine, *,
                 router: Optional[Router] = None,
                 fallback_engine=None, **kw):
        super().__init__(engine, **kw)
        self.router = router or Router(engine.n_slots)
        self.fallback_engine = fallback_engine
        self.fallback_served = 0

    # ----- routing (submit thread)

    def _make_item(self, rid: str, pods: List[dict], tenant: str,
                   query: Dict[str, Any]) -> tuple:
        if "slot" in query:  # explicit per-query override (drills, A/B
            # forcing, debugging): validated, reason "query"
            slot, reason = int(query["slot"]), "query"
            if not (slot == FALLBACK
                    or 0 <= slot < self.engine.n_slots):
                raise ValueError(
                    f"slot {slot} outside portfolio "
                    f"[0, {self.engine.n_slots}) and not {FALLBACK}")
            self.router.routed[reason] += 1
        else:
            slot, reason = self.router.route(rid, tenant, pods)
        if slot == FALLBACK and self.fallback_engine is None:
            slot, reason = self.router.default_slot, "default"
        self.recorder.metric("portfolio_route", request_id=rid,
                             tenant=tenant, slot=slot, reason=reason)
        return (rid, pods, tenant, slot)

    # ----- batch handling (batcher thread)

    def _answer(self, engine, items: List[tuple]) -> List[dict]:
        if not hasattr(engine, "swap_slot"):
            # degraded mode flipped the service to a plain fallback
            # engine: slots are meaningless there, serve flat
            return engine.answer_batch([it[1] for it in items])
        slots = [it[3] if len(it) > 3 else self.router.default_slot
                 for it in items]
        fb = [i for i, s in enumerate(slots) if s == FALLBACK]
        if not fb:
            answers = engine.answer_batch([it[1] for it in items],
                                          slots=slots)
            for ans, s in zip(answers, slots):
                ans["slot"] = s
            return answers
        # split the batch: portfolio lanes through the shared
        # executable, fallback lanes through the AOT escape hatch, then
        # merge positionally (the Future funnel is order-addressed)
        answers: List[Optional[dict]] = [None] * len(items)
        keep = [i for i in range(len(items)) if slots[i] != FALLBACK]
        if keep:
            for i, ans in zip(keep, engine.answer_batch(
                    [items[i][1] for i in keep],
                    slots=[slots[i] for i in keep])):
                ans["slot"] = slots[i]
                answers[i] = ans
        for i, ans in zip(fb, self.fallback_engine.answer_batch(
                [items[i][1] for i in fb])):
            ans["slot"] = FALLBACK
            answers[i] = ans
        self.fallback_served += len(fb)
        return answers  # type: ignore[return-value]

    # ----- stats

    def summary(self, record: bool = True) -> dict:
        out = super().summary(record=record)
        eng = self.engine
        if hasattr(eng, "slot_requests"):
            out["portfolio"] = {
                "n_slots": eng.n_slots,
                "slot_requests": list(eng.slot_requests),
                "slot_swaps": list(eng.slot_swaps),
                "fallback_served": self.fallback_served,
                "routes": {k: v for k, v in self.router.routed.items()
                           if v},
            }
        return out
