"""PortfolioEngine: N resident champions in ONE vmapped VM executable.

``VMServeEngine`` made the champion an argument; this engine makes the
ARGUMENT a table of champions. All N resident ``VMProgram``s are padded
to one shared capacity bucket (``vm.stack_programs`` semantics), packed
into a single stacked wire block (``pack_portfolio_tables``), and kept
device-resident replicated across the mesh — exactly as the single
champion's tables were — while each batch lane carries a SLOT INDEX that
``vm.select_slot`` gathers per lane inside the vmap. One executable per
(lanes, pod_bucket, program_capacity, n_slots) therefore answers batches
that MIX tenants and policies, and the whole fleet shares one compile
(the "Fast Population-Based RL on a Single Machine" move, serve-side).

Slot lifecycle is the ``swap_program`` story per slot: ``swap_slot(i,
champion)`` lowers through the shared transpile cache, re-stacks the
slot table host-side, uploads the block, and flips the resident pointer
under the batch lock — zero XLA compiles, the old slot champion returned
as the rollback handle, one ``slot_swap`` event emitted. Spare slots
(``n_slots`` > len(champions)) start as clones of slot 0 and serve as
SHADOW staging slots for the FleetController: a candidate is uploaded
into a spare slot and evaluated on mirrored traffic inside the same
executable before its target slot is flipped.
"""
from __future__ import annotations

import dataclasses
import time
import warnings
from typing import List, Optional, Sequence

import jax
import numpy as np

from fks_tpu import obs
from fks_tpu.data.entities import Workload
from fks_tpu.obs.memory import record_footprint
from fks_tpu.funsearch import vm
from fks_tpu.parallel.mesh import make_sharded_portfolio_serve_fn
from fks_tpu.serve.artifact import ChampionSpec
from fks_tpu.serve.batcher import (
    pack_portfolio_tables, tree_h2d_bytes, unpack_portfolio_tables,
    unpack_query_tables,
)
from fks_tpu.serve.vm_engine import VMServeEngine
from fks_tpu.sim.engine import run_batched_lanes


class PortfolioEngine(VMServeEngine):
    """A VM serve engine whose resident program is a SLOT TABLE.

    ``champions`` fills slots 0..len-1 (slot 0 is the default/reference
    champion — ``self.champion``/``self.params`` track it so every
    inherited single-champion path, ``reference_answer`` included, stays
    honest); ``n_slots`` (default ``len(champions)``) fixes the compiled
    slot-table shape, so spare slots are free shadow-staging capacity,
    not a recompile. All champions must lower to the VM vocabulary —
    ``VMUnsupported`` propagates from construction, and the Router's
    coverage fallback keeps such champions on the AOT escape hatch."""

    is_portfolio = True
    layout_component = "portfolio_serve"

    def __init__(self, champions: Sequence[ChampionSpec],
                 workload: Workload, *, n_slots: Optional[int] = None,
                 program_capacity: Optional[int] = None, **kw):
        champions = list(champions)
        if not champions:
            raise ValueError("PortfolioEngine needs at least one champion")
        self.n_slots = int(n_slots) if n_slots else len(champions)
        if self.n_slots < len(champions):
            raise ValueError(
                f"n_slots={self.n_slots} < {len(champions)} champions")
        # consumed by _resolve_policy during the parent constructor
        self._pending_portfolio = champions
        self._slot_champions: List[ChampionSpec] = []
        self._slot_progs: List[vm.VMProgram] = []
        self.slot_requests = [0] * self.n_slots
        self.slot_swaps = [0] * self.n_slots
        self.last_slot_swapped: Optional[int] = None
        self._batch_slots: Optional[List[int]] = None
        self._pending_slots_dev = None
        super().__init__(champions[0], workload,
                         program_capacity=program_capacity, **kw)
        # the parent uploaded slot 0 alone; replace with the full table
        self._prog_dev = self._upload_stacked(self._slot_progs)

    # ----- portfolio lowering / residency

    def _resolve_policy(self, code: str, n: int, g: int):
        """Lower EVERY pending champion, size the shared capacity bucket
        to the longest member, pad all to it, seed the transpile cache
        (re-swapping any construction champion is a warm swap). The
        parent contract (score_static, slot-0 program, "vm") holds."""
        champs = self._pending_portfolio
        raw = [vm.compile_policy(c.code, n, g) for c in champs]
        cap = self._capacity_override or max(
            vm.capacity_bucket(int(p.n_ops)) for p in raw)
        progs = [vm.pad_capacity(p, cap) for p in raw]
        self.program_capacity = cap
        with self._transpile_lock:
            for c, p in zip(champs, progs):
                self._transpile_cache[self._code_key(c.code, n, g, cap)] = p
        spare = self.n_slots - len(champs)
        self._slot_champions = list(champs) + [champs[0]] * spare
        self._slot_progs = list(progs) + [progs[0]] * spare
        return vm.score_static, progs[0], "vm"

    @property
    def slot_champions(self) -> List[ChampionSpec]:
        """The resident champion of every slot (copy)."""
        return list(self._slot_champions)

    def _upload_stacked(self, progs: Sequence[vm.VMProgram]):
        """Stacked slot tables -> device-resident pytree (replicated
        across the mesh), synchronously — same contract as the parent's
        ``_upload_program``, one slot axis wider."""
        packed = pack_portfolio_tables(progs)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            dev = jax.device_put(packed,
                                 NamedSharding(self.mesh, PartitionSpec()))
        else:
            dev = jax.device_put(packed)
        jax.block_until_ready(dev)
        return dev

    def swap_slot(self, slot: int, champion: ChampionSpec) -> ChampionSpec:
        """Per-slot zero-rebuild promotion: lower the champion (warm via
        the shared transpile cache), re-stack the slot table host-side,
        upload the block, flip the pointer under the batch lock. Raises
        ``VMUnsupported`` with the engine untouched. Returns the slot's
        previous champion — the rollback handle; rolling back is another
        ``swap_slot``. Emits one ``slot_swap`` event."""
        slot = int(slot)
        if not 0 <= slot < self.n_slots:
            raise ValueError(
                f"slot {slot} outside portfolio [0, {self.n_slots})")
        t0 = time.perf_counter()
        n, g = self.cluster.n_padded, self.cluster.g_padded
        prog, cache = self._lower_champion(champion.code, n, g)
        overlapped = self._consume_overlap(
            self._code_key(champion.code, n, g, self.program_capacity))
        t1 = time.perf_counter()
        new_progs = list(self._slot_progs)
        new_progs[slot] = prog
        dev = self._upload_stacked(new_progs)
        t2 = time.perf_counter()
        h2d = tree_h2d_bytes(pack_portfolio_tables(new_progs))
        with self._swap_lock:  # exclude in-flight batches for the flip
            old = self._slot_champions[slot]
            self._slot_progs = new_progs
            self._slot_champions[slot] = champion
            self._prog_dev = dev
            if slot == 0:  # slot 0 is the default/reference champion
                self.champion = champion
                self.params = prog
        self.slot_swaps[slot] += 1
        self.vm_swaps += 1
        self.vm_swap_h2d_bytes += h2d
        self.last_slot_swapped = slot
        self.last_swap_breakdown = {
            "slot": slot,
            "transpile_ms": round((t1 - t0) * 1e3, 3),
            "h2d_ms": round((t2 - t1) * 1e3, 3),
            "swap_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "h2d_bytes": h2d,
            "capacity": self.program_capacity,
            "transpile_cache": cache,
            "transpile_overlapped": overlapped,
        }
        self.recorder.event(
            "slot_swap", outcome="swapped",
            champion=champion.source or "<inline>",
            **self.last_swap_breakdown)
        return old

    def swap_program(self, champion: ChampionSpec) -> ChampionSpec:
        """The single-champion hot path maps to the DEFAULT slot, so
        ``ServeService.swap_engine(ChampionSpec)`` keeps working over a
        portfolio unchanged."""
        return self.swap_slot(0, champion)

    def shadow_for(self, champion: ChampionSpec):
        """Portfolio shadows are SLOTS, not engine copies — a copied view
        cannot satisfy the slot-table executable signature. The
        FleetController stages candidates in a spare slot instead."""
        raise TypeError(
            "PortfolioEngine stages shadows in slots: use "
            "FleetController (shadow_slot=...) or swap_slot directly")

    # ----- compilation (slot-agnostic executables)

    def _make_serve_fn(self, pod_bucket: int):
        """The VM pipeline with per-lane slot dispatch: the stacked
        program is broadcast into the vmap (``in_axes=None``) and each
        lane gathers its own champion via ``vm.select_slot`` — the
        general case of the parent's one-program layout."""
        cfg = self.bucket_config(pod_bucket)
        max_steps = cfg.max_steps
        mod = self._mod
        plan = self._pack_plan(pod_bucket)
        cluster = dataclasses.replace(self.cluster, node_ids=())

        def step_one(stacked, slot, p, k, s):
            prog = vm.select_slot(stacked, slot)
            w = Workload(cluster=cluster, pods=p, faults=None)
            return mod.build_step(
                w, lambda pod, nodes: vm.score_static(prog, pod, nodes),
                cfg, k, max_steps)(s)

        vstep = jax.vmap(step_one, in_axes=(None, 0, 0, 0, 0))
        vfin = jax.vmap(
            lambda p, s: mod.finalize(
                Workload(cluster=cluster, pods=p, faults=None), cfg, s),
            in_axes=(0, 0))

        def serve_fn(packed, slots, pods, kt, state0):
            stacked = unpack_portfolio_tables(packed)
            pods, kt = unpack_query_tables(pods, kt, plan)
            final = run_batched_lanes(
                lambda s: vstep(stacked, slots, pods, kt, s), state0,
                max_steps, active_fn=mod.lane_active)
            return vfin(pods, final)

        return serve_fn

    def _lane_put(self, arr: np.ndarray):
        """Host lane-axis array -> device, sharded like the batch."""
        if self._sharding is not None:
            return jax.device_put(arr, self._sharding)
        return jax.device_put(arr)

    def compiled_for(self, lanes: int, pod_bucket: int):
        """The (lanes, pod_bucket, program_capacity, n_slots) executable
        — keyed on the slot-table SHAPE, never its contents, so it
        survives every ``swap_slot``. pods (arg 2) and state0 (arg 4)
        are donated per batch; the resident slot tables (0), the lane
        slot indices (1) and the cached ktable (3) are NOT."""
        key = (lanes, pod_bucket, self.program_capacity, self.n_slots)
        hit = self._compiled.get(key)
        if hit is not None:
            return hit
        with self.profiler.stage("compile", lanes=lanes, pods=pod_bucket):
            with obs.span("serve_compile", lanes=lanes, pods=pod_bucket,
                          engine=self.engine_name,
                          capacity=self.program_capacity,
                          slots=self.n_slots):
                fn = self._make_serve_fn(pod_bucket)
                if self.mesh is not None:
                    fn = make_sharded_portfolio_serve_fn(fn, self.mesh)
                from fks_tpu.obs.layout import default_spec
                self._layout_key = getattr(fn, "_fks_layout_key",
                                           default_spec().key)
                slots0 = self._lane_put(np.zeros(lanes, np.int32))
                example = ((self._prog_dev, slots0)
                           + self._example_batch(lanes, pod_bucket))
                with warnings.catch_warnings():
                    warnings.filterwarnings("ignore",
                                            message="Some donated")
                    compiled = jax.jit(fn, donate_argnums=(2, 4)) \
                        .lower(*example).compile()
        self._compiled[key] = compiled
        self.cold_compiles += 1
        record_footprint(
            "serve_vm",
            f"lanes={lanes},pods={pod_bucket},"
            f"cap={self.program_capacity},slots={self.n_slots}",
            compiled, mesh=self.mesh, recorder=self.recorder,
            engine=self.engine_name, engine_kind=self.engine_kind,
            layout_key=self._layout_key)
        return compiled

    # ----- answering (slot threading)

    def answer_batch(self, pod_lists, slots: Optional[Sequence[int]] = None):
        """Answer a batch that may MIX champions: ``slots[i]`` picks the
        resident policy for query i (default: slot 0 for every lane).
        The slot list rides the instance across the parent's bucket
        grouping — ``_dispatch_chunk`` below re-derives each chunk's
        per-lane slice — and the whole batch stays under the swap lock,
        so a concurrent ``swap_slot`` flips between batches, never
        inside one."""
        if slots is not None:
            slots = [int(s) for s in slots]
            if len(slots) != len(pod_lists):
                raise ValueError(
                    f"{len(slots)} slots for {len(pod_lists)} queries")
            for s in slots:
                if not 0 <= s < self.n_slots:
                    raise ValueError(
                        f"slot {s} outside portfolio [0, {self.n_slots})")
        with self._swap_lock:
            self._batch_slots = slots
            try:
                return super().answer_batch(pod_lists)
            finally:
                self._batch_slots = None

    def _dispatch_chunk(self, bucket: int, idxs, pod_lists):
        lanes = self._global_lanes(len(idxs))
        chunk = ([self._batch_slots[i] for i in idxs]
                 if self._batch_slots is not None else [0] * len(idxs))
        for s in chunk:
            self.slot_requests[s] += 1
        # pad lanes replicate the last real lane's slot (the _pad_kt /
        # pad_population rule); their answers are never scattered back
        padded = np.asarray(chunk + [chunk[-1]] * (lanes - len(chunk)),
                            np.int32)
        self._pending_slots_dev = self._lane_put(padded)
        return super()._dispatch_chunk(bucket, idxs, pod_lists)

    def _invoke(self, compiled, pods, kt_dev, s0):
        return compiled(self._prog_dev, self._pending_slots_dev,
                        pods, kt_dev, s0)

    # ----- persistence (portfolio manifest)

    def save(self, directory: str) -> str:
        """The parent artifact plus a ``portfolio`` manifest: the full
        slot table, so ``ServeEngine.load`` rebuilds the whole fleet."""
        import json
        import os

        path = super().save(directory)
        with open(path) as f:
            doc = json.load(f)
        doc["portfolio"] = {
            "n_slots": self.n_slots,
            "slots": [c.to_json() for c in self._slot_champions],
        }
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


def portfolio_selftest(engine: PortfolioEngine, count: int = 8,
                       pods_per_query: int = 3, tol: float = 1e-5) -> dict:
    """The portfolio parity sweep the ``portfolio_gate`` runs: every
    slot's answers through the SHARED executable must match a
    single-champion ``VMServeEngine`` serving that champion alone
    (integer placements bit-identical, scores within ``tol``), and a
    batch MIXING slots must reproduce the per-slot answers lane for
    lane. The reference engine is ONE VM engine re-pointed per slot via
    ``swap_program`` at the portfolio's capacity bucket — so the sweep
    itself compiles exactly one reference ladder, not one per champion.
    """
    from fks_tpu.serve.artifact import _pods_from_dicts

    base = engine.base_pods
    if not base:
        base = [{"cpu_milli": 1 + i, "memory_mib": 1, "creation_time": i,
                 "duration_time": 100} for i in range(pods_per_query * 2)]
    queries = []
    for i in range(count):
        start = i % max(1, len(base) - pods_per_query + 1)
        q = base[start:start + pods_per_query]
        queries.append(q if q else base[:1])
    wl = Workload(cluster=engine.cluster,
                  pods=_pods_from_dicts(engine.base_pods))
    ref = VMServeEngine(engine.slot_champions[0], wl,
                        envelope=engine.envelope,
                        engine=engine.engine_name,
                        prefilter_k=engine.prefilter_k,
                        state_pack=engine.state_pack,
                        max_steps_factor=engine.max_steps_factor,
                        program_capacity=engine.program_capacity,
                        mesh=engine.mesh, recorder=engine.recorder)
    max_drift = 0.0
    placements_ok = True
    failures: List[dict] = []
    per_slot: List[List[dict]] = []
    for k in range(engine.n_slots):
        mine = engine.answer_batch(queries, slots=[k] * len(queries))
        ref.swap_program(engine.slot_champions[k])
        solo = ref.answer_batch(queries)
        per_slot.append(mine)
        for i, (a, b) in enumerate(zip(mine, solo)):
            drift = abs(a["score"] - b["score"])
            max_drift = max(max_drift, drift)
            same = a["placements"] == b["placements"]
            placements_ok = placements_ok and same
            if drift > tol or not same:
                failures.append({"slot": k, "query": i,
                                 "drift": round(drift, 8),
                                 "placements_match": same})
    # the mixing check: one batch, every lane on its own slot, must
    # reproduce the per-slot sweeps bit for bit
    mix = [i % engine.n_slots for i in range(len(queries))]
    mixed = engine.answer_batch(queries, slots=mix)
    mixed_drift = 0.0
    for i, a in enumerate(mixed):
        b = per_slot[mix[i]][i]
        drift = abs(a["score"] - b["score"])
        mixed_drift = max(mixed_drift, drift)
        same = a["placements"] == b["placements"]
        placements_ok = placements_ok and same
        if drift > tol or not same:
            failures.append({"slot": mix[i], "query": i, "mixed": True,
                             "drift": round(drift, 8),
                             "placements_match": same})
    return {
        "ok": not failures,
        "checked": len(queries),
        "n_slots": engine.n_slots,
        "program_capacity": engine.program_capacity,
        "max_drift": round(max_drift, 10),
        "mixed_max_drift": round(mixed_drift, 10),
        "placements_match": placements_ok,
        "tol": tol,
        "failures": failures[:5],
    }
