"""Router: request -> portfolio slot (or the AOT escape hatch).

Routing is a short, deterministic rule chain priced per request:

1. ``pin``      — explicit tenant -> slot map (contractual placement);
2. ``affinity`` — PR-18 workload-class fingerprint -> slot map: queries
   whose pod-shape class a champion was promoted FOR keep landing on it;
3. ``ab``       — weighted split over slots, keyed by a blake2b hash of
   the request id, so an experiment's assignment is REPEATABLE (the same
   request id always lands on the same arm — no RNG state to drift);
4. ``default``  — the default slot.

A rule may resolve to ``FALLBACK`` (-1): the champion behind that pin is
outside the VM vocabulary (``vm_coverage_split``), and the request is
served by the kept-warm AOT ``ServeEngine`` instead — the exact escape
hatch, reason ``fallback``. Every decision is one ``portfolio_route``
metric (request_id / tenant / slot / reason).
"""
from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

from fks_tpu.funsearch import vm
from fks_tpu.obs.workload import QueryFingerprinter

#: slot sentinel: serve this request on the AOT fallback engine
FALLBACK = -1

#: closed reason vocabulary (mirrored in tools/check_jsonl_schema.py)
ROUTE_REASONS = ("pin", "affinity", "ab", "default", "fallback", "query")


class Router:
    """Maps (request_id, tenant, pods) to a portfolio slot."""

    def __init__(self, n_slots: int, *, default_slot: int = 0,
                 pins: Optional[Dict[str, int]] = None,
                 affinity: Optional[Dict[str, int]] = None,
                 ab_split: Optional[Dict[int, float]] = None):
        self.n_slots = int(n_slots)
        self.default_slot = int(default_slot)
        self.pins = dict(pins or {})
        self.affinity = dict(affinity or {})
        for name, slot in list(self.pins.items()) + \
                list(self.affinity.items()):
            self._check_slot(slot, f"rule for {name!r}")
        self._check_slot(self.default_slot, "default_slot")
        # normalized cumulative weights, stable slot order
        self._split: List[Tuple[int, float]] = []
        if ab_split:
            total = float(sum(ab_split.values()))
            if total <= 0:
                raise ValueError("ab_split weights must sum > 0")
            for slot in sorted(ab_split):
                self._check_slot(slot, "ab_split")
                self._split.append((int(slot), ab_split[slot] / total))
        self._fp = QueryFingerprinter()
        self.routed: Dict[str, int] = {r: 0 for r in ROUTE_REASONS}

    def _check_slot(self, slot: int, what: str) -> None:
        if not (slot == FALLBACK or 0 <= int(slot) < self.n_slots):
            raise ValueError(f"{what}: slot {slot} outside portfolio "
                             f"[0, {self.n_slots}) and not FALLBACK")

    @staticmethod
    def _hash01(rid: str) -> float:
        """Request id -> [0, 1): deterministic, uniform, replayable."""
        h = hashlib.blake2b(rid.encode(), digest_size=8).digest()
        return int.from_bytes(h, "big") / float(1 << 64)

    def route(self, rid: str, tenant: str,
              pods: Sequence[dict]) -> Tuple[int, str]:
        """One routing decision -> (slot, reason). ``FALLBACK`` slots
        keep their originating rule's intent but report reason
        ``fallback`` — the observable fact is WHERE the request went."""
        slot, reason = self.default_slot, "default"
        if tenant in self.pins:
            slot, reason = self.pins[tenant], "pin"
        elif self.affinity and \
                (hit := self.affinity.get(self._fp.classify(pods))) \
                is not None:
            slot, reason = hit, "affinity"
        elif self._split:
            x = self._hash01(rid)
            cum = 0.0
            slot, reason = self._split[-1][0], "ab"
            for s, w in self._split:
                cum += w
                if x < cum:
                    slot = s
                    break
        if slot == FALLBACK:
            reason = "fallback"
        self.routed[reason] += 1
        return int(slot), reason


def vm_coverage_split(champions, n: int, g: int):
    """Partition champions by VM lowerability at cluster shape (n, g):
    ``(resident, fallback)``. Resident champions go into portfolio
    slots; fallback champions stay on the kept-warm AOT ``ServeEngine``
    (the Router pins their tenants to ``FALLBACK``)."""
    resident, fallback = [], []
    for c in champions:
        try:
            vm.compile_policy(c.code, n, g)
            resident.append(c)
        except vm.VMUnsupported:
            fallback.append(c)
    return resident, fallback
