"""Multi-trace batching: vmap the simulator over a stacked trace axis.

BASELINE.json config 4 ("multi-trace batch, padded lax.scan, shape-bucketed
jit") done the TPU-native way: traces inside one shape bucket
(fks_tpu.data.synthetic.bucket_workloads) are stacked leaf-by-leaf into one
pytree with a leading trace axis ``T`` and the whole engine runs under
``vmap`` — ONE compiled program per (bucket shape, policy), regardless of
how many traces it serves. The reference has no analogue: its benchmark
harness re-runs the Python simulator per trace file
(reference: tests/test_scheduler.py:245-284 one deep-copied run per policy,
benchmarks/parser.py:103-115 per-file discovery).

Composes with the population axis: ``make_trace_batch_eval`` optionally
vmaps params too -> fitness[C, T] from one program.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from fks_tpu.data.entities import ClusterArrays, PodArrays, Workload
from fks_tpu.models import parametric
from fks_tpu.parallel.population import ParamPolicyFn
from fks_tpu.sim import get_engine
from fks_tpu.sim.engine import SimConfig
from fks_tpu.sim.evaluator import max_snapshot_count, snapshot_trigger_table


def strip_ids(wl: Workload) -> Workload:
    """Drop host-side id tuples (static pytree meta) so same-shape workloads
    share one treedef and can stack under vmap. Public: the serving tier
    (fks_tpu.serve.batcher) stacks per-query workloads with exactly this
    normalization so queries match the AOT-compiled example's treedef."""
    return Workload(
        cluster=ClusterArrays(**{
            **{f: getattr(wl.cluster, f) for f in (
                "cpu_total", "mem_total", "gpu_declared", "num_gpus",
                "gpu_milli_total", "gpu_mem_total", "gpu_mask", "node_mask")},
            "node_ids": ()}),
        pods=PodArrays(**{
            **{f: getattr(wl.pods, f) for f in (
                "cpu", "mem", "num_gpu", "gpu_milli", "creation_time",
                "duration", "tie_rank", "pod_mask")},
            "pod_ids": ()}),
        faults=wl.faults)


_strip_ids = strip_ids  # internal alias, kept for existing call sites


def stack_traces(workloads: Sequence[Workload], cfg: SimConfig,
                 engine: str = "exact"):
    """Stack same-shape workloads into (workload[T,...], ktable[T,K],
    state0[T,...], max_steps).

    Host-side prep: per-trace snapshot tables are sized from each trace's
    REAL pod count (the reference's ``initialize(total_events)``,
    evaluator.py:47-53) then padded with an unreachable sentinel to a shared
    width; initial states are built per trace by the chosen engine (the
    exact engine runs real CPython heapq for its starting layout).
    """
    mod = get_engine(engine)
    if not workloads:
        raise ValueError("no workloads")
    shapes = {(w.cluster.n_padded, w.cluster.g_padded, w.pods.p_padded)
              for w in workloads}
    if len(shapes) != 1:
        raise ValueError(f"workloads span multiple padded shapes {shapes}; "
                         "bucket them first (fks_tpu.data.synthetic)")
    fshapes = {None if w.faults is None else w.faults.f_padded
               for w in workloads}
    if len(fshapes) != 1:
        raise ValueError(
            f"workloads mix fault-event padding {fshapes}; a stacked batch "
            "needs one shared FaultEvents shape on every trace (or none) — "
            "materialize suites via fks_tpu.scenarios, which pads faults "
            "uniformly (fault-free scenarios get an all-masked timeline)")
    max_steps = max(cfg.resolve_max_steps(w.num_pods) for w in workloads)
    ktables = [snapshot_trigger_table(
        w.num_pods,
        max_snapshot_count(max_steps, w.num_pods, cfg.snapshot_interval),
        cfg.snapshot_interval) for w in workloads]
    klen = max(len(k) for k in ktables)
    sentinel = np.iinfo(np.int32).max
    kt = np.full((len(workloads), klen), sentinel, np.int32)
    for i, k in enumerate(ktables):
        kt[i, : len(k)] = k

    states = [mod.initial_state(w, cfg) for w in workloads]
    hist_sizes = {s.wait_hist.shape[0] for s in states}
    if len(hist_sizes) != 1:
        raise ValueError(f"wait histogram sizes differ across traces "
                         f"{hist_sizes}; traces exceed the shared gpu_milli "
                         "range — split the bucket")

    stacked_wl = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
        *[_strip_ids(w) for w in workloads])
    stacked_state = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *states)
    return stacked_wl, jnp.asarray(kt), stacked_state, max_steps


def make_trace_batch_eval(workloads: Sequence[Workload],
                          param_policy: ParamPolicyFn = parametric.score,
                          cfg: SimConfig = SimConfig(),
                          population: bool = False,
                          jit: bool = True,
                          engine: str = "exact"):
    """Build ``eval(params) -> SimResult`` batched over the trace axis T.

    ``population=False``: params is one candidate, results have leading
    axis [T]. ``population=True``: params[C, ...] adds an outer candidate
    axis -> results [C, T] (fitness of every candidate on every trace from
    one program — the full config-4 matrix).

    Loop scaffold: the shared ``run_batched_lanes`` (one while_loop, cond
    = any of the chosen engine's ``lane_active``) over the (nested-)vmapped
    self-masking step, with the workload itself a traced vmap argument so
    one compiled program serves every same-shape trace.
    """
    from fks_tpu.sim.engine import run_batched_lanes

    mod = get_engine(engine)
    wl, kt, state0, max_steps = stack_traces(workloads, cfg, engine)

    def step_one(workload, ktable, params, s):
        return mod.build_step(
            workload, lambda pod, nodes: param_policy(params, pod, nodes),
            cfg, ktable, max_steps)(s)

    fin = lambda w, s: mod.finalize(w, cfg, s)  # noqa: E731

    def drive(vstep_bound, s0):
        return run_batched_lanes(vstep_bound, s0, max_steps,
                                 active_fn=mod.lane_active)

    if population:
        # lanes [C, T]: traces inner, candidates outer
        vstep = jax.vmap(jax.vmap(step_one, in_axes=(0, 0, None, 0)),
                         in_axes=(None, None, 0, 0))
        vfin = jax.vmap(jax.vmap(fin, in_axes=(0, 0)), in_axes=(None, 0))

        def eval_fn(params):
            pop = jax.tree_util.tree_leaves(params)[0].shape[0]
            final = drive(lambda s: vstep(wl, kt, params, s),
                          mod.broadcast_state(state0, pop))
            return vfin(wl, final)
    else:
        vstep = jax.vmap(step_one, in_axes=(0, 0, None, 0))
        vfin = jax.vmap(fin, in_axes=(0, 0))

        def eval_fn(params):
            final = drive(lambda s: vstep(wl, kt, params, s), state0)
            return vfin(wl, final)

    return jax.jit(eval_fn) if jit else eval_fn
