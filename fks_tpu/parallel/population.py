"""Population-parallel fitness evaluation: ``vmap`` over candidates.

TPU-native replacement for the reference's "distributed backend" — a
``ProcessPoolExecutor`` that forks one subprocess per candidate policy,
re-parses the trace CSVs, deep-copies cluster state, and runs the pure-Python
simulator (reference: funsearch/funsearch_integration.py:30-64, 535-562).
Here the whole population is ONE compiled XLA program: the trace lives on
device once, the initial state is broadcast (never copied per candidate),
and the event loop runs for all candidates in lockstep under ``vmap``.

Two candidate representations are supported:
- **parametric** (this module's fast path): candidate = weight vector,
  population = ``params[C, F]``, evaluated by the vmapped self-masking
  step inside ONE while_loop (engine.make_population_run_fn — not
  ``vmap(while_loop)``, which would full-carry-select every lane each
  event to freeze finished candidates).
- **compiled code** (general path): candidates from the LLM transpiler are
  distinct computations; they batch by Python loop over per-code jitted runs
  with an AST-keyed compile cache (fks_tpu.funsearch.backend).
"""
from __future__ import annotations

from typing import Callable

import jax

from fks_tpu.data.entities import Workload
from fks_tpu.models import parametric
from fks_tpu.sim.engine import (
    SimConfig, initial_state, make_param_run_fn, make_population_run_fn,
)
from fks_tpu.sim.types import NodeView, PodView, SimResult

# A parameterized policy: (params, PodView, NodeView) -> i32[N] scores.
ParamPolicyFn = Callable[[jax.Array, PodView, NodeView], jax.Array]

# Loop assembly (ktable/cond/while/finalize) is shared with the single-policy
# path via the engine, so batched and plain fitness cannot diverge.
make_single_run = make_param_run_fn


def lead_axis_size(tree) -> int:
    """Leading-axis length of a batched pytree — the candidate count of a
    population batch or the lane count of a coalesced serve batch. The
    one definition shared by the mesh padder/sharder and the serve tier,
    so "what is the batch axis" cannot drift between them."""
    return jax.tree_util.tree_leaves(tree)[0].shape[0]


def fused_runner(workload: Workload, param_policy, cfg: SimConfig,
                 lanes: int = 64, interpret: bool | None = None):
    """The ONE dispatch point for the fused Pallas engine (shared by the
    vmap path here and the shard_map path in fks_tpu.parallel.mesh, so the
    fused contract cannot drift between them). The kernel hard-wires the
    parametric feature basis, so any other policy is rejected. ``lanes``
    caps the per-grid-step chunk (the kernel auto-shrinks it to the VMEM
    budget); ``interpret=None`` auto-selects Mosaic on TPU."""
    if param_policy is not parametric.score:
        raise ValueError("engine='fused' hard-wires the parametric feature "
                         "basis; pass param_policy=parametric.score or use "
                         "engine='flat'")
    from fks_tpu.sim import fused
    return fused.make_fused_population_run(workload, cfg, lanes=lanes,
                                           interpret=interpret)


def make_population_eval(workload: Workload,
                         param_policy: ParamPolicyFn = parametric.score,
                         cfg: SimConfig = SimConfig(),
                         jit: bool = True,
                         engine: str = "exact"):
    """Build ``eval(params[C, ...]) -> SimResult`` batched over candidates.

    The reference's per-candidate subprocess fan-out collapsed into one
    compiled program: all candidates advance in lockstep through the
    while_loop; a candidate that finishes early (fewer retries) idles as
    dropped scatters until the slowest lane drains its queue.

    ``engine``: "exact" replicates the reference bit-for-bit (heap replica,
    layout-dependent retry rule); "flat" is the TPU throughput engine
    (fks_tpu.sim.flat — identical semantics except the documented
    retry-time rule; ~an order of magnitude faster per step on TPU);
    "fused" is the Pallas whole-loop-in-VMEM kernel (fks_tpu.sim.fused —
    flat semantics, parametric policies ONLY: ``param_policy`` must be
    the default ``parametric.score``).
    """
    if engine == "fused":
        run = fused_runner(workload, param_policy, cfg)
        # jit covers run()'s XLA-side pre/post work (padding, aux decode,
        # finalize) around the pallas_call
        return jax.jit(run) if jit else run

    from fks_tpu.sim import get_engine
    mod = get_engine(engine)
    run = mod.make_population_run_fn(workload, param_policy, cfg)
    state0 = mod.initial_state(workload, cfg)

    def population_eval(params):
        return run(params, state0)

    return jax.jit(population_eval) if jit else population_eval


def fitness(result: SimResult) -> jax.Array:
    """The scalar the evolution loop ranks on (reference evaluator.py:101-127
    semantics are already folded into ``policy_score`` by the engine)."""
    return result.policy_score
