"""Population-parallel fitness evaluation: ``vmap`` over candidates.

TPU-native replacement for the reference's "distributed backend" — a
``ProcessPoolExecutor`` that forks one subprocess per candidate policy,
re-parses the trace CSVs, deep-copies cluster state, and runs the pure-Python
simulator (reference: funsearch/funsearch_integration.py:30-64, 535-562).
Here the whole population is ONE compiled XLA program: the trace lives on
device once, the initial state is broadcast (never copied per candidate),
and the event loop runs for all candidates in lockstep under ``vmap``.

Two candidate representations are supported:
- **parametric** (this module's fast path): candidate = weight vector,
  population = ``params[C, F]``, evaluated by a single vmapped while_loop.
- **compiled code** (general path): candidates from the LLM transpiler are
  distinct computations; they batch by Python loop over per-code jitted runs
  with an AST-keyed compile cache (fks_tpu.funsearch.backend).
"""
from __future__ import annotations

from typing import Callable

import jax

from fks_tpu.data.entities import Workload
from fks_tpu.models import parametric
from fks_tpu.sim.engine import SimConfig, initial_state, make_param_run_fn
from fks_tpu.sim.types import NodeView, PodView, SimResult

# A parameterized policy: (params, PodView, NodeView) -> i32[N] scores.
ParamPolicyFn = Callable[[jax.Array, PodView, NodeView], jax.Array]

# Loop assembly (ktable/cond/while/finalize) is shared with the single-policy
# path via the engine, so batched and plain fitness cannot diverge.
make_single_run = make_param_run_fn


def make_population_eval(workload: Workload,
                         param_policy: ParamPolicyFn = parametric.score,
                         cfg: SimConfig = SimConfig(),
                         jit: bool = True):
    """Build ``eval(params[C, ...]) -> SimResult`` batched over candidates.

    The reference's per-candidate subprocess fan-out collapsed into one
    ``vmap``; the while_loop batching rule keeps all candidates stepping
    until the slowest finishes (per-candidate step counts differ only via
    retries, which are rare on the shipped traces).
    """
    run = make_single_run(workload, param_policy, cfg)
    state0 = initial_state(workload, cfg)

    def population_eval(params):
        return jax.vmap(lambda p: run(p, state0))(params)

    return jax.jit(population_eval) if jit else population_eval


def fitness(result: SimResult) -> jax.Array:
    """The scalar the evolution loop ranks on (reference evaluator.py:101-127
    semantics are already folded into ``policy_score`` by the engine)."""
    return result.policy_score
