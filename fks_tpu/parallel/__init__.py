"""Population/mesh parallelism: the framework's distributed backend.

Replaces the reference's ProcessPoolExecutor fan-out (reference:
funsearch/funsearch_integration.py:535-562) with ``vmap`` on-chip and
``shard_map`` + ICI all-gather across a ``jax.sharding.Mesh``.
"""
from fks_tpu.parallel.population import (  # noqa: F401
    ParamPolicyFn, fitness, lead_axis_size, make_population_eval,
    make_single_run,
)
from fks_tpu.parallel.mesh import (  # noqa: F401
    DCN_AXIS, POP_AXIS, hybrid_population_mesh, init_distributed,
    make_sharded_code_eval, make_sharded_eval, make_sharded_generation_step,
    make_sharded_serve_fn, num_shards, occupancy_stats, pad_population,
    pad_stats, population_mesh, serve_lane_count, serve_sharding,
    shard_population,
)
