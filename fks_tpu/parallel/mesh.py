"""Mesh scale-out: population sharding + ICI collectives for elite ranking.

TPU-native replacement for the reference's multi-worker story (a
``ProcessPoolExecutor`` with pickle-over-fork as the only inter-worker
substrate, reference: funsearch/funsearch_integration.py:535-562; elite
selection is a host-side Python sort at :494-496). Here:

- the candidate axis ``C`` is sharded over a 1-D ``jax.sharding.Mesh``
  ("pop" axis) via ``shard_map``; each device runs its population shard
  through the vmapped simulator entirely on-chip;
- per-shard fitness is combined with an **all_gather over ICI** so every
  device ranks the full population and agrees on the elite set (the
  BASELINE.json config-5 "ICI all-gather elite selection");
- only elite indices/scores return to host — candidate weights can stay
  device-resident across generations.

Single-host multi-chip uses one 1-D mesh over ``jax.devices()``.
Multi-slice / multi-host topologies use ``hybrid_population_mesh``: a 2-D
``("dcn", "pop")`` mesh whose outer axis crosses slice (DCN) boundaries and
whose inner axis rides ICI, after ``init_distributed()`` has brought up the
process group. The population is sharded over BOTH axes (it is the problem's
only parallel dimension); the fitness all-gather for elite ranking then
decomposes into an ICI gather within each slice and one DCN hop across
slices — collectives ride the fast fabric wherever possible, exactly the
layered layout the scaling playbook prescribes. shard_map and the
collectives are topology-agnostic; every entry point below accepts either
mesh shape.
"""
from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fks_tpu.data.entities import Workload
from fks_tpu.models import parametric
from fks_tpu.parallel.population import ParamPolicyFn, lead_axis_size
from fks_tpu.sim.engine import SimConfig, initial_state, make_population_run_fn
from fks_tpu.utils.compat import shard_map
from fks_tpu.utils.segments import segment_budget

POP_AXIS = "pop"
DCN_AXIS = "dcn"
SCN_AXIS = "scn"  # scenario axis of a layout_mesh (obs.layout specs)


def population_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over all (or the given) devices, population axis only.

    The problem has exactly one parallel dimension — candidates; events
    within a trace are sequential (SURVEY.md §5 long-context note) — so the
    mesh is 1-D by design, not a simplification.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    return Mesh(devices, (POP_AXIS,))


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> int:
    """Bring up the JAX process group for multi-host runs (the reference's
    only inter-worker substrate is a single-host ProcessPoolExecutor,
    funsearch_integration.py:535-562 — it has no multi-host story at all).

    On TPU pods with standard env (TPU_WORKER_HOSTNAMES etc.) the arguments
    auto-detect; pass them explicitly elsewhere. No-op when the process
    group is already up. A failed bring-up RAISES when explicit arguments
    were given (silently degrading a 2-host launch to one process would run
    at the wrong scale with no error); with auto-detection only, failure
    means single-process and is suppressed. Returns the process count.
    """
    explicit = any(v is not None
                   for v in (coordinator_address, num_processes, process_id))
    from fks_tpu.utils.compat import distributed_is_initialized
    if not distributed_is_initialized():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except (RuntimeError, ValueError):
            if explicit:
                raise
            # auto-detect found no cluster env: single-process run
    return jax.process_count()


def hybrid_population_mesh(devices: Optional[Sequence] = None,
                           num_slices: Optional[int] = None) -> Mesh:
    """A 2-D ``("dcn", "pop")`` mesh: outer axis across slices/hosts (DCN),
    inner axis within a slice (ICI). The population shards over both; the
    elite all-gather then moves one message per slice over DCN instead of
    per-device traffic.

    ``num_slices`` defaults to ``jax.process_count()`` (multi-host) and must
    divide the device count. With one slice this degenerates to a
    ``[1, n]`` mesh — same program, no DCN axis traffic.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    slices = num_slices or max(1, jax.process_count())
    if n % slices:
        raise ValueError(f"{n} devices not divisible into {slices} slices")
    return Mesh(devices.reshape(slices, n // slices), (DCN_AXIS, POP_AXIS))


def layout_mesh(devices: Optional[Sequence] = None,
                scenario_shards: int = 1) -> Mesh:
    """The mesh for a declared layout (fks_tpu.obs.layout.LayoutSpec):
    ``scenario_shards=1`` is the default layout's 1-D population mesh;
    ``scenario_shards>1`` factorizes the devices into a 2-D
    ``("pop", "scn")`` mesh — candidates shard the outer axis, scenarios
    the inner one, so the per-scenario all-gather rides the fastest
    (innermost) fabric while the elite gather crosses candidate shards
    exactly as on the 1-D mesh."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    s = int(scenario_shards)
    if s <= 1:
        return population_mesh(devices)
    if devices.size % s:
        raise ValueError(f"{devices.size} devices not divisible into "
                         f"{s} scenario shards")
    return Mesh(devices.reshape(devices.size // s, s), (POP_AXIS, SCN_AXIS))


def _resolve_layout(layout, *, scenarios: bool = False, seg_steps: int = 0,
                    scenario_shardable: bool = False):
    """Resolve an entry point's ``layout`` argument to a LayoutSpec:
    None means the historical hard-coded behavior (the default spec —
    bit-identical lowering, jaxpr-pinned). Entry points without a
    scenario axis reject specs that shard scenarios."""
    from fks_tpu.obs.layout import LayoutSpec, default_spec
    if layout is None:
        return default_spec(scenarios=scenarios, seg_steps=seg_steps)
    if not isinstance(layout, LayoutSpec):
        raise TypeError(f"layout must be a LayoutSpec or None, got "
                        f"{type(layout).__name__}")
    if "scenarios" in layout.shard and not scenario_shardable:
        raise ValueError(
            f"layout {layout.key!r} shards the scenario axis, but this "
            "entry point has no scenario axis (mesh-sharded SUITE "
            "evaluation lives at fks_tpu.scenarios.robust."
            "make_sharded_suite_eval)")
    return layout


def _pop_axes(mesh: Mesh):
    """The axes the population is sharded over, in mesh order: ("pop",) on
    a 1-D mesh, ("dcn", "pop") on a hybrid mesh. A layout_mesh's "scn"
    axis is never a population axis."""
    return tuple(a for a in mesh.axis_names if a in (DCN_AXIS, POP_AXIS))


def num_shards(mesh: Mesh) -> int:
    """Total population shards: the product of the mesh's pop axes."""
    n = 1
    for a in _pop_axes(mesh):
        n *= mesh.shape[a]
    return n


_num_shards = num_shards  # internal alias, kept for existing call sites


def _shard_index(mesh: Mesh):
    """Linearized shard id inside shard_map (row-major over the pop axes)."""
    axes = _pop_axes(mesh)
    idx = jax.lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * mesh.shape[a] + jax.lax.axis_index(a)
    return idx


def pad_population(params, num_shards):
    """Pad C up to a multiple of the shard count (pass the mesh itself or an
    int); returns (padded, real_count).

    ``params`` is any pytree whose every leaf carries the candidate axis as
    its LEADING dimension — a parametric weight matrix ``[C, F]`` or a
    ``vm.stack_programs`` batch alike. Padding replicates the last
    candidate's slice on every leaf. Pass ``real_count`` back into the
    sharded eval so pad slots (duplicates of the last candidate) are masked
    out of elite selection.
    """
    if isinstance(num_shards, Mesh):
        num_shards = _num_shards(num_shards)
    c = lead_axis_size(params)
    target = -(-c // num_shards) * num_shards
    if target != c:
        def _pad_leaf(x):
            pad = jnp.tile(x[-1:], (target - c,) + (1,) * (x.ndim - 1))
            return jnp.concatenate([x, pad], axis=0)

        params = jax.tree_util.tree_map(_pad_leaf, params)
    return params, c


def pad_stats(real_count: int, num_shards) -> dict:
    """Pad-lane accounting for a ``pad_population`` launch (pass the mesh
    itself or a shard count): how many of the launched lanes are padding
    duplicates of the last candidate rather than real work.
    ``pad_waste_fraction`` is the device-time share spent on pad lanes —
    the number the flight recorder's mesh snapshot reports
    (fks_tpu.obs.telemetry.mesh_snapshot)."""
    if isinstance(num_shards, Mesh):
        num_shards = _num_shards(num_shards)
    real = int(real_count)
    padded = -(-real // num_shards) * num_shards if real else 0
    return {
        "real_count": real,
        "padded_count": padded,
        "pad_lanes": padded - real,
        "pad_waste_fraction": (padded - real) / padded if padded else 0.0,
    }


def occupancy_stats(real_count: int, num_shards, scenarios: int = 1,
                    segments: int = 1) -> dict:
    """``pad_stats`` extended with the other two batch axes a launch
    multiplies over — scenarios (``scenarios.suite`` vmap) and trace
    segments (the segmented runner's host loop) — for the device-time
    attribution profiler (fks_tpu.obs.profiler): ``launched_lane_steps``
    is the total lane-dispatch count, ``real_lane_steps`` the share that
    was real candidates. Pad waste is per-lane, so it is unchanged by
    the extra axes; they scale the absolute accounting only."""
    s = pad_stats(real_count, num_shards)
    scenarios = max(1, int(scenarios))
    segments = max(1, int(segments))
    s["scenarios"] = scenarios
    s["segments"] = segments
    s["launched_lane_steps"] = s["padded_count"] * scenarios * segments
    s["real_lane_steps"] = s["real_count"] * scenarios * segments
    return s


def shard_population(params, mesh: Mesh):
    """``device_put`` every leaf of a candidate pytree with its leading
    (candidate) axis sharded over the mesh's pop axes. Identity layout for
    a bare ``jax.Array`` population — the historical fast path — and the
    generic entry for pytree payloads (stacked VM programs)."""
    c = lead_axis_size(params)
    if c % _num_shards(mesh):
        raise ValueError(
            f"population {c} not divisible by shard count "
            f"{_num_shards(mesh)}; use pad_population()")
    return jax.device_put(params, NamedSharding(mesh, P(_pop_axes(mesh))))


_shard_params = shard_population  # internal alias, kept for call sites


# -------------------------------------------------------- serve batch axis
#
# The serving tier (fks_tpu.serve) coalesces concurrent what-if queries
# onto the SAME leading batch axis the population machinery shards — a
# query lane is a one-candidate population. These three helpers are the
# serve-side pad/shard specs, mirroring make_sharded_code_eval's layout so
# one AOT executable per (lane, pod) bucket spans the whole mesh.


def serve_lane_count(lane_bucket: int, mesh: Optional[Mesh] = None) -> int:
    """Global lane count for a serve dispatch: the PER-DEVICE lane bucket
    times the mesh's shard count (identity with no mesh). The serve engine
    compiles one executable per (global_lanes, pod_bucket), so "equal
    per-device batch" comparisons across mesh sizes share lane buckets;
    remainder lanes inside the global count are ``pad_population``
    duplicates, accounted by ``pad_stats``/``occupancy_stats``."""
    if mesh is None:
        return int(lane_bucket)
    return int(lane_bucket) * _num_shards(mesh)


def serve_sharding(mesh: Mesh) -> NamedSharding:
    """The NamedSharding that places a leading lane/batch axis over the
    mesh's pop axes — what serve uploads (query deltas, cached snapshot
    tables, initial states) are ``device_put`` with, and what the AOT
    executable's in_shardings are lowered from."""
    return NamedSharding(mesh, P(_pop_axes(mesh)))


def make_sharded_serve_fn(serve_fn, mesh: Mesh, layout=None):
    """Wrap a lane-batched serve pipeline ``(pods, ktable, state0) ->
    SimResult`` in ``shard_map`` over the pop axes: every argument and
    result pytree shards on its leading lane axis. The pipeline contains
    NO collectives — each device drains its own lane chunk through its own
    ``run_batched_lanes`` while_loop, so per-device trip counts are
    independent and a short lane never stalls a long one across the mesh.
    ``check_vma=False`` for the same engine-internal reason as the
    population entry points (see NOTE above). The returned callable is
    tagged with the layout's canonical key and the wiring lands one
    ``layout_ledger`` row (component "serve"); the serve engine's
    per-batch occupancy rows join it at eval time."""
    from fks_tpu.obs.layout import record_layout, tag_layout
    spec = _resolve_layout(layout)
    axes = _pop_axes(mesh)
    fn = shard_map(serve_fn, mesh=mesh,
                   in_specs=(P(axes), P(axes), P(axes)),
                   out_specs=P(axes), check_vma=False)
    record_layout("serve", spec, mesh=mesh)
    return tag_layout(fn, spec.key)


def make_sharded_vm_serve_fn(serve_fn, mesh: Mesh, layout=None):
    """``make_sharded_serve_fn`` for the VM-native serving pipeline
    ``(program, pods, ktable, state0) -> SimResult``: the batch axes
    shard exactly as before, while the champion's packed ``VMProgram``
    tables (argument 0) are REPLICATED — ``P()`` as a pytree-prefix spec
    — so every device holds the full register program and lanes stay
    collective-free. One executable per (global_lanes, pod_bucket,
    program_capacity) then serves EVERY champion of that capacity bucket
    across the whole mesh. Layout-tagged like ``make_sharded_serve_fn``
    (component "vm_serve")."""
    from fks_tpu.obs.layout import record_layout, tag_layout
    spec = _resolve_layout(layout)
    axes = _pop_axes(mesh)
    fn = shard_map(serve_fn, mesh=mesh,
                   in_specs=(P(), P(axes), P(axes), P(axes)),
                   out_specs=P(axes), check_vma=False)
    record_layout("vm_serve", spec, mesh=mesh)
    return tag_layout(fn, spec.key)


def make_sharded_portfolio_serve_fn(serve_fn, mesh: Mesh, layout=None):
    """``make_sharded_vm_serve_fn`` for the portfolio serving pipeline
    ``(slot_tables, slots, pods, ktable, state0) -> SimResult``: the
    stacked per-slot program tables (argument 0) are REPLICATED exactly
    like the single champion's tables — every device holds the FULL
    portfolio, so any lane on any device can dispatch to any slot — while
    the per-lane slot indices (argument 1) shard with the batch axes they
    index. Lanes stay collective-free: slot dispatch is a local gather
    into the replicated tables. Layout-tagged with component
    "portfolio_serve"."""
    from fks_tpu.obs.layout import record_layout, tag_layout
    spec = _resolve_layout(layout)
    axes = _pop_axes(mesh)
    fn = shard_map(serve_fn, mesh=mesh,
                   in_specs=(P(), P(axes), P(axes), P(axes), P(axes)),
                   out_specs=P(axes), check_vma=False)
    record_layout("portfolio_serve", spec, mesh=mesh)
    return tag_layout(fn, spec.key)


def _global_results(run, state0, params_shard, axes):
    """Per-shard batched SimResult + the all-gather of the full population
    fitness vector (shared preamble of eval and generation-step). On a 1-D
    mesh the gather rides ICI only; on a hybrid mesh XLA decomposes the
    multi-axis gather into ICI-within-slice + one DCN hop. The full result
    stays shard-local (only the scalar score is gathered) so per-lane
    observables — the decision TraceBuffer included — ride out through the
    caller's sharded out_specs without crossing the interconnect."""
    res = run(params_shard, state0)
    return res, jax.lax.all_gather(res.policy_score, axes, tiled=True)


def _mask_pad(scores, real_count):
    """Pad slots must never win elite selection."""
    iota = jnp.arange(scores.shape[0])
    return jnp.where(iota < real_count, scores, -jnp.inf)


def _top_k_real(global_scores, real_count, k):
    """top_k that never surfaces a pad slot: when real_count < k the
    trailing slots repeat the best real candidate instead of returning a
    -inf pad entry (which would otherwise survive truncation and be
    sampled as a mutation parent)."""
    elite_scores, elite_idx = jax.lax.top_k(
        _mask_pad(global_scores, real_count), k)
    valid = jnp.isfinite(elite_scores)
    return (jnp.where(valid, elite_scores, elite_scores[0]),
            jnp.where(valid, elite_idx, elite_idx[0]))


# NOTE on check_vma=False: the engine's inner heap loops mix invariant
# literals into varying carries; the varying-manual-axes audit rejects that
# even though the program is correct. Correctness of the sharded path is
# covered by the sharded-vs-vmap parity tests instead. (On jax 0.4.x the
# same audit is spelled check_rep — the fks_tpu.utils.compat shim
# translates.)


def _engine_runner(workload, param_policy, cfg, engine):
    """(population run fn, initial state) for the chosen engine."""
    if engine == "fused":
        from fks_tpu.parallel.population import fused_runner
        frun = fused_runner(workload, param_policy, cfg)
        return (lambda params, _state0: frun(params)), None
    from fks_tpu.sim import get_engine
    mod = get_engine(engine)
    return (mod.make_population_run_fn(workload, param_policy, cfg),
            mod.initial_state(workload, cfg))


def _layout_eval_wrapper(jitted, component: str, spec, mesh: Mesh,
                         scenarios: int = 1, segments: int = 1):
    """Host-side wrap of a jitted ``(params, real_count=None)`` entry
    point: one ``layout_ledger`` row per launch (the eval-time pad/
    occupancy accounting — identical repeats dedupe in the ledger, so a
    steady generation loop costs one row until its population size
    changes padding). The jitted program is untouched — recording is
    pure host work before dispatch — and its AOT seam is forwarded
    (``.lower``), so the default layout still lowers bit-identically
    (the ``sharded_eval/default_layout`` jaxpr pin)."""
    from fks_tpu.obs.layout import record_layout, tag_layout

    record_layout(component, spec, mesh=mesh)

    def run(params, real_count=None):
        real = (lead_axis_size(params) if real_count is None
                else int(real_count))
        record_layout(component, spec, mesh=mesh, real_count=real,
                      scenarios=scenarios, segments=segments)
        return jitted(params, real_count)

    run.lower = jitted.lower
    run._fks_jitted = jitted
    return tag_layout(run, spec.key)


def make_sharded_eval(workload: Workload, mesh: Mesh,
                      param_policy: ParamPolicyFn = parametric.score,
                      cfg: SimConfig = SimConfig(),
                      elite_k: int = 8, engine: str = "exact",
                      layout=None):
    """Build ``eval(params[C, F], real_count) -> (scores[C], elite_idx[K],
    elite_scores[K])``.

    ``C`` must be a multiple of the mesh size (use ``pad_population``, and
    forward its ``real_count`` so duplicate pad candidates are excluded from
    the elite ranking). Inside ``shard_map`` each device vmaps over its
    C/shards chunk, then the fitness vector is all-gathered over the ``pop``
    ICI axis and every device computes the identical global top-k — the elite
    set used for parent sampling and truncation (reference semantics: sort
    desc + take elite_size, funsearch_integration.py:494-496).

    With ``cfg.decision_trace`` the tuple grows a fourth element: the
    per-candidate TraceBuffer pytree, sharded over ``pop`` like the scores
    (a ``P(axes)`` out_spec prefix over the whole subtree). Existing
    callers index the first three slots, so the extension is opt-in.

    ``layout`` declares the axis mapping (fks_tpu.obs.layout.LayoutSpec);
    None is the default spec — the behavior above, lowered bit-identically
    and jaxpr-pinned. This entry point has no scenario axis, so specs
    sharding scenarios are rejected. Wiring and every launch land
    ``layout_ledger`` rows (component "eval").
    """
    spec = _resolve_layout(layout)
    run, state0 = _engine_runner(workload, param_policy, cfg, engine)
    axes = _pop_axes(mesh)
    out_specs = (P(axes), P(), P()) + ((P(axes),) if cfg.decision_trace else ())

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P()),
        out_specs=out_specs,
        check_vma=False,
    )
    def shard_eval(params_shard, real_count):
        res, global_scores = _global_results(run, state0, params_shard, axes)
        elite_scores, elite_idx = _top_k_real(global_scores, real_count, elite_k)
        out = (res.policy_score, elite_idx, elite_scores)
        if cfg.decision_trace:
            out = out + (res.trace,)
        return out

    def sharded_eval(params, real_count=None):
        params = _shard_params(params, mesh)
        if real_count is None:
            real_count = params.shape[0]
        return shard_eval(params, jnp.asarray(real_count, jnp.int32))

    return _layout_eval_wrapper(jax.jit(sharded_eval), "eval", spec, mesh)


def make_sharded_generation_step(workload: Workload, mesh: Mesh,
                                 param_policy: ParamPolicyFn = parametric.score,
                                 cfg: SimConfig = SimConfig(),
                                 elite_k: int = 4,
                                 noise: float = 0.05,
                                 engine: str = "exact",
                                 layout=None):
    """One full on-device evolution generation for parametric populations:
    evaluate (sharded) -> all-gather fitness -> top-k elites -> mutate
    offspring from elites. This is the framework's "training step" — the
    device-resident analogue of the reference's evolve_generation
    (funsearch_integration.py:487-572) minus the host-side LLM stage, which
    stays on CPU exactly as the reference keeps it outside its hot path.

    Returns ``step(params[C,F], key, real_count=None) -> (new_params[C,F],
    scores[C], elite_scores[K])``; both params arrays are sharded over
    ``pop``. Forward ``pad_population``'s ``real_count`` so pad duplicates
    never win elite slots. Layout-tagged like ``make_sharded_eval``
    (component "gen_step"; no scenario axis here either).
    """
    spec = _resolve_layout(layout)
    run, state0 = _engine_runner(workload, param_policy, cfg, engine)
    axes = _pop_axes(mesh)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P(), P()),
        out_specs=(P(axes), P(axes), P()),
        check_vma=False,
    )
    def gen_step(params_shard, key, real_count):
        res, global_scores = _global_results(run, state0, params_shard, axes)
        local_scores = res.policy_score
        all_params = jax.lax.all_gather(params_shard, axes, tiled=True)
        elite_scores, elite_idx = _top_k_real(global_scores, real_count, elite_k)
        elites = all_params[elite_idx]

        # Per-shard offspring: elites survive in shard 0's slots, the rest
        # mutate from a random elite. Keys are folded per-shard so shards
        # draw independent noise.
        shard_id = _shard_index(mesh)
        k = jax.random.fold_in(key, shard_id)
        local_c = params_shard.shape[0]
        offspring = parametric.mutate(k, elites, local_c, noise)
        slot = shard_id * local_c + jnp.arange(local_c)
        is_elite_slot = slot < elite_k
        survivors = elites[jnp.minimum(slot, elite_k - 1)]
        new_shard = jnp.where(is_elite_slot[:, None], survivors, offspring)
        return new_shard, local_scores, elite_scores

    def step(params, key, real_count=None):
        params = _shard_params(params, mesh)
        if real_count is None:
            real_count = params.shape[0]
        return gen_step(params, key, jnp.asarray(real_count, jnp.int32))

    from fks_tpu.obs.layout import record_layout, tag_layout
    jitted = jax.jit(step)
    record_layout("gen_step", spec, mesh=mesh)

    def run_step(params, key, real_count=None):
        real = (lead_axis_size(params) if real_count is None
                else int(real_count))
        record_layout("gen_step", spec, mesh=mesh, real_count=real)
        return jitted(params, key, real_count)

    run_step.lower = jitted.lower
    run_step._fks_jitted = jitted
    return tag_layout(run_step, spec.key)


def make_sharded_code_eval(workload: Workload, mesh: Mesh,
                           cfg: SimConfig = SimConfig(),
                           elite_k: int = 8, engine: str = "exact",
                           seg_steps: int = 0, on_segment=None,
                           layout=None):
    """Build ``eval(stacked, real_count) -> (result, elite_idx[K],
    elite_scores[K])`` for STACKED VM code candidates — the code-candidate
    analogue of ``make_sharded_eval``.

    ``stacked`` is a ``vm.stack_programs`` batch; its candidate count must
    be a multiple of the mesh size (use ``pad_population``, which is
    pytree-generic, and forward ``real_count`` so pad duplicates are
    excluded from the elite ranking). Inside ``shard_map`` each device
    interprets its shard of the program batch through the population
    engine (``vm.score_static`` — one compiled program for the whole VM
    vocabulary, zero per-candidate XLA compiles), then the fitness vector
    is all-gathered over the pop axes so every device computes the
    identical global top-k. This closes the gap between the parametric
    tier (mesh-wide since the seed) and the headline FunSearch workload,
    which previously vmapped on one device (backend._run_vm_batch).

    ``result`` is the full per-candidate ``SimResult`` (sharded over the
    pop axes): the backend's failure semantics need ``failed``/
    ``truncated``/``policy_score``, not a bare fitness vector.

    ``seg_steps > 0`` bounds each device call to ~``seg_steps`` events per
    dispatch (the FKS_VM_SEG_STEPS contract, for runtimes that kill long
    device executions); engines without segmented internals fall back to
    the single-dispatch path. ``on_segment`` (zero-arg callable) fires on
    the host after every segment dispatch — the flight recorder's segment
    counter; ignored on the single-dispatch path.

    ``layout`` declares the axis mapping; None is the default spec with
    the ``seg_steps`` argument folded in as its segment size. Passing a
    spec whose ``seg_steps`` disagrees with a nonzero ``seg_steps``
    argument is an error (one declaration, one truth); specs sharding
    scenarios are rejected (no scenario axis here — see
    fks_tpu.scenarios.robust.make_sharded_suite_eval).
    """
    from fks_tpu.funsearch import vm
    from fks_tpu.sim import get_engine

    spec = _resolve_layout(layout, seg_steps=seg_steps)
    if layout is not None and seg_steps and spec.seg_steps != seg_steps:
        raise ValueError(
            f"layout {spec.key!r} declares seg_steps={spec.seg_steps} but "
            f"the seg_steps argument says {seg_steps}; declare it once")
    seg_steps = spec.seg_steps
    mod = get_engine(engine)
    if seg_steps > 0 and hasattr(mod, "make_segmented_population_run"):
        return _make_segmented_code_eval(workload, mesh, cfg, elite_k, mod,
                                         seg_steps, on_segment, spec)

    run = mod.make_population_run_fn(workload, vm.score_static, cfg)
    state0 = mod.initial_state(workload, cfg)
    axes = _pop_axes(mesh)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P()),
        out_specs=(P(axes), P(), P()),
        check_vma=False,
    )
    def shard_eval(progs_shard, real_count):
        res = run(progs_shard, state0)
        global_scores = jax.lax.all_gather(res.policy_score, axes,
                                           tiled=True)
        elite_scores, elite_idx = _top_k_real(global_scores, real_count,
                                              elite_k)
        return res, elite_idx, elite_scores

    def sharded_eval(stacked, real_count=None):
        stacked = shard_population(stacked, mesh)
        if real_count is None:
            real_count = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        return shard_eval(stacked, jnp.asarray(real_count, jnp.int32))

    return _layout_eval_wrapper(jax.jit(sharded_eval), "code_eval", spec,
                                mesh)


def _make_segmented_code_eval(workload: Workload, mesh: Mesh, cfg: SimConfig,
                              elite_k: int, mod, seg_steps: int,
                              on_segment=None, spec=None):
    """The segmented body of ``make_sharded_code_eval``: a host loop of
    jitted shard_map'd segments — ``flat.make_segmented_population_run``
    mirrored one level up, at the mesh. Per segment every shard advances
    its lanes ~``seg_steps`` events inside a bounded while_loop; one
    psum'd any-lane-active flag returns to the host, which re-dispatches
    until every lane on every shard drains (same carry, same divergence
    guard as the single-device runner). The handoff is double-buffered
    like ``flat.make_segmented_population_run``'s: segment i+1 is
    dispatched before segment i's psum'd flag is read, so no shard ever
    stalls on the host's flag sync; the flag lags one segment, the one
    overrun segment self-masks to a no-op on every shard, and the budget
    carries the matching extra observation slot (slack 2)."""
    from fks_tpu.funsearch import vm

    axes = _pop_axes(mesh)
    ktable, max_steps = mod.loop_tables(workload, cfg)

    def step_one(prog, s):
        return mod.build_step(
            workload, lambda pod, nodes: vm.score_static(prog, pod, nodes),
            cfg, ktable, max_steps)(s)

    vstep = jax.vmap(step_one, in_axes=(0, 0))

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P(axes)),
        out_specs=(P(axes), P()),
        check_vma=False,
    )
    def advance(progs_shard, bstate_shard):
        start = bstate_shard.steps  # frozen at segment entry

        def cond(s):
            return jnp.any(mod.lane_active(s, max_steps)
                           & (s.steps - start < seg_steps))

        out = jax.lax.while_loop(
            cond, lambda s: vstep(progs_shard, s), bstate_shard)
        # psum, not all_gather: one scalar per shard, and every device
        # holds the identical global continue/stop flag
        local = jnp.any(mod.lane_active(out, max_steps))
        active = jax.lax.psum(local.astype(jnp.int32), axes) > 0
        return out, active

    @jax.jit
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P()),
        out_specs=(P(axes), P(), P()),
        check_vma=False,
    )
    def finish(bstate_shard, real_count):
        res = jax.vmap(lambda s: mod.finalize(workload, cfg, s))(bstate_shard)
        global_scores = jax.lax.all_gather(res.policy_score, axes,
                                           tiled=True)
        elite_scores, elite_idx = _top_k_real(global_scores, real_count,
                                              elite_k)
        return res, elite_idx, elite_scores

    state0 = mod.initial_state(workload, cfg)
    from fks_tpu.obs.layout import record_layout, tag_layout
    if spec is None:
        spec = _resolve_layout(None, seg_steps=seg_steps)
    record_layout("code_eval", spec, mesh=mesh)

    def run(stacked, real_count=None):
        stacked = shard_population(stacked, mesh)
        pop = jax.tree_util.tree_leaves(stacked)[0].shape[0]
        if real_count is None:
            real_count = pop
        bstate = jax.device_put(mod.broadcast_state(state0, pop),
                                NamedSharding(mesh, P(_pop_axes(mesh))))
        active = True
        prev = None
        segments = 0
        for _ in range(segment_budget(max_steps, seg_steps, slack=2)):
            bstate, active = advance(stacked, bstate)
            segments += 1
            if on_segment is not None:
                on_segment()
            # double-buffered handoff: sync on the PREVIOUS segment's
            # psum'd flag only after this segment is already in flight
            if prev is not None and not bool(prev):
                active = prev
                break
            prev = active
        if bool(active):
            raise RuntimeError(
                "sharded segmented runner exhausted its segment budget "
                "with lanes still active — cond/step divergence in the "
                "population engine")
        # eval-time layout accounting: the segment count is only known
        # here, after the host loop drained (dedupes on identical repeats)
        record_layout("code_eval", spec, mesh=mesh,
                      real_count=int(real_count), segments=segments)
        return finish(bstate, jnp.asarray(real_count, jnp.int32))

    return tag_layout(run, spec.key)
