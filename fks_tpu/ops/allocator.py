"""GPU sub-allocation kernels: pick WHICH GPUs on the chosen node.

Vectorized re-design of the reference's list-sort allocators
(reference: simulator/main.py:150-199). Returns a boolean selection mask
over the node's GPU slots instead of index lists.
"""
from __future__ import annotations

import jax.numpy as jnp

# plain int, NOT jnp.int32(...): a module-level jnp scalar would initialize
# a backend at import time (this module is imported before callers get a
# chance to pin jax_platforms — e.g. __graft_entry__.dryrun_multichip)
_BIG = 2**30


def best_fit_gpus(milli_left, gpu_mask, gpu_milli_req, num_gpu):
    """Best-fit: the ``num_gpu`` eligible GPUs with the LEAST free milli,
    ties by ascending slot index (reference main.py:150-177 -- Python's
    stable sort on (milli_left,) preserves index order).

    Args are one node's row: milli_left i32[G], gpu_mask bool[G], scalars.
    Returns (select bool[G], ok bool). ``ok`` is False when fewer than
    ``num_gpu`` eligible GPUs exist (the reference raises ValueError there,
    main.py:164-165). For num_gpu == 0: empty selection, ok=True.
    """
    g = milli_left.shape[0]
    iota = jnp.arange(g, dtype=jnp.int32)
    eligible = gpu_mask & (milli_left >= gpu_milli_req)
    # lexicographic (milli_left, index) key; ineligible sorted last
    key = jnp.where(eligible, milli_left * g + iota, _BIG)
    order = jnp.argsort(key)
    rank = jnp.zeros(g, jnp.int32).at[order].set(iota)
    select = eligible & (rank < num_gpu)
    ok = jnp.sum(eligible.astype(jnp.int32)) >= num_gpu
    return select, ok


def first_fit_gpus(milli_left, gpu_mask, gpu_milli_req, num_gpu):
    """First-fit: the first ``num_gpu`` eligible GPUs in slot order
    (reference main.py:179-199, shipped as dead code -- kept for parity)."""
    eligible = gpu_mask & (milli_left >= gpu_milli_req)
    rank = jnp.cumsum(eligible.astype(jnp.int32)) - 1
    select = eligible & (rank < num_gpu)
    ok = jnp.sum(eligible.astype(jnp.int32)) >= num_gpu
    return select, ok
