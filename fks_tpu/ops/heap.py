"""An exact, on-device replica of CPython's binary heap (``heapq``).

Why this exists: the reference's event queue is a ``heapq`` of
``(time, Event)`` tuples (reference: simulator/event_simulator.py:19-58), and
one of its behaviors is *layout dependent*: when a pod cannot be placed, the
retry time is taken from the first DELETION found in raw heap-array order
(event_simulator.py:51-58), not in time order. To reproduce the reference's
observable numbers exactly (snapshot counts, fragmentation series, fitness)
we replicate the heap's array layout, which requires implementing CPython's
exact sift algorithms (``heapq._siftdown`` / ``_siftup``; the C module
mirrors the pure-Python ones).

Keys are ``(time, tie_rank)`` int32 pairs compared lexicographically -- the
reference compares tuples ``(time, Event)`` where ``Event.__lt__`` is pod-id
string order (event_simulator.py:16-17); ``tie_rank`` is the precomputed rank
of the pod id in lexicographic order, so integer comparison is equivalent.
Payload is ``(kind, pod_index)`` with kind 0=CREATION, 1=DELETION.

All ops are branchless/jit-safe: sift loops are ``lax.while_loop`` with
data-dependent (but O(log n)-bounded) trip counts; everything vmaps.
"""
from __future__ import annotations

import heapq
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

KIND_CREATE = 0
KIND_DELETE = 1


class EventHeap(NamedTuple):
    """Array-backed binary min-heap of scheduling events."""

    time: jax.Array  # i32[cap]
    rank: jax.Array  # i32[cap] pod-id tie rank (secondary key)
    kind: jax.Array  # i8[cap] 0=CREATE 1=DELETE
    pod: jax.Array  # i32[cap] pod index
    size: jax.Array  # i32[] live element count

    @property
    def capacity(self) -> int:
        return self.time.shape[0]


def _less(ta, ra, tb, rb):
    """Lexicographic (time, rank) compare == reference tuple compare."""
    return (ta < tb) | ((ta == tb) & (ra < rb))


def heap_from_events(times, ranks, kinds, pods, capacity: int | None = None) -> EventHeap:
    """Build the initial heap on host with CPython ``heapq.heapify`` itself.

    The reference heapifies the CREATE events in pod-list order
    (event_simulator.py:23-34); running the real ``heapq`` here guarantees an
    identical starting layout. Host-side only (trace prep), so using the
    stdlib is both simplest and exact.
    """
    items = [(int(t), int(r), int(k), int(p))
             for t, r, k, p in zip(times, ranks, kinds, pods)]
    heapq.heapify(items)  # (time, rank) unique per live pod => tuple order == key order
    n = len(items)
    cap = capacity or n
    if cap < n:
        raise ValueError(f"heap capacity {cap} < {n}")
    arr = np.zeros((4, cap), dtype=np.int64)
    if n:
        arr[:, :n] = np.array(items, dtype=np.int64).T
    return EventHeap(
        time=jnp.asarray(arr[0], jnp.int32),
        rank=jnp.asarray(arr[1], jnp.int32),
        kind=jnp.asarray(arr[2], jnp.int8),
        pod=jnp.asarray(arr[3], jnp.int32),
        size=jnp.asarray(n, jnp.int32),
    )


def _get(h: EventHeap, i):
    return h.time[i], h.rank[i], h.kind[i], h.pod[i]


def _set(h: EventHeap, i, item) -> EventHeap:
    t, r, k, p = item
    return h._replace(
        time=h.time.at[i].set(t),
        rank=h.rank.at[i].set(r),
        kind=h.kind.at[i].set(jnp.asarray(k, jnp.int8)),
        pod=h.pod.at[i].set(p),
    )


def _siftdown(h: EventHeap, startpos, pos, newitem) -> EventHeap:
    """CPython heapq._siftdown: bubble ``newitem`` up from ``pos``."""
    nt, nr, _, _ = newitem

    def cond(c):
        h_, pos_ = c
        parent = (pos_ - 1) >> 1
        pt, pr, _, _ = _get(h_, jnp.maximum(parent, 0))
        return (pos_ > startpos) & _less(nt, nr, pt, pr)

    def body(c):
        h_, pos_ = c
        parent = (pos_ - 1) >> 1
        h_ = _set(h_, pos_, _get(h_, parent))
        return h_, parent

    h, pos = jax.lax.while_loop(cond, body, (h, pos))
    return _set(h, pos, newitem)


def _siftup(h: EventHeap, pos, newitem, endpos) -> EventHeap:
    """CPython heapq._siftup: walk the smaller child up to the root path from
    ``pos``, then restore with ``_siftdown``. ``endpos`` is the live size."""
    startpos = pos

    def cond(c):
        _, pos_, childpos = c
        return childpos < endpos

    def body(c):
        h_, pos_, childpos = c
        right = childpos + 1
        ct, cr, _, _ = _get(h_, childpos)
        rt, rr, _, _ = _get(h_, jnp.minimum(right, endpos - 1))
        use_right = (right < endpos) & ~_less(ct, cr, rt, rr)
        childpos = jnp.where(use_right, right, childpos)
        h_ = _set(h_, pos_, _get(h_, childpos))
        return h_, childpos, 2 * childpos + 1

    h, pos, _ = jax.lax.while_loop(cond, body, (h, pos, 2 * pos + 1))
    return _siftdown(h, startpos, pos, newitem)


def heap_push(h: EventHeap, time, rank, kind, pod, pred=True) -> EventHeap:
    """heapq.heappush; no-op when ``pred`` is False (for branchless callers)."""
    pos = h.size
    h2 = _siftdown(h._replace(size=h.size + 1), jnp.int32(0), pos,
                   (time, rank, jnp.asarray(kind, jnp.int8), pod))
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(pred, a, b), h2, h)


def heap_pop(h: EventHeap):
    """heapq.heappop. Caller must ensure size > 0. Returns (heap, item)."""
    item = _get(h, 0)
    newsize = h.size - 1
    last = _get(h, newsize)
    # when newsize == 0 the sift degenerates to writing last back to slot 0,
    # which equals the popped item -- harmless, matching heapq's early return.
    h = _siftup(h._replace(size=newsize), jnp.int32(0), last, newsize)
    return h, item


def first_deletion_in_array_order(h: EventHeap):
    """Reference ``repush_creation_event`` scan (event_simulator.py:51-58):
    the first DELETION in raw backing-array order. Returns (found, time)."""
    cap = h.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_del = (h.kind == KIND_DELETE) & (idx < h.size)
    pos = jnp.argmax(is_del)  # first True in array order
    found = is_del[pos]
    return found, h.time[pos]
