"""An exact, on-device replica of CPython's binary heap (``heapq``).

Why this exists: the reference's event queue is a ``heapq`` of
``(time, Event)`` tuples (reference: simulator/event_simulator.py:19-58), and
one of its behaviors is *layout dependent*: when a pod cannot be placed, the
retry time is taken from the first DELETION found in raw heap-array order
(event_simulator.py:51-58), not in time order. To reproduce the reference's
observable numbers exactly (snapshot counts, fragmentation series, fitness)
we replicate the heap's array layout, which requires implementing CPython's
exact sift algorithms (``heapq._siftdown`` / ``_siftup``; the C module
mirrors the pure-Python ones).

Keys are ``(time, tie_rank)`` int32 pairs compared lexicographically -- the
reference compares tuples ``(time, Event)`` where ``Event.__lt__`` is pod-id
string order (event_simulator.py:16-17); ``tie_rank`` is the precomputed rank
of the pod id in lexicographic order, so integer comparison is equivalent.
Payload is ``(kind, pod_index)`` with kind 0=CREATION, 1=DELETION.

TPU-native formulation: a sift is "insert one item into the sorted
root-to-hole chain of slots" -- the chain is at most ``ceil(log2(cap))+1``
slots, its indices are pure arithmetic (push) or a fixed-depth unrolled
smaller-child descent with a *scalar* carry (pop), and the whole mutation is
ONE gather + ONE duplicate-free scatter of <= ~14 elements. No
data-dependent ``while_loop`` ever touches the backing arrays, so the ops
cost O(log n) elements of HBM traffic per event and batch cleanly under
``vmap`` (a lane-masked op is a dropped scatter, not a full-array select).
This is what makes the engine's event loop a lean ``lax.while_loop`` body
(SURVEY.md §7 "hard parts": 2.5M scan-steps/s/chip budget).
"""
from __future__ import annotations

import heapq
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

KIND_CREATE = 0
KIND_DELETE = 1


class EventHeap(NamedTuple):
    """Array-backed binary min-heap of scheduling events."""

    time: jax.Array  # i32[cap]
    rank: jax.Array  # i32[cap] pod-id tie rank (secondary key)
    kind: jax.Array  # i8[cap] 0=CREATE 1=DELETE
    pod: jax.Array  # i32[cap] pod index
    size: jax.Array  # i32[] live element count

    @property
    def capacity(self) -> int:
        return self.time.shape[0]

    @property
    def levels(self) -> int:
        """Max root-to-leaf path length: ceil(log2(cap)) + 1."""
        return max(1, int(np.ceil(np.log2(max(self.capacity, 2)))) + 1)


def _less(ta, ra, tb, rb):
    """Lexicographic (time, rank) compare == reference tuple compare."""
    return (ta < tb) | ((ta == tb) & (ra < rb))


def heap_from_events(times, ranks, kinds, pods, capacity: int | None = None) -> EventHeap:
    """Build the initial heap on host with CPython ``heapq.heapify`` itself.

    The reference heapifies the CREATE events in pod-list order
    (event_simulator.py:23-34); running the real ``heapq`` here guarantees an
    identical starting layout. Host-side only (trace prep), so using the
    stdlib is both simplest and exact.
    """
    items = [(int(t), int(r), int(k), int(p))
             for t, r, k, p in zip(times, ranks, kinds, pods)]
    heapq.heapify(items)  # (time, rank) unique per live pod => tuple order == key order
    n = len(items)
    cap = capacity or n
    if cap < n:
        raise ValueError(f"heap capacity {cap} < {n}")
    arr = np.zeros((4, cap), dtype=np.int64)
    if n:
        arr[:, :n] = np.array(items, dtype=np.int64).T
    return EventHeap(
        time=jnp.asarray(arr[0], jnp.int32),
        rank=jnp.asarray(arr[1], jnp.int32),
        kind=jnp.asarray(arr[2], jnp.int8),
        pod=jnp.asarray(arr[3], jnp.int32),
        size=jnp.asarray(n, jnp.int32),
    )


def _gather(h: EventHeap, idx):
    """Clamped gather of items at ``idx`` (any shape)."""
    i = jnp.clip(idx, 0, h.capacity - 1)
    return h.time[i], h.rank[i], h.kind[i], h.pod[i]


def _scatter(h: EventHeap, idx, t, r, k, p, new_size) -> EventHeap:
    """Duplicate-free drop-mode scatter of items; indices == cap are dropped."""
    return EventHeap(
        time=h.time.at[idx].set(t, mode="drop"),
        rank=h.rank.at[idx].set(r, mode="drop"),
        kind=h.kind.at[idx].set(k.astype(jnp.int8), mode="drop"),
        pod=h.pod.at[idx].set(p, mode="drop"),
        size=new_size,
    )


def heap_push(h: EventHeap, time, rank, kind, pod, pred=True) -> EventHeap:
    """``heapq.heappush``; no-op when ``pred`` is False.

    CPython's ``_siftdown(heap, 0, size)`` bubbles the new item up the
    ancestor chain of the insertion slot. In a valid heap that chain is
    sorted ascending root->leaf, so the sift is equivalent to: find the
    insertion depth ``s`` = number of ancestors <= newitem, shift the deeper
    ancestors down one level, write newitem at depth ``s``. All chain
    indices are arithmetic in ``pos = size``; one gather + one scatter.
    """
    L = h.levels
    cap = jnp.int32(h.capacity)
    pos = h.size
    xt = jnp.asarray(time, jnp.int32)
    xr = jnp.asarray(rank, jnp.int32)
    xk = jnp.asarray(kind, jnp.int8)
    xp = jnp.asarray(pod, jnp.int32)
    pred = jnp.asarray(pred, bool)

    # depth of the insertion slot: e = floor(log2(pos + 1))
    pos1 = pos + 1
    e = jnp.int32(0)
    for b in range(1, L + 1):
        e = e + ((pos1 >> b) > 0).astype(jnp.int32)

    # ancestor chain root->parent(pos): q_k = ((pos+1) >> (e-k)) - 1, k < e
    ks = jnp.arange(L, dtype=jnp.int32)
    shift = jnp.clip(e - ks, 0, 31)
    q = (pos1 >> shift) - 1  # [L]; q_e == pos for k == e
    valid = ks < e
    vt, vr, vk, vp = _gather(h, q)

    # insertion depth: ancestors with key <= newitem stay above it
    s = jnp.sum((valid & ~_less(xt, xr, vt, vr)).astype(jnp.int32))

    # ancestors at depth k in [s, e) move down to q_{k+1}; newitem -> q_s.
    # q_{k+1} = 2*q_k + 1 + (child parity of the path), but simpler: the
    # chain is q itself shifted, and q_{k+1} for k<e is exactly q[k+1]
    # (q has L entries; k+1 <= e <= L-1).
    q_next = jnp.concatenate([q[1:], jnp.full((1,), cap, jnp.int32)])
    move = valid & (ks >= s) & pred
    tgt = jnp.where(move, q_next, cap)  # drop when not moving
    x_tgt = jnp.where(pred, q[jnp.minimum(s, L - 1)], cap)

    idx = jnp.concatenate([tgt, x_tgt[None]])
    t_all = jnp.concatenate([vt, xt[None]])
    r_all = jnp.concatenate([vr, xr[None]])
    k_all = jnp.concatenate([vk, xk[None]])
    p_all = jnp.concatenate([vp, xp[None]])
    new_size = h.size + pred.astype(jnp.int32)
    return _scatter(h, idx, t_all, r_all, k_all, p_all, new_size)


def heap_pop(h: EventHeap, pred=True):
    """``heapq.heappop``; no-op (garbage item) when ``pred`` is False.

    CPython's pop moves the last element into the root hole and runs
    ``_siftup``: descend the smaller-child path all the way to a leaf,
    shifting each child up one level, then ``_siftdown`` the moved item
    back up that path. Net effect: insert the last element into the sorted
    root-to-leaf smaller-child chain -- items above its insertion depth
    shift up one level, items below stay put. The descent carries only a
    scalar position (unrolled, fixed depth); the mutation is one scatter.

    Caller must ensure size > 0 when ``pred`` holds. Returns (heap, item).
    """
    L = h.levels
    cap = jnp.int32(h.capacity)
    item = _gather(h, jnp.int32(0))
    newsize = jnp.maximum(h.size - 1, 0)
    xt, xr, xk, xp = _gather(h, newsize)  # relocated last element

    # smaller-child descent from the root among live slots [0, newsize)
    qs, vts, vrs, vks, vps, alive_ks = [], [], [], [], [], []
    pos = jnp.int32(0)
    alive = jnp.bool_(True)
    for _ in range(1, L):
        child = 2 * pos + 1
        right = child + 1
        ct, cr, ck, cp = _gather(h, child)
        rt, rr, rk, rp = _gather(h, right)
        use_right = (right < newsize) & ~_less(ct, cr, rt, rr)
        cpos = jnp.where(use_right, right, child)
        alive = alive & (child < newsize)
        vt = jnp.where(use_right, rt, ct)
        vr = jnp.where(use_right, rr, cr)
        vk = jnp.where(use_right, rk, ck)
        vp = jnp.where(use_right, rp, cp)
        qs.append(cpos)
        vts.append(vt)
        vrs.append(vr)
        vks.append(vk)
        vps.append(vp)
        alive_ks.append(alive)
        pos = jnp.where(alive, cpos, pos)

    q = jnp.stack(qs)  # [L-1] path slots q_1..q_{L-1}
    vt = jnp.stack(vts)
    vr = jnp.stack(vrs)
    vk = jnp.stack(vks)
    vp = jnp.stack(vps)
    valid = jnp.stack(alive_ks)  # k <= d (live path levels)

    # insertion depth s = #{live v_k <= x}; chain ascending => suffix moves
    s = jnp.sum((valid & ~_less(xt, xr, vt, vr)).astype(jnp.int32))

    # v_k for k in [1, s] shift up to q_{k-1}; x -> q_s (q_0 = root slot 0)
    ks = 1 + jnp.arange(L - 1, dtype=jnp.int32)
    q_prev = jnp.concatenate([jnp.zeros((1,), jnp.int32), q[:-1]])
    pred = jnp.asarray(pred, bool)
    move = valid & (ks <= s) & pred
    tgt = jnp.where(move, q_prev, cap)
    x_tgt = jnp.where(
        pred, jnp.where(s > 0, q[jnp.clip(s - 1, 0, L - 2)], 0), cap)

    idx = jnp.concatenate([tgt, x_tgt[None]])
    t_all = jnp.concatenate([vt, xt[None]])
    r_all = jnp.concatenate([vr, xr[None]])
    k_all = jnp.concatenate([vk, xk[None]])
    p_all = jnp.concatenate([vp, xp[None]])
    new_size = jnp.where(pred, newsize, h.size)
    h2 = _scatter(h, idx, t_all, r_all, k_all, p_all, new_size)
    return h2, item


def first_deletion_in_array_order(h: EventHeap):
    """Reference ``repush_creation_event`` scan (event_simulator.py:51-58):
    the first DELETION in raw backing-array order. Returns (found, time)."""
    cap = h.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    is_del = (h.kind == KIND_DELETE) & (idx < h.size)
    pos = jnp.argmax(is_del)  # first True in array order
    found = is_del[pos]
    return found, h.time[pos]
