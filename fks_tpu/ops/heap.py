"""An exact, on-device replica of CPython's binary heap (``heapq``).

Why this exists: the reference's event queue is a ``heapq`` of
``(time, Event)`` tuples (reference: simulator/event_simulator.py:19-58), and
one of its behaviors is *layout dependent*: when a pod cannot be placed, the
retry time is taken from the first DELETION found in raw heap-array order
(event_simulator.py:51-58), not in time order. To reproduce the reference's
observable numbers exactly (snapshot counts, fragmentation series, fitness)
we replicate the heap's array layout, which requires implementing CPython's
exact sift algorithms (``heapq._siftdown`` / ``_siftup``; the C module
mirrors the pure-Python ones).

Keys are ``(time, tie_rank)`` int32 pairs compared lexicographically -- the
reference compares tuples ``(time, Event)`` where ``Event.__lt__`` is pod-id
string order (event_simulator.py:16-17); ``tie_rank`` is the precomputed rank
of the pod id in lexicographic order, so integer comparison is equivalent.
Payload is ``(kind, pod_index)`` with kind 0=CREATION, 1=DELETION.

TPU-native formulation: a sift is "insert one item into the sorted
root-to-hole chain of slots" -- the chain is at most ``ceil(log2(cap))+1``
slots, its indices are pure arithmetic (push) or a fixed-depth unrolled
smaller-child descent with a *scalar* carry (pop), and the whole mutation is
ONE gather + ONE duplicate-free scatter of <= ~14 elements. No
data-dependent ``while_loop`` ever touches the backing arrays, so the ops
cost O(log n) elements of HBM traffic per event and batch cleanly under
``vmap`` (a lane-masked op is a dropped scatter, not a full-array select).
This is what makes the engine's event loop a lean ``lax.while_loop`` body
(SURVEY.md §7 "hard parts": 2.5M scan-steps/s/chip budget).

Storage layout (round 3): the four per-item fields live as COLUMNS of one
``i32[cap, 4]`` matrix, so every heap mutation is a single row-gather plus
a single row-scatter instruction instead of four of each. On TPU,
per-lane-indexed gathers/scatters in a vmapped loop body cost serialized
latency PER INSTRUCTION (~35 us each, tools/probe_ops.py / PROFILE.md), so
instruction count -- not bytes -- is the price; rows cut it 4x.
"""
from __future__ import annotations

import heapq
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

KIND_CREATE = 0
KIND_DELETE = 1
# Scenario fault events (fks_tpu.scenarios): cordon / uncordon a node.
# They ride the same heap with pod column = node index; the retry-rule
# scan below matches KIND_DELETE only, so fault events never become
# retry anchors (the reference has no fault vocabulary to mirror).
KIND_NODE_DOWN = 2
KIND_NODE_UP = 3

# column indices of EventHeap.data
COL_TIME, COL_RANK, COL_KIND, COL_POD = 0, 1, 2, 3


class EventHeap(NamedTuple):
    """Array-backed binary min-heap of scheduling events.

    ``data[i] == (time, rank, kind, pod)`` of heap slot ``i``; ``size`` is
    the live element count. The ``time``/``rank``/``kind``/``pod``
    properties are column views for read paths (tests, the engine's
    pending-deletion scans); mutation always goes through row ops.
    """

    data: jax.Array  # i32[cap, 4]
    size: jax.Array  # i32[] live element count

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    @property
    def levels(self) -> int:
        """Max root-to-leaf path length: ceil(log2(cap)) + 1."""
        return max(1, int(np.ceil(np.log2(max(self.capacity, 2)))) + 1)

    @property
    def time(self):
        return self.data[..., COL_TIME]

    @property
    def rank(self):
        return self.data[..., COL_RANK]

    @property
    def kind(self):
        return self.data[..., COL_KIND]

    @property
    def pod(self):
        return self.data[..., COL_POD]


def _less(ta, ra, tb, rb):
    """Lexicographic (time, rank) compare == reference tuple compare."""
    return (ta < tb) | ((ta == tb) & (ra < rb))


def heap_from_events(times, ranks, kinds, pods, capacity: int | None = None) -> EventHeap:
    """Build the initial heap on host with CPython ``heapq.heapify`` itself.

    The reference heapifies the CREATE events in pod-list order
    (event_simulator.py:23-34); running the real ``heapq`` here guarantees an
    identical starting layout. Host-side only (trace prep), so using the
    stdlib is both simplest and exact.
    """
    items = [(int(t), int(r), int(k), int(p))
             for t, r, k, p in zip(times, ranks, kinds, pods)]
    heapq.heapify(items)  # (time, rank) unique per live pod => tuple order == key order
    n = len(items)
    cap = capacity or n
    if cap < n:
        raise ValueError(f"heap capacity {cap} < {n}")
    arr = np.zeros((cap, 4), dtype=np.int64)
    if n:
        arr[:n, :] = np.array(items, dtype=np.int64)
    return EventHeap(data=jnp.asarray(arr, jnp.int32),
                     size=jnp.asarray(n, jnp.int32))


def _rows(h: EventHeap, idx):
    """Clamped row-gather of items at ``idx`` (any shape): one instruction.
    Returns ``[..., 4]`` rows."""
    i = jnp.clip(idx, 0, h.capacity - 1)
    return h.data[i]


def _scatter_rows(h: EventHeap, idx, rows, new_size) -> EventHeap:
    """Duplicate-free drop-mode row scatter; indices == cap are dropped.
    One instruction for all four fields."""
    return EventHeap(data=h.data.at[idx].set(rows, mode="drop"),
                     size=new_size)


def heap_push(h: EventHeap, time, rank, kind, pod, pred=True) -> EventHeap:
    """``heapq.heappush``; no-op when ``pred`` is False.

    CPython's ``_siftdown(heap, 0, size)`` bubbles the new item up the
    ancestor chain of the insertion slot. In a valid heap that chain is
    sorted ascending root->leaf, so the sift is equivalent to: find the
    insertion depth ``s`` = number of ancestors <= newitem, shift the deeper
    ancestors down one level, write newitem at depth ``s``. All chain
    indices are arithmetic in ``pos = size``; one gather + one scatter.
    """
    L = h.levels
    cap = jnp.int32(h.capacity)
    pos = h.size
    xt = jnp.asarray(time, jnp.int32)
    xr = jnp.asarray(rank, jnp.int32)
    xk = jnp.asarray(kind, jnp.int32)
    xp = jnp.asarray(pod, jnp.int32)
    pred = jnp.asarray(pred, bool)

    # depth of the insertion slot: e = floor(log2(pos + 1))
    pos1 = pos + 1
    e = jnp.int32(0)
    for b in range(1, L + 1):
        e = e + ((pos1 >> b) > 0).astype(jnp.int32)

    # ancestor chain root->parent(pos): q_k = ((pos+1) >> (e-k)) - 1, k < e
    ks = jnp.arange(L, dtype=jnp.int32)
    shift = jnp.clip(e - ks, 0, 31)
    q = (pos1 >> shift) - 1  # [L]; q_e == pos for k == e
    valid = ks < e
    v = _rows(h, q)  # [L, 4]
    vt, vr = v[:, COL_TIME], v[:, COL_RANK]

    # insertion depth: ancestors with key <= newitem stay above it
    s = jnp.sum((valid & ~_less(xt, xr, vt, vr)).astype(jnp.int32))

    # ancestors at depth k in [s, e) move down to q_{k+1}; newitem -> q_s.
    # q_{k+1} = 2*q_k + 1 + (child parity of the path), but simpler: the
    # chain is q itself shifted, and q_{k+1} for k<e is exactly q[k+1]
    # (q has L entries; k+1 <= e <= L-1).
    q_next = jnp.concatenate([q[1:], jnp.full((1,), cap, jnp.int32)])
    move = valid & (ks >= s) & pred
    tgt = jnp.where(move, q_next, cap)  # drop when not moving
    x_tgt = jnp.where(pred, q[jnp.minimum(s, L - 1)], cap)

    idx = jnp.concatenate([tgt, x_tgt[None]])
    x_row = jnp.stack([xt, xr, xk, xp])
    rows = jnp.concatenate([v, x_row[None, :]], axis=0)  # [L+1, 4]
    new_size = h.size + pred.astype(jnp.int32)
    return _scatter_rows(h, idx, rows, new_size)


def heap_pop(h: EventHeap, pred=True):
    """``heapq.heappop``; no-op (garbage item) when ``pred`` is False.

    CPython's pop moves the last element into the root hole and runs
    ``_siftup``: descend the smaller-child path all the way to a leaf,
    shifting each child up one level, then ``_siftdown`` the moved item
    back up that path. Net effect: insert the last element into the sorted
    root-to-leaf smaller-child chain -- items above its insertion depth
    shift up one level, items below stay put. The descent carries only a
    scalar position (unrolled, fixed depth); the mutation is one scatter.

    Caller must ensure size > 0 when ``pred`` holds. Returns (heap, item)
    with item = (time, rank, kind, pod) scalars.
    """
    L = h.levels
    cap = jnp.int32(h.capacity)
    newsize = jnp.maximum(h.size - 1, 0)
    head_last = _rows(h, jnp.stack([jnp.int32(0), newsize]))  # [2, 4]
    item = (head_last[0, COL_TIME], head_last[0, COL_RANK],
            head_last[0, COL_KIND], head_last[0, COL_POD])
    x = head_last[1]  # relocated last element
    xt, xr = x[COL_TIME], x[COL_RANK]

    # smaller-child descent from the root among live slots [0, newsize):
    # one [2, 4] row-gather per level (child + right sibling)
    qs, vrows, alive_ks = [], [], []
    pos = jnp.int32(0)
    alive = jnp.bool_(True)
    for _ in range(1, L):
        child = 2 * pos + 1
        right = child + 1
        pair = _rows(h, jnp.stack([child, right]))  # [2, 4]
        ct, cr = pair[0, COL_TIME], pair[0, COL_RANK]
        rt, rr = pair[1, COL_TIME], pair[1, COL_RANK]
        use_right = (right < newsize) & ~_less(ct, cr, rt, rr)
        cpos = jnp.where(use_right, right, child)
        alive = alive & (child < newsize)
        vrow = jnp.where(use_right, pair[1], pair[0])  # [4]
        qs.append(cpos)
        vrows.append(vrow)
        alive_ks.append(alive)
        pos = jnp.where(alive, cpos, pos)

    q = jnp.stack(qs)  # [L-1] path slots q_1..q_{L-1}
    v = jnp.stack(vrows)  # [L-1, 4]
    vt, vr = v[:, COL_TIME], v[:, COL_RANK]
    valid = jnp.stack(alive_ks)  # k <= d (live path levels)

    # insertion depth s = #{live v_k <= x}; chain ascending => suffix moves
    s = jnp.sum((valid & ~_less(xt, xr, vt, vr)).astype(jnp.int32))

    # v_k for k in [1, s] shift up to q_{k-1}; x -> q_s (q_0 = root slot 0)
    ks = 1 + jnp.arange(L - 1, dtype=jnp.int32)
    q_prev = jnp.concatenate([jnp.zeros((1,), jnp.int32), q[:-1]])
    pred = jnp.asarray(pred, bool)
    move = valid & (ks <= s) & pred
    tgt = jnp.where(move, q_prev, cap)
    x_tgt = jnp.where(
        pred, jnp.where(s > 0, q[jnp.clip(s - 1, 0, L - 2)], 0), cap)

    idx = jnp.concatenate([tgt, x_tgt[None]])
    rows = jnp.concatenate([v, x[None, :]], axis=0)  # [L, 4]
    new_size = jnp.where(pred, newsize, h.size)
    h2 = _scatter_rows(h, idx, rows, new_size)
    return h2, item


def first_deletion_in_array_order(h: EventHeap):
    """Reference ``repush_creation_event`` scan (event_simulator.py:51-58):
    the first DELETION in raw backing-array order. Returns (found, time)."""
    cap = h.capacity
    idx = jnp.arange(cap, dtype=jnp.int32)
    kind = h.data[:, COL_KIND]
    is_del = (kind == KIND_DELETE) & (idx < h.size)
    pos = jnp.argmax(is_del)  # first True in array order
    found = is_del[pos]
    return found, h.data[pos, COL_TIME]
