"""Named, versioned scenario suites.

A suite is an ordered tuple of ``ScenarioSpec``s materialized against one
base workload with a SHARED fault padding, so the resulting workloads have
identical pytree structure and stack into the ``parallel.traces`` batched
trace pytree (every scenario carries a FaultEvents timeline; fault-free
ones get an all-masked padding-only timeline).

Versioning: ``SUITE_VERSION`` bumps whenever the registry's specs or the
generator's derivation change, so a robust score recorded in a champion
JSON or the evolution ledger names the exact scenario family it was
measured on.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

from fks_tpu.data.entities import Workload
from fks_tpu.scenarios.generator import (
    ScenarioSpec, fault_events_for, perturb_workload,
)

#: bump when registry specs or generator derivations change
SUITE_VERSION = 1

#: registered suites: name -> ordered specs. ``default8`` is the headline
#: robust-fitness suite: the base trace + 7 perturbed/fault variants.
SUITE_SPECS: Dict[str, Tuple[ScenarioSpec, ...]] = {
    "default8": (
        ScenarioSpec("base"),
        ScenarioSpec("jitter", seed=11, arrival_jitter_frac=0.02),
        ScenarioSpec("demand_up", seed=12, demand_scale=1.10),
        ScenarioSpec("demand_down", seed=13, demand_scale=0.90),
        ScenarioSpec("podmix", seed=14, pod_mix_swap_frac=0.30),
        ScenarioSpec("fault1", seed=15, fault_nodes=1),
        ScenarioSpec("fault2", seed=16, fault_nodes=2,
                     fault_duration_frac=0.10),
        ScenarioSpec("mixed", seed=17, arrival_jitter_frac=0.01,
                     demand_scale=1.05, fault_nodes=1,
                     fault_start_frac=0.55),
    ),
    "smoke3": (
        ScenarioSpec("base"),
        ScenarioSpec("jitter", seed=21, arrival_jitter_frac=0.02),
        ScenarioSpec("fault1", seed=22, fault_nodes=1),
    ),
}


@dataclasses.dataclass(frozen=True)
class ScenarioSuite:
    """A materialized suite: specs + same-shape workloads, ready to stack."""

    name: str
    version: int
    specs: Tuple[ScenarioSpec, ...]
    workloads: Tuple[Workload, ...]
    fault_pad: int

    def __len__(self) -> int:
        return len(self.specs)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(s.name for s in self.specs)

    def describe(self) -> dict:
        """JSON-ready suite summary (cli scenarios / recorder metric)."""
        return {
            "suite": self.name,
            "version": self.version,
            "fault_pad": self.fault_pad,
            "scenarios": [
                {**spec.describe(),
                 "fault_events": int(wl.faults.num_events)}
                for spec, wl in zip(self.specs, self.workloads)
            ],
        }


def build_suite(name: str, version: int, specs: Sequence[ScenarioSpec],
                base: Workload) -> ScenarioSuite:
    """Materialize ``specs`` against ``base`` with one shared fault pad
    (>= 1 so every scenario shares the FaultEvents treedef)."""
    specs = tuple(specs)
    if not specs:
        raise ValueError(f"suite {name!r} has no scenarios")
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"suite {name!r} has duplicate scenario names")
    fault_pad = max(
        [1] + [len(fault_events_for(base, s)) for s in specs])
    workloads = tuple(
        perturb_workload(base, s, fault_pad=fault_pad) for s in specs)
    return ScenarioSuite(name=name, version=version, specs=specs,
                         workloads=workloads, fault_pad=fault_pad)


def list_suites() -> Dict[str, dict]:
    """Registry overview: name -> {version, size, scenario names}."""
    return {
        name: {"version": SUITE_VERSION, "size": len(specs),
               "scenarios": [s.name for s in specs]}
        for name, specs in sorted(SUITE_SPECS.items())
    }


def get_suite(name: str, base: Workload) -> ScenarioSuite:
    """Materialize a registered suite against ``base``."""
    if name not in SUITE_SPECS:
        raise ValueError(f"unknown scenario suite {name!r}; "
                         f"available: {', '.join(sorted(SUITE_SPECS))}")
    return build_suite(name, SUITE_VERSION, SUITE_SPECS[name], base)
