"""Scenario suites: deterministic workload variants + robust fitness.

The single-trace fitness the paper optimizes is a point estimate — a
candidate can overfit one arrival pattern on a healthy cluster. This
subsystem turns fitness into a robustness measure:

- ``generator`` — seed-derived perturbations of a base workload
  (arrival jitter, demand scaling, pod-mix shifts) plus fault injection
  as precomputable NODE_DOWN/NODE_UP trace events with cordon semantics.
- ``suite`` — named, versioned scenario suites (``default8``: base + 7
  variants) materialized as same-shape workloads that stack under
  ``parallel.traces``.
- ``robust`` — evaluate one candidate (or a population) over the whole
  suite in ONE vmapped device call (or sharded over a mesh) and fold the
  per-scenario scores into a composite robust score (weighted mean /
  min / CVaR-α).

Wired into ``funsearch.backend.CodeEvaluator`` and ``funsearch.evolution``
behind ``EvolutionConfig.scenario_suite`` so elites are selected by
robustness rather than single-trace fitness.
"""
from fks_tpu.scenarios.generator import (  # noqa: F401
    ScenarioSpec, fault_events_for, make_fault_events, perturb_workload,
)
from fks_tpu.scenarios.robust import (  # noqa: F401
    AGGREGATIONS, RobustConfig, aggregate, make_sharded_suite_eval,
    make_suite_eval,
)
from fks_tpu.scenarios.suite import (  # noqa: F401
    SUITE_VERSION, ScenarioSuite, build_suite, get_suite, list_suites,
)
