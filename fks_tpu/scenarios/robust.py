"""Robust fitness: evaluate candidates over a scenario suite in one call.

A suite's workloads share one padded shape (suite.py pins the fault pad),
so the whole suite rides the existing multi-trace machinery
(``parallel.traces.make_trace_batch_eval``): ONE vmapped device program
evaluates a candidate on every scenario — fault-injected variants
included — instead of T sequential single-trace runs. On a mesh the
candidate axis additionally shards over the pop axes exactly like
``parallel.mesh.make_sharded_eval``, and elite selection ranks the
COMPOSITE robust score, not any single trace's fitness.

Aggregations (host-static choice, folded over the trailing scenario axis):

- ``mean`` — (optionally weighted) average; the E[fitness] estimate.
- ``min``  — worst case; a candidate is only as good as its worst scenario.
- ``cvar`` — CVaR-α: mean of the worst ``ceil(α·T)`` scenarios; tail risk
  without min's single-outlier brittleness.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fks_tpu.models import parametric
from fks_tpu.parallel.mesh import (
    SCN_AXIS, _pop_axes, _resolve_layout, _top_k_real, shard_population,
)
from fks_tpu.parallel.population import ParamPolicyFn
from fks_tpu.parallel.traces import make_trace_batch_eval, stack_traces
from fks_tpu.scenarios.suite import ScenarioSuite
from fks_tpu.sim.engine import SimConfig
from fks_tpu.utils.compat import shard_map

AGGREGATIONS = ("mean", "min", "cvar")


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """How per-scenario fitness folds into one robust score."""

    aggregation: str = "mean"
    cvar_alpha: float = 0.25  # tail fraction for aggregation="cvar"
    weights: Optional[Tuple[float, ...]] = None  # aggregation="mean" only

    def __post_init__(self):
        if self.aggregation not in AGGREGATIONS:
            raise ValueError(f"unknown aggregation {self.aggregation!r}; "
                             f"choose from {AGGREGATIONS}")
        if not (0.0 < self.cvar_alpha <= 1.0):
            raise ValueError(f"cvar_alpha {self.cvar_alpha} not in (0, 1]")
        if self.weights is not None and self.aggregation != "mean":
            raise ValueError("weights only apply to aggregation='mean'")

    def describe(self) -> dict:
        return dataclasses.asdict(self)


def aggregate(scores, rc: RobustConfig = RobustConfig()):
    """Fold per-scenario scores (TRAILING axis) into the robust score.
    jit/vmap-safe: the aggregation choice and CVaR tail size are host
    constants, only the scores are traced."""
    scores = jnp.asarray(scores)
    if rc.aggregation == "mean":
        if rc.weights is not None:
            w = jnp.asarray(rc.weights, scores.dtype)
            if w.shape[0] != scores.shape[-1]:
                raise ValueError(
                    f"{w.shape[0]} weights for {scores.shape[-1]} scenarios")
            return jnp.sum(scores * w, axis=-1) / jnp.sum(w)
        return jnp.mean(scores, axis=-1)
    if rc.aggregation == "min":
        return jnp.min(scores, axis=-1)
    # cvar: mean of the worst ceil(alpha * T) scenarios
    k = max(1, int(np.ceil(rc.cvar_alpha * scores.shape[-1])))
    return jnp.mean(jnp.sort(scores, axis=-1)[..., :k], axis=-1)


def make_suite_eval(suite: ScenarioSuite,
                    param_policy: ParamPolicyFn = parametric.score,
                    cfg: SimConfig = SimConfig(),
                    population: bool = False,
                    jit: bool = True,
                    engine: str = "exact"):
    """``eval(params) -> SimResult`` over the suite's scenario axis: result
    leaves are [T] (one candidate) or [C, T] (``population=True``) with
    T = len(suite). Thin delegation to the multi-trace batcher — a suite
    IS a same-shape trace batch, faults included."""
    return make_trace_batch_eval(
        list(suite.workloads), param_policy=param_policy, cfg=cfg,
        population=population, jit=jit, engine=engine)


def make_sharded_suite_eval(suite: ScenarioSuite, mesh: Mesh,
                            param_policy: ParamPolicyFn = parametric.score,
                            cfg: SimConfig = SimConfig(),
                            rc: RobustConfig = RobustConfig(),
                            elite_k: int = 8, engine: str = "exact",
                            layout=None):
    """Build ``eval(params[C, ...], real_count) -> (robust[C],
    per_scenario[C, T], elite_idx[K], elite_scores[K])``: candidates
    sharded over the mesh's pop axes, each shard vmapping its chunk over
    candidates x scenarios, then ONE all-gather of the composite robust
    vector so every device ranks the identical robust elite set. Per-
    scenario scores stay shard-local (out_spec P(axes)) — only the
    aggregate crosses the interconnect, mirroring
    ``parallel.mesh.make_sharded_eval``'s traffic shape.

    ``layout`` (fks_tpu.obs.layout.LayoutSpec) may additionally shard the
    SCENARIO axis: on a 2-D ``layout_mesh`` each device then evaluates a
    (candidate chunk x scenario chunk) tile, the per-scenario scores
    all-gather over the inner "scn" axis before aggregation, and the
    robust gather crosses candidate shards exactly as before. The suite
    length must divide the scenario shard count's mesh extent (scenario
    suites are authored, never remainder-padded). ``layout=None`` is the
    default candidates-only spec — the behavior above, lowered
    bit-identically (jaxpr-pinned). Wiring and every launch land
    ``layout_ledger`` rows (component "suite_eval")."""
    from fks_tpu.obs.layout import record_layout, tag_layout

    spec = _resolve_layout(layout, scenarios=True, scenario_shardable=True)
    axes = _pop_axes(mesh)
    scn_shards = int(mesh.shape.get(SCN_AXIS, 1))
    if "scenarios" in spec.shard:
        if scn_shards <= 1:
            raise ValueError(
                f"layout {spec.key!r} shards scenarios but the mesh has "
                f"no '{SCN_AXIS}' axis — build one with "
                "parallel.mesh.layout_mesh(devices, scenario_shards)")
        if len(suite) % scn_shards:
            raise ValueError(
                f"suite of {len(suite)} scenarios does not divide into "
                f"{scn_shards} scenario shards")
        shard_eval = _scenario_sharded_suite_eval(
            suite, mesh, param_policy, cfg, rc, elite_k, engine, axes)
    elif scn_shards > 1:
        raise ValueError(
            f"mesh has a {scn_shards}-way '{SCN_AXIS}' axis but layout "
            f"{spec.key!r} does not shard scenarios")
    else:
        inner = make_trace_batch_eval(
            list(suite.workloads), param_policy=param_policy, cfg=cfg,
            population=True, jit=False, engine=engine)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(axes), P()),
            out_specs=(P(axes), P(axes), P(), P()),
            check_vma=False,
        )
        def shard_eval(params_shard, real_count):
            res = inner(params_shard)          # leaves [C/shards, T]
            per = res.policy_score
            robust = aggregate(per, rc)
            global_robust = jax.lax.all_gather(robust, axes, tiled=True)
            elite_scores, elite_idx = _top_k_real(global_robust, real_count,
                                                  elite_k)
            return robust, per, elite_idx, elite_scores

    def sharded_eval(params, real_count=None):
        params = shard_population(params, mesh)
        if real_count is None:
            real_count = jax.tree_util.tree_leaves(params)[0].shape[0]
        return shard_eval(params, jnp.asarray(real_count, jnp.int32))

    jitted = jax.jit(sharded_eval)
    record_layout("suite_eval", spec, mesh=mesh)

    def run(params, real_count=None):
        from fks_tpu.parallel.population import lead_axis_size
        real = (lead_axis_size(params) if real_count is None
                else int(real_count))
        record_layout("suite_eval", spec, mesh=mesh, real_count=real,
                      scenarios=len(suite))
        return jitted(params, real_count)

    run.lower = jitted.lower
    run._fks_jitted = jitted
    return tag_layout(run, spec.key)


def _scenario_sharded_suite_eval(suite, mesh, param_policy, cfg, rc,
                                 elite_k, engine, axes):
    """The scenario-sharded body of ``make_sharded_suite_eval``: the
    stacked suite pytrees (workload[T,...], ktable[T,K], state0[T,...])
    become shard_map ARGUMENTS split over the inner "scn" axis — the
    same arrays ``make_trace_batch_eval`` closes over on the default
    path — so each device drives its own scenario chunk through the
    shared ``run_batched_lanes`` while_loop. Per-scenario scores gather
    over "scn" (one [C_local, T] tile per device) before the host-static
    aggregation, so the robust fold sees the full scenario axis and the
    elite ranking is layout-invariant (parity-gated at 1e-5 by
    tools/run_full_suite's layout_gate). layout-exempt: the enclosing
    ``make_sharded_suite_eval`` resolves the spec and tags/records the
    runner it wraps around this body."""
    from fks_tpu.sim import get_engine
    from fks_tpu.sim.engine import run_batched_lanes

    mod = get_engine(engine)
    wl, kt, state0, max_steps = stack_traces(list(suite.workloads), cfg,
                                             engine)

    def step_one(workload, ktable, params, s):
        return mod.build_step(
            workload, lambda pod, nodes: param_policy(params, pod, nodes),
            cfg, ktable, max_steps)(s)

    vstep = jax.vmap(jax.vmap(step_one, in_axes=(0, 0, None, 0)),
                     in_axes=(None, None, 0, 0))
    vfin = jax.vmap(jax.vmap(lambda w, s: mod.finalize(w, cfg, s),
                             in_axes=(0, 0)), in_axes=(None, 0))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P(), P(SCN_AXIS), P(SCN_AXIS), P(SCN_AXIS)),
        out_specs=(P(axes), P(axes, SCN_AXIS), P(), P()),
        check_vma=False,
    )
    def shard_eval_args(params_shard, real_count, wl_s, kt_s, s0_s):
        pop = jax.tree_util.tree_leaves(params_shard)[0].shape[0]
        final = run_batched_lanes(
            lambda s: vstep(wl_s, kt_s, params_shard, s),
            mod.broadcast_state(s0_s, pop), max_steps,
            active_fn=mod.lane_active)
        res = vfin(wl_s, final)
        per = res.policy_score                    # [C_local, T_local]
        per_full = jax.lax.all_gather(per, SCN_AXIS, axis=1, tiled=True)
        robust = aggregate(per_full, rc)          # [C_local]
        global_robust = jax.lax.all_gather(robust, axes, tiled=True)
        elite_scores, elite_idx = _top_k_real(global_robust, real_count,
                                              elite_k)
        return robust, per, elite_idx, elite_scores

    def shard_eval(params, real_count):
        return shard_eval_args(params, real_count, wl, kt, state0)

    return shard_eval
