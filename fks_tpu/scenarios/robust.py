"""Robust fitness: evaluate candidates over a scenario suite in one call.

A suite's workloads share one padded shape (suite.py pins the fault pad),
so the whole suite rides the existing multi-trace machinery
(``parallel.traces.make_trace_batch_eval``): ONE vmapped device program
evaluates a candidate on every scenario — fault-injected variants
included — instead of T sequential single-trace runs. On a mesh the
candidate axis additionally shards over the pop axes exactly like
``parallel.mesh.make_sharded_eval``, and elite selection ranks the
COMPOSITE robust score, not any single trace's fitness.

Aggregations (host-static choice, folded over the trailing scenario axis):

- ``mean`` — (optionally weighted) average; the E[fitness] estimate.
- ``min``  — worst case; a candidate is only as good as its worst scenario.
- ``cvar`` — CVaR-α: mean of the worst ``ceil(α·T)`` scenarios; tail risk
  without min's single-outlier brittleness.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from fks_tpu.models import parametric
from fks_tpu.parallel.mesh import _pop_axes, _top_k_real, shard_population
from fks_tpu.parallel.population import ParamPolicyFn
from fks_tpu.parallel.traces import make_trace_batch_eval
from fks_tpu.scenarios.suite import ScenarioSuite
from fks_tpu.sim.engine import SimConfig
from fks_tpu.utils.compat import shard_map

AGGREGATIONS = ("mean", "min", "cvar")


@dataclasses.dataclass(frozen=True)
class RobustConfig:
    """How per-scenario fitness folds into one robust score."""

    aggregation: str = "mean"
    cvar_alpha: float = 0.25  # tail fraction for aggregation="cvar"
    weights: Optional[Tuple[float, ...]] = None  # aggregation="mean" only

    def __post_init__(self):
        if self.aggregation not in AGGREGATIONS:
            raise ValueError(f"unknown aggregation {self.aggregation!r}; "
                             f"choose from {AGGREGATIONS}")
        if not (0.0 < self.cvar_alpha <= 1.0):
            raise ValueError(f"cvar_alpha {self.cvar_alpha} not in (0, 1]")
        if self.weights is not None and self.aggregation != "mean":
            raise ValueError("weights only apply to aggregation='mean'")

    def describe(self) -> dict:
        return dataclasses.asdict(self)


def aggregate(scores, rc: RobustConfig = RobustConfig()):
    """Fold per-scenario scores (TRAILING axis) into the robust score.
    jit/vmap-safe: the aggregation choice and CVaR tail size are host
    constants, only the scores are traced."""
    scores = jnp.asarray(scores)
    if rc.aggregation == "mean":
        if rc.weights is not None:
            w = jnp.asarray(rc.weights, scores.dtype)
            if w.shape[0] != scores.shape[-1]:
                raise ValueError(
                    f"{w.shape[0]} weights for {scores.shape[-1]} scenarios")
            return jnp.sum(scores * w, axis=-1) / jnp.sum(w)
        return jnp.mean(scores, axis=-1)
    if rc.aggregation == "min":
        return jnp.min(scores, axis=-1)
    # cvar: mean of the worst ceil(alpha * T) scenarios
    k = max(1, int(np.ceil(rc.cvar_alpha * scores.shape[-1])))
    return jnp.mean(jnp.sort(scores, axis=-1)[..., :k], axis=-1)


def make_suite_eval(suite: ScenarioSuite,
                    param_policy: ParamPolicyFn = parametric.score,
                    cfg: SimConfig = SimConfig(),
                    population: bool = False,
                    jit: bool = True,
                    engine: str = "exact"):
    """``eval(params) -> SimResult`` over the suite's scenario axis: result
    leaves are [T] (one candidate) or [C, T] (``population=True``) with
    T = len(suite). Thin delegation to the multi-trace batcher — a suite
    IS a same-shape trace batch, faults included."""
    return make_trace_batch_eval(
        list(suite.workloads), param_policy=param_policy, cfg=cfg,
        population=population, jit=jit, engine=engine)


def make_sharded_suite_eval(suite: ScenarioSuite, mesh: Mesh,
                            param_policy: ParamPolicyFn = parametric.score,
                            cfg: SimConfig = SimConfig(),
                            rc: RobustConfig = RobustConfig(),
                            elite_k: int = 8, engine: str = "exact"):
    """Build ``eval(params[C, ...], real_count) -> (robust[C],
    per_scenario[C, T], elite_idx[K], elite_scores[K])``: candidates
    sharded over the mesh's pop axes, each shard vmapping its chunk over
    candidates x scenarios, then ONE all-gather of the composite robust
    vector so every device ranks the identical robust elite set. Per-
    scenario scores stay shard-local (out_spec P(axes)) — only the
    aggregate crosses the interconnect, mirroring
    ``parallel.mesh.make_sharded_eval``'s traffic shape."""
    inner = make_trace_batch_eval(
        list(suite.workloads), param_policy=param_policy, cfg=cfg,
        population=True, jit=False, engine=engine)
    axes = _pop_axes(mesh)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axes), P()),
        out_specs=(P(axes), P(axes), P(), P()),
        check_vma=False,
    )
    def shard_eval(params_shard, real_count):
        res = inner(params_shard)          # leaves [C/shards, T]
        per = res.policy_score
        robust = aggregate(per, rc)
        global_robust = jax.lax.all_gather(robust, axes, tiled=True)
        elite_scores, elite_idx = _top_k_real(global_robust, real_count,
                                              elite_k)
        return robust, per, elite_idx, elite_scores

    def sharded_eval(params, real_count=None):
        params = shard_population(params, mesh)
        if real_count is None:
            real_count = jax.tree_util.tree_leaves(params)[0].shape[0]
        return shard_eval(params, jnp.asarray(real_count, jnp.int32))

    return jax.jit(sharded_eval)
