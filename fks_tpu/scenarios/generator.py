"""Deterministic workload perturbations + fault-event injection.

Every transform is seed-derived and host-side numpy: the same
``(base workload, ScenarioSpec)`` pair always materializes the exact same
arrays (byte-identical — pinned by tests/test_scenarios.py), so a
scenario suite is a pure function of its spec and can be regenerated
anywhere instead of shipped as fixtures.

Perturbation families (each drawing from its own seeded stream, so adding
one family never shifts another's randomness):

- **arrival jitter** — creation times shift by up to ``±frac * span``,
  clipped at 0; pod ids, tie ranks, and durations are untouched, so the
  reference's equal-time tie-break semantics survive.
- **demand scaling** — cpu/mem scale multiplicatively, clipped to
  ``[1, max real node capacity]`` so every pod still fits SOME empty
  node; gpu_milli scales within ``[1, 1000]`` so the shared waiting
  histogram width (1001) holds across a stacked suite.
- **pod-mix shift** — swap the resource columns (cpu/mem/gpu) between
  random pod pairs, keeping ids and arrival times: the same demand
  distribution arrives in a different temporal order.
- **fault injection** — NODE_DOWN/NODE_UP pairs as precomputable trace
  events (``FaultEvents``): a downed node is cordoned (scores 0 for new
  placements) until its NODE_UP; running pods are never evicted, so both
  engines process faults as pure availability flips (sim/engine.py,
  sim/flat.py) and the jitted step stays a scan.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fks_tpu.data.entities import FaultEvents, Workload
from fks_tpu.ops.heap import KIND_NODE_DOWN, KIND_NODE_UP

INF_I32 = np.iinfo(np.int32).max

# per-family salt: each perturbation family owns an independent stream
_SALT_JITTER = 0x5ce7a710
_SALT_MIX = 0x5ce7a711
_SALT_FAULT = 0x5ce7a712


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """One scenario = a named, seeded bundle of perturbation parameters.
    All-defaults (except the name) is the identity: the base workload."""

    name: str
    seed: int = 0
    arrival_jitter_frac: float = 0.0  # ± fraction of the arrival span
    demand_scale: float = 1.0         # cpu/mem multiplier
    gpu_milli_scale: float = 1.0      # gpu_milli multiplier (clip to 1000)
    pod_mix_swap_frac: float = 0.0    # fraction of pods in resource swaps
    fault_nodes: int = 0              # nodes receiving a DOWN/UP window
    fault_start_frac: float = 0.45    # window start, fraction of the span
    fault_duration_frac: float = 0.15  # window length, fraction of the span

    def describe(self) -> dict:
        """JSON-ready parameter dump (cli scenarios / suite summaries)."""
        return dataclasses.asdict(self)


def _rng(salt: int, seed: int) -> np.random.Generator:
    return np.random.default_rng(np.random.SeedSequence([salt, seed]))


def make_fault_events(events: Sequence[Tuple[int, int, int]],
                      pad_to: Optional[int] = None) -> Optional[FaultEvents]:
    """``FaultEvents`` from ``(time, node, kind)`` triples, padded to
    ``pad_to`` rows (all-masked padding: time INT32_MAX, kind NODE_UP).
    Events are stably time-sorted — array order is the exact engine's
    equal-time fault rank AND the flat engine's argmin tie order, so the
    two engines agree by construction. Returns None when there is nothing
    to pad (no events and no pad_to): a fault-free workload should carry
    ``faults=None`` so it compiles to the pre-scenario program."""
    events = sorted(events, key=lambda e: int(e[0]))
    pad = max(len(events), int(pad_to or 0))
    if pad == 0:
        return None
    time = np.full(pad, INF_I32, np.int32)
    node = np.zeros(pad, np.int32)
    kind = np.full(pad, KIND_NODE_UP, np.int32)
    mask = np.zeros(pad, bool)
    for i, (t, nd, k) in enumerate(events):
        if k not in (KIND_NODE_DOWN, KIND_NODE_UP):
            raise ValueError(f"fault kind {k} is not NODE_DOWN/NODE_UP")
        time[i], node[i], kind[i], mask[i] = int(t), int(nd), int(k), True
    return FaultEvents(time=time, node=node, kind=kind, mask=mask)


def fault_events_for(base: Workload,
                     spec: ScenarioSpec) -> List[Tuple[int, int, int]]:
    """The (time, node, kind) fault triples a spec injects into ``base``:
    ``fault_nodes`` distinct nodes each get one DOWN→UP window inside the
    arrival span, staggered so windows overlap but never coincide."""
    if spec.fault_nodes <= 0:
        return []
    p = base.pods
    pm = np.asarray(p.pod_mask)
    if not pm.any():
        return []
    ct = np.asarray(p.creation_time)[pm]
    t0, t1 = int(ct.min()), int(ct.max())
    span = max(1, t1 - t0)
    nn = base.num_nodes
    k = min(int(spec.fault_nodes), nn)
    rng = _rng(_SALT_FAULT, spec.seed)
    nodes = np.sort(rng.choice(nn, size=k, replace=False))
    events: List[Tuple[int, int, int]] = []
    dur = max(1, int(round(spec.fault_duration_frac * span)))
    for i, nd in enumerate(nodes.tolist()):
        start = t0 + int(round((spec.fault_start_frac + 0.03 * i) * span))
        events.append((start, int(nd), KIND_NODE_DOWN))
        events.append((start + dur, int(nd), KIND_NODE_UP))
    return events


def perturb_workload(base: Workload, spec: ScenarioSpec,
                     fault_pad: Optional[int] = None) -> Workload:
    """Materialize one scenario: ``base`` with ``spec``'s perturbations
    applied and its fault timeline attached (padded to ``fault_pad`` rows
    so every scenario in a suite shares one FaultEvents shape — required
    by ``parallel.traces.stack_traces``). Padded shapes, pod ids, tie
    ranks, and masks are untouched, so a suite stacks under vmap."""
    if base.faults is not None:
        raise ValueError("base workload already carries fault events; "
                         "perturb the fault-free original")
    p = base.pods
    c = base.cluster
    pm = np.asarray(p.pod_mask)
    real = pm
    ct = np.asarray(p.creation_time).astype(np.int64).copy()
    cpu = np.asarray(p.cpu).astype(np.int64).copy()
    mem = np.asarray(p.mem).astype(np.int64).copy()
    num_gpu = np.asarray(p.num_gpu).copy()
    milli = np.asarray(p.gpu_milli).astype(np.int64).copy()

    span = int(ct[real].max() - ct[real].min()) if real.any() else 0
    if spec.arrival_jitter_frac > 0 and span > 0:
        j = max(1, int(round(spec.arrival_jitter_frac * span)))
        jit = _rng(_SALT_JITTER, spec.seed).integers(-j, j + 1, ct.shape[0])
        ct = np.where(real, np.maximum(ct + jit, 0), ct)

    if spec.demand_scale != 1.0:
        nm = np.asarray(c.node_mask)
        cap_cpu = int(np.asarray(c.cpu_total)[nm].max(initial=1))
        cap_mem = int(np.asarray(c.mem_total)[nm].max(initial=1))
        scale = float(spec.demand_scale)
        cpu = np.where(real & (cpu > 0),
                       np.clip(np.round(cpu * scale), 1, cap_cpu), cpu)
        mem = np.where(real & (mem > 0),
                       np.clip(np.round(mem * scale), 1, cap_mem), mem)

    if spec.gpu_milli_scale != 1.0:
        milli = np.where(
            real & (num_gpu > 0),
            np.clip(np.round(milli * float(spec.gpu_milli_scale)), 1, 1000),
            milli)

    if spec.pod_mix_swap_frac > 0:
        idx = np.nonzero(real)[0]
        k = int(len(idx) * min(spec.pod_mix_swap_frac, 1.0)) // 2
        if k > 0:
            order = _rng(_SALT_MIX, spec.seed).permutation(idx)
            a, b = order[:k], order[k:2 * k]
            for arr in (cpu, mem, num_gpu, milli):
                arr[a], arr[b] = arr[b].copy(), arr[a].copy()

    pods = dataclasses.replace(
        p,
        cpu=cpu.astype(np.int32), mem=mem.astype(np.int32),
        num_gpu=np.asarray(num_gpu, np.int32),
        gpu_milli=milli.astype(np.int32),
        creation_time=ct.astype(np.int32))
    wl = Workload(cluster=c, pods=pods)
    faults = make_fault_events(fault_events_for(wl, spec), pad_to=fault_pad)
    return dataclasses.replace(wl, faults=faults)
