"""Synthetic trace generation + shape bucketing for multi-trace batching.

Two scale axes the shipped traces don't cover (SURVEY.md §5 "long-context"
note and BASELINE.json configs 4-5):

- **synthetic workloads** up to 100k pods x 1k nodes, statistically shaped
  like the OpenB default trace (SURVEY.md §2 fine print 11: mostly 1-GPU
  pods, a tail of 2/4/8-GPU jobs, ~13% CPU-only; node park mixing CPU-only
  and 2/4/8-GPU machines of 1000 milli per GPU);
- **shape buckets**: traces of different sizes padded up to shared
  (N, G, P) shapes so one jitted simulator program serves a whole bucket —
  XLA recompiles per shape, so bucketing bounds compile count while padding
  waste stays bounded by the bucket growth factor.

Pure host-side numpy; deterministic per seed.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence, Tuple

import numpy as np

from fks_tpu.data.build import make_workload
from fks_tpu.data.entities import ClusterArrays, PodArrays, Workload

#: Node archetypes: (weight, cpu_milli, memory_mib, gpu_count).
#: Shaped after the default 16-node park (10x 2-GPU, 1x 4-GPU, 5x 8-GPU,
#: reference: benchmarks/traces/csv/gpu_models_filtered.csv) plus the
#: CPU-only machines present in the 1,523-node full park.
_NODE_TYPES = (
    (0.25, 32000, 131072, 0),
    (0.35, 64000, 262144, 2),
    (0.15, 96000, 393216, 4),
    (0.25, 128000, 786432, 8),
)

#: num_gpu distribution for GPU pods (reference default trace:
#: {1: 6989, 2: 16, 4: 15, 8: 44} of 7,064 GPU pods).
_GPU_COUNTS = ((1, 0.9894), (2, 0.0023), (4, 0.0021), (8, 0.0062))


def synthetic_workload(num_nodes: int, num_pods: int, seed: int = 0,
                       horizon: int = 12_900_000,
                       gpu_pod_frac: float = 0.8665,
                       load: float | None = 0.45,
                       pad_to: Tuple[int, int, int] | None = None,
                       nodes: Sequence[dict] | None = None) -> Workload:
    """Generate a cluster + pod stream of the requested size.

    ``horizon`` is the creation-time span (default: the default trace's
    ~12.9M-second span, SURVEY.md §2 fine print 11). ``load`` calibrates
    offered load: durations are rescaled so the binding resource's expected
    concurrent demand is ``load`` x cluster capacity (default 0.45 — the
    default trace's utilization regime, where everything eventually
    schedules; pass None to skip calibration and allow oversubscription,
    which exercises the retry/drop paths instead). ``pad_to`` optionally
    forces (N, G, P) padded shapes (used by bucketing).

    ``nodes`` injects an externally-loaded node park (make_cluster-schema
    dicts, e.g. ``fks_tpu.data.traces.parse_node_yaml()`` — the full
    OpenB node list) in place of the archetype sampler; ``num_nodes``
    then selects a prefix of the list (the synthetic pod stream and the
    load calibration run against the injected park unchanged).
    """
    rng = np.random.default_rng(seed)

    if nodes is not None:
        nodes = list(nodes)
        if num_nodes > len(nodes):
            raise ValueError(
                f"num_nodes {num_nodes} exceeds the injected node list "
                f"({len(nodes)} nodes)")
        nodes = nodes[:num_nodes]
    else:
        weights = np.array([t[0] for t in _NODE_TYPES])
        kinds = rng.choice(len(_NODE_TYPES), size=num_nodes,
                           p=weights / weights.sum())
        nodes = []
        for i, k in enumerate(kinds):
            _, cpu, mem, ng = _NODE_TYPES[k]
            nodes.append({
                "node_id": f"snode-{i:05d}", "cpu_milli": int(cpu),
                "memory_mib": int(mem), "gpus": [1000] * ng,
                "gpu_memory_mib": 16384,
            })

    is_gpu = rng.random(num_pods) < gpu_pod_frac
    counts = np.array([c for c, _ in _GPU_COUNTS])
    probs = np.array([p for _, p in _GPU_COUNTS])
    num_gpu = np.where(
        is_gpu, rng.choice(counts, size=num_pods, p=probs / probs.sum()), 0)
    gpu_milli = np.where(
        is_gpu, rng.choice((100, 250, 500, 1000), size=num_pods,
                           p=(0.2, 0.3, 0.3, 0.2)), 0)
    creation = np.sort(rng.integers(0, horizon, num_pods))
    duration = rng.integers(60, max(61, horizon // 4), num_pods)
    cpu = rng.integers(100, 16000, num_pods)
    mem = rng.integers(128, 65536, num_pods)

    if load is not None:
        # offered load per resource = sum(demand_i * dur_i) / (horizon * cap);
        # rescale durations so the binding resource sits at `load`
        cap = {
            "cpu": sum(n["cpu_milli"] for n in nodes),
            "mem": sum(n["memory_mib"] for n in nodes),
            "gpus": sum(len(n["gpus"]) for n in nodes),
            "milli": sum(sum(n["gpus"]) for n in nodes),
        }
        demand = {
            "cpu": cpu.astype(np.int64), "mem": mem.astype(np.int64),
            "gpus": num_gpu.astype(np.int64),
            "milli": (num_gpu * gpu_milli).astype(np.int64),
        }
        worst = max(
            float(np.sum(demand[k] * duration.astype(np.int64)))
            / (horizon * cap[k])
            for k in cap if cap[k] > 0)
        if worst > 0:
            duration = np.maximum(
                60, (duration * (load / worst)).astype(np.int64))

    pods = [{
        "pod_id": f"spod-{i:06d}", "cpu_milli": int(cpu[i]),
        "memory_mib": int(mem[i]), "num_gpu": int(num_gpu[i]),
        "gpu_milli": int(gpu_milli[i]), "creation_time": int(creation[i]),
        "duration_time": int(duration[i]),
    } for i in range(num_pods)]

    pad = {}
    if pad_to is not None:
        pad = {"pad_nodes_to": pad_to[0], "pad_gpus_to": pad_to[1],
               "pad_pods_to": pad_to[2]}
    return make_workload(nodes, pods, **pad)


# ------------------------------------------------------------- bucketing

def _round_up(x: int, quantum: int) -> int:
    return max(quantum, -(-x // quantum) * quantum)


@dataclasses.dataclass(frozen=True)
class BucketShape:
    """A shared padded shape (one jit compilation per bucket)."""

    n: int  # padded node count
    g: int  # padded per-node GPU count
    p: int  # padded pod count


def bucket_shape(wl: Workload, node_quantum: int = 16,
                 pod_quantum: int = 2048) -> BucketShape:
    """Round a workload's natural shape up to bucket boundaries. GPU width
    rounds to the next power of two (it enters a u32 bitmask, cap 32)."""
    g = 1
    while g < max(1, wl.cluster.g_padded):
        g *= 2
    return BucketShape(
        n=_round_up(wl.cluster.num_nodes or 1, node_quantum),
        g=min(g, 32),
        p=_round_up(wl.num_pods or 1, pod_quantum))


def pad_workload(wl: Workload, shape: BucketShape) -> Workload:
    """Re-pad an existing workload's arrays to a bucket shape (masks keep
    padding out of every decision and denominator)."""
    c, p = wl.cluster, wl.pods
    if shape.n < c.num_nodes or shape.g < c.g_padded \
            or shape.p < p.num_pods:
        raise ValueError(f"bucket {shape} smaller than workload "
                         f"({c.num_nodes}, {c.g_padded}, {p.num_pods})")

    def pad1(a, target):
        a = np.asarray(a)
        out = np.zeros((target,) + a.shape[1:], a.dtype)
        out[: a.shape[0]] = a
        return out

    def pad2(a, tn, tg):
        a = np.asarray(a)
        out = np.zeros((tn, tg), a.dtype)
        out[: a.shape[0], : a.shape[1]] = a
        return out

    cluster = ClusterArrays(
        cpu_total=pad1(c.cpu_total, shape.n), mem_total=pad1(c.mem_total, shape.n),
        gpu_declared=pad1(c.gpu_declared, shape.n),
        num_gpus=pad1(c.num_gpus, shape.n),
        gpu_milli_total=pad2(c.gpu_milli_total, shape.n, shape.g),
        gpu_mem_total=pad2(c.gpu_mem_total, shape.n, shape.g),
        gpu_mask=pad2(c.gpu_mask, shape.n, shape.g),
        node_mask=pad1(c.node_mask, shape.n), node_ids=c.node_ids)
    pods = PodArrays(
        cpu=pad1(p.cpu, shape.p), mem=pad1(p.mem, shape.p),
        num_gpu=pad1(p.num_gpu, shape.p), gpu_milli=pad1(p.gpu_milli, shape.p),
        creation_time=pad1(p.creation_time, shape.p),
        duration=pad1(p.duration, shape.p), tie_rank=pad1(p.tie_rank, shape.p),
        pod_mask=pad1(p.pod_mask, shape.p), pod_ids=p.pod_ids)
    return Workload(cluster=cluster, pods=pods)


def bucket_workloads(workloads: Sequence[Workload],
                     node_quantum: int = 16, pod_quantum: int = 2048,
                     ) -> Dict[BucketShape, List[Workload]]:
    """Group workloads by shared padded shape. Each bucket's members are
    re-padded identically, so one compiled simulator program (per policy)
    serves the whole bucket — the BASELINE.json config-4 multi-trace story."""
    out: Dict[BucketShape, List[Workload]] = {}
    for wl in workloads:
        shape = bucket_shape(wl, node_quantum, pod_quantum)
        out.setdefault(shape, []).append(pad_workload(wl, shape))
    return out
