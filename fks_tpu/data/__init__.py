from fks_tpu.data.entities import ClusterArrays, PodArrays, Workload
from fks_tpu.data.traces import TraceParser, DEFAULT_TRACES_DIR

__all__ = ["ClusterArrays", "PodArrays", "Workload", "TraceParser", "DEFAULT_TRACES_DIR"]
