from fks_tpu.data.entities import ClusterArrays, PodArrays, Workload
from fks_tpu.data.traces import TraceParser, default_traces_dir

__all__ = ["ClusterArrays", "PodArrays", "Workload", "TraceParser", "default_traces_dir"]
