"""Programmatic workload construction (tests, synthetic traces).

Builds the same padded array structures the CSV parser emits, from plain
Python specs. Mirrors what hand-built entity graphs do in the reference's
micro tests (reference: tests/test_simulator.py:40-85).
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from fks_tpu.data.entities import ClusterArrays, PodArrays, Workload


def make_cluster(nodes: Sequence[dict], pad_nodes_to: Optional[int] = None,
                 pad_gpus_to: Optional[int] = None) -> ClusterArrays:
    """nodes: dicts with node_id, cpu_milli, memory_mib, and either
    ``gpus`` (list of per-GPU milli capacities) or ``gpu_count`` +
    ``gpu_milli_capacity``; optional ``gpu_memory_mib``, ``gpu_declared``."""
    n = len(nodes)
    n_pad = pad_nodes_to or max(1, n)
    caps = []
    for spec in nodes:
        if "gpus" in spec:
            caps.append(list(spec["gpus"]))
        else:
            caps.append([spec.get("gpu_milli_capacity", 1000)] * spec.get("gpu_count", 0))
    g_pad = pad_gpus_to or max(1, max((len(c) for c in caps), default=1))

    cpu = np.zeros(n_pad, np.int32)
    mem = np.zeros(n_pad, np.int32)
    declared = np.zeros(n_pad, np.int32)
    num = np.zeros(n_pad, np.int32)
    gmt = np.zeros((n_pad, g_pad), np.int32)
    gmem = np.zeros((n_pad, g_pad), np.int32)
    gmask = np.zeros((n_pad, g_pad), bool)
    nmask = np.zeros(n_pad, bool)
    for i, spec in enumerate(nodes):
        cpu[i] = spec["cpu_milli"]
        mem[i] = spec["memory_mib"]
        k = len(caps[i])
        declared[i] = spec.get("gpu_declared", k)
        num[i] = k
        gmt[i, :k] = caps[i]
        gmem[i, :k] = spec.get("gpu_memory_mib", 0)
        gmask[i, :k] = True
        nmask[i] = True
    return ClusterArrays(
        cpu_total=cpu, mem_total=mem, gpu_declared=declared, num_gpus=num,
        gpu_milli_total=gmt, gpu_mem_total=gmem, gpu_mask=gmask,
        node_mask=nmask, node_ids=tuple(s["node_id"] for s in nodes))


def make_pods(pods: Sequence[dict], pad_pods_to: Optional[int] = None) -> PodArrays:
    """pods: dicts with pod_id, cpu_milli, memory_mib, num_gpu, gpu_milli,
    creation_time, duration_time."""
    p = len(pods)
    p_pad = pad_pods_to or max(1, p)
    arr = {k: np.zeros(p_pad, np.int32) for k in
           ("cpu", "mem", "num_gpu", "gpu_milli", "creation_time", "duration")}
    mask = np.zeros(p_pad, bool)
    ids = [s["pod_id"] for s in pods]
    for i, spec in enumerate(pods):
        arr["cpu"][i] = spec["cpu_milli"]
        arr["mem"][i] = spec["memory_mib"]
        arr["num_gpu"][i] = spec["num_gpu"]
        arr["gpu_milli"][i] = spec["gpu_milli"]
        arr["creation_time"][i] = spec["creation_time"]
        arr["duration"][i] = spec["duration_time"]
        mask[i] = True
    order = sorted(range(p), key=lambda i: ids[i])
    rank = np.zeros(p_pad, np.int32)
    for r, i in enumerate(order):
        rank[i] = r
    return PodArrays(tie_rank=rank, pod_mask=mask, pod_ids=tuple(ids), **arr)


def make_workload(nodes: Sequence[dict], pods: Sequence[dict],
                  **pad) -> Workload:
    return Workload(
        cluster=make_cluster(nodes, pad.get("pad_nodes_to"), pad.get("pad_gpus_to")),
        pods=make_pods(pods, pad.get("pad_pods_to")))
