"""Host-side trace ingest: OpenB/Alibaba CSVs -> padded numpy arrays.

Semantics-compatible redesign of the reference parser
(benchmarks/parser.py:9-122):
- node CSV schema ``sn,cpu_milli,memory_mib,gpu,model`` + gpu_mem_mapping.json
  (model -> MiB); every GPU gets 1000 milli capacity (parser.py:45-46);
  GPUs are only materialized when the model is in the mapping (parser.py:39)
  while ``gpu_left`` still starts at the declared count (parser.py:56).
- pod CSV schema ``name,cpu_milli,memory_mib,num_gpu,gpu_milli,...``;
  ``duration = deletion_time - creation_time`` (parser.py:95); empty
  ``gpu_milli`` -> 0 (parser.py:82).
- Node iteration order == CSV row order (dict insertion order, parser.py:59);
  we keep that order as the node index axis, which preserves the reference's
  argmax tie-breaking.

Differences (deliberate):
- Files may be gzip-compressed (``*.csv.gz``); the shipped dataset is stored
  compressed in-repo.
- Traces missing optional columns (creation/deletion times, gpu_spec -- e.g.
  the multigpu* traces, which the reference parser crashes on) parse with
  defaults of 0.
- Output is numpy struct-of-arrays (see fks_tpu.data.entities), padded to
  caller-chosen sizes.
"""
from __future__ import annotations

import csv
import gzip
import io
import json
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from fks_tpu.data.entities import ClusterArrays, PodArrays, Workload

def default_traces_dir() -> Path:
    """benchmarks/traces next to the package root (source checkout), falling
    back to the current working directory (the dataset is repo data, not
    package data -- an installed wheel must point at a checkout or cwd).
    Resolved at CALL time, so an installed package picks up the caller's
    cwd rather than freezing whatever cwd the first import happened in."""
    checkout = Path(__file__).resolve().parent.parent.parent / "benchmarks" / "traces"
    if checkout.is_dir():
        return checkout
    return Path.cwd() / "benchmarks" / "traces"

GPU_MILLI_CAPACITY = 1000  # per-GPU compute capacity (reference: parser.py:45-46)


def _open_text(path: Path):
    """Open a csv that may exist as plain or .gz."""
    if path.exists():
        return open(path, "r", newline="")
    gz = path.with_name(path.name + ".gz")
    if gz.exists():
        return io.TextIOWrapper(gzip.open(gz, "rb"), newline="")
    raise FileNotFoundError(f"{path} (or {gz})")


def _pad_to(n: int, multiple: int) -> int:
    return max(multiple, ((n + multiple - 1) // multiple) * multiple)


def _parse_cpu_milli(v: str) -> int:
    """k8s CPU quantity -> milli-cores: ``64000m`` or bare cores."""
    v = v.strip().strip("'\"")
    if v.endswith("m"):
        return int(v[:-1])
    return int(float(v) * 1000)


def _parse_memory_mib(v: str) -> int:
    """k8s memory quantity -> MiB: ``262144Mi`` plus the Ki/Gi/Ti scales."""
    v = v.strip().strip("'\"")
    for suffix, scale in (("Mi", 1.0), ("Gi", 1024.0), ("Ti", 1024.0 * 1024),
                          ("Ki", 1.0 / 1024)):
        if v.endswith(suffix):
            return int(float(v[: -len(suffix)]) * scale)
    return int(v)


#: node-YAML keys we lift (allocatable block first -> first-seen wins)
_NODE_YAML_KEYS = {
    "alibabacloud.com/gpu-card-model": "model",
    "kubernetes.io/hostname": "hostname",
    "alibabacloud.com/gpu-count": "gpu_count",
    "alibabacloud.com/gpu-milli": "gpu_milli",
    "cpu": "cpu",
    "memory": "memory",
}


def parse_node_yaml(path: str | Path | None = None,
                    traces_dir: str | Path | None = None) -> List[dict]:
    """The FULL OpenB node park (1,213 nodes) from the vendored k8s node
    manifests at ``benchmarks/traces/node_yaml/`` — the large-cluster
    scale tier's real node list (``cli scale --openb-nodes``,
    ``data.synthetic.synthetic_workload(nodes=...)``).

    Returns node dicts in ``fks_tpu.data.build.make_cluster`` schema
    (``node_id``/``cpu_milli``/``memory_mib``/``gpus``/``gpu_memory_mib``)
    in manifest order, which becomes the node index axis like CSV row
    order does for the csv traces. Per-GPU milli capacity is
    ``gpu-milli / gpu-count`` (1000 for every OpenB node); GPU memory
    comes from the same ``gpu_mem_mapping.json`` the CSV parser uses,
    keyed by the ``gpu-card-model`` label (0 for unmapped models,
    matching ``parse_cluster``'s treatment).

    The manifests are flat two-level YAML, parsed with line scanning so
    the loader needs no yaml dependency; files may be gzipped like the
    CSVs. Paths resolve against ``default_traces_dir()`` — repo-root-
    relative, NOT cwd-relative — so ``cli scale`` works from any cwd
    (the dataset lives at ``benchmarks/traces/node_yaml/``)."""
    base = Path(traces_dir) if traces_dir is not None else default_traces_dir()
    if path is None:
        path = base / "node_yaml" / "openb_node_list_gpu_node.yaml"
    with open(base / "gpu_mem_mapping.json") as f:
        gpu_mem = json.load(f)

    nodes: List[dict] = []

    def flush(rec: Dict[str, str]) -> None:
        if "cpu" not in rec:  # blank separator docs
            return
        count = int(rec.get("gpu_count", "0").strip("'\""))
        milli = int(rec.get("gpu_milli", "0").strip("'\""))
        per_gpu = milli // count if count else 0
        nodes.append({
            "node_id": rec.get("hostname", f"openb-node-{len(nodes):04d}"),
            "cpu_milli": _parse_cpu_milli(rec["cpu"]),
            "memory_mib": _parse_memory_mib(rec["memory"]),
            "gpus": [per_gpu] * count,
            "gpu_memory_mib": int(gpu_mem.get(rec.get("model", ""), 0)),
        })

    rec: Dict[str, str] = {}
    with _open_text(Path(path)) as f:
        for line in f:
            stripped = line.strip()
            if stripped.startswith("---"):
                flush(rec)
                rec = {}
                continue
            key, sep, value = stripped.partition(":")
            if not sep:
                continue
            name = _NODE_YAML_KEYS.get(key.strip())
            # first-seen wins: the allocatable block precedes capacity
            if name is not None and value.strip() and name not in rec:
                rec[name] = value.strip()
    flush(rec)
    return nodes


class TraceParser:
    """Parse OpenB dataset traces into array-based simulation inputs.

    API mirrors the reference ``TraceParser`` (benchmarks/parser.py:9-122):
    ``parse_cluster`` / ``parse_pods`` / ``parse_workload`` plus the file
    discovery helpers.
    """

    def __init__(self, traces_dir: str | Path | None = None):
        self.traces_dir = Path(traces_dir) if traces_dir is not None \
            else default_traces_dir()
        self.csv_dir = self.traces_dir / "csv"
        self.gpu_mem_mapping = self._load_gpu_memory_mapping()

    def _load_gpu_memory_mapping(self) -> Dict[str, int]:
        with open(self.traces_dir / "gpu_mem_mapping.json") as f:
            return json.load(f)

    # ---------------------------------------------------------------- nodes
    def parse_cluster(self, node_file: str = "openb_node_list_gpu_node.csv",
                      pad_nodes_to: Optional[int] = None,
                      pad_gpus_to: Optional[int] = None) -> ClusterArrays:
        rows = self._read_csv(self.csv_dir / node_file)
        node_ids: List[str] = []
        cpu, mem, declared, materialized, gpu_mem = [], [], [], [], []
        for row in rows:
            node_ids.append(row["sn"])
            cpu.append(int(row["cpu_milli"]))
            mem.append(int(row["memory_mib"]))
            gcount = int(row["gpu"])
            model = row.get("model", "")
            declared.append(gcount)
            if gcount > 0 and model in self.gpu_mem_mapping:
                materialized.append(gcount)
                gpu_mem.append(self.gpu_mem_mapping[model])
            else:
                materialized.append(0)
                gpu_mem.append(0)

        n = len(node_ids)
        g_needed = max(materialized, default=0)
        n_pad = pad_nodes_to or _pad_to(n, 8)
        g_pad = pad_gpus_to or max(1, g_needed)
        if n_pad < n or g_pad < g_needed:
            raise ValueError(f"padding too small: nodes {n}>{n_pad} or gpus {g_needed}>{g_pad}")

        def vec(xs, dtype=np.int32):
            out = np.zeros(n_pad, dtype=dtype)
            out[:n] = xs
            return out

        gpu_mask = np.zeros((n_pad, g_pad), dtype=bool)
        gpu_milli_total = np.zeros((n_pad, g_pad), dtype=np.int32)
        gpu_mem_total = np.zeros((n_pad, g_pad), dtype=np.int32)
        for i in range(n):
            k = materialized[i]
            gpu_mask[i, :k] = True
            gpu_milli_total[i, :k] = GPU_MILLI_CAPACITY
            gpu_mem_total[i, :k] = gpu_mem[i]

        node_mask = np.zeros(n_pad, dtype=bool)
        node_mask[:n] = True

        return ClusterArrays(
            cpu_total=vec(cpu),
            mem_total=vec(mem),
            gpu_declared=vec(declared),
            num_gpus=vec(materialized),
            gpu_milli_total=gpu_milli_total,
            gpu_mem_total=gpu_mem_total,
            gpu_mask=gpu_mask,
            node_mask=node_mask,
            node_ids=tuple(node_ids),
        )

    # ----------------------------------------------------------------- pods
    def parse_pods(self, pod_file: str = "openb_pod_list_default.csv",
                   pad_pods_to: Optional[int] = None) -> PodArrays:
        rows = self._read_csv(self.csv_dir / pod_file)
        ids, cpu, mem, ngpu, gmilli, ctime, dur = [], [], [], [], [], [], []
        for row in rows:
            ids.append(row["name"])
            cpu.append(int(row["cpu_milli"]))
            mem.append(int(row["memory_mib"]))
            ngpu.append(int(row["num_gpu"]))
            gmilli.append(int(row["gpu_milli"]) if row.get("gpu_milli") else 0)
            creation = int(row.get("creation_time") or 0)
            deletion = int(row.get("deletion_time") or 0)
            ctime.append(creation)
            dur.append(deletion - creation)

        p = len(ids)
        p_pad = pad_pods_to or _pad_to(p, 128)
        if p_pad < p:
            raise ValueError(f"padding too small: pods {p}>{p_pad}")

        def vec(xs):
            out = np.zeros(p_pad, dtype=np.int32)
            out[:p] = xs
            return out

        # Rank of pod_id in lexicographic order reproduces the reference's
        # string tie-break (event_simulator.py:16-17) as integer compares.
        order = sorted(range(p), key=lambda i: ids[i])
        rank = np.zeros(p_pad, dtype=np.int32)
        for r, i in enumerate(order):
            rank[i] = r

        pod_mask = np.zeros(p_pad, dtype=bool)
        pod_mask[:p] = True

        return PodArrays(
            cpu=vec(cpu), mem=vec(mem), num_gpu=vec(ngpu), gpu_milli=vec(gmilli),
            creation_time=vec(ctime), duration=vec(dur), tie_rank=rank,
            pod_mask=pod_mask, pod_ids=tuple(ids),
        )

    # ------------------------------------------------------------- combined
    def parse_workload(self, node_file: str = "gpu_models_filtered.csv",
                       pod_file: str = "openb_pod_list_default.csv",
                       pad_nodes_to: Optional[int] = None,
                       pad_gpus_to: Optional[int] = None,
                       pad_pods_to: Optional[int] = None) -> Workload:
        """Defaults match the reference benchmark workload (parser.py:117-118)."""
        cluster = self.parse_cluster(node_file, pad_nodes_to, pad_gpus_to)
        pods = self.parse_pods(pod_file, pad_pods_to)
        return Workload(cluster=cluster, pods=pods)

    # ------------------------------------------------------------ discovery
    def get_available_node_files(self) -> List[str]:
        return sorted({f.name.removesuffix(".gz")
                       for f in self.csv_dir.glob("openb_node_list_*.csv*")})

    def get_available_pod_files(self) -> List[str]:
        return sorted({f.name.removesuffix(".gz")
                       for f in self.csv_dir.glob("openb_pod_list_*.csv*")})

    @staticmethod
    def _read_csv(path: Path) -> List[dict]:
        with _open_text(path) as f:
            return list(csv.DictReader(f))
