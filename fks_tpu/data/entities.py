"""Struct-of-arrays data model for cluster + workload state.

TPU-first redesign of the reference's mutable Python dataclasses
(reference: simulator/entities.py:4-43 -- GPU/Node/Cluster/Pod). Instead of
object graphs we keep padded, fixed-shape integer arrays so the whole
simulation state is a pytree that lives on device and flows through
``lax.while_loop`` / ``vmap`` / ``shard_map``.

Conventions:
- Node axis ``N`` (padded), per-node GPU axis ``G`` (padded), pod axis ``P``
  (padded). Padding is masked via ``node_mask`` / ``gpu_mask`` / ``pod_mask``
  and never contributes to placement decisions or utilization denominators.
- All resource quantities are int32 (the reference uses exact Python ints;
  int32 covers every shipped trace: cpu_milli <= 128000, memory_mib <= 786432,
  gpu_milli <= 1000, times < 2**31).
"""
from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np


def _pytree_dataclass(cls):
    """Register a dataclass as a JAX pytree (all array fields are leaves)."""
    cls = dataclasses.dataclass(cls)
    fields = [f.name for f in dataclasses.fields(cls) if not f.metadata.get("static")]
    static = [f.name for f in dataclasses.fields(cls) if f.metadata.get("static")]
    jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=static)
    return cls


def static_field(**kwargs):
    return dataclasses.field(metadata={"static": True}, **kwargs)


@_pytree_dataclass
class ClusterArrays:
    """Initial cluster state as arrays.

    Mirrors the information content of reference ``Node``/``GPU``/``Cluster``
    (simulator/entities.py:4-26): per-node CPU/memory/GPU-count capacity and
    per-GPU compute (milli) + memory capacity.

    ``gpu_left`` can legitimately exceed ``num_gpus``: the reference parser
    (benchmarks/parser.py:39,56) sets ``gpu_left`` from the declared CSV count
    but only materializes GPU objects when the GPU model is in the memory
    mapping; we preserve that asymmetry.
    """

    cpu_total: Any  # i32[N]
    mem_total: Any  # i32[N]
    gpu_declared: Any  # i32[N] declared GPU count (initial gpu_left)
    num_gpus: Any  # i32[N] number of materialized GPUs (len(node.gpus))
    gpu_milli_total: Any  # i32[N, G] per-GPU compute capacity (0 where padded)
    gpu_mem_total: Any  # i32[N, G] per-GPU memory MiB (0 where padded)
    gpu_mask: Any  # bool[N, G] which GPU slots exist
    node_mask: Any  # bool[N] which node slots are real
    node_ids: tuple = static_field(default=())  # host-side node names, real nodes only

    @property
    def n_padded(self) -> int:
        return int(self.cpu_total.shape[0])

    @property
    def g_padded(self) -> int:
        return int(self.gpu_milli_total.shape[1])

    @property
    def num_nodes(self) -> int:
        return len(self.node_ids)

    def totals(self) -> dict:
        """Cluster-wide capacity totals (reference: evaluator.py:35-38)."""
        return {
            "cpu": int(np.sum(np.asarray(self.cpu_total))),
            "memory": int(np.sum(np.asarray(self.mem_total))),
            "gpu_count": int(np.sum(np.asarray(self.num_gpus))),
            "gpu_milli": int(np.sum(np.asarray(self.gpu_milli_total))),
        }


@_pytree_dataclass
class PodArrays:
    """Workload (pod requests) as time-ordered-by-input arrays.

    Mirrors reference ``Pod`` (simulator/entities.py:29-43). ``tie_rank`` is
    the rank of the pod id in lexicographic string order -- the reference
    breaks equal-time event ordering by ``pod_id`` string comparison
    (event_simulator.py:16-17); ranks reproduce that exactly without strings
    on device.
    """

    cpu: Any  # i32[P]
    mem: Any  # i32[P]
    num_gpu: Any  # i32[P]
    gpu_milli: Any  # i32[P]
    creation_time: Any  # i32[P]
    duration: Any  # i32[P]
    tie_rank: Any  # i32[P]
    pod_mask: Any  # bool[P]
    pod_ids: tuple = static_field(default=())  # host-side pod names, real pods only

    @property
    def p_padded(self) -> int:
        return int(self.cpu.shape[0])

    @property
    def num_pods(self) -> int:
        return len(self.pod_ids)


@_pytree_dataclass
class FaultEvents:
    """Precomputed node fault timeline (fks_tpu.scenarios generator).

    One row per NODE_DOWN / NODE_UP event, padded to a fixed length ``F``
    and masked like every other axis. Faults are *trace events*: both
    engines merge them into the event stream ahead of equal-time pod
    events and flip a per-node availability bit (cordon — a downed node
    scores 0 for new placements; running pods are not evicted), so the
    jitted step stays a pure scan.
    """

    time: Any  # i32[F] event times (padding: INT32_MAX)
    node: Any  # i32[F] node index the event applies to (padding: 0)
    kind: Any  # i32[F] KIND_NODE_DOWN | KIND_NODE_UP (ops.heap vocabulary)
    mask: Any  # bool[F] which rows are real

    @property
    def f_padded(self) -> int:
        return int(self.time.shape[0])

    @property
    def num_events(self) -> int:
        return int(np.sum(np.asarray(self.mask)))


@_pytree_dataclass
class Workload:
    """A parsed (cluster, pods) pair -- unit of simulation input.

    ``faults`` is None for plain workloads (zero pytree leaves — fault-free
    programs compile unchanged) or a ``FaultEvents`` timeline for
    scenario-generated variants.
    """

    cluster: ClusterArrays
    pods: PodArrays
    faults: Any = None

    @property
    def num_nodes(self) -> int:
        return self.cluster.num_nodes

    @property
    def num_pods(self) -> int:
        return self.pods.num_pods
