"""Run the FULL test suite (fast + slow tiers) and append one evidence row
to benchmarks/results/full_suite.jsonl — the per-round CI stand-in the
README's "CI story for the slow tier" section points at. One row per run:
pass/fail/deselected counts, wall time, git revision.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "results", "full_suite.jsonl")


def main() -> int:
    rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                         capture_output=True, text=True, cwd=REPO
                         ).stdout.strip()
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q",
         "-m", "slow or not slow"],
        capture_output=True, text=True, cwd=REPO)
    wall = round(time.time() - t0, 1)
    tail = (proc.stdout or "").strip().splitlines()[-1:]
    summary = tail[0] if tail else ""
    counts = {k: int(v) for v, k in re.findall(
        r"(\d+) (passed|failed|error|skipped|deselected|xfailed)", summary)}
    row = {"ts": round(time.time(), 1), "rev": rev, "rc": proc.returncode,
           "wall_s": wall, **counts, "summary": summary}
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row))
    sys.stderr.write((proc.stdout or "")[-2000:])
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
