"""Run the FULL test suite (fast + slow tiers) and append one evidence row
to benchmarks/results/full_suite.jsonl — the per-round CI stand-in the
README's "CI story for the slow tier" section points at. One row per run:
pass/fail/deselected counts, wall time, git revision.

Before pytest, an OBSERVABILITY GATE runs against the golden run-dir
fixture (tests/fixtures/golden_run): the JSONL schema checker must pass
it, and ``cli compare`` of the fixture against itself must exit 0 — the
two tools CI leans on must agree that a known-good run dir is good
before their verdicts on real runs mean anything. A gate failure is
recorded in the evidence row (``obs_gate``) and fails the suite run.

A TRACE GATE follows: ``cli trace-diff`` of the exact engine against
itself on the default trace must report zero divergence (exit 0). The
decision-trace instrument comparing an engine to itself and finding a
difference means the trace capture or alignment is broken — its verdicts
on real engine pairs would be noise. Recorded as ``trace_gate``.

A SCALE GATE follows: a small ``cli scale`` run with the scale-tier
knobs on (top-k node prefiltering + packed state dtypes, flat engine)
must complete and exit 0 — the cheap end-to-end check that the
large-cluster path stays wired before the slow-marked 1k-node smoke
test (tests/test_scale_tier.py) pays for the real shape. Recorded as
``scale_gate``.

A SERVE GATE follows: ``cli serve --selftest`` — batched warm-path
answers for queries sliced from the golden trace must match the
unbatched exact engine (score drift <= 1e-5, placements identical,
exit 0). A drift here means the serving tier's lane stacking or
scatter-back is corrupting answers. Recorded as ``serve_gate``.

A SHARDED SERVE GATE follows: the same selftest on an 8-virtual-device
dryrun mesh (``cli serve --cpu --devices 8 --state-pack --selftest``) —
mesh-sharded, 16-bit-packed batched answers must still match the exact
engine with 0.0 drift and identical placements. A drift here means the
batch-axis pad/shard specs, the device-resident snapshot cache, or the
pack/unpack pair is corrupting answers. Recorded as
``sharded_serve_gate``.

A LINT GATE follows: ``cli lint --cpu`` — the repo-wide JAX-invariant
AST lints must be clean AND the pinned-jaxpr manifest
(tests/fixtures/jaxpr_pins.json) must match the currently lowered
programs (exit 0). Pin drift means a key entry point compiles a
different program than the one the evidence was gathered on — re-pin
with ``cli lint --write-pins`` only when the change is intentional.
Recorded as ``lint_gate``.

A TRENDS GATE follows: ``cli trends`` over two synthetic bench-result
histories written to a temp dir — a 10-run series with an injected 30%
throughput drop must raise EXACTLY one alert (exit 1 under
``--fail-on-alert``), and the same series without the drop must raise
none (exit 0). A miss either way means the robust-z change-point pass
is broken — its alerts on the real archive would be noise or silence.
Recorded as ``trends_gate``. Pure-host (no jax import needed).

A SPAN TRACE GATE follows: a recorded ``cli serve --selftest`` run must
yield a COMPLETE causal waterfall (queue_wait / batch_wait / pack_h2d /
dispatch / scatter_back under one root) for 100% of its served requests
(``cli spans <dir> --check-complete``), and a recorded 1-generation
fake-LLM evolve must attribute >= 95% of the generation wall to traced
stages (``cli spans <dir> --critical-path --min-fraction 0.95``). A
failure means the trace-context propagation across the batcher / evolve
threads tore somewhere — per-request waterfalls and critical-path
attribution would silently lie. Recorded as ``span_trace_gate``.

A RESILIENCE GATE follows: the deterministic resilience drills
(deadline storm, queue overload, device loss mid-batch,
degrade-then-recover, SIGTERM drain, WAL resume mid-generation) from
``fks_tpu/resilience/drills.py`` must all pass via
``cli pipeline --drill --only <resilience drills>`` (exit 0). A failure
means the shed/degrade/drain/WAL machinery the serve and evolve loops
lean on under faults no longer holds its invariants. Recorded as
``resilience_gate``.

A VM SERVE GATE follows: the champion-as-data serving path —
``cli serve --serve-engine vm --selftest`` must answer with exact
parity against the unbatched reference (exit 0), and the double
hot-swap drill (``cli pipeline --drill --only vm_double_swap``) must
promote TWICE through the live controller with zero XLA compiles on
the serving process. A failure means the VM engine's program tables,
the shared executables, or the zero-rebuild swap path regressed to
recompiling. Recorded as ``vm_serve_gate``.

A MEMORY GATE follows: the deterministic memory drills
(fks_tpu.obs.memory) on an 8-virtual-device dryrun mesh —
``cli mem --cpu --devices 8 --drill vm_swap_leak`` must show ZERO net
``jax.live_arrays()`` growth across 50 swap_program promotions
interleaved with 200 served batches (every swap frees the displaced
program tables, every batch's buffers are donated or cache-hits), and
``--drill snapshot_cache_bound`` must show the device snapshot cache
holding a byte ceiling (evicts under pressure, never exceeds the cap,
still re-hits recent entries). A failure means the serving tier is
accreting device memory per promotion or the cache bound broke — the
exact leak class that kills a long-lived serving process. Recorded as
``memory_gate``.

A LOADGEN GATE follows: a short deterministic two-tenant closed-loop
run through the concurrent HTTP front (``bench.py --stage loadgen``)
with per-tenant accounting on — shed rate must stay bounded, the Jain
fairness index over tenant goodput must stay >= 0.8, and the steady
state must serve with ZERO recompiles. A failure means the tenant
accounting, the threaded HTTP front, or the warm serving path
regressed under overlapping clients. Recorded as ``loadgen_gate``.

A PORTFOLIO GATE follows: multi-tenant champion-portfolio serving —
``cli portfolio --cpu --devices 8 --selftest 4`` builds four resident
champions into ONE slot-vmapped VM executable on the 8-device dryrun
mesh, and must show every slot's answers matching a single-champion VM
engine (score drift <= 1e-5, placements identical), a mixed-slot batch
matching the per-slot answers, and one slot promoted mid-traffic with
ZERO XLA compiles. A failure means the slot-gather dispatch, the
replicated slot-table sharding, or the swap-under-traffic lock
regressed. Recorded as ``portfolio_gate``.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "benchmarks", "results", "full_suite.jsonl")
GOLDEN = os.path.join(REPO, "tests", "fixtures", "golden_run")


def obs_gate() -> dict:
    """Schema-check the golden run dir and self-compare it (exit 0
    expected). Returns {"ok": bool, "detail": ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    schema = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_jsonl_schema.py"),
         "--run-dir", GOLDEN],
        capture_output=True, text=True, cwd=REPO)
    compare = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "compare", GOLDEN, GOLDEN],
        capture_output=True, text=True, cwd=REPO, env=env)
    ok = schema.returncode == 0 and compare.returncode == 0
    detail = {"schema_rc": schema.returncode, "compare_rc": compare.returncode}
    if not ok:
        detail["schema_err"] = (schema.stderr or "")[-500:]
        detail["compare_err"] = (compare.stderr or compare.stdout or "")[-500:]
    return {"ok": ok, **detail}


def trace_gate() -> dict:
    """Trace-diff self-consistency: exact-vs-exact on the default trace
    must exit 0 (no divergence). Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "trace-diff", "--cpu",
         "--engines", "exact,exact", "--policy", "first_fit",
         "--max-steps", "256"],
        capture_output=True, text=True, cwd=REPO, env=env)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def scale_gate() -> dict:
    """Scale-tier smoke: a small ``cli scale`` run with prefiltering and
    packed state dtypes must complete (exit 0). Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "scale", "--cpu",
         "--nodes", "64", "--pods", "512", "--pop", "2",
         "--prefilter-k", "8", "--state-pack", "--engine", "flat"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def serve_gate() -> dict:
    """Serving parity: the champion-serving selftest (batched warm-path
    answers vs the unbatched exact engine, golden-trace queries) must
    exit 0. Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "serve", "--cpu",
         "--selftest", "4", "--pods-per-query", "3",
         "--max-pods", "16", "--max-batch", "4"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def sharded_serve_gate() -> dict:
    """Sharded-serving parity: the same selftest on an 8-virtual-device
    dryrun mesh with 16-bit packed uploads — batched mesh-sharded answers
    must match the unbatched exact engine with 0.0 drift and identical
    placements. Exercises the whole round-17 path: pad/shard specs,
    device-resident snapshot cache, packed H2D. Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "serve", "--cpu",
         "--devices", "8", "--state-pack",
         "--selftest", "4", "--pods-per-query", "3",
         "--max-pods", "16", "--max-batch", "4"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def lint_gate() -> dict:
    """Repo lint + jaxpr-pin drift: ``cli lint --cpu`` must exit 0
    (clean findings, no pin drift). Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "lint", "--cpu"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def promote_gate() -> dict:
    """Promotion-drill matrix: every deterministic fault-injection drill
    (corrupt champion, device-eval error, p99 regression, kill -9 per
    state, rollback on burn, zero-recompile swap, llm outage) must pass
    — ``cli pipeline --drill`` exits 0. Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "pipeline", "--cpu",
         "--drill"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=900)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def span_trace_gate() -> dict:
    """Causal-trace completeness: a recorded serve selftest must produce
    a complete waterfall for every served request, and a recorded 1-gen
    fake-LLM evolve must attribute >= 95% of the generation wall to
    traced stages. Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    detail = {}
    ok = True
    with tempfile.TemporaryDirectory() as tmp:
        serve_dir = os.path.join(tmp, "serve")
        evolve_dir = os.path.join(tmp, "evolve")
        steps = (
            ("serve", [sys.executable, "-m", "fks_tpu.cli", "serve",
                       "--cpu", "--selftest", "4", "--pods-per-query", "3",
                       "--max-pods", "16", "--max-batch", "4",
                       "--run-dir", serve_dir]),
            ("serve_waterfalls", [sys.executable, "-m", "fks_tpu.cli",
                                  "spans", serve_dir, "--check-complete"]),
            ("evolve", [sys.executable, "-m", "fks_tpu.cli", "evolve",
                        "--cpu", "--fake-llm", "--generations", "1",
                        "--run-dir", evolve_dir]),
            ("critical_path", [sys.executable, "-m", "fks_tpu.cli",
                               "spans", evolve_dir, "--critical-path",
                               "--min-fraction", "0.95"]),
        )
        for name, cmd in steps:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  cwd=REPO, env=env, timeout=900)
            detail[f"{name}_rc"] = proc.returncode
            if proc.returncode != 0:
                ok = False
                detail[f"{name}_err"] = (proc.stderr
                                         or proc.stdout or "")[-500:]
                break
    return {"ok": ok, **detail}


def resilience_gate() -> dict:
    """Resilience-drill matrix: the deterministic failure drills from
    fks_tpu/resilience/drills.py (deadline storm, queue overload, device
    loss mid-batch, degrade-then-recover, SIGTERM drain, WAL resume) must
    pass — ``cli pipeline --drill --only <resilience>`` exits 0.
    Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    only = ("deadline_storm,queue_overload,device_loss,degrade,"
            "sigterm,wal_resume")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "pipeline", "--cpu",
         "--drill", "--only", only],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=900)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def vm_serve_gate() -> dict:
    """VM-native serving: the champion-as-data selftest (engine_kind
    "vm", exact parity vs the unbatched reference) must exit 0, and the
    double hot-swap drill must perform two in-place promotions with
    ZERO XLA compiles (``pipeline --drill --only vm_double_swap``).
    Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    detail = {}
    ok = True
    steps = (
        ("selftest", [sys.executable, "-m", "fks_tpu.cli", "serve",
                      "--cpu", "--serve-engine", "vm",
                      "--selftest", "4", "--pods-per-query", "3",
                      "--max-pods", "16", "--max-batch", "4"]),
        ("double_swap", [sys.executable, "-m", "fks_tpu.cli", "pipeline",
                         "--cpu", "--drill", "--only", "vm_double_swap"]),
    )
    for name, cmd in steps:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              cwd=REPO, env=env, timeout=900)
        detail[f"{name}_rc"] = proc.returncode
        if proc.returncode != 0:
            ok = False
            detail[f"{name}_err"] = (proc.stderr
                                     or proc.stdout or "")[-500:]
            break
    return {"ok": ok, **detail}


def memory_gate() -> dict:
    """Memory drills: ``cli mem --drill vm_swap_leak`` on an 8-device
    dryrun mesh must show zero net ``jax.live_arrays()`` growth across
    repeated swap+serve cycles, and ``--drill snapshot_cache_bound``
    must show the snapshot cache evicting under a byte cap while still
    re-hitting recent entries. Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    detail = {}
    ok = True
    steps = (
        ("vm_swap_leak", [sys.executable, "-m", "fks_tpu.cli", "mem",
                          "--cpu", "--devices", "8",
                          "--drill", "vm_swap_leak"]),
        ("snapshot_cache_bound", [sys.executable, "-m", "fks_tpu.cli",
                                  "mem", "--cpu",
                                  "--drill", "snapshot_cache_bound"]),
    )
    for name, cmd in steps:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              cwd=REPO, env=env, timeout=900)
        detail[f"{name}_rc"] = proc.returncode
        if proc.returncode != 0:
            ok = False
            detail[f"{name}_err"] = (proc.stderr
                                     or proc.stdout or "")[-500:]
            break
    return {"ok": ok, **detail}


def loadgen_gate() -> dict:
    """Multi-tenant load generation: a short deterministic two-tenant
    closed-loop run through the concurrent HTTP front
    (``bench.py --stage loadgen``) must complete with a bounded shed
    rate, a Jain fairness index at or above threshold, and ZERO
    steady-state recompiles. A failure means the tenant accounting,
    the concurrent front, or the warm serving path regressed under
    overlapping clients. Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               FKS_BENCH_LOADGEN_S="2",
               FKS_BENCH_LOADGEN_TENANTS="a:closed:2,b:closed:2",
               FKS_BENCH_LOADGEN_FAIRNESS_MIN="0.8")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--stage", "loadgen"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=900)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def portfolio_gate() -> dict:
    """Portfolio serving: ``cli portfolio --selftest`` on the 8-device
    dryrun mesh — four resident champions in one slot-vmapped VM
    executable, per-slot + mixed-batch parity vs single-champion VM
    engines (<= 1e-5), then one slot promoted mid-traffic with zero XLA
    compiles. Returns {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "fks_tpu.cli", "portfolio", "--cpu",
         "--devices", "8", "--selftest", "4"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=900)
    ok = proc.returncode == 0
    detail = {"rc": proc.returncode}
    try:
        summary = json.loads(proc.stdout)
        detail["max_drift"] = summary.get("max_drift")
        detail["mixed_max_drift"] = summary.get("mixed_max_drift")
        detail["swap_compiles"] = summary.get("swap", {}).get("compiles")
        detail["n_slots"] = summary.get("n_slots")
    except json.JSONDecodeError:
        ok = False
    if not ok:
        detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
    return {"ok": ok, **detail}


def layout_gate() -> dict:
    """Layout observability: ``cli layout --explore`` on the 8-device
    dryrun mesh must find >= 2 distinct valid layouts of pop-16 x
    suite-8 with every layout's robust scores parity-equal to the
    default (<= 1e-5), and the pinned default-spec jaxpr must be
    unchanged (``cli lint``'s sharded_eval/default_layout pin, checked
    by the lint gate). The explore run itself must NOT fail on
    dominance — the dryrun mesh time-slices one host, so the default
    being beaten there is expected and informational; the gate asserts
    the measurement machinery, not a schedule. Returns
    {"ok": bool, ...}."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    detail = {}
    ok = True
    with tempfile.TemporaryDirectory() as tmp:
        proc = subprocess.run(
            [sys.executable, "-m", "fks_tpu.cli", "layout", "--explore",
             "--cpu", "--devices", "8", "--pop", "16",
             "--suite", "default8", "--history-root", tmp],
            capture_output=True, text=True, cwd=REPO, env=env,
            timeout=900)
        # rc 1 is the dominance verdict, not a machinery failure
        detail["rc"] = proc.returncode
        if proc.returncode not in (0, 1):
            detail["err"] = (proc.stderr or proc.stdout or "")[-500:]
            return {"ok": False, **detail}
        try:
            summary = json.loads(proc.stdout)
        except json.JSONDecodeError:
            detail["err"] = (proc.stdout or "")[-500:]
            return {"ok": False, **detail}
        detail["layouts_probed"] = summary.get("layouts_probed", 0)
        detail["parity_max_abs"] = summary.get("parity_max_abs")
        detail["best_mesh_shape"] = summary.get("best_mesh_shape")
        if summary.get("layouts_probed", 0) < 2:
            ok = False
            detail["err"] = "fewer than 2 distinct valid layouts probed"
        if float(summary.get("parity_max_abs", 1.0)) > 1e-5:
            ok = False
            detail["err"] = (f"layout parity {summary.get('parity_max_abs')}"
                             " > 1e-5")
        prior = os.path.join(tmp, "layouts.json")
        detail["prior_written"] = os.path.exists(prior)
        ok = ok and detail["prior_written"]
    return {"ok": ok, **detail}


def _write_history(root: str, values) -> None:
    now = time.time()
    for i, v in enumerate(values):
        p = os.path.join(root, f"BENCH_r{i:02d}.json")
        with open(p, "w") as f:
            json.dump({"metric": "evals/s", "value": v, "unit": "evals/s",
                       "vs_baseline": round(v / 40.0, 3)}, f)
        ts = now - (len(values) - i) * 3600
        os.utime(p, (ts, ts))


def trends_gate() -> dict:
    """Regression-flagging self-test: an injected 30% drop in a synthetic
    10-run history must alert (rc 1 with --fail-on-alert, exactly one
    alert); the clean series must not (rc 0). Returns {"ok": bool, ...}."""
    clean = [100.0, 101.5, 99.2, 100.8, 98.9, 101.1, 99.7, 100.4, 99.9,
             100.6]
    regressed = clean[:7] + [70.0, 69.5, 70.3]
    detail = {}
    ok = True
    with tempfile.TemporaryDirectory() as tmp:
        for name, series, want_rc in (("clean", clean, 0),
                                      ("regressed", regressed, 1)):
            root = os.path.join(tmp, name)
            os.makedirs(root)
            _write_history(root, series)
            proc = subprocess.run(
                [sys.executable, "-m", "fks_tpu.cli", "trends", root,
                 "--metric", "evals_per_sec", "--fail-on-alert"],
                capture_output=True, text=True, cwd=REPO, timeout=300)
            detail[f"{name}_rc"] = proc.returncode
            if proc.returncode != want_rc:
                ok = False
                detail[f"{name}_err"] = (proc.stderr
                                         or proc.stdout or "")[-500:]
            if name == "regressed":
                n = (proc.stdout or "").count("ALERT")
                detail["alerts"] = n
                ok = ok and n == 1
    return {"ok": ok, **detail}


def main() -> int:
    rev = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                         capture_output=True, text=True, cwd=REPO
                         ).stdout.strip()
    gate = obs_gate()
    if not gate["ok"]:
        print(f"OBS GATE FAILED: {gate}", file=sys.stderr)
    tgate = trace_gate()
    if not tgate["ok"]:
        print(f"TRACE GATE FAILED: {tgate}", file=sys.stderr)
    sgate = scale_gate()
    if not sgate["ok"]:
        print(f"SCALE GATE FAILED: {sgate}", file=sys.stderr)
    vgate = serve_gate()
    if not vgate["ok"]:
        print(f"SERVE GATE FAILED: {vgate}", file=sys.stderr)
    hgate = sharded_serve_gate()
    if not hgate["ok"]:
        print(f"SHARDED SERVE GATE FAILED: {hgate}", file=sys.stderr)
    lgate = lint_gate()
    if not lgate["ok"]:
        print(f"LINT GATE FAILED: {lgate}", file=sys.stderr)
    ngate = trends_gate()
    if not ngate["ok"]:
        print(f"TRENDS GATE FAILED: {ngate}", file=sys.stderr)
    pgate = promote_gate()
    if not pgate["ok"]:
        print(f"PROMOTE GATE FAILED: {pgate}", file=sys.stderr)
    rgate = resilience_gate()
    if not rgate["ok"]:
        print(f"RESILIENCE GATE FAILED: {rgate}", file=sys.stderr)
    mgate = vm_serve_gate()
    if not mgate["ok"]:
        print(f"VM SERVE GATE FAILED: {mgate}", file=sys.stderr)
    wgate = span_trace_gate()
    if not wgate["ok"]:
        print(f"SPAN TRACE GATE FAILED: {wgate}", file=sys.stderr)
    ygate = memory_gate()
    if not ygate["ok"]:
        print(f"MEMORY GATE FAILED: {ygate}", file=sys.stderr)
    dgate = loadgen_gate()
    if not dgate["ok"]:
        print(f"LOADGEN GATE FAILED: {dgate}", file=sys.stderr)
    fgate = portfolio_gate()
    if not fgate["ok"]:
        print(f"PORTFOLIO GATE FAILED: {fgate}", file=sys.stderr)
    ogate = layout_gate()
    if not ogate["ok"]:
        print(f"LAYOUT GATE FAILED: {ogate}", file=sys.stderr)
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q",
         "-m", "slow or not slow"],
        capture_output=True, text=True, cwd=REPO)
    wall = round(time.time() - t0, 1)
    tail = (proc.stdout or "").strip().splitlines()[-1:]
    summary = tail[0] if tail else ""
    counts = {k: int(v) for v, k in re.findall(
        r"(\d+) (passed|failed|error|skipped|deselected|xfailed)", summary)}
    gates_ok = (gate["ok"] and tgate["ok"] and sgate["ok"] and vgate["ok"]
                and hgate["ok"] and lgate["ok"] and ngate["ok"]
                and pgate["ok"] and rgate["ok"] and wgate["ok"]
                and mgate["ok"] and ygate["ok"] and dgate["ok"]
                and fgate["ok"] and ogate["ok"])
    rc = proc.returncode if gates_ok else (proc.returncode or 1)
    row = {"ts": round(time.time(), 1), "rev": rev, "rc": rc,
           "wall_s": wall, **counts, "obs_gate": gate,
           "trace_gate": tgate, "scale_gate": sgate, "serve_gate": vgate,
           "sharded_serve_gate": hgate, "lint_gate": lgate,
           "trends_gate": ngate, "promote_gate": pgate,
           "resilience_gate": rgate, "span_trace_gate": wgate,
           "vm_serve_gate": mgate, "memory_gate": ygate,
           "loadgen_gate": dgate, "portfolio_gate": fgate,
           "layout_gate": ogate, "summary": summary}
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row))
    sys.stderr.write((proc.stdout or "")[-2000:])
    return rc


if __name__ == "__main__":
    sys.exit(main())
