#!/bin/bash
# The round-4 TPU evidence session, in priority order (round-3 verdict
# "Next round" items #1-#6). Fired by tools/tpu_watch.sh on a healthy
# probe, or by hand. Every piece appends to
# benchmarks/results/round4_tpu.jsonl and survives a wedge mid-way:
# stages that already landed ok are SKIPPED on the next fire
# (tpu_session.py done_stages), a shared persistent XLA cache makes
# re-fired stages cheap, and the session aborts early when the tunnel
# wedges so the watcher can re-arm instead of burning every remaining
# stage against a dead device (the first round-4 window lost tiers to
# exactly that cascade).
#
#   1. tpu_session.py (stage order = its ORDER): first-ever Mosaic
#      compile + parity gate + throughput of the fused kernel (#1,#2),
#      batched VM code-candidate launches pop 8/32 (#3), flat-256
#      headline, tiers, on-chip evolve + resume (#4), scale + the
#      config-5 100k-pod single-chip run (#5)
#   2. hybrid cross-pollination, time-boxed (#6)
#   3. bench.py, so the self-run JSON matches what the driver records
#      in BENCH_r04
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=benchmarks/results/round4_tpu.jsonl
LOG=benchmarks/results/round4_session.log
EXTRAS_DONE=benchmarks/results/.r4_extras_done
# one cache for session stages AND bench (bench.py defaults to the same
# path for the driver's standalone end-of-round run)
export JAX_COMPILATION_CACHE_DIR="$PWD/benchmarks/results/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1

python -u tools/tpu_session.py "$@" 2>&1 | tee -a "$LOG"
rc=$?
if [ "$rc" -ne 0 ] && [ "$rc" -ne 1 ]; then
  # rc=3: device wedged mid-session — nothing more can land this window.
  # rc=1 with a healthy device means a stage is broken for real; hybrid
  # and bench are independent evidence, so bank them anyway below.
  echo "session aborted (rc=$rc); skipping hybrid+bench this window"
  exit "$rc"
fi
if [ "$#" -gt 0 ]; then
  # a manual selective run measures only what was asked; hybrid+bench
  # belong to the full session (the watcher's no-args fire)
  exit "$rc"
fi
session_rc=$rc
if [ -f "$EXTRAS_DONE" ]; then
  # hybrid+bench already landed this round; a re-fire is only chasing
  # missing session stages — don't re-measure (or re-append) the extras
  exit "$session_rc"
fi

# hybrid cross-pollination, time-boxed (verdict #6): does a code candidate
# ever beat the rendered parametric champion? Admission stats land in $OUT.
# A completed earlier hybrid resumes from its checkpoint and exits fast,
# so re-fires are cheap. Failures propagate: the watcher only stops once
# session + hybrid + bench ALL landed.
timeout 1500 python -u -m fks_tpu.cli evolve --fake-llm --engine flat \
  --generations 10 --parametric-rounds 2 \
  --checkpoint benchmarks/results/r4_hybrid_ck.json \
  --out policies/discovered --metrics "$OUT" 2>&1 | tee -a "$LOG"
hrc=$?
[ "$hrc" -ne 0 ] && { echo "hybrid failed rc=$hrc"; exit "$hrc"; }

FKS_BENCH_DEADLINE_S=1000 timeout 1100 python bench.py \
  2>benchmarks/results/round4_bench.stderr | tee -a "$OUT"
brc=$?
# bench.py prints a value:0.0 fallback line on probe failure but exits 1
[ "$brc" -ne 0 ] && { echo "bench failed rc=$brc"; exit "$brc"; }
# hybrid+bench landed; overall success still requires every session stage
touch "$EXTRAS_DONE"
exit "$session_rc"
