#!/bin/bash
# The round-4 TPU evidence session, in priority order (round-3 verdict
# "Next round" items #1-#6). Run the moment the axon tunnel is healthy
# (probe: timeout 90 python -c "import jax; print(jax.devices()[0].platform)").
# Every piece appends to benchmarks/results/round4_tpu.jsonl and survives a
# wedge mid-way — each stage is its own process-group-killed subprocess, so
# re-running skips nothing but re-measures cheaply.
#
#   1. tpu_session.py core: probe, flat-256 headline, first-ever Mosaic
#      compile + parity gate + throughput of the fused kernel (asks #1,#2)
#   2. vmbatch: a generation of LLM code candidates as ONE device launch —
#      on-chip code-candidate evals/s vs the reference's ~40/s/host (#3)
#   3. tiers: VM/jit/parametric per-tier device costs (#1)
#   4. evolve: the full loop on-chip, 20 FakeLLM generations + a
#      checkpoint resume (#4)
#   5. scale rows: 1000x20k and the config-5 1000x100k single-chip run (#5)
#   6. hybrid: time-boxed LLM(Fake)+parametric cross-pollination — champion
#      work only through the hybrid loop, per #6
#   7. bench.py, so the self-run JSON matches what the driver records in
#      BENCH_r04
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/results/round4_tpu.jsonl
LOG=benchmarks/results/round4_session.log

python -u tools/tpu_session.py probe flat fused64 gate fused256 vmbatch \
  tiers evolve scale scale100k 2>&1 | tee -a "$LOG"

# hybrid cross-pollination, time-boxed (verdict #6): does a code candidate
# ever beat the rendered parametric champion? Admission stats land in $OUT.
timeout 1500 python -u -m fks_tpu.cli evolve --fake-llm --engine flat \
  --generations 10 --parametric-rounds 2 \
  --checkpoint benchmarks/results/r4_hybrid_ck.json \
  --out policies/discovered --metrics "$OUT" 2>&1 | tee -a "$LOG"

FKS_BENCH_DEADLINE_S=1000 timeout 1100 python bench.py \
  2>benchmarks/results/round4_bench.stderr | tee -a "$OUT"
