#!/usr/bin/env python3
"""Differential-fuzz fixture generator: random micro workloads through the
RUNNING reference implementation.

Extends tools/make_golden.py's approach (execute the read-only reference at
/root/reference, record observables — no code copied) from 4 fixed traces to
a seeded population of adversarial micro workloads: heavy creation-time
ties, infeasible pods (retry/drop paths), GPU-sharing contention,
multi-GPU packing, zero durations, shuffled pod-id tie ranks. The recorded
behavior — fitness, snapshot/event counts, per-pod placements and GPU
picks, retry-mutated creation times, final per-resource remnants — is the
bar for tests/test_differential.py.

Reference entry points exercised (cited for parity checking):
  - simulator/entities.py GPU/Node/Cluster/Pod constructors
  - simulator/event_simulator.py DiscreteEventSimulator
  - simulator/main.py KubernetesSimulator.run_schedule
  - simulator/evaluator.py SchedulingEvaluator
  - tests/test_scheduler.py first_fit/best_fit schedulers

Regenerate with:  python tools/fuzz_golden.py
"""
import json
import os
import random
import sys

REF = "/root/reference"
sys.path.insert(0, REF)
sys.path.insert(0, os.path.join(REF, "tests"))
sys.dont_write_bytecode = True

from simulator.entities import GPU, Node, Cluster, Pod  # noqa: E402
from simulator.event_simulator import DiscreteEventSimulator  # noqa: E402
from simulator.main import KubernetesSimulator  # noqa: E402
from simulator.evaluator import SchedulingEvaluator  # noqa: E402
import test_scheduler as zoo  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                   "tests", "fixtures", "golden_fuzz.json")

N_CASES = 48
GPU_MEM_CHOICES = [7611, 15109, 22919, 32510]


def gen_case(rng: random.Random):
    """One random workload spec (plain dicts, JSON-able)."""
    n_nodes = rng.randint(1, 6)
    nodes = []
    for i in range(n_nodes):
        n_gpu = rng.choice([0, 0, 1, 2, 4])
        nodes.append({
            "node_id": f"node-{i:02d}",
            "cpu_milli": rng.randrange(500, 8001, 100),
            "memory_mib": rng.randrange(512, 16385, 128),
            "gpus": [1000] * n_gpu,
            "gpu_memory_mib": rng.choice(GPU_MEM_CHOICES),
        })
    n_pods = rng.randint(3, 40)
    ids = list(range(n_pods))
    rng.shuffle(ids)  # pod-id lexicographic rank != arrival order
    pods = []
    for k in range(n_pods):
        has_gpu = rng.random() < 0.6
        num_gpu = rng.choice([1, 1, 1, 2, 3]) if has_gpu else 0
        pods.append({
            "pod_id": f"pod-{ids[k]:03d}",
            "cpu_milli": rng.randrange(0, 5001, 50),
            "memory_mib": rng.randrange(0, 8193, 64),
            "num_gpu": num_gpu,
            "gpu_milli": rng.choice([50, 100, 250, 500, 1000]) if has_gpu else 0,
            "creation_time": rng.randint(0, 30),  # heavy ties
            "duration_time": rng.choice([0, 1, 2, 5, 10, 40]),
        })
    return {"nodes": nodes, "pods": pods}


def ref_build(case):
    nodes_dict = {}
    for spec in case["nodes"]:
        gpus = [GPU(memory_mib_left=spec["gpu_memory_mib"],
                    memory_mib_total=spec["gpu_memory_mib"],
                    gpu_milli_left=m, gpu_milli_total=m)
                for m in spec["gpus"]]
        nodes_dict[spec["node_id"]] = Node(
            node_id=spec["node_id"],
            cpu_milli_left=spec["cpu_milli"], cpu_milli_total=spec["cpu_milli"],
            memory_mib_left=spec["memory_mib"], memory_mib_total=spec["memory_mib"],
            gpu_left=len(gpus), gpus=gpus)
    pods = [Pod(pod_id=s["pod_id"], cpu_milli=s["cpu_milli"],
                memory_mib=s["memory_mib"], num_gpu=s["num_gpu"],
                gpu_milli=s["gpu_milli"], gpu_spec="",
                creation_time=s["creation_time"],
                duration_time=s["duration_time"],
                assigned_node="", assigned_gpus=[])
            for s in case["pods"]]
    return Cluster(nodes_dict=nodes_dict), pods


def ref_run(case, policy):
    cluster, pods = ref_build(case)
    node_index = {nid: i for i, nid in enumerate(cluster.nodes_dict)}
    ev = DiscreteEventSimulator(pods)
    evaluator = SchedulingEvaluator(cluster, enabled=True)
    sim = KubernetesSimulator(cluster, pods, ev, policy, evaluator=evaluator)
    try:
        sim.run_schedule()
    except ValueError as e:
        # GPU sub-allocation shortfall aborts the run (main.py:164-165);
        # the caller maps it to fitness 0 (funsearch_integration.py:63-64)
        return {"aborted": True, "error": str(e)[:80]}
    res = evaluator.get_evaluation_results()
    return {
        "aborted": False,
        "policy_score": evaluator.get_policy_score(pods),
        "num_snapshots": res.num_snapshots,
        "num_fragmentation_events": res.num_fragmentation_events,
        "gpu_fragmentation_score": res.gpu_fragmentation_score,
        "avg_cpu_utilization": res.avg_cpu_utilization,
        "avg_memory_utilization": res.avg_memory_utilization,
        "avg_gpu_count_utilization": res.avg_gpu_count_utilization,
        "avg_gpu_memory_utilization": res.avg_gpu_memory_utilization,
        "events_processed": evaluator.events_processed,
        "max_nodes": sim.max_nodes,
        "scheduled_pods": sum(1 for p in pods if p.assigned_node != ""),
        "assignments": [node_index.get(p.assigned_node, -1) for p in pods],
        "assigned_gpus": [sorted(p.assigned_gpus) for p in pods],
        "final_creation_time": [p.creation_time for p in pods],
        "final_cpu_left": [n.cpu_milli_left for n in cluster.nodes_dict.values()],
        "final_mem_left": [n.memory_mib_left for n in cluster.nodes_dict.values()],
        "final_gpu_left": [n.gpu_left for n in cluster.nodes_dict.values()],
        "final_gpu_milli_left": [[g.gpu_milli_left for g in n.gpus]
                                 for n in cluster.nodes_dict.values()],
    }


def main():
    rng = random.Random(20260729)
    policies = {"first_fit": zoo.first_fit_scheduler,
                "best_fit": zoo.best_fit_scheduler,
                "funsearch_4901": zoo.funsearch_4901_scheduler}
    cases = []
    aborted = 0
    for i in range(N_CASES):
        case = gen_case(rng)
        results = {}
        for name, fn in policies.items():
            results[name] = ref_run(case, fn)
            aborted += results[name]["aborted"]
        cases.append({"id": i, **case, "results": results})
        scores = {n: round(r.get("policy_score", -1), 4)
                  for n, r in results.items()}
        print(f"case {i:02d}: nodes={len(case['nodes'])} "
              f"pods={len(case['pods'])} scores={scores}", flush=True)
    with open(OUT, "w") as f:
        json.dump({"seed": 20260729, "cases": cases}, f)
    print(f"wrote {len(cases)} cases ({aborted} aborted runs) to {OUT}")


if __name__ == "__main__":
    main()
