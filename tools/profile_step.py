"""Step-cost profile of the engine's event-loop body on the current device.

VERDICT r1 asked where the ~0.7 ms/step goes on TPU; VERDICT r4 (ask #5)
asks which component of the FLAT step explains the measured-vs-projected
population-throughput gap at pop 256. This tool times each component of
the per-event step in isolation — loop overhead, heap pop, heap push, the
O(capacity) first-deletion scan, policy scoring + placement arithmetic —
as jitted ``lax.while_loop``s over the REAL default-trace shapes, at
several population widths, and prints a per-step cost table.

Flat-step attribution variants (all under the bench configuration,
``track_ctime=False, max_steps=4*pods`` — what bench.py actually times):
  flat-step    parametric policy (the bench workload)
  flat-ff      first-fit policy (cheap constant scorer) — the delta to
               flat-step is the parametric FEATURE BASIS cost
  flat-ffalloc parametric policy, first-fit GPU sub-allocator — the delta
               isolates the best-fit top_k allocator
  flat-ctime   parametric policy with the per-event [P]-wide pod_ctime
               blend ON — what bench saves by turning it off

Usage:  python tools/profile_step.py [--steps 4096] [--lanes 1,16,256] [--json]
``--json`` appends one machine-readable JSON line (consumed by the TPU
session's profile256 stage). Results are summarized in PROFILE.md.

Timing rides on the shared device-time attribution layer
(fks_tpu.obs.profiler.profile_launch): each variant's cold call lands in
a ``{name}:compile`` stage with its XLA backend-compile split read off
the CompileWatcher, the measured call in ``{name}:steady`` — so the
``--json`` payload carries the same ``device_profile`` record shape as
bench.py and the evolve/serve pipelines.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=4096)
    ap.add_argument("--lanes", type=str, default="1,16,256")
    ap.add_argument("--json", action="store_true",
                    help="append one machine-readable JSON result line")
    args = ap.parse_args()
    steps = args.steps
    lanes_list = [int(x) for x in args.lanes.split(",")]

    from fks_tpu.data import TraceParser
    from fks_tpu.models import parametric, zoo
    from fks_tpu.obs.profiler import StageProfiler, profile_launch
    from fks_tpu.ops.heap import (
        first_deletion_in_array_order, heap_pop, heap_push, KIND_DELETE)
    from fks_tpu.sim.engine import (
        SimConfig, build_step, initial_state, loop_tables)

    prof = StageProfiler(scope="profile_step")
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind}); steps={steps}",
          file=sys.stderr)
    wl = TraceParser().parse_workload()
    cfg = SimConfig()
    ktable, max_steps = loop_tables(wl, cfg)
    state0 = initial_state(wl, cfg)
    params = parametric.seed_weights("best_fit")

    def loop(body, carry0):
        def cond(c):
            return c[0] < steps

        def wrapped(c):
            i, x = c
            return (i + 1, body(x))

        return jax.lax.while_loop(cond, wrapped, (jnp.int32(0), carry0))

    # ---- component bodies (single lane) -------------------------------
    heap0 = state0.heap

    def body_noop(h):
        return h

    def body_pop(h):
        h2, (t, rk, kind, pod) = heap_pop(h, pred=h.size > 0)
        # re-push what we popped so the heap never drains
        return heap_push(h2, t + 7, rk, kind, pod, pred=h.size > 0)

    def body_push_pop(h):
        h2, (t, rk, kind, pod) = heap_pop(h, pred=h.size > 0)
        h3 = heap_push(h2, t + 7, rk, kind, pod, pred=h.size > 0)
        h4 = heap_push(h3, t + 11, rk, KIND_DELETE, pod, pred=h.size > 0)
        h5, _ = heap_pop(h4, pred=h4.size > 0)
        return h5

    def body_scan(h):
        found, dt = first_deletion_in_array_order(h)
        # fold result into the carry so it can't be DCE'd
        return h._replace(size=h.size + 0 * (found.astype(jnp.int32) + dt))

    step = build_step(wl, lambda pod, nodes: parametric.score(params, pod, nodes),
                      cfg, ktable, max_steps)

    def body_full(s):
        return step(s)

    from fks_tpu.sim import flat

    # flat variants under the BENCH configuration (what bench.py times)
    cfg_bench = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
    ktable_b, max_steps_b = loop_tables(wl, cfg_bench)
    cfg_ctime = dataclasses.replace(cfg_bench, track_ctime=True)
    cfg_ffalloc = dataclasses.replace(cfg_bench, gpu_allocator="first_fit")
    fstate0 = flat.initial_state(wl, cfg_bench)
    fstate0_ct = flat.initial_state(wl, cfg_ctime)

    def param_policy(pod, nodes):
        return parametric.score(params, pod, nodes)

    ff_policy = zoo.ZOO["first_fit"]()

    fstep = flat.build_step(wl, param_policy, cfg_bench, ktable_b, max_steps_b)
    fstep_ff = flat.build_step(wl, ff_policy, cfg_bench, ktable_b, max_steps_b)
    fstep_ffalloc = flat.build_step(
        wl, param_policy, cfg_ffalloc, ktable_b, max_steps_b)
    fstep_ctime = flat.build_step(
        wl, param_policy, cfg_ctime, ktable_b, max_steps_b)

    flat_variants = [
        ("flat-step", fstep, fstate0),
        ("flat-ff", fstep_ff, fstate0),
        ("flat-ffalloc", fstep_ffalloc, fstate0),
        ("flat-ctime", fstep_ctime, fstate0_ct),
    ]

    rows = []
    for lanes in lanes_list:
        for name, body, carry in [
            ("noop", body_noop, heap0),
            ("pop+repush", body_pop, heap0),
            ("2pop+2push", body_push_pop, heap0),
            ("del-scan", body_scan, heap0),
            ("full-step", body_full, state0),
        ] + [(n, (lambda s, st=st: st(s)), c0) for n, st, c0 in flat_variants]:
            if lanes == 1:
                fn = jax.jit(lambda c, b=body: loop(b, c))
                c0 = carry
            else:
                vbody = jax.vmap(body)
                fn = jax.jit(lambda c, b=vbody: loop(b, c))
                c0 = jax.tree_util.tree_map(
                    lambda x: jnp.broadcast_to(jnp.asarray(x),
                                               (lanes,) + jnp.shape(x)), carry)
            _, rec = profile_launch(fn, c0, name=f"{name}@l{lanes}",
                                    profiler=prof)
            secs = rec["best_seconds"]
            us = secs / steps * 1e6
            rows.append((lanes, name, us))
            print(f"lanes={lanes:4d} {name:12s} {us:9.2f} us/step "
                  f"({secs:.3f}s total)", flush=True)

    print("\nper-step cost summary (us):")
    for lanes in lanes_list:
        d = {n: u for (l, n, u) in rows if l == lanes}
        print(f"  lanes={lanes}: loop={d['noop']:.1f} "
              f"pop+push={d['pop+repush'] - d['noop']:.1f} "
              f"2pop+2push={d['2pop+2push'] - d['noop']:.1f} "
              f"del-scan={d['del-scan'] - d['noop']:.1f} "
              f"full={d['full-step']:.1f} flat={d['flat-step']:.1f} "
              f"basis={d['flat-step'] - d['flat-ff']:+.1f} "
              f"alloc={d['flat-step'] - d['flat-ffalloc']:+.1f} "
              f"ctime={d['flat-ctime'] - d['flat-step']:+.1f}")

    if args.json:
        payload = {
            "device": f"{dev.platform}:{dev.device_kind}", "steps": steps,
            "rows": [{"lanes": l, "name": n, "us_per_step": round(u, 2)}
                     for (l, n, u) in rows],
        }
        for lanes in lanes_list:
            d = {n: u for (l, n, u) in rows if l == lanes}
            payload[f"lanes{lanes}"] = {
                "flat_us": round(d["flat-step"], 2),
                "basis_us": round(d["flat-step"] - d["flat-ff"], 2),
                "alloc_us": round(d["flat-step"] - d["flat-ffalloc"], 2),
                "ctime_us": round(d["flat-ctime"] - d["flat-step"], 2),
                "exact_full_us": round(d["full-step"], 2),
            }
        # same attribution record shape as bench.py / cli report
        payload["device_profile"] = prof.summary()
        print(json.dumps(payload), flush=True)


if __name__ == "__main__":
    main()
