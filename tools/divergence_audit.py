"""Per-trace flat-vs-exact fitness divergence audit (round-3 verdict
weak #3).

The flat engine's documented retry-time rule divergence (fks_tpu/sim/
flat.py module docstring) was previously summarized by ONE global number
(|d| <= 0.029 on published policies, default trace). Search selection on a
retry-heavy trace needs a bound measured on THAT trace, so this tool runs
a panel of real candidate sources — the two seed policies plus the top
discovered champions (the policies flat-engine selection actually ranks) —
through BOTH engines on EVERY shipped pod trace and records the per-trace
max |score_flat - score_exact| at search precision (f32).

One engine compile per (engine, trace): the panel rides the VM tier
(policies as data through a single compiled interpreter program), so the
audit costs 2 compiles per trace, not 2 x |panel|.

Output: one JSONL row per trace to --out (default
benchmarks/results/divergence_audit.jsonl) and a summary table on stdout.
The evolve CLI reads the latest audit to warn when `--engine flat` is
selected on a trace whose measured bound exceeds the champion gap.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def panel_sources(top_k: int = 3):
    """Seed policies + the top-k discovered champion sources by score."""
    from fks_tpu.funsearch import template

    sources = dict(template.seed_policies())
    champs = []
    for path in glob.glob(os.path.join(REPO, "policies", "discovered",
                                       "funsearch_*_score*.json")):
        try:
            with open(path) as f:
                doc = json.load(f)
            champs.append((float(doc["score"]), os.path.basename(path),
                           doc["code"]))
        except (KeyError, ValueError, OSError, json.JSONDecodeError):
            continue  # skip-and-continue: one bad file must not end it
    champs.sort(reverse=True)
    for score, name, code in champs[:top_k]:
        sources[f"champion_{score:.4f}"] = code
    return sources


def audit_trace(pod_file: str, sources, cfg_kw) -> dict:
    import jax

    from fks_tpu.data import TraceParser
    from fks_tpu.funsearch import vm
    from fks_tpu.sim import flat
    from fks_tpu.sim import engine as exact
    from fks_tpu.sim.engine import SimConfig

    wl = TraceParser().parse_workload(pod_file=pod_file)
    n, g = wl.cluster.n_padded, wl.cluster.g_padded
    cfg = SimConfig(cond_policy=True, **cfg_kw)
    runs = {
        "exact": (jax.jit(exact.make_param_run_fn(wl, vm.score, cfg)),
                  exact.initial_state(wl, cfg)),
        "flat": (jax.jit(flat.make_param_run_fn(wl, vm.score, cfg)),
                 flat.initial_state(wl, cfg)),
    }
    per_policy = {}
    events = scheduled = 0
    for name, code in sources.items():
        try:
            prog = vm.compile_policy(code, n, g, capacity=512)
        except Exception as e:  # noqa: BLE001 — skip, keep the audit going
            per_policy[name] = {"skipped": f"{type(e).__name__}"}
            continue
        scores, trunc, ev = {}, {}, {}
        for eng, (run, s0) in runs.items():
            res = run(prog, s0)
            scores[eng] = float(res.policy_score)
            trunc[eng] = bool(res.truncated) or bool(res.failed)
            ev[eng] = int(res.events_processed)
            if eng == "exact":
                events = max(events, ev[eng])
                scheduled = max(scheduled, int(res.scheduled_pods))
        per_policy[name] = {
            "exact": round(scores["exact"], 6),
            "flat": round(scores["flat"], 6),
            "flat_events": ev["flat"],  # cascade magnitude is visible here
            "abs_d": round(abs(scores["exact"] - scores["flat"]), 6),
            # truncated-on-flat-only marks a RETRY CASCADE: the flat
            # retry-time rule re-queues enough extra creations to blow the
            # event budget, zeroing the score. Distinct from arithmetic
            # drift — conservative for search (the candidate is culled,
            # never over-promoted), but it under-ranks a true champion.
            "flat_cascade": trunc["flat"] and not trunc["exact"],
        }
    ds = [p["abs_d"] for p in per_policy.values() if "abs_d" in p]
    drift = [p["abs_d"] for p in per_policy.values()
             if "abs_d" in p and not p["flat_cascade"]]
    return {
        "trace": pod_file, "num_pods": wl.num_pods,
        "num_nodes": wl.num_nodes,
        "max_events_processed": events, "max_scheduled": scheduled,
        "max_abs_d": max(ds) if ds else None,
        "mean_abs_d": round(sum(ds) / len(ds), 6) if ds else None,
        "max_drift": max(drift) if drift else None,  # cascades excluded
        "flat_cascades": sum(p.get("flat_cascade", False)
                             for p in per_policy.values()),
        "policies": per_policy,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=os.path.join(
        REPO, "benchmarks", "results", "divergence_audit.jsonl"))
    ap.add_argument("--traces", default="",
                    help="comma-separated pod CSVs (default: all)")
    ap.add_argument("--top-champions", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data import TraceParser

    traces = (args.traces.split(",") if args.traces
              else TraceParser().get_available_pod_files())
    sources = panel_sources(args.top_champions)
    print(f"panel: {list(sources)}", file=sys.stderr)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    rows = []
    for pod_file in traces:
        t0 = time.time()
        try:
            row = audit_trace(pod_file, sources, {})
        except Exception as e:  # noqa: BLE001 — a bad trace must not end
            row = {"trace": pod_file, "error": f"{type(e).__name__}: {e}"}
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        with open(args.out, "a") as f:
            f.write(json.dumps({"ts": round(time.time(), 1), **row}) + "\n")
        print(f"{pod_file}: max|d|={row.get('max_abs_d')} "
              f"({row['wall_s']}s)", file=sys.stderr)

    width = max(len(r["trace"]) for r in rows)
    print(f"{'trace':<{width}}  {'pods':>6}  {'events':>7}  "
          f"{'max|d|':>8}  {'drift':>8}  {'cascades':>8}")
    for r in sorted(rows, key=lambda r: -(r.get("max_abs_d") or 0)):
        if "error" in r:
            print(f"{r['trace']:<{width}}  ERROR {r['error']}")
        else:
            print(f"{r['trace']:<{width}}  {r['num_pods']:>6}  "
                  f"{r['max_events_processed']:>7}  "
                  f"{r['max_abs_d']:>8}  {r['max_drift']:>8}  "
                  f"{r['flat_cascades']:>8}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
