"""Per-trace flat-vs-exact fitness divergence audit (round-3 verdict
weak #3) — thin entry point.

The divergence engine itself lives in ``fks_tpu.obs.watchdog``
(``panel_sources``/``audit_trace``/``run_audit``), shared with the
online parity sentinel so there is exactly ONE place that defines what
"engine drift" means. This wrapper keeps the historical invocation:

    python tools/divergence_audit.py [--out F] [--traces a.csv,b.csv]
                                     [--top-champions K] [--cpu]

Output: one JSONL row per trace to --out (default
benchmarks/results/divergence_audit.jsonl) and a summary table on
stdout. The evolve CLI reads the latest audit to warn when
``--engine flat`` is selected on a trace whose measured bound exceeds
the champion gap.
"""
from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from fks_tpu.obs.watchdog import audit_main  # noqa: E402

if __name__ == "__main__":
    sys.exit(audit_main())
