"""One-off instrumented TPU timing probe for the bench path.

Streams per-stage wall times so a tunnel kill can't eat the evidence.
Usage: python -u tools/tpu_probe.py [pops...]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def log(*a):
    print(*a, flush=True)


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    pop_only = "--pop-only" in sys.argv
    ctime = "--ctime" in sys.argv
    fused = "--fused" in sys.argv
    pops = [int(x) for x in args] or [8, 32]
    dev = jax.devices()[0]
    log(f"device: {dev.platform} ({dev.device_kind})")

    from fks_tpu.data import TraceParser
    from fks_tpu.models import parametric, zoo
    from fks_tpu.parallel import make_population_eval
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import SimConfig, simulate

    wl = TraceParser().parse_workload()
    log(f"workload: {wl.num_nodes} nodes x {wl.num_pods} pods")

    if pop_only:
        _pop_stage(wl, pops, ctime, fused)
        return

    # stage 1: exact engine single run (the parity-gate unit)
    t0 = time.perf_counter()
    r = simulate(wl, zoo.ZOO["first_fit"]())
    jax.block_until_ready(r.policy_score)
    log(f"exact first_fit compile+run: {time.perf_counter() - t0:.1f}s "
        f"score={float(r.policy_score):.4f}")

    # stage 2: flat engine single run
    t0 = time.perf_counter()
    r = flat.simulate(wl, zoo.ZOO["best_fit"]())
    jax.block_until_ready(r.policy_score)
    log(f"flat best_fit compile+run: {time.perf_counter() - t0:.1f}s "
        f"score={float(r.policy_score):.4f} "
        f"events={int(r.events_processed)} trunc={bool(r.truncated)}")

    run = jax.jit(lambda: flat.simulate(wl, zoo.ZOO['best_fit'](), jit=False))
    r = run()
    jax.block_until_ready(r.policy_score)
    t0 = time.perf_counter()
    r = run()
    jax.block_until_ready(r.policy_score)
    warm = time.perf_counter() - t0
    ev_n = int(r.events_processed)
    log(f"flat best_fit warm: {warm:.2f}s = {warm / max(ev_n,1) * 1e6:.1f}"
        f" us/event ({ev_n} events)")

    # stage 3: flat population chunks (same capped step budget as bench.py)
    _pop_stage(wl, pops, ctime, fused)


def _pop_stage(wl, pops, ctime, fused=False):
    from fks_tpu.models import parametric
    from fks_tpu.parallel import make_population_eval
    from fks_tpu.sim.engine import SimConfig

    cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=ctime)
    for pop in pops:
        key = jax.random.PRNGKey(0)
        params = parametric.init_population(key, pop, noise=0.1)
        if fused:
            from fks_tpu.sim import fused as fused_mod
            ev = fused_mod.make_fused_population_run(
                wl, cfg, lanes=min(64, pop))
        else:
            ev = make_population_eval(wl, cfg=cfg, engine="flat")
        t0 = time.perf_counter()
        res = ev(params)
        jax.block_until_ready(res.policy_score)
        log(f"flat pop={pop} compile+run: {time.perf_counter() - t0:.1f}s")
        t0 = time.perf_counter()
        res = ev(params)
        jax.block_until_ready(res.policy_score)
        dt = time.perf_counter() - t0
        evs = np.asarray(res.events_processed)
        tr = np.asarray(res.truncated)
        log(f"flat pop={pop} warm: {dt:.2f}s = {pop/dt:.1f} evals/s; "
            f"events max={int(evs.max())} mean={float(evs.mean()):.0f} "
            f"truncated={int(tr.sum())}")


if __name__ == "__main__":
    main()
