#!/bin/bash
# The round-5 TPU evidence session, in priority order (round-4 verdict
# "Next round" items #1-#8). Fired by tools/tpu_watch.sh on a healthy
# probe, or by hand. Every piece appends to
# benchmarks/results/round5_tpu.jsonl and survives a wedge mid-way:
# stages that already landed ok are SKIPPED on the next fire
# (tpu_session.py done_stages), a shared persistent XLA cache makes
# re-fired stages cheap, and the session aborts early when the tunnel
# wedges so the watcher can re-arm instead of burning every remaining
# stage against a dead device.
#
#   1. tpu_session.py (stage order = its ORDER): the repaired fused
#      kernel's first on-chip run + gate + throughput (#1), batched VM
#      code candidates pop 8/32/96 (#2), flat-256 headline, SEEDED
#      flat-256 (#6), per-component step profile at pop 256 (#5), tiers
#      incl. exact-engine µs/event (#8), on-chip evolve + resume (#4),
#      scale + the config-5 100k-pod single-chip run
#   2. hybrid cross-pollination, time-boxed
#   3. bench.py, so the self-run JSON matches what the driver records
#      in BENCH_r05 (bench.py also BANKS this session's freshest
#      measurement as its fallback payload — verdict ask #3)
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT=benchmarks/results/round5_tpu.jsonl
LOG=benchmarks/results/round5_session.log
EXTRAS_DONE=benchmarks/results/.r5_extras_done
# one cache for session stages AND bench (bench.py defaults to the same
# path for the driver's standalone end-of-round run)
export JAX_COMPILATION_CACHE_DIR="$PWD/benchmarks/results/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=1

python -u tools/tpu_session.py "$@" 2>&1 | tee -a "$LOG"
rc=$?
if [ "$rc" -ne 0 ] && [ "$rc" -ne 1 ]; then
  # rc=3: device wedged mid-session — nothing more can land this window.
  # rc=1 with a healthy device means a stage is broken for real; hybrid
  # and bench are independent evidence, so bank them anyway below.
  echo "session aborted (rc=$rc); skipping hybrid+bench this window"
  exit "$rc"
fi
if [ "$#" -gt 0 ]; then
  # a manual selective run measures only what was asked; hybrid+bench
  # belong to the full session (the watcher's no-args fire)
  exit "$rc"
fi
session_rc=$rc
if [ -f "$EXTRAS_DONE" ]; then
  # hybrid+bench already landed this round; a re-fire is only chasing
  # missing session stages — don't re-measure (or re-append) the extras
  exit "$session_rc"
fi

# hybrid cross-pollination, time-boxed: does a code candidate ever beat
# the rendered parametric champion? Admission stats land in $OUT.
# A completed earlier hybrid resumes from its checkpoint and exits fast,
# so re-fires are cheap. Failures propagate: the watcher only stops once
# session + hybrid + bench ALL landed.
timeout 1500 python -u -m fks_tpu.cli evolve --fake-llm --engine flat \
  --generations 10 --parametric-rounds 2 \
  --checkpoint benchmarks/results/r5_hybrid_ck.json \
  --out policies/discovered --metrics "$OUT" 2>&1 | tee -a "$LOG"
hrc=$?
[ "$hrc" -ne 0 ] && { echo "hybrid failed rc=$hrc"; exit "$hrc"; }

FKS_BENCH_DEADLINE_S=1000 timeout 1100 python bench.py \
  2>benchmarks/results/round5_bench.stderr | tee -a "$OUT"
brc=$?
# bench.py prints a banked-fallback line on probe failure but exits 1
[ "$brc" -ne 0 ] && { echo "bench failed rc=$brc"; exit "$brc"; }
# hybrid+bench landed; overall success still requires every session stage
touch "$EXTRAS_DONE"
exit "$session_rc"
