"""Export a node list as Kubernetes Node manifests (k8s-style YAML).

The reference dataset ships a k8s rendering of its GPU node list
(reference: benchmarks/traces/node_yaml/openb_node_list_gpu_node.yaml —
1,213 ``kind: Node`` documents; nothing in the reference code reads it,
SURVEY.md C8). For dataset completeness this tool GENERATES the same
rendering from the node CSV the repo already ships, instead of copying
the artifact: each node becomes a Node manifest with Alibaba GPU
extended-resource annotations (``alibabacloud.com/gpu-count`` /
``gpu-milli`` / ``gpu-card-model``), cpu in millicores, memory in Mi,
and the OpenB fixed pods capacity of 1001.

Usage:
  python tools/export_node_yaml.py [--nodes csv/openb_node_list_gpu_node.csv.gz]
                                   [--out benchmarks/traces/node_yaml/...yaml.gz]

The default regenerates benchmarks/traces/node_yaml/
openb_node_list_gpu_node.yaml.gz (stored gzipped, like the dataset's CSVs).
"""
from __future__ import annotations

import argparse
import csv
import gzip
import io
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACES = os.path.join(REPO, "benchmarks", "traces")

#: OpenB node manifests carry a fixed max-pods capacity of 1001.
PODS_CAPACITY = 1001

_DOC = """apiVersion: v1
kind: Node
metadata:
  labels:
{labels}    kubernetes.io/os: linux
  name: {name}
status:
  allocatable:
{resources}  capacity:
{resources}"""


def _resources(cpu_milli: int, memory_mib: int, gpu: int) -> str:
    lines = []
    if gpu > 0:
        lines.append(f"    alibabacloud.com/gpu-count: '{gpu}'")
        lines.append(f"    alibabacloud.com/gpu-milli: '{gpu * 1000}'")
    lines.append(f"    cpu: {cpu_milli}m")
    lines.append(f"    memory: {memory_mib}Mi")
    lines.append(f"    pods: '{PODS_CAPACITY}'")
    return "\n".join(lines) + "\n"


def render_node(sn: str, cpu_milli: int, memory_mib: int, gpu: int,
                model: str) -> str:
    labels = ""
    if gpu > 0 and model:
        labels += f"    alibabacloud.com/gpu-card-model: {model}\n"
    labels += "    beta.kubernetes.io/os: linux\n"
    labels += f"    kubernetes.io/hostname: {sn}\n"
    return _DOC.format(labels=labels, name=sn,
                       resources=_resources(cpu_milli, memory_mib, gpu))


def export(nodes_csv: str, out_path: str) -> int:
    opener = gzip.open if nodes_csv.endswith(".gz") else open
    with opener(nodes_csv, "rt") as f:
        rows = list(csv.DictReader(f))
    docs = [render_node(r["sn"], int(r["cpu_milli"]), int(r["memory_mib"]),
                        int(r["gpu"]), r.get("model", ""))
            for r in rows]
    body = "\n---\n\n".join(docs)
    buf = io.StringIO()
    buf.write(body)
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    if out_path.endswith(".gz"):
        # fixed mtime so regeneration is reproducible byte-for-byte
        with open(out_path, "wb") as raw, \
                gzip.GzipFile(fileobj=raw, mode="wb", mtime=0) as gz:
            gz.write(buf.getvalue().encode())
    else:
        with open(out_path, "w") as f:
            f.write(buf.getvalue())
    return len(docs)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", default=os.path.join(
        TRACES, "csv", "openb_node_list_gpu_node.csv.gz"))
    ap.add_argument("--out", default=os.path.join(
        TRACES, "node_yaml", "openb_node_list_gpu_node.yaml.gz"))
    args = ap.parse_args()
    n = export(args.nodes, args.out)
    print(f"wrote {n} Node manifests to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
