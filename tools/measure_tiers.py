"""Candidate-evaluation tier benchmark: VM vs per-candidate jit vs parametric.

VERDICT r1 #4: "measure VM-tier evals/s vs jit-tier compile+run on real
LLM-shaped candidates, and record an end-to-end evolve --fake-llm
generation throughput". This tool measures, on the current device:

  vm-warm      one candidate through the shared VM interpreter program
               (per-candidate cost once the interpreter is compiled)
  jit-compile  transpile + XLA-compile one UNSEEN candidate (the cost the
               VM tier avoids)
  jit-warm     re-run of a compiled candidate (pure device run)
  parametric   evals/s for a vmapped parametric population (the backbone)
  evolve-gen   wall time of one full FakeLLM generation through
               FunSearch.evolve_generation (codegen + eval + admission)

Prints one JSON object; pass --metrics FILE to append a JSONL record.
Usage: python tools/measure_tiers.py [--engine flat] [--cpu] [--pop 64]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", choices=("exact", "flat"), default="flat")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--pop", type=int, default=64)
    ap.add_argument("--candidates", type=int, default=6)
    ap.add_argument("--metrics", default="")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data import TraceParser
    from fks_tpu.funsearch import (
        CodeEvaluator, EvolutionConfig, FakeLLM, FunSearch, template,
    )
    from fks_tpu.models import parametric
    from fks_tpu.parallel import make_population_eval
    from fks_tpu.sim.engine import SimConfig

    dev = jax.devices()[0]
    wl = TraceParser().parse_workload()
    fake = FakeLLM(seed=11, junk_rate=0.0)
    codes = [template.fill_template(fake.complete("x"))
             for _ in range(args.candidates)]
    out = {"device": f"{dev.platform}:{dev.device_kind}",
           "engine": args.engine, "workload": f"{wl.num_nodes}x{wl.num_pods}"}

    # ---- VM tier: warm per-candidate cost (compile interpreter on c0)
    ev = CodeEvaluator(wl, engine=args.engine)
    t0 = time.perf_counter()
    r0 = ev.evaluate_one(codes[0])
    out["vm_first_s"] = round(time.perf_counter() - t0, 3)  # incl. compile
    assert r0.ok, r0.error
    times = []
    skipped = 0
    for c in codes[1:]:
        t0 = time.perf_counter()
        r = ev.evaluate_one(c)
        dt = time.perf_counter() - t0
        # only successful VM-tier evaluations may enter the timing: a
        # validation-error record returns in milliseconds and a
        # VM-unsupported candidate pays a jit compile — both would corrupt
        # vm_warm_s. A degenerate candidate that exhausts the step budget
        # (score 0, truncated) is skipped too: it times max_steps, not a
        # typical eval.
        if r.ok:
            times.append(dt)
        else:
            skipped += 1
    assert ev.compile_count == 0, "a candidate fell to the jit tier"
    assert len(times) >= 2, "too few clean candidates to time"
    out["vm_skipped_candidates"] = skipped
    out["vm_warm_s"] = round(min(times), 3)
    out["vm_tier_hits"] = ev.vm_count
    out["vm_evals_per_sec"] = round(1.0 / min(times), 3)

    # ---- batched VM tier: a GENERATION as one device launch (the
    # population-batched path; round-3 verdict ask #3). Two distinct
    # candidate sets: the first launch pays the population-engine
    # compile, the second is the steady-state per-generation cost.
    evb = CodeEvaluator(wl, engine=args.engine, vm_batch=True)
    gen_a = [template.fill_template(fake.complete("x"))
             for _ in range(args.candidates)]
    gen_b = [template.fill_template(fake.complete("x"))
             for _ in range(args.candidates)]
    t0 = time.perf_counter()
    recs = evb.evaluate(gen_a)
    out["vm_batch_first_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    recs = evb.evaluate(gen_b)
    dt = time.perf_counter() - t0
    assert evb.compile_count == 0, "a candidate fell to the jit tier"
    out["vm_batch_pop"] = len(recs)
    out["vm_batch_launches"] = evb.vm_batch_count
    out["vm_batch_warm_s"] = round(dt, 3)
    out["vm_batch_evals_per_sec"] = round(len(recs) / dt, 3)

    # ---- jit tier: per-unseen-candidate compile+run, then warm re-run
    ev2 = CodeEvaluator(wl, engine=args.engine, use_vm=False)
    t0 = time.perf_counter()
    ev2.evaluate_one(codes[0])
    out["jit_compile_run_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    ev2.evaluate_one(codes[0])
    out["jit_warm_s"] = round(time.perf_counter() - t0, 3)

    # ---- parametric tier: chunked vmapped population
    params = parametric.init_population(jax.random.PRNGKey(0), args.pop,
                                        noise=0.1)
    pev = make_population_eval(wl, cfg=SimConfig(), engine=args.engine)
    r = pev(params)
    jax.block_until_ready(r.policy_score)  # compile
    t0 = time.perf_counter()
    r = pev(params)
    jax.block_until_ready(r.policy_score)
    dt = time.perf_counter() - t0
    out["parametric_pop"] = args.pop
    out["parametric_sweep_s"] = round(dt, 3)
    out["parametric_evals_per_sec"] = round(args.pop / dt, 2)

    # ---- exact-engine diet (single lane): µs/event on THIS device — the
    # on-chip validation of the round-3 CPU-only instruction-diet claim
    # (117 -> 72.8 µs/event; VERDICT r4 weak #4 / ask #8). Fault-isolated:
    # a failure here records the error and keeps the tier numbers.
    try:
        from fks_tpu.models import zoo
        from fks_tpu.sim import engine as exact_engine
        ecfg = SimConfig()
        runfn = jax.jit(exact_engine.make_run_fn(
            wl, zoo.ZOO["best_fit"](), ecfg))
        es0 = exact_engine.initial_state(wl, ecfg)
        er = runfn(es0)
        jax.block_until_ready(er.policy_score)  # compile
        t0 = time.perf_counter()
        er = runfn(es0)
        jax.block_until_ready(er.policy_score)
        dt = time.perf_counter() - t0
        n_ev = int(er.events_processed)
        out["exact_best_fit_s"] = round(dt, 3)
        out["exact_events"] = n_ev
        out["exact_us_per_event"] = round(dt / max(n_ev, 1) * 1e6, 2)
    except Exception as e:  # noqa: BLE001 — keep the tier numbers
        out["exact_error"] = f"{type(e).__name__}: {e}"

    # ---- end-to-end generation: codegen + eval + admission (reuses the
    # warmed evaluator, as a steady-state generation would)
    cfg = EvolutionConfig(population_size=12, generations=1, elite_size=3,
                          candidates_per_generation=8, max_workers=8, seed=5,
                          early_stop_threshold=1.1)
    fs = FunSearch(ev, cfg, backend=FakeLLM(seed=5), log=lambda *a: None)
    fs.initialize_population()
    compiles_before = ev.compile_count
    t0 = time.perf_counter()
    st = fs.evolve_generation()
    out["evolve_gen_s"] = round(time.perf_counter() - t0, 3)
    out["evolve_gen_candidates"] = st.new_candidates
    out["evolve_cand_per_sec"] = round(st.new_candidates
                                       / max(out["evolve_gen_s"], 1e-9), 3)
    # delta, not cumulative: compiles from earlier sections must not be
    # attributed to the generation
    out["evolve_xla_compiles"] = ev.compile_count - compiles_before

    # compact, single line: tpu_session.py's stage runner takes the LAST
    # parsable stdout line as the stage payload — an indented dump would
    # leave it only a closing brace
    print(json.dumps(out))
    if args.metrics:
        from fks_tpu.utils import MetricsWriter
        with MetricsWriter(args.metrics) as mw:
            mw.write("tier_benchmark", out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
