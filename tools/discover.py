"""Search the parametric policy space for a champion on a real trace.

Runs the device-resident weight evolution (fks_tpu.funsearch.
device_evolution) against a trace, then re-scores the champion through
the EXACT engine (the bit-for-bit reference replica) so the reported
fitness is directly comparable to the reference's published numbers
(README parity table; funsearch_4901 = 0.4901 is the bar).

The champion is persisted in the reference's discovered-policy JSON
schema (reference: funsearch/funsearch_integration.py:606-633) with the
rendered source, so it can be dropped into either framework.

Usage:
  python -u tools/discover.py [--engine fused] [--gens 40] [--pop 32]
      [--seed 0] [--out policies/discovered] [--checkpoint CK [--resume]]
      [--metrics FILE]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--engine", default="flat",
                    choices=("exact", "flat", "fused"))
    ap.add_argument("--gens", type=int, default=40)
    ap.add_argument("--pop", type=int, default=32)
    ap.add_argument("--elite-k", type=int, default=4)
    ap.add_argument("--noise", type=float, default=0.08)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default="")
    ap.add_argument("--checkpoint", default="")
    ap.add_argument("--resume", action="store_true",
                    help="restore from --checkpoint before searching")
    ap.add_argument("--init-from", default="",
                    help="champion JSON whose weights seed the population "
                         "(lane 0 exact, others perturbed by --noise) — "
                         "lets a NEW pop size continue a finished search, "
                         "which --resume cannot (size must match)")
    ap.add_argument("--metrics", default="")
    args = ap.parse_args()
    if args.resume and args.init_from:
        ap.error("--resume and --init-from are mutually exclusive")

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from fks_tpu.data import TraceParser
    from fks_tpu.funsearch.device_evolution import ParametricEvolution
    from fks_tpu.models import parametric
    from fks_tpu.sim.engine import SimConfig, simulate

    wl = TraceParser().parse_workload()
    print(f"workload: {wl.num_nodes} nodes x {wl.num_pods} pods; "
          f"engine={args.engine} pop={args.pop} gens={args.gens}",
          file=sys.stderr, flush=True)

    pe = ParametricEvolution(
        wl, pop_size=args.pop, elite_k=args.elite_k, noise=args.noise,
        cfg=SimConfig(track_ctime=False), engine=args.engine,
        seed=args.seed)
    if args.resume:
        pe.restore_checkpoint(args.checkpoint)
        print(f"resumed at generation {pe.generation} "
              f"(best {pe.best_score:.4f})", file=sys.stderr)
    elif args.init_from:
        with open(args.init_from) as f:
            champ_doc = json.load(f)
        if "weights" not in champ_doc:
            print(f"error: {args.init_from} has no 'weights' field — it is "
                  "a code-evolved champion (reference schema); --init-from "
                  "needs a parametric champion", file=sys.stderr)
            return 2
        pe.init_from_weights(champ_doc["weights"], noise=args.noise,
                             seed=args.seed + 7)
        print(f"population seeded from {args.init_from} "
              f"(pop {args.pop}, noise {args.noise})", file=sys.stderr)
    t0 = time.time()

    def on_gen(st):
        print(f"gen {st.generation}: best {st.best_score:.4f} "
              f"mean {st.mean_score:.4f} ({time.time() - t0:.0f}s)",
              file=sys.stderr, flush=True)
        if args.metrics:
            with open(args.metrics, "a") as f:
                f.write(json.dumps({
                    "ts": round(time.time(), 1), "kind": "discover_gen",
                    "engine": args.engine, "generation": st.generation,
                    "best": st.best_score, "mean": st.mean_score}) + "\n")
        if args.checkpoint and st.generation % 10 == 0:
            pe.save_checkpoint(args.checkpoint)

    pe.run(args.gens, on_generation=on_gen)
    if args.checkpoint:
        pe.save_checkpoint(args.checkpoint)

    # re-score the champion through the exact (reference-replica) engine
    from fks_tpu.funsearch.device_evolution import _to_host
    weights = _to_host(pe.best_params)
    exact = simulate(wl, parametric.as_policy(weights))
    exact_score = float(exact.policy_score)
    print(f"champion: search-engine score {pe.best_score:.4f}; EXACT-engine "
          f"score {exact_score:.4f}; scheduled "
          f"{int(exact.scheduled_pods)}/{wl.num_pods}",
          file=sys.stderr, flush=True)

    # reference discovered-policy schema {score, generation, code,
    # timestamp} + provenance extras, same filename pattern as
    # evolution.save_best_policy so downstream globs pick both up
    stamp = time.strftime("%Y%m%d_%H%M%S")
    result = {
        "score": exact_score,
        "generation": pe.generation,
        "code": parametric.render_code(weights),
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "search_score": pe.best_score,
        "engine": args.engine,
        "weights": [float(w) for w in weights],
    }
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        path = os.path.join(
            args.out, f"funsearch_{stamp}_score{exact_score:.4f}.json")
        with open(path, "w") as f:
            json.dump(result, f, indent=2)
        print(f"saved {path}", file=sys.stderr)
    print(json.dumps({k: v for k, v in result.items() if k != "code"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
