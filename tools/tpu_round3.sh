#!/bin/bash
# The full round-3 TPU evidence session, in priority order. Run the moment
# the axon tunnel is healthy (probe: timeout 90 python -c "import jax;
# print(jax.devices()[0].platform)"). Each piece appends to
# benchmarks/results/round3_tpu.jsonl and survives a wedge mid-way —
# re-running skips nothing but re-measures cheaply.
#
#   1. tpu_session.py: probe, flat-256 throughput (the headline), the
#      first-ever Mosaic compile + gate + throughput of the fused kernel,
#      VM/jit/parametric tier costs, scale rows (verdict asks #1b,#2,#5,#6)
#   2. discover.py on-chip at pop 256 with exact re-score (verdict ask #4)
#   3. bench.py itself, so the self-run JSON matches what the driver will
#      record in BENCH_r03 (verdict ask #1)
set -u
cd "$(dirname "$0")/.."
OUT=benchmarks/results/round3_tpu.jsonl

python -u tools/tpu_session.py probe flat fused64 gate fused256 tiers 2>&1 |
  tee -a benchmarks/results/round3_session.log

# --resume only once a checkpoint exists, so a re-run after a mid-window
# wedge continues the search instead of redoing finished generations
CK=benchmarks/results/r3_discover_ck.npz
RESUME=""
[ -f "$CK" ] && RESUME="--resume"
timeout 1500 python -u tools/discover.py --engine flat --gens 60 --pop 256 \
  --seed 3 --out policies/discovered \
  --checkpoint "$CK" $RESUME \
  --metrics "$OUT" 2>&1 | tee -a benchmarks/results/round3_session.log

python -u tools/tpu_session.py scale scale100k 2>&1 |
  tee -a benchmarks/results/round3_session.log

FKS_BENCH_DEADLINE_S=1000 timeout 1100 python bench.py \
  2>benchmarks/results/round3_bench.stderr | tee -a "$OUT"
