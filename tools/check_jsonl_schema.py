"""JSONL schema checker for flight-recorder run dirs and results files.

Two jobs, one helper:

- ``check_jsonl(path, required=...)`` — every line must parse as a JSON
  object carrying the required keys. A torn FINAL line (a writer killed
  mid-append) is tolerated by default, matching ``obs.report.read_jsonl``;
  a torn line anywhere else is corruption and fails.
- ``check_run_dir(run_dir)`` — validate a ``fks_tpu.obs.FlightRecorder``
  directory: ``meta.json`` (run_id/started/status), ``events.jsonl`` and
  ``metrics.jsonl`` (ts/kind per line), ``heartbeat`` when present.

Usage:
    python tools/check_jsonl_schema.py --run-dir runs/evolve1
    python tools/check_jsonl_schema.py benchmarks/results/round*_tpu.jsonl

The second form checks arbitrary JSONL evidence files (the TPU session
logs under benchmarks/results/ predate the recorder and have no fixed
keys, so they are checked for parseability only unless --require is
given). Exit code 0 = clean, 1 = violations (printed one per line).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Sequence, Tuple

#: per-line required keys for the recorder's JSONL surfaces
RUN_DIR_REQUIRED: Dict[str, Tuple[str, ...]] = {
    "events.jsonl": ("ts", "kind"),
    "metrics.jsonl": ("ts", "kind"),
}
#: required keys in a run dir's meta.json
META_REQUIRED: Tuple[str, ...] = ("run_id", "started", "status")


class SchemaError(ValueError):
    """A JSONL file violated the schema; ``str(e)`` says where and why."""


def check_jsonl(path: str, required: Sequence[str] = (),
                allow_empty: bool = True,
                tolerate_torn_tail: bool = True) -> List[dict]:
    """Parse ``path`` line by line, requiring each record to be a JSON
    object with every key in ``required``. Returns the parsed records.
    Raises ``SchemaError`` on the first violation (with line number)."""
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        raise SchemaError(f"{path}: unreadable ({e})") from e
    if not lines and not allow_empty:
        raise SchemaError(f"{path}: empty")
    records: List[dict] = []
    last = len(lines) - 1
    for i, line in enumerate(lines):
        if not line.strip():
            if i == last:
                continue  # trailing newline
            raise SchemaError(f"{path}:{i + 1}: blank line mid-file")
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if i == last and tolerate_torn_tail:
                break  # writer killed mid-append; the prefix is valid
            raise SchemaError(f"{path}:{i + 1}: unparsable ({e})") from e
        if not isinstance(rec, dict):
            raise SchemaError(f"{path}:{i + 1}: not a JSON object "
                              f"({type(rec).__name__})")
        missing = [k for k in required if k not in rec]
        if missing:
            raise SchemaError(f"{path}:{i + 1}: missing {missing} "
                              f"(has {sorted(rec)[:8]})")
        records.append(rec)
    return records


def check_run_dir(run_dir: str) -> Dict[str, int]:
    """Validate a FlightRecorder run directory; returns per-file record
    counts. Raises ``SchemaError`` on the first violation."""
    meta_path = os.path.join(run_dir, "meta.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except OSError as e:
        raise SchemaError(f"{meta_path}: unreadable ({e})") from e
    except json.JSONDecodeError as e:
        raise SchemaError(f"{meta_path}: unparsable ({e})") from e
    missing = [k for k in META_REQUIRED if k not in meta]
    if missing:
        raise SchemaError(f"{meta_path}: missing {missing}")
    counts = {"meta.json": 1}
    for name, required in RUN_DIR_REQUIRED.items():
        path = os.path.join(run_dir, name)
        if not os.path.exists(path):
            counts[name] = 0  # a run may legitimately record no metrics
            continue
        counts[name] = len(check_jsonl(path, required=required))
    hb = os.path.join(run_dir, "heartbeat")
    if os.path.exists(hb):
        try:
            with open(hb) as f:
                beat = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SchemaError(f"{hb}: unparsable ({e})") from e
        if "ts" not in beat:
            raise SchemaError(f"{hb}: missing ['ts']")
        counts["heartbeat"] = 1
    return counts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="JSONL files to check (e.g. benchmarks/results/"
                         "round*_tpu.jsonl)")
    ap.add_argument("--run-dir", default="",
                    help="validate a flight-recorder run directory instead")
    ap.add_argument("--require", default="",
                    help="comma-separated keys every record must carry")
    args = ap.parse_args(argv)
    if not args.run_dir and not args.paths:
        ap.error("give JSONL paths or --run-dir")
    required = [k for k in args.require.split(",") if k]
    rc = 0
    if args.run_dir:
        try:
            counts = check_run_dir(args.run_dir)
            print(f"{args.run_dir}: ok "
                  + " ".join(f"{k}={v}" for k, v in sorted(counts.items())))
        except SchemaError as e:
            print(f"SCHEMA: {e}", file=sys.stderr)
            rc = 1
    for path in args.paths:
        try:
            records = check_jsonl(path, required=required)
            print(f"{path}: ok ({len(records)} records)")
        except SchemaError as e:
            print(f"SCHEMA: {e}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
