"""JSONL schema checker for flight-recorder run dirs and results files.

Two jobs, one helper:

- ``check_jsonl(path, required=...)`` — every line must parse as a JSON
  object carrying the required keys. A torn FINAL line (a writer killed
  mid-append) is tolerated by default, matching ``obs.report.read_jsonl``;
  a torn line anywhere else is corruption and fails.
- ``check_run_dir(run_dir)`` — validate a ``fks_tpu.obs.FlightRecorder``
  directory: ``meta.json`` (run_id/started/status), ``events.jsonl`` and
  ``metrics.jsonl`` (ts/kind per line), ``heartbeat`` when present.

Beyond the generic ts/kind floor, records of KNOWN kinds (the watchdog /
alert / parity / probe_failure vocabulary added with the numerics
watchdog, plus the evolution ledger's generation records, plus the
``decision_trace``/``trace_diff`` records from fks_tpu.obs.tracing —
whose embedded trace rows must carry a known CREATE/DELETE/RETRY/
NODE_DOWN/NODE_UP event kind, and the scenario-suite records from
fks_tpu.scenarios) are checked for their kind-specific required keys — a watchdog
event without a flag mask is as corrupt as a line without a timestamp.

``check_openmetrics(text)`` validates the ``cli export-metrics`` output:
every exposition line is a comment, a ``# TYPE``/``# HELP`` header, or a
``name{labels} value`` sample whose family was declared first, and the
exposition ends with ``# EOF``.

Usage:
    python tools/check_jsonl_schema.py --run-dir runs/evolve1
    python tools/check_jsonl_schema.py --openmetrics metrics.prom
    python tools/check_jsonl_schema.py benchmarks/results/round*_tpu.jsonl

The last form checks arbitrary JSONL evidence files (the TPU session
logs under benchmarks/results/ predate the recorder and have no fixed
keys, so they are checked for parseability only unless --require is
given). Exit code 0 = clean, 1 = violations (printed one per line).
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Sequence, Tuple

#: per-line required keys for the recorder's JSONL surfaces
RUN_DIR_REQUIRED: Dict[str, Tuple[str, ...]] = {
    "events.jsonl": ("ts", "kind"),
    "metrics.jsonl": ("ts", "kind"),
}
#: required keys in a run dir's meta.json
META_REQUIRED: Tuple[str, ...] = ("run_id", "started", "status")

#: kind-specific required keys, per surface. Unknown kinds pass (the
#: recorder is an open vocabulary); known kinds must be well-formed.
EVENT_KIND_REQUIRED: Dict[str, Tuple[str, ...]] = {
    "watchdog": ("flags", "kinds"),
    "alert": ("source",),
    "probe_failure": ("attempt",),
    "span": ("seconds",),
    "compile": ("seconds",),
    "decision_trace": ("engine", "events"),
    "trace_diff": ("engines", "divergent"),
    # static pre-flight analyzer (fks_tpu.analysis): one event per
    # candidate rejected before the sandbox/transpile/compile pipeline —
    # the taxonomy label is machine-readable and closed-vocabulary
    "candidate_rejected": ("taxonomy", "stage"),
    # promotion pipeline (fks_tpu.pipeline): a post-promotion SLO burn
    # swapped the last-good engine back
    "rollback": ("attempt", "reason"),
    # evolve circuit breaker: N consecutive all-failed-LLM generations
    # tripped the loop (cli evolve exits 4 after checkpointing)
    "llm_outage": ("generation", "consecutive"),
    # resilience layer (fks_tpu.resilience): admission control refused a
    # request (reason: queue_full / deadline_budget / draining)
    "shed": ("reason",),
    # degraded-mode state machine transition (state: degraded /
    # probation / normal / dead)
    "degraded": ("fault", "state"),
    # SIGTERM drain completed: every in-flight Future completed or shed
    "drain": ("pending",),
    # evolve WAL replay: a resumed generation reused persisted
    # candidates/evals instead of re-spending LLM calls / device evals
    "resume_wal": ("generation",),
    # VM-native serving (fks_tpu.serve.vm_engine + cli serve /
    # promotion controller): one event per champion table hot-swap
    # (outcome="swapped") or per AOT fallback when a champion is outside
    # the VM vocabulary (outcome="fallback")
    "vm_swap": ("outcome", "champion"),
    # portfolio serving (fks_tpu.portfolio.engine): one event per slot
    # promotion in the shared slot-vmapped executable — which slot's
    # tables were re-uploaded (always outcome="swapped"; a champion
    # outside the VM vocabulary never reaches a slot)
    "slot_swap": ("slot", "outcome", "champion"),
    # causal tracing (fks_tpu.obs.trace_ctx): one span of a request /
    # generation / promotion trace. parent_id is intentionally NOT
    # required: the root span carries an explicit JSON null there, and
    # key-presence is what this checker tests
    "trace_span": ("trace_id", "span_id", "path", "seconds"),
}

#: legal ``taxonomy`` values on a candidate_rejected event. This tool is
#: stdlib-only by design, so the vocabulary is duplicated from
#: fks_tpu.analysis.REJECT_TAXONOMY; tests/test_analysis.py pins the two
#: copies against each other.
CANDIDATE_REJECT_TAXONOMY = {
    "syntax", "forbidden_construct", "bad_signature", "unsupported_syntax",
    "unsupported_call", "bad_arity", "unknown_attribute", "loop_too_long",
    "duplicate_fingerprint",
}

#: legal event kinds inside an embedded decision-trace row (must match
#: fks_tpu.sim.types.TRACE_KIND_NAMES)
TRACE_EVENT_KINDS = {"CREATE", "DELETE", "RETRY", "NODE_DOWN", "NODE_UP"}

#: legal ``outcome`` values on a vm_swap event, and legal ``engine_kind``
#: values wherever the field appears (promotion_event / vm_swap /
#: serve meta) — which champion-binding strategy served the swap
VM_SWAP_OUTCOMES = {"swapped", "fallback"}
ENGINE_KINDS = {"aot", "vm"}

#: legal ``component`` values on a memory_footprint record — which tier
#: compiled the executable (duplicated from fks_tpu.obs.memory
#: .MEMORY_COMPONENTS; tests/test_memory.py pins the two copies)
MEMORY_COMPONENTS = {"serve_aot", "serve_vm", "evolve", "bench"}
#: legal ``loop`` values on a leak_check record (fks_tpu.obs.memory
#: .LEAK_LOOPS) — which hot loop the leak sentinel fenced
LEAK_LOOPS = {"serve_batch", "vm_swap", "promotion", "evolve_generation",
              "drill"}
#: legal ``mode`` values on a loadgen_summary record (duplicated from
#: fks_tpu.obs.workload.LOADGEN_MODES; tests/test_workload.py pins the
#: two copies) — the arrival process that produced the numbers
LOADGEN_MODES = {"open", "closed", "mixed"}
#: closed vocabulary of batchable layout axes, and the components that
#: may file layout_ledger rows (duplicated from fks_tpu.obs.layout
#: .LAYOUT_AXES / .LAYOUT_COMPONENTS; tests/test_layout.py pins the two
#: copies) — which axis a LayoutSpec shards/vmaps, and who recorded it
LAYOUT_AXES = {"candidates", "scenarios", "segments"}
LAYOUT_COMPONENTS = {"eval", "code_eval", "gen_step", "suite_eval",
                     "serve", "vm_serve", "portfolio_serve", "probe",
                     "bench"}
#: legal ``reason`` values on a portfolio_route metric (duplicated from
#: fks_tpu.portfolio.router.ROUTE_REASONS; tests/test_portfolio.py pins
#: the two copies) — which routing rule placed the request
ROUTE_REASONS = {"pin", "affinity", "ab", "default", "fallback", "query"}
#: canonical LayoutSpec key shape (fks_tpu.obs.layout.LayoutSpec.key)
_LAYOUT_KEY_RE = re.compile(
    r"^shard\[[a-z_,]*\]\|vmap\[[a-z_,]*\]\|seg=\d+$")
METRIC_KIND_REQUIRED: Dict[str, Tuple[str, ...]] = {
    "generation": ("generation", "best_score"),
    "parity": ("generation", "checked", "max_drift"),
    # scenario-suite vocabulary (fks_tpu.scenarios): the materialized
    # suite summary (cli scenarios --run-dir) and the per-generation
    # robust-fitness breakdown the evolution loop records
    "scenario_suite": ("suite", "version", "scenarios"),
    "robust_fitness": ("generation", "suite", "aggregation", "scores"),
    # eval-budget allocation (fks_tpu.funsearch.budget): one record per
    # rung per generation — who entered, who survived to the next rung,
    # and what the rung cost in device wall seconds
    "budget_rung": ("generation", "rung", "entered", "survived",
                    "device_seconds"),
    # large-cluster scale tier (bench stage_scale1k / cli scale): the
    # completion-run throughput record must say what shape ran and which
    # scale knobs (prefilter / packed dtypes) produced the number
    "scale_tier": ("nodes", "pods", "events_per_sec",
                   "node_prefilter_k", "state_pack"),
    # champion serving (fks_tpu.serve): one record per served request —
    # what it cost (latency), how well the coalescer packed the batch
    # (occupancy), and which compiled shape bucket answered it
    "serve_request": ("request_id", "latency_ms", "batch_size",
                      "batch_occupancy", "bucket_pods", "bucket_lanes"),
    # repo lint gate (cli lint --run-dir): the AST findings + jaxpr-pin
    # drift messages and the overall verdict
    "lint_report": ("paths", "findings", "pin_drift", "ok"),
    # device-time attribution (fks_tpu.obs.profiler): one record per
    # completed stage (wall/compile/compute split, occupancy) plus the
    # stage="__total__" aggregate with the attributed fraction
    "device_profile": ("stage", "wall_seconds"),
    # cross-run history (cli trends): per-metric timeline + robust-z
    # regression alerts over the bench-results archive
    "trend_report": ("metric", "runs", "alerts"),
    # serve-tier SLO pricing (fks_tpu.obs.history.slo_burn): one record
    # per objective; burn_rate > 1 means the error budget is burning
    "slo_burn": ("slo", "target", "observed", "burn_rate"),
    # promotion pipeline (fks_tpu.pipeline.state): one record per
    # state-machine transition in promotion.jsonl, mirrored to the
    # flight recorder so a run dir tells the whole promotion story
    "promotion_event": ("attempt", "state", "champion"),
    # device-resident snapshot cache (fks_tpu.serve.artifact): ktable
    # reuse vs upload economics of the (sharded) serve path — the
    # exporter renders these as fks_serve_snapshot_cache_* gauges
    "snapshot_cache": ("hits", "misses", "entries", "hit_rate",
                       "h2d_bytes_per_query"),
    # executable-footprint ledger (fks_tpu.obs.memory): one record per
    # compiled executable — its memory_analysis() byte breakdown tagged
    # with the compiling tier and mesh layout
    "memory_footprint": ("component", "exe_key", "temp_bytes",
                         "argument_bytes", "output_bytes",
                         "generated_code_bytes"),
    # watermark sampler (fks_tpu.obs.memory): host RSS + per-device
    # normalized memory watermarks, per stage or per sampling interval
    "memory_watermark": ("stage", "host_rss_kb", "devices"),
    # leak sentinel (fks_tpu.obs.memory): live_arrays() drift across N
    # iterations of a fenced hot loop, judged against a tolerance
    "leak_check": ("loop", "iterations", "drift_count", "drift_bytes",
                   "ok"),
    # workload fingerprinting (fks_tpu.obs.workload): the windowed
    # distribution of query classes the serve path observed
    "workload_mix": ("window", "distinct", "classes"),
    # per-tenant accounting (fks_tpu.obs.workload): one row per tenant —
    # counters, latency, goodput, SLO burn, global fairness index
    "tenant_stats": ("tenant", "requests", "shed", "expired", "ewma_ms",
                     "p99_ms", "goodput_qps", "burn_rate",
                     "fairness_index"),
    # load generator (fks_tpu.obs.workload.run_loadgen): the sustained
    # multi-tenant run summary carrying the four compare-gated keys
    "loadgen_summary": ("mode", "requests", "loadgen_qps",
                        "loadgen_p99_ms", "loadgen_shed_rate",
                        "loadgen_fairness_index"),
    # portfolio routing (fks_tpu.portfolio.service): one row per routed
    # request — which slot answered it and which rule decided (slot -1
    # means the AOT coverage-fallback engine served it)
    "portfolio_route": ("request_id", "tenant", "slot", "reason"),
    # per-layout cost ledger (fks_tpu.obs.layout): one row per sharded
    # entry point wiring/launch, tagged with the canonical LayoutSpec key
    # and the mesh layout it ran on
    "layout_ledger": ("component", "layout_key", "mesh_layout"),
    # layout explorer (fks_tpu.obs.layout.explore_layouts): one warm
    # probe per valid layout of a (population x suite x mesh) shape
    "layout_probe": ("layout_key", "mesh_shape", "steady_seconds"),
}

#: an OpenMetrics sample line: name, optional {labels}, value, optional
#: ts, optional exemplar (`# {labels} value [ts]` — carried on histogram
#: buckets by the exporter's latency family to link hot buckets back to
#: a trace id)
_LABELSET = (r'\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
             r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\}')
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'                 # metric name
    rf'({_LABELSET})?'                           # labels
    r' -?[0-9.eE+-]+( [0-9.eE+-]+)?'             # value, optional ts
    rf'( # {_LABELSET} -?[0-9.eE+-]+( [0-9.eE+-]+)?)?$')  # exemplar


class SchemaError(ValueError):
    """A JSONL file violated the schema; ``str(e)`` says where and why."""


def check_jsonl(path: str, required: Sequence[str] = (),
                allow_empty: bool = True,
                tolerate_torn_tail: bool = True) -> List[dict]:
    """Parse ``path`` line by line, requiring each record to be a JSON
    object with every key in ``required``. Returns the parsed records.
    Raises ``SchemaError`` on the first violation (with line number)."""
    try:
        with open(path) as f:
            lines = f.readlines()
    except OSError as e:
        raise SchemaError(f"{path}: unreadable ({e})") from e
    if not lines and not allow_empty:
        raise SchemaError(f"{path}: empty")
    records: List[dict] = []
    last = len(lines) - 1
    for i, line in enumerate(lines):
        if not line.strip():
            if i == last:
                continue  # trailing newline
            raise SchemaError(f"{path}:{i + 1}: blank line mid-file")
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            if i == last and tolerate_torn_tail:
                break  # writer killed mid-append; the prefix is valid
            raise SchemaError(f"{path}:{i + 1}: unparsable ({e})") from e
        if not isinstance(rec, dict):
            raise SchemaError(f"{path}:{i + 1}: not a JSON object "
                              f"({type(rec).__name__})")
        missing = [k for k in required if k not in rec]
        if missing:
            raise SchemaError(f"{path}:{i + 1}: missing {missing} "
                              f"(has {sorted(rec)[:8]})")
        records.append(rec)
    return records


def check_kinds(path: str, records: List[dict],
                kind_required: Dict[str, Tuple[str, ...]]) -> None:
    """Per-kind key validation over parsed records: every record whose
    ``kind`` is in the known vocabulary must carry that kind's required
    keys. Raises ``SchemaError`` naming the record index."""
    for i, rec in enumerate(records):
        # engine_kind is optional everywhere it appears (promotion_event,
        # vm_swap, serve summaries), but when present it must name a real
        # champion-binding strategy
        if "engine_kind" in rec and rec["engine_kind"] not in ENGINE_KINDS:
            raise SchemaError(
                f"{path}: record {i + 1}: unknown engine_kind "
                f"{rec['engine_kind']!r} (expect one of "
                f"{sorted(ENGINE_KINDS)})")
        required = kind_required.get(rec.get("kind", ""))
        if not required:
            continue
        missing = [k for k in required if k not in rec]
        if missing:
            raise SchemaError(
                f"{path}: record {i + 1} (kind={rec.get('kind')!r}): "
                f"missing {missing}")
        if rec.get("kind") == "candidate_rejected":
            tax = rec.get("taxonomy")
            if tax not in CANDIDATE_REJECT_TAXONOMY:
                raise SchemaError(
                    f"{path}: record {i + 1}: unknown rejection taxonomy "
                    f"{tax!r} (expect one of "
                    f"{sorted(CANDIDATE_REJECT_TAXONOMY)})")
        elif rec.get("kind") in ("vm_swap", "slot_swap"):
            out = rec.get("outcome")
            if out not in VM_SWAP_OUTCOMES:
                raise SchemaError(
                    f"{path}: record {i + 1}: unknown {rec['kind']} "
                    f"outcome {out!r} (expect one of "
                    f"{sorted(VM_SWAP_OUTCOMES)})")
        elif rec.get("kind") == "portfolio_route":
            reason = rec.get("reason")
            if reason not in ROUTE_REASONS:
                raise SchemaError(
                    f"{path}: record {i + 1}: unknown route reason "
                    f"{reason!r} (expect one of {sorted(ROUTE_REASONS)})")
        elif rec.get("kind") == "memory_footprint":
            comp = rec.get("component")
            if comp not in MEMORY_COMPONENTS:
                raise SchemaError(
                    f"{path}: record {i + 1}: unknown memory component "
                    f"{comp!r} (expect one of {sorted(MEMORY_COMPONENTS)})")
        elif rec.get("kind") == "leak_check":
            loop = rec.get("loop")
            if loop not in LEAK_LOOPS:
                raise SchemaError(
                    f"{path}: record {i + 1}: unknown leak_check loop "
                    f"{loop!r} (expect one of {sorted(LEAK_LOOPS)})")
        elif rec.get("kind") == "loadgen_summary":
            mode = rec.get("mode")
            if mode not in LOADGEN_MODES:
                raise SchemaError(
                    f"{path}: record {i + 1}: unknown loadgen mode "
                    f"{mode!r} (expect one of {sorted(LOADGEN_MODES)})")
        elif rec.get("kind") in ("layout_ledger", "layout_probe"):
            lk = rec.get("layout_key")
            if not isinstance(lk, str) or not _LAYOUT_KEY_RE.match(lk):
                raise SchemaError(
                    f"{path}: record {i + 1}: malformed layout_key {lk!r} "
                    "(expect 'shard[...]|vmap[...]|seg=N')")
            for ax in rec.get("axes", []):
                if ax not in LAYOUT_AXES:
                    raise SchemaError(
                        f"{path}: record {i + 1}: unknown layout axis "
                        f"{ax!r} (expect one of {sorted(LAYOUT_AXES)})")
            if rec.get("kind") == "layout_ledger" \
                    and rec.get("component") not in LAYOUT_COMPONENTS:
                raise SchemaError(
                    f"{path}: record {i + 1}: unknown layout component "
                    f"{rec.get('component')!r} (expect one of "
                    f"{sorted(LAYOUT_COMPONENTS)})")
        elif rec.get("kind") == "decision_trace":
            _check_embedded_events(path, i, rec.get("events", []))
        elif rec.get("kind") == "trace_diff":
            div = rec.get("first_divergence") or {}
            _check_embedded_events(
                path, i, [r for r in (div.get("a"), div.get("b")) if r])


def _check_embedded_events(path: str, idx: int, rows) -> None:
    """Decision-trace rows embedded in a record must be dicts whose
    ``kind`` is in the engine's event vocabulary — an unknown kind means
    a corrupt trace (or a vocabulary drift between writer and checker)."""
    if not isinstance(rows, (list, tuple)):
        raise SchemaError(
            f"{path}: record {idx + 1}: embedded events not a list "
            f"({type(rows).__name__})")
    for j, row in enumerate(rows):
        if not isinstance(row, dict):
            raise SchemaError(
                f"{path}: record {idx + 1}: trace row {j + 1} not an "
                f"object ({type(row).__name__})")
        if row.get("kind") not in TRACE_EVENT_KINDS:
            raise SchemaError(
                f"{path}: record {idx + 1}: trace row {j + 1} has unknown "
                f"event kind {row.get('kind')!r} "
                f"(expect one of {sorted(TRACE_EVENT_KINDS)})")


def check_openmetrics(text: str, path: str = "<openmetrics>") -> int:
    """Validate OpenMetrics text exposition (``cli export-metrics``):
    declared-before-sampled families, well-formed sample lines, terminal
    ``# EOF``. Returns the sample count; raises ``SchemaError``."""
    lines = text.splitlines()
    stripped = [ln for ln in lines if ln.strip()]
    if not stripped or stripped[-1] != "# EOF":
        raise SchemaError(f"{path}: missing terminal '# EOF'")
    declared = set()
    samples = 0
    for i, line in enumerate(lines, 1):
        if not line.strip() or line == "# EOF":
            continue
        if line.startswith("# TYPE ") or line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 4:
                raise SchemaError(f"{path}:{i}: malformed header {line!r}")
            declared.add(parts[2])
            continue
        if line.startswith("#"):
            continue  # free-form comment
        if not _SAMPLE_RE.match(line):
            raise SchemaError(f"{path}:{i}: malformed sample {line!r}")
        name = re.split(r"[{ ]", line, 1)[0]
        # suffixed samples (_total, _bucket, ...) belong to the base family
        base = {name} | {name[: -len(sfx)]
                         for sfx in ("_total", "_sum", "_count", "_bucket")
                         if name.endswith(sfx)}
        if not (base & declared):
            raise SchemaError(f"{path}:{i}: sample for undeclared family "
                              f"{name!r} (no preceding # TYPE)")
        samples += 1
    if samples == 0:
        raise SchemaError(f"{path}: no samples")
    return samples


def check_run_dir(run_dir: str) -> Dict[str, int]:
    """Validate a FlightRecorder run directory; returns per-file record
    counts. Raises ``SchemaError`` on the first violation."""
    meta_path = os.path.join(run_dir, "meta.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except OSError as e:
        raise SchemaError(f"{meta_path}: unreadable ({e})") from e
    except json.JSONDecodeError as e:
        raise SchemaError(f"{meta_path}: unparsable ({e})") from e
    missing = [k for k in META_REQUIRED if k not in meta]
    if missing:
        raise SchemaError(f"{meta_path}: missing {missing}")
    counts = {"meta.json": 1}
    for name, required in RUN_DIR_REQUIRED.items():
        path = os.path.join(run_dir, name)
        if not os.path.exists(path):
            counts[name] = 0  # a run may legitimately record no metrics
            continue
        records = check_jsonl(path, required=required)
        check_kinds(path, records,
                    EVENT_KIND_REQUIRED if name == "events.jsonl"
                    else METRIC_KIND_REQUIRED)
        counts[name] = len(records)
    hb = os.path.join(run_dir, "heartbeat")
    if os.path.exists(hb):
        try:
            with open(hb) as f:
                beat = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SchemaError(f"{hb}: unparsable ({e})") from e
        if "ts" not in beat:
            raise SchemaError(f"{hb}: missing ['ts']")
        counts["heartbeat"] = 1
    return counts


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="JSONL files to check (e.g. benchmarks/results/"
                         "round*_tpu.jsonl)")
    ap.add_argument("--run-dir", default="",
                    help="validate a flight-recorder run directory instead")
    ap.add_argument("--require", default="",
                    help="comma-separated keys every record must carry")
    ap.add_argument("--openmetrics", default="",
                    help="validate an OpenMetrics text file "
                         "(cli export-metrics output)")
    args = ap.parse_args(argv)
    if not args.run_dir and not args.paths and not args.openmetrics:
        ap.error("give JSONL paths, --run-dir, or --openmetrics")
    required = [k for k in args.require.split(",") if k]
    rc = 0
    if args.run_dir:
        try:
            counts = check_run_dir(args.run_dir)
            print(f"{args.run_dir}: ok "
                  + " ".join(f"{k}={v}" for k, v in sorted(counts.items())))
        except SchemaError as e:
            print(f"SCHEMA: {e}", file=sys.stderr)
            rc = 1
    if args.openmetrics:
        try:
            with open(args.openmetrics) as f:
                n = check_openmetrics(f.read(), args.openmetrics)
            print(f"{args.openmetrics}: ok ({n} samples)")
        except OSError as e:
            print(f"SCHEMA: {args.openmetrics}: unreadable ({e})",
                  file=sys.stderr)
            rc = 1
        except SchemaError as e:
            print(f"SCHEMA: {e}", file=sys.stderr)
            rc = 1
    for path in args.paths:
        try:
            records = check_jsonl(path, required=required)
            print(f"{path}: ok ({len(records)} records)")
        except SchemaError as e:
            print(f"SCHEMA: {e}", file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
