"""Characterize the per-iteration floor of device loops on this backend.

probe_ops.py showed a ~35 us/step cost that is nearly independent of lane
count (16..256) AND of body op type (1-element scatter == full [L,8192]
dense blend). This probe isolates what that floor is made of and whether
``lax.scan`` with ``unroll`` amortizes it:

  A. empty while_loop, carry sizes from scalar to [256,8192]x2
  B. same bodies under scan(length, unroll in {1,4,8,16})
  C. a composite "sweep step" shaped like the planned scatter-free flat
     engine body (argmin over [L,Q] + one-hot blends + small node math),
     while vs scan-unroll, lanes in {64, 256}

Output feeds PROFILE.md and the flat-engine redesign.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

INF = jnp.iinfo(jnp.int32).max


def timed(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def while_loop(body, carry0, steps):
    def cond(c):
        return c[0] < steps

    def wrapped(c):
        i, x = c
        return (i + 1, body(i, x))

    return jax.lax.while_loop(cond, wrapped, (jnp.int32(0), carry0))


def scan_loop(body, carry0, steps, unroll):
    def f(c, _):
        i, x = c
        return (i + 1, body(i, x)), None

    out, _ = jax.lax.scan(f, (jnp.int32(0), carry0), None, length=steps,
                          unroll=unroll)
    return out


def part_a_b(steps):
    print("== A/B: empty-ish bodies, while vs scan(unroll) ==", flush=True)
    shapes = {
        "scalar": lambda: jnp.int32(0),
        "[64,16]": lambda: jnp.zeros((64, 16), jnp.int32),
        "[64,8192]": lambda: jnp.zeros((64, 8192), jnp.int32),
        "[64,8192]x2": lambda: (jnp.zeros((64, 8192), jnp.int32),
                                jnp.zeros((64, 8192), jnp.int32)),
        "[256,8192]x2": lambda: (jnp.zeros((256, 8192), jnp.int32),
                                 jnp.zeros((256, 8192), jnp.int32)),
    }

    def touch(i, c):
        # minimal data-dependent touch so nothing folds away
        return jax.tree_util.tree_map(lambda x: x + i, c)

    for name, mk in shapes.items():
        c0 = mk()
        t_w = timed(jax.jit(lambda c: while_loop(touch, c, steps)), c0)
        row = [f"while {t_w / steps * 1e6:8.2f}"]
        for u in (1, 8, 16):
            t_s = timed(jax.jit(
                lambda c, u=u: scan_loop(touch, c, steps, u)), c0)
            row.append(f"scan/u{u} {t_s / steps * 1e6:8.2f}")
        print(f"{name:14s} " + "  ".join(row) + "  us/step", flush=True)


def make_sweep_step(lanes, Q, N=16, G=8, F=8):
    """Composite body shaped like the planned scatter-free engine step."""
    key = jax.random.PRNGKey(0)
    pod_feat = jax.random.randint(key, (Q, 8), 1, 1000, dtype=jnp.int32)
    w = jax.random.normal(key, (lanes, F), jnp.float32)
    n_iota = jnp.arange(N, dtype=jnp.int32)
    g_iota = jnp.arange(G, dtype=jnp.uint32)
    q_iota = jnp.arange(Q, dtype=jnp.int32)

    def body(i, c):
        ev_t, aux, cpu, mem, gmil, hist = c
        # 1. fused reduce pass: pop argmin + pending-delete min
        s = jnp.argmin(ev_t, axis=-1).astype(jnp.int32)        # [L]
        t = jnp.min(ev_t, axis=-1)                             # [L]
        bdel = jnp.min(jnp.where(aux >= 0, ev_t, INF), axis=-1)
        # 2. gather pod features + aux at popped slot
        pf = pod_feat[s]                                       # [L,8]
        aux_s = jnp.take_along_axis(aux, s[:, None], axis=-1)[:, 0]
        is_del = aux_s >= 0
        # 3. refunds: one-hot dense adds over node axes
        a = jnp.where(is_del, aux_s >> 8, 0)
        bits = (aux_s & 255).astype(jnp.uint32)
        oh_a = (n_iota[None, :] == a[:, None]).astype(jnp.int32)
        oh_a = oh_a * is_del.astype(jnp.int32)[:, None]
        cpu = cpu + oh_a * pf[:, 0:1]
        mem = mem + oh_a * pf[:, 1:2]
        selb = ((bits[:, None] >> g_iota[None, :]) & 1).astype(jnp.int32)
        gmil = gmil + oh_a[:, :, None] * (pf[:, 2:3, None] * selb[:, None, :])
        # 4. policy: linear features over node state
        feats = jnp.stack([
            cpu.astype(jnp.float32), mem.astype(jnp.float32),
            gmil.sum(-1).astype(jnp.float32),
            (cpu - pf[:, 0:1]).astype(jnp.float32),
            (mem - pf[:, 1:2]).astype(jnp.float32),
            gmil.max(-1).astype(jnp.float32),
            jnp.broadcast_to(t[:, None], cpu.shape).astype(jnp.float32),
            jnp.broadcast_to(pf[:, 3:4], cpu.shape).astype(jnp.float32),
        ], axis=-1)                                            # [L,N,F]
        scores = jnp.einsum("lnf,lf->ln", feats, w)
        wn = jnp.argmax(scores, axis=-1).astype(jnp.int32)     # [L]
        placed = (~is_del) & (jnp.max(scores, axis=-1) > 0)
        # 5. allocator: sort one gathered gpu row
        grow = jnp.take_along_axis(
            gmil, wn[:, None, None], axis=1)[:, 0, :]          # [L,G]
        order = jnp.argsort(grow, axis=-1)
        sel = order < pf[:, 4:5] % 3
        nbits = jnp.sum(jnp.where(sel, jnp.uint32(1) << g_iota[None, :],
                                  jnp.uint32(0)), axis=-1, dtype=jnp.uint32)
        # 6. place: one-hot dense node updates
        oh_w = (n_iota[None, :] == wn[:, None]).astype(jnp.int32)
        oh_w = oh_w * placed.astype(jnp.int32)[:, None]
        cpu = cpu - oh_w * pf[:, 0:1]
        mem = mem - oh_w * pf[:, 1:2]
        gmil = gmil - oh_w[:, :, None] * (pf[:, 2:3, None] * sel[:, None, :])
        # 7. hist blend + frag reduce
        hb = jnp.clip(pf[:, 5], 0, hist.shape[-1] - 1)
        hist = hist + ((jnp.arange(hist.shape[-1])[None, :] == hb[:, None])
                       & (~placed & ~is_del)[:, None]).astype(jnp.int32)
        mn = jnp.argmax(hist > 0, axis=-1)
        frag = jnp.sum(jnp.where((gmil > 0) & (gmil < mn[:, None, None]),
                                 gmil, 0), axis=(1, 2))
        # 8. slot blend: one fused pass writing ev_t + aux
        newt = jnp.where(placed, t + pf[:, 6], INF)
        newa = jnp.where(placed, (wn << 8) | nbits.astype(jnp.int32), -1)
        m = q_iota[None, :] == s[:, None]
        ev_t = jnp.where(m, newt[:, None], ev_t)
        aux = jnp.where(m, newa[:, None] + (frag[:, None] & 0), aux)
        return (ev_t, aux, cpu, mem, gmil, hist)

    def init():
        kt = jax.random.randint(key, (lanes, Q), 1, 1 << 24, dtype=jnp.int32)
        return (kt, jnp.full((lanes, Q), -1, jnp.int32),
                jnp.full((lanes, N), 64000, jnp.int32),
                jnp.full((lanes, N), 256000, jnp.int32),
                jnp.full((lanes, N, G), 1000, jnp.int32),
                jnp.zeros((lanes, 1001), jnp.int32))

    return body, init


def part_c(steps):
    print("== C: composite sweep-step prototype ==", flush=True)
    for lanes in (64, 256):
        body, init = make_sweep_step(lanes, 8192)
        c0 = init()
        t_w = timed(jax.jit(lambda c: while_loop(body, c, steps)), c0)
        print(f"lanes={lanes:4d} while    {t_w / steps * 1e6:8.2f} us/step"
              f"  -> {lanes / (t_w / steps * 32608):7.1f} evals/s proj",
              flush=True)
        for u in (4, 8):
            t_s = timed(jax.jit(
                lambda c, u=u: scan_loop(body, c, steps, u)), c0)
            print(f"lanes={lanes:4d} scan/u{u}  {t_s / steps * 1e6:8.2f}"
                  f" us/step  -> {lanes / (t_s / steps * 32608):7.1f}"
                  " evals/s proj", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=2048)
    ap.add_argument("--parts", type=str, default="abc")
    args = ap.parse_args()
    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind}); steps={args.steps}",
          file=sys.stderr)
    if "a" in args.parts or "b" in args.parts:
        part_a_b(args.steps)
    if "c" in args.parts:
        part_c(args.steps)


if __name__ == "__main__":
    main()
