"""Run the round's full TPU measurement session, wedge-safely.

Each stage runs in its own subprocess with a timeout so one killed/wedged
device call cannot take down the session; results append to a JSONL file
(benchmarks/results/round2_tpu.jsonl by default) as they land. Stages:

  probe     device aliveness + kind
  flat      flat-engine population throughput (pop 256, ctime off)
  fused64   fused-kernel population throughput, pop 64
  fused256  fused-kernel population throughput, pop 256
  gate      fused-vs-flat same-device parity gate (8 candidates)
  tiers     measure_tiers (VM / jit / parametric / evolve-gen) on device
  scale     synthetic 1000x20000 single-chip flat-engine run
  scale100k BASELINE config-5 shape: 1000 nodes x 100k pods, single chip

Usage: python -u tools/tpu_session.py [stage ...]   (default: all)
Output file: benchmarks/results/round3_tpu.jsonl (FKS_SESSION_OUT to override).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.environ.get("FKS_SESSION_OUT") or os.path.join(
    REPO, "benchmarks", "results", "round3_tpu.jsonl")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def record(obj):
    obj = {"ts": round(time.time(), 1), **obj}
    with open(OUT, "a") as f:
        f.write(json.dumps(obj) + "\n")
    print(json.dumps(obj), flush=True)


def run_stage(name, code, timeout_s):
    t0 = time.time()
    # start_new_session so a timeout kills the WHOLE process group —
    # otherwise grandchildren (the tiers stage's measure_tiers child)
    # would survive and keep the device wedged
    import signal
    proc = subprocess.Popen([sys.executable, "-u", "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, cwd=REPO, start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.communicate()
        log(f"[{name}] TIMEOUT after {timeout_s}s (process group killed)")
        record({"stage": name, "ok": False, "error": "timeout",
                "wall_s": round(time.time() - t0, 1)})
        return False
    rc = proc.returncode
    log(f"[{name}] rc={rc} ({time.time() - t0:.0f}s)\n{(err or '')[-2500:]}")
    payload = None
    for line in reversed((out or "").strip().splitlines()):
        try:
            cand = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(cand, dict):  # stray numbers/nulls are not results
            payload = cand
            break
    record({"stage": name, "ok": rc == 0 and payload is not None,
            "wall_s": round(time.time() - t0, 1),
            **({"result": payload} if payload is not None else {}),
            **({} if rc == 0 else {"rc": rc})})
    return rc == 0


COMMON = """
import json, time
import jax, numpy as np
from fks_tpu.data import TraceParser
from fks_tpu.models import parametric
from fks_tpu.parallel import make_population_eval
from fks_tpu.sim.engine import SimConfig

def bench_pop(engine, pop, reps=2):
    wl = TraceParser().parse_workload()
    cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(0), pop, noise=0.1)
    ev = make_population_eval(wl, cfg=cfg, engine=engine)
    t0 = time.perf_counter()
    res = ev(params); jax.block_until_ready(res.policy_score)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res = ev(params); jax.block_until_ready(res.policy_score)
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {"engine": engine, "pop": pop, "compile_s": round(compile_s, 2),
            "best_s": round(best, 3), "evals_per_sec": round(pop / best, 1),
            "truncated": int(np.asarray(res.truncated).sum()),
            "events_mean": int(np.asarray(res.events_processed).mean())}
"""

STAGES = {
    "probe": (90, """
import json, jax
d = jax.devices()[0]
print(json.dumps({"platform": d.platform, "kind": d.device_kind}))
"""),
    "flat": (600, COMMON + """
print(json.dumps(bench_pop("flat", 256)))
"""),
    "fused64": (600, COMMON + """
print(json.dumps(bench_pop("fused", 64)))
"""),
    "fused256": (900, COMMON + """
print(json.dumps(bench_pop("fused", 256)))
"""),
    "gate": (600, """
import json
import jax, numpy as np
from fks_tpu.data import TraceParser
from fks_tpu.models import parametric
from fks_tpu.parallel import make_population_eval
from fks_tpu.sim.engine import SimConfig
wl = TraceParser().parse_workload()
cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
params = parametric.init_population(jax.random.PRNGKey(0), 8, noise=0.1)
a = make_population_eval(wl, cfg=cfg, engine="fused")(params)
b = make_population_eval(wl, cfg=cfg, engine="flat")(params)
jax.block_until_ready((a.policy_score, b.policy_score))
sa, sb = np.asarray(a.policy_score), np.asarray(b.policy_score)
ok = (np.allclose(sa, sb, rtol=2e-5, atol=2e-5)
      and np.array_equal(np.asarray(a.scheduled_pods),
                         np.asarray(b.scheduled_pods))
      and np.array_equal(np.asarray(a.events_processed),
                         np.asarray(b.events_processed)))
print(json.dumps({"gate_ok": bool(ok), "fused": sa.round(4).tolist(),
                  "flat": sb.round(4).tolist()}))
assert ok
"""),
    "tiers": (1200, f"""
import subprocess, sys, os
r = subprocess.run([sys.executable, "tools/measure_tiers.py",
                    "--engine", "flat", "--pop", "16",
                    "--metrics", {OUT!r}],
                   text=True, capture_output=True)
sys.stderr.write(r.stderr[-2000:])
print(r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{{}}")
sys.exit(r.returncode)
"""),
}

# synthetic-scale stages share one script template (nodes, pods, pop).
# scale100k is BASELINE config 5's trace-length axis on one chip — the
# mesh spreads population, not the sequential event scan, so per-chip
# cost is the number that matters (round-2 verdict ask #6).
_SCALE_TEMPLATE = """
import json, time
import jax, numpy as np
from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.models import parametric
from fks_tpu.parallel import make_population_eval
from fks_tpu.sim.engine import SimConfig
nodes, pods, pop = {nodes}, {pods}, {pop}
wl = synthetic_workload(nodes, pods, seed=0)
cfg = SimConfig(track_ctime=False)
params = parametric.init_population(jax.random.PRNGKey(0), pop, noise=0.1)
ev = make_population_eval(wl, cfg=cfg, engine="flat")
t0 = time.perf_counter()
res = ev(params); jax.block_until_ready(res.policy_score)
compile_s = time.perf_counter() - t0
t0 = time.perf_counter()
res = ev(params); jax.block_until_ready(res.policy_score)
best = time.perf_counter() - t0
print(json.dumps({{"nodes": nodes, "pods": pods, "pop": pop,
                  "compile_s": round(compile_s, 1), "best_s": round(best, 2),
                  "evals_per_sec": round(pop / best, 3)}}))
"""

STAGES["scale"] = (900, _SCALE_TEMPLATE.format(nodes=1000, pods=20000, pop=8))
STAGES["scale100k"] = (
    1800, _SCALE_TEMPLATE.format(nodes=1000, pods=100_000, pop=8))

ORDER = ["probe", "flat", "fused64", "gate", "fused256", "tiers", "scale",
         "scale100k"]


def main():
    stages = sys.argv[1:] or ORDER
    unknown = [s for s in stages if s not in STAGES]
    if unknown:
        log(f"unknown stage(s) {unknown}; valid: {list(STAGES)}")
        return 2
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    for name in stages:
        timeout_s, code = STAGES[name]
        ok = run_stage(name, code, timeout_s)
        if name == "probe" and not ok:
            log("device unreachable; aborting session")
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
