"""Run the round's full TPU measurement session, wedge-safely.

Each stage runs in its own subprocess with a timeout so one killed/wedged
device call cannot take down the session; results append to a JSONL file
(benchmarks/results/round2_tpu.jsonl by default) as they land. Stages:

  probe     device aliveness + kind
  flat      flat-engine population throughput (pop 256, ctime off)
  fused64   fused-kernel population throughput, pop 64
  fused256  fused-kernel population throughput, pop 256
  gate      fused-vs-flat same-device parity gate (8 candidates)
  tiers     measure_tiers (VM / jit / parametric / evolve-gen) on device
  vmbatch   population-batched VM: a generation of LLM code candidates as
            ONE device launch, pops 8/32/96 (round-4 verdict ask #2);
            reports code-candidate evals/s vs the reference's ~40/s/host
  flatseed  flat-engine throughput with a SEEDED population (the 0.5365
            champion's neighborhood, as real search would run) — the
            de-noised counterpart of the random-seeded ``flat`` stage
            (round-4 verdict ask #6); reports truncation counts
  profile256  per-component step-cost profile at pop 256 on the chip
            (tools/profile_step.py --json; round-4 verdict ask #5)
  evolve    full evolution loop on-chip: 12 FakeLLM generations (flat
            engine, batched VM fitness), checkpoint, then RESUME for 2
            more generations (round-4 verdict ask #4)
  scale     synthetic 1000x20000 single-chip flat-engine run
  scale100k BASELINE config-5 shape: 1000 nodes x 100k pods, single chip

Usage: python -u tools/tpu_session.py [stage ...]   (default: all)
Output file: benchmarks/results/round5_tpu.jsonl (FKS_SESSION_OUT to override).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.environ.get("FKS_SESSION_OUT") or os.path.join(
    REPO, "benchmarks", "results", "round5_tpu.jsonl")


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def record(obj):
    obj = {"ts": round(time.time(), 1), **obj}
    with open(OUT, "a") as f:
        f.write(json.dumps(obj) + "\n")
    print(json.dumps(obj), flush=True)


# bench.py defaults to the same path for the driver's standalone run —
# keep the two in sync if this ever moves
CACHE_DIR = os.path.join(REPO, "benchmarks", "results", ".jax_cache")


def run_stage(name, code, timeout_s):
    t0 = time.time()
    # start_new_session so a timeout kills the WHOLE process group —
    # otherwise grandchildren (the tiers stage's measure_tiers child)
    # would survive and keep the device wedged
    import signal
    # persistent compilation cache shared by every stage process: a
    # wedge that closes the window mid-session costs the remaining
    # MEASUREMENTS, not the compiles already paid for — the re-fired
    # session resumes from warm XLA artifacts (the round-4 first window
    # died 5 stages in; each stage had recompiled from scratch)
    env = {**os.environ,
           "JAX_COMPILATION_CACHE_DIR": CACHE_DIR,
           "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1",
           "FKS_SESSION_OUT": OUT}
    proc = subprocess.Popen([sys.executable, "-u", "-c", code],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, cwd=REPO, env=env,
                            start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.communicate()
        log(f"[{name}] TIMEOUT after {timeout_s}s (process group killed)")
        record({"stage": name, "ok": False, "error": "timeout",
                "wall_s": round(time.time() - t0, 1)})
        return False
    rc = proc.returncode
    log(f"[{name}] rc={rc} ({time.time() - t0:.0f}s)\n{(err or '')[-2500:]}")
    payload = None
    for line in reversed((out or "").strip().splitlines()):
        try:
            cand = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(cand, dict):  # stray numbers/nulls are not results
            payload = cand
            break
    record({"stage": name, "ok": rc == 0 and payload is not None,
            "wall_s": round(time.time() - t0, 1),
            **({"result": payload} if payload is not None else {}),
            **({} if rc == 0 else {"rc": rc})})
    return rc == 0


COMMON = """
import json, time
import jax, numpy as np
from fks_tpu.data import TraceParser
from fks_tpu.models import parametric
from fks_tpu.parallel import make_population_eval
from fks_tpu.sim.engine import SimConfig

def bench_pop(engine, pop, reps=2):
    wl = TraceParser().parse_workload()
    cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(0), pop, noise=0.1)
    ev = make_population_eval(wl, cfg=cfg, engine=engine)
    t0 = time.perf_counter()
    res = ev(params); jax.block_until_ready(res.policy_score)
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        res = ev(params); jax.block_until_ready(res.policy_score)
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {"engine": engine, "pop": pop, "compile_s": round(compile_s, 2),
            "best_s": round(best, 3), "evals_per_sec": round(pop / best, 1),
            "truncated": int(np.asarray(res.truncated).sum()),
            "events_mean": int(np.asarray(res.events_processed).mean())}
"""

STAGES = {
    "probe": (90, """
import json, jax
d = jax.devices()[0]
print(json.dumps({"platform": d.platform, "kind": d.device_kind}))
"""),
    "flat": (600, COMMON + """
print(json.dumps(bench_pop("flat", 256)))
"""),
    "fused64": (600, COMMON + """
print(json.dumps(bench_pop("fused", 64)))
"""),
    "fused256": (900, COMMON + """
print(json.dumps(bench_pop("fused", 256)))
"""),
    "gate": (600, """
import json
import jax, numpy as np
from fks_tpu.data import TraceParser
from fks_tpu.models import parametric
from fks_tpu.parallel import make_population_eval
from fks_tpu.sim.engine import SimConfig
wl = TraceParser().parse_workload()
cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
params = parametric.init_population(jax.random.PRNGKey(0), 8, noise=0.1)
a = make_population_eval(wl, cfg=cfg, engine="fused")(params)
b = make_population_eval(wl, cfg=cfg, engine="flat")(params)
jax.block_until_ready((a.policy_score, b.policy_score))
sa, sb = np.asarray(a.policy_score), np.asarray(b.policy_score)
ok = (np.allclose(sa, sb, rtol=2e-5, atol=2e-5)
      and np.array_equal(np.asarray(a.scheduled_pods),
                         np.asarray(b.scheduled_pods))
      and np.array_equal(np.asarray(a.events_processed),
                         np.asarray(b.events_processed)))
print(json.dumps({"gate_ok": bool(ok), "fused": sa.round(4).tolist(),
                  "flat": sb.round(4).tolist()}))
assert ok
"""),
    "tiers": (1200, f"""
import subprocess, sys, os
r = subprocess.run([sys.executable, "tools/measure_tiers.py",
                    "--engine", "flat", "--pop", "16",
                    "--metrics", {OUT!r}],
                   text=True, capture_output=True)
sys.stderr.write(r.stderr[-2000:])
print(r.stdout.strip().splitlines()[-1] if r.stdout.strip() else "{{}}")
sys.exit(r.returncode)
"""),
    "vmbatch": (1500, """
import json, os, time
import jax, numpy as np
from fks_tpu.data import TraceParser
from fks_tpu.funsearch import llm, template, vm
from fks_tpu.sim import flat
from fks_tpu.sim.engine import SimConfig

OUT = os.environ["FKS_SESSION_OUT"]
def land(obj):   # partial results survive a mid-stage wedge
    with open(OUT, "a") as f:
        f.write(json.dumps({"ts": round(time.time(), 1), **obj}) + "\\n")

wl = TraceParser().parse_workload()
cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
n, g = wl.cluster.n_padded, wl.cluster.g_padded
CAP = 256   # FakeLLM gpu-loop candidates lower to ~70-200 ops
NEED = 2 * 96   # warm + disjoint timed set for the largest pop

fake = llm.FakeLLM(seed=7, junk_rate=0.0)
progs, lower_s = [], []
for _ in range(12 * NEED):  # bounded: junk/too-long candidates are skipped
    if len(progs) >= NEED:
        break
    c = template.fill_template(fake.complete("x"))
    t0 = time.perf_counter()
    try:
        p = vm.compile_policy(c, n, g, capacity=CAP)
    except Exception:
        continue
    lower_s.append(time.perf_counter() - t0)
    progs.append(p)
assert len(progs) >= NEED, f"only {len(progs)} VM-able candidates"
land({"stage": "vmbatch_lowering", "ok": True, "n_cands": len(progs),
      "host_lowering_ms_per_cand":
          round(1e3 * float(np.mean(lower_s)), 1)})

# segmented: no single device call exceeds ~seg_steps events, so the
# tunnel's ~60 s execution kill window cannot kill a full-trace launch
run = flat.make_segmented_population_run(wl, vm.score_static, cfg,
                                         seg_steps=4096)
state0 = flat.initial_state(wl, cfg)
summary = {"capacity": CAP}
# smallest-first: pop 8 is EXACTLY one reference generation (<=8
# candidates/gen) and the cheapest compile — if the tunnel dies later,
# the verdict answer has already landed; 32/96 are the round-4 verdict
# ask-#2 sizes (how the apples-to-apples margin scales with batch)
for pop in (8, 32, 96):
    t0 = time.perf_counter()
    res = run(vm.stack_programs(progs[:pop], capacity=CAP), state0)
    jax.block_until_ready(res.policy_score)
    compile_s = time.perf_counter() - t0
    batch = vm.stack_programs(progs[pop:2 * pop], capacity=CAP)
    t0 = time.perf_counter()
    res = run(batch, state0)
    jax.block_until_ready(res.policy_score)
    best = time.perf_counter() - t0
    row = {"stage": f"vmbatch_pop{pop}", "ok": True, "pop": pop,
           "capacity": CAP, "first_launch_s": round(compile_s, 2),
           "best_s": round(best, 3),
           "code_evals_per_sec": round(pop / best, 1),
           "vs_reference_host_40eps": round(pop / best / 40.0, 2),
           "scores_sample":
               np.asarray(res.policy_score)[:4].round(4).tolist()}
    land(row)
    summary[f"pop{pop}_evals_per_sec"] = row["code_evals_per_sec"]
print(json.dumps(summary))
"""),
    "flatseed": (600, COMMON + """
import jax.numpy as jnp
# de-noised throughput: population = the 0.5365 champion's neighborhood
# (how real search actually samples), not random-seeded candidates whose
# degenerate members retry to the step budget and drag their lockstep
# lanes (round-4 flat row: 96/256 truncated, events_mean 25834 vs ~16.4k)
champ = np.load("benchmarks/results/r3_anneal.npz")["best_params"]
def seeded_pop(pop, noise=0.05):
    key = jax.random.PRNGKey(5)
    base = jnp.broadcast_to(jnp.asarray(champ), (pop, champ.shape[0]))
    jitter = noise * jax.random.normal(key, base.shape, base.dtype)
    keep = jnp.arange(pop) < 1   # lane 0 = the champion itself, pure
    return jnp.where(keep[:, None], base, base + jitter)
wl = TraceParser().parse_workload()
cfg = SimConfig(max_steps=4 * wl.num_pods, track_ctime=False)
params = seeded_pop(256)
ev = make_population_eval(wl, cfg=cfg, engine="flat")
t0 = time.perf_counter()
res = ev(params); jax.block_until_ready(res.policy_score)
compile_s = time.perf_counter() - t0
times = []
for _ in range(2):
    t0 = time.perf_counter()
    res = ev(params); jax.block_until_ready(res.policy_score)
    times.append(time.perf_counter() - t0)
best = min(times)
print(json.dumps({
    "engine": "flat", "pop": 256, "seeded": "champion_0.5365_noise0.05",
    "compile_s": round(compile_s, 2), "best_s": round(best, 3),
    "evals_per_sec": round(256 / best, 1),
    "truncated": int(np.asarray(res.truncated).sum()),
    "events_mean": int(np.asarray(res.events_processed).mean()),
    "score_champion_lane": round(float(np.asarray(res.policy_score)[0]), 4),
    "score_max": round(float(np.asarray(res.policy_score).max()), 4)}))
"""),
    "profile256": (900, """
import json, subprocess, sys
r = subprocess.run([sys.executable, "tools/profile_step.py",
                    "--steps", "2048", "--lanes", "256", "--json"],
                   text=True, capture_output=True)
sys.stderr.write((r.stderr or "")[-2000:])
lines = [l for l in (r.stdout or "").strip().splitlines() if l.startswith("{")]
print(lines[-1] if lines else "{}")
sys.exit(r.returncode)
"""),
    "evolve": (2700, f"""
import json, os, subprocess, sys, time
ck = "benchmarks/results/r5_evolve_ck.json"
if os.path.exists(ck):   # a stale checkpoint would resume mid-way and
    os.remove(ck)        # inflate the reported generations/minute
t0 = time.perf_counter()
r = subprocess.run([sys.executable, "-u", "-m", "fks_tpu.cli", "evolve",
                    "--fake-llm", "--engine", "flat",
                    "--generations", "12", "--checkpoint", ck,
                    "--out", "policies/discovered",
                    "--metrics", {OUT!r}],
                   text=True, capture_output=True)
sys.stderr.write((r.stderr or "")[-2500:])
wall1 = time.perf_counter() - t0
if r.returncode != 0:
    sys.exit(r.returncode)
t0 = time.perf_counter()
r2 = subprocess.run([sys.executable, "-u", "-m", "fks_tpu.cli", "evolve",
                     "--fake-llm", "--engine", "flat",
                     "--generations", "14", "--checkpoint", ck,
                     "--metrics", {OUT!r}],
                    text=True, capture_output=True)
sys.stderr.write((r2.stderr or "")[-1500:])
wall2 = time.perf_counter() - t0
best = [l for l in (r.stdout or "").splitlines() if "best fitness" in l]
print(json.dumps({{"generations": 12, "wall_s": round(wall1, 1),
                  "gens_per_min": round(12 * 60 / wall1, 2),
                  "resume_ok": r2.returncode == 0,
                  "resume_wall_s": round(wall2, 1),
                  "best_line": best[-1] if best else None}}))
sys.exit(r2.returncode)
"""),
}

# synthetic-scale stages share one script template (nodes, pods, pop).
# scale100k is BASELINE config 5's trace-length axis on one chip — the
# mesh spreads population, not the sequential event scan, so per-chip
# cost is the number that matters (round-2 verdict ask #6).
_SCALE_TEMPLATE = """
import json, sys, time
import jax, numpy as np
import jax.numpy as jnp
from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.models import parametric
from fks_tpu.sim import flat
from fks_tpu.sim.engine import SimConfig
nodes, pods, pop = {nodes}, {pods}, {pop}
wl = synthetic_workload(nodes, pods, seed=0)
# scale-tier knobs recorded in the payload either way, so rounds with
# different defaults stay comparable (bench.py stage payloads do the same)
cfg = SimConfig(track_ctime=False, node_prefilter_k={prefilter_k},
                state_pack={state_pack})
params = parametric.init_population(jax.random.PRNGKey(0), pop, noise=0.1)
# segmented so no single device call outlives the tunnel's ~60 s
# execution kill window (a 100k-pod trace is ~200k+ sequential events)
run = flat.make_segmented_population_run(wl, parametric.score, cfg,
                                         seg_steps=16384)
state0 = flat.initial_state(wl, cfg)
t0 = time.perf_counter()
res = run(params, state0); jax.block_until_ready(res.policy_score)
compile_s = time.perf_counter() - t0
t0 = time.perf_counter()
res = run(params, state0); jax.block_until_ready(res.policy_score)
best = time.perf_counter() - t0
# XLA's static cost model for the hot segment program (AOT: reuses the
# jit's shapes, no extra device time); best-effort — a backend that
# doesn't publish the analysis just omits the fields
cost = {{}}
try:
    bstate0 = jax.tree_util.tree_map(
        lambda leaf: jnp.broadcast_to(leaf, (pop,) + leaf.shape), state0)
    c = run.advance.lower(params, bstate0).compile().cost_analysis()
    if isinstance(c, (list, tuple)):
        c = c[0] if c else {{}}
    if isinstance(c, dict):
        for key, name in (("flops", "cost_flops"),
                          ("bytes accessed", "cost_bytes_accessed")):
            if c.get(key) is not None:
                cost[name] = float(c[key])
except Exception as e:
    sys.stderr.write("cost_analysis unavailable: %r\\n" % (e,))
print(json.dumps({{"nodes": nodes, "pods": pods, "pop": pop,
                  "compile_s": round(compile_s, 1), "best_s": round(best, 2),
                  "evals_per_sec": round(pop / best, 3),
                  "node_prefilter_k": cfg.node_prefilter_k,
                  "state_pack": cfg.state_pack, **cost}}))
"""

STAGES["scale"] = (900, _SCALE_TEMPLATE.format(
    nodes=1000, pods=20000, pop=8, prefilter_k=0, state_pack=False))
STAGES["scale100k"] = (1800, _SCALE_TEMPLATE.format(
    nodes=1000, pods=100_000, pop=8, prefilter_k=0, state_pack=False))

# value-priority order: the measurements no round has ever landed come
# first (fused kernel + code candidates, round-4 verdict asks #1/#2), so
# a short healthy window banks the most novel evidence; flat/flatseed
# re-measure the headline with round-5 context (seeded de-noising)
ORDER = ["probe", "fused64", "gate", "fused256", "vmbatch", "flat",
         "flatseed", "profile256", "tiers", "evolve", "scale", "scale100k"]


def done_stages():
    """Stage names with an ok:true record already in OUT (this round)."""
    done = set()
    try:
        with open(OUT) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if r.get("ok"):
                    done.add(r.get("stage"))
    except FileNotFoundError:
        pass
    return done


def device_healthy(timeout_s=90):
    """One tiny real computation in a fresh killable process group."""
    t, code = STAGES["probe"]
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c",
         code + "\nimport jax.numpy as jnp\n"
                "x = jnp.ones((8, 128)); (x @ x.T).sum().block_until_ready()"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, cwd=REPO,
        start_new_session=True)
    try:
        proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        import signal
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.communicate()
        return False
    return proc.returncode == 0


def main():
    stages = sys.argv[1:] or ORDER
    unknown = [s for s in stages if s not in STAGES]
    if unknown:
        log(f"unknown stage(s) {unknown}; valid: {list(STAGES)}")
        return 2
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    force = os.environ.get("FKS_SESSION_FORCE") == "1"
    all_ok = True
    for name in stages:
        if not force and name != "probe" and name in done_stages():
            log(f"[{name}] already landed ok; skipping "
                "(FKS_SESSION_FORCE=1 to re-measure)")
            continue
        timeout_s, code = STAGES[name]
        ok = run_stage(name, code, timeout_s)
        if name == "probe" and not ok:
            # same exit as a mid-session wedge: the device is unreachable,
            # so the caller must not spend the window on hybrid/bench
            log("device unreachable; aborting session")
            return 3
        if not ok:
            all_ok = False
            # distinguish "this stage is broken" from "the tunnel died
            # under it": a wedged device fails every later stage with
            # noise failures (the round-4 first window burned tiers
            # against vmbatch's wedge). Abort so the watcher re-arms.
            if not device_healthy():
                record({"stage": "session_abort", "ok": False,
                        "after": name,
                        "reason": "device wedged mid-session"})
                log(f"device wedged after [{name}]; aborting session")
                return 3
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
