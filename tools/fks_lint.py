#!/usr/bin/env python
"""Standalone entry for the repo lint gate — ``python tools/fks_lint.py``
is ``python -m fks_tpu.cli lint`` with the same flags and exit codes
(0 clean / 1 findings-or-drift / 2 error), for CI configs that invoke
tools/ scripts directly. ``--cpu`` is NOT implied; pass it where the TPU
tunnel must be skipped."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fks_tpu.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["lint", *sys.argv[1:]]))
