"""Primitive cost model for the per-event step on the current device.

Measures the building blocks a fast event engine could be made of, each as
a jitted ``lax.while_loop`` over ``--steps`` iterations at several lane
(population) widths:

  dense16     predicated dense update of a [L,16] i32 row (no scatter)
  dense-grid  predicated dense update of a [L,16,8] grid
  scat1-8k    batched 1-element scatter into [L,8192]
  scat15-8k   batched 15-element scatter into [L,8192] (heap-sift shape)
  scat15u-8k  same with unique_indices=True
  gath15-8k   batched 15-element gather from [L,8192]
  chain14     14 DEPENDENT rounds of 2-wide dynamic-slice gathers
              (the heap pop descent's critical path shape)
  argmin256   masked argmin over a [L,256] ring buffer
  tape-read   indexed row read from a static [40k, 8] tape
  dense-8k    full dense blend of [L,8192] (scatter-free waiting-set upd)

Output feeds PROFILE.md; design decisions reference these numbers.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timed(fn, *args):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def loop(body, carry0, steps):
    def cond(c):
        return c[0] < steps

    def wrapped(c):
        i, x = c
        return (i + 1, body(i, x))

    return jax.lax.while_loop(cond, wrapped, (jnp.int32(0), carry0))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=2048)
    ap.add_argument("--lanes", type=str, default="16,256,1024")
    args = ap.parse_args()
    steps = args.steps

    dev = jax.devices()[0]
    print(f"device: {dev.platform} ({dev.device_kind}); steps={steps}",
          file=sys.stderr)

    P = 8192
    tape = jnp.arange(40000 * 8, dtype=jnp.int32).reshape(40000, 8)

    for lanes in [int(x) for x in args.lanes.split(",")]:
        key = jax.random.PRNGKey(0)
        row = jnp.zeros((lanes, 16), jnp.int32)
        grid = jnp.zeros((lanes, 16, 8), jnp.int32)
        big = jnp.zeros((lanes, P), jnp.int32)
        ring = jnp.zeros((lanes, 256), jnp.int32)
        idx1 = jax.random.randint(key, (lanes,), 0, P)
        idx15 = jax.random.randint(key, (lanes, 15), 0, P)

        def mk(name):
            if name == "dense16":
                def body(i, c):
                    b = (i + jnp.arange(lanes)) % 16
                    return c + jnp.where(jnp.arange(16)[None, :] == b[:, None], i, 0)
                return body, row
            if name == "dense-grid":
                def body(i, c):
                    b = (i + jnp.arange(lanes)) % 16
                    oh = (jnp.arange(16)[None, :] == b[:, None])
                    return c + jnp.where(oh[:, :, None], i, 0)
                return body, grid
            if name == "scat1-8k":
                def body(i, c):
                    ix = (idx1 + i) % P
                    return jax.vmap(lambda a, j, v: a.at[j].set(v))(
                        c, ix, i + jnp.arange(lanes))
                return body, big
            if name in ("scat15-8k", "scat15u-8k"):
                uniq = name.endswith("u-8k")
                def body(i, c):
                    ix = (idx15 + i) % P
                    vals = jnp.broadcast_to(i, (lanes, 15)) + ix
                    return jax.vmap(lambda a, j, v: a.at[j].set(
                        v, mode="drop", unique_indices=uniq))(c, ix, vals)
                return body, big
            if name == "gath15-8k":
                def body(i, c):
                    ix = (idx15 + i) % P
                    g = jax.vmap(lambda a, j: a[j])(c, ix)
                    return c + jnp.sum(g, axis=1, keepdims=True) * 0 + 1
                return body, big
            if name == "chain14":
                def body(i, c):
                    pos = jnp.zeros((lanes,), jnp.int32) + (i % 7)
                    acc = jnp.zeros((lanes,), jnp.int32)
                    for _ in range(14):
                        pair = jax.vmap(
                            lambda a, p: jax.lax.dynamic_slice_in_dim(a, p, 2))(
                                c, jnp.clip(2 * pos + 1, 0, P - 2))
                        use_r = pair[:, 1] < pair[:, 0]
                        pos = jnp.clip(2 * pos + 1 + use_r.astype(jnp.int32),
                                       0, P - 1)
                        acc = acc + pair[:, 0]
                    return c.at[:, 0].set(acc)
                return body, big
            if name == "argmin256":
                def body(i, c):
                    m = jnp.argmin(c + i % 3, axis=1)
                    return jax.vmap(lambda a, j, v: a.at[j].set(v))(
                        c, m, i + jnp.arange(lanes))
                return body, ring
            if name == "tape-read":
                def body(i, c):
                    r = tape[jnp.minimum(i, 39999)]
                    return c.at[:, :8].add(r[None, :])
                return body, row if False else jnp.zeros((lanes, 16), jnp.int32)
            if name == "dense-8k":
                def body(i, c):
                    ix = (idx1 + i) % P
                    oh = jnp.arange(P)[None, :] == ix[:, None]
                    return jnp.where(oh, c + i, c)
                return body, big
            raise ValueError(name)

        for name in ["dense16", "dense-grid", "scat1-8k", "scat15-8k",
                     "scat15u-8k", "gath15-8k", "chain14", "argmin256",
                     "tape-read", "dense-8k"]:
            body, c0 = mk(name)
            fn = jax.jit(lambda c, b=body: loop(b, c, steps))
            secs = timed(fn, c0)
            print(f"lanes={lanes:5d} {name:11s} {secs / steps * 1e6:9.2f} us/step",
                  flush=True)


if __name__ == "__main__":
    main()
