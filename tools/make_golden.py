#!/usr/bin/env python3
"""Generate ground-truth fixtures by RUNNING the reference implementation.

This script imports the reference (read-only, at /root/reference) and records
its observable behavior into tests/fixtures/*.json. The fixtures are the
parity bar for the TPU-native framework (fitness to 1e-5, exact event counts).

No reference code is copied; we only execute it and record outputs.
Reference entry points exercised:
  - benchmarks/parser.py TraceParser.parse_workload
  - simulator/main.py KubernetesSimulator.run_schedule
  - simulator/evaluator.py SchedulingEvaluator.get_policy_score
  - tests/test_scheduler.py policy zoo (imported as module)
"""
import json
import os
import sys
import copy

REF = "/root/reference"
sys.path.insert(0, REF)
sys.path.insert(0, os.path.join(REF, "tests"))

os.chdir(REF)  # TraceParser uses relative paths

from benchmarks.parser import TraceParser  # noqa: E402
from simulator.event_simulator import DiscreteEventSimulator  # noqa: E402
from simulator.main import KubernetesSimulator  # noqa: E402
from simulator.evaluator import SchedulingEvaluator  # noqa: E402
import test_scheduler as zoo  # noqa: E402
import test_simulator as micro  # noqa: E402

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "tests", "fixtures")


def run_policy(cluster, pods, policy, with_eval=True):
    cluster = copy.deepcopy(cluster)
    pods = copy.deepcopy(pods)
    node_index = {nid: i for i, nid in enumerate(cluster.nodes_dict)}
    ev = DiscreteEventSimulator(pods)
    evaluator = SchedulingEvaluator(cluster, enabled=True) if with_eval else None
    sim = KubernetesSimulator(cluster, pods, ev, policy, evaluator=evaluator)
    sim.run_schedule()
    out = {
        "scheduled_pods": sum(1 for p in pods if p.assigned_node != ""),
        "max_nodes": sim.max_nodes,
        "assignments": [node_index.get(p.assigned_node, -1) for p in pods],
        "assigned_gpus": [sorted(p.assigned_gpus) for p in pods],
        "final_creation_time": [p.creation_time for p in pods],
        "final_cpu_left": [n.cpu_milli_left for n in cluster.nodes_dict.values()],
        "final_mem_left": [n.memory_mib_left for n in cluster.nodes_dict.values()],
        "final_gpu_left": [n.gpu_left for n in cluster.nodes_dict.values()],
        "final_gpu_milli_left": [[g.gpu_milli_left for g in n.gpus] for n in cluster.nodes_dict.values()],
    }
    if with_eval:
        res = evaluator.get_evaluation_results()
        out.update({
            "policy_score": evaluator.get_policy_score(pods),
            "avg_cpu_utilization": res.avg_cpu_utilization,
            "avg_memory_utilization": res.avg_memory_utilization,
            "avg_gpu_count_utilization": res.avg_gpu_count_utilization,
            "avg_gpu_memory_utilization": res.avg_gpu_memory_utilization,
            "gpu_fragmentation_score": res.gpu_fragmentation_score,
            "num_snapshots": res.num_snapshots,
            "num_fragmentation_events": res.num_fragmentation_events,
            "events_processed": evaluator.events_processed,
            "snapshots": [
                [s.cpu_utilization, s.memory_utilization, s.gpu_count_utilization,
                 s.gpu_memory_utilization, s.event_progress]
                for s in evaluator.utilization_snapshots
            ],
            "fragmentation_events": evaluator.fragmentation_events,
        })
    return out


def main():
    os.makedirs(OUT, exist_ok=True)
    parser = TraceParser()

    policies = {
        "first_fit": zoo.first_fit_scheduler,
        "best_fit": zoo.best_fit_scheduler,
        "funsearch_4901": zoo.funsearch_4901_scheduler,
        "funsearch_4816": zoo.funsearch_4816_scheduler,
        "funsearch_4800": zoo.funsearch_4800_scheduler,
    }

    # 1. Default workload, all 5 zoo policies.
    cluster, pods = parser.parse_workload()
    golden = {"trace": {"node_file": "gpu_models_filtered.csv",
                        "pod_file": "openb_pod_list_default.csv",
                        "num_nodes": len(cluster.nodes_dict),
                        "num_pods": len(pods)},
              "policies": {}}
    for name, fn in policies.items():
        print(f"running {name}...", flush=True)
        golden["policies"][name] = run_policy(cluster, pods, fn)
        print(f"  score={golden['policies'][name]['policy_score']:.6f} "
              f"snaps={golden['policies'][name]['num_snapshots']}")
    with open(os.path.join(OUT, "golden_default.json"), "w") as f:
        json.dump(golden, f)

    # 2. Alternate traces with best_fit + first_fit (robustness).
    alt = {}
    # NOTE: the multigpu* traces lack the gpu_spec/creation_time columns and the
    # reference parser raises KeyError on them -- excluded (no parity obligation).
    for pod_file in ["openb_pod_list_gpushare40.csv", "openb_pod_list_gpuspec33.csv",
                     "openb_pod_list_cpu250.csv"]:
        cluster2, pods2 = parser.parse_workload(pod_file=pod_file)
        alt[pod_file] = {}
        for name in ["first_fit", "best_fit"]:
            print(f"running {name} on {pod_file}...", flush=True)
            alt[pod_file][name] = run_policy(cluster2, pods2, policies[name])
    with open(os.path.join(OUT, "golden_alt_traces.json"), "w") as f:
        json.dump(alt, f)

    # 3. Micro scenario (test_simulator.py): 2 nodes, 4 pods, no evaluator.
    mc = micro.create_test_cluster()
    mp = micro.create_test_pods()
    m = run_policy(mc, mp, micro.best_fit_scheduler, with_eval=False)
    m["pods"] = [
        {"pod_id": p.pod_id, "cpu_milli": p.cpu_milli, "memory_mib": p.memory_mib,
         "num_gpu": p.num_gpu, "gpu_milli": p.gpu_milli,
         "creation_time": p.creation_time, "duration_time": p.duration_time}
        for p in micro.create_test_pods()
    ]
    with open(os.path.join(OUT, "golden_micro.json"), "w") as f:
        json.dump(m, f)

    print("fixtures written to", OUT)


if __name__ == "__main__":
    main()
