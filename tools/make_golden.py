#!/usr/bin/env python3
"""Generate ground-truth fixtures into tests/fixtures/*.json.

Two fixture families:

- **Reference fixtures** (default mode): import the reference
  implementation (read-only, at /root/reference) and record its observable
  behavior. These are the parity bar for the TPU-native framework (fitness
  to 1e-5, exact event counts). No reference code is copied; we only
  execute it and record outputs. Reference entry points exercised:
    - benchmarks/parser.py TraceParser.parse_workload
    - simulator/main.py KubernetesSimulator.run_schedule
    - simulator/evaluator.py SchedulingEvaluator.get_policy_score
    - tests/test_scheduler.py policy zoo (imported as module)

- **Scenario-fault fixture** (``--scenario-fault``): the reference has no
  fault vocabulary (NODE_DOWN/NODE_UP cordon events are a fks_tpu.scenarios
  extension), so this fixture is pinned from the repo's OWN exact engine —
  the bit-replica of the reference event loop — on a deterministic
  fault-injected scenario. It is a regression pin, not reference parity:
  tests/test_scenarios.py replays the scenario through the exact AND flat
  engines and holds both to the recorded scores (<= 1e-5), so any future
  change to fault semantics that shifts fitness must come with a
  regenerated fixture.
"""
import argparse
import copy
import json
import os
import sys

REF = "/root/reference"
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "..", "tests", "fixtures")

# scenario-fault fixture recipe (everything the test needs to rebuild the
# exact same workload + scenario from seeds alone). The spec is chosen so
# the cordon windows REROUTE ~half the placements (the pinned assignment
# vector is fault-sensitive) without forcing retries — retry semantics are
# the flat engine's one documented divergence, and this fixture gates
# BOTH engines to 1e-5.
FAULT_WORKLOAD = {"num_nodes": 4, "num_pods": 60, "seed": 7}
FAULT_SPEC = {"name": "golden_fault", "seed": 42, "fault_nodes": 3,
              "fault_start_frac": 0.3, "fault_duration_frac": 0.4,
              "demand_scale": 1.2}
FAULT_POLICIES = ("first_fit", "best_fit")


def _load_reference():
    """Import the reference implementation (module-level state: sys.path +
    cwd, as its TraceParser uses relative paths). Lazy so --scenario-fault
    works in containers without /root/reference."""
    sys.path.insert(0, REF)
    sys.path.insert(0, os.path.join(REF, "tests"))
    os.chdir(REF)
    from benchmarks.parser import TraceParser
    from simulator.event_simulator import DiscreteEventSimulator
    from simulator.evaluator import SchedulingEvaluator
    from simulator.main import KubernetesSimulator
    import test_scheduler as zoo
    import test_simulator as micro
    return (TraceParser, DiscreteEventSimulator, SchedulingEvaluator,
            KubernetesSimulator, zoo, micro)


def make_run_policy(DiscreteEventSimulator, SchedulingEvaluator,
                    KubernetesSimulator):
    def run_policy(cluster, pods, policy, with_eval=True):
        cluster = copy.deepcopy(cluster)
        pods = copy.deepcopy(pods)
        node_index = {nid: i for i, nid in enumerate(cluster.nodes_dict)}
        ev = DiscreteEventSimulator(pods)
        evaluator = (SchedulingEvaluator(cluster, enabled=True)
                     if with_eval else None)
        sim = KubernetesSimulator(cluster, pods, ev, policy,
                                  evaluator=evaluator)
        sim.run_schedule()
        out = {
            "scheduled_pods": sum(1 for p in pods if p.assigned_node != ""),
            "max_nodes": sim.max_nodes,
            "assignments": [node_index.get(p.assigned_node, -1) for p in pods],
            "assigned_gpus": [sorted(p.assigned_gpus) for p in pods],
            "final_creation_time": [p.creation_time for p in pods],
            "final_cpu_left": [n.cpu_milli_left
                               for n in cluster.nodes_dict.values()],
            "final_mem_left": [n.memory_mib_left
                               for n in cluster.nodes_dict.values()],
            "final_gpu_left": [n.gpu_left
                               for n in cluster.nodes_dict.values()],
            "final_gpu_milli_left": [[g.gpu_milli_left for g in n.gpus]
                                     for n in cluster.nodes_dict.values()],
        }
        if with_eval:
            res = evaluator.get_evaluation_results()
            out.update({
                "policy_score": evaluator.get_policy_score(pods),
                "avg_cpu_utilization": res.avg_cpu_utilization,
                "avg_memory_utilization": res.avg_memory_utilization,
                "avg_gpu_count_utilization": res.avg_gpu_count_utilization,
                "avg_gpu_memory_utilization": res.avg_gpu_memory_utilization,
                "gpu_fragmentation_score": res.gpu_fragmentation_score,
                "num_snapshots": res.num_snapshots,
                "num_fragmentation_events": res.num_fragmentation_events,
                "events_processed": evaluator.events_processed,
                "snapshots": [
                    [s.cpu_utilization, s.memory_utilization,
                     s.gpu_count_utilization, s.gpu_memory_utilization,
                     s.event_progress]
                    for s in evaluator.utilization_snapshots
                ],
                "fragmentation_events": evaluator.fragmentation_events,
            })
        return out
    return run_policy


def make_reference_fixtures():
    (TraceParser, DiscreteEventSimulator, SchedulingEvaluator,
     KubernetesSimulator, zoo, micro) = _load_reference()
    run_policy = make_run_policy(DiscreteEventSimulator, SchedulingEvaluator,
                                 KubernetesSimulator)
    os.makedirs(OUT, exist_ok=True)
    parser = TraceParser()

    policies = {
        "first_fit": zoo.first_fit_scheduler,
        "best_fit": zoo.best_fit_scheduler,
        "funsearch_4901": zoo.funsearch_4901_scheduler,
        "funsearch_4816": zoo.funsearch_4816_scheduler,
        "funsearch_4800": zoo.funsearch_4800_scheduler,
    }

    # 1. Default workload, all 5 zoo policies.
    cluster, pods = parser.parse_workload()
    golden = {"trace": {"node_file": "gpu_models_filtered.csv",
                        "pod_file": "openb_pod_list_default.csv",
                        "num_nodes": len(cluster.nodes_dict),
                        "num_pods": len(pods)},
              "policies": {}}
    for name, fn in policies.items():
        print(f"running {name}...", flush=True)
        golden["policies"][name] = run_policy(cluster, pods, fn)
        print(f"  score={golden['policies'][name]['policy_score']:.6f} "
              f"snaps={golden['policies'][name]['num_snapshots']}")
    with open(os.path.join(OUT, "golden_default.json"), "w") as f:
        json.dump(golden, f)

    # 2. Alternate traces with best_fit + first_fit (robustness).
    alt = {}
    # NOTE: the multigpu* traces lack the gpu_spec/creation_time columns and
    # the reference parser raises KeyError on them -- excluded (no parity
    # obligation).
    for pod_file in ["openb_pod_list_gpushare40.csv",
                     "openb_pod_list_gpuspec33.csv",
                     "openb_pod_list_cpu250.csv"]:
        cluster2, pods2 = parser.parse_workload(pod_file=pod_file)
        alt[pod_file] = {}
        for name in ["first_fit", "best_fit"]:
            print(f"running {name} on {pod_file}...", flush=True)
            alt[pod_file][name] = run_policy(cluster2, pods2, policies[name])
    with open(os.path.join(OUT, "golden_alt_traces.json"), "w") as f:
        json.dump(alt, f)

    # 3. Micro scenario (test_simulator.py): 2 nodes, 4 pods, no evaluator.
    mc = micro.create_test_cluster()
    mp = micro.create_test_pods()
    m = run_policy(mc, mp, micro.best_fit_scheduler, with_eval=False)
    m["pods"] = [
        {"pod_id": p.pod_id, "cpu_milli": p.cpu_milli,
         "memory_mib": p.memory_mib, "num_gpu": p.num_gpu,
         "gpu_milli": p.gpu_milli, "creation_time": p.creation_time,
         "duration_time": p.duration_time}
        for p in micro.create_test_pods()
    ]
    with open(os.path.join(OUT, "golden_micro.json"), "w") as f:
        json.dump(m, f)

    print("fixtures written to", OUT)


def make_scenario_fault_fixture():
    """Pin the exact engine's behavior on a deterministic fault-injected
    scenario (see module docstring: a regression pin from the repo's own
    reference-replica engine, consumed by tests/test_scenarios.py)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    import numpy as np

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.models import zoo
    from fks_tpu.obs import tracing
    from fks_tpu.scenarios import ScenarioSpec, perturb_workload
    from fks_tpu.sim.engine import SimConfig

    wl = synthetic_workload(FAULT_WORKLOAD["num_nodes"],
                            FAULT_WORKLOAD["num_pods"],
                            seed=FAULT_WORKLOAD["seed"])
    spec = ScenarioSpec(**FAULT_SPEC)
    swl = perturb_workload(wl, spec)
    fe = swl.faults
    m = np.asarray(fe.mask)
    fixture = {
        "workload": dict(FAULT_WORKLOAD),
        "spec": spec.describe(),
        "fault_timeline": [
            {"time": int(t), "node": int(nd), "kind": int(k)}
            for t, nd, k in zip(np.asarray(fe.time)[m],
                                np.asarray(fe.node)[m],
                                np.asarray(fe.kind)[m])],
        "policies": {},
    }
    cfg = SimConfig()
    for name in FAULT_POLICIES:
        pol = zoo.ZOO[name]()
        res = tracing.replay(swl, "exact",
                             lambda _p, pod, nodes: pol(pod, nodes),
                             None, cfg)
        rows = tracing.extract_trace(res)
        fixture["policies"][name] = {
            "policy_score": float(res.policy_score),
            "scheduled_pods": int(res.scheduled_pods),
            "events_processed": int(res.events_processed),
            "num_snapshots": int(res.num_snapshots),
            "max_nodes": int(res.max_nodes),
            # Placement vector: the aggregate score is invariant to WHICH
            # node hosts a pod, so the per-CREATE [pod, node] sequence is
            # the fixture's actual fault-sensitivity evidence (the cordon
            # reroutes ~half of these relative to a no-fault run).
            "assignments": [[r["pod"], r["node"]] for r in rows
                            if r["kind"] == "CREATE"],
            "fault_rows": sum(1 for r in rows
                              if r["kind"] in ("NODE_DOWN", "NODE_UP")),
        }
        print(f"{name}: score={fixture['policies'][name]['policy_score']:.6f}"
              f" scheduled={fixture['policies'][name]['scheduled_pods']}"
              f" fault_rows={fixture['policies'][name]['fault_rows']}",
              flush=True)
    os.makedirs(OUT, exist_ok=True)
    path = os.path.join(OUT, "golden_scenario_fault.json")
    with open(path, "w") as f:
        json.dump(fixture, f, indent=1)
    print("fixture written to", path)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--scenario-fault", action="store_true",
                    help="write tests/fixtures/golden_scenario_fault.json "
                         "from the repo's own exact engine (no reference "
                         "checkout needed)")
    args = ap.parse_args()
    if args.scenario_fault:
        make_scenario_fault_fixture()
    else:
        make_reference_fixtures()


if __name__ == "__main__":
    main()
