#!/bin/bash
# Probe the axon TPU tunnel on a timer and FIRE the round-4 evidence
# session (tools/tpu_round4.sh) each time a probe succeeds, until the
# session completes rc=0 (every stage landed ok — already-landed stages
# are skipped inside tpu_session.py, so each fire only runs what is
# still missing). Run detached:
#   nohup bash tools/tpu_watch.sh > benchmarks/results/round4_watch.log 2>&1 &
# A lockfile prevents double-firing if a manual session is also started.
set -u
cd "$(dirname "$0")/.."
LOCK=benchmarks/results/.r4_session_running
MAX_FIRES=8   # a stage broken for real (not a wedge) must not spin forever
fires=0
PROBE='import jax, jax.numpy as jnp
x = jnp.ones((8, 128)); (x @ x.T).sum().block_until_ready()
print(jax.devices()[0].platform)'

while true; do
  if [ -f "$LOCK" ]; then
    holder=$(cat "$LOCK" 2>/dev/null)
    if [ -n "$holder" ] && kill -0 "$holder" 2>/dev/null; then
      echo "$(date -u +%FT%TZ) session already running (pid $holder); watcher exiting"
      exit 0
    fi
    # holder died without cleanup (SIGKILL / reboot): a dead lock must
    # not silently disable the retry-until-done loop
    echo "$(date -u +%FT%TZ) stale lock (pid ${holder:-none} gone); clearing"
    rm -f "$LOCK"
  fi
  if timeout 90 python -c "$PROBE" 2>/dev/null | grep -q tpu; then
    fires=$((fires + 1))
    if [ "$fires" -gt "$MAX_FIRES" ]; then
      echo "$(date -u +%FT%TZ) fire cap ($MAX_FIRES) reached; watcher done"
      exit 1
    fi
    echo "$(date -u +%FT%TZ) PROBE OK — firing tpu_round4.sh (fire $fires)"
    # the lock holds the SESSION's pid, not the watcher's: if the watcher
    # is SIGKILLed the session child survives, and a restarted watcher
    # must see the lock as live until that session actually exits
    bash tools/tpu_round4.sh &
    echo "$!" > "$LOCK"
    wait "$!"
    rc=$?
    echo "$(date -u +%FT%TZ) session finished rc=$rc"
    rm -f "$LOCK"
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%FT%TZ) all stages landed; watcher done"
      exit 0
    fi
    # incomplete (wedge mid-session or a failing stage): re-arm; the
    # next fire skips everything that already landed
    echo "$(date -u +%FT%TZ) session incomplete; re-arming watcher"
    sleep 120
    continue
  fi
  echo "$(date -u +%FT%TZ) probe timed out (tunnel wedged); sleeping 300s"
  sleep 300
done
