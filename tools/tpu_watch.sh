#!/bin/bash
# Probe the axon TPU tunnel on a timer and FIRE the round-4 evidence
# session (tools/tpu_round4.sh) the moment a probe succeeds. Run detached:
#   nohup bash tools/tpu_watch.sh > benchmarks/results/round4_watch.log 2>&1 &
# A lockfile prevents double-firing if a manual session is also started.
set -u
cd "$(dirname "$0")/.."
LOCK=benchmarks/results/.r4_session_running
PROBE='import jax; print(jax.devices()[0].platform)'

while true; do
  if [ -f "$LOCK" ]; then
    echo "$(date -u +%FT%TZ) session already running/fired; watcher exiting"
    exit 0
  fi
  if timeout 90 python -c "$PROBE" 2>/dev/null | grep -q .; then
    echo "$(date -u +%FT%TZ) PROBE OK — firing tpu_round4.sh"
    touch "$LOCK"
    bash tools/tpu_round4.sh
    rc=$?
    echo "$(date -u +%FT%TZ) session finished rc=$rc"
    if grep -q '"ok": true' benchmarks/results/round4_tpu.jsonl 2>/dev/null
    then
      # real measurements landed; a re-run is a human call
      exit $rc
    fi
    # the window closed before anything landed (wedged mid-probe):
    # re-arm and keep watching
    echo "$(date -u +%FT%TZ) no stage succeeded; re-arming watcher"
    rm -f "$LOCK"
  fi
  echo "$(date -u +%FT%TZ) probe timed out (tunnel wedged); sleeping 600s"
  sleep 600
done
