#!/bin/bash
# Probe the axon TPU tunnel on a timer and FIRE the round-5 evidence
# session (tools/tpu_round5.sh) each time a probe succeeds, until the
# session completes rc=0 (every stage landed ok — already-landed stages
# are skipped inside tpu_session.py, so each fire only runs what is
# still missing). Run detached:
#   nohup bash tools/tpu_watch.sh > benchmarks/results/round5_watch.log 2>&1 &
# A lockfile prevents double-firing if a manual session is also started.
# The lock is acquired ATOMICALLY (noclobber create) BEFORE the session
# launches, so two watchers racing the same check-then-write window can't
# both fire (round-4 advisor finding).
set -u
cd "$(dirname "$0")/.."
LOCK=benchmarks/results/.r5_session_running
MAX_FIRES=8   # a stage broken for real (not a wedge) must not spin forever
fires=0
PROBE='import jax, jax.numpy as jnp
x = jnp.ones((8, 128)); (x @ x.T).sum().block_until_ready()
print(jax.devices()[0].platform)'

take_lock() {
  # atomic create-or-fail; on failure inspect the holder and clear only
  # a provably dead one, then retry exactly once
  for _ in 1 2; do
    if (set -C; echo "$$" > "$LOCK") 2>/dev/null; then
      return 0
    fi
    holder=$(cat "$LOCK" 2>/dev/null)
    if [ -n "$holder" ] && kill -0 "$holder" 2>/dev/null; then
      return 1   # live holder (another watcher or a manual session)
    fi
    # holder died without cleanup (SIGKILL / reboot): a dead lock must
    # not silently disable the retry-until-done loop
    echo "$(date -u +%FT%TZ) stale lock (pid ${holder:-none} gone); clearing"
    rm -f "$LOCK"
  done
  return 1
}

while true; do
  if timeout 90 python -c "$PROBE" 2>/dev/null | grep -q tpu; then
    if ! take_lock; then
      echo "$(date -u +%FT%TZ) lock held by live pid $(cat "$LOCK" 2>/dev/null); watcher exiting"
      exit 0
    fi
    fires=$((fires + 1))
    if [ "$fires" -gt "$MAX_FIRES" ]; then
      rm -f "$LOCK"
      echo "$(date -u +%FT%TZ) fire cap ($MAX_FIRES) reached; watcher done"
      exit 1
    fi
    echo "$(date -u +%FT%TZ) PROBE OK — firing tpu_round5.sh (fire $fires)"
    # the lock holds the SESSION's pid once launched (if the watcher is
    # SIGKILLed the session child survives, and a restarted watcher must
    # see the lock as live until that session actually exits); the
    # atomic placeholder above held our own pid during the launch gap
    bash tools/tpu_round5.sh &
    echo "$!" > "$LOCK"
    wait "$!"
    rc=$?
    echo "$(date -u +%FT%TZ) session finished rc=$rc"
    rm -f "$LOCK"
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u +%FT%TZ) all stages landed; watcher done"
      exit 0
    fi
    # incomplete (wedge mid-session or a failing stage): re-arm; the
    # next fire skips everything that already landed
    echo "$(date -u +%FT%TZ) session incomplete; re-arming watcher"
    sleep 120
    continue
  fi
  echo "$(date -u +%FT%TZ) probe timed out (tunnel wedged); sleeping 300s"
  sleep 300
done
