"""The real (HTTP) LLM path, exercised hermetically against a local
OpenAI-compatible stub server.

The reference's production codegen path (reference:
funsearch/safe_execution.py:283-317 ``LLMCodeGenerator.generate_policy``)
talks to OpenRouter over the OpenAI SDK and returns None on ANY failure.
Every prior test of our ``OpenAIBackend`` mirrored it without ever crossing
real HTTP (round-2 verdict, missing #1). These tests stand up an actual
socket-listening chat/completions endpoint so serialization, response
parsing, timeout, retry, and error paths all run for real — no mocks, no
network egress.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from fks_tpu.funsearch import template
from fks_tpu.funsearch.llm import CandidateGenerator, OpenAIBackend

GOOD_LOGIC = (
    "score = 10000 * (1.0 + (node.cpu_milli_left - pod.cpu_milli)"
    " / max(1, node.cpu_milli_total))"
)


def _completion_payload(content: str) -> bytes:
    return json.dumps({
        "id": "chatcmpl-stub", "object": "chat.completion", "created": 0,
        "model": "stub-model",
        "choices": [{"index": 0, "finish_reason": "stop",
                     "message": {"role": "assistant", "content": content}}],
        "usage": {"prompt_tokens": 1, "completion_tokens": 1,
                  "total_tokens": 2},
    }).encode()


class StubHandler(BaseHTTPRequestHandler):
    """One behavior per server instance, set via ``server.mode``. Records
    request bodies so tests can assert on what the SDK actually sent."""

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        self.server.requests.append((self.path, body))
        mode = self.server.mode
        if mode == "flaky":  # one transient 503, then healthy
            mode = "http503" if len(self.server.requests) == 1 else "ok"
        if mode == "ok":
            content = GOOD_LOGIC
        elif mode == "fenced":
            content = f"```python\n{GOOD_LOGIC}\n```"
        elif mode == "malformed":
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(b"this is not json {{{")
            return
        elif mode in ("http500", "http503"):
            self.send_response(int(mode[4:]))
            self.end_headers()
            self.wfile.write(b"upstream error")
            return
        elif mode == "retry_after":  # one throttle naming its delay, then ok
            if len(self.server.requests) == 1:
                self.send_response(429)
                self.send_header("Retry-After", "1")
                self.end_headers()
                self.wfile.write(b"throttled")
                return
            content = GOOD_LOGIC
        elif mode == "retry_after_always":  # throttled forever, huge delay
            self.send_response(429)
            self.send_header("Retry-After", "3600")
            self.end_headers()
            self.wfile.write(b"throttled")
            return
        elif mode == "hang":
            time.sleep(10)  # far beyond the client timeout
            self.send_response(200)
            self.end_headers()
            return
        else:  # pragma: no cover - test bug
            raise AssertionError(f"unknown stub mode {mode}")
        payload = _completion_payload(content)
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)

    def log_message(self, *args):  # silence per-request stderr noise
        pass


@pytest.fixture()
def stub_server():
    server = ThreadingHTTPServer(("127.0.0.1", 0), StubHandler)
    server.mode = "ok"
    server.requests = []
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        yield server
    finally:
        server.shutdown()
        server.server_close()


def _backend(server, **kw) -> OpenAIBackend:
    kw.setdefault("timeout", 2.0)
    kw.setdefault("max_retries", 0)
    return OpenAIBackend(
        api_key="stub-key",
        base_url=f"http://127.0.0.1:{server.server_address[1]}/v1",
        model="stub-model", **kw)


def test_success_round_trip(stub_server):
    """Full HTTP round trip: prompt goes out with the configured model/
    sampling params, the returned logic block comes back verbatim."""
    backend = _backend(stub_server, max_tokens=123, temperature=0.4)
    out = backend.complete(template.build_prompt([], ""))
    assert out == GOOD_LOGIC
    path, body = stub_server.requests[0]
    assert path.endswith("/chat/completions")
    assert body["model"] == "stub-model"
    assert body["max_tokens"] == 123
    assert body["temperature"] == 0.4
    assert body["messages"][0]["role"] == "user"
    assert "priority_function" in body["messages"][0]["content"]


def test_generator_produces_valid_candidate(stub_server):
    """CandidateGenerator over real HTTP: validated, transpilable source."""
    gen = CandidateGenerator(_backend(stub_server))
    code = gen.generate([], "")
    assert code is not None
    assert "priority_function" in code
    assert GOOD_LOGIC.split(" = ", 1)[1] in code


def test_fenced_response_is_unwrapped(stub_server):
    """Real models wrap output in ``` fences despite instructions."""
    stub_server.mode = "fenced"
    code = CandidateGenerator(_backend(stub_server)).generate([], "")
    assert code is not None
    assert "```" not in code


def test_malformed_response_yields_none(stub_server):
    """Unparsable body -> SDK raises -> generate returns None (reference
    returns None on any failure, safe_execution.py:315-317)."""
    stub_server.mode = "malformed"
    assert CandidateGenerator(_backend(stub_server)).generate([], "") is None


def test_http_error_yields_none(stub_server):
    stub_server.mode = "http500"
    assert CandidateGenerator(_backend(stub_server)).generate([], "") is None


def test_transient_error_is_retried(stub_server):
    """429/5xx retry up to max_retries; a one-off 503 is invisible."""
    stub_server.mode = "flaky"
    backend = _backend(stub_server, max_retries=1)
    assert backend.complete("p") == GOOD_LOGIC
    assert len(stub_server.requests) == 2


def test_retry_after_header_honored(stub_server):
    """A 429 naming its delay is respected: the retry waits ~Retry-After
    instead of the 0.5s backoff ladder, then succeeds."""
    stub_server.mode = "retry_after"
    backend = _backend(stub_server, max_retries=1)
    t0 = time.monotonic()
    assert backend.complete("p") == GOOD_LOGIC
    elapsed = time.monotonic() - t0
    assert len(stub_server.requests) == 2
    assert elapsed >= 0.9  # waited the server-named second, not 0.5s


def test_retry_after_capped_by_deadline(stub_server):
    """A server demanding an hour between retries gets only the deadline:
    complete() fails within the configured budget, not in 3600s."""
    stub_server.mode = "retry_after_always"
    backend = _backend(stub_server, max_retries=2, deadline=2.0)
    t0 = time.monotonic()
    with pytest.raises(Exception):
        backend.complete("p")
    assert time.monotonic() - t0 < 8  # bounded by deadline, not Retry-After


def test_retry_after_parsing():
    """Header parsing: delta-seconds, HTTP-date, absent, garbage."""
    from email.utils import formatdate

    from fks_tpu.funsearch.llm import _retry_after_seconds

    assert _retry_after_seconds({"Retry-After": "7"}) == 7.0
    assert _retry_after_seconds({"Retry-After": "0"}) == 0.0
    http_date = _retry_after_seconds(
        {"Retry-After": formatdate(time.time() + 30, usegmt=True)})
    assert http_date is not None and 20 <= http_date <= 31
    # a date in the past clamps to "retry now", never negative
    past = _retry_after_seconds(
        {"Retry-After": formatdate(time.time() - 60, usegmt=True)})
    assert past == 0.0
    assert _retry_after_seconds({}) is None
    assert _retry_after_seconds(None) is None
    assert _retry_after_seconds({"Retry-After": "soonish"}) is None


def test_timeout_yields_none(stub_server):
    """A hung upstream must not stall codegen past the configured timeout."""
    stub_server.mode = "hang"
    t0 = time.monotonic()
    out = CandidateGenerator(_backend(stub_server, timeout=1.0)).generate([], "")
    assert out is None
    assert time.monotonic() - t0 < 8  # bounded by timeout, not the 10s hang


def test_evolution_end_to_end_against_stub(stub_server):
    """The whole evolve loop against live HTTP: seeds + one generation of
    stub-generated candidates, champion persisted. This is the reference's
    production configuration (OpenAI-SDK backend) running hermetically."""
    from fks_tpu.funsearch import CodeEvaluator, EvolutionConfig, FunSearch
    from tests.test_engine_micro import micro_workload

    cfg = EvolutionConfig(population_size=6, generations=1, elite_size=2,
                          candidates_per_generation=3, max_workers=2,
                          early_stop_threshold=1.1)
    fs = FunSearch(CodeEvaluator(micro_workload()), cfg,
                   backend=_backend(stub_server), log=lambda _m: None)
    fs.run_evolution()
    assert fs.best is not None
    assert fs.best[1] > 0
    # the stub's candidate entered the population alongside the seeds
    assert any(GOOD_LOGIC.split(" = ", 1)[1] in c for c, _ in fs.population)
    # n candidate requests hit the wire (dedup happens after generation)
    assert len(stub_server.requests) == 3
