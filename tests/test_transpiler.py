"""Transpiler differential tests: the vectorized JAX lowering of candidate
source must agree, node for node, with plain scalar Python execution of the
SAME source in the sandbox (the per-(pod,node) interpretation the reference
uses, reference: funsearch/funsearch_integration.py:67-101). This oracle
check is the transpiler's correctness bar."""
import zlib

import numpy as np
import jax.numpy as jnp
import pytest

from fks_tpu.funsearch import sandbox, template, transpiler
from fks_tpu.sim.types import NodeView, PodView

# ----------------------------------------------------- state generators


def random_state(rng, n_nodes=5, g_max=4):
    """A random mid-simulation cluster + one pod, as (views, scalar objects)."""
    cpu_tot = rng.integers(2000, 96000, n_nodes)
    mem_tot = rng.integers(4000, 262144, n_nodes)
    cpu_left = rng.integers(0, cpu_tot + 1)
    mem_left = rng.integers(0, mem_tot + 1)
    num_gpus = rng.integers(0, g_max + 1, n_nodes)
    gpu_left = np.array([rng.integers(0, k + 1) for k in num_gpus])
    gmask = np.arange(g_max)[None, :] < num_gpus[:, None]
    gm_tot = np.where(gmask, 1000, 0).astype(np.int64)
    gm_left = np.where(gmask, rng.integers(0, 1001, (n_nodes, g_max)), 0)
    gmem = np.where(gmask, 16000, 0)

    nodes = NodeView(
        cpu_milli_left=jnp.asarray(cpu_left), cpu_milli_total=jnp.asarray(cpu_tot),
        memory_mib_left=jnp.asarray(mem_left), memory_mib_total=jnp.asarray(mem_tot),
        gpu_left=jnp.asarray(gpu_left), num_gpus=jnp.asarray(num_gpus),
        gpu_milli_left=jnp.asarray(gm_left), gpu_milli_total=jnp.asarray(gm_tot),
        gpu_mem_total=jnp.asarray(gmem), gpu_mask=jnp.asarray(gmask),
        node_mask=jnp.ones(n_nodes, bool))

    pod_vals = dict(
        cpu_milli=int(rng.integers(100, 16000)),
        memory_mib=int(rng.integers(100, 65536)),
        num_gpu=int(rng.integers(0, 3)),
        gpu_milli=int(rng.integers(0, 1001)))
    pod = PodView(creation_time=0, duration_time=100, **pod_vals)

    scalar_nodes = []
    for i in range(n_nodes):
        gpus = tuple(
            sandbox.ScalarGPU(int(gm_left[i, g]), int(gm_tot[i, g]),
                              int(gmem[i, g]), int(gmem[i, g]))
            for g in range(num_gpus[i]))
        scalar_nodes.append(sandbox.ScalarNode(
            int(cpu_left[i]), int(cpu_tot[i]), int(mem_left[i]),
            int(mem_tot[i]), int(gpu_left[i]), gpus))
    scalar_pod = sandbox.ScalarPod(**pod_vals)
    return pod, nodes, scalar_pod, scalar_nodes


# candidate logic blocks spanning the transpilable subset
LOGIC_BLOCKS = {
    "constant": "score = 1000",
    "linear": "score = node.cpu_milli_left - pod.cpu_milli + 7",
    "ratio": (
        "score = 10000 * (node.cpu_milli_left - pod.cpu_milli)"
        " / max(1, node.cpu_milli_total)"),
    "branchy": (
        "if node.cpu_milli_left > node.cpu_milli_total / 2:\n"
        "        score = 50\n"
        "    else:\n"
        "        score = 150\n"
        "    if pod.num_gpu > 0:\n"
        "        score = score + 25"),
    "gpu_loop": (
        "free = 0\n"
        "    for gpu in node.gpus:\n"
        "        free = free + gpu.gpu_milli_left\n"
        "    score = free / max(1, len(node.gpus)) + 1"),
    "gpu_loop_if": (
        "tight = 0\n"
        "    for gpu in node.gpus:\n"
        "        if gpu.gpu_milli_left >= pod.gpu_milli:\n"
        "            tight = tight + gpu.gpu_milli_left - pod.gpu_milli\n"
        "    score = 5000 - tight"),
    "genexp_sum": (
        "score = 1 + sum(gpu.gpu_milli_left for gpu in node.gpus"
        " if gpu.gpu_milli_left >= pod.gpu_milli)"),
    "boolops": (
        "ok = node.gpu_left > 0 and pod.num_gpu > 0 or pod.cpu_milli > 5000\n"
        "    score = 400 if ok else 80"),
    "math_fns": (
        "score = math.sqrt(max(1, node.cpu_milli_left))"
        " + math.log(max(1, node.memory_mib_left))"),
    "modfloor": (
        "score = 1 + (node.cpu_milli_left % max(1, pod.cpu_milli))"
        " + node.memory_mib_left // max(1, pod.memory_mib)"),
    "minmax_gen": (
        "best = min(gpu.gpu_milli_left for gpu in node.gpus)"
        " if len(node.gpus) > 0 else 0\n"
        "    score = best + 3"),
    "early_return": (
        "if node.gpu_left == 0:\n"
        "        return 7\n"
        "    score = 77"),
    "chained_compare": (
        "score = 900 if 0 < pod.num_gpu <= node.gpu_left else 12"),
}


@pytest.mark.parametrize("name", sorted(LOGIC_BLOCKS))
def test_transpiled_matches_scalar_oracle(name):
    code = template.fill_template(LOGIC_BLOCKS[name])
    assert sandbox.validate(code), name
    policy = transpiler.transpile(code)
    rng = np.random.default_rng(zlib.crc32(name.encode()))
    for trial in range(8):
        pod, nodes, spod, snodes = random_state(rng)
        got = np.asarray(policy(pod, nodes))
        fn = sandbox.compile_policy(code)
        want = [int(fn(spod, sn)) for sn in snodes]
        assert got.tolist() == want, f"{name} trial {trial}"


def test_transpiled_seeds_match_oracle():
    rng = np.random.default_rng(0)
    for name, code in template.seed_policies().items():
        policy = transpiler.transpile(code)
        fn = sandbox.compile_policy(code)
        for _ in range(5):
            pod, nodes, spod, snodes = random_state(rng)
            got = np.asarray(policy(pod, nodes)).tolist()
            want = [int(fn(spod, sn)) for sn in snodes]
            assert got == want, name


@pytest.mark.slow
def test_transpiled_policy_runs_in_engine():
    """End to end: a transpiled candidate drives the jitted simulator and
    produces the same fitness as the equivalent zoo policy."""
    from fks_tpu.models import zoo
    from fks_tpu.sim.engine import SimConfig, simulate
    from tests.test_engine_micro import micro_workload

    wl = micro_workload()
    cfg = SimConfig(score_dtype=jnp.float64)
    ref = simulate(wl, zoo.first_fit(dtype=jnp.float64), cfg)
    cand = simulate(wl, transpiler.transpile(template.seed_policies()["first_fit"]), cfg)
    assert np.asarray(cand.assigned_node).tolist() == \
        np.asarray(ref.assigned_node).tolist()
    assert float(cand.policy_score) == pytest.approx(float(ref.policy_score), abs=1e-12)


def test_nonfinite_lanes_refuse():
    code = template.fill_template("score = 1.0 / (pod.num_gpu * 0)")
    policy = transpiler.transpile(code)
    rng = np.random.default_rng(3)
    pod, nodes, _, _ = random_state(rng)
    got = np.asarray(policy(pod, nodes))
    assert (got == 0).all()  # inf lanes refuse rather than poison argmax


@pytest.mark.parametrize("bad_logic", [
    "score = sorted(node.gpus)",          # sorted() of a non-generator
    "for i in range(1000000):\n        score = 1",  # unbounded unroll
    "score = node.gpus[pod.num_gpu].gpu_milli_left",  # dynamic subscript
    "score = pod.nonexistent_field",
    "score = abs()",                      # wrong arity must not escape
    "score = min(5)",
    "score = math.sqrt(1, 2)",
    "for i in range():\n        score = 1",
])
def test_unsupported_subset_raises(bad_logic):
    code = template.fill_template(bad_logic)
    with pytest.raises(transpiler.TranspileError):
        transpiler.transpile(code)


def _lane_scores(logic, rng_seed=11):
    code = template.fill_template(logic)
    policy = transpiler.transpile(code)
    rng = np.random.default_rng(rng_seed)
    pod, nodes, spod, snodes = random_state(rng)
    return code, np.asarray(policy(pod, nodes)), spod, snodes


@pytest.mark.parametrize("logic", [
    # sorted() over a generator + static indexing, against the scalar
    # oracle (reference whitelists `sorted`, safe_execution.py:19-22)
    "gpus = sorted(g.gpu_milli_left for g in node.gpus)\n"
    "score = gpus[0] + 1",
    "gpus = sorted(g.gpu_milli_left for g in node.gpus)\n"
    "score = gpus[-1] + 2 * len(gpus)",
    "score = node.gpus[1].gpu_milli_left + 3",
])
def test_sorted_and_subscript_match_oracle(logic):
    """Lanes where Python would raise (IndexError on short lists) refuse;
    every other lane matches the reference-style scalar evaluation."""
    code, got, spod, snodes = _lane_scores(logic)
    fn = sandbox.compile_policy(code)
    for i, sn in enumerate(snodes):
        try:
            want = int(fn(spod, sn))
        except Exception:
            want = 0
        assert got[i] == want, (i, logic)


def test_sorted_list_overwritten_by_scalar():
    """Regression: rebinding a name that held a sorted() list must not
    crash the transpiler; unconditional rebinding works, conditional
    rebinding is cleanly rejected (outside the lowerable subset)."""
    code, got, spod, snodes = _lane_scores(
        "xs = sorted(g.gpu_milli_left for g in node.gpus)\n"
        "xs = 7.0\n"
        "score = xs")
    fn = sandbox.compile_policy(code)
    assert got.tolist() == [int(fn(spod, sn)) for sn in snodes]
    with pytest.raises(transpiler.TranspileError):
        transpiler.transpile(template.fill_template(
            "xs = sorted(g.gpu_milli_left for g in node.gpus)\n"
            "if pod.num_gpu > 0:\n"
            "        xs = 1.0\n"
            "score = 1"))


def test_empty_generator_minmax_poisons_lane():
    """min() over zero GPUs raises in Python (candidate -> fitness 0 in the
    reference); the lowered lane must refuse, never leak the int sentinel."""
    code, got, spod, snodes = _lane_scores(
        "score = min(gpu.gpu_milli_left for gpu in node.gpus)")
    for i, sn in enumerate(snodes):
        if len(sn.gpus) == 0:
            assert got[i] == 0
        else:
            fn = sandbox.compile_policy(code)
            assert got[i] == int(fn(spod, sn))


def test_untaken_ifexp_arm_does_not_poison():
    """int(inf) in the arm Python would never evaluate must not poison."""
    logic = ("score = int(100.0 / (node.gpu_left * 0)) "
             "if node.gpu_left > 9999 else 5")
    code, got, spod, snodes = _lane_scores(logic)
    fn = sandbox.compile_policy(code)
    want = [int(fn(spod, sn)) for sn in snodes]
    assert got.tolist() == want  # every feasible node scores 5


def test_conditionally_unbound_read_poisons():
    """Reading a variable only assigned on the untaken branch raises
    UnboundLocalError in Python; those lanes must refuse."""
    logic = ("if node.gpu_left > 0:\n"
             "        bonus = 5\n"
             "    score = 10 + bonus")
    code, got, spod, snodes = _lane_scores(logic)
    fn = sandbox.compile_policy(code)
    for i, sn in enumerate(snodes):
        try:
            want = int(fn(spod, sn))
        except sandbox.PolicyRuntimeError:
            want = 0  # reference: candidate aborts; our lane refuses
        except Exception:
            want = 0
        assert got[i] == want, i


def test_canonical_key_ignores_formatting():
    a = template.fill_template("score = 1 + 2")
    b = a.replace("score = 1 + 2", "score = 1   +    2")
    assert transpiler.canonical_key(a) == transpiler.canonical_key(b)
