"""Memory observability layer (fks_tpu.obs.memory).

The ISSUE-17 acceptance criteria, as tests:

- footprint ledger: ``footprint_of`` prices a compiled executable from
  ``memory_analysis()`` (None when the backend can't), ``record_footprint``
  lands one tagged ``memory_footprint`` record in both the process LEDGER
  and the recorder, ``rollup`` aggregates per (component, mesh_layout);
- watermark sampler: disabled is a true no-op ({} samples, no records —
  the Python-static contract the ``flat_step/mem_sampled`` jaxpr pin
  proves); enabled records host RSS + per-device rows;
- leak sentinel: drift math against live ``jax.Array`` allocations, the
  zero-tolerance default, and the fence-before-check contract;
- closed vocabularies pinned against tools/check_jsonl_schema.py's
  stdlib-only copies;
- gated memory budgets: ``cli compare`` flags an injected
  ``peak_device_bytes`` regression, rides out sub-page noise, and skips
  stale-fallback donor values on the candidate side;
- ``cli mem`` smoke over the golden fixture.

The deterministic drills themselves run here at reduced scale; the full
50-swap/200-batch criterion is gated end-to-end by
tools/run_full_suite.py's ``memory_gate``.
"""
import json
import os
import pathlib
import shutil
import sys

import jax.numpy as jnp
import pytest

from fks_tpu import cli
from fks_tpu.obs import memory as mem
from fks_tpu.obs.compare import compare_runs, extract_metrics, has_regression
from fks_tpu.obs.memory import (
    FOOTPRINT_KEYS, LEAK_LOOPS, LEDGER, MEMORY_COMPONENTS, LeakSentinel,
    NULL_SAMPLER, WatermarkSampler, footprint_of, leak_fence,
    live_array_stats, mesh_layout_label, record_footprint, rollup, run_drill,
)
from fks_tpu.obs.telemetry import normalize_memory_stats

GOLDEN = str(pathlib.Path(__file__).parent / "fixtures" / "golden_run")


class RecStub:
    enabled = True

    def __init__(self):
        self.metrics = []

    def metric(self, kind, *a, **fields):
        rec = dict(a[0]) if a and isinstance(a[0], dict) else {}
        rec.update(fields)
        self.metrics.append({"kind": kind, **rec})


class FakeAnalysis:
    temp_size_in_bytes = 1000
    argument_size_in_bytes = 200
    output_size_in_bytes = 50
    generated_code_size_in_bytes = 4096
    alias_size_in_bytes = 0


class FakeCompiled:
    def memory_analysis(self):
        return FakeAnalysis()


# --------------------------------------------------------------- ledger

def test_footprint_of_fake_compiled():
    fp = footprint_of(FakeCompiled())
    assert fp == {"temp_bytes": 1000, "argument_bytes": 200,
                  "output_bytes": 50, "generated_code_bytes": 4096,
                  "alias_bytes": 0, "total_bytes": 5346}


def test_footprint_of_unpriceable_returns_none():
    assert footprint_of(object()) is None

    class Raises:
        def memory_analysis(self):
            raise RuntimeError("no backend")

    class Empty:
        def memory_analysis(self):
            return object()  # none of the byte attrs

    assert footprint_of(Raises()) is None
    assert footprint_of(Empty()) is None


def test_record_footprint_lands_in_ledger_and_recorder():
    rec = RecStub()
    LEDGER.clear()
    out = record_footprint("serve_vm", "lanes=2,cap=64", FakeCompiled(),
                           recorder=rec, engine="flat")
    assert out is not None and out["component"] == "serve_vm"
    assert out["exe_key"] == "lanes=2,cap=64"
    assert out["engine"] == "flat"
    assert [r["exe_key"] for r in LEDGER.records()] == ["lanes=2,cap=64"]
    assert rec.metrics[0]["kind"] == "memory_footprint"
    assert rec.metrics[0]["total_bytes"] == 5346


def test_record_footprint_rejects_unknown_component():
    with pytest.raises(ValueError):
        record_footprint("gpu_tier", "x", FakeCompiled(), recorder=RecStub())


def test_record_footprint_unpriceable_records_nothing():
    rec = RecStub()
    LEDGER.clear()
    assert record_footprint("bench", "k", object(), recorder=rec) is None
    assert not LEDGER.records() and not rec.metrics


def test_rollup_aggregates_per_component_and_layout():
    rows = [
        {"component": "serve_aot", "mesh_layout": "", "temp_bytes": 100,
         "argument_bytes": 10, "output_bytes": 1,
         "generated_code_bytes": 5, "total_bytes": 116},
        {"component": "serve_aot", "mesh_layout": "", "temp_bytes": 300,
         "argument_bytes": 10, "output_bytes": 1,
         "generated_code_bytes": 5, "total_bytes": 316},
        {"component": "evolve", "mesh_layout": "pop=4", "temp_bytes": 9000,
         "argument_bytes": 0, "output_bytes": 0,
         "generated_code_bytes": 0},  # total derived from the byte keys
    ]
    agg = rollup(rows)
    assert [a["component"] for a in agg] == ["evolve", "serve_aot"]
    aot = agg[1]
    assert aot["executables"] == 2
    assert aot["predicted_hbm_bytes"] == 432
    assert aot["peak_temp_bytes"] == 300
    assert agg[0]["predicted_hbm_bytes"] == 9000


def test_rollup_defaults_to_process_ledger():
    LEDGER.clear()
    record_footprint("bench", "probe", FakeCompiled(), recorder=RecStub())
    agg = rollup()
    assert len(agg) == 1 and agg[0]["component"] == "bench"
    LEDGER.clear()


def test_mesh_layout_label_none_is_empty():
    assert mesh_layout_label(None) == ""


# ----------------------------------------------------- stats + sampler

def test_normalize_memory_stats_aliases_and_partials():
    assert normalize_memory_stats(None) is None
    assert normalize_memory_stats({}) is None
    assert normalize_memory_stats({"weird": 1}) is None
    out = normalize_memory_stats({"bytes_in_use": 10,
                                  "peak_bytes_in_use": 20,
                                  "bytes_limit": 30})
    assert out == {"bytes_in_use": 10, "peak_bytes_in_use": 20,
                   "bytes_limit": 30}
    # partial dicts keep what they can answer
    assert normalize_memory_stats({"bytes_in_use": 7}) == {"bytes_in_use": 7}


def test_disabled_sampler_is_a_true_noop():
    rec = RecStub()
    s = WatermarkSampler(enabled=False, recorder=rec)
    with s:
        assert s.sample(stage="x") == {}
    assert not s.samples and not rec.metrics
    assert NULL_SAMPLER.sample() == {}


def test_enabled_sampler_records_watermarks():
    rec = RecStub()
    with WatermarkSampler(enabled=True, recorder=rec) as s:
        out = s.sample(stage="unit")
    assert out["stage"] == "unit"
    assert out["host_rss_kb"] > 0
    assert isinstance(out["devices"], list) and out["devices"]
    row = out["devices"][0]
    assert "id" in row and "platform" in row  # identity even on CPU
    assert rec.metrics and rec.metrics[0]["kind"] == "memory_watermark"


def test_sampler_interval_thread_lifecycle():
    rec = RecStub()
    s = WatermarkSampler(enabled=True, interval_s=0.01, recorder=rec)
    s.start()
    import time
    deadline = time.time() + 5.0
    while not s.samples and time.time() < deadline:
        time.sleep(0.01)
    s.stop()
    assert s.samples and s.samples[0]["stage"] == "interval"
    assert s._thread is None


# --------------------------------------------------------- leak sentinel

def test_leak_sentinel_flags_real_growth_and_clears_on_free():
    rec = RecStub()
    held = []
    s = LeakSentinel("serve_batch", recorder=rec)
    s.fence()
    held.append(jnp.zeros(1024, dtype=jnp.float32) + 1.0)
    verdict = s.check(iterations=1)
    assert not verdict["ok"]
    assert verdict["drift_count"] >= 1
    assert verdict["drift_bytes"] >= 4096
    held.clear()
    s2 = LeakSentinel("serve_batch", recorder=rec)
    s2.fence()
    tmp = jnp.ones(1024, dtype=jnp.float32) * 2.0
    del tmp
    assert s2.check(iterations=1)["ok"]
    kinds = {m["kind"] for m in rec.metrics}
    assert kinds == {"leak_check"}


def test_leak_fence_context_manager_sets_result():
    with leak_fence("promotion", iterations=3, recorder=RecStub()) as s:
        pass
    assert s.result is not None and s.result["iterations"] == 3


def test_leak_sentinel_contracts():
    with pytest.raises(ValueError):
        LeakSentinel("not_a_loop", recorder=RecStub())
    s = LeakSentinel("vm_swap", recorder=RecStub())
    with pytest.raises(RuntimeError):
        s.check(1)
    stats = live_array_stats()
    assert stats["count"] >= 0 and stats["bytes"] >= 0


# ------------------------------------------------- vocabulary pinning

def _schema_tool():
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    return cjs


def test_vocabularies_pinned_against_schema_tool():
    cjs = _schema_tool()
    assert set(MEMORY_COMPONENTS) == cjs.MEMORY_COMPONENTS
    assert set(LEAK_LOOPS) == cjs.LEAK_LOOPS
    assert set(FOOTPRINT_KEYS) < set(
        cjs.METRIC_KIND_REQUIRED["memory_footprint"])


# -------------------------------------------------------------- drills

def test_unknown_drill_raises():
    with pytest.raises(KeyError):
        run_drill("coffee_leak")


def test_drill_vm_swap_leak_reduced_scale():
    rec = RecStub()
    out = run_drill("vm_swap_leak", swaps=3, batches=6, recorder=rec)
    assert out["ok"], out
    assert out["drift_count"] == 0 and out["drift_bytes"] == 0
    assert out["batches"] == 6 and "seconds" in out
    assert any(m["kind"] == "leak_check" for m in rec.metrics)


def test_drill_snapshot_cache_bound():
    out = run_drill("snapshot_cache_bound", recorder=RecStub())
    assert out["ok"], out
    assert out["over_cap_observations"] == 0
    assert out["evicted"] and out["recent_rehit"]


# ------------------------------------------------- gated memory budgets

def _with_memory_budget(tmp_path, name, peak):
    """Copy the golden run dir, stamping ``peak_device_bytes`` onto its
    bench_stage rows (the gate reads the high-water mark across rows)."""
    dst = str(tmp_path / name)
    shutil.copytree(GOLDEN, dst)
    p = os.path.join(dst, "metrics.jsonl")
    with open(p) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    for r in rows:
        if r["kind"] == "bench_stage":
            r["peak_device_bytes"] = peak
    with open(p, "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)
    return dst


def test_injected_memory_regression_gates(tmp_path):
    base = _with_memory_budget(tmp_path, "base", 1_000_000)
    cand = _with_memory_budget(tmp_path, "cand", 1_000_000 + 65536)
    rows = compare_runs(base, cand)
    assert has_regression(rows)
    by = {r["metric"]: r["status"] for r in rows}
    assert by["peak_device_bytes"] == "REGRESSION"


def test_memory_noise_below_one_page_rides_out(tmp_path):
    base = _with_memory_budget(tmp_path, "base", 1_000_000)
    cand = _with_memory_budget(tmp_path, "cand", 1_000_000 + 4096)
    by = {r["metric"]: r["status"] for r in compare_runs(base, cand)}
    assert by["peak_device_bytes"] == "OK"


def test_memory_improvement_is_not_a_regression(tmp_path):
    base = _with_memory_budget(tmp_path, "base", 1_000_000)
    cand = _with_memory_budget(tmp_path, "cand", 500_000)
    rows = compare_runs(base, cand)
    assert not has_regression(rows)
    by = {r["metric"]: r["status"] for r in rows}
    assert by["peak_device_bytes"] == "IMPROVED"


def test_stale_fallback_memory_counts_for_baseline_only(tmp_path):
    p = tmp_path / "stale.jsonl"
    p.write_text(json.dumps({
        "benchmark": "fks_tpu", "value": 0.0, "unit": "evals/s",
        "stale_from_run": "round19.jsonl", "peak_device_bytes": 123456,
        "exe_temp_bytes": 789}) + "\n")
    assert "peak_device_bytes" not in extract_metrics(str(p))
    donor = extract_metrics(str(p), allow_stale=True)
    assert donor["peak_device_bytes"] == 123456.0
    assert donor["exe_temp_bytes"] == 789.0


# ------------------------------------------------------------ cli mem

def test_cli_mem_view_golden(capsys):
    assert cli.main(["mem", "--run-dir", GOLDEN]) == 0
    out = capsys.readouterr().out
    assert "memory" in out
    assert "lanes=2,pods=8" in out
    assert "leak sentinel" in out


def test_cli_mem_requires_a_mode(capsys):
    assert cli.main(["mem"]) == 2


def test_cli_mem_sample(capsys):
    assert cli.main(["mem", "--sample"]) == 0
    rec = json.loads(capsys.readouterr().out)
    assert rec["host_rss_kb"] > 0 and rec["devices"]
