"""Observability utilities: timing respects device sync, throughput math,
JSONL metrics schema, logger configuration. (These subsystems are framework
additions — the reference has neither profiler hooks nor ``logging``,
SURVEY.md §5 — so the tests define their contract.)"""
import json
import logging

import pytest
import jax
import jax.numpy as jnp

from fks_tpu.utils import (
    MetricsWriter, ThroughputMeter, block_timed, get_logger, result_record,
    timed,
)


def test_timed_syncs_registered_value(monkeypatch):
    """The clock must stop only after the value registered via t.sync() is
    materialized — i.e. block_until_ready is invoked on exactly that value
    at context exit (deleting the sync would regress to enqueue timing)."""
    from fks_tpu.utils import profiling

    synced = []
    monkeypatch.setattr(profiling.jax, "block_until_ready",
                        lambda v: synced.append(v))
    sentinel = object()
    with timed("eval") as t:
        got = t.sync(sentinel)
        assert synced == []  # not yet: only at context exit
    assert got is sentinel
    assert synced == [sentinel]
    assert t.seconds >= 0

    pre = object()
    with timed("pre-existing", sync=pre):
        pass
    assert synced == [sentinel, pre]


def test_block_timed_returns_materialized_result(monkeypatch):
    from fks_tpu.utils import profiling

    synced = []
    real = jax.block_until_ready
    monkeypatch.setattr(profiling.jax, "block_until_ready",
                        lambda v: (synced.append(v), real(v))[1])
    r, secs = block_timed(lambda a: a + 1, jnp.ones(8))
    assert float(r[0]) == 2.0
    assert secs > 0
    assert len(synced) == 1 and synced[0] is r


def test_throughput_meter_rate_is_total_over_total():
    m = ThroughputMeter()
    assert m.rate is None
    m.add(10, 1.0)
    m.add(30, 1.0)
    assert m.rate == 20.0  # 40 items / 2 s, not mean(10, 30)
    assert "40 in 2.00s" in m.summary()


def test_metrics_writer_jsonl(tmp_path):
    path = tmp_path / "m" / "run.jsonl"
    with MetricsWriter(str(path)) as w:
        w.write("bench", {"policy_score": 0.5}, policy="best_fit")
        w.write("generation", generation=1, best_score=0.9)
    lines = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["kind"] == "bench"
    assert lines[0]["policy"] == "best_fit"
    assert lines[0]["policy_score"] == 0.5
    assert "ts" in lines[0]
    assert lines[1]["best_score"] == 0.9


def test_throughput_meter_rate_none_at_zero_seconds():
    """Zero accumulated time must yield None, not ZeroDivisionError — a
    sub-resolution timed rep (perf_counter delta 0.0) feeds this."""
    m = ThroughputMeter()
    m.add(10, 0.0)
    assert m.rate is None
    assert m.summary() == "10 in 0.00s"
    m.add(10, 2.0)
    assert m.rate == 10.0  # 20 items / 2 s total


def test_block_timed_pytree_result(monkeypatch):
    """block_timed must materialize EVERY leaf of a pytree result (dicts/
    tuples of arrays), not just a lone array."""
    from fks_tpu.utils import profiling

    synced = []
    real = jax.block_until_ready
    monkeypatch.setattr(profiling.jax, "block_until_ready",
                        lambda v: (synced.append(v), real(v))[1])
    tree, secs = block_timed(
        lambda a: {"x": a + 1, "pair": (a * 2, a.sum())}, jnp.ones(4))
    assert float(tree["x"][0]) == 2.0
    assert float(tree["pair"][0][0]) == 2.0
    assert float(tree["pair"][1]) == 4.0
    assert secs > 0
    assert len(synced) == 1 and synced[0] is tree  # whole tree, one call


def test_device_trace_noop_when_profiler_unavailable(tmp_path, monkeypatch):
    """A backend without profiler support must not break the traced block,
    and stop_trace must not be called for a trace that never started."""
    from fks_tpu.utils import profiling

    stopped = []
    monkeypatch.setattr(
        profiling.jax.profiler, "start_trace",
        lambda d: (_ for _ in ()).throw(RuntimeError("no profiler")))
    monkeypatch.setattr(profiling.jax.profiler, "stop_trace",
                        lambda: stopped.append(True))
    ran = []
    with profiling.device_trace(str(tmp_path)):
        ran.append(True)
    assert ran == [True]
    assert stopped == []  # never started => never stopped


def test_device_trace_stops_started_trace(tmp_path, monkeypatch):
    from fks_tpu.utils import profiling

    calls = []
    monkeypatch.setattr(profiling.jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(profiling.jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    with profiling.device_trace(str(tmp_path)):
        pass
    assert calls == [("start", str(tmp_path)), ("stop",)]


def test_metrics_writer_coerces_accelerator_scalars(tmp_path):
    """Satellite fix: numpy/jax scalar fields must serialize instead of
    crashing json.dumps (device results leak into metric records)."""
    import numpy as np

    path = tmp_path / "m.jsonl"
    with MetricsWriter(str(path)) as w:
        w.write("bench", score=np.float32(0.5), n=np.int64(7),
                arr=np.arange(3), jscore=jnp.float32(0.25),
                jarr=jnp.arange(2))
    row = json.loads(path.read_text().splitlines()[0])
    assert row["score"] == 0.5 and row["n"] == 7
    assert row["arr"] == [0, 1, 2]
    assert row["jscore"] == 0.25 and row["jarr"] == [0, 1]


def test_metrics_writer_rejects_unserializable():
    from fks_tpu.utils.logging import json_ready

    with pytest.raises(TypeError):
        json_ready(object())


@pytest.mark.slow
def test_result_record_schema(default_workload):
    from fks_tpu.models import zoo
    from fks_tpu.sim.engine import SimConfig, simulate

    res = simulate(default_workload, zoo.ZOO["best_fit"](),
                   SimConfig(max_steps=500))
    rec = result_record(res, policy="best_fit")
    # reference metric schema (evaluator.py:16-25 + main.py:42,67-72)
    for key in ("policy_score", "avg_cpu_utilization", "avg_memory_utilization",
                "avg_gpu_count_utilization", "avg_gpu_memory_utilization",
                "gpu_fragmentation_score", "num_snapshots",
                "num_fragmentation_events", "events_processed",
                "scheduled_pods", "max_nodes"):
        assert key in rec
    json.dumps(rec)  # JSON-ready: plain python scalars only
    assert rec["policy"] == "best_fit"


def test_get_logger_single_handler():
    a = get_logger()
    b = get_logger("evolution")
    assert b.name == "fks_tpu.evolution"
    root = logging.getLogger("fks_tpu")
    assert len(root.handlers) == 1
    get_logger("again")
    assert len(root.handlers) == 1


@pytest.mark.slow
def test_cli_metrics_flag(tmp_path, default_workload):
    from fks_tpu.cli import main

    path = tmp_path / "bench.jsonl"
    rc = main(["bench", "--policies", "first_fit", "--metrics", str(path),
               "--trace", "openb_pod_list_default.csv"])
    assert rc == 0
    recs = [json.loads(line) for line in path.read_text().splitlines()]
    assert recs and recs[0]["kind"] == "bench"
    assert recs[0]["policy"] == "first_fit"
    assert abs(recs[0]["policy_score"] - 0.4292) < 1e-3
