"""Resilience-layer tests (fks_tpu.resilience) — the ISSUE-13
acceptance criteria, as tests:

- deadline budgets: expired requests fail with TYPED errors (shed at
  admission or ``DeadlineExceeded`` in queue), never hang;
- exactly-once Future completion in the batcher — a handler returning
  too few answers fails the unmatched Futures instead of zipping them
  into silence, and a handler exception fails every live Future once;
- bounded-queue shedding with a Retry-After hint, typed post-drain
  shed, and the legacy post-close RuntimeError kept intact;
- degraded-mode serving: a device fault flips the service to the
  reduced-batch exact fallback with 0.0 parity drift, then recovers
  through probation back to the primary;
- preemption safety: a REAL ``SIGTERM`` through the installed handler
  drains every Future and persists the replay buffer; torn state files
  are refused on load;
- the generation WAL: fsync'd records, torn-tail tolerance, and a
  mid-generation kill resumed with zero LLM calls and zero device
  evaluations;
- fsync'd checkpoints: a torn (half-written) checkpoint is refused
  with a targeted error instead of corrupting the population;
- the JSONL schema vocabulary: the new ``shed``/``degraded``/``drain``/
  ``resume_wal`` kinds enforce their required keys.

Everything here is CPU-hosted and event-gated (no sleeps as
synchronization); the serving stack is built once per module.
"""
import json
import os
import signal
import sys
import threading

import pytest

from fks_tpu.resilience import (
    AdmissionConfig, AdmissionController, Deadline, DeadlineExceeded,
    DegradeConfig, DrainCoordinator, GenerationWAL, ResilienceError,
    ShedError, classify_fault, load_serve_state,
)
from fks_tpu.serve.batcher import RequestBatcher

# ------------------------------------------------------- deadline units


def test_deadline_from_query_and_expiry():
    d = Deadline.after(1e-9)
    assert d.expired()
    assert d.remaining() <= 0.0
    q = {"deadline_ms": 50.0}
    d = Deadline.from_query(q, default_s=0.0)
    assert d is not None and not d.expired()
    assert 0.0 < d.remaining() <= 0.05 + 1e-6
    # the per-query deadline wins over the service default
    tight = Deadline.from_query({"deadline_ms": 0.0}, default_s=60.0)
    assert tight is not None and tight.expired()


def test_deadline_absent_means_none():
    assert Deadline.from_query({}, default_s=0.0) is None
    d = Deadline.from_query({}, default_s=60.0)
    assert d is not None and not d.expired()


def test_resilience_error_json_shape():
    e = ShedError("queue full", retry_after_s=0.25, reason="queue_full")
    j = e.to_json()
    assert j["kind"] == "shed" and j["retry_after_s"] == 0.25
    assert e.http_status == 503
    assert "retry_after_s" not in DeadlineExceeded("late").to_json()


# ------------------------------------------------------ admission units


def test_admission_queue_full_shed():
    ctl = AdmissionController(AdmissionConfig(max_queue=2))
    ctl.admit(None)
    ctl.admit(None)
    with pytest.raises(ShedError) as ei:
        ctl.admit(None)
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after_s >= ctl.cfg.min_retry_after_s
    assert ctl.shed_queue_full == 1 and ctl.depth == 2
    ctl.release(2)
    ctl.admit(None)  # room again
    assert ctl.submitted == 3


def test_admission_deadline_budget_shed():
    ctl = AdmissionController(AdmissionConfig(max_queue=0))
    # cold estimator: never shed on a guess, even with a queue
    ctl.admit(Deadline.after(0.001))
    # observed service time makes the projected wait exceed the budget
    ctl.note_batch(1, 1.0)  # 1 s per request
    with pytest.raises(ShedError) as ei:
        ctl.admit(Deadline.after(0.01))
    assert ei.value.reason == "deadline_budget"
    assert ctl.shed_deadline == 1
    # a roomy deadline is still admitted under the same estimate
    ctl.admit(Deadline.after(60.0))
    assert ctl.shed_rate == pytest.approx(1.0 / 3.0)


def test_admission_ewma_tracks_batches():
    ctl = AdmissionController(AdmissionConfig(ewma_alpha=0.5))
    ctl.note_batch(2, 0.2)  # 0.1 s/item
    ctl.note_batch(1, 0.3)  # ewma -> 0.5*0.3 + 0.5*0.1 = 0.2
    ctl.admit(None)
    assert ctl.projected_wait_s() == pytest.approx(0.2)


# -------------------------------------------------------- batcher units


def _gated_batcher(**kw):
    """A batcher whose worker parks inside the batch until released —
    the deterministic way to hold requests IN the queue."""
    gate, entered = threading.Event(), threading.Event()

    def handler(queries, enq):
        entered.set()
        if not gate.wait(30):
            raise RuntimeError("test gate never released")
        return list(queries)

    return RequestBatcher(handler, max_wait_s=0.0, **kw), gate, entered


def test_batcher_completes_and_counts():
    b = RequestBatcher(lambda qs, enq: [q * 2 for q in qs], max_batch=4)
    try:
        futs = [b.submit(i) for i in range(5)]
        assert [f.result(30) for f in futs] == [0, 2, 4, 6, 8]
        assert b.completed == 5 and b.submitted == 5
    finally:
        b.close()


def test_batcher_short_answer_list_fails_unmatched_futures():
    # the exactly-once audit: a handler dropping answers must FAIL the
    # unmatched Futures (the old zip() silently left them hanging)
    b = RequestBatcher(lambda qs, enq: [q for q in qs][:1],
                       max_batch=4, max_wait_s=0.01)
    try:
        futs = [b.submit(i) for i in range(3)]
        assert futs[0].result(30) == 0
        for f in futs[1:]:
            with pytest.raises(RuntimeError, match="answers for"):
                f.result(30)
    finally:
        b.close()


def test_batcher_handler_exception_fails_all_once():
    def boom(queries, enq):
        raise ValueError("device fell over")

    b = RequestBatcher(boom, max_batch=4, max_wait_s=0.01)
    try:
        futs = [b.submit(i) for i in range(3)]
        for f in futs:
            with pytest.raises(ValueError, match="device fell over"):
                f.result(30)
        assert b.completed == 0
    finally:
        b.close()


def test_batcher_pre_expired_deadline_sheds_at_submit():
    b = RequestBatcher(lambda qs, enq: list(qs), max_batch=2)
    try:
        with pytest.raises(ShedError):
            b.submit("x", deadline=Deadline.after(-1.0))
        # normal traffic is unharmed
        assert b.submit("y").result(30) == "y"
    finally:
        b.close()


def test_batcher_in_queue_expiry_is_typed():
    b, gate, entered = _gated_batcher(max_batch=1)
    try:
        first = b.submit("a")
        assert entered.wait(30)
        # queued behind the parked batch with a budget that lapses while
        # the worker is provably inside the blocked batch
        deadline = Deadline.after(0.02)
        late = b.submit("b", deadline=deadline)
        import time
        while not deadline.expired():
            time.sleep(0.001)
        gate.set()
        assert first.result(30) == "a"
        with pytest.raises(DeadlineExceeded):
            late.result(30)
        assert b.expired == 1 and b.admission.expired == 1
    finally:
        gate.set()
        b.close()


def test_batcher_bounded_queue_sheds_with_retry_after():
    b, gate, entered = _gated_batcher(max_batch=1, max_queue=1)
    try:
        first = b.submit("a")
        assert entered.wait(30)
        queued = b.submit("b")
        with pytest.raises(ShedError) as ei:
            b.submit("c")
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after_s >= 0.05
        gate.set()
        assert [first.result(30), queued.result(30)] == ["a", "b"]
    finally:
        gate.set()
        b.close()


def test_batcher_drain_then_typed_shed_then_close_runtimeerror():
    b = RequestBatcher(lambda qs, enq: list(qs), max_batch=2)
    futs = [b.submit(i) for i in range(3)]
    report = b.drain(grace_s=30.0)
    assert report["stuck"] is False
    assert all(f.result(0) == i for i, f in enumerate(futs))
    # post-drain submits shed with a TYPED error (clients can retry
    # against a replacement replica) ...
    with pytest.raises(ShedError) as ei:
        b.submit("late")
    assert ei.value.reason == "draining"
    # ... while a plain close() keeps the legacy contract
    b2 = RequestBatcher(lambda qs, enq: list(qs))
    b2.close()
    with pytest.raises(RuntimeError, match="closed"):
        b2.submit("x")


# -------------------------------------------------- fault classification


def test_classify_fault_vocabulary():
    from fks_tpu.resilience import DeviceFault, EngineBuildError, NaNFlood

    class XlaRuntimeError(Exception):  # name is what classification sees
        pass

    assert classify_fault(DeviceFault("lost")) == "device_fault"
    assert classify_fault(NaNFlood("flood")) == "nan_flood"
    assert classify_fault(EngineBuildError("bad build")) == "engine_build"
    assert classify_fault(XlaRuntimeError("dead device")) == "xla_runtime"
    assert classify_fault(ValueError("not a device fault")) is None


# ------------------------------------------------- serving stack (shared)


@pytest.fixture(scope="module")
def stack():
    """One warm incumbent + exact fallback for the degrade/drain tests."""
    import dataclasses

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.serve import ChampionSpec, ServeEngine, ShapeEnvelope

    wl = synthetic_workload(8, 16, seed=0)
    champ = ChampionSpec(code=template.fill_template("score = 1000"),
                         score=0.5, source="<test-seed>")
    env = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2)
    incumbent = ServeEngine(champ, wl, envelope=env, engine="flat")
    incumbent.warmup()
    fallback = ServeEngine(champ, wl,
                           envelope=dataclasses.replace(env, max_batch=1),
                           engine="exact")
    fallback.warmup()
    return {"incumbent": incumbent, "fallback": fallback, "workload": wl}


def _pods(stack, i, n=3):
    base = stack["incumbent"].base_pods
    return [dict(base[(i + j) % len(base)]) for j in range(n)]


def test_degraded_flip_serves_same_batch_with_zero_drift(stack):
    from fks_tpu.pipeline.faults import FlakyEngineProxy
    from fks_tpu.serve import ServeService

    flaky = FlakyEngineProxy(stack["incumbent"], failures=1)
    service = ServeService(flaky, max_wait_s=0.002)
    service.enable_degraded_mode(
        lambda: stack["fallback"],
        config=DegradeConfig(background_rebuild=False))
    try:
        pods = _pods(stack, 0)
        ans = service.submit({"pods": [dict(p) for p in pods]}).result(300)
        ref = stack["incumbent"].reference_answer(pods)
        assert abs(ans["score"] - ref["score"]) == 0.0
        hz = service.degrade.healthz()
        assert hz == {"state": "degraded", "flips": 1, "recoveries": 0,
                      "last_fault": "device_fault"}
        assert service.engine is stack["fallback"]
        assert service.healthz()["engine_state"] == "degraded"
    finally:
        service.close()


def test_degraded_recovery_through_probation(stack):
    from fks_tpu.pipeline.faults import FlakyEngineProxy
    from fks_tpu.serve import ServeService

    flaky = FlakyEngineProxy(stack["incumbent"], failures=1)
    service = ServeService(flaky, max_wait_s=0.002)
    mgr = service.enable_degraded_mode(
        lambda: stack["fallback"],
        rebuild_factory=lambda: stack["incumbent"],
        config=DegradeConfig(probation_requests=1,
                             background_rebuild=False))
    try:
        for i in range(4):
            service.submit({"pods": _pods(stack, i)}).result(300)
        hz = mgr.healthz()
        assert hz["state"] == "normal" and hz["recoveries"] == 1
        assert service.engine is stack["incumbent"]
    finally:
        service.close()


def test_unclassified_exception_still_raises(stack):
    from fks_tpu.serve import ServeService

    class Broken:
        def __getattr__(self, name):
            return getattr(stack["incumbent"], name)

        def answer_batch(self, queries):
            raise ValueError("a plain bug, not a device fault")

    service = ServeService(Broken(), max_wait_s=0.002)
    service.enable_degraded_mode(
        lambda: stack["fallback"],
        config=DegradeConfig(background_rebuild=False))
    try:
        fut = service.submit({"pods": _pods(stack, 0)})
        with pytest.raises(ValueError, match="plain bug"):
            fut.result(300)
        assert service.degrade.healthz()["state"] == "normal"
    finally:
        service.close()


# --------------------------------------------------------- drain + state


def test_real_sigterm_drains_and_persists(stack, tmp_path):
    from fks_tpu.serve import ServeService

    service = ServeService(stack["incumbent"], max_wait_s=0.002)
    state_path = str(tmp_path / "serve_state.json")
    dc = DrainCoordinator(service, state_path=state_path, grace_s=30.0)
    assert dc.install()  # main test thread
    try:
        futs = [service.submit({"pods": _pods(stack, i)}) for i in range(3)]
        os.kill(os.getpid(), signal.SIGTERM)
        # the Python-level handler runs at the next bytecode boundary of
        # this (main) thread; the loop body is that boundary
        import time
        t0 = time.monotonic()
        while dc.report is None:
            assert time.monotonic() - t0 < 30, "SIGTERM handler never ran"
        assert all(f.done() for f in futs)
        assert dc.report["stuck"] is False
        state = load_serve_state(state_path)
        assert state["requests_served"] >= 3
        assert len(state["replay"]) >= 3
    finally:
        dc.uninstall()

    # a fresh replica preloads the persisted replay buffer
    service2 = ServeService(stack["incumbent"], max_wait_s=0.002)
    try:
        assert service2.preload_replay(state["replay"]) == len(state["replay"])
    finally:
        service2.close()


def test_load_serve_state_refuses_torn_file(tmp_path):
    torn = tmp_path / "state.json"
    torn.write_text('{"version": 1, "replay": [')
    with pytest.raises(ValueError):
        load_serve_state(str(torn))
    torn.write_text(json.dumps({"version": 99, "replay": []}))
    with pytest.raises(ValueError):
        load_serve_state(str(torn))


# ---------------------------------------------------------- WAL + resume


def test_wal_round_trip_commit_and_views(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    wal = GenerationWAL(path)

    class Rec:
        code, score, error = "score = 1", 0.5, None
        scenario_scores, aggregation, budget_rung = None, None, None

    wal.record_codes(3, ["score = 1", "score = 2"])
    wal.record_eval(3, Rec())
    assert wal.pending_codes(3) == ["score = 1", "score = 2"]
    assert set(wal.cached_evals(3)) == {GenerationWAL.code_key("score = 1")}
    wal.commit(3)
    assert wal.committed(3)
    assert wal.pending_codes(3) is None and wal.cached_evals(3) == {}
    # a reopened WAL sees the same committed state (fsync'd)
    wal2 = GenerationWAL(path)
    assert wal2.committed(3) and wal2.summary()["generations"] == [3]


def test_wal_torn_tail_skipped_and_repaired(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    wal = GenerationWAL(path)
    wal.record_codes(1, ["score = 1"])
    with open(path, "a") as f:
        f.write('{"kind": "eval", "generation": 1, "ke')  # kill mid-write
    wal2 = GenerationWAL(path)
    assert wal2.skipped_lines == 1
    assert wal2.pending_codes(1) == ["score = 1"]
    wal2.commit(1)  # the repaired append stays its own parseable line
    assert GenerationWAL(path).committed(1)


def test_wal_resume_spends_zero_llm_calls(tmp_path):
    from fks_tpu.funsearch import EvolutionConfig
    from fks_tpu.funsearch import evolution as evo
    from fks_tpu.pipeline.faults import CountingBackend, KillSwitch
    from tests.test_engine_micro import micro_workload

    wl = micro_workload()
    ck, wal = str(tmp_path / "evo.json"), str(tmp_path / "wal.jsonl")

    def cfg():
        return EvolutionConfig(population_size=4, generations=2,
                               elite_size=2, candidates_per_generation=2,
                               max_workers=1, seed=3)

    fired = {}

    def kill_mid_gen2(stats):
        if stats.generation == 2 and not fired:
            fired["x"] = True
            raise KillSwitch("injected kill mid-generation")

    backend = CountingBackend(seed=3)
    with pytest.raises(KillSwitch):
        evo.run(wl, cfg(), backend=backend, checkpoint_path=ck,
                wal_path=wal, on_generation=kill_mid_gen2,
                log=lambda _m: None)
    assert backend.calls > 0

    backend2 = CountingBackend(seed=3)
    fs = evo.run(wl, cfg(), backend=backend2, checkpoint_path=ck,
                 wal_path=wal, log=lambda _m: None)
    assert backend2.calls == 0  # the whole point of the WAL
    assert fs.wal_replayed_codes > 0 and fs.wal_replayed_evals > 0
    assert fs.evaluator.compile_count == 0
    assert fs.generation == 2 and fs.best is not None
    assert GenerationWAL(wal).committed(2)

    # the SAME run replayed deterministically matches an uninterrupted one
    ck2, wal2 = str(tmp_path / "evo2.json"), str(tmp_path / "wal2.jsonl")
    fs_ref = evo.run(wl, cfg(), backend=CountingBackend(seed=3),
                     checkpoint_path=ck2, wal_path=wal2,
                     log=lambda _m: None)
    assert fs.best == fs_ref.best
    assert sorted(fs.population) == sorted(fs_ref.population)


def test_torn_checkpoint_refused(tmp_path):
    from fks_tpu.funsearch import (
        CodeEvaluator, EvolutionConfig, FakeLLM, FunSearch,
    )
    from tests.test_engine_micro import micro_workload

    fs = FunSearch(CodeEvaluator(micro_workload()),
                   EvolutionConfig(population_size=4, max_workers=1),
                   backend=FakeLLM(seed=1), log=lambda _m: None)
    torn = tmp_path / "evo.json"
    torn.write_text('{"version": 1, "generation": 2, "popul')
    with pytest.raises(ValueError, match="torn checkpoint"):
        fs.restore(str(torn))


# ------------------------------------------------------ schema vocabulary


def test_schema_enforces_new_resilience_kinds(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)

    good = [
        {"ts": 1, "kind": "shed", "reason": "queue_full",
         "queue_depth": 2, "retry_after_s": 0.05},
        {"ts": 2, "kind": "degraded", "fault": "xla_runtime",
         "state": "degraded"},
        {"ts": 3, "kind": "drain", "pending": 2, "completed": 2},
        {"ts": 4, "kind": "resume_wal", "generation": 2},
    ]
    p = tmp_path / "events.jsonl"
    p.write_text("".join(json.dumps(r) + "\n" for r in good))
    records = cjs.check_jsonl(str(p), required=("ts", "kind"))
    cjs.check_kinds(str(p), records, cjs.EVENT_KIND_REQUIRED)

    for rec, key in ((good[0], "reason"), (good[1], "state"),
                     (good[2], "pending"), (good[3], "generation")):
        bad = dict(rec)
        del bad[key]
        p.write_text(json.dumps(bad) + "\n")
        records = cjs.check_jsonl(str(p), required=("ts", "kind"))
        with pytest.raises(cjs.SchemaError, match=key):
            cjs.check_kinds(str(p), records, cjs.EVENT_KIND_REQUIRED)
