"""Sandbox validation semantics (reference: funsearch/safe_execution.py
SafeExecutor behavior — accept restricted math policies, reject escapes)."""
import pytest

from fks_tpu.funsearch import sandbox, template

GOOD = template.fill_template("score = 100 + pod.cpu_milli / max(1, node.cpu_milli_left)")


def test_accepts_good_policy():
    assert sandbox.validate(GOOD)


def test_seed_policies_validate_and_run():
    for name, code in template.seed_policies().items():
        assert sandbox.validate(code), name
        assert sandbox.smoke_test(code) is None, name


@pytest.mark.parametrize("bad", [
    "import os",
    "score = __builtins__",
    "score = eval('1')",
    "score = exec('x = 1')",
    "score = open('/etc/passwd')",
    "score = getattr(pod, 'cpu_milli')",
    "score = (lambda: 1)()",
    "while True:\n        score = 1",
])
def test_rejects_escapes(bad):
    code = template.fill_template(bad)
    assert not sandbox.validate(code)


def test_rejects_lambda_at_ast_stage():
    # 'lambda' is caught by the substring blacklist first; the AST stage
    # (validate_structure) must ALSO deny it on its own — defense in
    # depth for the node allowlist
    code = ("def priority_function(pod, node):\n"
            "    f = lambda: 1\n    return 1")
    r = sandbox.validate_structure(code)
    assert not r and "Lambda" in r.reason


def test_rejects_starred_call_and_slice():
    # neither Starred nor Slice is in the node allowlist (ast.Index /
    # ast.Slice were dropped from it — Index is never produced on
    # py3.9+, and slice syntax can never transpile)
    r = sandbox.validate(template.fill_template("score = max(*node.gpus)"))
    assert not r and "Starred" in r.reason
    r = sandbox.validate(
        "def priority_function(pod, node):\n"
        "    x = node.gpus[0:1]\n    return 1")
    assert not r and "Slice" in r.reason


def test_rejects_wrong_signature():
    assert not sandbox.validate("def priority_function(a, b):\n    return 1")
    assert not sandbox.validate("def other(pod, node):\n    return 1")
    assert not sandbox.validate(
        "def priority_function(pod, node):\n    return 1\nx = 2")


def test_rejects_non_whitelisted_call():
    code = template.fill_template("score = print(1)")
    r = sandbox.validate(code)
    assert not r and "print" in r.reason


def test_rejects_syntax_error():
    assert not sandbox.validate("def priority_function(pod, node:\n    return 1")


def test_scalar_execution_matches_hand_math():
    pod = sandbox.ScalarPod(cpu_milli=1000, memory_mib=2048, num_gpu=1,
                            gpu_milli=300)
    node = sandbox.ScalarNode(
        cpu_milli_left=5000, cpu_milli_total=8000,
        memory_mib_left=9000, memory_mib_total=16000, gpu_left=2,
        gpus=(sandbox.ScalarGPU(700, 1000), sandbox.ScalarGPU(200, 1000)))
    code = template.fill_template(
        "score = node.cpu_milli_left - pod.cpu_milli")
    # feasible (gpu0 fits 300): score = max(1, int(4000)) = 4000
    assert sandbox.execute_scalar(code, pod, node) == 4000.0


def test_scalar_execution_infeasible_returns_zero():
    pod = sandbox.ScalarPod(cpu_milli=99999, memory_mib=1, num_gpu=0, gpu_milli=0)
    node = sandbox.ScalarNode(1000, 1000, 1000, 1000, 0, ())
    assert sandbox.execute_scalar(GOOD, pod, node) == 0.0


def test_runtime_error_raises_policy_error():
    code = template.fill_template("score = 1 / (pod.num_gpu - pod.num_gpu)")
    pod = sandbox.ScalarPod(1, 1, 0, 0)
    node = sandbox.ScalarNode(1000, 1000, 1000, 1000, 0, ())
    # prologue passes (num_gpu=0): division by zero must surface as
    # PolicyRuntimeError, not crash the process
    with pytest.raises(sandbox.PolicyRuntimeError):
        sandbox.execute_scalar(code, pod, node)


# --------------------------------------------------- execution deadline guard

BOMB = template.fill_template(
    "score = 0\n"
    "    for i in range(1000000000):\n"
    "        score = score + 1")


def test_range_bomb_validates_but_times_out_in_bare_oracle():
    """The whitelist admits the loop; the SIGALRM deadline must fail it
    fast instead of hanging the host (reference safe_execution.py:81-96)."""
    assert sandbox.validate(BOMB)
    pod = sandbox.ScalarPod(1, 1, 0, 0)
    node = sandbox.ScalarNode(1000, 1000, 1000, 1000, 0, ())
    import time
    t0 = time.monotonic()
    with pytest.raises(sandbox.PolicyTimeoutError):
        sandbox.execute_scalar(BOMB, pod, node, timeout_s=0.2)
    assert time.monotonic() - t0 < 2.0


def test_deadline_restores_signal_state():
    import signal
    old = signal.getsignal(signal.SIGALRM)
    pod = sandbox.ScalarPod(1, 1, 0, 0)
    node = sandbox.ScalarNode(1000, 1000, 1000, 1000, 0, ())
    sandbox.execute_scalar(GOOD, pod, node, timeout_s=0.5)
    assert signal.getsignal(signal.SIGALRM) == old


def test_generator_transpiles_before_smoke(monkeypatch):
    """The generation path's bomb defence is ordering: MAX_UNROLL rejection
    at transpile happens BEFORE any scalar execution, so smoke_test must
    never be reached for a range bomb (the thread-pooled generator cannot
    arm SIGALRM)."""
    from fks_tpu.funsearch import llm

    def _boom(code):
        raise AssertionError("smoke_test ran before transpile rejection")

    monkeypatch.setattr(llm.sandbox, "smoke_test", _boom)

    class _Bomb:
        def complete(self, prompt):
            return ("score = 0\n"
                    "    for i in range(1000000000):\n"
                    "        score = score + 1")

    gen = llm.CandidateGenerator(_Bomb())
    assert gen.generate([]) is None  # rejected at the transpile stage
