"""CLI wiring tests (the heavy paths — full-trace bench/evolve — are
exercised by the engine/evolution suites; here we check the argparse
surface, discovery, and error handling)."""
import pytest

from fks_tpu import cli


def test_traces_lists_dataset(capsys):
    assert cli.main(["traces"]) == 0
    out = capsys.readouterr().out
    assert "openb_pod_list_default.csv" in out
    assert "openb_node_list_gpu_node.csv" in out


def test_bench_unknown_policy_errors(capsys):
    assert cli.main(["bench", "--policies", "nope"]) == 2


def test_evolve_requires_key_or_fake(capsys):
    assert cli.main(["evolve"]) == 2


def test_requires_subcommand():
    with pytest.raises(SystemExit):
        cli.main([])


def test_cli_scale_synthetic(capsys):
    from fks_tpu.cli import main

    rc = main(["scale", "--nodes-count", "16", "--pods-count", "300",
               "--pop", "2", "--seed", "1"])
    assert rc == 0
    import json as _json

    out = _json.loads(capsys.readouterr().out)
    assert out["pods"] == 300 and out["population"] == 2
    assert out["evals_per_sec"] > 0
    # calibrated load: the seed population should actually schedule
    assert out["score_max"] > 0
