"""CLI wiring tests (the heavy paths — full-trace bench/evolve — are
exercised by the engine/evolution suites; here we check the argparse
surface, discovery, and error handling)."""
import pytest

from fks_tpu import cli


def test_traces_lists_dataset(capsys):
    assert cli.main(["traces"]) == 0
    out = capsys.readouterr().out
    assert "openb_pod_list_default.csv" in out
    assert "openb_node_list_gpu_node.csv" in out


def test_bench_unknown_policy_errors(capsys):
    assert cli.main(["bench", "--policies", "nope"]) == 2


def test_evolve_requires_key_or_fake(capsys):
    assert cli.main(["evolve"]) == 2


def test_requires_subcommand():
    with pytest.raises(SystemExit):
        cli.main([])


def test_cli_scale_synthetic(capsys):
    from fks_tpu.cli import main

    rc = main(["scale", "--nodes-count", "16", "--pods-count", "300",
               "--pop", "2", "--seed", "1"])
    assert rc == 0
    import json as _json

    out = _json.loads(capsys.readouterr().out)
    assert out["pods"] == 300 and out["population"] == 2
    assert out["evals_per_sec"] > 0
    # calibrated load: the seed population should actually schedule
    assert out["score_max"] > 0


# ----------------------------------------------------------- round-4 depth:
# the CLI is the reported-evidence surface, so the fast tier drives the
# evolve loop end-to-end (checkpoint -> resume), the virtual-mesh scale
# path, and the --metrics JSONL schema, not just argparse wiring.

import json


@pytest.fixture
def micro_cli(monkeypatch, micro_workload):
    """Route the CLI's workload loading to the shared micro cluster so
    end-to-end command tests stay in the fast tier (full-trace paths are
    exercised by the engine/evolution suites and the slow tier)."""
    monkeypatch.setattr(cli, "_parse_workload",
                        lambda args: ("micro", micro_workload))
    return micro_workload


def test_evolve_end_to_end_with_checkpoint_and_resume(micro_cli, tmp_path,
                                                      capsys):
    ck = tmp_path / "evolve.ck.json"
    out = tmp_path / "champs"
    metrics = tmp_path / "m1.jsonl"
    rc = cli.main(["evolve", "--fake-llm", "--generations", "2",
                   "--engine", "exact", "--checkpoint", str(ck),
                   "--out", str(out), "--metrics", str(metrics)])
    assert rc == 0
    assert ck.exists()
    stdout = capsys.readouterr().out
    assert "best fitness:" in stdout
    saved = list(out.glob("*.json"))
    assert len(saved) >= 2  # top-K + best-policy JSONs

    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    gens = [r for r in rows if r["kind"] == "generation"]
    assert [g["generation"] for g in gens] == [1, 2]
    for key in ("best_score", "mean_score", "new_candidates", "accepted",
                "rejected_similar", "eval_seconds", "compile_count", "ts"):
        assert key in gens[0], key

    # resume: same checkpoint, deeper horizon -> continues at generation 3
    metrics2 = tmp_path / "m2.jsonl"
    rc = cli.main(["evolve", "--fake-llm", "--generations", "4",
                   "--engine", "exact", "--checkpoint", str(ck),
                   "--metrics", str(metrics2)])
    assert rc == 0
    rows2 = [json.loads(l) for l in metrics2.read_text().splitlines()]
    gens2 = [r["generation"] for r in rows2 if r["kind"] == "generation"]
    assert gens2 and gens2[0] == 3  # not restarted from 1
    assert gens2[-1] == 4


def test_evolve_champion_json_reference_schema(micro_cli, tmp_path, capsys):
    out = tmp_path / "champs"
    rc = cli.main(["evolve", "--fake-llm", "--generations", "1",
                   "--engine", "exact", "--out", str(out)])
    assert rc == 0
    best = [p for p in out.glob("funsearch_*.json")]
    assert best
    doc = json.loads(best[0].read_text())
    for key in ("code", "score", "generation", "timestamp"):  # ref schema
        assert key in doc, key
    assert "priority_function" in doc["code"]
    assert f"score{doc['score']:.4f}" in best[0].name


def test_scale_runs_sharded_over_virtual_mesh(tmp_path, capsys):
    metrics = tmp_path / "scale.jsonl"
    rc = cli.main(["scale", "--nodes-count", "8", "--pods-count", "80",
                   "--pop", "2", "--seed", "1", "--metrics", str(metrics)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["mode"] == "sharded over 8 devices"  # conftest's virtual mesh
    assert out["evals_per_sec"] > 0
    rows = [json.loads(l) for l in metrics.read_text().splitlines()]
    assert rows and rows[-1]["kind"] == "scale"
    assert rows[-1]["pods"] == 80


def test_scale_code_pop_reports_code_tier(capsys):
    rc = cli.main(["scale", "--nodes-count", "8", "--pods-count", "16",
                   "--pop", "2", "--seed", "1", "--engine", "flat",
                   "--code-pop", "2"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["mode"] == "sharded over 8 devices"
    assert out["code_population"] == 2
    assert out["code_evals_per_sec"] > 0
    assert out["code_engine"] == "flat"


def test_simulate_metrics_schema(micro_cli, tmp_path, capsys):
    metrics = tmp_path / "sim.jsonl"
    rc = cli.main(["simulate", "--policy", "best_fit",
                   "--metrics", str(metrics)])
    assert rc == 0
    row = json.loads(metrics.read_text().splitlines()[-1])
    assert row["kind"] == "simulate" and row["policy"] == "best_fit"
    # the reference-compatible result schema (utils.result_record)
    for key in ("policy_score", "avg_cpu_utilization",
                "avg_memory_utilization", "avg_gpu_count_utilization",
                "avg_gpu_memory_utilization", "gpu_fragmentation_score",
                "num_snapshots", "scheduled_pods", "failed", "truncated"):
        assert key in row, key


def test_metrics_bad_path_fails_fast(micro_cli, tmp_path):
    # missing parent dirs are created; a genuinely unopenable path (a
    # directory) must fail up front, before any simulation work
    with pytest.raises(OSError):
        cli.main(["simulate", "--policy", "best_fit",
                  "--metrics", str(tmp_path)])


def test_evolve_run_dir_then_report_smoke(micro_cli, tmp_path, capsys):
    """Tier-1 smoke (ISSUE 2 satellite): evolve --run-dir writes a valid
    flight-recorder directory, every JSONL line parses against the schema
    helper, and `cli report` renders the summary from the files alone."""
    run_dir = tmp_path / "run"
    rc = cli.main(["evolve", "--fake-llm", "--generations", "2",
                   "--engine", "exact", "--run-dir", str(run_dir)])
    assert rc == 0
    capsys.readouterr()

    # layout + line-by-line schema via the reusable tools/ helper
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    counts = cjs.check_run_dir(str(run_dir))
    assert counts["metrics.jsonl"] >= 2  # one ledger row per generation
    assert counts["events.jsonl"] >= 1
    assert counts["heartbeat"] == 1

    meta = json.loads((run_dir / "meta.json").read_text())
    assert meta["command"] == "evolve"
    assert meta["status"] == "ok"
    assert "best_score" in meta
    gens = [json.loads(l) for l
            in (run_dir / "metrics.jsonl").read_text().splitlines()
            if json.loads(l)["kind"] == "generation"]
    assert [g["generation"] for g in gens] == [1, 2]
    for key in ("median_score", "p10_score", "sandbox_failed",
                "transpile_failed", "rescore_fallbacks", "llm_seconds",
                "programs_compiled", "vm_segments"):
        assert key in gens[0], key
    kinds = {json.loads(l)["kind"] for l
             in (run_dir / "events.jsonl").read_text().splitlines()}
    # evolve spans now run under a generation trace ctx -> trace_span
    assert "trace_span" in kinds and "device" in kinds
    assert "compile" in kinds  # jax.monitoring listener captured compiles

    rc = cli.main(["report", str(run_dir)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "generations: 2" in out
    assert "status ok" in out
    assert "spans (by path" in out
    assert "compile events:" in out
    assert "fitness best" in out

    # a non-run directory errors cleanly, not with a traceback
    assert cli.main(["report", str(tmp_path / "nope")]) == 2


def test_scale_run_dir_records_mesh(tmp_path, capsys):
    run_dir = tmp_path / "run"
    rc = cli.main(["scale", "--nodes-count", "8", "--pods-count", "80",
                   "--pop", "5", "--seed", "1", "--run-dir", str(run_dir)])
    assert rc == 0
    capsys.readouterr()
    events = [json.loads(l) for l
              in (run_dir / "events.jsonl").read_text().splitlines()]
    mesh = [e for e in events if e["kind"] == "mesh"]
    assert mesh and mesh[0]["shards"] == 8
    # pop 5 on 8 shards pads 3 lanes
    assert mesh[0]["pad_lanes"] == 3
    assert mesh[0]["pad_waste_fraction"] == pytest.approx(3 / 8)
    rows = [json.loads(l) for l
            in (run_dir / "metrics.jsonl").read_text().splitlines()]
    assert rows[-1]["kind"] == "scale" and rows[-1]["evals_per_sec"] > 0
    rc = cli.main(["report", str(run_dir)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "mesh: 8 shards" in out and "pad waste 37.5%" in out


def test_divergence_bound_reads_latest_row(tmp_path):
    p = tmp_path / "audit.jsonl"
    rows = [{"trace": "t.csv", "max_abs_d": 0.01},
            {"trace": "casc.csv", "max_abs_d": 0.43, "max_drift": 0.008,
             "flat_cascades": 1},
            {"trace": "t.csv", "max_abs_d": 0.02},  # latest t.csv row wins
            {"trace": "t.csv", "error": "boom"}]  # error rows are skipped
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    # pre-cascade-era rows (no max_drift) fall back to max_abs_d
    assert cli._divergence_bound("t.csv", str(p)) == (0.02, 0)
    # cascade rows report arithmetic drift + the cascade count separately
    assert cli._divergence_bound("casc.csv", str(p)) == (0.008, 1)
    assert cli._divergence_bound("missing.csv", str(p)) is None
    assert cli._divergence_bound("t.csv", str(tmp_path / "nope")) is None
