"""Population-batched VM evaluation (fks_tpu.funsearch.vm.stack_programs +
backend._run_vm_batch). Contract: a stacked generation through ONE
population-engine launch produces fitness identical to per-candidate
evaluation, with zero per-candidate XLA compiles — the on-device
counterpart of the reference's subprocess fan-out
(funsearch/funsearch_integration.py:535-562)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.funsearch import backend, template, vm
from tests.test_vm import _corpus, _rand_views, G, N


def test_pad_capacity_is_semantically_neutral():
    """NOP padding never changes scores: score_static over the padded
    capacity equals score over the live op count."""
    rng = np.random.default_rng(11)
    code = list(template.seed_policies().values())[0]
    prog = vm.compile_policy(code, N, G)
    padded = vm.pad_capacity(prog, 2 * prog.capacity)
    assert padded.capacity == 2 * prog.capacity
    for _ in range(3):
        pod, nodes = _rand_views(rng)
        np.testing.assert_array_equal(
            np.asarray(vm.score(prog, pod, nodes)),
            np.asarray(vm.score_static(padded, pod, nodes)))


def test_stack_programs_shapes_and_bucket():
    codes = list(template.seed_policies().values())
    progs = [vm.compile_policy(c, N, G) for c in codes]
    stacked = vm.stack_programs(progs)
    longest = max(int(p.n_ops) for p in progs)
    assert stacked.opcode.shape[0] == len(progs)
    cap = stacked.opcode.shape[1]
    assert cap >= longest and cap & (cap - 1) == 0  # pow2 bucket
    assert stacked.n_ops.shape == (len(progs),)


def test_stacked_scores_match_per_candidate():
    """vmapped score_static over a stacked generation == per-candidate
    score, integer-exact."""
    rng = np.random.default_rng(5)
    codes = _corpus()[:6]
    progs = [vm.compile_policy(c, N, G) for c in codes]
    stacked = vm.stack_programs(progs)
    pod, nodes = _rand_views(rng)
    batched = jax.jit(jax.vmap(vm.score_static, in_axes=(0, None, None)))
    got = np.asarray(batched(stacked, pod, nodes))
    for i, prog in enumerate(progs):
        np.testing.assert_array_equal(
            got[i], np.asarray(vm.score(prog, pod, nodes)))


def test_evaluator_batches_a_generation(micro_workload):
    """evaluate() on a mixed generation: VM-able candidates land in ONE
    batched launch, the VM-unsupported one falls to the jit tier, a syntax
    error maps to 0.0 — and every fitness equals evaluate_one's."""
    wl = micro_workload
    vmable = _corpus()[:5]
    hard = template.fill_template(
        "gpus = sorted(g.gpu_milli_left for g in node.gpus)\n"
        "return max(1, gpus[0]) if pod.num_gpu == 0 else 1")
    codes = vmable[:3] + [hard, "def broken(:"] + vmable[3:]

    ev = backend.CodeEvaluator(wl, vm_batch=True)
    recs = ev.evaluate(codes)
    assert len(recs) == len(codes)
    assert ev.vm_batch_count == 1  # one device launch for the generation
    assert ev.vm_count == len(vmable)
    assert ev.compile_count == 1  # only the VM-unsupported candidate
    assert recs[4].score == 0.0 and "syntax" in recs[4].error

    solo = backend.CodeEvaluator(wl, vm_batch=False)
    for rec, code in zip(recs, codes):
        if code == "def broken(:":
            continue
        one = solo.evaluate_one(code)
        assert rec.score == one.score, code
        assert rec.ok == one.ok


def test_single_candidate_keeps_unbatched_vm_tier(micro_workload):
    wl = micro_workload
    ev = backend.CodeEvaluator(wl, vm_batch=True)
    code = list(template.seed_policies().values())[0]
    rec = ev.evaluate([code])[0]
    assert rec.ok
    assert ev.vm_batch_count == 0  # no population program for one lane
    assert ev.vm_count == 1 and ev.compile_count == 0


def test_duplicate_candidates_evaluate_once(micro_workload):
    wl = micro_workload
    ev = backend.CodeEvaluator(wl, vm_batch=True)
    codes = list(template.seed_policies().values())
    recs = ev.evaluate(codes + codes)
    assert ev.vm_count == len(codes)
    for a, b in zip(recs[:len(codes)], recs[len(codes):]):
        assert a.score == b.score


def test_const_pool_overflow_falls_back():
    """>CONST_POOL distinct literals -> VMUnsupported (the jit tier's
    job), never silent pool corruption."""
    body = "score = 1.0\n"
    terms = "\n".join(
        f"    score = score + {i}.{i:03d}1 * pod.cpu_milli"
        for i in range(vm.CONST_POOL + 2))
    code = template.fill_template(body + "    " + terms.strip())
    with pytest.raises(vm.VMUnsupported, match="constants"):
        vm.compile_policy(code, N, G, capacity=512)


def test_const_pool_preserves_signed_zero():
    """-0.0 and 0.0 are distinct pool entries: 1/min(x, -0.0) style math
    must match the jit tier's sign semantics."""
    lo = vm._Lowerer(N, G)
    r_pos = lo.const(0.0)
    r_neg = lo.const(-0.0)
    assert r_pos != r_neg
    import math
    assert math.copysign(1.0, lo.consts[r_neg - vm.N_INPUTS]) == -1.0


def _stack_corpus(wl, n):
    c = wl.cluster
    progs = [vm.compile_policy(code, c.n_padded, c.g_padded)
             for code in _corpus()[:n]]
    return vm.stack_programs(progs)


@pytest.mark.parametrize("seg_steps", [0, 3])
def test_sharded_code_eval_matches_single_device(micro_workload, seg_steps):
    """Mesh-sharded VM-batch evaluation (make_sharded_code_eval, pad
    lanes = duplicates of the last program) == the single-device vmapped
    population run to 1e-9, for both the one-dispatch and the segmented
    host-loop paths; elites never come from pad lanes."""
    from fks_tpu.parallel import (
        make_sharded_code_eval, pad_population, population_mesh,
    )
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import SimConfig

    wl = micro_workload
    stacked = _stack_corpus(wl, 6)
    mesh = population_mesh()
    padded, real = pad_population(stacked, mesh)
    assert real == 6 and padded.opcode.shape[0] == 8  # conftest mesh
    cfg = SimConfig()
    ev = make_sharded_code_eval(wl, mesh, cfg=cfg, elite_k=3,
                                engine="flat", seg_steps=seg_steps)
    res, elite_idx, elite_scores = ev(padded, real)
    ref = flat.make_population_run_fn(wl, vm.score_static, cfg)(
        stacked, flat.initial_state(wl, cfg))
    got = np.asarray(res.policy_score)[:real]
    want = np.asarray(ref.policy_score)
    np.testing.assert_allclose(got, want, atol=1e-9)
    ei = np.asarray(elite_idx)
    assert np.all(ei < real)  # pad duplicates never win elite slots
    np.testing.assert_allclose(np.asarray(elite_scores),
                               np.sort(want)[::-1][:3], atol=1e-9)
    np.testing.assert_allclose(want[ei], np.asarray(elite_scores),
                               atol=1e-9)


def test_evaluator_mesh_shards_the_generation(micro_workload):
    """CodeEvaluator(mesh=...) turns the batched tier on automatically and
    routes the generation through ONE sharded launch, with per-candidate
    fitness identical to the unbatched single-device tier."""
    from fks_tpu.parallel import population_mesh

    wl = micro_workload
    ev = backend.CodeEvaluator(wl, mesh=population_mesh())
    assert ev.vm_batch  # >1 mesh shard flips the auto default on CPU
    codes = _corpus()[:5]
    recs = ev.evaluate(codes)
    assert ev.vm_batch_count == 1
    solo = backend.CodeEvaluator(wl, vm_batch=False)
    for rec, code in zip(recs, codes):
        one = solo.evaluate_one(code)
        assert rec.ok and one.ok
        np.testing.assert_allclose(rec.score, one.score, atol=1e-9)


def test_segmented_batch_tier_matches_unsegmented(micro_workload, monkeypatch):
    """FKS_VM_SEG_STEPS forces the batched tier through the segmented
    runner (the TPU default — axon-tunnel kill-window protection); every
    generation fitness must match the monolithic launch."""
    monkeypatch.setenv("FKS_VM_SEG_STEPS", "3")
    seg = backend.CodeEvaluator(micro_workload, vm_batch=True, engine="flat")
    assert seg.vm_seg_steps == 3
    monkeypatch.setenv("FKS_VM_SEG_STEPS", "0")
    mono = backend.CodeEvaluator(micro_workload, vm_batch=True, engine="flat")
    assert mono.vm_seg_steps == 0
    codes = _corpus()[:4]
    a = seg.evaluate(codes)
    b = mono.evaluate(codes)
    assert seg.vm_batch_count == 1 and mono.vm_batch_count == 1
    for ra, rb in zip(a, b):
        assert ra.score == rb.score and ra.ok == rb.ok
