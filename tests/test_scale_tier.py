"""Large-cluster scale tier: top-k node prefiltering, packed state
dtypes, and the double-buffered segmented runner.

Contract under test (fks_tpu/sim/engine.py SimConfig doc):
- ``node_prefilter_k=0`` and ``state_pack=False`` compile the
  BIT-IDENTICAL program to the seed default (jaxpr-pinned);
- prefiltering is EXACT for feasibility-gated index-preferring policies
  (first_fit family): same fitness, same placements, on clean and
  faulted workloads, in both engines, at any k (k >= n_padded falls back
  to the dense scan);
- a cordoned node can never enter a candidate slot while any feasible
  node exists;
- ``state_pack`` is exact integer narrowing: bit-identical results;
- decision-trace rows and numeric_flags keep working over the gathered
  candidate view (COL_NODE is always the GLOBAL index);
- the double-buffered segmented runner matches the unsegmented runner
  exactly, with the scale knobs on or off.
"""
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.data.build import make_workload
from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.models import parametric, zoo
from fks_tpu.scenarios import get_suite
from fks_tpu.sim import engine, flat, fused
from fks_tpu.sim.engine import (
    SimConfig, _gather_node_view, _prefilter_candidates,
)
from fks_tpu.sim.types import NodeView, PodView, TraceBuffer
from fks_tpu.utils.segments import segment_budget

CLEAN = parametric.seed_weights("first_fit")


# ------------------------------------------------------------ config API

def test_resolve_prefilter_k():
    assert SimConfig().resolve_prefilter_k(16) == 0
    assert SimConfig(node_prefilter_k=8).resolve_prefilter_k(16) == 8
    # k >= n_padded: the candidate list would be the whole node axis —
    # fall back to the dense (bit-identical) program
    assert SimConfig(node_prefilter_k=16).resolve_prefilter_k(16) == 0
    assert SimConfig(node_prefilter_k=64).resolve_prefilter_k(16) == 0
    with pytest.raises(ValueError, match="node_prefilter_k"):
        SimConfig(node_prefilter_k=-1).resolve_prefilter_k(16)


def test_segment_budget():
    assert segment_budget(100, 10) == 11          # ceil + classic slack 1
    assert segment_budget(100, 10, slack=2) == 12  # double-buffered
    assert segment_budget(101, 10) == 12
    assert segment_budget(1, 4096) == 2


def test_fused_rejects_scale_knobs(micro_workload):
    with pytest.raises(ValueError, match="node_prefilter_k"):
        fused._build_plan(micro_workload, SimConfig(node_prefilter_k=1))
    with pytest.raises(ValueError, match="state_pack"):
        fused._build_plan(micro_workload, SimConfig(state_pack=True))


# ------------------------------------------------- candidate-list kernel

def _node_view_8():
    """8 nodes, 2 GPUs each; nodes 0-5 tiny (cpu 100), 6-7 roomy."""
    n, g = 8, 2
    cpu = jnp.asarray([100] * 6 + [64000] * 2, jnp.int32)
    mem = jnp.full((n,), 262144, jnp.int32)
    milli = jnp.full((n, g), 1000, jnp.int32)
    return NodeView(
        cpu_milli_left=cpu, cpu_milli_total=cpu,
        memory_mib_left=mem, memory_mib_total=mem,
        gpu_left=jnp.full((n,), g, jnp.int32),
        num_gpus=jnp.full((n,), g, jnp.int32),
        gpu_milli_left=milli, gpu_milli_total=milli,
        gpu_mem_total=jnp.full((n, g), 16384, jnp.int32),
        gpu_mask=jnp.ones((n, g), bool),
        node_mask=jnp.ones((n,), bool))


def _pod(cpu=4000, num_gpu=0, gpu_milli=0):
    return PodView(cpu_milli=jnp.int32(cpu), memory_mib=jnp.int32(1024),
                   num_gpu=jnp.int32(num_gpu),
                   gpu_milli=jnp.int32(gpu_milli),
                   creation_time=jnp.int32(0), duration_time=jnp.int32(10))


def test_prefilter_candidates_first_k_feasible():
    nodes = _node_view_8()
    # small pod: every node feasible -> first k ascending global indices
    cand = np.asarray(_prefilter_candidates(
        _pod(cpu=50), nodes, nodes.node_mask, 4))
    np.testing.assert_array_equal(cand, [0, 1, 2, 3])
    # big pod: only nodes 6, 7 fit; tail repeats the FIRST candidate
    cand = np.asarray(_prefilter_candidates(
        _pod(cpu=4000), nodes, nodes.node_mask, 4))
    np.testing.assert_array_equal(cand, [6, 7, 6, 6])


def test_prefilter_candidates_exclude_cordoned():
    nodes = _node_view_8()
    # cordon nodes 6 and 0: a cordoned node must never enter a slot
    # while any feasible node exists
    place_mask = nodes.node_mask & ~jnp.asarray(
        [True, False, False, False, False, False, True, False])
    cand = np.asarray(_prefilter_candidates(
        _pod(cpu=50), nodes, place_mask, 4))
    assert 6 not in cand and 0 not in cand
    np.testing.assert_array_equal(cand, [1, 2, 3, 4])
    # big pod under the same cordon: only node 7 survives; duplicates
    # all point at it
    cand = np.asarray(_prefilter_candidates(
        _pod(cpu=4000), nodes, place_mask, 4))
    np.testing.assert_array_equal(cand, [7, 7, 7, 7])
    # nothing feasible: the list degrades to node 0, which the caller's
    # place_mask[cand] re-mask scores to 0 (dense-sweep-equivalent fail)
    cand = np.asarray(_prefilter_candidates(
        _pod(cpu=999999), nodes, place_mask, 4))
    np.testing.assert_array_equal(cand, [0, 0, 0, 0])


def test_gather_node_view_shapes():
    nodes = _node_view_8()
    sub = _gather_node_view(nodes, jnp.asarray([6, 7, 6], jnp.int32))
    assert sub.cpu_milli_left.shape == (3,)
    assert sub.gpu_milli_left.shape == (3, 2)
    np.testing.assert_array_equal(np.asarray(sub.cpu_milli_left),
                                  [64000, 64000, 64000])


# -------------------------------------------------- jaxpr-pin discipline

@pytest.mark.parametrize("mod", [engine, flat], ids=["exact", "flat"])
def test_scale_knobs_off_compile_identical_program(micro_workload, mod):
    """k=0 + state_pack=False must be invisible to the compiler: same
    jaxpr as the seed default. k>0 (and, flat only, state_pack) change
    the program."""
    off = SimConfig(node_prefilter_k=0, state_pack=False)
    default = SimConfig()

    def jx(cfg):
        return str(jax.make_jaxpr(
            mod.make_param_run_fn(micro_workload, parametric.score, cfg))(
            CLEAN, mod.initial_state(micro_workload, cfg)))

    assert jx(off) == jx(default)
    # micro workload pads to 2 nodes, so k=1 is the smallest real filter
    assert jx(SimConfig(node_prefilter_k=1)) != jx(default)
    if mod is flat:
        assert jx(SimConfig(state_pack=True)) != jx(default)
    else:
        # the exact engine ignores state_pack entirely
        assert jx(SimConfig(state_pack=True)) == jx(default)


# ------------------------------------------------------- parity: default

def test_prefilter_parity_default_trace(default_workload):
    """Prefilter parity at 1e-5 with k in {0, 8, 64} on the default
    trace (16 padded nodes: k=8 really filters; k=64 >= n falls back to
    the dense program, pinned by jaxpr identity below). The two engines
    already differ by retry timing on this trace (first_fit delta 0.002,
    bounded at 4e-2 — see test_default_trace_close_to_exact), so the
    1e-5 budget is charged to what prefiltering ADDS: each engine's k=8
    run against its own dense k=0 run, and the cross-engine gap staying
    inside its documented bound at every k."""
    wl = default_workload
    policy = zoo.ZOO["first_fit"]()
    dense = {}
    for k in (0, 8):
        cfg = SimConfig(node_prefilter_k=k)
        ex = engine.simulate(wl, policy, cfg)
        fl = flat.simulate(wl, policy, cfg)
        assert int(ex.scheduled_pods) == int(fl.scheduled_pods)
        assert abs(float(ex.policy_score) - float(fl.policy_score)) <= 4e-2
        if k == 0:
            dense = {"exact": ex, "flat": fl}
        else:
            for name, res in (("exact", ex), ("flat", fl)):
                d = dense[name]
                assert abs(float(res.policy_score)
                           - float(d.policy_score)) <= 1e-5, name
                np.testing.assert_array_equal(
                    np.asarray(res.assigned_node),
                    np.asarray(d.assigned_node), err_msg=name)

    # k=64 on the 16-node trace: same compiled program as k=0, so the
    # k=0 parity above IS the k=64 parity — pin that claim
    for mod in (engine, flat):
        j64 = str(jax.make_jaxpr(
            mod.make_param_run_fn(wl, parametric.score,
                                  SimConfig(node_prefilter_k=64)))(
            CLEAN, mod.initial_state(wl, SimConfig(node_prefilter_k=64))))
        j0 = str(jax.make_jaxpr(
            mod.make_param_run_fn(wl, parametric.score, SimConfig()))(
            CLEAN, mod.initial_state(wl, SimConfig())))
        assert j64 == j0


# ------------------------------------------------------- parity: faulted

def test_prefilter_parity_faulted_smoke3():
    """Parity holds on a fault-injected scenario workload (cordon events
    flow through place_mask into the prefilter feasibility test)."""
    base = synthetic_workload(4, 24, seed=3)
    suite = get_suite("smoke3", base)
    assert suite.names[2] == "fault1"
    wl = suite.workloads[2]
    policy = zoo.ZOO["first_fit"]()
    dense_e = engine.simulate(wl, policy, SimConfig())
    for k in (1, 2):
        cfg = SimConfig(node_prefilter_k=k)
        ex = engine.simulate(wl, policy, cfg)
        fl = flat.simulate(wl, policy, cfg)
        assert abs(float(ex.policy_score) - float(fl.policy_score)) <= 1e-5
        assert abs(float(ex.policy_score)
                   - float(dense_e.policy_score)) <= 1e-5
        np.testing.assert_array_equal(np.asarray(ex.assigned_node),
                                      np.asarray(dense_e.assigned_node))


# ----------------------------------------------------------- state_pack

def test_state_pack_bit_identical():
    """Packed dtypes are exact integer narrowing: every observable in
    the SimResult matches the unpacked run bit for bit."""
    wl = synthetic_workload(8, 60, seed=2)
    policy = zoo.ZOO["best_fit"]()
    a = flat.simulate(wl, policy, SimConfig())
    b = flat.simulate(wl, policy, SimConfig(state_pack=True))
    for name, va, vb in zip(a._fields, a, b):
        if va is None:
            assert vb is None
            continue
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb),
                                      err_msg=name)
        # finalize widens packed columns back: dtypes config-independent
        assert np.asarray(va).dtype == np.asarray(vb).dtype, name


def test_state_pack_narrows_carry():
    wl = synthetic_workload(8, 60, seed=2)
    s = flat.initial_state(wl, SimConfig(state_pack=True))
    assert s.gpu_milli_left.dtype == jnp.int16
    assert s.wait_hist.dtype == jnp.int16
    s0 = flat.initial_state(wl, SimConfig())
    assert s0.gpu_milli_left.dtype == jnp.int32


# ------------------------------------------- trace + watchdog invariants

def _skewed_workload():
    """6 tiny nodes then 2 roomy ones; pods only fit on nodes >= 6, so a
    k=2 prefilter must gather the winner back to a GLOBAL index >= 6."""
    nodes = [{"node_id": f"n{i}", "cpu_milli": 100, "memory_mib": 262144,
              "gpus": [], "gpu_memory_mib": 0} for i in range(6)]
    nodes += [{"node_id": f"n{i}", "cpu_milli": 64000,
               "memory_mib": 262144, "gpus": [1000] * 2,
               "gpu_memory_mib": 16384} for i in (6, 7)]
    pods = [{"pod_id": f"p{i}", "cpu_milli": 4000, "memory_mib": 1024,
             "num_gpu": 0, "gpu_milli": 0, "creation_time": i,
             "duration_time": 50} for i in range(4)]
    return make_workload(nodes, pods)


@pytest.mark.parametrize("mod", [engine, flat], ids=["exact", "flat"])
def test_trace_records_global_node_index(mod):
    """TraceBuffer COL_NODE carries the GLOBAL node index after the
    prefilter gather-back, never the local top-k slot."""
    wl = _skewed_workload()
    cfg = SimConfig(node_prefilter_k=2, decision_trace=True)
    res = mod.simulate(wl, zoo.ZOO["first_fit"](), cfg)
    data = np.asarray(res.trace.data)
    count = int(res.trace.count)
    creates = data[:count][data[:count, TraceBuffer.COL_KIND] == 0]
    assert len(creates) == 4
    # all four pods land on the roomy nodes — a local slot would be 0/1
    assert set(creates[:, TraceBuffer.COL_NODE]) <= {6, 7}
    assert np.asarray(res.assigned_node)[0] == 6
    # and the placements match the dense program exactly
    dense = mod.simulate(wl, zoo.ZOO["first_fit"](), SimConfig())
    np.testing.assert_array_equal(np.asarray(res.assigned_node),
                                  np.asarray(dense.assigned_node))


@pytest.mark.parametrize("mod", [engine, flat], ids=["exact", "flat"])
def test_numeric_flags_survive_prefilter(mod):
    """The watchdog sees the gathered [k] score vector; a NaN-emitting
    policy must set the same sticky flags as under the dense sweep."""
    wl = _skewed_workload()

    def nan_policy(pod, nodes):
        return jnp.full(nodes.cpu_milli_left.shape, jnp.nan, jnp.float32)

    dense = mod.simulate(wl, nan_policy, SimConfig(watchdog=True))
    pre = mod.simulate(wl, nan_policy,
                       SimConfig(watchdog=True, node_prefilter_k=2))
    assert int(dense.numeric_flags) != 0
    assert int(pre.numeric_flags) == int(dense.numeric_flags)


# ----------------------------------------- segmented runner / population

def test_segmented_double_buffer_matches_unsegmented():
    wl = synthetic_workload(8, 96, seed=4)
    pop = 3
    params = jnp.tile(jnp.asarray(CLEAN)[None], (pop, 1))
    for cfg in (SimConfig(track_ctime=False),
                SimConfig(track_ctime=False, node_prefilter_k=4,
                          state_pack=True)):
        base = flat.make_population_run_fn(wl, parametric.score, cfg)(
            params, flat.initial_state(wl, cfg))
        for dbuf in (True, False):
            seg = flat.make_segmented_population_run(
                wl, parametric.score, cfg, seg_steps=32,
                double_buffer=dbuf)(params, flat.initial_state(wl, cfg))
            # score: the segmented finalize re-reduces the fitness sum
            # in a different association order — last-ulp float32 noise
            np.testing.assert_allclose(
                np.asarray(base.policy_score), np.asarray(seg.policy_score),
                rtol=1e-6)
            np.testing.assert_array_equal(
                np.asarray(base.assigned_node), np.asarray(seg.assigned_node))


def test_prefilter_under_vmap_population():
    """Prefilter parity holds lane-wise under vmap: a population of
    identical first_fit lanes scores identically with and without it."""
    wl = synthetic_workload(16, 64, seed=1)
    pop = 4
    params = jnp.tile(jnp.asarray(CLEAN)[None], (pop, 1))
    dense = flat.make_population_run_fn(
        wl, parametric.score, SimConfig())(
        params, flat.initial_state(wl, SimConfig()))
    cfg = SimConfig(node_prefilter_k=8, state_pack=True)
    pre = flat.make_population_run_fn(wl, parametric.score, cfg)(
        params, flat.initial_state(wl, cfg))
    np.testing.assert_array_equal(np.asarray(dense.policy_score),
                                  np.asarray(pre.policy_score))
    np.testing.assert_array_equal(np.asarray(dense.assigned_node),
                                  np.asarray(pre.assigned_node))


# --------------------------------------------------------- OpenB loader

def test_openb_node_yaml_loader(tmp_path, monkeypatch):
    from fks_tpu.data.traces import parse_node_yaml

    # repo-root-relative resolution: must work from a foreign cwd
    monkeypatch.chdir(tmp_path)
    nodes = parse_node_yaml()
    assert len(nodes) == 1213
    n0 = nodes[0]
    assert n0["cpu_milli"] == 64000
    assert n0["memory_mib"] == 262144
    assert n0["gpus"] == [1000, 1000]
    assert n0["gpu_memory_mib"] == 16280
    # every record is make_cluster-schema complete
    for n in nodes:
        assert set(n) >= {"node_id", "cpu_milli", "memory_mib", "gpus",
                          "gpu_memory_mib"}


def test_openb_nodes_feed_synthetic_workload():
    from fks_tpu.data.traces import parse_node_yaml

    nodes = parse_node_yaml()
    wl = synthetic_workload(32, 48, seed=0, nodes=nodes)
    assert wl.num_nodes == 32
    assert int(np.asarray(wl.cluster.cpu_total)[0]) == 64000
    with pytest.raises(ValueError, match="exceeds"):
        synthetic_workload(len(nodes) + 1, 8, nodes=nodes)


# ------------------------------------------------------- tooling wiring

def test_scale_tier_schema_and_compare_threshold(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    assert cjs.METRIC_KIND_REQUIRED["scale_tier"] == (
        "nodes", "pods", "events_per_sec", "node_prefilter_k",
        "state_pack")

    from fks_tpu.obs import compare
    th = compare.DEFAULT_THRESHOLDS["scale1k_events_per_sec"]
    assert th.higher_is_better and th.rel == 0.10

    # a bench scale1k JSON line feeds the comparator extractor
    p = tmp_path / "bench.jsonl"
    p.write_text('{"scale1k_events_per_sec": 5000.0}\n')
    rows = compare.compare_runs(str(p), str(p))
    assert any(r["metric"] == "scale1k_events_per_sec" for r in rows)


# ------------------------------------------------------- slow-tier smoke

@pytest.mark.slow
def test_scale_smoke_1k_nodes_10k_pods():
    """The scale-tier shape at reduced pod count: 1k nodes x 10k pods
    runs to completion through the double-buffered segmented runner with
    prefiltering + packed dtypes on (run_full_suite's slow tier; the
    full 100k-pod headline lives in bench.py --stage scale1k)."""
    wl = synthetic_workload(1000, 10000, seed=1)
    cfg = SimConfig(max_steps=4 * 10000, track_ctime=False,
                    node_prefilter_k=64, state_pack=True)
    pop = 2
    params = jnp.tile(jnp.asarray(CLEAN)[None], (pop, 1))
    run = flat.make_segmented_population_run(wl, parametric.score, cfg,
                                             seg_steps=8192)
    res = run(params, flat.initial_state(wl, cfg))
    assert not bool(np.asarray(res.truncated).any())
    assert not bool(np.asarray(res.failed).any())
    scheduled = np.asarray(res.scheduled_pods)
    assert (scheduled == scheduled[0]).all()
    assert int(scheduled[0]) >= 9500  # load-calibrated: ~all schedule
    assert np.isfinite(np.asarray(res.policy_score)).all()
