"""Cross-engine differential fuzzing on the adversarial micro corpus.

tests/test_differential.py pins the EXACT engine against the running
reference on 48 fuzz workloads; this file pins the other two engines
against each other on the same corpus:

- flat vs exact: bit-identical on every case with zero failed placements
  (the engines share all semantics except the retry-time rule, which
  only fires on failures — fks_tpu/sim/flat.py);
- fused vs flat: identical integer observables on a deterministic subset
  (interpret mode is slow, so 6 cases x 4 parametric candidates) —
  including cases WITH retries, drops, and fragmentation, where the two
  must still agree event for event.
"""
import json
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from fks_tpu.data.build import make_workload
from fks_tpu.models import parametric, zoo
from fks_tpu.sim import flat, fused
from fks_tpu.sim.engine import SimConfig

FIXTURE = pathlib.Path(__file__).parent / "fixtures" / "golden_fuzz.json"


def _workloads():
    with open(FIXTURE) as f:
        cases = json.load(f)["cases"]
    return [make_workload(c["nodes"], [dict(p) for p in c["pods"]],
                          pad_nodes_to=8, pad_gpus_to=4, pad_pods_to=40)
            for c in cases]


def test_flat_matches_exact_on_failure_free_fuzz_cases():
    from fks_tpu.parallel.traces import make_trace_batch_eval

    wls = _workloads()
    hits = 0
    for name in ("first_fit", "best_fit"):
        policy = zoo.ZOO[name]()
        pf = lambda _p, pod, nodes: policy(pod, nodes)  # noqa: E731
        cfg = SimConfig(wait_hist_size=1002)
        ex = make_trace_batch_eval(wls, pf, cfg, engine="exact")(
            jnp.zeros(1))
        fl = make_trace_batch_eval(wls, pf, cfg, engine="flat")(
            jnp.zeros(1))
        frag = np.asarray(ex.num_fragmentation_events)
        ok = frag == 0  # retry rule may legitimately diverge elsewhere
        for field, va, vb in zip(ex._fields, ex, fl):
            np.testing.assert_array_equal(
                np.asarray(va)[ok], np.asarray(vb)[ok],
                err_msg=f"{name}: {field}")
        hits += int(ok.sum())
    assert hits >= 4  # the corpus must keep providing comparable cases


def test_fused_matches_flat_on_fuzz_subset():
    wls = _workloads()[::8][:6]  # deterministic spread across the corpus
    cfg = SimConfig(track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(9), 4, noise=0.6)
    saw_failures = 0
    for wl in wls:
        run = fused.make_fused_population_run(wl, cfg, lanes=4,
                                              interpret=True)
        res = run(params)
        ref = flat.make_population_run_fn(wl, parametric.score, cfg)(
            params, flat.initial_state(wl, cfg))
        saw_failures += int(np.asarray(ref.num_fragmentation_events).sum() > 0)
        for field in ("events_processed", "scheduled_pods", "num_snapshots",
                      "num_fragmentation_events", "assigned_node",
                      "assigned_gpus", "cpu_left", "mem_left", "gpu_left",
                      "gpu_milli_left", "max_nodes", "truncated", "failed"):
            np.testing.assert_array_equal(
                np.asarray(getattr(res, field)),
                np.asarray(getattr(ref, field)), err_msg=field)
        np.testing.assert_allclose(
            np.asarray(res.policy_score), np.asarray(ref.policy_score),
            rtol=2e-6, atol=2e-6)
    assert saw_failures >= 2  # the subset must exercise the failure paths
