"""Full-trace parity vs the reference implementation (recorded fixtures).

The bar (SURVEY.md, BASELINE.json north star): fitness to 1e-5. With the
exact heap replica + float64 policy arithmetic we require far tighter:
identical event counts, snapshot counts, fragmentation events, per-pod
assignments, and fitness to ~1e-9.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.data import TraceParser
from fks_tpu.models import zoo
from fks_tpu.sim.engine import SimConfig, simulate

# best_fit stays in the fast tier as the default-trace parity sentinel;
# the other four run with the slow tier (-m slow)
POLICIES = [pytest.param("first_fit", marks=pytest.mark.slow),
            "best_fit",
            pytest.param("funsearch_4901", marks=pytest.mark.slow),
            pytest.param("funsearch_4816", marks=pytest.mark.slow),
            pytest.param("funsearch_4800", marks=pytest.mark.slow)]


def check_parity(res, ref, wl, tol=1e-9):
    assert not bool(res.failed)
    assert not bool(res.truncated)
    assert int(res.events_processed) == ref["events_processed"]
    assert int(res.num_snapshots) == ref["num_snapshots"]
    assert int(res.num_fragmentation_events) == ref["num_fragmentation_events"]
    assert int(res.scheduled_pods) == ref["scheduled_pods"]
    assert int(res.max_nodes) == ref["max_nodes"]
    n_pods = wl.num_pods
    np.testing.assert_array_equal(
        np.asarray(res.assigned_node)[:n_pods], np.array(ref["assignments"]))
    np.testing.assert_array_equal(
        np.asarray(res.pod_ctime)[:n_pods], np.array(ref["final_creation_time"]))
    n = wl.num_nodes
    np.testing.assert_array_equal(np.asarray(res.cpu_left)[:n],
                                  np.array(ref["final_cpu_left"]))
    gml = np.asarray(res.gpu_milli_left)
    for i, row in enumerate(ref["final_gpu_milli_left"]):
        assert gml[i, :len(row)].tolist() == row
    assert abs(float(res.policy_score) - ref["policy_score"]) < tol
    for k in ("avg_cpu_utilization", "avg_memory_utilization",
              "avg_gpu_count_utilization", "avg_gpu_memory_utilization",
              "gpu_fragmentation_score"):
        assert abs(float(getattr(res, k)) - ref[k]) < tol, k


@pytest.mark.parametrize("name", POLICIES)
def test_default_trace_parity(name, default_workload, golden_default):
    policy = zoo.ZOO[name](dtype=jnp.float64)
    res = simulate(default_workload, policy, SimConfig(score_dtype=jnp.float64))
    check_parity(res, golden_default["policies"][name], default_workload)


@pytest.mark.parametrize("pod_file,name", [
    ("openb_pod_list_gpushare40.csv", "best_fit"),
    ("openb_pod_list_gpuspec33.csv", "first_fit"),
    ("openb_pod_list_cpu250.csv", "best_fit"),
])
@pytest.mark.slow
def test_alt_trace_parity(pod_file, name, golden_alt):
    wl = TraceParser().parse_workload(pod_file=pod_file)
    policy = zoo.ZOO[name](dtype=jnp.float64)
    res = simulate(wl, policy, SimConfig(score_dtype=jnp.float64))
    check_parity(res, golden_alt[pod_file][name], wl)


@pytest.mark.slow
def test_float32_fitness_within_1e5(default_workload, golden_default):
    """The TPU-fast dtype must still meet the 1e-5 north-star bar on the
    default trace (placement decisions are integer; only evaluator sums and
    policy float math differ)."""
    res = simulate(default_workload, zoo.best_fit(dtype=jnp.float32),
                   SimConfig(score_dtype=jnp.float32))
    ref = golden_default["policies"]["best_fit"]
    assert int(res.num_snapshots) == ref["num_snapshots"]
    assert abs(float(res.policy_score) - ref["policy_score"]) < 1e-5
