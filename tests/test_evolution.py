"""Hermetic evolution-loop tests with the deterministic fake LLM — the
testability gap SURVEY.md §4 calls out in the reference (whose loop needs a
live OpenRouter key). Runs on the micro workload so a full multi-generation
evolution takes seconds."""
import json

import pytest

from fks_tpu.funsearch import (
    CodeEvaluator, EvolutionConfig, FakeLLM, FunSearch, seed_policies,
)
from fks_tpu.funsearch import evolution as evo
from tests.test_engine_micro import micro_workload


@pytest.fixture(scope="module")
def evaluator():
    return CodeEvaluator(micro_workload())


def quiet(_msg):
    pass


def make_fs(evaluator, **overrides):
    # max_workers=1: with a shared-RNG FakeLLM, >1 worker can permute which
    # draw lands on which future, breaking the bit-identical-resume checks
    cfg = EvolutionConfig(
        population_size=8, generations=2, elite_size=2,
        candidates_per_generation=4, max_workers=1, seed=7,
        early_stop_threshold=1.1,  # never early-stop in tests
        **overrides)
    return FunSearch(evaluator, cfg, backend=FakeLLM(seed=7), log=quiet)


def test_seeds_score_positive(evaluator):
    recs = evaluator.evaluate(list(seed_policies().values()))
    assert all(r.ok for r in recs)
    assert all(r.score > 0 for r in recs)


def test_failed_candidates_score_zero(evaluator):
    recs = evaluator.evaluate(["import os", "def priority_function(pod, node:"])
    assert [r.score for r in recs] == [0.0, 0.0]
    assert all(not r.ok for r in recs)


def test_compile_cache_hits_on_reformatted_code(evaluator0=None):
    ev = CodeEvaluator(micro_workload())
    code = list(seed_policies().values())[0]
    ev.evaluate([code])
    n = ev.compile_count
    ev.evaluate([code.replace("return max(1, int(score))",
                              "return max(1,  int(score))")])
    assert ev.compile_count == n  # same AST -> cached program


def test_evolution_runs_and_improves_or_holds(evaluator):
    fs = make_fs(evaluator)
    best_code, best_score = fs.run_evolution()
    assert best_score > 0
    assert "priority_function" in best_code
    assert fs.generation == 2
    assert len(fs.population) <= 8
    assert len(fs.history) == 2
    # population sorted desc, best tracks the top
    scores = [s for _, s in fs.population]
    assert scores == sorted(scores, reverse=True)
    assert best_score >= scores[0] - 1e-12


def test_evolution_deterministic(evaluator):
    a = make_fs(evaluator).run_evolution()
    b = make_fs(evaluator).run_evolution()
    assert a == b


def test_dedup_rejects_near_duplicates(evaluator):
    fs = make_fs(evaluator)
    fs.initialize_population()
    code, score = fs.population[0]
    assert fs._is_too_similar(code, score - 0.01)  # identical code, lower score
    assert not fs._is_too_similar("def priority_function(pod, node):\n"
                                  "    return 1\n", 0.0)


def test_early_stop(evaluator):
    fs = make_fs(evaluator)
    fs.cfg = EvolutionConfig(
        population_size=8, generations=5, elite_size=2,
        candidates_per_generation=4, max_workers=2, seed=7,
        early_stop_threshold=0.01)
    fs.run_evolution()
    assert fs.generation == 1  # seeds already beat 0.01 -> stop after gen 1


def test_checkpoint_resume_round_trip(evaluator, tmp_path):
    ck = str(tmp_path / "evo.json")
    fs = make_fs(evaluator)
    fs.initialize_population()
    fs.evolve_generation()
    fs.checkpoint(ck)
    mid_best = fs.best
    fs.evolve_generation()
    final = (fs.best, [s for _, s in fs.population], fs.generation)

    fs2 = make_fs(evaluator)
    fs2.restore(ck)
    assert fs2.generation == 1
    assert fs2.best == mid_best
    fs2.evolve_generation()
    resumed = (fs2.best, [s for _, s in fs2.population], fs2.generation)
    assert resumed == final  # bit-identical continuation (incl. RNG state)


def test_save_top_policies_schema(evaluator, tmp_path):
    fs = make_fs(evaluator)
    fs.initialize_population()
    path = fs.save_top_policies(str(tmp_path / "discovered"), k=2)
    with open(path) as f:
        payload = json.load(f)
    assert len(payload) == 2
    assert {"rank", "score", "generation", "code", "timestamp"} <= set(payload[0])
    assert payload[0]["rank"] == 1
    assert payload[0]["score"] >= payload[1]["score"]


def test_save_best_policy_schema(evaluator, tmp_path):
    """Single-champion JSON: reference filename pattern + {score,
    generation, code, timestamp} schema (funsearch_integration.py:606-633)."""
    fs = make_fs(evaluator)
    fs.initialize_population()
    path = fs.save_best_policy(str(tmp_path / "discovered"))
    assert "funsearch_" in path and "_score" in path
    with open(path) as f:
        payload = json.load(f)
    assert set(payload) == {"score", "generation", "code", "timestamp"}
    assert payload["score"] == fs.best[1]
    assert payload["code"] == fs.best[0]


def test_flat_engine_champions_rescored_on_exact(tmp_path):
    """Search on the fast (flat) engine, report on the exact engine: every
    persisted champion's ``score`` must be exact-engine fitness, with the
    raw search fitness alongside (round-2 verdict ask #3 — fast-engine
    fitness uses relaxed retry semantics and is not comparable to the
    reference's published table)."""
    from fks_tpu.sim.engine import simulate
    from fks_tpu.funsearch import transpiler

    wl = micro_workload()
    fs = make_fs(CodeEvaluator(wl, engine="flat"))
    fs.initialize_population()
    fs.evolve_generation()
    assert fs.best_exact is not None

    path = fs.save_best_policy(str(tmp_path / "discovered"))
    with open(path) as f:
        payload = json.load(f)
    assert {"score", "search_score", "search_engine"} <= set(payload)
    assert payload["search_engine"] == "flat"
    assert payload["search_score"] == fs.best[1]
    # the persisted score really is the exact engine's verdict on this code
    want = float(simulate(wl, transpiler.transpile(payload["code"])).policy_score)
    assert payload["score"] == pytest.approx(want, abs=1e-9)
    # filename carries the exact score, not the search score
    assert f"_score{payload['score']:.4f}" in path

    top = fs.save_top_policies(str(tmp_path / "discovered"), k=2)
    with open(top) as f:
        ranked = json.load(f)
    assert all({"score", "search_score", "search_engine"} <= set(r)
               for r in ranked)


def test_exact_engine_champions_have_no_search_fields(evaluator, tmp_path):
    """engine="exact" searches stay single-score: no redundant
    search_score/search_engine fields (the reference schema untouched)."""
    fs = make_fs(evaluator)
    fs.initialize_population()
    path = fs.save_best_policy(str(tmp_path / "discovered"))
    with open(path) as f:
        payload = json.load(f)
    assert set(payload) == {"score", "generation", "code", "timestamp"}
    assert fs.best_exact == fs.best[1]


def test_interrupt_mid_evolution_saves_champions(tmp_path, monkeypatch):
    """A KeyboardInterrupt inside the generation loop still leaves top-K +
    best champion JSONs and a checkpoint on disk (reference saves top-5 on
    interrupt, funsearch_integration.py:698-702)."""
    out = tmp_path / "discovered"
    ck = str(tmp_path / "evo.json")
    cfg = EvolutionConfig(population_size=6, generations=3, elite_size=2,
                          candidates_per_generation=2, max_workers=1, seed=3,
                          early_stop_threshold=1.1)
    calls = {"n": 0}
    orig = FunSearch.evolve_generation

    def interrupting(self):
        calls["n"] += 1
        if calls["n"] == 2:
            raise KeyboardInterrupt
        return orig(self)

    monkeypatch.setattr(FunSearch, "evolve_generation", interrupting)
    fs = evo.run(micro_workload(), cfg, backend=FakeLLM(3),
                 checkpoint_path=ck, out_dir=str(out), log=quiet)
    assert fs.best is not None
    saved = sorted(p.name for p in out.iterdir())
    assert any(p.startswith("top_policies_") for p in saved)
    assert any(p.startswith("funsearch_") for p in saved)
    import os
    assert os.path.exists(ck)


def test_config_from_reference_json(tmp_path):
    p = tmp_path / "llm_config.json"
    p.write_text(json.dumps({
        "openrouter": {"api_key": "k", "base_url": "https://x/v1",
                       "model": "m", "max_tokens": 100, "temperature": 0.3,
                       "timeout": 12.5, "max_retries": 1},
        "funsearch": {"population_size": 9, "generations": 3,
                      "early_stop_threshold": 0.5, "elite_size": 4,
                      "max_workers": 2},
    }))
    cfg = EvolutionConfig.from_json(str(p))
    assert cfg.population_size == 9
    assert cfg.elite_size == 4
    assert cfg.llm.model == "m"
    assert cfg.llm.temperature == 0.3
    assert cfg.llm.timeout == 12.5
    assert cfg.llm.max_retries == 1


def test_run_entry_point_with_checkpoint(tmp_path):
    ck = str(tmp_path / "run.json")
    cfg = EvolutionConfig(population_size=6, generations=1, elite_size=2,
                          candidates_per_generation=2, max_workers=2, seed=3,
                          early_stop_threshold=1.1)
    fs = evo.run(micro_workload(), cfg, backend=FakeLLM(3),
                 checkpoint_path=ck, log=quiet)
    assert fs.best is not None
    # resume picks up where the checkpoint left off
    fs2 = evo.run(micro_workload(), cfg, backend=FakeLLM(3),
                  checkpoint_path=ck, log=quiet)
    assert fs2.generation == 1  # already at generation budget; no extra gens


# ---------------------------------------------------- ISSUE 2: observability

def test_generation_stats_failure_classification():
    """EvalRecord errors split into transpile-fail (static rejection) vs
    sandbox-fail (raised while running) by prefix."""
    from fks_tpu.funsearch.backend import EvalRecord
    from fks_tpu.funsearch.evolution import _failure_counts

    records = [
        EvalRecord("a", 0.5, None),
        EvalRecord("b", 0.0, "syntax: invalid syntax"),
        EvalRecord("c", 0.0, "transpile: unsupported node"),
        EvalRecord("d", 0.0, "runtime: ZeroDivisionError"),
        EvalRecord("e", 0.0, "gpu allocation aborted"),
    ]
    sandbox, transpile = _failure_counts(records)
    assert transpile == 2
    assert sandbox == 2


def test_generation_stats_extended_fields(evaluator):
    fs = make_fs(evaluator)
    fs.initialize_population()
    stats = fs.evolve_generation()
    assert stats.p10_score <= stats.median_score <= stats.best_score
    assert stats.median_score > 0  # seeds score positive on the micro trace
    assert stats.sandbox_failed >= 0 and stats.transpile_failed >= 0
    assert stats.rescore_fallbacks == 0  # exact engine: no rescoring at all
    assert stats.llm_seconds >= 0
    # the ledger row carries every dataclass field + evaluator deltas
    row = fs.ledger.generation_record(stats)
    import dataclasses
    for f in dataclasses.fields(stats):
        assert f.name in row
    assert "programs_compiled" in row and "vm_segments" in row


def test_rescore_fallback_counter(evaluator, monkeypatch):
    """A transiently failing exact rescore increments the counter (and the
    per-generation delta lands in stats)."""
    fs = make_fs(evaluator)
    fs.evaluator = type(fs.evaluator)(micro_workload(), engine="flat")
    monkeypatch.setattr(
        type(fs.evaluator), "evaluate_one",
        lambda self, code: (_ for _ in ()).throw(RuntimeError("wedged")),
        raising=False)
    before = fs.rescore_fallbacks
    got = fs._exact_score("def priority_function(pod, node):\n    return 1\n",
                          0.42)
    assert got == 0.42  # falls back to the search fitness
    assert fs.rescore_fallbacks == before + 1


def test_restore_rejects_config_drift(evaluator, tmp_path):
    """Resuming a checkpoint under a different suite/aggregation/population
    would mix incomparable fitness scales — restore must fail loudly,
    naming the drifted keys."""
    import dataclasses

    ck = str(tmp_path / "evo.json")
    fs = make_fs(evaluator)
    fs.initialize_population()
    fs.checkpoint(ck)

    for key, value in (("population_size", 16),
                       ("scenario_suite", "default8"),
                       ("robust_aggregation", "cvar")):
        cfg2 = dataclasses.replace(fs.cfg, **{key: value})
        fs2 = FunSearch(evaluator, cfg2, backend=FakeLLM(seed=7), log=quiet)
        with pytest.raises(ValueError, match=key):
            fs2.restore(ck)
    # the matching config still restores
    fs3 = make_fs(evaluator)
    fs3.restore(ck)
    assert fs3.generation == fs.generation


def test_restore_tolerates_checkpoint_without_config(evaluator, tmp_path):
    """Pre-drift-check checkpoints carry no config block; they must keep
    restoring (drift detection is best-effort on old files)."""
    ck = tmp_path / "evo.json"
    fs = make_fs(evaluator)
    fs.initialize_population()
    fs.checkpoint(str(ck))
    state = json.loads(ck.read_text())
    del state["config"]
    ck.write_text(json.dumps(state))
    fs2 = make_fs(evaluator)
    fs2.restore(str(ck))
    assert fs2.generation == fs.generation


def test_llm_outage_circuit_breaker(tmp_path):
    """A total LLM outage (every call raises) halts the loop after N
    consecutive empty generations with the llm_outage flag up, a ledger
    event recorded, and the checkpoint still written by run()."""
    import os

    class DeadBackend:
        calls = 0

        def complete(self, prompt):
            DeadBackend.calls += 1
            raise RuntimeError("endpoint down")

    class EventRec:
        def __init__(self):
            self.events = []

        def event(self, kind, **fields):
            self.events.append({"kind": kind, **fields})

        def metric(self, kind, record=None, **fields):
            pass

        def heartbeat(self):
            pass

    rec = EventRec()
    ck = str(tmp_path / "evo.json")
    cfg = EvolutionConfig(population_size=6, generations=6, elite_size=2,
                          candidates_per_generation=3, max_workers=1,
                          seed=3, early_stop_threshold=1.1,
                          llm_outage_generations=2)
    fs = evo.run(micro_workload(), cfg, backend=DeadBackend(),
                 checkpoint_path=ck, out_dir=str(tmp_path / "out"),
                 recorder=rec, log=quiet)
    assert fs.llm_outage
    assert fs.generation == 2  # halted, not the 6-generation budget
    assert fs.best is not None  # seeds still scored
    assert os.path.exists(ck)  # the shutdown path checkpointed first
    assert DeadBackend.calls > 0
    outage = [e for e in rec.events if e["kind"] == "llm_outage"]
    assert outage and outage[0]["consecutive"] == 2


def test_llm_failures_reset_on_success(evaluator):
    """A flaky endpoint (one empty generation, then drafts) must NOT trip
    the breaker: the consecutive-failure counter resets."""
    fs = make_fs(evaluator, llm_outage_generations=2)
    fs.initialize_population()
    real_complete = fs.generator.backend.complete
    fs.generator.backend.complete = lambda prompt: (_ for _ in ()).throw(
        RuntimeError("down"))
    fs.evolve_generation()
    assert fs.llm_failures == 1
    fs.generator.backend.complete = real_complete
    fs.evolve_generation()
    assert fs.llm_failures == 0
    assert not fs.llm_outage
