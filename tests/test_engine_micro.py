"""Golden micro test: the reference's 2-node/4-pod scenario
(reference: tests/test_simulator.py) must produce identical placements,
GPU selections, and final cluster state."""
import jax.numpy as jnp
import numpy as np

from fks_tpu.data.build import make_workload
from fks_tpu.models.zoo import micro_best_fit
from fks_tpu.sim.engine import SimConfig, simulate


def micro_workload():
    nodes = [
        {"node_id": "node1", "cpu_milli": 8000, "memory_mib": 16000,
         "gpus": [1000, 1000], "gpu_memory_mib": 8000},
        {"node_id": "node2", "cpu_milli": 4000, "memory_mib": 8000, "gpus": []},
    ]
    pods = [
        {"pod_id": "pod1", "cpu_milli": 1000, "memory_mib": 2000, "num_gpu": 0,
         "gpu_milli": 0, "creation_time": 0, "duration_time": 10},
        {"pod_id": "pod2", "cpu_milli": 2000, "memory_mib": 4000, "num_gpu": 1,
         "gpu_milli": 500, "creation_time": 5, "duration_time": 15},
        {"pod_id": "pod3", "cpu_milli": 3000, "memory_mib": 6000, "num_gpu": 0,
         "gpu_milli": 0, "creation_time": 10, "duration_time": 8},
        {"pod_id": "pod4", "cpu_milli": 1500, "memory_mib": 3000, "num_gpu": 2,
         "gpu_milli": 400, "creation_time": 15, "duration_time": 12},
    ]
    return make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=4, pad_pods_to=8)


def bits_to_indices(bits):
    return sorted(i for i in range(32) if (int(bits) >> i) & 1)


def test_micro_matches_reference(golden_micro):
    wl = micro_workload()
    res = simulate(wl, micro_best_fit(dtype=jnp.float64),
                   SimConfig(score_dtype=jnp.float64))
    assert not bool(res.failed)
    assert not bool(res.truncated)
    n_pods = wl.num_pods
    got_nodes = np.asarray(res.assigned_node)[:n_pods].tolist()
    assert got_nodes == golden_micro["assignments"]
    got_gpus = [bits_to_indices(b) for b in np.asarray(res.assigned_gpus)[:n_pods]]
    assert got_gpus == golden_micro["assigned_gpus"]
    assert int(res.scheduled_pods) == golden_micro["scheduled_pods"]
    assert int(res.max_nodes) == golden_micro["max_nodes"]
    n = wl.num_nodes
    assert np.asarray(res.cpu_left)[:n].tolist() == golden_micro["final_cpu_left"]
    assert np.asarray(res.mem_left)[:n].tolist() == golden_micro["final_mem_left"]
    assert np.asarray(res.gpu_left)[:n].tolist() == golden_micro["final_gpu_left"]
    gml = np.asarray(res.gpu_milli_left)
    for i, row in enumerate(golden_micro["final_gpu_milli_left"]):
        assert gml[i, :len(row)].tolist() == row
