"""Trace-parser parity: totals and structure match the reference dataset facts.

Ground truth from SURVEY.md §2 fine print 11-12 and the recorded fixtures:
16 nodes / 48 GPUs / 48,000 gpu_milli; 8,152 pods (7,064 GPU pods).
"""
import numpy as np

from fks_tpu.data import TraceParser


def test_default_workload_totals(default_workload):
    wl = default_workload
    assert wl.num_nodes == 16
    assert wl.num_pods == 8152
    totals = wl.cluster.totals()
    # NOTE: SURVEY.md says 48 GPUs but running the reference gives 64
    # (10x2 P100 + 8 G3 + 2x8 V100M32 + 4 V100M16 + 2x8 G2 = 64).
    assert totals["gpu_count"] == 64
    assert totals["gpu_milli"] == 64_000
    ngpu = np.asarray(wl.pods.num_gpu)[np.asarray(wl.pods.pod_mask)]
    assert int((ngpu > 0).sum()) == 7064
    # num_gpu distribution (SURVEY fine print 11)
    vals, counts = np.unique(ngpu, return_counts=True)
    dist = dict(zip(vals.tolist(), counts.tolist()))
    assert dist == {0: 1088, 1: 6989, 2: 16, 4: 15, 8: 44}


def test_padding_masks(default_workload):
    wl = default_workload
    c, p = wl.cluster, wl.pods
    assert c.node_mask.sum() == 16
    assert p.pod_mask.sum() == 8152
    # padded slots contribute nothing
    assert c.cpu_total[~c.node_mask].sum() == 0
    assert c.gpu_milli_total[~c.gpu_mask].sum() == 0
    assert p.cpu[~p.pod_mask].sum() == 0


def test_tie_rank_matches_lexicographic_order(default_workload):
    p = default_workload.pods
    ids = list(p.pod_ids)
    rank = np.asarray(p.tie_rank)[: len(ids)]
    order_by_rank = [ids[i] for i in np.argsort(rank)]
    assert order_by_rank == sorted(ids)


def test_gpu_memory_mapping_applied(default_workload):
    c = default_workload.cluster
    # gpu_models_filtered.csv row 0 is a 2-GPU P100 node (16280 MiB per GPU)
    assert c.gpu_mem_total[0, 0] == 16280
    assert c.num_gpus[0] == 2


def test_node_and_pod_file_discovery():
    parser = TraceParser()
    # matches reference glob semantics (parser.py:103-115): openb_* only
    assert parser.get_available_node_files() == [
        "openb_node_list_all_node.csv", "openb_node_list_gpu_node.csv"]
    assert len(parser.get_available_pod_files()) == 23


def test_duration_derivation(default_workload):
    p = default_workload.pods
    # pod 0: creation 0, deletion 12537496 (CSV row 1)
    assert int(p.creation_time[0]) == 0
    assert int(p.duration[0]) == 12537496


def test_multigpu_trace_parses_with_defaults():
    # The reference parser crashes on these (missing columns); we accept them.
    pods = TraceParser().parse_pods("openb_pod_list_multigpu50.csv")
    assert pods.num_pods > 0
    assert int(np.asarray(pods.creation_time).max()) == 0
