"""Fused Pallas kernel (fks_tpu/sim/fused.py) vs the XLA flat engine.

Contract: for the same parametric population the fused kernel reproduces
the flat engine's trajectory EXACTLY on every integer observable
(placements, GPU picks, event/snapshot/fragmentation counts, final node
remnants, truncation/failure flags). Float accumulators (utilization
sums, fragmentation mean, policy score) may differ by a few ulp because
the two programs compile the same f32 arithmetic separately.

CPU runs use interpret mode, so workloads here are small; the TPU bench
path exercises the compiled kernel on the full default trace
(tools/tpu_probe.py --fused).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.data.build import make_workload
from fks_tpu.models import parametric
from fks_tpu.sim import flat, fused
from fks_tpu.sim.engine import SimConfig

INT_FIELDS = (
    "events_processed", "scheduled_pods", "num_snapshots",
    "num_fragmentation_events", "assigned_node", "assigned_gpus",
    "cpu_left", "mem_left", "gpu_left", "gpu_milli_left", "max_nodes",
    "truncated", "failed", "invariant_violations",
)
FLOAT_FIELDS = (
    "policy_score", "avg_cpu_utilization", "avg_memory_utilization",
    "avg_gpu_count_utilization", "avg_gpu_memory_utilization",
    "gpu_fragmentation_score",
)


def _assert_matches(res, ref):
    for f in INT_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(res, f)), np.asarray(getattr(ref, f)),
            err_msg=f)
    for f in FLOAT_FIELDS:
        np.testing.assert_allclose(
            np.asarray(getattr(res, f)), np.asarray(getattr(ref, f)),
            rtol=2e-6, atol=2e-6, err_msg=f)


def _run_both(wl, cfg, params, lanes=8):
    run = fused.make_fused_population_run(wl, cfg, lanes=lanes,
                                          interpret=True)
    res = run(params)
    pop = flat.make_population_run_fn(wl, parametric.score, cfg)
    ref = pop(params, flat.initial_state(wl, cfg))
    return res, ref


def _roomy():
    rng = np.random.default_rng(11)
    nodes = [{"node_id": f"n{i}", "cpu_milli": 64000, "memory_mib": 262144,
              "gpus": [1000] * 8, "gpu_memory_mib": 16384} for i in range(4)]
    pods = [{"pod_id": f"pod-{i:04d}",
             "cpu_milli": int(rng.integers(100, 1500)),
             "memory_mib": int(rng.integers(100, 4000)),
             "num_gpu": int(rng.integers(0, 3)),
             "gpu_milli": int(rng.integers(1, 300)),
             "creation_time": int(rng.integers(0, 1000)),
             "duration_time": int(rng.integers(0, 500))}
            for i in range(48)]
    for p in pods:
        if p["num_gpu"] == 0:
            p["gpu_milli"] = 0
    return make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=8,
                         pad_pods_to=64)


def _contended():
    rng = np.random.default_rng(7)
    nodes = [{"node_id": f"n{i}", "cpu_milli": 16000, "memory_mib": 32000,
              "gpus": [1000] * 2, "gpu_memory_mib": 8000} for i in range(4)]
    pods = [{"pod_id": f"pod-{i:04d}",
             "cpu_milli": int(rng.integers(500, 6000)),
             "memory_mib": int(rng.integers(500, 12000)),
             "num_gpu": int(rng.integers(0, 3)),
             "gpu_milli": int(rng.integers(100, 1000)),
             "creation_time": int(rng.integers(0, 300)),
             "duration_time": int(rng.integers(10, 200))}
            for i in range(96)]
    for p in pods:
        if p["num_gpu"] == 0:
            p["gpu_milli"] = 0
    return make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=2,
                         pad_pods_to=128)


@pytest.mark.slow
def test_roomy_population_matches_flat():
    wl = _roomy()
    cfg = SimConfig(track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(0), 8, noise=0.2)
    res, ref = _run_both(wl, cfg, params)
    assert int(np.asarray(ref.truncated).sum()) == 0
    _assert_matches(res, ref)


@pytest.mark.slow
def test_contended_population_matches_flat():
    """Retries, fragmentation events, silent drops, step-budget truncation
    — the full set of failure paths — must match event for event."""
    wl = _contended()
    cfg = SimConfig(track_ctime=False, max_steps=4 * 96)
    params = parametric.init_population(jax.random.PRNGKey(3), 8, noise=0.5)
    res, ref = _run_both(wl, cfg, params)
    assert int(np.asarray(ref.num_fragmentation_events).sum()) > 0
    _assert_matches(res, ref)


@pytest.mark.slow
def test_population_padding_to_lane_multiple():
    """pop not a multiple of lanes: results for the real candidates are
    unchanged by the padding rows."""
    wl = _roomy()
    cfg = SimConfig(track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(1), 5, noise=0.2)
    res, ref = _run_both(wl, cfg, params, lanes=8)
    assert np.asarray(res.policy_score).shape == (5,)
    _assert_matches(res, ref)


def test_builder_rejects_unsupported_configs():
    wl = _roomy()
    with pytest.raises(ValueError, match="best_fit"):
        fused.make_fused_population_run(
            wl, SimConfig(gpu_allocator="first_fit"))
    with pytest.raises(ValueError, match="audit"):
        fused.make_fused_population_run(
            wl, SimConfig(validate_invariants=True))


def test_fused_under_shard_map_matches_flat():
    """The pallas_call composes with shard_map over the population mesh:
    per-shard fused chunks + ICI all-gather elite selection must agree
    with the sharded flat engine."""
    from fks_tpu.parallel import make_sharded_eval, population_mesh

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    wl = _roomy()
    cfg = SimConfig(track_ctime=False)
    mesh = population_mesh(devices)
    pop = parametric.init_population(jax.random.PRNGKey(2),
                                     2 * len(devices), noise=0.3)
    sf, idxf, esf = make_sharded_eval(wl, mesh, cfg=cfg, elite_k=4,
                                      engine="fused")(pop)
    sl, idxl, esl = make_sharded_eval(wl, mesh, cfg=cfg, elite_k=4,
                                      engine="flat")(pop)
    np.testing.assert_allclose(np.asarray(sf), np.asarray(sl),
                               rtol=2e-6, atol=2e-6)
    np.testing.assert_array_equal(np.asarray(idxf), np.asarray(idxl))


def test_unified_population_eval_fused_engine():
    from fks_tpu.parallel import make_population_eval

    wl = _roomy()
    cfg = SimConfig(track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(4), 6, noise=0.2)
    res = make_population_eval(wl, cfg=cfg, engine="fused")(params)
    ref = make_population_eval(wl, cfg=cfg, engine="flat")(params)
    np.testing.assert_allclose(np.asarray(res.policy_score),
                               np.asarray(ref.policy_score),
                               rtol=2e-6, atol=2e-6)
    with pytest.raises(ValueError, match="parametric"):
        make_population_eval(wl, param_policy=lambda p, a, b: 0,
                             engine="fused")


def test_vmem_guard_rejects_scale_shapes():
    from fks_tpu.data.synthetic import synthetic_workload

    wl = synthetic_workload(1000, 100_000, seed=0)
    with pytest.raises(ValueError, match="VMEM"):
        fused.make_fused_population_run(wl, SimConfig(track_ctime=False),
                                        interpret=True)


def test_sharded_generation_step_fused():
    """device_evolution's training step (eval -> all-gather -> top-k ->
    mutate) drives the fused engine end to end on the virtual mesh."""
    from fks_tpu.parallel import make_sharded_generation_step, population_mesh

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    wl = _roomy()
    cfg = SimConfig(track_ctime=False)
    mesh = population_mesh(devices)
    pop = parametric.init_population(jax.random.PRNGKey(5),
                                     2 * len(devices), noise=0.2)
    step = make_sharded_generation_step(wl, mesh, cfg=cfg, elite_k=4,
                                        engine="fused")
    new_pop, scores, elite_scores = step(pop, jax.random.PRNGKey(6))
    assert new_pop.shape == pop.shape
    assert np.isfinite(np.asarray(scores)).all()
    assert float(np.max(elite_scores)) >= float(np.min(scores))


def test_parametric_evolution_on_fused_engine():
    """ParametricEvolution (device-resident weight evolution) driving the
    fused kernel for 2 generations improves-or-holds its best score."""
    from fks_tpu.funsearch.device_evolution import ParametricEvolution

    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs the multi-device CPU mesh")
    pe = ParametricEvolution(_roomy(), pop_size=2 * len(devices),
                             cfg=SimConfig(track_ctime=False),
                             engine="fused", seed=1)
    first = pe.run(1)
    second = pe.run(1)
    assert pe.generation == 2
    assert second.best_score >= 0.0
    assert pe.best_score >= first.best_score
    assert "priority_function" in pe.best_code()


@pytest.mark.skipif(
    tuple(int(x) for x in jax.__version__.split(".")[:3]) < (0, 5, 0),
    reason="jax 0.4.x Mosaic cannot lower integer reductions (its "
           "lowering raises NotImplementedError 'Reductions over integers "
           "not implemented' on the kernel's i32 min/sum sweeps); the "
           "kernel's primitive set is pinned on jax >= 0.5 where the "
           "lowering exists")
def test_mosaic_lowering_for_tpu_from_cpu():
    """The kernel LOWERS for the TPU target (host-side Mosaic pass) even
    on a CPU-only host. Interpret mode accepts primitives real Mosaic
    rejects — the first on-hardware compile of this kernel failed on a
    ``.at[:, 0].set`` scatter that every interpret-mode test had passed
    (round-4 session, stage fused64). This pins the full primitive set:
    any future edit that sneaks a non-lowerable op in fails HERE, not in
    a scarce healthy-tunnel window."""
    wl = _roomy()
    cfg = SimConfig(max_steps=4 * 48, track_ctime=False)
    params = parametric.init_population(jax.random.PRNGKey(0), 8, noise=0.1)
    run = fused.make_fused_population_run(wl, cfg, lanes=8, interpret=False)
    # lower under the kernel's real conditions: the session runs without
    # x64 (the kernel pins i32/f32); under the test harness's global x64
    # the mosaic pass recurses without terminating (jax-internal), which
    # no production path ever hits
    prev_x64 = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", False)
    try:
        low = jax.jit(run).trace(params).lower(lowering_platforms=("tpu",))
    finally:
        jax.config.update("jax_enable_x64", prev_x64)
    assert "tpu_custom_call" in low.as_text()
