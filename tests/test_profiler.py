"""Device-time attribution profiler (fks_tpu.obs.profiler).

The acceptance bar this file holds: a profiled flat-CPU evolve smoke
attributes >= 95% of the measured wall to named stages; the per-stage
compile split agrees with the CompileWatcher's own deltas; the DISABLED
path is a pure no-op (no records, no fences, bit-identical lowering —
also pinned as ``flat_step/profiled`` in the jaxpr manifest); and the
occupancy math (``parallel.mesh.occupancy_stats``) folds pad/scenario/
segment axes the way ``utilization_pct`` expects.
"""
import json
import pathlib
import time

import jax
import jax.numpy as jnp
import pytest

from fks_tpu.obs.profiler import (
    NULL_PROFILER, StageProfiler, profile_launch,
)
from fks_tpu.obs.telemetry import CompileWatcher
from fks_tpu.parallel.mesh import occupancy_stats, pad_stats

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


class _Recorder:
    """Capture ``metric(kind, ...)`` calls (NullRecorder-shaped; the
    profiler's owned CompileWatcher also routes ``event`` through it)."""

    def __init__(self):
        self.rows = []

    def metric(self, kind, *dicts, **fields):
        row = {"kind": kind}
        for d in dicts:
            row.update(d)
        row.update(fields)
        self.rows.append(row)

    def event(self, *a, **kw):
        pass


def _fresh_jit():
    # a new lambda each call -> a new jit cache entry -> a real compile
    return jax.jit(lambda x: jnp.sin(x) * 2.0 + jnp.sum(x))


def test_stage_records_wall_and_compile_split():
    rec = _Recorder()
    with StageProfiler(scope="t", recorder=rec) as prof:
        f = _fresh_jit()
        x = jnp.ones(64)
        with prof.stage("warm", lanes=64) as h:
            h.sync(f(x))
        with prof.stage("steady") as h:
            h.sync(f(x))
    warm, steady = prof.records
    assert warm["stage"] == "warm" and warm["scope"] == "t"
    assert warm["lanes"] == 64 and warm["depth"] == 0
    assert warm["compile_count"] >= 1
    assert 0.0 < warm["compile_seconds"] <= warm["wall_seconds"]
    # the second call hits the jit cache: no compile charged
    assert steady["compile_count"] == 0
    assert steady["compute_seconds"] == steady["wall_seconds"]
    # each stage landed as one device_profile metric
    assert [r["kind"] for r in rec.rows] == ["device_profile"] * 2


def test_compile_split_matches_watcher():
    watcher = CompileWatcher().install()
    try:
        prof = StageProfiler(scope="t", recorder=_Recorder(),
                             watcher=watcher)
        x = jnp.ones(32)  # fill-program compile, BEFORE the baselines
        jax.block_until_ready(x)
        s0 = watcher.backend_compile_seconds
        n0 = watcher.backend_compile_count
        for name in ("a", "b"):
            with prof.stage(name) as h:
                h.sync(_fresh_jit()(x))
        got_n = sum(r["compile_count"] for r in prof.records)
        got_s = sum(r["compile_seconds"] for r in prof.records)
        assert got_n == watcher.backend_compile_count - n0 >= 2
        assert got_s == pytest.approx(
            watcher.backend_compile_seconds - s0, abs=1e-5)
    finally:
        watcher.uninstall()


def test_nested_stage_depth_excluded_from_summary():
    prof = StageProfiler(scope="t", recorder=_Recorder())
    with prof.stage("outer"):
        with prof.stage("inner"):
            time.sleep(0.01)
    prof.close()
    by = {r["stage"]: r for r in prof.records}
    assert by["inner"]["depth"] == 1 and by["outer"]["depth"] == 0
    # the inner stage's wall is already inside the outer's: only depth-0
    # stages count toward the attributed total
    summ = prof.summary(measured_wall=by["outer"]["wall_seconds"])
    assert [s["stage"] for s in summ["stages"]] == ["outer"]


def test_disabled_profiler_is_pure_noop():
    assert not NULL_PROFILER.enabled
    sentinel = object()  # block_until_ready would choke on this
    with NULL_PROFILER.stage("anything", lanes=8) as h:
        assert h.sync(sentinel) is sentinel
        h.annotate(ignored=1)
        assert h.record is None
    NULL_PROFILER.segment_tick()
    assert NULL_PROFILER.records == []
    assert NULL_PROFILER.watcher is None


def test_profiled_lowering_bit_identical(micro_workload):
    from fks_tpu.models import zoo
    from fks_tpu.sim import flat
    from fks_tpu.sim.engine import SimConfig, loop_tables

    cfg = SimConfig()
    ktable, max_steps = loop_tables(micro_workload, cfg)
    step = flat.build_step(micro_workload, zoo.first_fit(), cfg, ktable,
                           max_steps)
    s0 = flat.initial_state(micro_workload, cfg)
    base = str(jax.make_jaxpr(step)(s0))
    with StageProfiler(scope="t", recorder=_Recorder()) as prof:
        with prof.stage("pin"):
            inside = str(jax.make_jaxpr(step)(s0))
    assert inside == base


def test_manifest_pins_profiled_path():
    with open(FIXTURES / "jaxpr_pins.json") as f:
        pins = json.load(f)["pins"]
    assert "flat_step/profiled" in pins
    assert pins["flat_step/profiled"] == pins["flat_step/baseline"]


def test_occupancy_stats_folds_axes():
    s = occupancy_stats(3, 4)
    assert s["real_count"] == 3 and s["padded_count"] == 4
    assert s["pad_waste_fraction"] == pytest.approx(0.25)
    assert s["launched_lane_steps"] == 4 and s["real_lane_steps"] == 3
    s = occupancy_stats(3, 4, scenarios=2, segments=5)
    assert s["launched_lane_steps"] == 40 and s["real_lane_steps"] == 30
    # degenerate inputs clamp instead of exploding
    assert occupancy_stats(0, 4)["pad_waste_fraction"] == 0.0
    assert occupancy_stats(4, 4, scenarios=0)["scenarios"] == 1
    # base keys come straight from pad_stats
    assert set(pad_stats(3, 4)) <= set(s)


def test_utilization_from_occupancy_and_flops():
    prof = StageProfiler(scope="t", recorder=_Recorder())
    f = _fresh_jit()
    x = jnp.ones(16)
    h0 = h = None
    with prof.stage("eval", **occupancy_stats(3, 4)) as h:
        h.sync(f(x))  # compile inside: utilization must discount it
        h.annotate(cost_flops=1e6)
    with prof.stage("eval2", pad_waste_fraction=0.0) as h0:
        h0.sync(f(x))
    prof.close()
    r, r0 = h.record, h0.record
    assert r["occupancy"] == pytest.approx(0.75)
    # occupancy * compute/wall * 100 — compile time can't be utilized
    assert r["utilization_pct"] == pytest.approx(
        100.0 * 0.75 * r["compute_seconds"] / r["wall_seconds"], abs=0.01)
    assert r["est_flops_per_sec"] == pytest.approx(
        1e6 / r["compute_seconds"], rel=1e-3)
    assert r0["utilization_pct"] == pytest.approx(100.0, abs=0.1)


def test_profile_launch_record_shape():
    prof = StageProfiler(scope="t", recorder=_Recorder())
    f = _fresh_jit()
    out, rec = profile_launch(f, jnp.ones(8), name="step", profiler=prof,
                              reps=3)
    prof.close()
    assert out.shape == (8,)
    assert rec["name"] == "step" and rec["reps"] == 3
    assert rec["compile_count"] >= 1
    assert 0.0 < rec["best_seconds"] <= rec["steady_total_seconds"]
    assert rec["compile_seconds"] <= rec["first_call_seconds"]
    stages = [r["stage"] for r in prof.records]
    assert stages == ["step:compile", "step:steady"]
    # the disabled path still measures best_seconds, without stage records
    out2, rec2 = profile_launch(f, jnp.ones(8), name="off")
    assert "compile_seconds" not in rec2 and rec2["best_seconds"] > 0


def test_summary_attribution_and_emit():
    rec = _Recorder()
    prof = StageProfiler(scope="t", recorder=rec)
    for name, secs in (("a", 0.03), ("b", 0.01)):
        with prof.stage(name, pad_waste_fraction=0.5):
            time.sleep(secs)
    prof.close()
    summ = prof.summary(measured_wall=0.05, emit=True)
    assert [s["stage"] for s in summ["stages"]] == ["a", "b"]
    assert summ["attributed_fraction"] >= 0.75
    assert summ["attributed_fraction"] + summ["idle_fraction"] == \
        pytest.approx(1.0, abs=1e-3)
    # annotated utilization survives aggregation (wall-weighted mean)
    assert all("utilization_pct" in s for s in summ["stages"])
    total = [r for r in rec.rows if r.get("stage") == "__total__"]
    assert len(total) == 1
    assert total[0]["attributed_fraction"] == summ["attributed_fraction"]


def test_evolve_profile_attribution_ge_95pct():
    """The tentpole acceptance number: a profiled flat-CPU evolve smoke
    attributes >= 95% of its wall clock to named pipeline stages."""
    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import evolution

    wl = synthetic_workload(8, 12, seed=0)
    cfg = evolution.EvolutionConfig(
        population_size=6, generations=2, candidates_per_generation=3,
        early_stop_threshold=10.0, max_workers=2)
    t0 = time.perf_counter()
    fs = evolution.run(wl, cfg, engine="flat", log=lambda *_: None,
                       profile=True)
    wall = time.perf_counter() - t0
    assert fs.profiler.enabled and fs.profiler.records
    summ = fs.profiler.summary(measured_wall=wall)
    assert summ["attributed_fraction"] >= 0.95, summ
    stages = {s["stage"] for s in summ["stages"]}
    assert {"setup", "seed", "codegen", "rank", "ledger"} <= stages
    # backend stages run at depth 0 during generations (the evolution
    # spans don't nest profiler stages around evaluate())
    assert "device-eval" in {r["stage"] for r in fs.profiler.records}
    # profile=off leaves the same run un-instrumented
    fs2 = evolution.run(wl, cfg, engine="flat", log=lambda *_: None)
    assert not fs2.profiler.enabled and fs2.profiler.records == []
