"""Promotion-pipeline tests (fks_tpu.pipeline).

The ISSUE-12 acceptance criteria, as tests:

- the promotion.jsonl state machine: legal/illegal transitions, reload
  round-trip, torn-tail tolerance (kill -9 mid-append) + self-repair;
- gates: a fitness loser is rejected before any device work, a corrupt
  champion degrades to REJECTED at load, an injected p99 regression is
  rejected at shadow — serve keeps answering on the incumbent;
- the hot swap: promotion flips the engine atomically with ZERO
  recompiles on the post-swap warm path (the ladder compiled off the
  request path);
- kill -9 right after each state record lands: a fresh controller +
  service resumes to a consistent state from the log alone;
- probation: post-promotion SLO burn rolls back automatically (and the
  recorded run dir passes the schema checker); a quiet probation window
  releases with PROBATION_PASSED;
- the ``serve --follow-ledger`` poll thread promotes a dropped champion
  end to end;
- the slow tier runs the whole deterministic drill matrix.
"""
import dataclasses
import json
import os
import sys
import time

import pytest

from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.funsearch import template
from fks_tpu.obs import CompileWatcher, FlightRecorder, recording
from fks_tpu.obs.history import SLOConfig
from fks_tpu.pipeline import (
    FaultPlan, KillSwitch, PromotionConfig, PromotionController,
    PromotionLog, attempt_id, follow_ledger, write_champion,
    write_corrupt_champion,
)
from fks_tpu.serve import (
    ChampionSpec, ServeEngine, ServeService, ShapeEnvelope, latest_champion,
    load_champion,
)

BETTER_LOGIC = ("score = 1000 + (node.cpu_milli_left - pod.cpu_milli) "
                "/ max(1, node.cpu_milli_total)")


class RecStub:
    """Recorder double: keeps every event/metric for assertions."""

    def __init__(self):
        self.events = []
        self.metrics = []

    def event(self, kind, **fields):
        self.events.append({"kind": kind, **fields})

    def metric(self, kind, record=None, **fields):
        self.metrics.append({"kind": kind, **fields})


class Stack:
    """Shared warm serving stack: one incumbent, engines cached per
    champion code so the module pays each XLA compile once."""

    def __init__(self):
        self.wl = synthetic_workload(8, 16, seed=0)
        self.envelope = ShapeEnvelope(max_pods=8, min_pod_bucket=8,
                                      max_batch=2)
        self._cache = {}
        self.incumbent = self.factory(ChampionSpec(
            code=template.fill_template("score = 1000"), score=0.4,
            source="<test-seed>"))

    def factory(self, champ):
        if champ.code not in self._cache:
            eng = ServeEngine(champ, self.wl, envelope=self.envelope)
            eng.warmup()
            self._cache[champ.code] = eng
        return self._cache[champ.code]


@pytest.fixture(scope="module")
def stack():
    return Stack()


def _service(stack):
    return ServeService(stack.incumbent, max_wait_s=0.002)


def _traffic(service, n=3, pods=3):
    base = service.engine.base_pods
    futs = [service.submit(
        {"pods": [dict(base[(i + j) % len(base)]) for j in range(pods)]})
        for i in range(n)]
    return [f.result(timeout=300) for f in futs]


def _ctrl(stack, service, tmp, *, faults=None, recorder=None, **over):
    cfg = PromotionConfig(shadow_queries=2, **over)
    return PromotionController(
        service, stack.wl, ledger_dir=str(tmp),
        log_path=os.path.join(str(tmp), "promotion.jsonl"), config=cfg,
        recorder=recorder, faults=faults, engine_factory=stack.factory)


def _better(tmp, score=0.9):
    return write_champion(str(tmp), template.fill_template(BETTER_LOGIC),
                          score)


# -------------------------------------------------------- promotion log


def test_promotion_log_lifecycle(tmp_path):
    log = PromotionLog(tmp_path / "promotion.jsonl")
    log.append("a1", "PENDING", champion="c.json")
    log.append("a1", "SHADOW", champion="c.json")
    log.append("a1", "PROMOTED", champion="c.json")
    assert log.state_of("a1") == "PROMOTED"
    assert log.active()["attempt"] == "a1"
    with pytest.raises(ValueError):
        log.append("a1", "SHADOW")       # PROMOTED only ever rolls back
    with pytest.raises(ValueError):
        log.append("a2", "SHADOW")       # new attempts start at PENDING
    with pytest.raises(ValueError):
        log.append("a1", "LAUNCHED")     # unknown state
    log.append("a1", "ROLLED_BACK", champion="c.json")
    assert log.active() is None
    with pytest.raises(ValueError):
        log.append("a1", "PENDING")      # terminal states are closed
    # reload round-trips the latest-state map
    log2 = PromotionLog(log.path)
    assert log2.states() == {"a1": "ROLLED_BACK"}
    assert log2.skipped_lines == 0


def test_promotion_log_torn_tail_skipped_and_repaired(tmp_path):
    path = tmp_path / "promotion.jsonl"
    log = PromotionLog(path)
    log.append("a1", "PENDING")
    log.append("a1", "SHADOW")
    # a kill -9 mid-append leaves a torn trailing line with no newline
    with open(path, "a") as f:
        f.write('{"ts": 1, "attempt": "a1", "state": "PROMO')
    log2 = PromotionLog(path)
    assert log2.skipped_lines == 1
    assert log2.state_of("a1") == "SHADOW"  # the torn record never happened
    assert log2.interrupted() == ["a1"]
    # the next append repairs the missing newline; the file stays JSONL
    log2.append("a1", "PROMOTED")
    log3 = PromotionLog(path)
    assert log3.skipped_lines == 1
    assert log3.state_of("a1") == "PROMOTED"
    assert log3.active() is not None


def test_attempt_id_content_addressed(tmp_path):
    a = write_champion(str(tmp_path), "code-a", 0.5, name="a")
    b = write_champion(str(tmp_path), "code-b", 0.5, name="b")
    assert attempt_id(a) == attempt_id(a)
    assert attempt_id(a) != attempt_id(b)  # different bytes, new attempt


# ------------------------------------------------------ gates + rejects


def test_fitness_gate_rejects_before_any_device_work(stack, tmp_path):
    service = _service(stack)
    calls = []

    def factory(champ):
        calls.append(champ)
        return stack.factory(champ)

    try:
        _better(tmp_path, score=0.1)  # worse than the incumbent's 0.4
        ctrl = PromotionController(
            service, stack.wl, ledger_dir=str(tmp_path),
            config=PromotionConfig(shadow_queries=2),
            engine_factory=factory)
        out = ctrl.poll_once()
        assert out["action"] == "rejected"
        assert "fitness" in out["reason"]
        assert not calls  # a fitness loser never costs a ladder build
        assert ctrl.log.state_of(out["attempt"]) == "REJECTED"
        assert ctrl.poll_once()["action"] == "idle"  # never retried
    finally:
        service.close()


def test_load_champion_validates_fields(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text('{"code": "def f(): pass", ')  # torn mid-write
    with pytest.raises(ValueError, match="JSON"):
        load_champion(str(p))
    p.write_text(json.dumps({"code": "", "score": 1.0}))
    with pytest.raises(ValueError, match="code"):
        load_champion(str(p))
    p.write_text(json.dumps({"code": "def f(): pass", "score": "wat"}))
    with pytest.raises(ValueError, match="score"):
        load_champion(str(p))
    p.write_text(json.dumps({"code": "def f(): pass", "score": "Infinity"}))
    with pytest.raises(ValueError, match="non-finite"):
        load_champion(str(p))


def test_corrupt_champion_skipped_with_warning(tmp_path):
    rec = RecStub()
    write_corrupt_champion(str(tmp_path))
    # the torn file (best score in the dir) must not hide the ledger
    assert latest_champion(str(tmp_path), recorder=rec) is None
    alerts = [e for e in rec.events if e["kind"] == "alert"]
    assert alerts and alerts[0]["source"] == "champion_ledger"
    good = write_champion(str(tmp_path), "def f(): pass", 0.7, name="good")
    assert latest_champion(str(tmp_path), recorder=rec) == good


def test_corrupt_champion_rejected_serving_survives(stack, tmp_path):
    service = _service(stack)
    try:
        corrupt = write_corrupt_champion(str(tmp_path))
        ctrl = _ctrl(stack, service, tmp_path)
        out = ctrl.poll_once(corrupt)
        assert out["action"] == "rejected"
        assert "load_failed" in out["reason"]
        assert len(_traffic(service, 2)) == 2
    finally:
        service.close()


def test_p99_regression_rejected_at_shadow(stack, tmp_path):
    service = _service(stack)
    try:
        _traffic(service, 3)
        _better(tmp_path)
        ctrl = _ctrl(stack, service, tmp_path,
                     faults=FaultPlan(shadow_latency_ms=400.0),
                     max_p99_regression=1.5, slo=SLOConfig(p99_ms=50.0))
        out = ctrl.poll_once()
        assert out["action"] == "rejected"
        assert "latency" in out["reason"] or "slo" in out["reason"]
        assert service.engine is stack.incumbent
        assert service.swaps == 0
    finally:
        service.close()


# --------------------------------------------------------- the hot swap


def test_promotion_hot_swap_zero_recompiles(stack, tmp_path):
    service = _service(stack)
    try:
        _traffic(service, 3)
        _better(tmp_path)
        ctrl = _ctrl(stack, service, tmp_path)
        out = ctrl.poll_once()
        assert out["action"] == "promoted"
        assert service.swaps == 1
        assert service.engine.champion.score == 0.9
        watcher = CompileWatcher().install()
        try:
            answers = _traffic(service, 4)
            assert len(answers) == 4
            # the contract the swap exists for: the promoted ladder was
            # compiled off the request path, so warm traffic compiles 0
            assert watcher.backend_compile_count == 0
        finally:
            watcher.uninstall()
        assert ctrl.poll_once()["action"] == "idle"
    finally:
        service.close()


@pytest.mark.parametrize("state", ["PENDING", "SHADOW", "PROMOTED"])
def test_kill_and_recover(stack, tmp_path, state):
    service = _service(stack)
    try:
        cand = _better(tmp_path)
        ctrl = _ctrl(stack, service, tmp_path,
                     faults=FaultPlan(kill_after_state=state))
        with pytest.raises(KillSwitch):
            ctrl.poll_once()
        # the crashed controller never took serving down
        assert len(_traffic(service, 2)) == 2
        # a restarted process: fresh service + controller, same log
        service2 = _service(stack)
        try:
            ctrl2 = _ctrl(stack, service2, tmp_path)
            rec = ctrl2.recover()
            out = ctrl2.poll_once()
            if state == "PROMOTED":
                # the log committed before the flip: restart resolves to
                # the candidate with nothing left to replay
                assert rec["active"] is not None
                assert ctrl2.active_champion() == cand
                assert out["action"] == "idle"
            else:
                assert rec["interrupted"]
                assert out["action"] == "promoted"
                assert service2.engine.champion.score == 0.9
        finally:
            service2.close()
    finally:
        service.close()


# ------------------------------------------------------------ probation


def test_rollback_on_burn_and_run_dir_schema(stack, tmp_path):
    run_dir = tmp_path / "run"
    ledger = tmp_path / "ledger"
    rec = FlightRecorder(str(run_dir))
    service = ServeService(stack.incumbent, max_wait_s=0.002, recorder=rec)
    try:
        with recording(rec):
            _traffic(service, 2)
            _better(ledger)
            ctrl = PromotionController(
                service, stack.wl, ledger_dir=str(ledger),
                config=PromotionConfig(shadow_queries=2,
                                       probation_requests=32),
                recorder=rec, engine_factory=stack.factory)
            assert ctrl.poll_once()["action"] == "promoted"
            # production degrades post-swap: every request now misses the
            # (retroactively impossible) p99 target
            ctrl.cfg = dataclasses.replace(ctrl.cfg,
                                           slo=SLOConfig(p99_ms=1e-6))
            _traffic(service, 3)
            out = ctrl.check_probation()
            assert out is not None and out["action"] == "rolled_back"
            assert service.engine is stack.incumbent
            assert ctrl.log.state_of(out["attempt"]) == "ROLLED_BACK"
            assert ctrl.poll_once()["action"] == "idle"
    finally:
        service.close()
    # everything the pipeline recorded parses against the schema tool
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    counts = cjs.check_run_dir(str(run_dir))
    assert counts["metrics.jsonl"] > 0
    events = [json.loads(ln) for ln in
              (run_dir / "events.jsonl").read_text().splitlines()]
    assert any(e["kind"] == "rollback" for e in events)
    states = [m.get("state") for m in
              (json.loads(ln) for ln in
               (run_dir / "metrics.jsonl").read_text().splitlines())
              if m.get("kind") == "promotion_event"]
    assert "PROMOTED" in states and "ROLLED_BACK" in states


def test_probation_release(stack, tmp_path):
    service = _service(stack)
    try:
        _traffic(service, 2)
        _better(tmp_path)
        ctrl = _ctrl(stack, service, tmp_path, probation_requests=2,
                     slo=SLOConfig(p99_ms=1e9))
        assert ctrl.poll_once()["action"] == "promoted"
        _traffic(service, 3)
        out = ctrl.check_probation()
        assert out is not None and out["action"] == "probation_passed"
        assert ctrl.check_probation() is None  # released exactly once
    finally:
        service.close()


# -------------------------------------------------- follow-ledger + CLI


def test_follow_ledger_thread_promotes(stack, tmp_path):
    service = _service(stack)
    try:
        ctrl = _ctrl(stack, service, tmp_path)
        stop, thread = follow_ledger(ctrl, interval=0.05)
        try:
            _better(tmp_path)
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline and service.swaps == 0:
                time.sleep(0.05)
            assert service.swaps == 1
            assert service.engine.champion.score == 0.9
        finally:
            stop.set()
            thread.join(timeout=30)
    finally:
        service.close()


def test_cli_pipeline_status(tmp_path, capsys):
    from fks_tpu import cli

    log = PromotionLog(tmp_path / "promotion.jsonl")
    log.append("abc", "PENDING", champion="c.json")
    log.append("abc", "SHADOW", champion="c.json")
    rc = cli.main(["pipeline", "--cpu", "--ledger-dir", str(tmp_path)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["attempts"] == {"abc": "SHADOW"}
    assert out["interrupted"] == ["abc"]
    assert out["active"] is None
    assert out["skipped_lines"] == 0


# ----------------------------------------------------- the drill matrix


@pytest.mark.slow
def test_full_drill_matrix():
    from fks_tpu.pipeline import run_drills

    results = run_drills(log=lambda _m: None)
    assert results, "empty drill matrix"
    failed = [r for r in results if not r["ok"]]
    assert not failed, failed
