"""Cross-run regression gating + OpenMetrics export.

Pins the issue's acceptance bar directly: ``cli compare`` exits nonzero
when a candidate run carries an injected regression (throughput -20% or
parity drift above 1e-5) and zero on identical runs; the OpenMetrics
exposition round-trips through the schema checker's validator; heartbeat
liveness classifies FINISHED/HEALTHY/STALE/DEAD from the run's own
cadence. The golden run-dir fixture under tests/fixtures/golden_run is
the same one tools/run_full_suite.py gates on.
"""
import json
import os
import pathlib
import shutil
import sys
import time

import pytest

from fks_tpu import cli, obs
from fks_tpu.obs.compare import (
    DEFAULT_THRESHOLDS, Threshold, compare_runs, extract_metrics,
    format_comparison, has_regression, parse_threshold_overrides,
)
from fks_tpu.obs.exporter import run_health, to_openmetrics, watch

GOLDEN = str(pathlib.Path(__file__).parent / "fixtures" / "golden_run")


def _schema_tool():
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    return cjs


def _regressed_copy(tmp_path, *, perf_factor=1.0, drift=None):
    """Copy the golden run dir, scaling bench throughput and/or injecting
    parity drift into the candidate's metrics stream."""
    dst = str(tmp_path / "candidate")
    shutil.copytree(GOLDEN, dst)
    rows = []
    with open(os.path.join(dst, "metrics.jsonl")) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    for r in rows:
        if r["kind"] == "bench_stage" and "evals_per_sec" in r:
            r["evals_per_sec"] *= perf_factor
        if drift is not None and r["kind"] == "parity":
            r["max_drift"] = drift
    with open(os.path.join(dst, "metrics.jsonl"), "w") as f:
        f.writelines(json.dumps(r) + "\n" for r in rows)
    return dst


# ------------------------------------------------------------- comparator

def test_identical_runs_no_regression():
    rows = compare_runs(GOLDEN, GOLDEN)
    assert rows and not has_regression(rows)
    assert all(r["status"] == "OK" for r in rows)


def test_injected_perf_regression_gates(tmp_path):
    cand = _regressed_copy(tmp_path, perf_factor=0.8)  # the issue's -20%
    rows = compare_runs(GOLDEN, cand)
    assert has_regression(rows)
    by = {r["metric"]: r["status"] for r in rows}
    assert by["evals_per_sec"] == "REGRESSION"
    assert "REGRESSION: " in format_comparison(rows, GOLDEN, cand)


def test_injected_parity_drift_gates(tmp_path):
    cand = _regressed_copy(tmp_path, drift=0.01)  # > 1e-5 tolerance
    by = {r["metric"]: r["status"] for r in compare_runs(GOLDEN, cand)}
    assert by["parity_max_drift"] == "REGRESSION"


def test_small_perf_noise_rides_out(tmp_path):
    cand = _regressed_copy(tmp_path, perf_factor=0.95)  # within 10% rel
    by = {r["metric"]: r["status"] for r in compare_runs(GOLDEN, cand)}
    assert by["evals_per_sec"] == "OK"


def test_improvement_is_not_a_regression(tmp_path):
    cand = _regressed_copy(tmp_path, perf_factor=1.5)
    rows = compare_runs(GOLDEN, cand)
    assert not has_regression(rows)
    by = {r["metric"]: r["status"] for r in rows}
    assert by["evals_per_sec"] == "IMPROVED"


def test_metric_in_one_run_never_gates(tmp_path):
    base = tmp_path / "base.jsonl"
    cand = tmp_path / "cand.jsonl"
    base.write_text(json.dumps({"value": 100.0, "unit": "evals/s",
                                "best_score": 0.5}) + "\n")
    cand.write_text(json.dumps({"value": 100.0, "unit": "evals/s"}) + "\n")
    rows = compare_runs(str(base), str(cand))
    assert not has_regression(rows)
    by = {r["metric"]: r["status"] for r in rows}
    assert by["best_score"] == "BASELINE-ONLY"


def test_bench_fallback_value_contributes_nothing(tmp_path):
    """The 0.0-with-banked_from headline means 'nothing measured' and must
    not enter the throughput vocabulary (a later honest 0.0 baseline would
    otherwise make every candidate an infinite improvement)."""
    p = tmp_path / "fallback.jsonl"
    p.write_text(json.dumps({
        "benchmark": "fks_tpu", "value": 0.0, "unit": "evals/s",
        "error": "tpu timeout", "banked_from": "round6_tpu.jsonl"}) + "\n")
    assert "evals_per_sec" not in extract_metrics(str(p))


def test_bench_headline_and_session_log_extraction(tmp_path):
    p = tmp_path / "bench.jsonl"
    p.write_text(
        "prose line survives\n"
        + json.dumps({"ok": True, "stage": "throughput",
                      "result": {"evals_per_sec": 1200.0,
                                 "compile_seconds": 4.0}}) + "\n"
        + json.dumps({"value": 1500.0, "unit": "evals/s",
                      "compile_seconds": 3.5}) + "\n")
    m = extract_metrics(str(p))
    assert m["evals_per_sec"] == 1500.0  # best across rows
    assert m["compile_seconds"] == 3.5   # min: best measured compile


def test_threshold_overrides():
    th = parse_threshold_overrides("evals_per_sec=rel:0.5,best_score=abs:0.2")
    assert th["evals_per_sec"] == Threshold(higher_is_better=True, rel=0.5)
    assert th["best_score"].abs_tol == 0.2 and th["best_score"].rel is None
    # untouched metrics keep the defaults
    assert th["parity_max_drift"] == DEFAULT_THRESHOLDS["parity_max_drift"]
    with pytest.raises(ValueError, match="bad threshold"):
        parse_threshold_overrides("evals_per_sec=0.5")


def test_watchdog_and_alert_counts_gate(tmp_path):
    cand = str(tmp_path / "candidate")
    shutil.copytree(GOLDEN, cand)
    with open(os.path.join(cand, "events.jsonl"), "a") as f:
        f.write(json.dumps({"ts": 1785585691.0, "kind": "watchdog",
                            "seq": 6, "flags": 2, "kinds": ["inf"]}) + "\n")
    by = {r["metric"]: r["status"] for r in compare_runs(GOLDEN, cand)}
    assert by["watchdog_violations"] == "REGRESSION"  # any increase gates


# ------------------------------------------------------ openmetrics export

def test_openmetrics_round_trips_schema_checker():
    text = to_openmetrics(GOLDEN)
    assert text.endswith("# EOF\n")
    n = _schema_tool().check_openmetrics(text, "<golden>")
    assert n > 0


def test_openmetrics_families_and_labels():
    text = to_openmetrics(GOLDEN)
    assert '# TYPE fks_generation_best_score gauge' in text
    assert 'fks_run_info{run_id="20260801-120000-abc123"' in text
    assert 'fks_events_total{run_id="20260801-120000-abc123",kind="watchdog"} 1' in text
    assert "fks_parity_max_drift" in text
    assert "fks_bench_evals_per_sec" in text
    # finished golden run: healthy regardless of heartbeat age
    assert "fks_run_healthy" in text


def test_openmetrics_checker_rejects_malformed():
    cjs = _schema_tool()
    with pytest.raises(cjs.SchemaError, match="EOF"):
        cjs.check_openmetrics("fks_x 1\n", "<t>")
    with pytest.raises(cjs.SchemaError):
        # sample for an undeclared family
        cjs.check_openmetrics("fks_x{a=\"b\"} 1\n# EOF\n", "<t>")


def test_schema_checker_validates_watchdog_event_kinds(tmp_path):
    cjs = _schema_tool()
    assert cjs.main(["--run-dir", GOLDEN]) == 0
    bad = tmp_path / "run"
    shutil.copytree(GOLDEN, bad)
    with open(bad / "events.jsonl", "a") as f:
        # watchdog event missing its required flags/kinds payload
        f.write(json.dumps({"ts": 1.0, "kind": "watchdog", "seq": 9}) + "\n")
    assert cjs.main(["--run-dir", str(bad)]) == 1


# -------------------------------------------------------------- liveness

def _live_run(tmp_path, heartbeat_age, gap=10.0):
    """Unfinished run whose metrics tick every ``gap`` seconds and whose
    last heartbeat is ``heartbeat_age`` seconds old."""
    d = tmp_path / f"live-{heartbeat_age}"
    d.mkdir()
    now = time.time()
    (d / "meta.json").write_text(json.dumps(
        {"run_id": "live", "status": "running", "command": "evolve"}))
    rows = [{"ts": now - 100 + i * gap, "kind": "generation",
             "generation": i, "best_score": 0.1} for i in range(5)]
    (d / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows))
    (d / "heartbeat").write_text(json.dumps(
        {"ts": now - heartbeat_age, "run_id": "live"}))
    # a genuinely stale heartbeat is old on BOTH signals: the embedded
    # ts and the file mtime (run_health takes the fresher of the two so
    # writer/reader clock skew cannot flap a live run to STALE)
    os.utime(d / "heartbeat", (now - heartbeat_age, now - heartbeat_age))
    return str(d)


def test_run_health_states(tmp_path):
    assert run_health(GOLDEN)["state"] == "FINISHED"
    assert run_health(_live_run(tmp_path, 5.0))["state"] == "HEALTHY"
    # cadence is ~10s: STALE beyond 2x, DEAD beyond 10x
    assert run_health(_live_run(tmp_path, 45.0))["state"] == "STALE"
    assert run_health(_live_run(tmp_path, 900.0))["state"] == "DEAD"
    # unfinished run with no heartbeat file at all: DEAD
    no_beat = _live_run(tmp_path, 1.0, gap=10.0)
    os.remove(os.path.join(no_beat, "heartbeat"))
    assert run_health(no_beat)["state"] == "DEAD"


def test_run_health_monotonic_skew_guard(tmp_path):
    """A heartbeat whose embedded ts looks old but whose file was just
    modified (writer/reader clock skew, shared-filesystem lag) must NOT
    flap to STALE/DEAD — the fresher of the two signals wins."""
    d = _live_run(tmp_path, 60.0)
    os.utime(os.path.join(d, "heartbeat"), None)  # mtime = now
    assert run_health(d)["state"] == "HEALTHY"


def test_report_flags_stale_run(tmp_path):
    from fks_tpu.obs.report import render_report

    stale = _live_run(tmp_path, 60.0)
    head = render_report(stale).splitlines()[0]
    assert "STALE" in head
    assert "STALE" not in render_report(GOLDEN).splitlines()[0]


def test_watch_once_finished_run(capsys):
    rc = watch(GOLDEN, once=True)
    out = capsys.readouterr().out
    assert rc == 0
    assert "[FINISHED]" in out
    assert "gen 3" in out and "parity gen 3" in out


def test_watch_dead_run_exits_nonzero(tmp_path, capsys):
    rc = watch(_live_run(tmp_path, 900.0), once=True)
    assert rc == 1
    assert "[DEAD]" in capsys.readouterr().out


# ------------------------------------------------------------ cli surface

def test_cli_compare_exit_codes(tmp_path, capsys):
    assert cli.main(["compare", GOLDEN, GOLDEN]) == 0
    assert "no regressions" in capsys.readouterr().out
    cand = _regressed_copy(tmp_path, perf_factor=0.8)
    assert cli.main(["compare", GOLDEN, cand]) == 1
    assert "REGRESSION" in capsys.readouterr().out
    assert cli.main(["compare", GOLDEN, str(tmp_path / "nope")]) == 2


def test_cli_compare_threshold_override(tmp_path, capsys):
    cand = _regressed_copy(tmp_path, perf_factor=0.8)
    rc = cli.main(["compare", GOLDEN, cand,
                   "--threshold", "evals_per_sec=rel:0.5,"
                   "parity_max_drift=abs:0.1"])
    capsys.readouterr()
    assert rc == 0  # widened gate rides out the -20%


def test_cli_export_metrics(tmp_path, capsys):
    out = tmp_path / "metrics.prom"
    assert cli.main(["export-metrics", GOLDEN, "--out", str(out)]) == 0
    capsys.readouterr()
    text = out.read_text()
    assert text.endswith("# EOF\n")
    assert _schema_tool().check_openmetrics(text, str(out)) > 0
    # stdout mode
    assert cli.main(["export-metrics", GOLDEN]) == 0
    assert "# EOF" in capsys.readouterr().out


def test_cli_watch_once(capsys):
    assert cli.main(["watch", GOLDEN, "--once"]) == 0
    assert "[FINISHED]" in capsys.readouterr().out
