"""Causal-tracing tests (fks_tpu.obs.trace_ctx + the instrumented serve
path).

The PR-15 acceptance criteria, as tests:

- context mechanics: preallocated root span id, explicit cross-thread
  activation, nesting restores the previous context;
- ``obs.span`` dual emission: ``kind="span"`` with no active context,
  ``kind="trace_span"`` (with parent linkage + child context active in
  the body) under one;
- reconstruction: tree building, waterfall completeness, critical-path
  attribution — including torn-trail tolerance;
- end-to-end: every request served through a recorded ``ServeService``
  yields ONE complete causally-linked waterfall whose components sum to
  the root wall; a degraded-mode retry stays on the SAME trace with a
  ``primary_attempt`` child carrying the fault class;
- typed resilience errors carry the request's trace id in ``to_json``;
- schema/CI surface: the ``trace_span`` kind and the OpenMetrics
  exemplar syntax are accepted by tools/check_jsonl_schema.py.
"""
import json
import threading

import pytest

from fks_tpu.obs import FlightRecorder, trace_ctx
from fks_tpu.obs.report import read_jsonl


# ----------------------------------------------------- context mechanics


def test_new_trace_preallocates_root_span_id():
    ctx = trace_ctx.new_trace()
    assert ctx.trace_id.startswith("req-")
    assert len(ctx.span_id) == 16
    gen = trace_ctx.new_trace(prefix="gen")
    assert gen.trace_id.startswith("gen-")
    assert gen.trace_id != ctx.trace_id


def test_activate_nesting_restores_previous():
    assert trace_ctx.current() is None
    a, b = trace_ctx.new_trace(), trace_ctx.new_trace()
    with trace_ctx.activate(a):
        assert trace_ctx.current() is a
        with trace_ctx.activate(b):
            assert trace_ctx.current() is b
        assert trace_ctx.current() is a
    assert trace_ctx.current() is None


def test_activate_none_is_noop():
    with trace_ctx.activate(None) as got:
        assert got is None
        assert trace_ctx.current() is None


def test_context_object_crosses_threads():
    """The propagation contract: the context OBJECT is handed over and
    re-activated on the consuming thread — no ambient inheritance."""
    ctx = trace_ctx.new_trace()
    seen = {}

    def worker():
        seen["before"] = trace_ctx.current()
        with trace_ctx.activate(ctx):
            seen["during"] = trace_ctx.current()

    with trace_ctx.activate(ctx):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["before"] is None  # thread-locals do not leak across
    assert seen["during"] is ctx


def test_emit_noop_without_context_or_recorder(tmp_path):
    from fks_tpu.obs import NULL

    assert trace_ctx.emit(NULL, "x", 0.1,
                          ctx=trace_ctx.new_trace()) is None
    rec = FlightRecorder(str(tmp_path / "r"))
    try:
        assert trace_ctx.emit(rec, "x", 0.1) is None  # no active ctx
    finally:
        rec.close()
    ep = tmp_path / "r" / "events.jsonl"
    rows = read_jsonl(str(ep)) if ep.exists() else []
    assert trace_ctx.trace_spans(rows) == []


def test_emit_root_and_child_linkage(tmp_path):
    rec = FlightRecorder(str(tmp_path / "r"))
    ctx = trace_ctx.new_trace()
    with trace_ctx.activate(ctx):
        child_sid = trace_ctx.emit(rec, "serve/request/queue_wait", 0.002)
    root_sid = trace_ctx.emit(rec, "serve/request", 0.01, ctx=ctx,
                              root=True)
    rec.close()
    rows = read_jsonl(str(tmp_path / "r" / "events.jsonl"))
    spans = trace_ctx.trace_spans(rows)
    assert len(spans) == 2
    by_sid = {s["span_id"]: s for s in spans}
    # root reuses the preallocated id with an explicit null parent;
    # the child (emitted BEFORE the root event existed) links to it
    assert root_sid == ctx.span_id
    assert by_sid[root_sid]["parent_id"] is None
    assert by_sid[child_sid]["parent_id"] == root_sid
    assert all(s["trace_id"] == ctx.trace_id for s in spans)


def test_obs_span_dual_emission(tmp_path):
    """Same call site, two vocabularies: plain ``span`` without a trace
    context, ``trace_span`` (parented, child ctx active inside) with one."""
    from fks_tpu import obs

    rec = FlightRecorder(str(tmp_path / "r"))
    with obs.recording(rec):
        with obs.span("outer"):
            pass
        ctx = trace_ctx.new_trace()
        with trace_ctx.activate(ctx):
            with obs.span("outer"):
                inner_ctx = trace_ctx.current()
                assert inner_ctx is not ctx  # child active in the body
                assert inner_ctx.trace_id == ctx.trace_id
                with obs.span("inner"):
                    pass
    rec.close()
    rows = read_jsonl(str(tmp_path / "r" / "events.jsonl"))
    plain = [r for r in rows if r.get("kind") == "span"]
    traced = [r for r in rows if r.get("kind") == "trace_span"]
    assert [s["path"] for s in plain] == ["outer"]
    assert "trace_id" not in plain[0]
    outer = next(s for s in traced if s["path"] == "outer")
    inner = next(s for s in traced if s["path"] == "outer/inner")
    assert outer["parent_id"] == ctx.span_id
    assert inner["parent_id"] == outer["span_id"]


# -------------------------------------------------------- reconstruction


def _span(trace_id, span_id, parent_id, path, seconds, ts):
    return {"kind": "trace_span", "trace_id": trace_id, "span_id": span_id,
            "parent_id": parent_id, "path": path, "seconds": seconds,
            "ts": ts}


def _serve_trace(tid="req-x"):
    rows = [_span(tid, "root", None, "serve/request", 0.01, 10.01)]
    t = 10.0
    for i, comp in enumerate(trace_ctx.SERVE_COMPONENTS):
        rows.append(_span(tid, f"c{i}", "root", f"serve/request/{comp}",
                          0.002, t + 0.002 * (i + 1)))
    return rows


def test_build_tree_and_orphans():
    rows = _serve_trace()
    roots = trace_ctx.build_tree(rows)
    assert len(roots) == 1
    assert len(roots[0]["children"]) == len(trace_ctx.SERVE_COMPONENTS)
    # a torn parent link surfaces as an extra root, not a lost span
    rows.append(_span("req-x", "orphan", "missing", "stray", 0.001, 10.0))
    assert len(trace_ctx.build_tree(rows)) == 2


def test_waterfall_complete_requires_every_component():
    rows = _serve_trace()
    assert trace_ctx.waterfall_complete(rows)
    assert not trace_ctx.waterfall_complete(rows[:-1])  # scatter_back gone
    assert not trace_ctx.waterfall_complete([])
    two_roots = rows + [_span("req-x", "r2", None, "serve/request",
                              0.01, 10.01)]
    assert not trace_ctx.waterfall_complete(two_roots)
    torn = rows + [_span("req-x", "t", "missing", "extra", 0.001, 10.0)]
    assert not trace_ctx.waterfall_complete(torn)


def test_render_waterfall_orders_and_labels():
    out = trace_ctx.render_waterfall(_serve_trace())
    lines = out.splitlines()
    assert "req-x" in lines[0] and "6 spans" in lines[0]
    assert "serve/request" in lines[1]
    # components render indented under the root, in start order
    for comp, line in zip(trace_ctx.SERVE_COMPONENTS, lines[2:]):
        assert comp in line and "|" in line


def test_critical_path_attribution():
    tid = "gen-y"
    rows = [_span(tid, "root", None, "generation", 10.0, 110.0),
            _span(tid, "a", "root", "llm", 6.0, 106.0),
            _span(tid, "b", "root", "evaluate", 3.0, 109.0),
            _span(tid, "c", "root", "rank", 0.5, 109.5),
            # grandchildren must NOT double-count into the attribution
            _span(tid, "d", "b", "evaluate/candidate", 0.0, 109.0)]
    cp = trace_ctx.critical_path(rows)
    assert cp["ok"] and cp["wall_seconds"] == 10.0
    assert cp["bounding_stage"] == "llm"
    assert cp["attributed_fraction"] == pytest.approx(0.95)
    # the device idles while the LLM drafts; the LLM idles the rest
    assert cp["device_idle_seconds"] == 6.0
    assert cp["llm_idle_seconds"] == pytest.approx(3.5)
    assert trace_ctx.critical_path([rows[1]]) == {
        "ok": False, "reason": "no root span"}


# ------------------------------------------------- end-to-end serve path


@pytest.fixture(scope="module")
def stack():
    """Warm incumbent + exact fallback (same shape as test_resilience)."""
    import dataclasses

    from fks_tpu.data.synthetic import synthetic_workload
    from fks_tpu.funsearch import template
    from fks_tpu.serve import ChampionSpec, ServeEngine, ShapeEnvelope

    wl = synthetic_workload(8, 16, seed=0)
    champ = ChampionSpec(code=template.fill_template("score = 1000"),
                         score=0.5, source="<test-seed>")
    env = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2)
    incumbent = ServeEngine(champ, wl, envelope=env, engine="flat")
    incumbent.warmup()
    fallback = ServeEngine(champ, wl,
                           envelope=dataclasses.replace(env, max_batch=1),
                           engine="exact")
    fallback.warmup()
    return {"incumbent": incumbent, "fallback": fallback}


def _pods(stack, i, n=3):
    base = stack["incumbent"].base_pods
    return [dict(base[(i + j) % len(base)]) for j in range(n)]


def _run_traced_service(tmp_path, stack, n, flaky=False):
    """Serve ``n`` requests through a recorded service; returns
    (answers, trace groups, serve_request metrics)."""
    from fks_tpu.serve import ServeService

    engine = stack["incumbent"]
    if flaky:
        from fks_tpu.pipeline.faults import FlakyEngineProxy
        from fks_tpu.resilience.degrade import DegradeConfig

        engine = FlakyEngineProxy(engine, failures=1)
    rec = FlightRecorder(str(tmp_path / "run"))
    service = ServeService(engine, max_wait_s=0.002, recorder=rec)
    if flaky:
        service.enable_degraded_mode(
            lambda: stack["fallback"],
            config=DegradeConfig(background_rebuild=False))
    try:
        answers = [service.submit({"id": f"q{i}",
                                   "pods": _pods(stack, i)}).result(300)
                   for i in range(n)]
    finally:
        service.close()
        rec.finish("ok")
        rec.close()
    events = read_jsonl(str(tmp_path / "run" / "events.jsonl"))
    metrics = read_jsonl(str(tmp_path / "run" / "metrics.jsonl"))
    by = trace_ctx.traces_by_id(trace_ctx.trace_spans(events))
    served = [m for m in metrics if m.get("kind") == "serve_request"]
    return answers, by, served


def test_served_requests_reconstruct_complete_waterfalls(tmp_path, stack):
    answers, by, served = _run_traced_service(tmp_path, stack, 3)
    assert len(served) == 3
    for ans, m in zip(answers, served):
        tid = m["trace_id"]
        assert ans["trace_id"] == tid  # answer and metric agree
        spans = by[tid]
        assert trace_ctx.waterfall_complete(spans)
        root = next(s for s in spans if s["parent_id"] is None)
        assert root["path"] == trace_ctx.SERVE_ROOT
        # children sum exactly to the root wall (scatter_back is the
        # clamped remainder, so the waterfall never lies about totals)
        child_sum = sum(s["seconds"] for s in spans
                        if s["parent_id"] == root["span_id"])
        assert child_sum == pytest.approx(root["seconds"], abs=5e-6)


def test_degraded_retry_stays_on_one_trace(tmp_path, stack):
    """Primary-fail -> fallback-retry is ONE connected trace: the faulted
    request's waterfall carries a ``primary_attempt`` child with the
    fault class, and later requests (already degraded) carry none."""
    answers, by, served = _run_traced_service(tmp_path, stack, 3,
                                              flaky=True)
    assert [m["trace_id"] for m in served] == \
        [a["trace_id"] for a in answers]
    retried = []
    for m in served:
        spans = by[m["trace_id"]]
        assert trace_ctx.waterfall_complete(spans)
        attempts = [s for s in spans
                    if s["path"] == "serve/request/primary_attempt"]
        if attempts:
            retried.append(m["trace_id"])
            assert attempts[0]["fault"] == "DeviceFault"
            root = next(s for s in spans if s["parent_id"] is None)
            assert attempts[0]["parent_id"] == root["span_id"]
    assert retried == [served[0]["trace_id"]]  # only the faulted batch


def test_resilience_errors_carry_trace_id():
    from fks_tpu.resilience.deadline import (
        DeadlineExceeded, ResilienceError, ShedError,
    )

    e = ShedError("full", retry_after_s=0.5, trace_id="req-abc")
    assert e.to_json()["trace_id"] == "req-abc"
    assert json.loads(json.dumps(e.to_json()))["kind"] == "shed"
    assert "trace_id" not in ResilienceError("plain").to_json()
    d = DeadlineExceeded("late", trace_id="req-def")
    assert d.to_json() == {"error": "late", "kind": "deadline",
                           "trace_id": "req-def"}


def test_batcher_shed_error_carries_trace_id(stack):
    """An in-queue expiry surfaces the request's OWN trace id on the
    typed error — the client can join its failure to the trace."""
    from fks_tpu.resilience.deadline import Deadline, ResilienceError
    from fks_tpu.serve.batcher import RequestBatcher

    gate, entered = threading.Event(), threading.Event()

    def blocked(queries, enq):
        entered.set()
        gate.wait(30)
        return list(queries)

    import time

    from fks_tpu.resilience.deadline import ShedError

    b = RequestBatcher(blocked, max_batch=1, max_wait_s=0.0)
    ctx = trace_ctx.new_trace()
    try:
        first = b.submit("a")
        assert entered.wait(30)
        # generous enough to pass admission's projected-wait check, short
        # enough to expire while the worker is provably still blocked
        try:
            doomed = b.submit("b",
                              deadline=Deadline(time.perf_counter() + 0.2),
                              ctx=ctx)
        except ShedError as e:
            # admission refused it up front — the shed path must carry
            # the trace id too
            assert e.trace_id == ctx.trace_id
            doomed = None
        if doomed is not None:
            time.sleep(0.25)  # worker still gated: the budget expires
        gate.set()
        first.result(30)
        if doomed is not None:
            with pytest.raises(ResilienceError) as ei:
                doomed.result(30)
            assert ei.value.trace_id == ctx.trace_id
            assert ei.value.to_json()["trace_id"] == ctx.trace_id
    finally:
        gate.set()
        b.close()


# ------------------------------------------------------ schema/CI surface


def test_schema_accepts_trace_span_and_exemplars(tmp_path):
    import sys

    sys.path.insert(0, "tools")
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    row = {"ts": 1.0, "kind": "trace_span", "trace_id": "req-a",
           "span_id": "s1", "parent_id": None, "path": "serve/request",
           "seconds": 0.01}
    p = tmp_path / "events.jsonl"
    p.write_text(json.dumps(row) + "\n")
    recs = cjs.check_jsonl(str(p), required=("ts", "kind"))
    cjs.check_kinds(str(p), recs, cjs.EVENT_KIND_REQUIRED)  # no raise
    bad = dict(row)
    del bad["span_id"]
    p.write_text(json.dumps(bad) + "\n")
    recs = cjs.check_jsonl(str(p), required=("ts", "kind"))
    with pytest.raises(cjs.SchemaError, match="span_id"):
        cjs.check_kinds(str(p), recs, cjs.EVENT_KIND_REQUIRED)
    # exemplar'd histogram buckets are legal OpenMetrics samples
    text = "\n".join([
        "# TYPE fks_serve_latency_seconds histogram",
        'fks_serve_latency_seconds_bucket{le="0.5"} 3 '
        '# {trace_id="req-a"} 0.41',
        'fks_serve_latency_seconds_bucket{le="+Inf"} 3',
        "fks_serve_latency_seconds_sum 1.2",
        "fks_serve_latency_seconds_count 3",
        "# EOF", ""])
    assert cjs.check_openmetrics(text) == 4
    with pytest.raises(cjs.SchemaError, match="malformed"):
        cjs.check_openmetrics(text.replace('} 0.41', '} nope extra'))
