"""Eval-budget allocator tests (fks_tpu.funsearch.budget).

Coverage map:
- BudgetConfig validation + survivor arithmetic (ceil(n/eta), the
  min_survivors floor, never more than n)
- probe_sim_config (probe scoring on; max_steps replaced only when
  probe_steps is set)
- CodeEvaluator wiring: budget requires suite mode, rejects the fused
  engine with a pointer message, forces the batched VM tier on CPU
- fused kernel rejects probe-scored SimConfigs at build time
- unified FKS_VM_SEG_STEPS / seg_steps validation (one helper, one
  error vocabulary, backend.py and sim/flat.py both on it)
- the budgeted evaluate() path end-to-end: rung tagging, survivor
  count, pruned-score capping below the worst survivor, per-rung
  stats, champion invariance vs the unbudgeted full evaluation
- compile-once-per-bucket: a second generation of the same size must
  not trigger new XLA backend compiles
- ParitySentinel.check_champion: silent on a sound pruning, alert
  (source="budget_champion") when a pruned candidate's reference score
  beats the pruned champion
- evolution integration: budget_rung metrics + GenerationStats budget
  fields land in the run dir over a multi-generation stub-LLM run with
  zero sentinel alerts, and the schema checker accepts the run dir
"""
import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from fks_tpu.funsearch import llm, template, transpiler, vm
from fks_tpu.funsearch.backend import CodeEvaluator, EvalRecord
from fks_tpu.funsearch.budget import (
    BudgetConfig, BudgetedSuiteEval, probe_sim_config,
)
from fks_tpu.scenarios import RobustConfig, get_suite
from fks_tpu.sim.engine import SimConfig

REPO = pathlib.Path(__file__).parent.parent


def micro_workload():
    from tests.test_engine_micro import micro_workload as mw
    return mw()


def _vm_codes(wl, need, seed=7):
    """``need`` UNIQUE (by canonical key) VM-lowerable candidate sources
    from the stub LLM — the same candidate stream the bench stages use."""
    fake = llm.FakeLLM(seed=seed, junk_rate=0.0)
    c = wl.cluster
    codes, seen = [], set()
    for _ in range(40 * need):
        if len(codes) >= need:
            break
        code = template.fill_template(fake.complete("x"))
        try:
            key = transpiler.canonical_key(code)
            vm.compile_policy(code, c.n_padded, c.g_padded)
        except Exception:  # noqa: BLE001 — outside the VM vocabulary
            continue
        if key in seen:
            continue
        seen.add(key)
        codes.append(code)
    assert len(codes) >= need, f"only {len(codes)} unique VM candidates"
    return codes


# ------------------------------------------------------------- config


def test_budget_config_validation():
    with pytest.raises(ValueError, match="unknown budget schedule"):
        BudgetConfig(schedule="bandit")
    with pytest.raises(ValueError, match="eta must be >= 2"):
        BudgetConfig(schedule="halving", eta=1)
    with pytest.raises(ValueError, match="probe_steps must be >= 0"):
        BudgetConfig(schedule="halving", probe_steps=-1)
    with pytest.raises(ValueError, match="min_survivors must be >= 1"):
        BudgetConfig(schedule="halving", min_survivors=0)
    assert not BudgetConfig().enabled
    assert BudgetConfig(schedule="halving").enabled
    d = BudgetConfig(schedule="halving", eta=3, probe_steps=64).describe()
    assert d["eta"] == 3 and d["probe_steps"] == 64


def test_budget_survivor_arithmetic():
    b = BudgetConfig(schedule="halving", eta=2)
    assert b.survivors(8) == 4
    assert b.survivors(7) == 4  # ceil(7/2)
    assert b.survivors(1) == 1
    assert BudgetConfig(schedule="halving", eta=4).survivors(64) == 16
    # the floor wins over the fraction, but never exceeds n
    b = BudgetConfig(schedule="halving", eta=4, min_survivors=3)
    assert b.survivors(8) == 3
    assert b.survivors(2) == 2


def test_probe_sim_config():
    cfg = SimConfig(max_steps=512, track_ctime=False)
    p = probe_sim_config(cfg, BudgetConfig(schedule="halving",
                                           probe_steps=128))
    assert p.probe_score and p.max_steps == 128
    assert not p.track_ctime  # everything else rides along
    # probe_steps=0: full trace on the probe, only the scoring changes
    p0 = probe_sim_config(cfg, BudgetConfig(schedule="halving"))
    assert p0.probe_score and p0.max_steps == 512
    assert not cfg.probe_score  # the input config is untouched


# ------------------------------------------------------------- wiring


def test_budget_requires_suite_mode():
    with pytest.raises(ValueError, match="requires suite mode"):
        CodeEvaluator(micro_workload(),
                      budget=BudgetConfig(schedule="halving"))


def test_budget_rejects_fused_engine():
    wl = micro_workload()
    with pytest.raises(ValueError, match="fused"):
        CodeEvaluator(wl, engine="fused", suite=get_suite("smoke3", wl),
                      budget=BudgetConfig(schedule="halving"))


def test_disabled_budget_is_inert():
    wl = micro_workload()
    ev = CodeEvaluator(wl, suite=get_suite("smoke3", wl),
                       budget=BudgetConfig(schedule="none"))
    assert ev.budget is None
    assert not ev._budget_active(8)


def test_budget_forces_batched_vm_tier_on_cpu():
    wl = micro_workload()
    suite = get_suite("smoke3", wl)
    assert not CodeEvaluator(wl, suite=suite).vm_batch  # CPU default
    assert CodeEvaluator(wl, suite=suite,
                         budget=BudgetConfig(schedule="halving")).vm_batch


def test_fused_kernel_rejects_probe_score():
    from fks_tpu.sim import fused

    with pytest.raises(ValueError, match="probe_score"):
        fused.make_fused_population_run(
            micro_workload(), SimConfig(probe_score=True))


def test_seg_steps_validation_unified():
    from fks_tpu.utils import validate_seg_steps

    assert validate_seg_steps("4096") == 4096
    assert validate_seg_steps(0) == 0
    with pytest.raises(ValueError, match="must be an integer"):
        validate_seg_steps("abc")
    with pytest.raises(ValueError, match="must be >= 0"):
        validate_seg_steps(-3)
    with pytest.raises(ValueError, match="make_population_run_fn"):
        validate_seg_steps(0, zero_disables=False)
    # both consumers speak the same vocabulary: the backend names its env
    # var, the flat runner points at the unsegmented entry point
    with pytest.raises(ValueError, match="FKS_VM_SEG_STEPS must be"):
        validate_seg_steps("nope", source="FKS_VM_SEG_STEPS")


def test_backend_env_seg_steps_uses_helper(monkeypatch):
    monkeypatch.setenv("FKS_VM_SEG_STEPS", "-7")
    with pytest.raises(ValueError, match="FKS_VM_SEG_STEPS must be >= 0"):
        CodeEvaluator(micro_workload())
    monkeypatch.setenv("FKS_VM_SEG_STEPS", "2048")
    assert CodeEvaluator(micro_workload()).vm_seg_steps == 2048


def test_flat_segmented_runner_uses_helper():
    from fks_tpu.sim import flat

    wl = micro_workload()
    with pytest.raises(ValueError, match="make_population_run_fn"):
        flat.make_segmented_population_run(wl, vm.score_static, SimConfig(),
                                           seg_steps=0)
    with pytest.raises(ValueError, match="must be an integer"):
        flat.make_segmented_population_run(wl, vm.score_static, SimConfig(),
                                           seg_steps="junk")


# ------------------------------------------------- budgeted evaluation


@pytest.fixture(scope="module")
def budget_eval_setup():
    wl = micro_workload()
    suite = get_suite("smoke3", wl)
    robust = RobustConfig("cvar", cvar_alpha=0.5)
    budget = BudgetConfig(schedule="halving", eta=2, probe_suite="smoke3",
                          probe_steps=6)
    codes = _vm_codes(wl, 6)
    return wl, suite, robust, budget, codes


def test_budgeted_evaluate_end_to_end(budget_eval_setup):
    wl, suite, robust, budget, codes = budget_eval_setup
    ev = CodeEvaluator(wl, suite=suite, robust=robust, budget=budget)
    recs = ev.evaluate(codes)
    assert [r.code for r in recs] == codes  # input order preserved
    survivors = [r for r in recs if r.budget_rung == 1]
    pruned = [r for r in recs if r.budget_rung == 0]
    assert len(survivors) == 3 and len(pruned) == 3
    # pruned probe scores are capped BELOW every survivor's full score
    floor = min(r.score for r in survivors)
    assert all(r.score <= floor for r in pruned)
    # per-rung ledger stats: probe saw everyone, full rung the survivors
    assert [(r["rung"], r["entered"], r["survived"])
            for r in ev.last_budget_stats] == [(0, 6, 3), (1, 3, 3)]
    assert all(r["device_seconds"] > 0 for r in ev.last_budget_stats)
    assert all(r["lanes"] >= r["entered"] for r in ev.last_budget_stats)
    assert ev.last_eval_stats["budget_pruned"] == 3
    assert ev.vm_batch_count == 2  # one launch per rung


def test_budget_champion_matches_full_eval(budget_eval_setup):
    wl, suite, robust, budget, codes = budget_eval_setup
    budgeted = CodeEvaluator(wl, suite=suite, robust=robust, budget=budget)
    full = CodeEvaluator(wl, suite=suite, robust=robust, vm_batch=True)
    b_recs = budgeted.evaluate(codes)
    f_recs = full.evaluate(codes)
    assert all(r.budget_rung is None for r in f_recs)
    b_champ = max(b_recs, key=lambda r: r.score)
    f_best = max(r.score for r in f_recs)
    # pruning may change WHO gets full fidelity, never who wins: the
    # budget champion's full-suite score equals the unbudgeted maximum
    assert b_champ.budget_rung == 1
    assert b_champ.score == pytest.approx(f_best, abs=1e-6)
    # survivors carry true full-suite records — identical to the
    # unbudgeted evaluation of the same code
    by_code = {r.code: r for r in f_recs}
    for r in b_recs:
        if r.budget_rung == 1:
            ref = by_code[r.code]
            assert r.score == pytest.approx(ref.score, abs=1e-6)
            np.testing.assert_allclose(r.scenario_scores,
                                       ref.scenario_scores, atol=1e-6)


def test_budget_compiles_once_per_bucket(budget_eval_setup):
    from fks_tpu.obs import CompileWatcher

    wl, suite, robust, budget, codes = budget_eval_setup
    ev = CodeEvaluator(wl, suite=suite, robust=robust, budget=budget)
    watcher = CompileWatcher().install()
    try:
        ev.evaluate(codes)
        warm = watcher.backend_compile_count
        # a fresh generation of the SAME size must hit both rungs'
        # compiled programs — bucketed lanes, stable probe shape
        ev.evaluate(_vm_codes(wl, 6, seed=11))
        assert watcher.backend_compile_count == warm
    finally:
        watcher.uninstall()


def test_budget_inactive_below_two_candidates(budget_eval_setup):
    wl, suite, robust, budget, codes = budget_eval_setup
    ev = CodeEvaluator(wl, suite=suite, robust=robust, budget=budget)
    recs = ev.evaluate(codes[:1])
    assert recs[0].budget_rung is None  # unbudgeted path served it
    assert ev.last_budget_stats == []


def test_budgeted_suite_eval_direct():
    """The ladder below the evaluator: BudgetedSuiteEval.run on lowered
    programs — survivor indices sorted, probe scores for everyone, rung
    stats consistent."""
    import jax

    wl = micro_workload()
    cfg = SimConfig()
    robust = RobustConfig("mean")
    budget = BudgetConfig(schedule="halving", eta=3, probe_steps=6)
    codes = _vm_codes(wl, 6)
    c = wl.cluster
    progs = [vm.compile_policy(s, c.n_padded, c.g_padded) for s in codes]

    from fks_tpu.scenarios.robust import make_suite_eval
    suite = get_suite("smoke3", wl)
    full_ev = make_suite_eval(suite, vm.score_static, cfg,
                              population=True, engine="exact")
    ladder = BudgetedSuiteEval(
        wl, cfg, budget, robust,
        full_runner=lambda stacked: full_ev(stacked))
    out = ladder.run(progs)
    assert len(out.results) == 6
    assert out.survivor_indices == sorted(out.survivor_indices)
    assert len(out.survivor_indices) == 2  # ceil(6/3)
    assert [r.rung for r in out.rungs] == [0, 1]
    assert out.rungs[0].entered == 6 and out.rungs[0].survived == 2
    assert out.rungs[1].entered == 2
    assert len(out.probe_scores) == 6
    # the survivors ARE the probe's top-2 (stable argsort)
    order = np.argsort(-np.asarray(out.probe_scores), kind="stable")
    assert set(out.survivor_indices) == set(int(i) for i in order[:2])
    # pruned flags complement the survivor set
    assert [not p for p in out.pruned] == [
        i in out.survivor_indices for i in range(6)]
    del jax  # imported for parity with other direct-ladder users


# ------------------------------------------------------------ sentinel


class _Recorder:
    def __init__(self):
        self.metrics, self.events = [], []

    def metric(self, kind, payload=None, **kw):
        rec = dict(payload or {})
        rec.update(kw)
        self.metrics.append((kind, rec))

    def event(self, kind, **kw):
        self.events.append((kind, kw))


def test_check_champion_silent_on_sound_pruning(budget_eval_setup):
    from fks_tpu.obs.watchdog import ParitySentinel

    wl, suite, robust, budget, codes = budget_eval_setup
    ev = CodeEvaluator(wl, suite=suite, robust=robust, budget=budget)
    recs = ev.evaluate(codes)
    rec = _Recorder()
    sentinel = ParitySentinel(ev, tol=1e-5, recorder=rec)
    stats = sentinel.check_champion(0, recs)
    assert stats["alerts"] == 0 and sentinel.alerts == 0
    assert stats["checked"] == 4  # 3 pruned + the champion
    kinds = [k for k, _ in rec.metrics]
    assert kinds == ["parity"]
    assert rec.metrics[0][1]["source"] == "budget_champion"
    assert not rec.events


def test_check_champion_alerts_on_wrong_prune():
    from fks_tpu.obs.watchdog import ParitySentinel

    wl = micro_workload()
    ev = CodeEvaluator(wl, suite=get_suite("smoke3", wl),
                       budget=BudgetConfig(schedule="halving"))
    rec = _Recorder()
    sentinel = ParitySentinel(ev, tol=1e-5, recorder=rec)

    class _Ref:
        def evaluate_one(self, code):
            # the pruned candidate's true score beats the champion's
            return EvalRecord(code, 0.9 if code == "pruned" else 0.4)

    sentinel._ref = _Ref()
    records = [EvalRecord("champ", 0.5, budget_rung=1),
               EvalRecord("pruned", 0.1, budget_rung=0)]
    stats = sentinel.check_champion(3, records)
    assert stats["alerts"] == 1 and sentinel.alerts == 1
    assert stats["max_gap"] == pytest.approx(0.5)
    alerts = [kw for k, kw in rec.events if k == "alert"]
    assert len(alerts) == 1
    assert alerts[0]["source"] == "budget_champion"
    assert alerts[0]["generation"] == 3


def test_check_champion_skips_without_budget_records():
    from fks_tpu.obs.watchdog import ParitySentinel

    wl = micro_workload()
    ev = CodeEvaluator(wl, suite=get_suite("smoke3", wl),
                       budget=BudgetConfig(schedule="halving"))
    rec = _Recorder()
    sentinel = ParitySentinel(ev, tol=1e-5, recorder=rec)
    stats = sentinel.check_champion(0, [EvalRecord("a", 0.5)])
    assert stats == {"generation": 0, "checked": 0, "max_gap": 0.0,
                     "alerts": 0}
    assert not rec.metrics and not rec.events


# ----------------------------------------------------------- evolution


def test_evolution_with_budget_ledger_and_zero_alerts(tmp_path):
    from fks_tpu import obs
    from fks_tpu.funsearch import EvolutionConfig, FakeLLM
    from fks_tpu.funsearch import evolution as evo

    run_dir = tmp_path / "run"
    recorder = obs.FlightRecorder(str(run_dir), meta={"command": "test"})
    cfg = EvolutionConfig(population_size=8, generations=5, elite_size=2,
                          candidates_per_generation=6, max_workers=1,
                          seed=7, early_stop_threshold=1.1,
                          scenario_suite="smoke3",
                          robust_aggregation="cvar", robust_cvar_alpha=0.5,
                          budget_schedule="halving", budget_eta=2,
                          probe_suite="smoke3", probe_steps=6)
    fs = evo.run(micro_workload(), cfg, backend=FakeLLM(seed=7),
                 log=lambda _m: None, recorder=recorder)
    recorder.finish("ok")
    recorder.close()
    assert fs.evaluator.budget is not None
    # the acceptance bar: pruning never changed a champion over >= 5
    # generations of the stub LLM
    assert fs.sentinel.alerts == 0
    budgeted = [s for s in fs.history if s.budget_pruned > 0]
    assert budgeted, "no generation engaged the budget ladder"
    assert all(s.budget_device_seconds > 0 for s in budgeted)

    metrics = [json.loads(line) for line in
               (run_dir / "metrics.jsonl").read_text().splitlines()]
    rungs = [m for m in metrics if m["kind"] == "budget_rung"]
    assert rungs, "no budget_rung records in the run dir"
    by_gen = {}
    for r in rungs:
        by_gen.setdefault(r["generation"], []).append(r)
    for gen_rungs in by_gen.values():
        gen_rungs.sort(key=lambda r: r["rung"])
        assert [r["rung"] for r in gen_rungs] == [0, 1]
        assert gen_rungs[0]["survived"] == gen_rungs[1]["entered"]
        assert gen_rungs[0]["entered"] > gen_rungs[0]["survived"]
    # the champion audit ran each budgeted generation
    audits = [m for m in metrics if m["kind"] == "parity"
              and m.get("source") == "budget_champion"]
    assert len(audits) == len(by_gen)
    # ledger rows carry the budget columns
    gens = [m for m in metrics if m["kind"] == "generation"]
    assert any(g.get("budget_pruned", 0) > 0 for g in gens)

    # the schema checker accepts the new kind in a REAL run dir
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "check_jsonl_schema.py"),
         "--run-dir", str(run_dir)],
        capture_output=True, text=True, cwd=str(REPO))
    assert proc.returncode == 0, proc.stderr + proc.stdout


def test_evolution_config_budget_from_json(tmp_path):
    from fks_tpu.funsearch.evolution import EvolutionConfig

    path = tmp_path / "cfg.json"
    path.write_text(json.dumps({"funsearch": {
        "budget_schedule": "halving", "budget_eta": 3,
        "probe_suite": "smoke3", "probe_steps": 99}}))
    cfg = EvolutionConfig.from_json(str(path))
    assert cfg.budget_schedule == "halving"
    assert cfg.budget_eta == 3
    assert cfg.probe_suite == "smoke3"
    assert cfg.probe_steps == 99
    bare = tmp_path / "bare.json"
    bare.write_text("{}")
    assert EvolutionConfig.from_json(str(bare)).budget_schedule == "none"
