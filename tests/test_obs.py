"""Flight recorder contract: run-dir layout, null-path zero writes, span
nesting/sync, jax.monitoring compile capture, device/mesh snapshots, the
evolution ledger, and the report renderer. (The recorder is the evidence
surface for every ROADMAP claim, so these tests pin its schema.)"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu import obs
from fks_tpu.obs import recorder as recorder_mod


# --------------------------------------------------------------- recorder

def test_flight_recorder_run_dir_layout(tmp_path):
    d = tmp_path / "run"
    with obs.FlightRecorder(str(d), meta={"command": "test"}) as rec:
        rec.event("span", label="x", seconds=0.1)
        rec.metric("generation", {"generation": 1, "best_score": 0.5})
        rec.annotate_meta(note="hello")
    meta = json.loads((d / "meta.json").read_text())
    assert meta["run_id"] == rec.run_id
    assert meta["command"] == "test"
    assert meta["note"] == "hello"
    assert meta["status"] == "ok"
    assert "wall_seconds" in meta
    events = [json.loads(l) for l in (d / "events.jsonl").read_text()
              .splitlines()]
    assert events[0]["kind"] == "span" and events[0]["seq"] == 0
    assert "ts" in events[0]
    metrics = [json.loads(l) for l in (d / "metrics.jsonl").read_text()
               .splitlines()]
    assert metrics[0]["kind"] == "generation"
    assert metrics[0]["best_score"] == 0.5
    beat = json.loads((d / "heartbeat").read_text())
    assert beat["run_id"] == rec.run_id


def test_flight_recorder_error_status(tmp_path):
    d = tmp_path / "run"
    with pytest.raises(RuntimeError):
        with obs.recording(obs.FlightRecorder(str(d))):
            raise RuntimeError("boom")
    assert json.loads((d / "meta.json").read_text())["status"] == "error"
    assert obs.get_recorder() is obs.NULL  # restored


def test_recorder_coerces_numpy_and_jax_scalars(tmp_path):
    d = tmp_path / "run"
    with obs.FlightRecorder(str(d)) as rec:
        rec.metric("scale", score=np.float32(0.25), n=np.int64(3),
                   arr=jnp.arange(2), dev=jnp.float32(1.5))
    row = json.loads((d / "metrics.jsonl").read_text().splitlines()[0])
    assert row["score"] == 0.25 and row["n"] == 3
    assert row["arr"] == [0, 1] and row["dev"] == 1.5


def test_null_recorder_writes_nothing(tmp_path, monkeypatch):
    """The disabled path's contract: zero filesystem writes."""
    monkeypatch.chdir(tmp_path)
    rec = obs.NullRecorder()
    rec.event("span", label="x")
    rec.metric("generation", {"g": 1})
    rec.heartbeat()
    rec.annotate_meta(a=1)
    rec.finish()
    rec.close()
    assert list(tmp_path.iterdir()) == []
    assert rec.enabled is False


def test_recording_installs_and_restores(tmp_path):
    assert obs.get_recorder() is obs.NULL
    rec = obs.FlightRecorder(str(tmp_path / "r"))
    with obs.recording(rec) as got:
        assert got is rec
        assert obs.get_recorder() is rec
    assert obs.get_recorder() is obs.NULL
    assert json.loads((tmp_path / "r" / "meta.json").read_text())[
        "status"] == "ok"


# ------------------------------------------------------------------ spans

def test_span_nesting_paths_and_fields(tmp_path):
    with obs.FlightRecorder(str(tmp_path / "r")) as rec:
        with obs.span("outer", recorder=rec):
            assert obs.span_path() == "outer"
            with obs.span("inner", recorder=rec, generation=3):
                assert obs.span_path() == "outer/inner"
        assert obs.span_path() == ""
    events = [json.loads(l) for l in
              (tmp_path / "r" / "events.jsonl").read_text().splitlines()]
    by_label = {e["label"]: e for e in events if e["kind"] == "span"}
    assert by_label["inner"]["path"] == "outer/inner"
    assert by_label["inner"]["depth"] == 1
    assert by_label["inner"]["generation"] == 3
    assert by_label["outer"]["path"] == "outer"
    assert by_label["outer"]["depth"] == 0
    # inner exits (and records) before outer
    assert by_label["inner"]["seq"] < by_label["outer"]["seq"]
    assert by_label["outer"]["seconds"] >= by_label["inner"]["seconds"]


def test_span_syncs_device_value_before_stopping_clock(monkeypatch):
    from fks_tpu.utils import profiling

    synced = []
    monkeypatch.setattr(profiling.jax, "block_until_ready",
                        lambda v: synced.append(v))
    sentinel = object()
    with obs.span("eval") as t:
        got = t.sync(sentinel)
    assert got is sentinel and synced == [sentinel]
    assert t.seconds >= 0


def test_span_stack_unwinds_on_exception():
    with pytest.raises(ValueError):
        with obs.span("broken"):
            raise ValueError("x")
    assert obs.span_path() == ""


# -------------------------------------------------------------- telemetry

def test_compile_watcher_captures_compile_events(tmp_path):
    """Acceptance: the jax.monitoring listener captures >= 1 compile event
    when a fresh program is jit-compiled inside the watch scope."""
    with obs.FlightRecorder(str(tmp_path / "r")) as rec:
        with obs.CompileWatcher(rec) as w:
            # fresh shape+closure => cannot hit jit cache from other tests
            @jax.jit
            def _fresh(x):
                return (x * 3.14159).sum() + 41.0

            _fresh(jnp.arange(17.0)).block_until_ready()
        assert len(w.events) >= 1
        assert w.backend_compile_count >= 1
        assert w.backend_compile_seconds > 0
        summary = w.summary()
        assert any(k.startswith("/jax/core/compile") for k in summary)
    events = [json.loads(l) for l in
              (tmp_path / "r" / "events.jsonl").read_text().splitlines()]
    compiles = [e for e in events if e["kind"] == "compile"]
    assert compiles and all("seconds" in e for e in compiles)


def test_compile_watcher_uninstall_stops_capture():
    w = obs.CompileWatcher(obs.NULL).install()
    w.uninstall()
    n0 = len(w.events)

    @jax.jit
    def _after(x):
        return x - 2.71828

    _after(jnp.arange(5.0)).block_until_ready()
    assert len(w.events) == n0


def test_watch_compiles_null_when_disabled():
    with obs.watch_compiles(obs.NULL) as w:
        assert w is None


def test_device_snapshot_cpu_guarded():
    snap = obs.device_snapshot()
    assert len(snap) == len(jax.devices())
    for d in snap:
        assert d["platform"] == "cpu"
        assert "memory_stats" in d  # None on CPU is fine; key must exist


def test_mesh_snapshot_pad_waste(tmp_path):
    from fks_tpu.parallel import population_mesh
    from fks_tpu.parallel.mesh import num_shards, pad_stats

    mesh = population_mesh(jax.devices())
    shards = num_shards(mesh)
    assert shards == 8  # conftest's virtual 8-device mesh
    snap = obs.mesh_snapshot(mesh, real_count=5)
    assert snap["shards"] == shards
    assert snap["real_count"] == 5
    assert snap["padded_count"] == 8
    assert snap["pad_lanes"] == 3
    assert snap["pad_waste_fraction"] == pytest.approx(3 / 8)
    assert pad_stats(8, 8)["pad_waste_fraction"] == 0.0
    assert pad_stats(0, 8)["padded_count"] == 0
    with obs.FlightRecorder(str(tmp_path / "r")) as rec:
        obs.record_mesh(mesh, real_count=5, recorder=rec)
    ev = [json.loads(l) for l in
          (tmp_path / "r" / "events.jsonl").read_text().splitlines()]
    assert ev[0]["kind"] == "mesh" and ev[0]["pad_lanes"] == 3


# ----------------------------------------------------------------- ledger

class _FakeEvaluator:
    compile_count = 2
    vm_count = 0
    vm_batch_count = 1
    segments_dispatched = 10


def test_ledger_counter_deltas_and_throughput(tmp_path):
    from fks_tpu.funsearch.evolution import GenerationStats

    ev = _FakeEvaluator()
    with obs.FlightRecorder(str(tmp_path / "r")) as rec:
        ledger = obs.EvolutionLedger(rec, ev)
        ledger.begin_generation()
        ev.compile_count = 5
        ev.segments_dispatched = 16
        stats = GenerationStats(
            generation=1, best_score=0.5, mean_score=0.4, new_candidates=8,
            accepted=6, rejected_similar=2, eval_seconds=2.0, compile_count=5,
            median_score=0.45, p10_score=0.3, sandbox_failed=1,
            transpile_failed=1, rescore_fallbacks=0, llm_seconds=0.7)
        row = ledger.commit(stats)
    assert row["programs_compiled"] == 3  # 5 - 2
    assert row["vm_segments"] == 6  # 16 - 10
    assert row["vm_batches"] == 0
    assert row["evals_per_sec"] == 4.0
    assert row["sandbox_failed"] == 1 and row["transpile_failed"] == 1
    disk = json.loads((tmp_path / "r" / "metrics.jsonl").read_text()
                      .splitlines()[0])
    assert disk["kind"] == "generation" and disk["generation"] == 1
    assert (tmp_path / "r" / "heartbeat").exists()


def test_ledger_null_recorder_no_writes(tmp_path, monkeypatch):
    from fks_tpu.funsearch.evolution import GenerationStats

    monkeypatch.chdir(tmp_path)
    ledger = obs.EvolutionLedger(obs.NULL, _FakeEvaluator())
    ledger.begin_generation()
    row = ledger.commit(GenerationStats(
        generation=1, best_score=0.1, mean_score=0.1, new_candidates=1,
        accepted=1, rejected_similar=0, eval_seconds=0.0, compile_count=0))
    assert row["generation"] == 1
    assert "evals_per_sec" not in row  # zero eval time -> no rate
    assert list(tmp_path.iterdir()) == []


# ------------------------------------------------- evolve null-path contract

def test_evolve_generation_without_recorder_writes_nothing(tmp_path,
                                                           monkeypatch,
                                                           micro_workload):
    """Acceptance: with no recorder configured, evolve_generation makes
    zero filesystem writes (relative to the cwd it runs in)."""
    from fks_tpu.funsearch import EvolutionConfig, FakeLLM
    from fks_tpu.funsearch.backend import CodeEvaluator
    from fks_tpu.funsearch.evolution import FunSearch

    fs = FunSearch(
        CodeEvaluator(micro_workload, engine="exact"),
        EvolutionConfig(generations=1, population_size=4, elite_size=1,
                        candidates_per_generation=2, max_workers=2),
        backend=FakeLLM(seed=0), log=lambda s: None)
    assert fs.recorder is obs.NULL
    fs.initialize_population()
    monkeypatch.chdir(tmp_path)  # any relative write would land here
    stats = fs.evolve_generation()
    assert list(tmp_path.iterdir()) == []
    assert stats.generation == 1
    assert stats.median_score <= stats.best_score
    assert stats.p10_score <= stats.median_score <= stats.best_score


# ----------------------------------------------------------------- report

def test_percentiles_nearest_rank():
    from fks_tpu.funsearch.evolution import _percentile

    desc = [5.0, 4.0, 3.0, 2.0, 1.0]
    assert _percentile(desc, 0.5) == 3.0
    assert _percentile(desc, 0.10) == 1.0
    assert _percentile(desc, 1.0) == 5.0
    assert _percentile([2.5], 0.5) == 2.5
    assert _percentile([], 0.5) == 0.0


def test_sparkline():
    assert obs.sparkline([]) == ""
    assert obs.sparkline([1.0, 1.0]) == "▄▄"
    s = obs.sparkline([0.0, 0.5, 1.0])
    assert s[0] == "▁" and s[-1] == "█" and len(s) == 3


def test_render_report_from_jsonl_alone(tmp_path):
    """The report is a pure function of the run dir's files."""
    d = str(tmp_path / "r")
    with obs.FlightRecorder(d, meta={"command": "evolve"}) as rec:
        rec.event("device", platform="cpu", id=0, memory_stats=None)
        rec.event("span", label="llm", path="llm", depth=0, seconds=0.5)
        rec.event("compile",
                  key="/jax/core/compile/backend_compile_duration",
                  seconds=1.25)
        for g, best in ((1, 0.3), (2, 0.45)):
            rec.metric("generation", {
                "generation": g, "best_score": best, "median_score": best / 2,
                "p10_score": best / 4, "new_candidates": 8, "accepted": 6,
                "rejected_similar": 2, "sandbox_failed": 1,
                "transpile_failed": 0, "rescore_fallbacks": 0,
                "llm_seconds": 0.5, "eval_seconds": 2.0,
                "evals_per_sec": 4.0, "vm_segments": 3})
        rec.metric("bench_stage", {"stage": "throughput",
                                   "evals_per_sec": 100.0,
                                   "compile_seconds": 9.5,
                                   "steady_state_seconds": 5.0})
        rec.annotate_meta(best_score=0.45)
    out = obs.render_report(d)
    assert "status ok" in out
    assert "[evolve]" in out
    assert "generations: 2" in out
    assert "0.45" in out
    assert "backend_compile_duration: 1x 1.250s total" in out
    assert "llm: 1x 0.500s" in out
    assert "bench stage throughput:" in out
    assert "compile_seconds=9.5" in out
    assert "devices: 1x cpu" in out
    # the sparkline line tracks best fitness across generations
    assert "fitness best 0.3000 -> 0.4500" in out


def test_render_report_tolerates_torn_tail_and_missing_files(tmp_path):
    d = tmp_path / "r"
    d.mkdir()
    (d / "meta.json").write_text(json.dumps(
        {"run_id": "x", "started": "now", "status": "running"}))
    (d / "metrics.jsonl").write_text(
        json.dumps({"ts": 1, "kind": "generation", "generation": 1,
                    "best_score": 0.2}) + "\n" + '{"ts": 2, "kind": "gen')
    out = obs.render_report(str(d))
    assert "generations: 1" in out
    assert "status running" in out
    with pytest.raises(FileNotFoundError):
        obs.render_report(str(tmp_path / "nope"))


def test_read_jsonl_rejects_mid_file_corruption(tmp_path):
    from fks_tpu.obs.report import read_jsonl

    p = tmp_path / "bad.jsonl"
    p.write_text('{"ok": 1}\n{broken\n{"ok": 2}\n')
    with pytest.raises(ValueError, match="bad.jsonl:2"):
        read_jsonl(str(p))


# ---------------------------------------------------------- schema checker

def test_check_jsonl_schema_tool(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)

    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps({"ts": 1, "kind": "a"}) + "\n"
                    + json.dumps({"ts": 2, "kind": "b"}) + "\n")
    assert len(cjs.check_jsonl(str(good), required=("ts", "kind"))) == 2

    missing = tmp_path / "missing.jsonl"
    missing.write_text(json.dumps({"ts": 1}) + "\n")
    with pytest.raises(cjs.SchemaError, match="missing"):
        cjs.check_jsonl(str(missing), required=("kind",))

    torn = tmp_path / "torn.jsonl"
    torn.write_text(json.dumps({"ts": 1, "kind": "a"}) + "\n" + '{"half')
    assert len(cjs.check_jsonl(str(torn), required=("ts",))) == 1

    with obs.FlightRecorder(str(tmp_path / "run")) as rec:
        rec.event("span", label="x", seconds=0.0)
        # known kinds must carry their required keys (watchdog schema)
        rec.metric("generation", {"generation": 1, "best_score": 0.5})
    counts = cjs.check_run_dir(str(tmp_path / "run"))
    assert counts["events.jsonl"] == 1
    assert counts["metrics.jsonl"] == 1
    assert counts["heartbeat"] == 1
    assert cjs.main([str(good), "--require", "ts,kind"]) == 0
    assert cjs.main(["--run-dir", str(tmp_path / "run")]) == 0
    assert cjs.main([str(missing), "--require", "kind"]) == 1
