"""Layout observability layer (fks_tpu.obs.layout).

The ISSUE-19 acceptance criteria, as tests:

- ``LayoutSpec``: canonical keys (axis order normalized), round-trips
  through ``parse_layout_key``, and the validation rules — axes come
  from the closed vocabulary, candidates always shard, sharded axes
  stay vmapped, segments never shard (they are the host loop);
- default-spec bit-identity: ``layout=None`` and an explicit
  ``default_spec()`` lower to the SAME jaxpr on both sharded entry
  points (the in-process twin of the ``sharded_eval/default_layout``
  pin in tests/fixtures/jaxpr_pins.json);
- ``LayoutLedger``: dedupe of identical consecutive rows per
  (component, layout_key, mesh_layout, workload_key), changed rows
  kept, cap trimming;
- ``rollup_layouts``: occupancy from summed lane-steps, worst
  pad-waste, best steady / worst compile seconds, and the predicted
  HBM join from footprint rows by mesh layout;
- ``valid_layouts`` enumeration math and the s=1-first ordering;
- ``explore_layouts`` over the conftest 8-device mesh: every probe's
  robust vector matches the default layout (parity), the summary
  carries the compare-gated keys, and the best layout persists into
  ``RunHistory`` for prior read-back;
- closed vocabularies and the key regex pinned against
  tools/check_jsonl_schema.py's stdlib-only copies;
- ``cli layout`` exit contract (view needs --run-dir, golden renders).

The full pop-64 x suite-8 exploration is gated end-to-end by
tools/run_full_suite.py's ``layout_gate`` and ``bench.py --stage
layout``; here it runs at reduced scale (pop 8, flat engine).
"""
import json
import os
import pathlib
import sys

import jax
import numpy as np
import pytest

from fks_tpu import cli
from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.obs.history import RunHistory
from fks_tpu.obs.layout import (
    LAYOUT_AXES, LAYOUT_COMPONENTS, LEDGER, LayoutLedger, LayoutSpec,
    cost_stats_of, default_spec, explore_layouts, parse_layout_key,
    record_layout, rollup_layouts, tag_layout, valid_layouts,
)
from fks_tpu.models import parametric
from fks_tpu.parallel.mesh import layout_mesh, make_sharded_eval
from fks_tpu.scenarios import get_suite, make_sharded_suite_eval
from fks_tpu.sim.engine import SimConfig

FIXTURES = pathlib.Path(__file__).parent / "fixtures"
GOLDEN = str(FIXTURES / "golden_run")


class RecStub:
    enabled = True

    def __init__(self):
        self.metrics = []

    def metric(self, kind, *a, **fields):
        rec = dict(a[0]) if a and isinstance(a[0], dict) else {}
        rec.update(fields)
        self.metrics.append({"kind": kind, **rec})


# ----------------------------------------------------------------- spec

def test_spec_key_canonicalizes_axis_order():
    a = LayoutSpec(shard=("candidates",),
                   vmap=("scenarios", "candidates"))
    b = LayoutSpec(shard=("candidates",),
                   vmap=("candidates", "scenarios"))
    assert a.key == b.key == "shard[candidates]|vmap[candidates,scenarios]|seg=0"


def test_spec_key_roundtrips():
    for spec in (default_spec(), default_spec(scenarios=True),
                 default_spec(seg_steps=128),
                 LayoutSpec(shard=("candidates", "scenarios"),
                            vmap=("candidates", "scenarios"))):
        back = parse_layout_key(spec.key)
        assert back == spec
        assert back.key == spec.key


def test_default_spec_keys():
    assert default_spec().key == "shard[candidates]|vmap[candidates]|seg=0"
    assert default_spec(scenarios=True).key == \
        "shard[candidates]|vmap[candidates,scenarios]|seg=0"
    assert default_spec(seg_steps=64).seg_steps == 64


@pytest.mark.parametrize("kwargs,msg", [
    (dict(shard=("candidates", "bogus"), vmap=("candidates", "bogus")),
     "unknown layout axis"),
    (dict(shard=("candidates", "candidates"),
          vmap=("candidates",)), "duplicate"),
    (dict(shard=("candidates", "segments"),
          vmap=("candidates", "segments")), "host loop"),
    (dict(shard=("scenarios",), vmap=("scenarios",)),
     "'candidates' must shard"),
    (dict(shard=("candidates", "scenarios"), vmap=("candidates",)),
     "missing from vmap"),
    (dict(seg_steps=-1), "seg_steps"),
])
def test_spec_validation_errors(kwargs, msg):
    with pytest.raises(ValueError, match=msg):
        LayoutSpec(**kwargs)


@pytest.mark.parametrize("key", [
    "", "shard[candidates]", "shard[candidates]|vmap[candidates]",
    "shard[candidates]|vmap[candidates]|seg=x",
    "shard[CANDIDATES]|vmap[candidates]|seg=0",
    "vmap[candidates]|shard[candidates]|seg=0",
])
def test_parse_layout_key_rejects_malformed(key):
    with pytest.raises(ValueError):
        parse_layout_key(key)


def test_parse_layout_key_rejects_out_of_vocabulary():
    # matches the regex shape but names an unknown axis
    with pytest.raises(ValueError, match="unknown layout axis"):
        parse_layout_key("shard[candidates,pods]|vmap[candidates,pods]|seg=0")


def test_tag_layout_best_effort():
    def fn():
        pass

    assert tag_layout(fn, "k") is fn
    assert fn._fks_layout_key == "k"
    assert tag_layout(object(), "k") is not None  # slots: no raise


# --------------------------------------------------------- valid layouts

def test_valid_layouts_eight_devices_eight_scenarios():
    lays = valid_layouts(8, 8)
    assert [l["mesh_shape"] for l in lays] == ["8x1", "4x2", "2x4", "1x8"]
    assert lays[0]["spec"] == default_spec(scenarios=True)
    for l in lays[1:]:
        assert "scenarios" in l["spec"].shard
        assert l["candidate_shards"] * l["scenario_shards"] == 8


def test_valid_layouts_scenario_divisibility():
    # 3 scenarios: no s>1 divides both 8 devices and 3 scenarios
    assert [l["mesh_shape"] for l in valid_layouts(8, 3)] == ["8x1"]
    assert [l["mesh_shape"] for l in valid_layouts(4, 8)] == \
        ["4x1", "2x2", "1x4"]
    with pytest.raises(ValueError):
        valid_layouts(0, 8)


# --------------------------------------------------------------- ledger

def test_ledger_dedupes_identical_consecutive_rows():
    led = LayoutLedger(cap=8)
    row = {"component": "eval", "layout_key": "k", "mesh_layout": "pop=8",
           "workload_key": "w", "real_count": 64}
    assert led.add(dict(row)) is True
    assert led.add(dict(row)) is False          # identical repeat drops
    changed = dict(row, real_count=65)
    assert led.add(changed) is True             # changed padding lands
    assert led.add(dict(row)) is True           # differs from the LAST row
    assert len(led.records()) == 3


def test_ledger_dedupe_is_per_identity_and_cap_trims():
    led = LayoutLedger(cap=3)
    row = lambda wk: {"component": "eval", "layout_key": "k",  # noqa: E731
                      "mesh_layout": "", "workload_key": wk}
    assert led.add(row("a")) is True
    assert led.add(row("b")) is True
    # interleaving does NOT defeat dedupe: last-row memory is per identity
    assert led.add(row("a")) is False
    assert led.add(row("b")) is False
    led.clear()
    for i in range(5):
        led.add({"component": "eval", "layout_key": "k",
                 "mesh_layout": "", "workload_key": str(i)})
    assert [r["workload_key"] for r in led.records()] == ["2", "3", "4"]


def test_record_layout_row_shape_and_dedupe():
    LEDGER.clear()
    stub = RecStub()
    rec = record_layout("eval", default_spec(), workload_key="w",
                        real_count=5, recorder=stub)
    assert rec["component"] == "eval"
    assert rec["layout_key"] == default_spec().key
    assert rec["mesh_layout"] == ""             # no mesh given
    assert rec["real_count"] == 5               # kept even without a mesh
    assert rec["axes"] == ["candidates"]
    assert record_layout("eval", default_spec(), workload_key="w",
                         real_count=5, recorder=stub) is None  # deduped
    assert [m["kind"] for m in stub.metrics] == ["layout_ledger"]
    with pytest.raises(ValueError, match="unknown layout component"):
        record_layout("controller", default_spec(), recorder=stub)
    LEDGER.clear()


def test_record_layout_folds_mesh_occupancy():
    LEDGER.clear()
    stub = RecStub()
    mesh = layout_mesh(jax.devices(), 1)        # 8 candidate shards
    rec = record_layout("suite_eval", default_spec(scenarios=True),
                        mesh=mesh, workload_key="w", real_count=6,
                        scenarios=8, recorder=stub)
    assert rec["mesh_layout"] == "pop=8"        # s=1: plain pop mesh
    assert rec["padded_count"] == 8
    assert rec["pad_waste_fraction"] == pytest.approx(0.25)
    assert rec["real_lane_steps"] == 6 * 8
    assert rec["launched_lane_steps"] == 8 * 8
    LEDGER.clear()


def test_cost_stats_of_summarizes_and_degrades():
    class Ok:
        def cost_analysis(self):
            return {"flops": 10.0, "bytes accessed": 100.0,
                    "collective-permute bytes": 7.0, "utilization": 0.5}

    class Listy(Ok):
        def cost_analysis(self):
            return [super().cost_analysis()]

    class Broken:
        def cost_analysis(self):
            raise RuntimeError("no analysis")

    want = {"cost_flops": 10.0, "cost_bytes_accessed": 100.0,
            "collective_bytes": 7.0}
    assert cost_stats_of(Ok()) == want
    assert cost_stats_of(Listy()) == want
    assert cost_stats_of(Broken()) == {}


# --------------------------------------------------------------- rollup

def test_rollup_layouts_math_and_hbm_join():
    key = default_spec().key
    rows = [
        {"component": "eval", "layout_key": key, "mesh_layout": "pop=8",
         "workload_key": "w", "real_count": 6, "padded_count": 8,
         "pad_waste_fraction": 0.25, "real_lane_steps": 6,
         "launched_lane_steps": 8, "steady_seconds": 0.5,
         "compile_seconds": 2.0},
        {"component": "probe", "layout_key": key, "mesh_layout": "pop=8",
         "workload_key": "w", "real_count": 8, "padded_count": 8,
         "pad_waste_fraction": 0.0, "real_lane_steps": 8,
         "launched_lane_steps": 8, "steady_seconds": 0.25,
         "compile_seconds": 3.0, "cost_bytes_accessed": 1e6},
        {"component": "serve", "layout_key": key, "mesh_layout": "pop=4",
         "workload_key": "w"},
    ]
    feet = [{"mesh_layout": "pop=8", "total_bytes": 1000},
            {"mesh_layout": "pop=8", "total_bytes": 4000},
            {"mesh_layout": "pop=2", "total_bytes": 9000}]
    agg = rollup_layouts(rows, feet)
    assert len(agg) == 2
    eight = next(a for a in agg if a["mesh_layout"] == "pop=8")
    assert eight["rows"] == 2
    assert eight["components"] == ["eval", "probe"]
    assert eight["occupancy"] == pytest.approx(14 / 16)
    assert eight["pad_waste_fraction_max"] == pytest.approx(0.25)
    assert eight["real_count"] == 8             # latest padded row wins
    assert eight["steady_seconds"] == pytest.approx(0.25)   # best
    assert eight["compile_seconds"] == pytest.approx(3.0)   # worst
    assert eight["cost_bytes_accessed"] == pytest.approx(1e6)
    assert eight["predicted_hbm_bytes"] == 4000  # largest same-mesh claim
    four = next(a for a in agg if a["mesh_layout"] == "pop=4")
    assert four["occupancy"] == 1.0             # no lane-step rows
    assert "predicted_hbm_bytes" not in four    # no pop=4 footprint


# --------------------------------------------- default-spec bit-identity

def test_default_layout_lowers_bit_identically():
    wl = synthetic_workload(8, 12, seed=0)
    mesh = layout_mesh(jax.devices()[:1], 1)
    params = parametric.init_population(jax.random.PRNGKey(0), 2)
    implicit = make_sharded_eval(wl, mesh, cfg=SimConfig(), elite_k=2,
                                 engine="flat")
    explicit = make_sharded_eval(wl, mesh, cfg=SimConfig(), elite_k=2,
                                 engine="flat", layout=default_spec())
    assert str(jax.make_jaxpr(implicit)(params)) == \
        str(jax.make_jaxpr(explicit)(params))

    suite = get_suite("smoke3", wl)
    implicit = make_sharded_suite_eval(suite, mesh, cfg=SimConfig(),
                                       elite_k=2, engine="flat")
    explicit = make_sharded_suite_eval(
        suite, mesh, cfg=SimConfig(), elite_k=2, engine="flat",
        layout=default_spec(scenarios=True))
    assert str(jax.make_jaxpr(implicit)(params)) == \
        str(jax.make_jaxpr(explicit)(params))


def test_default_layout_pin_present():
    doc = json.loads((FIXTURES / "jaxpr_pins.json").read_text())
    assert "sharded_eval/default_layout" in doc["pins"]


def test_sharded_entry_points_carry_layout_tags():
    wl = synthetic_workload(8, 12, seed=0)
    mesh = layout_mesh(jax.devices()[:1], 1)
    ev = make_sharded_eval(wl, mesh, cfg=SimConfig(), elite_k=2,
                           engine="flat")
    assert ev._fks_layout_key == default_spec().key
    sv = make_sharded_suite_eval(get_suite("smoke3", wl), mesh,
                                 cfg=SimConfig(), elite_k=2, engine="flat")
    assert sv._fks_layout_key == default_spec(scenarios=True).key


# ------------------------------------------------------------- explorer

def test_explore_layouts_parity_summary_and_prior(tmp_path):
    LEDGER.clear()
    stub = RecStub()
    wl = synthetic_workload(8, 16, seed=0)
    suite = get_suite("default8", wl)
    history = RunHistory(str(tmp_path))
    summary = explore_layouts(suite, population=8, elite_k=4,
                              engine="flat", recorder=stub,
                              history=history, workload_key="pop8_default8",
                              reps=1)
    assert summary["layouts_probed"] == 4       # 8x1, 4x2, 2x4, 1x8
    assert summary["devices"] == 8 and summary["scenarios"] == 8
    assert summary["default_layout_key"] == default_spec(scenarios=True).key
    assert summary["parity_max_abs"] < 1e-6     # x64: layouts agree
    assert summary["layout_best_over_default"] >= 1.0
    assert 0.0 <= summary["layout_pad_waste_frac"] < 1.0
    shapes = [p["mesh_shape"] for p in summary["probes"]]
    assert shapes[0] == "8x1" and summary["best_mesh_shape"] in shapes
    # one layout_probe metric per layout, plus the ledger rows
    kinds = [m["kind"] for m in stub.metrics]
    assert kinds.count("layout_probe") == 4
    assert kinds.count("layout_ledger") >= 4
    # the best measured layout persisted as a prior and reads back
    prior = history.layout_prior("pop8_default8", "8")
    assert prior is not None
    assert prior["layout_key"] == summary["best_layout_key"]
    assert prior["mesh_shape"] == summary["best_mesh_shape"]
    assert prior["layout_best_over_default"] == \
        summary["layout_best_over_default"]
    LEDGER.clear()


def test_history_layout_prior_roundtrip(tmp_path):
    h = RunHistory(str(tmp_path))
    assert h.layout_prior("w", "8") is None
    h.record_layout_prior("w", "8", "k1", {"steady_seconds": 0.5})
    h.record_layout_prior("w", "8", "k2", {"steady_seconds": 0.4})
    h.record_layout_prior("w", "4", "k3")
    assert h.layout_prior("w", "8")["layout_key"] == "k2"  # newest wins
    assert h.layout_prior("w", "4")["layout_key"] == "k3"
    # corrupted store degrades to empty, never raises
    (tmp_path / "layouts.json").write_text("{broken")
    assert h.layout_prior("w", "8") is None


# ------------------------------------------------- vocabulary pinning

def _schema_tool():
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    return cjs


def test_vocabularies_pinned_against_schema_tool():
    cjs = _schema_tool()
    assert set(LAYOUT_AXES) == cjs.LAYOUT_AXES
    assert set(LAYOUT_COMPONENTS) == cjs.LAYOUT_COMPONENTS
    for spec in (default_spec(), default_spec(scenarios=True),
                 LayoutSpec(shard=("candidates", "scenarios"),
                            vmap=("candidates", "scenarios"),
                            seg_steps=256)):
        assert cjs._LAYOUT_KEY_RE.match(spec.key)
    assert not cjs._LAYOUT_KEY_RE.match("shard[x]|vmap[x]")


# ----------------------------------------------------------- cli layout

def test_cli_layout_requires_a_mode(capsys):
    assert cli.main(["layout"]) == 2


def test_cli_layout_view_golden(capsys):
    assert cli.main(["layout", "--run-dir", GOLDEN]) == 0
    out = capsys.readouterr().out
    assert "layout" in out
    assert default_spec(scenarios=True).key in out
    assert "4x2" in out                          # the golden probe row
