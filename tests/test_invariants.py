"""Invariant-audit mode (reference: simulator/main.py:201-272
``_validate_cluster_invariants``, opt-in via ``validate_invariants``):
a correct run reports zero violations; corrupted state is detected."""
import dataclasses

import pytest
import jax.numpy as jnp
import numpy as np

from fks_tpu.models import zoo
from fks_tpu.sim.engine import SimConfig, initial_state, make_run_fn, simulate
from tests.test_engine_micro import micro_workload


@pytest.mark.slow
def test_micro_run_zero_violations():
    wl = micro_workload()
    res = simulate(wl, zoo.micro_best_fit(dtype=jnp.float64),
                   SimConfig(score_dtype=jnp.float64, validate_invariants=True))
    assert int(res.invariant_violations) == 0
    assert not bool(res.failed)


@pytest.mark.slow
def test_default_trace_zero_violations(default_workload):
    res = simulate(default_workload, zoo.ZOO["best_fit"](),
                   SimConfig(validate_invariants=True))
    assert int(res.invariant_violations) == 0
    assert float(res.policy_score) > 0.4  # audit must not perturb results


def test_corrupted_state_detected():
    """Hand-corrupt the initial carry (a node owing more CPU than its
    capacity allows) — every subsequent audited step must flag it."""
    wl = micro_workload()
    cfg = SimConfig(score_dtype=jnp.float64, validate_invariants=True)
    state = initial_state(wl, cfg)
    state = state._replace(cpu_left=state.cpu_left.at[0].add(-999))
    run = make_run_fn(wl, zoo.micro_best_fit(dtype=jnp.float64), cfg)
    res = run(state)
    assert int(res.invariant_violations) > 0


def test_audit_off_reports_zero_even_when_corrupt():
    wl = micro_workload()
    cfg = SimConfig(score_dtype=jnp.float64, validate_invariants=False)
    state = initial_state(wl, cfg)
    state = state._replace(cpu_left=state.cpu_left.at[0].add(-999))
    run = make_run_fn(wl, zoo.micro_best_fit(dtype=jnp.float64), cfg)
    res = run(state)
    assert int(res.invariant_violations) == 0
