"""Cross-run history, trend regression flagging, SLO burn, stale fallback.

The contracts this file holds: a synthetic 10-run history with one
injected 30% throughput drop raises EXACTLY one trend alert (the change
point) while +/-2% noise raises none; ``cli compare --baseline auto``
resolves a non-0.0 healthy baseline; a failed bench probe's fallback
carries the last healthy headline under ``stale_from_run`` and staleness
never chains; SLO burn rates price p99/qps windows against the error
budget; and the exporter/watch/schema layers speak the three new metric
kinds (``device_profile`` / ``trend_report`` / ``slo_burn``).
"""
import json
import os
import pathlib
import sys
import time

import pytest

from fks_tpu import cli
from fks_tpu.obs.history import (
    RunHistory, SLOConfig, record_slo_burn, resolve_auto_baseline, slo_burn,
)

REPO = pathlib.Path(__file__).parent.parent
GOLDEN = str(pathlib.Path(__file__).parent / "fixtures" / "golden_run")

CLEAN = [100.0, 101.5, 99.2, 100.8, 98.9, 101.1, 99.7, 100.4, 99.9, 100.6]
REGRESSED = CLEAN[:7] + [70.0, 69.5, 70.3]


def _write_history(root, values, start=None):
    """Bench headline files with 1h-spaced mtimes (newest = last)."""
    root = pathlib.Path(root)
    root.mkdir(parents=True, exist_ok=True)
    start = time.time() - 3600 * len(values) if start is None else start
    paths = []
    for i, v in enumerate(values):
        p = root / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps(
            {"metric": "evals/s", "value": v, "unit": "evals/s",
             "vs_baseline": round(v / 40.0, 3)}) + "\n")
        ts = start + i * 3600
        os.utime(p, (ts, ts))
        paths.append(str(p))
    return paths


# ------------------------------------------------------------------ trends


def test_trends_flag_injected_regression_exactly_once(tmp_path):
    _write_history(tmp_path, REGRESSED)
    reports = RunHistory(str(tmp_path)).trends(["evals_per_sec"])
    assert len(reports) == 1
    rep = reports[0]
    assert rep["metric"] == "evals_per_sec" and rep["runs"] == 10
    # the 70.0/69.5/70.3 level shift collapses to ONE alert at the
    # change point, not one per post-shift run
    assert len(rep["alerts"]) == 1
    alert = rep["alerts"][0]
    assert alert["run"] == "BENCH_r07.json"
    assert alert["direction"] == "drop" and alert["z"] < -3.5


def test_trends_quiet_on_noise(tmp_path):
    _write_history(tmp_path, CLEAN)
    reports = RunHistory(str(tmp_path)).trends(["evals_per_sec"])
    assert reports[0]["alerts"] == []


def test_trends_direction_for_lower_is_better(tmp_path):
    # compile_seconds regresses UPWARD; a drop must not alert
    root = tmp_path / "r"
    root.mkdir()
    vals = [10.0, 10.2, 9.9, 10.1, 10.0, 10.1, 9.8, 30.0, 29.5, 30.2]
    for i, v in enumerate(vals):
        p = root / f"BENCH_r{i:02d}.json"
        p.write_text(json.dumps({"value": 100.0, "unit": "evals/s",
                                 "compile_seconds": v}) + "\n")
        ts = time.time() - 3600 * (len(vals) - i)
        os.utime(p, (ts, ts))
    rep = RunHistory(str(root)).trends(["compile_seconds"])[0]
    assert len(rep["alerts"]) == 1
    assert rep["alerts"][0]["direction"] == "rise"


def test_write_index_is_tailable_jsonl(tmp_path):
    _write_history(tmp_path, CLEAN[:4])
    hist = RunHistory(str(tmp_path))
    path = hist.write_index()
    lines = [json.loads(ln) for ln in
             pathlib.Path(path).read_text().splitlines()]
    assert len(lines) == 4
    assert all(e["metrics"]["evals_per_sec"] > 0 for e in lines)
    # a rescan must not index the index file itself
    assert len(RunHistory(str(tmp_path)).scan()) == 4


# --------------------------------------------------- baselines & staleness


def test_best_healthy_and_auto_baseline(tmp_path):
    paths = _write_history(tmp_path, [95.0, 101.5, 99.0])
    # an unmeasured (0.0) newest run must never win
    bad = tmp_path / "BENCH_r99.json"
    bad.write_text(json.dumps({"value": 0.0, "unit": "evals/s",
                               "error": "probe failed"}) + "\n")
    hist = RunHistory(str(tmp_path))
    best = hist.best_healthy("evals_per_sec")
    assert best["path"] == paths[1]
    assert resolve_auto_baseline(str(tmp_path)) == paths[1]
    assert resolve_auto_baseline(str(tmp_path / "nothing_here")) is None


def test_stale_headline_never_chains(tmp_path):
    paths = _write_history(tmp_path, [95.0, 101.5])
    donor = RunHistory(str(tmp_path)).last_healthy_headline()
    assert donor["value"] == 101.5 and donor["path"] == paths[1]
    # a NEWER stale carry-forward is indexed but unhealthy: the next
    # fallback must reach past it to the measured 101.5
    stale = tmp_path / "BENCH_r50.json"
    stale.write_text(json.dumps(
        {"value": 101.5, "unit": "evals/s", "error": "probe failed",
         "stale_from_run": {"run": "BENCH_r01.json"}}) + "\n")
    hist = RunHistory(str(tmp_path))
    hist.scan()
    by_run = {e["run"]: e for e in hist.entries}
    assert by_run["BENCH_r50.json"]["stale"]
    assert not by_run["BENCH_r50.json"]["healthy"]
    assert hist.last_healthy_headline()["path"] == paths[1]
    assert resolve_auto_baseline(str(tmp_path)) == paths[1]


def test_bench_fallback_carries_stale_headline(tmp_path, monkeypatch):
    _write_history(tmp_path, [95.0, 101.5])
    monkeypatch.setenv("FKS_BENCH_RESULTS_DIR", str(tmp_path))
    sys.path.insert(0, str(REPO))
    try:
        import bench
    finally:
        sys.path.pop(0)
    out = json.loads(bench._fallback_json("tunnel wedged"))
    assert out["value"] == 101.5
    assert out["vs_baseline"] == pytest.approx(101.5 / 40.0, abs=1e-3)
    assert out["stale_from_run"]["run"] == "BENCH_r01.json"
    assert out["error"] == "tunnel wedged"
    assert "NOT a live measurement" in out["note"]
    # with no healthy history the headline stays 0.0
    empty = tmp_path / "empty"
    empty.mkdir()
    monkeypatch.setenv("FKS_BENCH_RESULTS_DIR", str(empty))
    out0 = json.loads(bench._fallback_json("still wedged"))
    assert out0["value"] == 0.0 and "stale_from_run" not in out0


def test_compare_refuses_stale_candidate_allows_stale_baseline(tmp_path):
    from fks_tpu.obs.compare import extract_metrics

    p = tmp_path / "stale.json"
    p.write_text(json.dumps(
        {"value": 101.5, "unit": "evals/s",
         "stale_from_run": {"run": "BENCH_r01.json"}}) + "\n")
    assert "evals_per_sec" not in extract_metrics(str(p))
    assert extract_metrics(str(p), allow_stale=True)[
        "evals_per_sec"] == 101.5


def test_cli_compare_auto_baseline(tmp_path, capsys):
    _write_history(tmp_path, [95.0, 101.5, 99.0])
    cand = tmp_path / "candidate.json"
    cand.write_text(json.dumps({"value": 60.0, "unit": "evals/s"}) + "\n")
    rc = cli.main(["compare", "auto", str(cand),
                   "--history-root", str(tmp_path)])
    err = capsys.readouterr().err
    # auto resolved the non-0.0 best healthy run, and the 41% drop
    # against it is a regression
    assert "BENCH_r01.json" in err
    assert rc == 1
    # no history -> unresolvable, not silently green
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli.main(["compare", "auto", str(cand),
                     "--history-root", str(empty)]) == 2


def test_cli_trends_exit_codes(tmp_path, capsys):
    regressed = tmp_path / "reg"
    _write_history(regressed, REGRESSED)
    clean = tmp_path / "clean"
    _write_history(clean, CLEAN)
    assert cli.main(["trends", str(tmp_path / "missing")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli.main(["trends", str(empty)]) == 2
    capsys.readouterr()
    assert cli.main(["trends", str(clean), "--fail-on-alert"]) == 0
    assert "ALERT" not in capsys.readouterr().out
    rc = cli.main(["trends", str(regressed), "--fail-on-alert",
                   "--write-index"])
    out = capsys.readouterr().out
    assert rc == 1
    assert out.count("ALERT") == 1 and "BENCH_r07.json" in out
    assert (regressed / "history.jsonl").exists()
    # without --fail-on-alert the same alerts render but exit 0
    assert cli.main(["trends", str(regressed)]) == 0


# ---------------------------------------------------------------- SLO burn


def test_slo_burn_math():
    slo = SLOConfig(p99_ms=50.0, qps=100.0, error_budget=0.01)
    assert slo.enabled and not SLOConfig().enabled
    lat = [10.0] * 95 + [60.0] * 5
    recs = {r["slo"]: r for r in slo_burn(slo, lat, elapsed_s=2.0)}
    # 5% of requests over the 50ms target / 1% budget = 5x burn
    assert recs["p99_ms"]["burn_rate"] == pytest.approx(5.0)
    assert recs["p99_ms"]["target"] == 50.0
    assert recs["p99_ms"]["observed"] >= 50.0
    # 100 requests in 2s = 50 qps observed vs 100 target: 50% shortfall
    assert recs["qps"]["observed"] == pytest.approx(50.0)
    assert recs["qps"]["burn_rate"] == pytest.approx(50.0)
    # within budget -> burn below 1
    calm = slo_burn(SLOConfig(p99_ms=50.0), [10.0] * 200, 1.0)
    assert calm[0]["burn_rate"] == 0.0
    assert slo_burn(SLOConfig(), lat, 1.0) == []


def test_record_slo_burn_emits_metrics():
    class Rec:
        def __init__(self):
            self.rows = []

        def metric(self, kind, *dicts, **fields):
            row = {"kind": kind}
            for d in dicts:
                row.update(d)
            row.update(fields)
            self.rows.append(row)

    rec = Rec()
    out = record_slo_burn(SLOConfig(p99_ms=5.0), [1.0, 9.0], 1.0,
                          recorder=rec)
    assert len(out) == 1 and len(rec.rows) == 1
    row = rec.rows[0]
    assert row["kind"] == "slo_burn"
    for key in ("slo", "target", "observed", "burn_rate"):
        assert key in row


def test_serve_service_summary_prices_slo(micro_workload):
    from fks_tpu.serve.artifact import ChampionSpec, ServeEngine, \
        ShapeEnvelope
    from fks_tpu.serve.service import ServeService

    code = ('def priority_function(pod, node):\n'
            '    return 1000\n')
    eng = ServeEngine(ChampionSpec(code=code), micro_workload,
                      envelope=ShapeEnvelope(max_pods=8, max_batch=2,
                                             min_pod_bucket=8),
                      engine="exact")
    svc = ServeService(eng, slo=SLOConfig(p99_ms=0.001), max_wait_s=0.0)
    futs = [svc.submit({"pods": [{"cpu_milli": 100, "memory_mib": 100,
                                  "creation_time": 0, "duration_time": 5}]})
            for _ in range(3)]
    for f in futs:
        f.result(timeout=60.0)
    svc.close()
    out = svc.summary(record=False)
    assert out["requests"] == 3
    # a 1us p99 target is unmeetable: the budget must be burning
    slo_recs = {r["slo"]: r for r in out["slo"]}
    assert slo_recs["p99_ms"]["burn_rate"] > 1.0


# ------------------------------------------------- exporter / watch / schema


def _mini_run_dir(tmp_path, metrics):
    d = tmp_path / "run"
    d.mkdir()
    (d / "meta.json").write_text(json.dumps(
        {"run_id": "t1", "status": "ok", "started_ts": 1.0}))
    with open(d / "metrics.jsonl", "w") as f:
        for i, m in enumerate(metrics):
            f.write(json.dumps({"ts": 1.0 + i, **m}) + "\n")
    return str(d)


def test_openmetrics_profile_and_slo_gauges(tmp_path):
    from fks_tpu.obs.exporter import to_openmetrics

    d = _mini_run_dir(tmp_path, [
        {"kind": "device_profile", "scope": "evolve", "stage": "device-eval",
         "depth": 0, "wall_seconds": 2.0, "compile_seconds": 0.5,
         "compute_seconds": 1.5, "compile_count": 1,
         "utilization_pct": 71.2},
        {"kind": "device_profile", "stage": "__total__", "scope": "evolve",
         "wall_seconds": 2.0, "measured_wall_seconds": 2.1,
         "attributed_fraction": 0.952, "idle_fraction": 0.048,
         "compile_seconds": 0.5, "segments": 0},
        {"kind": "slo_burn", "slo": "p99_ms", "target": 50.0,
         "observed": 80.0, "over_fraction": 0.05, "burn_rate": 5.0,
         "requests": 100},
    ])
    text = to_openmetrics(d)
    assert ('fks_profile_attributed_fraction'
            '{run_id="t1",scope="evolve"} 0.952') in text
    assert 'stage="device-eval"' in text
    assert "fks_profile_stage_wall_seconds" in text
    assert 'fks_slo_burn_rate{run_id="t1",slo="p99_ms"} 5' in text
    assert "fks_slo_target" in text and "fks_slo_observed" in text


def test_watch_prints_slo_alert(tmp_path, capsys):
    from fks_tpu.obs.exporter import watch

    d = _mini_run_dir(tmp_path, [
        {"kind": "slo_burn", "slo": "p99_ms", "target": 50.0,
         "observed": 80.0, "burn_rate": 5.0},
        {"kind": "slo_burn", "slo": "qps", "target": 10.0,
         "observed": 12.0, "burn_rate": 0.0},
    ])
    watch(d, once=True)
    out = capsys.readouterr().out
    assert "SLO ALERT slo p99_ms: burn 5.00x" in out
    # an in-budget objective reports without the alert prefix
    assert "SLO ALERT slo qps" not in out


def test_schema_checker_knows_new_kinds(tmp_path):
    import shutil

    sys.path.insert(0, str(REPO / "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    for kind in ("device_profile", "trend_report", "slo_burn"):
        assert kind in cjs.METRIC_KIND_REQUIRED
    # the refreshed golden fixture carries all three new kinds
    golden = [json.loads(ln) for ln in
              (pathlib.Path(GOLDEN) / "metrics.jsonl").read_text()
              .splitlines()]
    kinds = {m["kind"] for m in golden}
    assert {"device_profile", "trend_report", "slo_burn"} <= kinds
    assert cjs.main(["--run-dir", GOLDEN]) == 0
    # a field-less record of a known kind still fails the run-dir check
    bad = tmp_path / "run"
    shutil.copytree(GOLDEN, bad)
    with open(bad / "metrics.jsonl", "a") as f:
        f.write(json.dumps({"ts": 2e9, "kind": "slo_burn",
                            "slo": "p99_ms"}) + "\n")
    assert cjs.main(["--run-dir", str(bad)]) == 1


def test_report_renders_attribution_and_slo(capsys):
    assert cli.main(["report", GOLDEN]) == 0
    out = capsys.readouterr().out
    assert "device-time attribution" in out
    assert "device-eval" in out
    assert "attributed" in out
    assert "slo" in out.lower()
