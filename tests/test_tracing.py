"""Decision-trace instrument + first-divergence localization.

The contract under test (sim/types.TraceBuffer + obs/tracing docstrings):
``decision_trace=False`` compiles the IDENTICAL program (the trailing
``trace=None`` state field has zero pytree leaves); ``decision_trace=True``
logs one row per processed event inside the jitted step, per-lane under
vmap and the 8-virtual-device shard_map mesh; ``obs.tracing`` aligns two
engines' logs and names the first divergent step; the fused kernel
rejects the instrument with a pointer at the replay path.
"""
import functools
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu import cli, obs
from fks_tpu.models import parametric, zoo
from fks_tpu.obs import tracing
from fks_tpu.sim import engine, flat, fused
from fks_tpu.sim.engine import SimConfig
from fks_tpu.sim.types import TRACE_KIND_NAMES, TraceBuffer

CLEAN = parametric.seed_weights("first_fit")


def _node_pref_policy(node_idx: int):
    """(param, pod, nodes) policy that always prefers ``node_idx`` among
    the feasible nodes — two different preferences are GUARANTEED to
    diverge at the very first CREATE, which pins down the first-divergence
    localization deterministically."""
    def pol(_p, pod, nodes):
        mask = zoo.feasible_mask(pod, nodes)
        pref = jnp.where(jnp.arange(mask.shape[0]) == node_idx, 2000, 1000)
        return jnp.where(mask, pref, 0)
    return pol


def _lane(trace, i) -> TraceBuffer:
    """Lane ``i`` of a batched TraceBuffer."""
    return TraceBuffer(data=trace.data[i], scores=trace.scores[i],
                       count=trace.count[i])


def _tools(name):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


# ------------------------------------------------- disabled-path identity

@pytest.mark.parametrize("mod", [engine, flat], ids=["exact", "flat"])
def test_trace_off_compiles_identical_program(micro_workload, mod):
    """decision_trace=False must be invisible to the compiler: same jaxpr
    as the seed default, and no trace on the result."""
    off = SimConfig(decision_trace=False)
    default = SimConfig()
    j_off = jax.make_jaxpr(mod.make_param_run_fn(micro_workload,
                                                 parametric.score, off))(
        CLEAN, mod.initial_state(micro_workload, off))
    j_def = jax.make_jaxpr(mod.make_param_run_fn(micro_workload,
                                                 parametric.score, default))(
        CLEAN, mod.initial_state(micro_workload, default))
    assert str(j_off) == str(j_def)

    on = SimConfig(decision_trace=True)
    j_on = jax.make_jaxpr(mod.make_param_run_fn(micro_workload,
                                                parametric.score, on))(
        CLEAN, mod.initial_state(micro_workload, on))
    assert str(j_on) != str(j_off)

    res = mod.simulate(micro_workload, zoo.ZOO["first_fit"](), off)
    assert res.trace is None


# ------------------------------------------------------- trace invariants

@pytest.mark.parametrize("mod", [engine, flat], ids=["exact", "flat"])
def test_trace_rows_match_processed_events(micro_workload, mod):
    cfg = SimConfig(decision_trace=True)
    res = mod.simulate(micro_workload, zoo.ZOO["first_fit"](), cfg)
    rows = tracing.extract_trace(res)
    assert len(rows) == int(np.asarray(res.events_processed))
    assert len(rows) == int(np.asarray(res.trace.count)) > 0
    for r in rows:
        assert r["kind"] in TRACE_KIND_NAMES
        assert r["pending"] >= 0
        assert r["free_cpu"] >= 0 and r["free_mem"] >= 0
        if r["kind"] == "DELETE":
            assert r["score"] == 0.0 and r["margin"] == 0.0
    assert rows[0]["kind"] == "CREATE"
    # the instrument must not perturb the simulation itself
    off = mod.simulate(micro_workload, zoo.ZOO["first_fit"](), SimConfig())
    assert float(res.policy_score) == float(off.policy_score)
    assert int(res.scheduled_pods) == int(off.scheduled_pods)


@pytest.mark.parametrize("name", ["first_fit", "best_fit"])
def test_exact_and_flat_traces_align(micro_workload, name):
    """Same policy through both engines: the decision logs must agree
    step for step (the flat engine's pod column carries the original
    input-order id precisely so this alignment needs no un-permuting)."""
    cfg = SimConfig(decision_trace=True)
    a = tracing.extract_trace(
        engine.simulate(micro_workload, zoo.ZOO[name](), cfg))
    b = tracing.extract_trace(
        flat.simulate(micro_workload, zoo.ZOO[name](), cfg))
    assert tracing.align_traces(a, b) is None


def test_trace_buffer_saturates_at_trace_len(micro_workload):
    """A trace shorter than the event count keeps the first rows and the
    count stops at capacity instead of wrapping or going out of bounds."""
    full = engine.simulate(micro_workload, zoo.ZOO["first_fit"](),
                           SimConfig(decision_trace=True))
    short = engine.simulate(micro_workload, zoo.ZOO["first_fit"](),
                            SimConfig(decision_trace=True, trace_len=3))
    assert int(short.trace.count) == 3
    np.testing.assert_array_equal(np.asarray(short.trace.data),
                                  np.asarray(full.trace.data)[:3])


# ------------------------------------------------- vmap / mesh isolation

def test_vmap_per_lane_trace_isolation(micro_workload):
    cfg = SimConfig(decision_trace=True)
    run = jax.jit(engine.make_population_run_fn(micro_workload,
                                                parametric.score, cfg))
    params = jnp.stack([parametric.seed_weights("first_fit"),
                        parametric.seed_weights("best_fit")])
    res = run(params, engine.initial_state(micro_workload, cfg))
    single = jax.jit(engine.make_param_run_fn(micro_workload,
                                              parametric.score, cfg))
    for i in range(2):
        sres = single(params[i], engine.initial_state(micro_workload, cfg))
        lane = _lane(res.trace, i)
        assert int(lane.count) == int(sres.trace.count)
        np.testing.assert_array_equal(np.asarray(lane.data),
                                      np.asarray(sres.trace.data))
        np.testing.assert_array_equal(np.asarray(lane.scores),
                                      np.asarray(sres.trace.scores))


def test_shard_map_mesh_per_lane_traces(micro_workload):
    """8-virtual-device mesh: each shard fills its own lane's trace, and
    the gathered result is bit-identical to the vmap run — a single
    ``P(POP_AXIS)`` out_spec covers the whole TraceBuffer subtree as a
    pytree prefix."""
    from jax.sharding import PartitionSpec as P

    from fks_tpu.parallel.mesh import POP_AXIS, population_mesh
    from fks_tpu.utils.compat import shard_map

    mesh = population_mesh()
    assert mesh.shape[POP_AXIS] == 8  # conftest forces 8 virtual devices
    cfg = SimConfig(decision_trace=True)
    run = engine.make_population_run_fn(micro_workload, parametric.score,
                                        cfg)
    state0 = engine.initial_state(micro_workload, cfg)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(POP_AXIS),),
                       out_specs=(P(POP_AXIS), P(POP_AXIS)), check_vma=False)
    def shard_run(params_shard):
        res = run(params_shard, state0)
        return res.policy_score, res.trace

    params = parametric.init_population(jax.random.PRNGKey(0), 8, noise=0.1)
    scores, trace = jax.jit(shard_run)(params)
    ref = jax.jit(run)(params, state0)
    np.testing.assert_array_equal(np.asarray(scores),
                                  np.asarray(ref.policy_score))
    np.testing.assert_array_equal(np.asarray(trace.count),
                                  np.asarray(ref.trace.count))
    np.testing.assert_array_equal(np.asarray(trace.data),
                                  np.asarray(ref.trace.data))
    assert int(np.asarray(trace.count).min()) > 0


def test_sharded_eval_returns_traces_when_enabled(micro_workload):
    from fks_tpu.parallel.mesh import (
        make_sharded_eval, pad_population, population_mesh,
    )

    mesh = population_mesh()
    cfg = SimConfig(decision_trace=True)
    ev = make_sharded_eval(micro_workload, mesh, cfg=cfg, elite_k=2)
    params = parametric.init_population(jax.random.PRNGKey(1), 8, noise=0.1)
    padded, real = pad_population(np.asarray(params), mesh)
    out = ev(padded, real)
    assert len(out) == 4  # scores, elite idx, elite scores, traces
    trace = out[3]
    assert np.asarray(trace.data).shape[0] == padded.shape[0]
    rows = tracing.extract_trace(_lane(trace, 0))
    assert rows and rows[0]["kind"] == "CREATE"


# -------------------------------------------- alignment / diff host logic

def _row(**kw):
    base = dict(step=0, kind="CREATE", pod=0, node=1, pending=0,
                free_cpu=10, free_mem=10, free_gpu=0, free_gpu_milli=0,
                score=1.0, margin=0.5)
    base.update(kw)
    return base


def test_align_traces_units():
    a = [_row(), _row(step=1, pod=1)]
    assert tracing.align_traces(a, [dict(r) for r in a]) is None
    # integer field mismatch names the field and both rows
    div = tracing.align_traces(a, [_row(node=0), _row(step=1, pod=1)])
    assert div == {"step": 0, "field": "node", "a": a[0],
                   "b": _row(node=0)}
    # scores compare within tolerance
    assert tracing.align_traces(a, [_row(score=1.0 + 1e-7),
                                    _row(step=1, pod=1)]) is None
    div = tracing.align_traces(a, [_row(score=2.0), _row(step=1, pod=1)])
    assert div["field"] == "score" and div["step"] == 0
    # strict prefix: diverges at the first missing row
    div = tracing.align_traces(a, a[:1])
    assert div == {"step": 1, "field": "length", "a": a[1], "b": None}


def test_extract_trace_rejects_none_and_batched(micro_workload):
    with pytest.raises(ValueError, match="no decision trace"):
        tracing.extract_trace(None)
    cfg = SimConfig(decision_trace=True)
    run = jax.jit(engine.make_population_run_fn(micro_workload,
                                                parametric.score, cfg))
    res = run(jnp.stack([CLEAN, CLEAN]),
              engine.initial_state(micro_workload, cfg))
    with pytest.raises(ValueError, match="batched"):
        tracing.extract_trace(res)


def test_trace_diff_localizes_first_divergence(micro_workload, tmp_path):
    specs = [("prefer0", "exact", _node_pref_policy(0), None),
             ("prefer1", "exact", _node_pref_policy(1), None)]
    d = tmp_path / "run"
    with obs.FlightRecorder(str(d)) as rec:
        record = tracing.trace_diff(micro_workload, specs, recorder=rec,
                                    label="unit")
    assert record["divergent"]
    div = record["first_divergence"]
    assert div["step"] == 0 and div["field"] == "node"
    assert div["a"]["node"] == 0 and div["b"]["node"] == 1
    text = tracing.format_diff(record)
    assert "FIRST DIVERGENCE at step 0" in text
    events = [json.loads(l)
              for l in (d / "events.jsonl").read_text().splitlines()]
    kinds = [e["kind"] for e in events]
    assert kinds.count("decision_trace") == 2
    assert kinds.count("trace_diff") == 1
    # the run dir (embedded trace rows included) passes the schema checker
    cjs = _tools("check_jsonl_schema")
    assert cjs.check_run_dir(str(d))["events.jsonl"] == 3


def test_trace_diff_self_is_clean(micro_workload):
    pp, params = tracing.policy_params(micro_workload,
                                       policy_name="best_fit")
    record = tracing.trace_diff(
        micro_workload,
        [("exact", "exact", pp, params), ("flat", "flat", pp, params)],
        recorder=obs.NULL)
    assert not record["divergent"]
    assert record["first_divergence"] is None
    assert "no divergence" in tracing.format_diff(record)
    steps = record["steps"]
    assert steps["exact"] == steps["flat"] > 0


def test_policy_params_unknown_name(micro_workload):
    with pytest.raises(ValueError, match="unknown policy"):
        tracing.policy_params(micro_workload, policy_name="nope")


# --------------------------------------------------- engine-gate behavior

def test_fused_plan_rejects_decision_trace(micro_workload):
    with pytest.raises(ValueError, match="decision trace"):
        fused._build_plan(micro_workload, SimConfig(decision_trace=True))


def test_replay_rejects_fused(micro_workload):
    with pytest.raises(ValueError):
        tracing.replay(micro_workload, "fused", parametric.score, CLEAN)


# --------------------------------------------------------- CLI + schema

@pytest.fixture
def micro_cli(monkeypatch, micro_workload):
    monkeypatch.setattr(cli, "_parse_workload",
                        lambda args: ("micro", micro_workload))
    return micro_workload


def test_cli_trace_diff_no_divergence_exit_zero(micro_cli, tmp_path,
                                                capsys):
    d = tmp_path / "td"
    rc = cli.main(["trace-diff", "--cpu", "--engines", "exact,flat",
                   "--policy", "first_fit", "--run-dir", str(d)])
    assert rc == 0
    assert "no divergence" in capsys.readouterr().out
    cjs = _tools("check_jsonl_schema")
    counts = cjs.check_run_dir(str(d))
    assert counts["events.jsonl"] == 3


def test_cli_trace_diff_divergence_exit_one(micro_cli, monkeypatch,
                                            capsys):
    fake = {"engines": ["exact", "flat"], "label": "first_fit",
            "steps": {"exact": 2, "flat": 2},
            "scores": {"exact": 0.5, "flat": 0.4}, "score_tol": 1e-5,
            "divergent": True,
            "first_divergence": {"step": 1, "field": "node",
                                 "a": _row(step=1), "b": _row(step=1,
                                                              node=0)}}
    monkeypatch.setattr(tracing, "trace_diff", lambda *a, **k: fake)
    rc = cli.main(["trace-diff", "--cpu", "--engines", "exact,flat",
                   "--policy", "first_fit"])
    assert rc == 1
    assert "FIRST DIVERGENCE" in capsys.readouterr().out


def test_cli_trace_diff_usage_errors(micro_cli):
    assert cli.main(["trace-diff", "--cpu", "--engines", "exact"]) == 2
    assert cli.main(["trace-diff", "--cpu",
                     "--engines", "exact,fused"]) == 2
    assert cli.main(["trace-diff", "--cpu", "--engines", "exact,flat",
                     "--policy", "nope"]) == 2
    assert cli.main(["trace-diff", "--cpu", "--engines", "exact,flat",
                     "--code", "/nonexistent/path.py"]) == 2


def test_schema_checker_embedded_trace_kinds(tmp_path):
    cjs = _tools("check_jsonl_schema")
    good = [{"ts": 1, "kind": "decision_trace", "engine": "exact",
             "events": [{"kind": "CREATE"}, {"kind": "RETRY"}]},
            {"ts": 2, "kind": "trace_diff", "engines": ["a", "b"],
             "divergent": True,
             "first_divergence": {"step": 0, "field": "node",
                                  "a": {"kind": "DELETE"}, "b": None}}]
    cjs.check_kinds("x", good, cjs.EVENT_KIND_REQUIRED)  # no raise
    bad = [{"ts": 1, "kind": "decision_trace", "engine": "exact",
            "events": [{"kind": "SPAWN"}]}]
    with pytest.raises(cjs.SchemaError, match="unknown.*SPAWN"):
        cjs.check_kinds("x", bad, cjs.EVENT_KIND_REQUIRED)
    missing = [{"ts": 1, "kind": "trace_diff", "engines": ["a", "b"]}]
    with pytest.raises(cjs.SchemaError, match="missing"):
        cjs.check_kinds("x", missing, cjs.EVENT_KIND_REQUIRED)


def test_report_summarizes_trace_diffs():
    from fks_tpu.obs.report import _trace_diff_lines
    events = [
        {"kind": "trace_diff", "engines": ["exact", "flat"],
         "divergent": True, "first_divergence": {"step": 7}},
        {"kind": "trace_diff", "engines": ["exact", "flat"],
         "divergent": True, "first_divergence": {"step": 3}},
        {"kind": "trace_diff", "engines": ["exact", "exact#1"],
         "divergent": False, "first_divergence": None},
    ]
    lines = _trace_diff_lines(events)
    assert lines[0] == "trace diffs: 3 recorded, 2 divergent"
    assert any("exact vs flat: first divergent step 3" in l for l in lines)
    assert _trace_diff_lines([]) == []
