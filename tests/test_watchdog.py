"""Numerics watchdog: in-graph guard semantics (per-lane isolation under
vmap and the 8-virtual-device shard_map mesh, disabled-path bit-identity),
host-side event reporting, and the online parity sentinel.

The guard contract under test (sim/guards.py docstring): watchdog=False
compiles the identical program; watchdog=True is bit-identical whenever no
violation fires; a violating lane is masked to "refuse placement" and
flagged WITHOUT poisoning sibling lanes.
"""
import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu import obs
from fks_tpu.models import parametric, zoo
from fks_tpu.sim import engine, flat
from fks_tpu.sim.engine import SimConfig
from fks_tpu.sim.guards import (
    FLAG_INF, FLAG_NAN, FLAG_RANGE, describe_flags, fitness_flags,
    sanitize_scores, score_flags,
)

CLEAN = parametric.seed_weights("first_fit")


def _float_first_fit(pod, nodes):
    """Float-scored first-fit. The score guard is a static no-op for the
    integer score dtypes the stock policies emit (the VM masks non-finite
    values before its own int cast), so guard tests ride the supported
    float-policy surface."""
    return jnp.where(zoo.feasible_mask(pod, nodes), 1000.0, 0.0)


def _poison_policy(p, pod, nodes):
    """Param policy: p=0 -> clean float first-fit scores, p=1 -> all-NaN,
    p=2 -> all-Inf. The scalar param lets one vmap/shard_map lane go bad
    while its siblings stay clean."""
    base = _float_first_fit(pod, nodes)
    bad = jnp.where(p >= 1.5, jnp.inf, jnp.nan).astype(base.dtype)
    return jnp.where(p >= 0.5, bad, base)


# ------------------------------------------------------------ guard units

def test_score_flags_classifies_nan_and_inf():
    nan_mask = int(score_flags(jnp.asarray([1.0, jnp.nan]), jnp.bool_(True)))
    inf_mask = int(score_flags(jnp.asarray([jnp.inf, 0.0]), jnp.bool_(True)))
    both = int(score_flags(jnp.asarray([jnp.nan, jnp.inf]), jnp.bool_(True)))
    assert nan_mask == FLAG_NAN
    assert inf_mask == FLAG_INF
    assert both == FLAG_NAN | FLAG_INF
    assert int(score_flags(jnp.asarray([0.5, 2.0]), jnp.bool_(True))) == 0


def test_score_flags_gated_and_integer_noop():
    # a discarded (gate=False) score must not flag
    assert int(score_flags(jnp.asarray([jnp.nan]), jnp.bool_(False))) == 0
    # integer dtypes cannot hold NaN/Inf: statically clean
    assert int(score_flags(jnp.asarray([1, 2], jnp.int32),
                           jnp.bool_(True))) == 0


def test_sanitize_scores_masks_to_refuse():
    out = np.asarray(sanitize_scores(jnp.asarray([1.5, jnp.nan, -jnp.inf])))
    np.testing.assert_array_equal(out, [1.5, 0.0, 0.0])
    # identity for finite inputs and integer dtypes
    np.testing.assert_array_equal(
        np.asarray(sanitize_scores(jnp.asarray([2.0, -3.0]))), [2.0, -3.0])
    ints = jnp.asarray([4, 5], jnp.int32)
    assert sanitize_scores(ints) is ints


def test_fitness_flags_range_check():
    assert int(fitness_flags(jnp.float32(0.5))) == 0
    assert int(fitness_flags(jnp.float32(jnp.nan))) == FLAG_NAN
    assert int(fitness_flags(jnp.float32(jnp.inf))) == FLAG_INF
    assert int(fitness_flags(jnp.float32(-0.1))) == FLAG_RANGE
    assert int(fitness_flags(jnp.float32(1.5))) == FLAG_RANGE


def test_describe_and_combine_flags():
    assert describe_flags(FLAG_NAN | FLAG_INF) == ["nan", "inf"]
    assert describe_flags(0) == []
    assert obs.combined_flags(np.asarray([[0, 1], [4, 0]])) == 5
    assert obs.combined_flags(np.asarray([], np.int32)) == 0
    assert obs.combined_flags(0) == 0


# ----------------------------------------------------- engine integration

@pytest.mark.parametrize("pol", [parametric.as_policy(CLEAN),
                                 _float_first_fit],
                         ids=["int-scores", "float-scores"])
@pytest.mark.parametrize("mod", [engine, flat], ids=["exact", "flat"])
def test_watchdog_enabled_clean_is_bit_identical(micro_workload, mod, pol):
    off = mod.simulate(micro_workload, pol, SimConfig(watchdog=False))
    on = mod.simulate(micro_workload, pol, SimConfig(watchdog=True))
    assert float(on.policy_score) == float(off.policy_score)
    np.testing.assert_array_equal(np.asarray(on.assigned_node),
                                  np.asarray(off.assigned_node))
    assert int(on.scheduled_pods) == int(off.scheduled_pods)
    assert obs.combined_flags(on.numeric_flags) == 0
    assert obs.combined_flags(off.numeric_flags) == 0


@pytest.mark.parametrize("mod", [engine, flat], ids=["exact", "flat"])
def test_nan_policy_flagged_and_fitness_stays_finite(micro_workload, mod):
    cfg = SimConfig(watchdog=True)
    run = jax.jit(mod.make_param_run_fn(micro_workload, _poison_policy, cfg))
    res = run(jnp.float64(1.0), mod.initial_state(micro_workload, cfg))
    assert obs.combined_flags(res.numeric_flags) & FLAG_NAN
    assert np.isfinite(float(res.policy_score))
    inf_res = run(jnp.float64(2.0), mod.initial_state(micro_workload, cfg))
    assert obs.combined_flags(inf_res.numeric_flags) & FLAG_INF
    assert np.isfinite(float(inf_res.policy_score))


def test_watchdog_off_does_not_flag(micro_workload):
    cfg = SimConfig(watchdog=False)
    run = jax.jit(engine.make_param_run_fn(micro_workload, _poison_policy,
                                           cfg))
    res = run(jnp.float64(1.0), engine.initial_state(micro_workload, cfg))
    assert obs.combined_flags(res.numeric_flags) == 0


def test_vmap_population_lane_isolation(micro_workload):
    cfg = SimConfig(watchdog=True)
    run = jax.jit(engine.make_population_run_fn(micro_workload,
                                                _poison_policy, cfg))
    params = jnp.asarray([0.0, 1.0, 0.0, 2.0])
    res = run(params, engine.initial_state(micro_workload, cfg))
    flags = np.asarray(res.numeric_flags)
    assert flags[1] & FLAG_NAN
    assert flags[3] & FLAG_INF
    assert flags[0] == 0 and flags[2] == 0
    # clean lanes are bit-identical to a watchdog-off single-policy run
    ref = engine.simulate(micro_workload, _float_first_fit,
                          SimConfig(watchdog=False))
    scores = np.asarray(res.policy_score)
    assert scores[0] == float(ref.policy_score)
    assert scores[2] == float(ref.policy_score)


def test_shard_map_mesh_lane_isolation(micro_workload):
    from jax.sharding import PartitionSpec as P

    from fks_tpu.parallel.mesh import POP_AXIS, population_mesh
    from fks_tpu.utils.compat import shard_map

    mesh = population_mesh()
    assert mesh.shape[POP_AXIS] == 8  # conftest forces 8 virtual devices
    cfg = SimConfig(watchdog=True)
    run = engine.make_population_run_fn(micro_workload, _poison_policy, cfg)
    state0 = engine.initial_state(micro_workload, cfg)

    @functools.partial(shard_map, mesh=mesh, in_specs=(P(POP_AXIS),),
                       out_specs=(P(POP_AXIS), P(POP_AXIS)), check_vma=False)
    def shard_run(params_shard):
        res = run(params_shard, state0)
        return res.numeric_flags, res.policy_score

    params = jnp.zeros(8).at[3].set(1.0).at[6].set(2.0)
    flags, scores = jax.jit(shard_run)(params)
    flags, scores = np.asarray(flags), np.asarray(scores)
    assert flags[3] & FLAG_NAN
    assert flags[6] & FLAG_INF
    clean = [i for i in range(8) if i not in (3, 6)]
    assert all(flags[i] == 0 for i in clean)
    ref = engine.simulate(micro_workload, _float_first_fit,
                          SimConfig(watchdog=False))
    for i in clean:
        assert scores[i] == float(ref.policy_score)


# --------------------------------------------------------- host reporting

def test_check_result_emits_watchdog_event(tmp_path):
    class _Res:
        numeric_flags = np.asarray([0, FLAG_NAN | FLAG_INF])

    d = tmp_path / "run"
    with obs.FlightRecorder(str(d)) as rec:
        mask = obs.check_result(_Res(), recorder=rec, generation=4)
    assert mask == FLAG_NAN | FLAG_INF
    events = [json.loads(l) for l in (d / "events.jsonl").read_text()
              .splitlines()]
    wd = [e for e in events if e["kind"] == "watchdog"]
    assert len(wd) == 1
    assert wd[0]["flags"] == mask
    assert wd[0]["kinds"] == ["nan", "inf"]
    assert wd[0]["generation"] == 4


def test_check_result_clean_and_flagless_objects(tmp_path):
    class _Clean:
        numeric_flags = np.zeros(3, np.int32)

    d = tmp_path / "run"
    with obs.FlightRecorder(str(d)) as rec:
        assert obs.check_result(_Clean(), recorder=rec) == 0
        assert obs.check_result(object(), recorder=rec) == 0
    events = (d / "events.jsonl").read_text() \
        if (d / "events.jsonl").exists() else ""
    assert "watchdog" not in events


# --------------------------------------------------------- parity sentinel

class _StubRecord:
    def __init__(self, score, ok=True):
        self.score, self.ok = score, ok


class _StubReference:
    """Stands in for the lazily-built exact CodeEvaluator."""

    def __init__(self, scores):
        self.scores = scores

    def evaluate_one(self, code):
        v = self.scores[code]
        if v == "raise":
            raise RuntimeError("reference blew up")
        if v == "not-ok":
            return _StubRecord(0.0, ok=False)
        return _StubRecord(v)


def _load(d, name):
    p = d / name
    if not p.exists():
        return []
    return [json.loads(l) for l in p.read_text().splitlines()]


def test_parity_sentinel_zero_drift_no_alert(tmp_path):
    d = tmp_path / "run"
    with obs.FlightRecorder(str(d)) as rec:
        s = obs.ParitySentinel(object(), sample=2, tol=1e-5, recorder=rec)
        s._ref = _StubReference({"a": 0.5, "b": 0.25})
        stats = s.check(1, [("a", 0.5), ("b", 0.25)])
    assert stats == {"generation": 1, "checked": 2, "max_drift": 0.0,
                     "alerts": 0, "failed": 0}
    assert s.alerts == 0 and s.checked == 2 and s.max_drift == 0.0
    parity = [m for m in _load(d, "metrics.jsonl") if m["kind"] == "parity"]
    assert len(parity) == 1
    assert parity[0]["checked"] == 2 and parity[0]["tol"] == 1e-5
    assert not [e for e in _load(d, "events.jsonl") if e["kind"] == "alert"]


def test_parity_sentinel_alerts_on_drift(tmp_path):
    d = tmp_path / "run"
    with obs.FlightRecorder(str(d)) as rec:
        s = obs.ParitySentinel(object(), sample=2, tol=1e-5, recorder=rec)
        s._ref = _StubReference({"a": 0.5, "b": 0.26})  # b drifted by 0.01
        stats = s.check(3, [("a", 0.5), ("b", 0.25)])
    assert stats["alerts"] == 1 and s.alerts == 1
    assert stats["max_drift"] == pytest.approx(0.01)
    alerts = [e for e in _load(d, "events.jsonl") if e["kind"] == "alert"]
    assert len(alerts) == 1
    assert alerts[0]["source"] == "parity"
    assert alerts[0]["generation"] == 3
    assert alerts[0]["max_drift"] == pytest.approx(0.01)
    assert alerts[0]["tol"] == 1e-5


def test_parity_sentinel_sample_zero_is_noop():
    s = obs.ParitySentinel(object(), sample=0, recorder=obs.NULL)
    stats = s.check(1, [("a", 1.0)])
    assert stats == {"generation": 1, "checked": 0, "max_drift": 0.0,
                     "alerts": 0}
    assert s._ref is None  # reference evaluator never built


def test_parity_sentinel_survives_reference_failures(tmp_path):
    d = tmp_path / "run"
    with obs.FlightRecorder(str(d)) as rec:
        s = obs.ParitySentinel(object(), sample=3, tol=1e-5, recorder=rec)
        s._ref = _StubReference({"a": "raise", "b": "not-ok", "c": 0.75})
        stats = s.check(2, [("a", 0.1), ("b", 0.2), ("c", 0.75)])
    assert stats["failed"] == 2 and stats["checked"] == 1
    assert s.alerts == 0  # failures are counted, never alerted or raised


def test_parity_sentinel_exact_reference_round_trip(micro_workload):
    """End to end on the real evaluator: re-scoring a candidate against
    the score the same evaluator produced must show zero drift."""
    from fks_tpu.funsearch import template
    from fks_tpu.funsearch.backend import CodeEvaluator

    ev = CodeEvaluator(micro_workload, SimConfig(), engine="exact",
                       use_vm=False)
    code = dict(template.seed_policies())["first_fit"]
    base = ev.evaluate_one(code)
    assert base.ok
    s = obs.ParitySentinel(ev, sample=1, tol=1e-5, recorder=obs.NULL)
    s._ref = ev  # reuse the already-compiled evaluator as the reference
    stats = s.check(0, [(code, float(base.score))])
    assert stats["checked"] == 1
    assert stats["max_drift"] == 0.0
    assert s.alerts == 0
