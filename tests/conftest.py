"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference offers no
distributed-test pattern; this is the TPU-mesh stand-in per SURVEY.md §4) and
with x64 enabled so parity tests can evaluate policy arithmetic in float64,
matching the reference's Python-float semantics. Framework code pins its own
dtypes (int32/float32 by default) and accepts a dtype override.

Env must be set before the first jax import.
"""
import os

# Force CPU for tests even when the environment points at a TPU tunnel
# (JAX_PLATFORMS=axon in this image): tests model the mesh with 8 virtual
# CPU devices; only bench.py runs on the real chip.
#
# NOTE: in this image /root/.axon_site/sitecustomize.py imports jax at
# interpreter startup, so env vars are too late -- use jax.config.update
# (effective until the first backend initialization). XLA_FLAGS is read at
# backend creation, so setting it here still works.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import json  # noqa: E402
import pathlib  # noqa: E402

import pytest  # noqa: E402

FIXTURES = pathlib.Path(__file__).parent / "fixtures"


def pytest_collection_modifyitems(config, items):
    """Default to the fast tier by DESELECTING `slow` items — unless the
    user passed -m (their marker expression wins) or named a test file
    explicitly (running `pytest tests/test_differential.py`, an all-slow
    module, means "run it", not "collect 0 tests and exit green" — the
    footgun an addopts-level `-m "not slow"` default had)."""
    if config.option.markexpr:
        return
    named = {
        pathlib.Path(a.split("::")[0]).resolve()
        for a in config.args if a.split("::")[0].endswith(".py")
    }
    selected, deselected = [], []
    for item in items:
        if ("slow" in item.keywords
                and pathlib.Path(str(item.fspath)).resolve() not in named):
            deselected.append(item)
        else:
            selected.append(item)
    if deselected:
        config.hook.pytest_deselected(items=deselected)
        items[:] = selected


@pytest.fixture(scope="session")
def golden_default():
    with open(FIXTURES / "golden_default.json") as f:
        return json.load(f)


@pytest.fixture(scope="session")
def golden_micro():
    with open(FIXTURES / "golden_micro.json") as f:
        return json.load(f)


@pytest.fixture(scope="session")
def golden_alt():
    with open(FIXTURES / "golden_alt_traces.json") as f:
        return json.load(f)


@pytest.fixture(scope="session")
def default_workload():
    from fks_tpu.data import TraceParser
    return TraceParser().parse_workload()


def make_micro_workload():
    """Tiny 2-node x 6-pod cluster for fast-tier end-to-end tests (one
    GPU node, one CPU-only node, alternating GPU/CPU pods)."""
    from fks_tpu.data.build import make_workload

    nodes = [{"node_id": "n0", "cpu_milli": 4000, "memory_mib": 8000,
              "gpus": [1000, 1000]},
             {"node_id": "n1", "cpu_milli": 2000, "memory_mib": 4000,
              "gpus": []}]
    pods = [{"pod_id": f"p{i}", "cpu_milli": 500, "memory_mib": 500,
             "num_gpu": i % 2, "gpu_milli": 300 * (i % 2),
             "creation_time": i, "duration_time": 5} for i in range(6)]
    return make_workload(nodes, pods, pad_nodes_to=2, pad_gpus_to=2,
                         pad_pods_to=8)


@pytest.fixture(scope="session")
def micro_workload():
    return make_micro_workload()
