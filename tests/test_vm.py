"""The candidate VM: jaxpr->bytecode lowering + on-device interpretation
(fks_tpu.funsearch.vm). Contract: for every candidate it accepts, the VM's
scores EQUAL the directly-transpiled policy's scores (integer-exact), and
full-simulation fitness through the shared engine program equals the
per-candidate jit tier; candidates outside the vocabulary fall back."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.funsearch import backend, llm, template, transpiler, vm
from fks_tpu.sim.types import NodeView, PodView

N, G = 16, 8


def _rand_views(rng):
    pod = PodView(*(jnp.int32(x) for x in (
        rng.integers(0, 5000), rng.integers(0, 8000), rng.integers(0, 4),
        rng.integers(0, 1001), rng.integers(0, 100), rng.integers(0, 50))))
    tot = rng.integers(1, 10000, N).astype(np.int32)
    left = (tot * rng.random(N)).astype(np.int32)
    mt = rng.integers(1, 20000, N).astype(np.int32)
    ml = (mt * rng.random(N)).astype(np.int32)
    ng = rng.integers(0, G + 1, N).astype(np.int32)
    gmask = np.arange(G)[None, :] < ng[:, None]
    gmt = np.where(gmask, 1000, 0).astype(np.int32)
    gml = (gmt * rng.random((N, G))).astype(np.int32)
    gmem = np.where(gmask, 16384, 0).astype(np.int32)
    nodes = NodeView(*(jnp.asarray(a) for a in (
        left, tot, ml, mt, ng, ng, gml, gmt, gmem, gmask,
        np.ones(N, bool))))
    return pod, nodes


def _corpus():
    fake = llm.FakeLLM(seed=3, junk_rate=0.0)
    return (list(template.seed_policies().values())
            + [template.fill_template(fake.complete("x")) for _ in range(30)])


@pytest.mark.slow
def test_corpus_lowers_and_matches_exactly():
    """Every seed + FakeLLM candidate lowers to the VM, and interpreted
    scores equal the transpiled policy's on randomized views."""
    rng = np.random.default_rng(7)
    score = jax.jit(vm.score)
    lowered = 0
    for code in _corpus():
        policy = transpiler.transpile(code)
        prog = vm.compile_policy(code, N, G, capacity=512)  # must not raise
        lowered += 1
        for _ in range(4):
            pod, nodes = _rand_views(rng)
            want = np.asarray(policy(pod, nodes))
            got = np.asarray(score(prog, pod, nodes))
            np.testing.assert_array_equal(got, want)
    assert lowered == len(_corpus())


@pytest.mark.slow
def test_full_simulation_fitness_matches_jit_tier(default_workload):
    """Seed candidates through the shared VM engine program reproduce the
    reference fitness table exactly (first_fit 0.4292, best_fit 0.4465)."""
    from fks_tpu.sim.engine import SimConfig, initial_state, make_param_run_fn

    wl = default_workload
    n, g = wl.cluster.n_padded, wl.cluster.g_padded
    cfg = SimConfig(cond_policy=True)
    run = jax.jit(make_param_run_fn(wl, vm.score, cfg))
    s0 = initial_state(wl, cfg)
    want = {"first_fit": 0.4292, "best_fit": 0.4465}
    for name, code in template.seed_policies().items():
        prog = vm.compile_policy(code, n, g, capacity=512)
        res = run(prog, s0)
        assert abs(float(res.policy_score) - want[name]) < 1e-4, name
        assert int(res.scheduled_pods) == wl.num_pods


def test_unsupported_construct_falls_back():
    code = template.fill_template(
        "gpus = sorted(g.gpu_milli_left for g in node.gpus)\n"
        "return max(1, gpus[0]) if pod.num_gpu == 0 else 1")
    transpiler.transpile(code)  # transpilable...
    with pytest.raises(vm.VMUnsupported):
        vm.compile_policy(code, N, G, capacity=512)  # ...but not VM-able


@pytest.mark.slow
def test_code_evaluator_uses_vm_tier(micro_workload_or_none=None):
    from fks_tpu.data.build import make_workload

    nodes = [{"node_id": "n0", "cpu_milli": 4000, "memory_mib": 8000,
              "gpus": [1000, 1000]},
             {"node_id": "n1", "cpu_milli": 2000, "memory_mib": 4000,
              "gpus": []}]
    pods = [{"pod_id": f"p{i}", "cpu_milli": 500, "memory_mib": 500,
             "num_gpu": i % 2, "gpu_milli": 300 * (i % 2),
             "creation_time": i, "duration_time": 5} for i in range(6)]
    wl = make_workload(nodes, pods, pad_nodes_to=2, pad_gpus_to=2,
                       pad_pods_to=8)
    ev = backend.CodeEvaluator(wl)
    seeds = list(template.seed_policies().values())
    recs = ev.evaluate(seeds)
    assert all(r.ok for r in recs)
    assert ev.vm_count == len(seeds)
    assert ev.compile_count == 0  # nothing hit the per-candidate jit tier

    # and the jit tier still answers for VM-unsupported candidates
    hard = template.fill_template(
        "gpus = sorted(g.gpu_milli_left for g in node.gpus)\n"
        "return max(1, gpus[0]) if pod.num_gpu == 0 else 1")
    rec = ev.evaluate([hard])[0]
    assert rec.ok
    assert ev.compile_count == 1


@pytest.mark.slow
def test_vm_matches_jit_tier_scores():
    """CodeEvaluator with and without the VM tier produce identical
    fitness for the same candidates."""
    from fks_tpu.data.build import make_workload

    nodes = [{"node_id": "n0", "cpu_milli": 9000, "memory_mib": 9000,
              "gpus": [1000] * 3},
             {"node_id": "n1", "cpu_milli": 5000, "memory_mib": 5000,
              "gpus": [1000]}]
    pods = [{"pod_id": f"q{i}", "cpu_milli": 700, "memory_mib": 600,
             "num_gpu": 1 if i % 3 else 0, "gpu_milli": 250 if i % 3 else 0,
             "creation_time": i // 2, "duration_time": 4} for i in range(10)]
    wl = make_workload(nodes, pods, pad_nodes_to=2, pad_gpus_to=3,
                       pad_pods_to=16)
    codes = _corpus()[:8]
    with_vm = backend.CodeEvaluator(wl, use_vm=True).scores(codes)
    without = backend.CodeEvaluator(wl, use_vm=False).scores(codes)
    np.testing.assert_array_equal(with_vm, without)
