"""Champion-serving subsystem tests (fks_tpu.serve).

The ISSUE-8 acceptance criteria, as tests:

- batched serving parity: every lane of a coalesced batch matches the
  UNBATCHED exact-engine answer (score <= 1e-5, placements identical) —
  scatter-back isolation means lane i sees only query i;
- zero-recompile warm path: repeated same-bucket queries after a warm
  call compile zero new XLA programs (CompileWatcher delta == 0);
- artifact round-trip: a saved+reloaded engine answers identically;
- plus units for bucket/lane routing, the prefilter auto-heuristic,
  the request coalescer's flush policy, the served-answer parity audit,
  and a CLI smoke over the real champion ledger.
"""
import json

import numpy as np
import pytest

from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.funsearch import template
from fks_tpu.serve import (
    ChampionSpec, RequestBatcher, ServeEngine, ServeService, ShapeEnvelope,
    load_champion, selftest,
)


@pytest.fixture(scope="module")
def engine():
    """One warm ServeEngine for the module: tiny synthetic cluster, the
    first_fit seed champion, a 2-rung bucket ladder."""
    wl = synthetic_workload(8, 16, seed=0)
    champ = ChampionSpec(code=template.fill_template("score = 1000"),
                         score=0.5)
    env = ShapeEnvelope(max_pods=16, min_pod_bucket=4, max_batch=4,
                        max_gpu_milli=1000)
    return ServeEngine(champ, wl, envelope=env)


def _query(i, n=2):
    return [{"cpu_milli": 10 + 7 * i + j, "memory_mib": 50 + 11 * j,
             "creation_time": j, "duration_time": 40}
            for j in range(n)]


# ----------------------------------------------------------- envelope units


def test_bucket_ladder_and_routing():
    env = ShapeEnvelope(max_pods=1024, min_pod_bucket=16,
                        pod_bucket_growth=4, max_batch=8)
    assert env.pod_buckets() == (16, 64, 256, 1024)
    assert env.pod_bucket_for(1) == 16
    assert env.pod_bucket_for(16) == 16
    assert env.pod_bucket_for(17) == 64
    assert env.pod_bucket_for(1024) == 1024
    with pytest.raises(ValueError):
        env.pod_bucket_for(1025)
    # min_real_pods: the routing guarantee the snapshot-table width
    # leans on — no query below this count lands in the bucket
    assert env.min_real_pods(16) == 1
    assert env.min_real_pods(64) == 17
    assert env.lane_buckets() == (1, 2, 4, 8)
    assert env.lanes_for(3) == 4
    with pytest.raises(ValueError):
        env.lanes_for(9)


def test_envelope_ladder_not_hitting_max():
    env = ShapeEnvelope(max_pods=100, min_pod_bucket=16,
                        pod_bucket_growth=4, max_batch=3)
    assert env.pod_buckets() == (16, 64, 100)
    assert env.lane_buckets() == (1, 2, 3)


# -------------------------------------------------------- champion loading


def test_load_champion_single_and_list(tmp_path):
    single = {"code": "def f(): pass", "score": 0.4, "generation": 3}
    top = [{"code": "a", "score": 0.1}, {"code": "b", "score": 0.9},
           {"code": "c", "score": 0.5}]
    p1 = tmp_path / "one.json"
    p1.write_text(json.dumps(single))
    p2 = tmp_path / "top.json"
    p2.write_text(json.dumps(top))
    c1 = load_champion(str(p1))
    assert c1.score == 0.4 and c1.generation == 3
    assert load_champion(str(p2)).code == "b"  # best of the list wins
    (tmp_path / "bad.json").write_text("{\"notcode\": 1}")
    with pytest.raises(ValueError):
        load_champion(str(tmp_path / "bad.json"))


# ------------------------------------------------- prefilter auto-heuristic


def test_auto_prefilter_k_units():
    from fks_tpu.sim.engine import auto_prefilter_k

    # override always wins, probe or not
    assert auto_prefilter_k(4096, 1e-2, override=0) == 0
    assert auto_prefilter_k(64, None, override=32) == 32
    # small node parks never prefilter (the dense sweep is already cheap)
    assert auto_prefilter_k(128, 1e-2) == 0
    # big park + expensive policy -> on; cheap policy -> off
    assert auto_prefilter_k(4096, 1e-2) == 64
    assert auto_prefilter_k(4096, 1e-6) == 0
    assert auto_prefilter_k(4096, None) == 0  # probe failed -> stay dense


# --------------------------------------------------------- serving parity


def test_batch_parity_and_scatterback_isolation(engine):
    """Three DISTINCT queries batched together: each lane's answer equals
    its own unbatched exact answer — a lane leak (query j's pods bleeding
    into lane i) would break score or placements immediately."""
    queries = [_query(0, 1), _query(1, 2), _query(2, 3)]
    batched = engine.answer_batch(queries)
    for q, ans in zip(queries, batched):
        ref = engine.reference_answer(q)
        assert abs(ans["score"] - ref["score"]) <= 1e-5
        assert ans["placements"] == ref["placements"]
        assert ans["scheduled"] == ref["scheduled"]
    # distinct queries should produce at least two distinct answers here
    assert len({a["score"] for a in batched}) > 1


def test_batch_order_preserved(engine):
    queries = [_query(3, 2), _query(4, 2)]
    fwd = engine.answer_batch(queries)
    rev = engine.answer_batch(queries[::-1])
    assert fwd[0]["score"] == rev[1]["score"]
    assert fwd[0]["placements"] == rev[1]["placements"]


def test_selftest_green(engine):
    result = selftest(engine, count=4, pods_per_query=3)
    assert result["ok"], result
    assert result["max_drift"] <= 1e-5 and result["placements_match"]


def test_oversized_and_malformed_queries_rejected(engine):
    with pytest.raises(ValueError):
        engine.answer_batch([[]])
    with pytest.raises(ValueError):
        engine.answer_batch([_query(0, 17)])  # > max_pods
    with pytest.raises(ValueError):
        engine.answer_batch([[{"cpu_milli": -1}]])


# ----------------------------------------------------------- zero recompile


def test_warm_path_zero_recompile(engine):
    from fks_tpu.obs import CompileWatcher

    queries = [_query(5, 2), _query(6, 3)]
    engine.answer_batch(queries)  # warm: AOT + eager stacking programs
    watcher = CompileWatcher().install()
    try:
        for i in range(3):
            engine.answer_batch([_query(7 + i, 3), _query(9 + i, 2)])
        delta = watcher.backend_compile_count
    finally:
        watcher.uninstall()
    assert delta == 0, (
        f"{delta} XLA programs compiled on the warm path — the AOT "
        "bucket cache leaked a shape")


# --------------------------------------------------------- artifact I/O


def test_artifact_round_trip(tmp_path, engine):
    q = _query(10, 2)
    before = engine.answer_batch([q])[0]
    d = str(tmp_path / "artifact")
    engine.save(d)
    loaded = ServeEngine.load(d)
    after = loaded.answer_batch([q])[0]
    assert before["score"] == after["score"]
    assert before["placements"] == after["placements"]
    assert loaded.envelope == engine.envelope
    assert loaded.prefilter_k == engine.prefilter_k
    assert loaded.base_pods == engine.base_pods
    # version guard: a future-format artifact must refuse to half-load
    doc = json.loads((tmp_path / "artifact" / "artifact.json").read_text())
    doc["version"] = 999
    (tmp_path / "artifact" / "artifact.json").write_text(json.dumps(doc))
    with pytest.raises(ValueError):
        ServeEngine.load(d)


# ------------------------------------------------------- request coalescer


def test_batcher_coalesces_and_scatters():
    seen_batches = []

    def handler(queries, _enq):
        seen_batches.append(list(queries))
        return [q * 10 for q in queries]

    b = RequestBatcher(handler, max_batch=3, max_wait_s=0.2)
    futs = [b.submit(i) for i in (1, 2, 3)]
    assert [f.result(timeout=5) for f in futs] == [10, 20, 30]
    assert len(seen_batches) == 1  # full batch flushed as one call
    b.close()
    assert b.submitted == 3 and b.batches == 1
    assert b.mean_occupancy == 1.0


def test_batcher_max_wait_flush_and_errors():
    def handler(queries, _enq):
        if any(q == "boom" for q in queries):
            raise RuntimeError("bad batch")
        return queries

    b = RequestBatcher(handler, max_batch=8, max_wait_s=0.01)
    f = b.submit("lonely")
    assert f.result(timeout=5) == "lonely"  # flushed by max_wait, not size
    g = b.submit("boom")
    with pytest.raises(RuntimeError):
        g.result(timeout=5)
    b.close()
    with pytest.raises(RuntimeError):
        b.submit("after close")


# ---------------------------------------------------------- service + audit


def test_service_answers_and_audits(engine):
    service = ServeService(engine, max_wait_s=0.005, audit_every=1)
    try:
        futs = [service.submit({"id": f"q{i}", "pods": _query(i, 2)})
                for i in range(3)]
        answers = [f.result(timeout=60) for f in futs]
    finally:
        service.close()
    assert [a["id"] for a in answers] == ["q0", "q1", "q2"]
    assert all(a["latency_ms"] > 0 for a in answers)
    summary = service.summary(record=False)
    assert summary["requests"] == 3
    assert summary["audits"] == 3 and summary["audit_failures"] == 0
    with pytest.raises(ValueError):
        service.resolve_query({"nope": 1})


def test_service_tenant_accounting(engine):
    """accounting=True threads tenant identity end to end: the batcher
    item carries it, serve_request rows are labelled, the accountant
    aggregates per tenant, and every ``workload_every`` requests one
    tenant_stats row per tenant plus a workload_mix row land on the
    recorder."""
    class Rec:
        enabled = True

        def __init__(self):
            self.metrics, self.events = [], []

        def metric(self, kind, record=None, **f):
            self.metrics.append({"kind": kind, **(record or f)})

        def event(self, kind, **f):
            self.events.append((kind, f))

    rec = Rec()
    service = ServeService(engine, recorder=rec, max_wait_s=0.002,
                           accounting=True, workload_every=2)
    try:
        futs = [service.submit({"id": f"q{i}", "tenant": t,
                                "pods": _query(i, 2)})
                for i, t in enumerate(("acme", "acme", "zoo"))]
        for f in futs:
            f.result(timeout=60)
        summary = service.summary(record=False)
    finally:
        service.close()
    stats = service.accountant.stats()
    assert stats["acme"]["requests"] == 2 and stats["zoo"]["requests"] == 1
    assert stats["acme"]["ewma_ms"] > 0
    reqs = [m for m in rec.metrics if m["kind"] == "serve_request"]
    assert [m["tenant"] for m in reqs] == ["acme", "acme", "zoo"]
    assert all(m["workload_class"].startswith("p2:") for m in reqs)
    # windowed accounting fired after crossing workload_every
    tstats = [m for m in rec.metrics if m["kind"] == "tenant_stats"]
    assert {m["tenant"] for m in tstats} == {"acme", "zoo"}
    mixes = [m for m in rec.metrics if m["kind"] == "workload_mix"]
    # the windowed record saw all 3 requests, then reset the window
    assert mixes and mixes[0]["window"] == 3
    assert 0.0 < summary["fairness_index"] <= 1.0
    assert set(summary["tenants"]) == {"acme", "zoo"}


def test_service_accounting_disabled_is_inert(engine):
    """The disabled path allocates no accountant and labels rows with
    the default tenant only — no workload_class field at all."""
    class Rec:
        enabled = False

        def __init__(self):
            self.metrics = []

        def metric(self, kind, record=None, **f):
            self.metrics.append({"kind": kind, **(record or f)})

    rec = Rec()
    service = ServeService(engine, recorder=rec, max_wait_s=0.002)
    try:
        service.submit({"pods": _query(0, 2)}).result(timeout=60)
    finally:
        service.close()
    assert service.accountant is None and service.fingerprinter is None
    row = [m for m in rec.metrics if m["kind"] == "serve_request"][0]
    assert row["tenant"] == "default"
    assert "workload_class" not in row


# -------------------------------------------------------------- HTTP front


def test_http_front_concurrent_clients_share_a_batch(engine):
    """Two clients POSTing at once must land in ONE coalesced batch: with
    max_batch=2 and a 5s flush wait, a serialized (single-threaded) front
    would make each request wait out the full window alone — both
    answering well under the window proves the handlers genuinely
    overlap."""
    import threading
    import time

    from fks_tpu.obs.workload import http_client
    from fks_tpu.serve.service import make_http_server

    service = ServeService(engine, max_batch=2, max_wait_s=5.0)
    server = make_http_server(service, 0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    send = http_client(port)
    outcomes = [None, None]

    def client(k):
        outcomes[k] = send({"id": f"c{k}", "pods": _query(k, 2)})

    try:
        t0 = time.perf_counter()
        c0 = threading.Thread(target=client, args=(0,))
        c1 = threading.Thread(target=client, args=(1,))
        c0.start()
        c1.start()
        c0.join(timeout=30)
        c1.join(timeout=30)
        elapsed = time.perf_counter() - t0
    finally:
        server.shutdown()
        server.server_close()
        service.close()
    assert [o["outcome"] for o in outcomes] == ["ok", "ok"]
    assert elapsed < 4.0, (
        f"two concurrent POSTs took {elapsed:.1f}s — they waited out the "
        "flush window instead of coalescing into one batch")
    assert service.summary(record=False)["batches"] == 1


def test_http_front_routes_and_errors(engine):
    """GET /stats and /healthz answer; a malformed POST answers a
    structured 400 instead of wedging the socket."""
    import json as _json
    import threading
    import urllib.error
    import urllib.request

    from fks_tpu.serve.service import make_http_server

    service = ServeService(engine, max_wait_s=0.002)
    server = make_http_server(service, 0)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{port}"
    try:
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            assert _json.loads(r.read())["ok"]
        with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
            assert "requests" in _json.loads(r.read())
        bad = urllib.request.Request(
            f"{base}/query", data=b'{"nope": 1}',
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=30)
        assert ei.value.code == 400
    finally:
        server.shutdown()
        server.server_close()
        service.close()


def test_audit_served_alerts_on_drift():
    from fks_tpu.obs import ParitySentinel

    class Rec:
        def __init__(self):
            self.metrics, self.events = [], []
            self.enabled = True

        def metric(self, kind, record=None, **f):
            self.metrics.append((kind, record or f))

        def event(self, kind, **f):
            self.events.append((kind, f))

    rec = Rec()
    s = ParitySentinel(None, tol=1e-5, recorder=rec)
    assert s.audit_served("r1", 0.5, 0.5)
    assert s.alerts == 0
    assert not s.audit_served("r2", 0.5, 0.6)  # drift
    assert not s.audit_served("r3", 0.5, 0.5, placements_match=False)
    assert s.alerts == 2 and s.checked == 3
    assert [k for k, _ in rec.metrics] == ["parity"] * 3
    alert_kinds = [f["source"] for k, f in rec.events if k == "alert"]
    assert alert_kinds == ["serve_parity", "serve_parity"]


# ----------------------------------------------------------------- CLI


def test_cli_serve_jsonl_smoke(tmp_path, capsys):
    from fks_tpu import cli

    qfile = tmp_path / "q.jsonl"
    qfile.write_text(
        json.dumps({"id": "a", "pods": _query(0, 2)}) + "\n"
        + json.dumps({"id": "b", "pods": _query(1, 1)}) + "\n")
    rc = cli.main(["serve", "--cpu", "--max-pods", "16", "--max-batch", "2",
                   "--queries", str(qfile), "--audit-every", "2",
                   "--run-dir", str(tmp_path / "run")])
    out = capsys.readouterr().out
    assert rc == 0
    answers = [json.loads(line) for line in out.strip().splitlines()]
    assert [a["id"] for a in answers] == ["a", "b"]
    assert all("score" in a and "placements" in a for a in answers)
    # the run dir passes the schema checker, serve_request kind included
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    assert cjs.main(["--run-dir", str(tmp_path / "run")]) == 0
    metrics = [json.loads(ln) for ln in
               (tmp_path / "run" / "metrics.jsonl").read_text().splitlines()]
    assert sum(m["kind"] == "serve_request" for m in metrics) == 2
    assert any(m["kind"] == "parity" and m.get("source") == "serve"
               for m in metrics)


def test_cli_serve_selftest_smoke(tmp_path):
    from fks_tpu import cli

    rc = cli.main(["serve", "--cpu", "--max-pods", "8", "--max-batch", "2",
                   "--selftest", "2", "--pods-per-query", "2",
                   "--save-artifact", str(tmp_path / "art")])
    assert rc == 0
    assert (tmp_path / "art" / "artifact.json").exists()
