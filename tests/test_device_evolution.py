"""Device-resident parametric evolution (fks_tpu.funsearch.device_evolution)
and the weights->code bridge (models.parametric.render_code).

Runs on the 8-virtual-device CPU mesh (conftest), i.e. the sharded
generation step is exercised with real population sharding + all-gather.
"""
import jax
import numpy as np
import pytest

from fks_tpu.funsearch import (
    CodeEvaluator, EvolutionConfig, FakeLLM, FunSearch, ParametricEvolution,
)
from fks_tpu.models import parametric, zoo
from fks_tpu.sim.engine import SimConfig, simulate
from tests.test_engine_micro import micro_workload


def quiet(*_a, **_k):
    pass


def test_n_generations_through_sharded_step():
    """VERDICT #6 'done' criterion: N generations through the sharded
    generation step with weights staying device-resident."""
    wl = micro_workload()
    evo = ParametricEvolution(wl, pop_size=16, elite_k=4, seed=1)
    st = evo.run(3)
    assert evo.generation == 3
    assert len(evo.history) == 3
    assert st.best_score >= 0.0
    # best never decreases across rounds (elites survive)
    bests = [h.best_score for h in evo.history]
    assert bests == sorted(bests)
    # params stayed sharded on the mesh across rounds
    assert evo.params.shape[1] == parametric.NUM_FEATURES
    assert len(evo.params.sharding.device_set) == len(jax.devices())


def test_rendered_champion_is_valid_candidate():
    wl = micro_workload()
    evo = ParametricEvolution(wl, pop_size=8, elite_k=2, seed=2)
    evo.run(1)
    code = evo.best_code()
    rec = CodeEvaluator(wl).evaluate([code])[0]
    assert rec.ok, rec.error


@pytest.mark.parametrize("seed_name", ["best_fit", "packing"])
@pytest.mark.slow
def test_render_code_fitness_close_to_parametric(seed_name, default_workload):
    """The rendered source re-scored through the code path lands near the
    on-device parametric fitness (rendering is f64 Python vs f32 device
    arithmetic, so near, not equal)."""
    w = parametric.seed_weights(seed_name)
    dev = simulate(default_workload, parametric.as_policy(w))
    from fks_tpu.funsearch import transpiler
    rendered = simulate(default_workload,
                        transpiler.transpile(parametric.render_code(w)))
    assert abs(float(dev.policy_score) - float(rendered.policy_score)) < 2e-2
    assert int(rendered.scheduled_pods) == int(dev.scheduled_pods)


@pytest.mark.slow
def test_funsearch_hybrid_parametric_rounds():
    """FunSearch with parametric_rounds > 0 interleaves device rounds and
    admits the rendered champion through the normal dedup/admission path."""
    wl = micro_workload()
    cfg = EvolutionConfig(population_size=8, generations=2, elite_size=2,
                          candidates_per_generation=2, max_workers=1, seed=3,
                          early_stop_threshold=1.1, parametric_rounds=2,
                          parametric_pop=8)
    fs = FunSearch(CodeEvaluator(wl), cfg, backend=FakeLLM(seed=3), log=quiet)
    fs.run_evolution()
    assert fs.best is not None
    assert fs._device_evo is not None
    assert fs._device_evo.generation == 4  # 2 rounds x 2 generations
    # the device searcher's champion entered the code population in
    # rendered form at least once (or was dedup-rejected against a better
    # incumbent — either way the loop must have evaluated it)
    assert fs.history[-1].generation == 2


@pytest.mark.slow
def test_checkpoint_resume_reproduces_uninterrupted_run(tmp_path):
    """save after 2 generations -> fresh instance -> restore -> 1 more
    generation == 3 uninterrupted generations, bit for bit."""
    import numpy as np
    from fks_tpu.funsearch.device_evolution import ParametricEvolution
    from fks_tpu.sim.engine import SimConfig

    wl = micro_workload()
    kw = dict(pop_size=8, cfg=SimConfig(track_ctime=False), seed=3)
    a = ParametricEvolution(wl, **kw)
    a.run(2)
    ckpt = a.save_checkpoint(str(tmp_path / "pe.npz"))

    b = ParametricEvolution(wl, **kw)
    b.restore_checkpoint(ckpt)
    assert b.generation == 2 and b.best_score == a.best_score
    b.run(1)

    c = ParametricEvolution(wl, **kw)
    c.run(3)
    np.testing.assert_array_equal(np.asarray(b.params), np.asarray(c.params))
    assert b.best_score == c.best_score
    assert [h.best_score for h in b.history] == [h.best_score for h in c.history]


def test_restore_rejects_mismatched_population(tmp_path):
    import pytest as _pytest
    from fks_tpu.funsearch.device_evolution import ParametricEvolution
    from fks_tpu.sim.engine import SimConfig

    wl = micro_workload()
    a = ParametricEvolution(wl, pop_size=8, cfg=SimConfig(track_ctime=False))
    a.run(1)
    ckpt = a.save_checkpoint(str(tmp_path / "pe.npz"))
    b = ParametricEvolution(wl, pop_size=16, cfg=SimConfig(track_ctime=False))
    with _pytest.raises(ValueError, match="population shape"):
        b.restore_checkpoint(ckpt)
