"""Workload observability layer (fks_tpu.obs.workload).

The ISSUE-18 acceptance criteria, as tests:

- query fingerprints: ``classify`` is order-independent (pod permutation
  and dict key order change nothing), deterministic ACROSS PROCESSES
  (a fresh interpreter computes the same class), splits on pod-count
  bucket and resource decade while clustering within a decade, and the
  windowed mix resets on ``record_mix``;
- fairness/burn math, hand-computed: Jain of [10, 10] is 1.0, of
  [10, 0] is 0.5; 10 of 100 requests over a 50 ms target with a 1%
  error budget burns at exactly 10x;
- tenant accounting: shed/expired/degraded counters, per-row global
  fairness, and ``record`` rows carrying every key the stdlib schema
  checker requires of ``tenant_stats``;
- ``parse_tenant_spec`` round trips and rejects malformed specs;
- ``run_loadgen`` drives a fake client and summarizes into the four
  compare-gated keys, recording one ``loadgen_summary`` metric;
- closed vocabularies pinned against tools/check_jsonl_schema.py's
  stdlib-only copies, and the golden fixture carries schema-complete
  exemplar rows for all three new metric kinds.

The end-to-end two-tenant run through the real HTTP front is gated by
``bench.py --stage loadgen`` via tools/run_full_suite.py's
``loadgen_gate``; here the drivers run against fakes.
"""
import json
import os
import pathlib
import subprocess
import sys
import threading
import time

import pytest

from fks_tpu.obs.history import SLOConfig
from fks_tpu.obs.workload import (
    DEFAULT_TENANT, LOADGEN_MODES, QueryFingerprinter, TenantAccountant,
    TenantLoad, default_make_pods, jain_fairness, parse_tenant_spec,
    run_loadgen, tenant_of,
)

REPO = pathlib.Path(__file__).parent.parent
GOLDEN = str(REPO / "tests" / "fixtures" / "golden_run")

PODS = [
    {"cpu_milli": 120, "memory_mib": 512, "creation_time": 0,
     "duration_time": 40},
    {"cpu_milli": 55, "memory_mib": 1024, "creation_time": 1,
     "duration_time": 40},
    {"cpu_milli": 700, "memory_mib": 256, "creation_time": 2,
     "duration_time": 80},
]


class RecStub:
    enabled = True

    def __init__(self):
        self.metrics = []

    def metric(self, kind, *a, **fields):
        rec = dict(a[0]) if a and isinstance(a[0], dict) else {}
        rec.update(fields)
        self.metrics.append({"kind": kind, **rec})


def _schema_tool():
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    try:
        import check_jsonl_schema as cjs
    finally:
        sys.path.pop(0)
    return cjs


# ---------------------------------------------------------- fingerprints

def test_tenant_of():
    assert tenant_of({"tenant": "acme"}) == "acme"
    assert tenant_of({"tenant": 7}) == "7"
    assert tenant_of({}) == DEFAULT_TENANT
    assert tenant_of({"tenant": ""}) == DEFAULT_TENANT
    assert tenant_of(None) == DEFAULT_TENANT


def test_fingerprint_order_independent():
    fp = QueryFingerprinter()
    base = fp.classify(PODS)
    # pod permutation
    assert fp.classify(list(reversed(PODS))) == base
    # dict key order (JSON round trip preserves values, reorders keys)
    reordered = [dict(sorted(p.items(), reverse=True)) for p in PODS]
    assert fp.classify(reordered) == base
    assert base.startswith("p4:")  # 3 pods -> pow2 bucket 4


def test_fingerprint_splits_and_clusters():
    fp = QueryFingerprinter()
    base = fp.classify(PODS)
    # same decade clusters: 120 -> 160 is still +e3
    tweak = [dict(PODS[0], cpu_milli=160)] + PODS[1:]
    assert fp.classify(tweak) == base
    # decade jump splits: 120 -> 12000
    jump = [dict(PODS[0], cpu_milli=12000)] + PODS[1:]
    assert fp.classify(jump) != base
    # pod-count bucket splits: 3 pods (bucket 4) vs 5 pods (bucket 8)
    five = PODS + [dict(PODS[0]), dict(PODS[1])]
    assert fp.classify(five).startswith("p8:")
    assert fp.classify(five) != base


def test_fingerprint_cross_process():
    fp = QueryFingerprinter()
    local = fp.classify(PODS)
    code = (
        "import json,sys\n"
        "from fks_tpu.obs.workload import QueryFingerprinter\n"
        "pods=json.loads(sys.argv[1])\n"
        "print(QueryFingerprinter().classify(pods))\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code, json.dumps(PODS)],
        capture_output=True, text=True, cwd=str(REPO), env=env,
        timeout=300)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert proc.stdout.strip() == local


def test_fingerprint_window_and_record_mix():
    fp = QueryFingerprinter()
    for _ in range(3):
        fp.observe(PODS)
    fp.observe(PODS[:1])
    mix = fp.mix()
    assert sum(mix.values()) == 4 and len(mix) == 2
    rec = RecStub()
    out = fp.record_mix(rec)
    assert out["window"] == 4 and out["distinct"] == 2
    assert sum(out["classes"].values()) == 4
    assert rec.metrics[0]["kind"] == "workload_mix"
    # reset=True started a fresh window; an empty window records nothing
    assert fp.mix() == {}
    assert fp.record_mix(rec) == {}
    assert len(rec.metrics) == 1


# --------------------------------------------------- fairness/burn math

def test_jain_fairness_hand_computed():
    assert jain_fairness([10, 10]) == pytest.approx(1.0)
    assert jain_fairness([10, 0]) == pytest.approx(0.5)
    assert jain_fairness([1, 1, 1, 1]) == pytest.approx(1.0)
    # one of n tenants has everything -> 1/n
    assert jain_fairness([5, 0, 0, 0]) == pytest.approx(0.25)
    # idle reads as fair
    assert jain_fairness([]) == 1.0
    assert jain_fairness([0, 0]) == 1.0


def test_slo_burn_hand_computed():
    acct = TenantAccountant(slo=SLOConfig(p99_ms=50.0, error_budget=0.01))
    for _ in range(90):
        acct.note_request("a", 10.0)
    for _ in range(10):
        acct.note_request("a", 60.0)
    row = acct.stats()["a"]
    # 10% of requests over target / 1% budget = burning at exactly 10x
    assert row["burn_rate"] == pytest.approx(10.0)
    assert row["requests"] == 100


def test_accountant_counters_and_record():
    acct = TenantAccountant()
    acct.note_request("a", 10.0)
    acct.note_request("a", 20.0, degraded=True)
    acct.note_request("b", 10.0)
    acct.note_shed("b")
    acct.note_expired("b")
    acct.note_shed("c")  # shed-only tenant still gets a row
    rec = RecStub()
    stats = acct.record(rec)
    assert stats["a"]["requests"] == 2 and stats["a"]["degraded"] == 1
    assert stats["b"]["shed"] == 1 and stats["b"]["expired"] == 1
    assert stats["c"]["requests"] == 0 and stats["c"]["shed"] == 1
    # EWMA: first sample seeds, second blends at alpha=0.2
    assert stats["a"]["ewma_ms"] == pytest.approx(0.2 * 20 + 0.8 * 10)
    # every row carries the same GLOBAL fairness index
    fair = {row["fairness_index"] for row in stats.values()}
    assert fair == {round(jain_fairness([2, 1, 0]), 4)}
    cjs = _schema_tool()
    required = set(cjs.METRIC_KIND_REQUIRED["tenant_stats"])
    for row in rec.metrics:
        assert row["kind"] == "tenant_stats"
        assert required <= set(row)


# ------------------------------------------------------------- tenant spec

def test_parse_tenant_spec():
    plan = parse_tenant_spec("a:closed:2, b:open:25, c:closed:1:5")
    assert [ld.tenant for ld in plan] == ["a", "b", "c"]
    assert plan[0].mode == "closed" and plan[0].concurrency == 2
    assert plan[1].mode == "open" and plan[1].rate_qps == 25.0
    assert plan[2].pods_per_query == 5


@pytest.mark.parametrize("bad", [
    "", "a:closed", "a:open:0", "a:closed:0", "a:zigzag:3",
])
def test_parse_tenant_spec_rejects(bad):
    with pytest.raises(ValueError):
        parse_tenant_spec(bad)


def test_default_make_pods_deterministic():
    load = TenantLoad("a", "closed", concurrency=1, pods_per_query=3)
    assert default_make_pods(load, 7) == default_make_pods(load, 7)
    assert len(default_make_pods(load, 0)) == 3


# ---------------------------------------------------------------- loadgen

def test_run_loadgen_fake_send():
    calls = []
    lock = threading.Lock()

    def send(query):
        with lock:
            calls.append(query)
            n = len(calls)
        time.sleep(0.001)
        return {"outcome": "shed"} if n % 5 == 0 else {"outcome": "ok"}

    plan = parse_tenant_spec("a:closed:2,b:closed:2")
    rec = RecStub()
    out = run_loadgen(send, plan, duration_s=0.25, recorder=rec)
    assert out["mode"] == "closed" and out["tenant_count"] == 2
    assert out["requests"] == out["completed"] + out["shed"] + out["errors"]
    assert out["requests"] > 0 and out["errors"] == 0
    assert out["loadgen_qps"] > 0
    assert 0.0 < out["loadgen_shed_rate"] < 1.0
    assert 0.0 < out["loadgen_fairness_index"] <= 1.0
    assert set(out["tenants"]) == {"a", "b"}
    # queries carried tenant identity and deterministic pods
    assert all(tenant_of(q) in ("a", "b") for q in calls)
    assert all(len(q["pods"]) == 2 for q in calls)
    summary = [m for m in rec.metrics if m["kind"] == "loadgen_summary"]
    assert len(summary) == 1 and summary[0]["mode"] == "closed"


def test_run_loadgen_mixed_mode():
    def send(query):
        time.sleep(0.001)
        return {"outcome": "ok"}

    plan = parse_tenant_spec("a:closed:1,b:open:80")
    out = run_loadgen(send, plan, duration_s=0.25, seed=3)
    assert out["mode"] == "mixed"
    assert out["tenants"]["b"]["sent"] > 0  # Poisson arrivals fired


# ------------------------------------------------- vocabulary pinning

def test_loadgen_modes_pinned_against_schema_tool():
    cjs = _schema_tool()
    assert set(LOADGEN_MODES) == cjs.LOADGEN_MODES


def test_golden_fixture_has_workload_rows():
    cjs = _schema_tool()
    rows = [json.loads(line) for line in
            open(os.path.join(GOLDEN, "metrics.jsonl"))]
    by_kind = {}
    for r in rows:
        by_kind.setdefault(r.get("kind"), []).append(r)
    assert len(by_kind["tenant_stats"]) >= 2
    assert by_kind["workload_mix"] and by_kind["loadgen_summary"]
    for kind in ("workload_mix", "tenant_stats", "loadgen_summary"):
        required = set(cjs.METRIC_KIND_REQUIRED[kind])
        for r in by_kind[kind]:
            assert required <= set(r), (kind, sorted(required - set(r)))
    for r in by_kind["loadgen_summary"]:
        assert r["mode"] in cjs.LOADGEN_MODES
