"""Population vmap + mesh shard_map layer tests (micro workload).

Property under test: batched/sharded evaluation is bit-identical to running
each candidate through the single-policy engine — the TPU replacement for
the reference's per-candidate subprocess fan-out must not change fitness
(reference: funsearch/funsearch_integration.py:30-64, 535-562).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.data.build import make_workload
from fks_tpu.models import parametric
from fks_tpu.parallel.mesh import (
    POP_AXIS, make_sharded_eval, make_sharded_generation_step, pad_population,
    population_mesh,
)
from fks_tpu.parallel.population import make_population_eval
from fks_tpu.sim.engine import SimConfig, simulate


def micro_workload():
    nodes = [
        {"node_id": "node1", "cpu_milli": 8000, "memory_mib": 16000,
         "gpus": [1000, 1000], "gpu_memory_mib": 8000},
        {"node_id": "node2", "cpu_milli": 4000, "memory_mib": 8000, "gpus": []},
    ]
    pods = [
        {"pod_id": "pod1", "cpu_milli": 1000, "memory_mib": 2000, "num_gpu": 0,
         "gpu_milli": 0, "creation_time": 0, "duration_time": 10},
        {"pod_id": "pod2", "cpu_milli": 2000, "memory_mib": 4000, "num_gpu": 1,
         "gpu_milli": 500, "creation_time": 5, "duration_time": 15},
        {"pod_id": "pod3", "cpu_milli": 3000, "memory_mib": 6000, "num_gpu": 0,
         "gpu_milli": 0, "creation_time": 10, "duration_time": 8},
        {"pod_id": "pod4", "cpu_milli": 1500, "memory_mib": 3000, "num_gpu": 2,
         "gpu_milli": 400, "creation_time": 15, "duration_time": 12},
    ]
    return make_workload(nodes, pods, pad_nodes_to=4, pad_gpus_to=4, pad_pods_to=8)


@pytest.fixture(scope="module")
def wl():
    return micro_workload()


@pytest.fixture(scope="module")
def pop8():
    key = jax.random.PRNGKey(0)
    return parametric.init_population(key, 8, noise=0.2)


@pytest.mark.slow
def test_vmap_matches_single(wl, pop8):
    res = make_population_eval(wl)(pop8)
    for i in range(pop8.shape[0]):
        single = simulate(wl, parametric.as_policy(pop8[i]))
        assert np.asarray(res.policy_score)[i] == pytest.approx(
            float(single.policy_score), abs=0)
        assert int(np.asarray(res.scheduled_pods)[i]) == int(single.scheduled_pods)
        np.testing.assert_array_equal(
            np.asarray(res.assigned_node)[i], np.asarray(single.assigned_node))


@pytest.mark.slow
def test_seed_policies_schedule_micro(wl):
    for name in ("first_fit", "best_fit", "worst_fit", "packing"):
        res = simulate(wl, parametric.as_policy(parametric.seed_weights(name)))
        assert int(res.scheduled_pods) == 4, name
        assert float(res.policy_score) > 0, name


@pytest.mark.slow
def test_sharded_eval_matches_vmap(wl, pop8):
    mesh = population_mesh()
    assert mesh.shape[POP_AXIS] == 8  # conftest forces 8 virtual devices
    padded, real = pad_population(pop8, mesh.shape[POP_AXIS])
    scores, elite_idx, elite_scores = make_sharded_eval(
        wl, mesh, elite_k=4)(padded)
    ref = make_population_eval(wl)(pop8).policy_score
    np.testing.assert_array_equal(np.asarray(scores)[:real], np.asarray(ref))
    # elites are the true global top-k
    order = np.argsort(-np.asarray(scores), kind="stable")
    np.testing.assert_allclose(
        np.sort(np.asarray(elite_scores))[::-1],
        np.sort(np.asarray(scores)[order[:4]])[::-1])


def test_padded_population_excludes_pad_from_elites(wl):
    """A non-divisible population is padded with copies of the last
    candidate; those pad slots must not enter the elite ranking."""
    mesh = population_mesh()
    # 6 real candidates; make the LAST one the best so its pad duplicates
    # would win elite slots if not masked.
    key = jax.random.PRNGKey(2)
    pop6 = parametric.init_population(key, 6, noise=0.3)
    pop6 = pop6.at[5].set(parametric.seed_weights("best_fit"))
    padded, real = pad_population(pop6, mesh.shape[POP_AXIS])
    assert padded.shape[0] == 8 and real == 6
    scores, elite_idx, elite_scores = make_sharded_eval(
        wl, mesh, elite_k=4)(padded, real)
    assert np.all(np.asarray(elite_idx) < real)
    assert len(set(np.asarray(elite_idx).tolist())) == 4


@pytest.mark.slow
def test_generation_step_preserves_elites(wl, pop8):
    mesh = population_mesh()
    step = make_sharded_generation_step(wl, mesh, elite_k=4, noise=0.05)
    new_params, scores, elite_scores = step(pop8, jax.random.PRNGKey(1))
    assert new_params.shape == pop8.shape
    # top-k elites occupy the first k slots of the new population, unchanged
    top = np.asarray(jax.lax.top_k(scores, 4)[1])
    np.testing.assert_allclose(
        np.asarray(new_params)[:4], np.asarray(pop8)[top], rtol=0, atol=0)
    # and a second evaluation of the elites reproduces their scores
    res2 = make_population_eval(wl)(new_params[:4])
    np.testing.assert_allclose(
        np.asarray(res2.policy_score),
        np.sort(np.asarray(elite_scores))[::-1])


# ---------------------------------------------------------------- hybrid mesh

@pytest.mark.slow
def test_hybrid_mesh_matches_flat_mesh(wl, pop8):
    """2-D ("dcn","pop") mesh (multi-slice topology modeled on the 8 virtual
    devices as 2 slices x 4 chips) must produce identical fitness and elite
    selection to the 1-D mesh and to plain vmap."""
    from fks_tpu.parallel import DCN_AXIS, hybrid_population_mesh

    mesh = hybrid_population_mesh(num_slices=2)
    assert mesh.shape[DCN_AXIS] == 2 and mesh.shape[POP_AXIS] == 4
    padded, real = pad_population(pop8, mesh)
    assert real == 8
    scores, elite_idx, elite_scores = make_sharded_eval(
        wl, mesh, elite_k=4)(padded)
    ref = make_population_eval(wl)(pop8).policy_score
    np.testing.assert_array_equal(np.asarray(scores), np.asarray(ref))

    flat = make_sharded_eval(wl, population_mesh(), elite_k=4)(pop8)
    np.testing.assert_array_equal(np.asarray(elite_idx), np.asarray(flat[1]))
    np.testing.assert_array_equal(np.asarray(elite_scores), np.asarray(flat[2]))


@pytest.mark.slow
def test_hybrid_generation_step_runs_and_preserves_elites(wl, pop8):
    from fks_tpu.parallel import hybrid_population_mesh

    mesh = hybrid_population_mesh(num_slices=2)
    step = make_sharded_generation_step(wl, mesh, elite_k=4, noise=0.05)
    new_params, scores, elite_scores = step(pop8, jax.random.PRNGKey(1))
    assert new_params.shape == pop8.shape
    top = np.asarray(jax.lax.top_k(scores, 4)[1])
    np.testing.assert_allclose(
        np.asarray(new_params)[:4], np.asarray(pop8)[top], rtol=0, atol=0)


def test_hybrid_mesh_rejects_indivisible_slices():
    from fks_tpu.parallel import hybrid_population_mesh

    with pytest.raises(ValueError, match="divisible"):
        hybrid_population_mesh(num_slices=3)
