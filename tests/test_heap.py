"""Differential test: the JAX heap must reproduce CPython heapq's exact
array layout (not just pop order) under arbitrary push/pop interleavings --
the reference's retry semantics read the raw heap array
(reference: simulator/event_simulator.py:51-58)."""
import heapq
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.ops.heap import (
    EventHeap, KIND_CREATE, KIND_DELETE,
    heap_from_events, heap_push, heap_pop, first_deletion_in_array_order,
)


def as_tuples(h: EventHeap):
    n = int(h.size)
    t, r, k, p = (np.asarray(x) for x in (h.time, h.rank, h.kind, h.pod))
    return [(int(t[i]), int(r[i]), int(k[i]), int(p[i])) for i in range(n)]


def ref_first_deletion(pyheap):
    for (t, r, k, p) in pyheap:
        if k == KIND_DELETE:
            return True, t
    return False, None


@pytest.mark.parametrize("seed", [0, 1])
def test_random_ops_layout_parity(seed):
    rng = random.Random(seed)
    n0 = 50
    times = [rng.randrange(0, 40) for _ in range(n0)]  # many duplicate times
    ranks = list(range(n0))
    rng.shuffle(ranks)
    kinds = [KIND_CREATE] * n0
    pods = list(range(n0))

    pyheap = [(t, r, k, p) for t, r, k, p in zip(times, ranks, kinds, pods)]
    heapq.heapify(pyheap)
    h = heap_from_events(times, ranks, kinds, pods, capacity=n0 + 64)

    push = jax.jit(heap_push)
    pop = jax.jit(heap_pop)
    first_del = jax.jit(first_deletion_in_array_order)

    next_rank = n0
    for step in range(120):
        if step % 5 == 0:
            assert as_tuples(h) == pyheap, f"layout diverged at step {step}"
            found, t = first_del(h)
            rfound, rt = ref_first_deletion(pyheap)
            assert bool(found) == rfound
            if rfound:
                assert int(t) == rt

        do_push = rng.random() < 0.5 or not pyheap
        if do_push:
            item = (rng.randrange(0, 40), next_rank,
                    rng.choice([KIND_CREATE, KIND_DELETE]), next_rank)
            next_rank += 1
            heapq.heappush(pyheap, item)
            h = push(h, *[jnp.int32(x) if i != 2 else jnp.int8(x)
                          for i, x in enumerate(item)])
        else:
            expect = heapq.heappop(pyheap)
            h, item = pop(h)
            got = tuple(int(x) for x in item)
            assert got == expect


def test_push_pred_false_is_noop():
    h = heap_from_events([5, 3], [0, 1], [0, 0], [0, 1], capacity=8)
    h2 = heap_push(h, jnp.int32(1), jnp.int32(9), jnp.int8(1), jnp.int32(7),
                   pred=jnp.bool_(False))
    assert as_tuples(h2) == as_tuples(h)
    assert int(h2.size) == 2


def test_equal_time_orders_by_rank():
    # same time, ranks decide order (reference Event.__lt__ on pod_id)
    h = heap_from_events([7, 7, 7], [2, 0, 1], [0, 0, 0], [10, 11, 12])
    pods = []
    for _ in range(3):
        h, (t, r, k, p) = heap_pop(h)
        pods.append(int(p))
    assert pods == [11, 12, 10]


@pytest.mark.slow
def test_vmapped_heap_ops():
    def trace(times):
        h = EventHeap(data=jnp.zeros((8, 4), jnp.int32), size=jnp.int32(0))
        for i in range(4):
            h = heap_push(h, times[i], jnp.int32(i), jnp.int8(0), jnp.int32(i))
        out = []
        for _ in range(4):
            h, (t, _, _, _) = heap_pop(h)
            out.append(t)
        return jnp.stack(out)

    times = jnp.array([[4, 1, 3, 2], [9, 9, 0, 5]], jnp.int32)
    got = jax.vmap(trace)(times)
    np.testing.assert_array_equal(np.asarray(got), [[1, 2, 3, 4], [0, 5, 9, 9]])
