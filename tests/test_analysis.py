"""Static-analysis subsystem (fks_tpu.analysis): candidate pre-flight
(pillar A) and the repo linter + jaxpr-pin gate (pillar B).

The pre-flight contract under test is REPRODUCIBILITY: every static
rejection must correspond to a real failure of the actual pipeline
(sandbox.validate / transpiler.transpile), and everything the analyzer
accepts must actually transpile — the analyzer may be conservative about
COST, never about verdicts.
"""
import json
import os
import sys

import pytest

from fks_tpu import analysis, obs
from fks_tpu.analysis import candidate, lint
from fks_tpu.funsearch import backend, llm, sandbox, template, transpiler
from fks_tpu.sim.engine import SimConfig

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import check_jsonl_schema as cjs  # noqa: E402

sys.path.pop(0)


# ------------------------------------------------------------ table sync

def test_taxonomy_synced_with_schema_checker():
    """The schema checker is stdlib-only and carries a duplicated copy of
    the taxonomy; this is the pin that keeps the copies identical."""
    assert len(set(analysis.REJECT_TAXONOMY)) == len(analysis.REJECT_TAXONOMY)
    assert set(analysis.REJECT_TAXONOMY) == cjs.CANDIDATE_REJECT_TAXONOMY


def test_tables_derived_from_transpiler():
    """Pre-flight tables must be the transpiler's own, not re-hardcoded —
    a transpiler whitelist change must flow through automatically."""
    assert candidate.ARITY == transpiler._ARITY
    assert candidate.MATH_FNS == frozenset(transpiler._MATH_FNS)
    assert candidate.MAX_UNROLL == transpiler._Interp.MAX_UNROLL
    assert candidate.POD_FIELDS == frozenset(transpiler._Pod.FIELDS)
    assert candidate.NODE_FIELDS == (
        frozenset(transpiler._Node.FIELDS) | {"gpus"})
    # GPU fields are derived from _Gpu.attr's source; the two real fields
    # must be present (derivation returning garbage would break this)
    assert {"gpu_milli_left", "gpu_milli_total"} <= candidate.GPU_FIELDS


def test_math_table_entries_actually_transpile():
    """Every math.* name the arity table admits must lower for real."""
    for name in sorted(candidate.MATH_FNS):
        lo, _hi = candidate.ARITY[f"math.{name}"]
        args = ", ".join(["1.5"] * lo)
        code = template.fill_template(f"score = 1 + math.{name}({args})")
        rep = analysis.preflight_check(code)
        assert rep.ok, (name, rep.reason)
        assert callable(transpiler.transpile(code))


# ------------------------------------ rejections reproduce real failures

BAD = [
    ("syntax", "def priority_function(pod, node:\n    return 1"),
    ("bad_signature", "def priority_function(pod, nodes):\n    return 1"),
    ("bad_signature",
     "def priority_function(pod, node):\n    return 1\nx = 2"),
    ("forbidden_construct", None, "score = pod.__class__"),
    ("forbidden_construct",
     "def priority_function(pod, node):\n    x = node.gpus[0:1]\n"
     "    return 1"),
    ("unsupported_syntax", None, "x, y = 1, 2\n    score = x + y"),
    ("unsupported_syntax", None,
     "x = 1\n    for i in range(x):\n        x = x + 1\n    score = x"),
    ("unsupported_syntax", None, "score = node.gpus[pod.num_gpu]"
     ".gpu_milli_left"),
    ("unsupported_call", None, "score = str(pod.cpu_milli)"),
    ("unsupported_call", None, "score = sum(node.gpus)"),
    ("bad_arity", None, "score = math.sqrt(1.0, 2.0)"),
    ("bad_arity", None, "score = min(5)"),
    ("unknown_attribute", None, "score = pod.gpu_count"),
    ("unknown_attribute", None, "score = node.cpu_total"),
    ("unknown_attribute", None,
     "score = sum(g.volts for g in node.gpus)"),
    ("loop_too_long", None,
     "score = 0\n    for i in range(100):\n        score = score + 1"),
]
BAD = [(t, rest[-1] if rest[0] is None else rest[0])
       for t, *rest in BAD]


@pytest.mark.parametrize("taxonomy,form", BAD,
                         ids=[f"{t}-{i}" for i, (t, _) in enumerate(BAD)])
def test_rejection_reproduces_as_real_failure(taxonomy, form):
    code = (form if form.startswith("def ")
            else template.fill_template(form))
    rep = analysis.preflight_check(code)
    assert not rep.ok
    assert rep.taxonomy == taxonomy, (rep.taxonomy, rep.reason)
    # the static verdict must match the actual pipeline: transpile (which
    # runs sandbox.validate first) must fail on the same candidate
    with pytest.raises(transpiler.TranspileError):
        transpiler.transpile(code)


GOOD = [
    "score = 100 + pod.cpu_milli / max(1, node.cpu_milli_left)",
    template.SEED_LOGIC["best_fit"],
    # loop bound that is a loop index of an enclosing static range
    "score = 0\n    for i in range(2):\n        for j in range(i):\n"
    "            score = score + 1",
    # static int arithmetic in the bound
    "score = 0\n    for i in range(2 + 1):\n        score = score + i",
    # zero-trip loop: the body is dead and never lowered, so a call the
    # transpiler cannot lower is still fine there — the analyzer must not
    # reject guaranteed-dead code the pipeline accepts
    "score = 1\n    for i in range(0):\n        score = str(i)",
    "score = sum(g.gpu_milli_left for g in node.gpus"
    " if g.gpu_milli_left > 100)",
    "score = len(sorted(g.gpu_milli_left for g in node.gpus))",
    "score = sorted(g.gpu_milli_left for g in node.gpus)[0]",
    "score = 0\n    for i, g in enumerate(node.gpus):\n"
    "        score = score + g.gpu_milli_left * i",
]


@pytest.mark.parametrize("form", GOOD,
                         ids=[f"good-{i}" for i in range(len(GOOD))])
def test_accepted_forms_actually_transpile(form):
    code = template.fill_template(form)
    rep = analysis.preflight_check(code)
    assert rep.ok, rep.reason
    assert rep.cost is not None and rep.fingerprint is not None
    assert callable(transpiler.transpile(code))


def test_fakellm_stream_verdicts_reproduce():
    """Property check over the synthetic candidate stream: every pre-flight
    verdict (accept or reject, any taxonomy) matches the real pipeline."""
    gen = llm.FakeLLM(seed=11, junk_rate=0.5)
    rejected = 0
    for _ in range(40):
        code = template.fill_template(gen.complete(""))
        rep = analysis.preflight_check(code)
        if rep.ok:
            assert callable(transpiler.transpile(code))
        else:
            rejected += 1
            assert rep.taxonomy in analysis.REJECT_TAXONOMY
            with pytest.raises(transpiler.TranspileError):
                transpiler.transpile(code)
    assert rejected > 0  # junk_rate=0.5 must exercise the reject path


# ---------------------------------------------------------- fingerprints

def _fp(logic: str) -> str:
    return analysis.fingerprint(template.fill_template(logic))


def test_fingerprint_alpha_rename_invariant():
    assert _fp("x = 1\n    score = x") == _fp("y = 1\n    score = y")


def test_fingerprint_buckets_same_decade_constants():
    a = _fp("score = pod.cpu_milli * 1.5")
    b = _fp("score = pod.cpu_milli * 1.7")
    c = _fp("score = pod.cpu_milli * 150.0")
    assert a == b      # same sign+decade bucket -> near-duplicate
    assert a != c      # different decade is a different policy shape


def test_fingerprint_sees_structure():
    assert _fp("score = pod.cpu_milli + 1") != _fp("score = pod.cpu_milli * 2")


def test_fingerprint_ignores_docstring():
    a = analysis.fingerprint(
        'def priority_function(pod, node):\n    """a"""\n    return 1\n')
    b = analysis.fingerprint(
        'def priority_function(pod, node):\n    """totally new"""\n'
        '    return 1\n')
    assert a == b


# ------------------------------------------------------------- cost model

def test_cost_scales_with_gpu_loop_depth():
    flat = analysis.preflight_check(
        template.fill_template("score = pod.cpu_milli + 1"))
    loop = analysis.preflight_check(template.fill_template(
        "score = sum(g.gpu_milli_left for g in node.gpus)"))
    assert flat.ok and loop.ok
    # the template prologue already loops over node.gpus, so BOTH grow
    # with the padded GPU count — but the gpu-loop body must grow faster
    # (a larger per-GPU coefficient) and cost more at equal G
    assert loop.cost.work(2) < loop.cost.work(16)
    assert (loop.cost.work(16) - loop.cost.work(2)
            > flat.cost.work(16) - flat.cost.work(2))
    assert loop.cost.work(8) > flat.cost.work(8)


def test_cost_grows_with_more_ops():
    small = analysis.preflight_check(
        template.fill_template("score = pod.cpu_milli + 1"))
    big = analysis.preflight_check(template.fill_template(
        "score = pod.cpu_milli * 2 + pod.memory_mib * 3 + pod.num_gpu * 4"))
    assert small.cost.work(8) < big.cost.work(8)


# ------------------------------------------- evaluator integration proof

_FP_TWIN_A = "x = 1\n    score = x + pod.cpu_milli * 1.5"
_FP_TWIN_B = "y = 1\n    score = y + pod.cpu_milli * 1.7"


def test_statically_rejected_never_reaches_sandbox(micro_workload,
                                                   monkeypatch):
    """The acceptance criterion: a pre-flight-rejected candidate (and a
    fingerprint-duplicate echo) provably never reaches sandbox.validate —
    every source sandbox.validate actually sees is recorded."""
    seen = []
    real_validate = sandbox.validate

    def counting_validate(code, *a, **k):
        seen.append(code)
        return real_validate(code, *a, **k)

    monkeypatch.setattr(sandbox, "validate", counting_validate)

    good = template.fill_template(GOOD[0])
    twin_a = template.fill_template(_FP_TWIN_A)
    twin_b = template.fill_template(_FP_TWIN_B)
    bad = [code for _, form in BAD
           for code in [form if form.startswith("def ")
                        else template.fill_template(form)]]
    ev = backend.CodeEvaluator(micro_workload, SimConfig())
    recs = ev.evaluate([good, twin_a, *bad, twin_b])
    assert len(recs) == len(bad) + 3

    assert ev.preflight_rejected == len(bad)
    assert ev.preflight_duplicates == 1
    for code in bad:
        assert code not in seen  # never validated, never transpiled
    assert twin_b not in seen    # dup echo rides the twin_a representative
    assert recs[0].ok
    # the echo gets the representative's record, not a zero
    assert recs[-1].score == recs[1].score
    stats = ev.last_eval_stats
    assert stats["preflight_rejected"] == len(bad)
    assert stats["fingerprint_duplicates"] == 1
    assert stats["unique"] == 2
    assert stats["mean_static_work"] > 0


def test_preflight_off_restores_legacy_path(micro_workload):
    """preflight=False / fp_dedup=False must fall back to the pre-analyzer
    pipeline: rejects still fail (downstream), duplicates evaluate twice."""
    ev = backend.CodeEvaluator(micro_workload, SimConfig(),
                               preflight=False, fp_dedup=False)
    recs = ev.evaluate([template.fill_template("score = str(pod.cpu_milli)"),
                        template.fill_template(_FP_TWIN_A),
                        template.fill_template(_FP_TWIN_B)])
    assert ev.preflight_rejected == 0 and ev.preflight_duplicates == 0
    assert not recs[0].ok and "preflight" not in recs[0].error
    assert recs[1].ok and recs[2].ok


def test_rejection_events_round_trip_through_schema_checker(
        micro_workload, tmp_path):
    """candidate_rejected events written by a real evaluate() batch must
    satisfy the ledger schema checker, taxonomy vocabulary included."""
    d = str(tmp_path / "run")
    with obs.recording(obs.FlightRecorder(d, meta={"command": "test"})):
        ev = backend.CodeEvaluator(micro_workload, SimConfig())
        ev.evaluate([
            template.fill_template("score = str(pod.cpu_milli)"),
            template.fill_template(_FP_TWIN_A),
            template.fill_template(_FP_TWIN_B),
            "def priority_function(pod, node:\n    return 1",
        ])
    with open(os.path.join(d, "events.jsonl")) as f:
        events = [json.loads(l) for l in f if l.strip()]
    rej = [e for e in events if e["kind"] == "candidate_rejected"]
    assert sorted(e["taxonomy"] for e in rej) == [
        "duplicate_fingerprint", "syntax", "unsupported_call"]
    assert {e["stage"] for e in rej} == {"preflight", "fp_dedup"}
    counts = cjs.check_run_dir(d)
    assert counts["events.jsonl"] == len(events)


def test_schema_checker_rejects_unknown_taxonomy(tmp_path):
    p = tmp_path / "events.jsonl"
    p.write_text(json.dumps({"ts": 1.0, "kind": "candidate_rejected",
                             "taxonomy": "vibes", "stage": "preflight"})
                 + "\n")
    recs = cjs.check_jsonl(str(p), required=("ts", "kind"))
    with pytest.raises(cjs.SchemaError, match="taxonomy"):
        cjs.check_kinds(str(p), recs, cjs.EVENT_KIND_REQUIRED)


# ------------------------------------------------------------- AST lints

_LINT_BAD = '''
import functools
import jax
import numpy as np
from functools import partial

@jax.jit
def f(x, cfg):
    while x > 0:
        x = x - 1
    if x > 0:
        x = np.ones(3)
    return x.item()

@partial(jax.jit, static_argnames=("mode",))
def g(x, mode):
    if mode:
        return x
    if x > 0:
        return -x
    return x

@jax.jit
def h(state, cfg: SimConfig):
    return state
'''


def test_lint_rules_fire():
    findings = lint.lint_source("mod.py", _LINT_BAD)
    codes = [f.code for f in findings]
    assert codes.count("FKS101") == 1   # while in f
    assert codes.count("FKS102") == 2   # if in f, traced if in g
    assert codes.count("FKS103") == 1   # .item() in f
    assert codes.count("FKS104") == 1   # np.ones in f
    assert codes.count("FKS105") == 1   # cfg: SimConfig traced in h
    # static_argnames excluded: `if mode:` in g must NOT be flagged
    g_hits = [f for f in findings if "'mode'" in f.message]
    assert not g_hits
    assert all(f.path == "mod.py" and f.line > 0 for f in findings)
    assert all(f.code in str(f) for f in findings)


def test_lint_ignores_unjitted_and_closures():
    src = (
        "import jax\n"
        "def plain(x):\n"
        "    while x > 0:\n"
        "        x = x - 1\n"
        "    return x.item()\n"
        "def build(cfg):\n"
        "    @jax.jit\n"
        "    def step(s):\n"
        "        if cfg.watchdog:\n"   # closure read: sanctioned pattern
        "            return s + 1\n"
        "        return s\n"
        "    return step\n")
    assert lint.lint_source("mod.py", src) == []


def test_lint_syntax_error_is_a_finding():
    findings = lint.lint_source("broken.py", "def f(:\n")
    assert [f.code for f in findings] == ["FKS100"]


def test_repo_lints_clean():
    """The acceptance criterion: the package's own sources carry zero
    findings (the gate tools/run_full_suite.py runs is a subprocess of
    the same function)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert lint.lint_paths([os.path.join(root, "fks_tpu")]) == []


# ------------------------------------------------------------ jaxpr pins

@pytest.fixture(scope="module")
def pins():
    """One sweep for the whole module — the lowering is trace-only but
    still seconds, so every pin test shares it via check_pins(current=)."""
    return lint.compute_pins()


def test_committed_manifest_matches_current_lowerings(pins):
    assert lint.check_pins(lint.PIN_MANIFEST, current=pins) == []


def test_pin_catches_traced_static_flag(pins):
    """A Python-static SimConfig flag turning into a traced read changes
    the lowered program — each single-flag variant must hash differently
    from baseline, so that regression is detectable as drift."""
    base = pins["pins"]["flat_step/baseline"]
    for name in ("watchdog", "decision_trace", "prefilter_k1",
                 "no_track_ctime", "state_pack", "cond_policy"):
        assert pins["pins"][f"flat_step/{name}"] != base, name
    # probe_score gates finalize, not the step — its pair is pinned there
    assert (pins["pins"]["flat_finalize/probe_score"]
            != pins["pins"]["flat_finalize/baseline"])
    assert pins["pins"]["flat_step/probe_score"] == base


def test_pin_drift_and_staleness_detected(pins, tmp_path):
    man = json.loads(json.dumps(pins))  # deep copy
    man["pins"]["flat_step/watchdog"] = "0" * 64
    man["pins"]["ghost/entry"] = "1" * 64
    del man["pins"]["serve_bucket/exact_l1_p16"]
    p = tmp_path / "pins.json"
    p.write_text(json.dumps(man))
    msgs = lint.check_pins(str(p), current=pins)
    assert any("drift" in m and "flat_step/watchdog" in m for m in msgs)
    assert any("stale" in m and "ghost/entry" in m for m in msgs)
    assert any("unpinned" in m and "serve_bucket" in m for m in msgs)


def test_missing_manifest_reported(pins, tmp_path):
    msgs = lint.check_pins(str(tmp_path / "nope.json"), current=pins)
    assert len(msgs) == 1 and "missing" in msgs[0]


def test_jax_version_change_reported(pins, tmp_path):
    man = json.loads(json.dumps(pins))
    man["jax"] = "9.9.9"
    p = tmp_path / "pins.json"
    p.write_text(json.dumps(man))
    msgs = lint.check_pins(str(p), current=pins)
    assert any("jax version" in m for m in msgs)


def test_write_pins_round_trips(pins, tmp_path, monkeypatch):
    monkeypatch.setattr(lint, "compute_pins", lambda: pins)
    p = str(tmp_path / "pins.json")
    man = lint.write_pins(p)
    assert man == pins
    assert lint.check_pins(p, current=pins) == []


def test_pinner_workload_matches_conftest_recipe():
    """lint._micro_workload is a copy of conftest.make_micro_workload
    (the pinner must run outside pytest); the copies must stay identical
    or the committed pins stop describing what the tests exercise."""
    import numpy as np
    from tests.conftest import make_micro_workload

    a = lint._micro_workload()
    b = make_micro_workload()
    import jax
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ----------------------------------------------------------- cli surface

def test_cli_lint_exit_codes(tmp_path):
    from fks_tpu import cli

    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x + 1\n")
    dirty = tmp_path / "dirty.py"
    dirty.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                     "    while x > 0:\n        x = x - 1\n    return x\n")
    assert cli.main(["lint", "--cpu", "--no-pins", str(clean)]) == 0
    assert cli.main(["lint", "--cpu", "--no-pins", str(dirty)]) == 1
    # missing manifest is drift (exit 1), reported before any lowering
    assert cli.main(["lint", "--cpu", "--pins",
                     str(tmp_path / "nope.json"), str(clean)]) == 1


def test_cli_lint_report_record(tmp_path):
    from fks_tpu import cli

    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x\n")
    d = str(tmp_path / "run")
    rc = cli.main(["lint", "--cpu", "--no-pins", "--run-dir", d,
                   str(clean)])
    assert rc == 0
    with open(os.path.join(d, "metrics.jsonl")) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    rep = next(r for r in recs if r["kind"] == "lint_report")
    assert rep["ok"] and rep["findings"] == [] and rep["pin_drift"] == []
    counts = cjs.check_run_dir(d)
    assert counts["metrics.jsonl"] == len(recs)
