"""Mesh-sharded serving tests (fks_tpu.serve on the dryrun device mesh).

The ISSUE-14 acceptance criteria, as tests. conftest.py forces an
8-virtual-CPU-device backend, so every test here runs against a REAL
8-way mesh in-process:

- sharded parity: the mesh-sharded engine's batched answers match the
  plain single-device engine EXACTLY (same scores, same placements) and
  the unbatched exact reference with 0.0 drift;
- per-lane isolation: a lane's answer is independent of what the other
  mesh lanes are serving;
- zero-recompile warm path: repeated warm batches across the mesh
  compile zero new XLA programs (CompileWatcher delta == 0);
- snapshot cache: repeated query content hits the device-resident
  ktable cache (hit/miss counters move the right way), uploads shrink;
- packed H2D: the 16-bit ``state_pack`` upload path is bit-identical to
  unpacked serving, plus pack/unpack round-trip units incl. the
  KT sentinel.
"""
import jax
import numpy as np
import pytest

from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.funsearch import template
from fks_tpu.parallel.mesh import num_shards, population_mesh
from fks_tpu.serve import ChampionSpec, ServeEngine, ShapeEnvelope


def _make_engine(**kw):
    wl = synthetic_workload(8, 16, seed=0)
    champ = ChampionSpec(code=template.fill_template("score = 1000"),
                         score=0.5)
    env = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2,
                        max_gpu_milli=1000)
    return ServeEngine(champ, wl, envelope=env, engine="flat", **kw)


@pytest.fixture(scope="module")
def plain():
    """Single-device baseline: no mesh, no packing."""
    return _make_engine()


@pytest.fixture(scope="module")
def sharded():
    """The round-17 path: batch axis sharded over every virtual device,
    16-bit packed uploads, device-resident snapshot cache."""
    return _make_engine(mesh=population_mesh(jax.devices()),
                        state_pack=True)


def _query(i, n=3):
    return [{"cpu_milli": 10 + 7 * i + j, "memory_mib": 50 + 11 * j,
             "creation_time": j, "duration_time": 40}
            for j in range(n)]


def test_suite_runs_on_a_real_mesh(sharded):
    # conftest forces 8 virtual devices; if this drops to 1 the rest of
    # the file silently stops testing sharding at all
    assert num_shards(sharded.mesh) == len(jax.devices()) >= 8


def test_sharded_matches_plain_exactly(plain, sharded):
    queries = [_query(i) for i in range(4)]
    a = plain.answer_batch(queries)
    b = sharded.answer_batch(queries)
    for i, (pa, pb) in enumerate(zip(a, b)):
        assert pa["score"] == pb["score"], f"lane {i} score drifted"
        assert pa["placements"] == pb["placements"], f"lane {i} placements"


def test_sharded_zero_drift_vs_reference(sharded):
    queries = [_query(10 + i) for i in range(3)]
    answers = sharded.answer_batch(queries)
    drift = 0.0
    for q, ans in zip(queries, answers):
        ref = sharded.reference_answer(q)
        drift = max(drift, abs(ans["score"] - ref["score"]))
        assert ans["placements"] == ref["placements"]
    assert drift == 0.0


def test_per_lane_isolation(sharded):
    # lane i's answer must not depend on its batch neighbours: answering
    # queries together and alone gives identical results
    queries = [_query(20 + i) for i in range(4)]
    together = sharded.answer_batch(queries)
    alone = [sharded.answer_batch([q])[0] for q in queries]
    for t, s in zip(together, alone):
        assert t["score"] == s["score"]
        assert t["placements"] == s["placements"]


def test_sharded_zero_recompiles_warm(sharded):
    from fks_tpu.obs import CompileWatcher

    sharded.answer_batch([_query(30), _query(31)])  # warm
    watcher = CompileWatcher().install()
    try:
        for i in range(3):
            sharded.answer_batch([_query(32 + i), _query(35 + i)])
        delta = watcher.backend_compile_count
    finally:
        watcher.uninstall()
    assert delta == 0, (
        f"{delta} XLA programs compiled on the warm sharded path — the "
        "mesh-wide AOT bucket cache leaked a shape")


def test_snapshot_cache_hits_and_misses(sharded):
    queries = [_query(40), _query(41)]
    sharded.answer_batch(queries)
    s0 = sharded.snapshot_cache_stats()
    sharded.answer_batch(queries)  # identical content -> device-resident
    s1 = sharded.snapshot_cache_stats()
    assert s1["hits"] > s0["hits"]
    assert s1["misses"] == s0["misses"]
    assert s1["entries"] <= 32  # the LRU cap
    assert 0.0 <= s1["hit_rate"] <= 1.0
    # a hit ships only the query delta: per-query upload volume shrinks
    assert s1["h2d_bytes_total"] - s0["h2d_bytes_total"] < (
        s0["h2d_bytes_total"])


def test_packed_scores_bit_identical(plain):
    packed = _make_engine(state_pack=True)
    queries = [_query(50 + i) for i in range(4)]
    a = plain.answer_batch(queries)
    b = packed.answer_batch(queries)
    for pa, pb in zip(a, b):
        assert pa["score"] == pb["score"]  # bitwise, not approx
        assert pa["placements"] == pb["placements"]


def test_pack_roundtrip_units():
    from fks_tpu.data.entities import PodArrays
    from fks_tpu.serve.batcher import (
        KT_SENTINEL, KT_SENTINEL_PACKED, pack_query_tables,
        unpack_query_tables,
    )

    plan = {"ktable": np.uint16, "gpu_milli": np.int16,
            "tie_rank": np.int16}
    kt = np.array([[3, 17, KT_SENTINEL, 200]], dtype=np.int32)
    i32 = lambda *v: np.array([list(v)], dtype=np.int32)  # noqa: E731
    pods = PodArrays(cpu=i32(5, 6, 7), mem=i32(50, 60, 70),
                     num_gpu=i32(0, 1, 0), gpu_milli=i32(100, 0, 32000),
                     creation_time=i32(0, 1, 2), duration=i32(40, 40, 40),
                     tie_rank=i32(0, 1, 2),
                     pod_mask=np.ones((1, 3), dtype=bool))
    ppods, pkt = pack_query_tables(pods, kt, plan)
    assert pkt.dtype == np.uint16
    assert pkt[0, 2] == KT_SENTINEL_PACKED  # sentinel remapped, not clipped
    assert np.asarray(ppods.gpu_milli).dtype == np.int16
    assert np.asarray(ppods.tie_rank).dtype == np.int16
    assert np.asarray(ppods.cpu).dtype == np.int32  # not in the plan
    upods, ukt = unpack_query_tables(ppods, pkt, plan)
    np.testing.assert_array_equal(np.asarray(ukt), kt)
    assert np.asarray(ukt).dtype == np.int32
    np.testing.assert_array_equal(np.asarray(upods.gpu_milli),
                                  np.asarray(pods.gpu_milli))
    np.testing.assert_array_equal(np.asarray(upods.tie_rank),
                                  np.asarray(pods.tie_rank))
    # empty plan is the identity
    ppods2, pkt2 = pack_query_tables(pods, kt, {})
    assert pkt2 is kt and ppods2 is pods


def test_pack_plan_gates_on_value_ranges():
    from fks_tpu.serve.batcher import query_pack_plan

    class _Cfg:
        state_pack = True
        max_steps = 1000

    plan = query_pack_plan(_Cfg(), 32, 1000)
    assert plan.get("ktable") == np.uint16
    assert plan.get("gpu_milli") == np.int16
    assert plan.get("tie_rank") == np.int16

    class _Off:
        state_pack = False
        max_steps = 1000

    assert query_pack_plan(_Off(), 32, 1000) == {}

    class _Huge:
        state_pack = True
        max_steps = 70000  # trigger values overflow uint16

    assert "ktable" not in query_pack_plan(_Huge(), 32, 1000)
