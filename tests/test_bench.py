"""bench.py stage wiring (fast tier): the code-candidate throughput stage
runs in-process on the conftest 8-virtual-device mesh, and the fallback
contract banks only CURRENT-round session measurements while the headline
carries the last healthy historical value under stale_from_run
provenance (round 14 — see the bench.py module docstring).

The heavy stages (flat/fused parametric throughput) need the full trace
and are exercised by the TPU measurement session; here the codetput stage
is routed to the micro workload so its wiring — candidate sourcing via
``vm.lower_fake_candidates``, the sharded dispatch, the JSON contract —
stops being device-only code.
"""
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `import bench` regardless of pytest rootdir
    sys.path.insert(0, REPO)

import bench  # noqa: E402


class _MicroParser:
    def __init__(self, wl):
        self._wl = wl

    def parse_workload(self, *a, **k):
        return self._wl


def test_stage_codetput_sharded_smoke(micro_workload, monkeypatch, capsys):
    """The stage sources FakeLLM candidates, shards them over the 8-device
    mesh, and prints the {"code_evals_per_sec": ...} JSON line."""
    import fks_tpu.data

    monkeypatch.setattr(fks_tpu.data, "TraceParser",
                        lambda: _MicroParser(micro_workload))
    monkeypatch.setenv("FKS_BENCH_CODE_POP", "2")
    assert bench.stage_codetput() == 0
    out = capsys.readouterr().out
    payload = json.loads(out.strip().splitlines()[-1])
    assert payload["code_evals_per_sec"] > 0
    assert payload["mode"] == "sharded over 8 devices"


def test_stage_codetput_gates_on_candidate_count(micro_workload, monkeypatch):
    """Fewer VM-able candidates than the stage needs -> rc 1 (the
    controller treats it as a skipped probe), not a crash or a fabricated
    number."""
    import fks_tpu.data
    from fks_tpu.funsearch import vm

    monkeypatch.setattr(fks_tpu.data, "TraceParser",
                        lambda: _MicroParser(micro_workload))
    monkeypatch.setattr(vm, "lower_fake_candidates",
                        lambda *a, **k: ([], []))
    assert bench.stage_codetput() == 1


def _write_round(results_dir, n, records):
    path = results_dir / f"round{n}_tpu.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in records) + "\n")


@pytest.fixture
def banked_repo(tmp_path, monkeypatch):
    """Point bench's results directory at a temp tree (it is derived from
    the module's __file__; the env override must not leak in either)."""
    results = tmp_path / "benchmarks" / "results"
    results.mkdir(parents=True)
    monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
    monkeypatch.delenv("FKS_BENCH_RESULTS_DIR", raising=False)
    return results


def test_banked_measurement_only_reads_current_round(banked_repo):
    """A prior round's (higher!) number must not leak into this round's
    fallback — only the highest-numbered round file is evidence."""
    _write_round(banked_repo, 5, [
        {"ok": True, "stage": "flat", "ts": 1,
         "result": {"evals_per_sec": 999.0}},
        {"ok": True, "stage": "codetput", "ts": 1,
         "result": {"code_evals_per_sec": 777.0}},
    ])
    _write_round(banked_repo, 6, [
        {"ok": True, "stage": "flat", "ts": 2,
         "result": {"evals_per_sec": 100.0, "truncated": 0}},
        {"ok": True, "stage": "vmbatch_pop64", "ts": 3,
         "result": {"code_evals_per_sec": 50.0}},
        {"ok": False, "stage": "fused64", "ts": 4,
         "result": {"evals_per_sec": 12345.0}},  # failed probe: ignored
    ])
    best, code_best = bench._banked_measurement()
    assert best["value"] == 100.0 and best["file"] == "round6_tpu.jsonl"
    assert code_best["value"] == 50.0
    assert code_best["file"] == "round6_tpu.jsonl"


def test_banked_measurement_empty_results(banked_repo):
    assert bench._banked_measurement() == (None, None)


def test_fallback_json_carries_stale_headline(banked_repo):
    """Round 14 revision of the round-6 contract: a failed probe's
    headline carries the last HEALTHY historical value under an explicit
    ``stale_from_run`` marker (here the session's own round file is the
    newest healthy donor); the current round's session measurement still
    rides along under banked_from."""
    _write_round(banked_repo, 6, [
        {"ok": True, "stage": "flatseed", "ts": 2,
         "result": {"evals_per_sec": 321.0}},
        {"ok": True, "stage": "codetput", "ts": 3,
         "result": {"code_evals_per_sec": 7.5}},
    ])
    payload = json.loads(bench._fallback_json("tunnel wedged"))
    assert payload["value"] == 321.0
    assert payload["vs_baseline"] == pytest.approx(321.0 / 40.0, abs=1e-3)
    assert payload["stale_from_run"]["value"] == 321.0
    assert payload["error"] == "tunnel wedged"
    assert payload["banked_from"]["value"] == 321.0
    assert payload["code_banked_from"]["value"] == 7.5
    assert "NOT a live measurement" in payload["note"]


def test_fallback_json_without_any_bank(banked_repo):
    payload = json.loads(bench._fallback_json("no device"))
    assert payload["value"] == 0.0
    assert "banked_from" not in payload
    assert "no recorded" in payload["note"]


def test_classify_probe_failure_taxonomy():
    """The four structured probe-failure kinds (round 7): a timeout, a
    signal death, an import failure, and a plain init failure are told
    apart instead of collapsing into one error string."""
    import signal as _signal

    assert bench._classify_probe_failure(None, "")[0] == "timeout"
    kind, detail = bench._classify_probe_failure(-_signal.SIGILL, "")
    assert kind == "sigill-risk" and "SIGILL" in detail
    kind, _ = bench._classify_probe_failure(-9999, "")  # unknown signal
    assert kind == "sigill-risk"
    kind, _ = bench._classify_probe_failure(
        1, "Traceback...\nModuleNotFoundError: no module named jax")
    assert kind == "import-error"
    kind, detail = bench._classify_probe_failure(1, "RuntimeError: boom")
    assert kind == "init-failure" and "rc=1" in detail


def test_fallback_json_carries_failure_taxonomy(banked_repo):
    """The taxonomy rides along in the fallback payload next to the
    stale-carried headline and the banked session measurement."""
    _write_round(banked_repo, 6, [
        {"ok": True, "stage": "flatseed", "ts": 2,
         "result": {"evals_per_sec": 321.0}},
    ])
    attempts = [
        {"attempt": 1, "kind": "timeout",
         "detail": "device backend initialization timed out"},
        {"attempt": 2, "kind": "timeout",
         "detail": "device backend initialization timed out"},
        {"attempt": 3, "kind": "init-failure",
         "detail": "backend initialization failed (rc=1)"},
    ]
    payload = json.loads(bench._fallback_json("probe failed",
                                              failure_taxonomy=attempts))
    assert payload["value"] == 321.0
    assert payload["stale_from_run"]["value"] == 321.0
    assert payload["banked_from"]["value"] == 321.0
    assert payload["failure_taxonomy"]["kinds"] == {
        "timeout": 2, "init-failure": 1}
    assert payload["failure_taxonomy"]["attempts"] == attempts


def test_gate_judges_headline_against_baseline(tmp_path, capsys):
    baseline = tmp_path / "baseline.jsonl"
    baseline.write_text(json.dumps(
        {"value": 100.0, "unit": "evals/s"}) + "\n")
    ok = bench._gate(str(baseline), {"value": 95.0, "unit": "evals/s"})
    reg = bench._gate(str(baseline), {"value": 70.0, "unit": "evals/s"})
    err = capsys.readouterr().err
    assert ok == 0 and reg == 1
    assert "REGRESSION" in err
    # a broken gate (missing baseline) fails closed without raising
    assert bench._gate(str(tmp_path / "nope.jsonl"), {"value": 1.0}) == 1
