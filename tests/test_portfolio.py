"""Multi-tenant champion-portfolio serving (fks_tpu.portfolio).

The contract under test: N resident policies live in ONE slot-vmapped
VM executable; per-request slot selection is bit-identical to serving
each champion alone; promoting one slot under live traffic is a table
upload — zero XLA compiles — that never perturbs the other slots; and
the router's rule chain (pin / affinity / A-B / coverage fallback) is
deterministic and closed-vocabulary.
"""
import json
import os
import threading

import numpy as np
import pytest

from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.funsearch import template, vm
from fks_tpu.obs import CompileWatcher
from fks_tpu.obs.workload import QueryFingerprinter
from fks_tpu.pipeline import PromotionConfig, write_champion
from fks_tpu.portfolio import (
    FALLBACK, FleetController, PortfolioEngine, PortfolioService,
    ROUTE_REASONS, Router, portfolio_selftest, vm_coverage_split,
)
from fks_tpu.serve import (
    ChampionSpec, ServeEngine, ShapeEnvelope, VMServeEngine,
)
from fks_tpu.serve.artifact import Workload
from fks_tpu.serve.batcher import (
    pack_portfolio_tables, unpack_portfolio_tables,
)

SEED_LOGIC = "score = 1000"
BETTER_LOGIC = ("score = 1000 + (node.cpu_milli_left - pod.cpu_milli) "
                "/ max(1, node.cpu_milli_total)")
EVEN_BETTER_LOGIC = ("score = 2000 + (node.memory_mib_left - "
                     "pod.memory_mib) / max(1, node.memory_mib_total)")
WORST_FIT_LOGIC = ("score = 1000 - (node.cpu_milli_left - pod.cpu_milli) "
                   "/ max(1, node.cpu_milli_total)")
UNSUPPORTED_LOGIC = ("gpus = sorted(g.gpu_milli_left for g in node.gpus)\n"
                     "return max(1, gpus[0]) if pod.num_gpu == 0 else 1")


def _champ(logic, score=0.5, source="<test>"):
    return ChampionSpec(code=template.fill_template(logic), score=score,
                        source=source)


class RecStub:
    enabled = True

    def __init__(self):
        self.events = []
        self.metrics = []

    def event(self, kind, **fields):
        self.events.append({"kind": kind, **fields})

    def metric(self, kind, *a, **fields):
        self.metrics.append({"kind": kind, **fields})


@pytest.fixture(scope="module")
def wl():
    return synthetic_workload(8, 16, seed=0)


@pytest.fixture(scope="module")
def envelope():
    return ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2,
                         max_gpu_milli=1000)


@pytest.fixture(scope="module")
def champs():
    # raw-milli scores: genuinely distinct policies. The normalized
    # "+fit/total" logic variants collapse into all-tie constant
    # policies under the template's int() truncation — four identical
    # slots could never catch a cross-slot routing bug in the parity
    # checks below.
    return [_champ(SEED_LOGIC, 0.4, "<c0>"),
            _champ("score = node.cpu_milli_left - pod.cpu_milli",
                   0.5, "<c1>"),
            _champ("score = node.memory_mib_left - pod.memory_mib",
                   0.6, "<c2>"),
            _champ("score = pod.cpu_milli - node.cpu_milli_left",
                   0.7, "<c3>")]


@pytest.fixture(scope="module")
def portfolio(wl, envelope, champs):
    eng = PortfolioEngine(champs, wl, envelope=envelope, engine="flat",
                          n_slots=5)
    eng.warmup()
    return eng


def _query(base, i, n=3):
    return [dict(base[(i + j) % len(base)]) for j in range(n)]


# ------------------------------------------------------------- units


def test_pack_unpack_portfolio_tables(wl):
    n, g = wl.cluster.n_padded, wl.cluster.g_padded
    progs = [vm.pad_capacity(vm.compile_policy(
        template.fill_template(lg), n, g), 256)
        for lg in (SEED_LOGIC, BETTER_LOGIC)]
    packed = pack_portfolio_tables(progs)
    stacked = unpack_portfolio_tables(packed)
    for s, prog in enumerate(progs):
        one = vm.select_slot(stacked, s)
        for a, b in zip(one, prog):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_select_slot_capacity_is_shape_derived(wl):
    n, g = wl.cluster.n_padded, wl.cluster.g_padded
    progs = [vm.pad_capacity(vm.compile_policy(
        template.fill_template(lg), n, g), 256)
        for lg in (SEED_LOGIC, BETTER_LOGIC)]
    stacked = vm.stack_programs(progs)
    one = vm.select_slot(stacked, 1)
    assert one.capacity == 256  # shape-derived, not the slot axis


def test_n_slots_must_cover_champions(wl, envelope, champs):
    with pytest.raises(ValueError):
        PortfolioEngine(champs, wl, envelope=envelope, n_slots=2)


def test_shadow_for_is_not_the_portfolio_shadow_path(portfolio):
    with pytest.raises(TypeError):
        portfolio.shadow_for(_champ(BETTER_LOGIC))


# ----------------------------------------------------- slot parity


def test_per_slot_and_mixed_parity(portfolio):
    """The acceptance criterion: every resident slot's answers match a
    single-champion VM engine serving that champion alone, and a mixed
    batch matches the per-slot answers."""
    result = portfolio_selftest(portfolio, count=6, pods_per_query=3)
    assert result["ok"], result["failures"]
    assert result["max_drift"] == 0.0  # integer-scored VM: bit-identical
    assert result["mixed_max_drift"] == 0.0
    assert result["placements_match"]
    # guard against vacuous parity: the resident policies must actually
    # disagree somewhere, or slot-routing bugs would be invisible
    base = portfolio.base_pods
    queries = [_query(base, i) for i in range(6)]
    s1 = portfolio.answer_batch(queries, slots=[1] * 6)
    s3 = portfolio.answer_batch(queries, slots=[3] * 6)
    assert any(a["score"] != b["score"] or a["placements"] != b["placements"]
               for a, b in zip(s1, s3))


def test_slot_validation(portfolio):
    base = portfolio.base_pods
    with pytest.raises(ValueError):
        portfolio.answer_batch([_query(base, 0)], slots=[99])
    with pytest.raises(ValueError):
        portfolio.answer_batch([_query(base, 0)], slots=[0, 1])


def test_swap_slot_returns_rollback_handle(wl, envelope):
    # opposed raw-milli champions: their scores differ by hundreds, so
    # int() truncation in the template can't collapse them into ties
    a = _champ("score = node.cpu_milli_left - pod.cpu_milli", 0.4, "<a>")
    b = _champ("score = pod.cpu_milli - node.cpu_milli_left", 0.9, "<b>")
    eng = PortfolioEngine([a, b], wl, envelope=envelope, engine="flat",
                          n_slots=3)
    eng.warmup()
    base = eng.base_pods
    queries = [_query(base, 7), _query(base, 11)]

    def key(answers):
        return tuple((round(float(x["score"]), 9),
                      tuple(str(p) for p in x["placements"]))
                     for x in answers)

    before = key(eng.answer_batch(queries, slots=[0, 0]))
    other = key(eng.answer_batch(queries, slots=[1, 1]))
    assert before != other  # the pair is genuinely opposed on these
    old = eng.swap_slot(0, b)
    assert old.source == "<a>"  # the rollback handle
    changed = key(eng.answer_batch(queries, slots=[0, 0]))
    assert changed == other  # slot 0 now serves b, bit-identically
    eng.swap_slot(0, old)  # roll back
    after = key(eng.answer_batch(queries, slots=[0, 0]))
    assert after == before


def test_save_load_roundtrip(tmp_path, portfolio):
    portfolio.save(str(tmp_path))
    with open(os.path.join(str(tmp_path), "artifact.json")) as f:
        doc = json.load(f)
    assert doc["portfolio"]["n_slots"] == portfolio.n_slots
    loaded = ServeEngine.load(str(tmp_path))
    assert isinstance(loaded, PortfolioEngine)
    assert [c.source for c in loaded.slot_champions] == \
        [c.source for c in portfolio.slot_champions]
    q = _query(portfolio.base_pods, 1)
    for s in range(3):
        a = portfolio.answer_batch([q], slots=[s])[0]
        b = loaded.answer_batch([q], slots=[s])[0]
        assert a["score"] == b["score"]
        assert a["placements"] == b["placements"]


# ---------------------------------------------------------- router


def test_router_rule_precedence(wl):
    base_pods = [{"cpu_milli": 100, "memory_mib": 200}] * 3
    cls = QueryFingerprinter().classify(base_pods)
    r = Router(4, pins={"vip": 1}, affinity={cls: 2},
               ab_split={0: 0.5, 3: 0.5})
    assert r.route("r1", "vip", base_pods) == (1, "pin")
    assert r.route("r2", "other", base_pods) == (2, "affinity")
    slot, reason = r.route("r3", "other", [{"cpu_milli": 999999,
                                            "memory_mib": 1}] * 3)
    assert reason == "ab" and slot in (0, 3)


def test_router_ab_is_deterministic():
    r = Router(4, ab_split={0: 0.5, 3: 0.5})
    pods = [{"cpu_milli": 1, "memory_mib": 1}]
    first = [r.route(f"req-{i}", "t", pods)[0] for i in range(64)]
    again = [r.route(f"req-{i}", "t", pods)[0] for i in range(64)]
    assert first == again  # same request id -> same arm, always
    assert set(first) == {0, 3}  # both arms actually drawn


def test_router_fallback_reason_and_validation():
    r = Router(2, pins={"legacy": FALLBACK})
    slot, reason = r.route("r1", "legacy", [])
    assert slot == FALLBACK and reason == "fallback"
    with pytest.raises(ValueError):
        Router(2, pins={"bad": 7})
    with pytest.raises(ValueError):
        Router(2, ab_split={0: 0.0})


def test_vm_coverage_split(wl):
    n, g = wl.cluster.n_padded, wl.cluster.g_padded
    resident, fallback = vm_coverage_split(
        [_champ(SEED_LOGIC), _champ(UNSUPPORTED_LOGIC)], n, g)
    assert len(resident) == 1 and len(fallback) == 1
    assert fallback[0].code == template.fill_template(UNSUPPORTED_LOGIC)


def test_route_reasons_pins_schema_checker_vocabulary():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_jsonl_schema",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "check_jsonl_schema.py"))
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    assert set(ROUTE_REASONS) == checker.ROUTE_REASONS
    assert "slot_swap" in checker.EVENT_KIND_REQUIRED
    assert "portfolio_route" in checker.METRIC_KIND_REQUIRED


# --------------------------------------------------------- service


def test_service_routes_and_records(portfolio):
    rec = RecStub()
    router = Router(portfolio.n_slots, pins={"vip": 1},
                    ab_split={0: 0.5, 2: 0.5})
    svc = PortfolioService(portfolio, router=router, recorder=rec,
                           max_wait_s=0.002)
    try:
        base = portfolio.base_pods
        futs = [svc.submit({"pods": _query(base, i),
                            "tenant": "vip" if i % 2 else "t"})
                for i in range(6)]
        answers = [f.result(timeout=300) for f in futs]
    finally:
        svc.close()
    assert all("slot" in a for a in answers)
    routes = [m for m in rec.metrics if m["kind"] == "portfolio_route"]
    assert len(routes) == 6
    assert all(m["reason"] in ROUTE_REASONS for m in routes)
    assert {m["reason"] for m in routes} == {"pin", "ab"}
    summ = svc.summary(record=False)
    assert summ["portfolio"]["n_slots"] == portfolio.n_slots
    assert sum(summ["portfolio"]["slot_requests"]) >= 6


def test_service_query_slot_override(portfolio):
    svc = PortfolioService(portfolio, max_wait_s=0.002)
    try:
        q = {"pods": _query(portfolio.base_pods, 0), "slot": 2}
        ans = svc.submit(q).result(timeout=300)
    finally:
        svc.close()
    assert ans["slot"] == 2
    assert svc.router.routed["query"] == 1


def test_service_fallback_engine(wl, envelope, portfolio):
    """FALLBACK-routed requests are answered on the kept-warm AOT
    engine and marked slot -1; portfolio lanes are unaffected."""
    fallback = ServeEngine(_champ(BETTER_LOGIC), wl, envelope=envelope,
                           engine="flat")
    router = Router(portfolio.n_slots, pins={"legacy": FALLBACK})
    svc = PortfolioService(portfolio, router=router,
                           fallback_engine=fallback, max_wait_s=0.002)
    try:
        base = portfolio.base_pods
        f1 = svc.submit({"pods": _query(base, 0), "tenant": "legacy"})
        f2 = svc.submit({"pods": _query(base, 1), "tenant": "normal"})
        a1, a2 = f1.result(timeout=300), f2.result(timeout=300)
    finally:
        svc.close()
    assert a1["slot"] == FALLBACK
    assert a2["slot"] == svc.router.default_slot
    assert svc.fallback_served == 1


# -------------------------------------------- swap under live fire


def test_concurrent_slot_swap_never_perturbs_other_slots(wl, envelope):
    """ISSUE-20 extension of the PR-17 race criterion: promoting slot
    UNDER's neighbour must be invisible to slot UNDER — its answers
    stay bit-identical across 30 swaps of slot SWAP, every future
    resolves exactly once, and the whole race performs zero compiles."""
    champs = [_champ("score = node.cpu_milli_left - pod.cpu_milli",
                     0.4, source="<a>"),
              _champ("score = pod.cpu_milli - node.cpu_milli_left",
                     0.9, source="<b>")]
    eng = PortfolioEngine(champs, wl, envelope=envelope, engine="flat",
                          n_slots=3)
    eng.warmup()
    SWAP, UNDER = 0, 1
    base = eng.base_pods
    queries = [_query(base, 7), _query(base, 11)]

    def key(answers):
        return tuple((round(float(a["score"]), 9), tuple(a["placements"]))
                     for a in answers)

    expected = key(eng.answer_batch(queries, slots=[UNDER, UNDER]))
    # the swap alternates programs whose slot-SWAP answers differ, so a
    # torn slot table would have something to tear
    legal_swap = {}
    for i, c in enumerate(champs):
        eng.swap_slot(SWAP, c)
        legal_swap[i] = key(eng.answer_batch(queries, slots=[SWAP, SWAP]))
    assert legal_swap[0] != legal_swap[1]

    watcher = CompileWatcher().install()
    errors, torn, served = [], [], []
    stop = threading.Event()

    def hammer():
        try:
            while not stop.is_set():
                got = key(eng.answer_batch(queries, slots=[UNDER, UNDER]))
                served.append(1)
                if got != expected:
                    torn.append(got)
        except Exception as e:  # pragma: no cover - failure diagnostics
            errors.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(30):
            eng.swap_slot(SWAP, champs[(i + 1) % 2])
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        watcher.uninstall()
    assert not errors, errors
    assert not torn, (f"{len(torn)} perturbed slot-{UNDER} batches "
                      f"across slot-{SWAP} swaps, first: {torn[:1]}")
    assert len(served) > 0
    assert eng.slot_swaps[SWAP] >= 30
    assert watcher.backend_compile_count == 0


def test_concurrent_service_futures_exactly_once(wl, envelope):
    """The routed front under the same race: every submitted future
    resolves exactly once with a well-formed answer while a neighbour
    slot is being promoted."""
    champs = [_champ(SEED_LOGIC, 0.4, "<a>"),
              _champ(BETTER_LOGIC, 0.9, "<b>")]
    eng = PortfolioEngine(champs, wl, envelope=envelope, engine="flat",
                          n_slots=3)
    eng.warmup()
    svc = PortfolioService(eng, router=Router(3, pins={"t": 1}),
                           max_wait_s=0.002)
    base = eng.base_pods
    try:
        futs = [svc.submit({"pods": _query(base, i), "tenant": "t"})
                for i in range(8)]
        for i in range(10):
            eng.swap_slot(0, champs[(i + 1) % 2])
        answers = [f.result(timeout=300) for f in futs]
    finally:
        svc.close()
    assert len(answers) == 8
    assert all(a["slot"] == 1 and a["score"] is not None
               for a in answers)


# ----------------------------------------------- fleet controller


def test_fleet_promotes_one_slot(tmp_path, wl, envelope, champs):
    rec = RecStub()
    eng = PortfolioEngine(champs[:3], wl, envelope=envelope,
                          engine="flat", n_slots=4, recorder=rec)
    eng.warmup()
    svc = PortfolioService(eng, router=Router(4), recorder=rec,
                           max_wait_s=0.002)
    base = eng.base_pods
    try:
        futs = [svc.submit({"pods": _query(base, i)}) for i in range(4)]
        [f.result(timeout=300) for f in futs]
        ctrl = FleetController(
            svc, wl, slot=1, shadow_slot=3, ledger_dir=str(tmp_path),
            log_path=os.path.join(str(tmp_path), "promotion.jsonl"),
            config=PromotionConfig(shadow_queries=2), recorder=rec)
        watcher = CompileWatcher().install()
        try:
            write_champion(str(tmp_path),
                           template.fill_template(
                               "score = 3000 + (node.cpu_milli_left - "
                               "pod.cpu_milli) / "
                               "max(1, node.cpu_milli_total)"), 0.9)
            verdict = ctrl.poll_once()
            compiles = watcher.backend_compile_count
        finally:
            watcher.uninstall()
    finally:
        svc.close()
    assert verdict.get("action") == "promoted", verdict
    assert verdict.get("engine_kind") == "vm"
    assert compiles == 0
    assert eng.slot_swaps[1] == 1  # commit into the target slot
    assert eng.slot_swaps[3] == 1  # shadow staging into the spare slot
    # every promotion record carries the slot
    promo = [m for m in rec.metrics if m["kind"] == "promotion_event"
             and "slot" in m]
    assert promo and all(m["slot"] == 1 for m in promo)
    swaps = [e for e in rec.events if e["kind"] == "slot_swap"]
    assert [e["slot"] for e in swaps] == [3, 1]
    assert all(e["outcome"] == "swapped" for e in swaps)


def test_fleet_slot_validation(wl, envelope, champs):
    eng = PortfolioEngine(champs[:3], wl, envelope=envelope,
                          engine="flat", n_slots=4)
    svc = PortfolioService(eng, max_wait_s=0.002)
    try:
        with pytest.raises(ValueError):
            FleetController(svc, wl, slot=1, shadow_slot=1)
        with pytest.raises(ValueError):
            FleetController(svc, wl, slot=9, shadow_slot=3)
    finally:
        svc.close()


def test_fleet_fitness_gate_compares_against_slot(tmp_path, wl, envelope):
    """The fitness gate prices the candidate against the TARGET SLOT's
    resident champion, not the engine default: a candidate above slot 0
    but below slot 1 is rejected when slot 1 is the target."""
    eng = PortfolioEngine([_champ(SEED_LOGIC, 0.1, "<weak>"),
                           _champ(BETTER_LOGIC, 2.0, "<strong>")],
                          wl, envelope=envelope, engine="flat", n_slots=3)
    eng.warmup()
    svc = PortfolioService(eng, max_wait_s=0.002)
    try:
        ctrl = FleetController(
            svc, wl, slot=1, shadow_slot=2, ledger_dir=str(tmp_path),
            log_path=os.path.join(str(tmp_path), "promotion.jsonl"),
            config=PromotionConfig(shadow_queries=2))
        write_champion(str(tmp_path),
                       template.fill_template(EVEN_BETTER_LOGIC), 0.5)
        verdict = ctrl.poll_once()
    finally:
        svc.close()
    assert verdict.get("action") == "rejected", verdict
    assert "fitness" in verdict.get("reason", "")


# ------------------------------------------------------ satellites


def test_per_tenant_retry_after(wl, envelope, portfolio):
    """Satellite 1: a shed request's Retry-After is priced at the
    SHEDDING tenant's observed EWMA service time when accounting is on,
    falling back to the global estimate for cold tenants."""
    from fks_tpu.resilience.admission import (
        AdmissionConfig, AdmissionController,
    )
    from fks_tpu.resilience.deadline import ShedError

    ctl = AdmissionController(AdmissionConfig(max_queue=1))
    ctl.note_batch(1, 0.010)  # global EWMA: 10ms
    ctl.service_time_for = {"slow": 0.500, "fast": 0.001,
                            "cold": None}.get
    ctl.admit(None)  # fills the queue
    hints = {}
    for tenant in ("slow", "fast", "cold", None):
        with pytest.raises(ShedError) as e:
            ctl.admit(None, tenant=tenant)
        hints[tenant] = e.value.retry_after_s
    assert hints["slow"] == pytest.approx(0.500)
    assert hints["fast"] > 0.0
    assert hints["slow"] > hints["fast"]
    assert hints["cold"] == hints[None]  # cold tenant -> global EWMA


def test_service_wires_accountant_into_admission(portfolio):
    from fks_tpu.serve.service import ServeService

    svc = ServeService(portfolio, max_wait_s=0.002, accounting=True)
    try:
        assert svc._batcher.admission.service_time_for is not None
        base = portfolio.base_pods
        svc.submit({"pods": _query(base, 0),
                    "tenant": "t0"}).result(timeout=300)
        est = svc._batcher.admission.service_time_for("t0")
        assert est is not None and est > 0.0
        assert svc._batcher.admission.service_time_for("never-seen") \
            is None
    finally:
        svc.close()


def test_transpile_overlap(wl, envelope):
    """Satellite 2: ``begin_overlapped_transpile`` (kicked at SHADOW
    entry) warms the transpile cache off the promotion path, and the
    following swap reports ``transpile_overlapped``."""
    eng = VMServeEngine(_champ(SEED_LOGIC, 0.4), wl, envelope=envelope,
                        engine="flat")
    champ = _champ(EVEN_BETTER_LOGIC, 0.9, "<overlap>")
    t = eng.begin_overlapped_transpile(champ)
    t.join(timeout=60)
    eng.swap_program(champ)
    assert eng.last_swap_breakdown["transpile_overlapped"] is True
    assert eng.last_swap_breakdown["transpile_cache"] == "hit"
    # the flag is consumed: a re-swap of the same champion is a plain
    # cache hit, not another overlap claim
    eng.swap_program(_champ(SEED_LOGIC))
    eng.swap_program(champ)
    assert eng.last_swap_breakdown["transpile_overlapped"] is False


def test_transpile_overlap_rides_fleet_promotion(tmp_path, wl, envelope,
                                                 champs):
    rec = RecStub()
    eng = PortfolioEngine(champs[:2], wl, envelope=envelope,
                          engine="flat", n_slots=3, recorder=rec)
    eng.warmup()
    svc = PortfolioService(eng, recorder=rec, max_wait_s=0.002)
    base = eng.base_pods
    try:
        futs = [svc.submit({"pods": _query(base, i)}) for i in range(4)]
        [f.result(timeout=300) for f in futs]
        ctrl = FleetController(
            svc, wl, slot=1, shadow_slot=2, ledger_dir=str(tmp_path),
            log_path=os.path.join(str(tmp_path), "promotion.jsonl"),
            config=PromotionConfig(shadow_queries=2), recorder=rec)
        write_champion(str(tmp_path),
                       template.fill_template(
                           "score = 4000 + (node.memory_mib_left - "
                           "pod.memory_mib) / "
                           "max(1, node.memory_mib_total)"), 5.0)
        verdict = ctrl.poll_once()
    finally:
        svc.close()
    assert verdict.get("action") == "promoted", verdict
    swaps = [e for e in rec.events if e["kind"] == "slot_swap"]
    assert [e["slot"] for e in swaps] == [2, 1]
    # the staging swap is the candidate's first sighting (miss); the
    # COMMIT swap lowers from a warm cache entry and carries the
    # overlapped-transpile claim kicked at SHADOW entry
    assert swaps[0]["transpile_cache"] == "miss"
    assert swaps[1]["transpile_cache"] == "hit"
    assert swaps[1]["transpile_overlapped"] is True
