"""Synthetic traces, shape bucketing, and the vmapped multi-trace engine:
the batched path must agree exactly with per-trace simulation."""
import jax.numpy as jnp
import numpy as np
import pytest

from fks_tpu.data import synthetic
from fks_tpu.models import parametric
from fks_tpu.parallel.traces import make_trace_batch_eval, stack_traces
from fks_tpu.sim.engine import SimConfig, initial_state, make_param_run_fn


def small(seed, nodes=6, pods=40):
    return synthetic.synthetic_workload(
        nodes, pods, seed=seed, horizon=5000, pad_to=(8, 8, 64))


def test_synthetic_workload_shapes():
    wl = synthetic.synthetic_workload(10, 100, seed=1)
    assert wl.num_nodes == 10
    assert wl.num_pods == 100
    assert bool(np.asarray(wl.cluster.node_mask).sum() == 10)
    # creation times sorted, durations positive
    ct = np.asarray(wl.pods.creation_time)[:100]
    assert (np.diff(ct) >= 0).all()
    assert (np.asarray(wl.pods.duration)[:100] > 0).all()


def test_synthetic_deterministic():
    a = synthetic.synthetic_workload(5, 20, seed=42)
    b = synthetic.synthetic_workload(5, 20, seed=42)
    assert np.array_equal(np.asarray(a.pods.cpu), np.asarray(b.pods.cpu))
    assert np.array_equal(np.asarray(a.cluster.cpu_total),
                          np.asarray(b.cluster.cpu_total))


def test_bucketing_groups_and_pads():
    from fks_tpu.data.build import make_workload

    def wl(n_nodes, n_pods):
        nodes = [{"node_id": f"n{i}", "cpu_milli": 8000, "memory_mib": 16000,
                  "gpus": [1000] * 4} for i in range(n_nodes)]
        pods = [{"pod_id": f"p{i:04d}", "cpu_milli": 100, "memory_mib": 100,
                 "num_gpu": 1, "gpu_milli": 100, "creation_time": i,
                 "duration_time": 10} for i in range(n_pods)]
        return make_workload(nodes, pods)

    wls = [wl(4, 30), wl(7, 900), wl(9, 1800), wl(40, 3000)]
    buckets = synthetic.bucket_workloads(wls, node_quantum=16, pod_quantum=2048)
    # first three share (n=16, g=4, p=2048); the last is (n=48, g=4, p=4096)
    assert len(buckets) == 2
    sizes = sorted(len(m) for m in buckets.values())
    assert sizes == [1, 3]
    for shape, members in buckets.items():
        for w in members:
            assert w.cluster.n_padded == shape.n
            assert w.pods.p_padded == shape.p
            assert w.cluster.g_padded == shape.g


def test_pad_workload_rejects_shrink():
    wl = synthetic.synthetic_workload(20, 50, seed=0)
    with pytest.raises(ValueError):
        synthetic.pad_workload(wl, synthetic.BucketShape(n=4, g=1, p=8))


def test_stack_traces_rejects_mixed_shapes():
    a = synthetic.synthetic_workload(4, 20, seed=0, pad_to=(8, 8, 32))
    b = synthetic.synthetic_workload(4, 20, seed=1, pad_to=(16, 8, 32))
    with pytest.raises(ValueError):
        stack_traces([a, b], SimConfig())


@pytest.mark.slow
def test_batched_matches_per_trace():
    """The one-program batched path == N independent simulations."""
    cfg = SimConfig(score_dtype=jnp.float64)
    wls = [small(seed) for seed in range(3)]
    params = parametric.seed_weights("best_fit")

    batched = make_trace_batch_eval(wls, cfg=cfg)
    res = batched(params)

    for i, wl in enumerate(wls):
        run = make_param_run_fn(wl, parametric.score, cfg)
        single = run(params, initial_state(wl, cfg))
        assert float(res.policy_score[i]) == pytest.approx(
            float(single.policy_score), abs=1e-12), i
        assert int(res.scheduled_pods[i]) == int(single.scheduled_pods)
        assert int(res.num_snapshots[i]) == int(single.num_snapshots)
        assert np.array_equal(np.asarray(res.assigned_node[i]),
                              np.asarray(single.assigned_node))


@pytest.mark.slow
def test_population_by_trace_matrix():
    cfg = SimConfig(score_dtype=jnp.float64)
    wls = [small(seed) for seed in (5, 6)]
    pop = jnp.stack([parametric.seed_weights("first_fit"),
                     parametric.seed_weights("best_fit"),
                     parametric.seed_weights("packing")])
    ev = make_trace_batch_eval(wls, cfg=cfg, population=True)
    res = ev(pop)
    assert res.policy_score.shape == (3, 2)
    # row 1 must equal the single-candidate batched eval of best_fit
    single = make_trace_batch_eval(wls, cfg=cfg)(pop[1])
    assert np.allclose(np.asarray(res.policy_score[1]),
                       np.asarray(single.policy_score))


@pytest.mark.slow
def test_batched_flat_engine_matches_per_trace():
    """The flat engine drives the same stacked-trace program shape; each
    lane equals its independent flat simulation."""
    from fks_tpu.sim import flat

    cfg = SimConfig(score_dtype=jnp.float64)
    wls = [small(seed) for seed in range(3)]
    params = parametric.seed_weights("best_fit")
    res = make_trace_batch_eval(wls, cfg=cfg, engine="flat")(params)
    for i, wl in enumerate(wls):
        run = flat.make_param_run_fn(wl, parametric.score, cfg)
        single = run(params, flat.initial_state(wl, cfg))
        assert float(res.policy_score[i]) == pytest.approx(
            float(single.policy_score), abs=1e-12), i
        assert np.array_equal(np.asarray(res.assigned_node[i]),
                              np.asarray(single.assigned_node))
