"""VM-native serving tests (fks_tpu.serve.vm_engine + the controller's
zero-rebuild promotion fast path).

The ISSUE-16 acceptance criteria, as tests:

- VM-vs-AOT parity: the champion-as-data engine answers every query
  with the same score/placements as the AOT closure engine — exact on
  the integer contract, <= 1e-5 otherwise;
- zero-rebuild hot swap: TWO consecutive promotions through the live
  PromotionController perform ZERO XLA compiles on the serving process
  (CompileWatcher delta == 0) — the swap is transpile + pack + H2D;
- AOT fallback: a VM-unlowerable candidate promotes through the
  closure-engine slow path with a recorded ``vm_swap`` fallback event;
- per-lane isolation on the 8-virtual-device mesh: a lane's answer is
  independent of its batch neighbours, and matches the plain engine.

Plus units for the capacity bucket, the packed program wire format,
artifact round-trip (engine_kind dispatch), the service summary
surface, and the evolution ledger's ``vm_coverage`` stat.
"""
import os

import jax
import numpy as np
import pytest

from fks_tpu.data.synthetic import synthetic_workload
from fks_tpu.funsearch import backend, template, vm
from fks_tpu.obs import CompileWatcher
from fks_tpu.parallel.mesh import num_shards, population_mesh
from fks_tpu.pipeline import (
    PromotionConfig, PromotionController, write_champion,
)
from fks_tpu.serve import (
    ChampionSpec, ServeEngine, ServeService, ShapeEnvelope, VMServeEngine,
    pack_program_tables, unpack_program_tables,
)

SEED_LOGIC = "score = 1000"
BETTER_LOGIC = ("score = 1000 + (node.cpu_milli_left - pod.cpu_milli) "
                "/ max(1, node.cpu_milli_total)")
EVEN_BETTER_LOGIC = ("score = 2000 + (node.memory_mib_left - "
                     "pod.memory_mib) / max(1, node.memory_mib_total)")
UNSUPPORTED_LOGIC = ("gpus = sorted(g.gpu_milli_left for g in node.gpus)\n"
                     "return max(1, gpus[0]) if pod.num_gpu == 0 else 1")


def _champ(logic, score=0.5, source="<test>"):
    return ChampionSpec(code=template.fill_template(logic), score=score,
                        source=source)


def _query(i, n=3):
    return [{"cpu_milli": 10 + 7 * i + j, "memory_mib": 50 + 11 * j,
             "creation_time": j, "duration_time": 40}
            for j in range(n)]


def _traffic(service, n=3, pods=3):
    base = service.engine.base_pods
    futs = [service.submit(
        {"pods": [dict(base[(i + j) % len(base)]) for j in range(pods)]})
        for i in range(n)]
    return [f.result(timeout=300) for f in futs]


class RecStub:
    """Recorder double: keeps every event/metric for assertions. The
    ``metric`` signature must absorb positional record payloads."""

    enabled = True

    def __init__(self):
        self.events = []
        self.metrics = []

    def event(self, kind, **fields):
        self.events.append({"kind": kind, **fields})

    def metric(self, kind, *a, **fields):
        self.metrics.append({"kind": kind, **fields})


@pytest.fixture(scope="module")
def wl():
    return synthetic_workload(8, 16, seed=0)


@pytest.fixture(scope="module")
def envelope():
    return ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=2,
                         max_gpu_milli=1000)


@pytest.fixture(scope="module")
def aot(wl, envelope):
    return ServeEngine(_champ(BETTER_LOGIC), wl, envelope=envelope,
                       engine="flat")


@pytest.fixture(scope="module")
def vm_engine(wl, envelope):
    return VMServeEngine(_champ(BETTER_LOGIC), wl, envelope=envelope,
                        engine="flat")


# ------------------------------------------------------------- units


def test_capacity_bucket():
    assert vm.capacity_bucket(0) == 64
    assert vm.capacity_bucket(1) == 64
    assert vm.capacity_bucket(64) == 64
    assert vm.capacity_bucket(65) == 128
    assert vm.capacity_bucket(128) == 128
    assert vm.capacity_bucket(200) == 256


def test_pack_program_tables_round_trip():
    prog = vm.compile_policy(template.fill_template(BETTER_LOGIC), 8, 2)
    packed = pack_program_tables(prog)
    tables = packed[0]
    assert tables.shape == (4, prog.capacity)  # ONE op-table buffer
    assert tables.dtype == np.int32
    back = unpack_program_tables(packed)
    for a, b in zip(prog, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_vm_engine_binds_champion_as_data(vm_engine):
    assert vm_engine.engine_kind == "vm"
    assert vm_engine.policy_tier == "vm"
    assert vm_engine.program_capacity >= int(vm_engine.params.n_ops)
    # capacity is a pow2 bucket floored at 64 — shared across champions
    cap = vm_engine.program_capacity
    assert cap >= 64 and cap & (cap - 1) == 0


def test_vm_unsupported_champion_raises_at_construction(wl, envelope):
    with pytest.raises(vm.VMUnsupported):
        VMServeEngine(_champ(UNSUPPORTED_LOGIC), wl, envelope=envelope,
                      engine="flat")


# ------------------------------------------------------------- parity


def test_vm_matches_aot_on_batches(aot, vm_engine):
    queries = [_query(i) for i in range(4)]
    a = aot.answer_batch(queries)
    b = vm_engine.answer_batch(queries)
    for i, (pa, pb) in enumerate(zip(a, b)):
        # float arithmetic champion: x64 tests evaluate both tiers in
        # f64, so the contract is <= 1e-5 (observed: exact)
        assert abs(pa["score"] - pb["score"]) <= 1e-5, f"lane {i} score"
        assert pa["placements"] == pb["placements"], f"lane {i} placements"


def test_vm_matches_aot_exactly_on_integer_contract(wl, envelope):
    logic = "score = 3 * node.cpu_milli_left - 2 * pod.cpu_milli"
    a = ServeEngine(_champ(logic), wl, envelope=envelope, engine="flat")
    b = VMServeEngine(_champ(logic), wl, envelope=envelope, engine="flat")
    queries = [_query(10 + i) for i in range(3)]
    for pa, pb in zip(a.answer_batch(queries), b.answer_batch(queries)):
        assert pa["score"] == pb["score"]  # integer contract: exact
        assert pa["placements"] == pb["placements"]


# ------------------------------------------------ zero-rebuild hot swap


def test_double_hot_swap_zero_recompiles(wl, envelope, tmp_path):
    """TWO consecutive promotions through the live controller: every
    swap is a table upload into the warm executables — zero XLA
    compiles across shadow eval, swap, and post-swap traffic."""
    rec = RecStub()
    incumbent = VMServeEngine(_champ(SEED_LOGIC, 0.4), wl,
                              envelope=envelope, engine="flat",
                              recorder=rec)
    incumbent.warmup()
    service = ServeService(incumbent, max_wait_s=0.002)
    try:
        _traffic(service, 4)  # replay buffer for the shadow eval
        ctrl = PromotionController(
            service, wl, ledger_dir=str(tmp_path),
            log_path=os.path.join(str(tmp_path), "promotion.jsonl"),
            config=PromotionConfig(shadow_queries=2), recorder=rec)
        watcher = CompileWatcher().install()
        try:
            write_champion(str(tmp_path),
                           template.fill_template(BETTER_LOGIC), 0.9)
            v1 = ctrl.poll_once()
            _traffic(service, 3)
            write_champion(str(tmp_path),
                           template.fill_template(EVEN_BETTER_LOGIC), 1.3)
            v2 = ctrl.poll_once()
            _traffic(service, 3)
            compiles = watcher.backend_compile_count
        finally:
            watcher.uninstall()
        assert v1.get("action") == "promoted" and \
            v1.get("engine_kind") == "vm", v1
        assert v2.get("action") == "promoted" and \
            v2.get("engine_kind") == "vm", v2
        assert compiles == 0, (
            f"{compiles} XLA programs compiled across two VM hot-swaps "
            "— promotion must be transpile + pack + H2D only")
        # the swap was IN PLACE: same engine object, new champion tables
        assert service.engine is incumbent
        assert incumbent.vm_swaps == 2
        assert incumbent.vm_swap_h2d_bytes > 0
        bd = incumbent.last_swap_breakdown
        assert bd["h2d_bytes"] > 0 and bd["swap_ms"] >= 0.0
        assert bd["capacity"] == incumbent.program_capacity
        swaps = [e for e in rec.events if e["kind"] == "vm_swap"]
        assert [e["outcome"] for e in swaps] == ["swapped", "swapped"]
    finally:
        service.close()


def test_swap_program_returns_rollback_handle(wl, envelope):
    eng = VMServeEngine(_champ(SEED_LOGIC, 0.4, source="<old>"), wl,
                        envelope=envelope, engine="flat")
    queries = [_query(40)]
    before = eng.answer_batch(queries)
    old = eng.swap_program(_champ(BETTER_LOGIC, 0.9, source="<new>"))
    assert old.source == "<old>"
    assert eng.champion.source == "<new>"
    # the swapped-in tables serve EXACTLY like an engine built on the
    # new champion from scratch
    fresh = VMServeEngine(_champ(BETTER_LOGIC, 0.9), wl,
                          envelope=envelope, engine="flat")
    swapped = eng.answer_batch(queries)
    target = fresh.answer_batch(queries)
    assert swapped[0]["score"] == target[0]["score"]
    assert swapped[0]["placements"] == target[0]["placements"]
    eng.swap_program(old)  # rolling back is another swap_program
    rolled = eng.answer_batch(queries)
    assert rolled[0]["score"] == before[0]["score"]
    assert rolled[0]["placements"] == before[0]["placements"]


def test_transpile_cache_makes_reswap_warm(wl, envelope):
    """Host-side transpile cache (ISSUE-18): re-promoting a champion the
    engine already lowered must skip ``compile_policy`` entirely — the
    breakdown says "hit", the counters move, and the warm transpile leg
    costs no more than the cold one. Keyed on the EXACT source hash, so
    two different champions never alias; seeded at construction, so a
    rollback to the original champion is warm from swap one."""
    rec = RecStub()
    eng = VMServeEngine(_champ(SEED_LOGIC, 0.4, source="<seed>"), wl,
                        envelope=envelope, engine="flat", recorder=rec)
    assert eng.transpile_cache_hits == 0
    eng.swap_program(_champ(BETTER_LOGIC, 0.9, source="<new>"))
    cold = dict(eng.last_swap_breakdown)
    assert cold["transpile_cache"] == "miss"
    assert cold["transpile_cache_misses"] == 1
    # same source again (a rollback / A-B flip): pure cache lookup
    eng.swap_program(_champ(BETTER_LOGIC, 0.9, source="<again>"))
    warm = dict(eng.last_swap_breakdown)
    assert warm["transpile_cache"] == "hit"
    assert warm["transpile_cache_hits"] == 1
    assert warm["transpile_ms"] <= cold["transpile_ms"]
    # construction champion was seeded into the cache: rollback is warm
    eng.swap_program(_champ(SEED_LOGIC, 0.4))
    assert eng.last_swap_breakdown["transpile_cache"] == "hit"
    swaps = [e for e in rec.events if e["kind"] == "vm_swap"]
    assert [e["transpile_cache"] for e in swaps] == ["miss", "hit", "hit"]
    # warm-swapped tables still serve exactly like a fresh build
    eng.swap_program(_champ(BETTER_LOGIC, 0.9))
    fresh = VMServeEngine(_champ(BETTER_LOGIC, 0.9), wl, envelope=envelope,
                          engine="flat")
    q = [_query(90)]
    assert eng.answer_batch(q)[0]["score"] == \
        fresh.answer_batch(q)[0]["score"]


def test_transpile_cache_shared_with_shadow(wl, envelope):
    """``shadow_for`` lowers through the incumbent's cache, so the
    shadow-then-promote flow promotes WARM: the controller's real swap
    is H2D only."""
    eng = VMServeEngine(_champ(SEED_LOGIC, 0.4), wl, envelope=envelope,
                        engine="flat")
    cand = _champ(BETTER_LOGIC, 0.9, source="<cand>")
    eng.shadow_for(cand)
    eng.swap_program(cand)
    assert eng.last_swap_breakdown["transpile_cache"] == "hit"


def test_transpile_cache_never_caches_unsupported(wl, envelope):
    """A VM-unlowerable champion must raise on EVERY attempt — a cached
    rejection (or worse, a cached bogus program) would break the AOT
    fallback's retry semantics."""
    eng = VMServeEngine(_champ(SEED_LOGIC, 0.4), wl, envelope=envelope,
                        engine="flat")
    bad = _champ(UNSUPPORTED_LOGIC, 0.9)
    misses_before = eng.transpile_cache_misses
    for _ in range(2):
        with pytest.raises(vm.VMUnsupported):
            eng.swap_program(bad)
    assert eng.transpile_cache_misses == misses_before
    assert eng.transpile_cache_hits == 0


def test_service_swap_engine_routes_championspec(wl, envelope):
    eng = VMServeEngine(_champ(SEED_LOGIC, 0.4, source="<old>"), wl,
                        envelope=envelope, engine="flat")
    service = ServeService(eng, max_wait_s=0.002)
    try:
        old = service.swap_engine(_champ(BETTER_LOGIC, 0.9))
        assert isinstance(old, ChampionSpec) and old.source == "<old>"
        assert service.engine is eng  # in-place: no engine flip
        assert service.swaps == 1
        summary = service.summary()
        assert summary["engine_kind"] == "vm"
        assert summary["program_capacity"] == eng.program_capacity
        assert summary["vm_swaps"] == 1
        assert summary["vm_swap_h2d_bytes"] > 0
        # an AOT engine has no swap_program: ChampionSpec must be refused
        plain = ServeEngine(_champ(SEED_LOGIC), wl, envelope=envelope,
                            engine="flat")
        service.swap_engine(plain)
        with pytest.raises(TypeError):
            service.swap_engine(_champ(BETTER_LOGIC, 0.9))
    finally:
        service.close()


# --------------------------------------------------------- AOT fallback


def test_vm_unsupported_candidate_falls_back_to_aot(wl, envelope,
                                                    tmp_path):
    """A candidate outside the VM vocabulary still promotes — through
    the AOT closure factory — and the fallback is a recorded event."""
    rec = RecStub()
    incumbent = VMServeEngine(_champ(SEED_LOGIC, 0.4), wl,
                              envelope=envelope, engine="flat")
    incumbent.warmup()
    service = ServeService(incumbent, max_wait_s=0.002)
    try:
        _traffic(service, 4)
        ctrl = PromotionController(
            service, wl, ledger_dir=str(tmp_path),
            log_path=os.path.join(str(tmp_path), "promotion.jsonl"),
            config=PromotionConfig(shadow_queries=2), recorder=rec)
        write_champion(str(tmp_path),
                       template.fill_template(UNSUPPORTED_LOGIC), 0.9)
        verdict = ctrl.poll_once()
        assert verdict.get("action") == "promoted", verdict
        assert verdict.get("engine_kind") == "aot"
        # the service flipped to a NEW closure engine — the VM incumbent
        # could not serve this champion in place
        assert service.engine is not incumbent
        assert service.engine.engine_kind == "aot"
        falls = [e for e in rec.events
                 if e["kind"] == "vm_swap" and e["outcome"] == "fallback"]
        assert len(falls) == 1
        assert "sort" in falls[0]["detail"]
        _traffic(service, 2)  # the promoted AOT engine serves
    finally:
        service.close()


# ------------------------------------------------------- mesh sharding


def test_mesh_per_lane_isolation_and_parity(wl):
    """8-virtual-device mesh: each lane of a full batch answers exactly
    as the plain single-device VM engine, alone or together — and the
    program tables replicate while the lanes shard."""
    assert num_shards(population_mesh(jax.devices())) >= 8
    env = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=8,
                        max_gpu_milli=1000)
    plain = VMServeEngine(_champ(BETTER_LOGIC), wl, envelope=env,
                          engine="flat")
    sharded = VMServeEngine(_champ(BETTER_LOGIC), wl, envelope=env,
                            engine="flat",
                            mesh=population_mesh(jax.devices()))
    queries = [_query(60 + i) for i in range(8)]
    together = sharded.answer_batch(queries)
    baseline = plain.answer_batch(queries)
    for i, (t, b) in enumerate(zip(together, baseline)):
        assert t["score"] == b["score"], f"lane {i} score"
        assert t["placements"] == b["placements"], f"lane {i} placements"
    alone = [sharded.answer_batch([q])[0] for q in queries[:3]]
    for t, s in zip(together, alone):
        assert t["score"] == s["score"]
        assert t["placements"] == s["placements"]


def test_mesh_swap_keeps_parity(wl):
    env = ShapeEnvelope(max_pods=8, min_pod_bucket=8, max_batch=4,
                        max_gpu_milli=1000)
    sharded = VMServeEngine(_champ(SEED_LOGIC), wl, envelope=env,
                            engine="flat",
                            mesh=population_mesh(jax.devices()))
    sharded.swap_program(_champ(BETTER_LOGIC, 0.9))
    fresh = VMServeEngine(_champ(BETTER_LOGIC, 0.9), wl, envelope=env,
                          engine="flat")
    queries = [_query(70 + i) for i in range(4)]
    for a, b in zip(sharded.answer_batch(queries),
                    fresh.answer_batch(queries)):
        assert a["score"] == b["score"]
        assert a["placements"] == b["placements"]


# ----------------------------------------------------- artifact + ledger


def test_vm_artifact_round_trip(tmp_path, wl, envelope):
    eng = VMServeEngine(_champ(BETTER_LOGIC), wl, envelope=envelope,
                        engine="flat")
    queries = [_query(80), _query(81)]
    before = eng.answer_batch(queries)
    d = str(tmp_path / "artifact")
    eng.save(d)
    loaded = ServeEngine.load(d)  # engine_kind dispatch in load()
    assert isinstance(loaded, VMServeEngine)
    assert loaded.engine_kind == "vm"
    assert loaded.program_capacity == eng.program_capacity
    after = loaded.answer_batch(queries)
    for a, b in zip(before, after):
        assert a["score"] == b["score"]
        assert a["placements"] == b["placements"]


def test_vm_coverage_stat(micro_workload):
    """The ledger's vm_coverage: fraction of the batch's unique
    candidates served by the VM tier."""
    from tests.test_vm import _corpus

    ev = backend.CodeEvaluator(micro_workload, vm_batch=True)
    vmable = _corpus()[:3]
    hard = template.fill_template(UNSUPPORTED_LOGIC)
    ev.evaluate(vmable + [hard])
    assert ev.last_eval_stats["vm_coverage"] == pytest.approx(3 / 4)
    ev.evaluate(vmable)
    assert ev.last_eval_stats["vm_coverage"] == 1.0


def test_generation_stats_carries_vm_coverage():
    from fks_tpu.funsearch.evolution import GenerationStats

    stats = GenerationStats(generation=1, best_score=1.0, mean_score=1.0,
                            new_candidates=4, accepted=2,
                            rejected_similar=0, eval_seconds=0.1,
                            compile_count=0, vm_coverage=0.75)
    assert stats.vm_coverage == 0.75
    # exporter surface: the gauge rides the standard generation table
    from fks_tpu.obs.exporter import GENERATION_GAUGES
    assert any(key == "vm_coverage" for _, key, _ in GENERATION_GAUGES)


def test_concurrent_swap_never_tears_a_batch(wl, envelope):
    """ISSUE-17 thread-race criterion: ``swap_program`` racing in-flight
    ``answer_batch`` calls must be atomic per batch — every answer set
    matches ONE of the two champions exactly (the engine's swap lock
    holds across a batch), never a torn mix of old tables and new
    params, and the race must not leak or recompile."""
    import threading

    # behaviorally OPPOSED champions (worst-fit vs best-fit) so the two
    # programs place differently — a torn swap has something to tear
    champs = [_champ("score = node.cpu_milli_left - pod.cpu_milli",
                     0.4, source="<a>"),
              _champ("score = pod.cpu_milli - node.cpu_milli_left",
                     0.9, source="<b>")]
    eng = VMServeEngine(champs[0], wl, envelope=envelope, engine="flat")
    queries = [_query(7), _query(11)]

    def key(answers):
        return tuple((round(float(a["score"]), 9), tuple(a["placements"]))
                     for a in answers)

    # one reference answer set per champion, from the same engine while
    # it is single-threaded (VM answers are deterministic per program)
    legal = {}
    for i, c in enumerate(champs):
        eng.swap_program(c)
        legal[i] = key(eng.answer_batch(queries))
    assert legal[0] != legal[1]  # the race has something to tear

    watcher = CompileWatcher().install()
    errors, torn = [], []
    stop = threading.Event()

    def hammer():
        try:
            while not stop.is_set():
                got = key(eng.answer_batch(queries))
                if got not in (legal[0], legal[1]):
                    torn.append(got)
        except Exception as e:  # pragma: no cover - failure diagnostics
            errors.append(repr(e))

    threads = [threading.Thread(target=hammer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for i in range(30):
            eng.swap_program(champs[(i + 1) % 2])
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        watcher.uninstall()
    assert not errors, errors
    assert not torn, f"{len(torn)} torn batches, first: {torn[:1]}"
    assert watcher.backend_compile_count == 0  # swaps never rebuild
